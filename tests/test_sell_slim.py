"""SellSlim: the padding-free distributed slim layout (single matrix)
vs the scipy golden and the stacked slim layout."""

import numpy as np
import pytest
from scipy import sparse

from arrow_matrix_tpu.decomposition import arrow_decomposition
from arrow_matrix_tpu.parallel import make_mesh
from arrow_matrix_tpu.parallel.sell_slim import SellSlim, degree_ladder
from arrow_matrix_tpu.utils import barabasi_albert, random_dense


def test_degree_ladder():
    lad = degree_ladder(100)
    assert lad[0] == 0 and lad[1] == 8
    assert lad[-1] >= 100
    assert all(b % 8 == 0 for b in lad)
    assert degree_ladder(0) == [0]


def slim_level(n, width, seed):
    a = barabasi_albert(n, 4, seed=seed)
    levels = arrow_decomposition(a, width, max_levels=4,
                                 block_diagonal=True, seed=seed)
    return levels[0]   # one arrow matrix, block-diagonal slim structure


@pytest.mark.parametrize("n_dev", [2, 4, 8])
def test_sell_slim_matches_golden(n_dev):
    lvl = slim_level(1024, 64, seed=3)
    mesh = make_mesh((n_dev,), ("blocks",))
    d = SellSlim(lvl.matrix, 64, mesh)
    assert d.binary
    n = lvl.matrix.shape[0]
    x = random_dense(n, 8, seed=1)
    got = d.gather_result(d.spmm(d.set_features(x)))
    want = lvl.matrix @ x
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_sell_slim_weighted_and_iterated():
    lvl = slim_level(640, 32, seed=9)
    aw = (lvl.matrix * 0.25).tocsr().astype(np.float32)
    mesh = make_mesh((4,), ("blocks",))
    d = SellSlim(aw, 32, mesh)
    assert not d.binary
    n = aw.shape[0]
    x = random_dense(n, 4, seed=2)
    xt = d.set_features(x)
    for _ in range(3):
        xt = d.spmm(xt)
    want = x
    for _ in range(3):
        want = aw @ want
    np.testing.assert_allclose(d.gather_result(xt), want,
                               rtol=1e-4, atol=1e-5)


def test_sell_slim_multi_hop_halos_cover_far_entries():
    """An entry far outside the shard-diagonal grows the halo reach
    (whole-shard ppermute hops) instead of being dropped or rejected —
    correctness degrades gracefully into more communication."""
    a = sparse.csr_matrix((256, 256), dtype=np.float32).tolil()
    a[200, 100] = 2.0    # far off-diagonal, outside head arm at w=32
    a[10, 250] = 3.0     # head row, covered by the head operator
    a[100, 101] = 1.0
    a = a.tocsr()
    mesh = make_mesh((4,), ("blocks",))
    d = SellSlim(a, 32, mesh)
    assert d.ops.hops >= 1
    x = random_dense(256, 4, seed=0)
    got = d.gather_result(d.spmm(d.set_features(x)))
    np.testing.assert_allclose(got, a @ x, rtol=1e-5, atol=1e-6)


def test_sell_multi_level_matches_golden():
    """SellMultiLevel = feature-major mesh multi-level: must equal the
    decomposition golden AND MultiLevelArrow, including a grown banded
    last level (cross-shard halos)."""
    from arrow_matrix_tpu.decomposition.decompose import decomposition_spmm
    from arrow_matrix_tpu.parallel import MultiLevelArrow
    from arrow_matrix_tpu.parallel.sell_slim import SellMultiLevel

    n, width = 1024, 64
    a = barabasi_albert(n, 4, seed=7)
    levels = arrow_decomposition(a, width, max_levels=3,
                                 block_diagonal=True, seed=2)
    mesh = make_mesh((4,), ("blocks",))
    sm = SellMultiLevel(levels, width, mesh)
    assert sm.binary
    x = random_dense(n, 8, seed=3)
    got = sm.gather_result(sm.step(sm.set_features(x)))
    want = decomposition_spmm(levels, x)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    ml = MultiLevelArrow(levels, width, mesh=make_mesh((4,), ("blocks",)),
                         fmt="ell")
    ref = ml.gather_result(ml.step(ml.set_features(x)))
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)


def test_sell_multi_level_iterated_weighted():
    from arrow_matrix_tpu.parallel.sell_slim import SellMultiLevel

    n, width = 640, 32
    a = (barabasi_albert(n, 4, seed=11) * 0.25).tocsr().astype(np.float32)
    levels = arrow_decomposition(a, width, max_levels=3,
                                 block_diagonal=True, seed=1)
    mesh = make_mesh((8,), ("blocks",))
    sm = SellMultiLevel(levels, width, mesh)
    assert not sm.binary
    x = random_dense(n, 4, seed=5)
    xt = sm.run(sm.set_features(x), 3)
    want = x
    for _ in range(3):
        want = a @ want
    np.testing.assert_allclose(sm.gather_result(xt), want,
                               rtol=1e-3, atol=1e-4)


@pytest.mark.parametrize("n_dev", [2, 8])
def test_sell_multi_level_mesh_sizes(n_dev):
    from arrow_matrix_tpu.decomposition.decompose import decomposition_spmm
    from arrow_matrix_tpu.parallel.sell_slim import SellMultiLevel

    n, width = 512, 32
    a = barabasi_albert(n, 3, seed=29)
    levels = arrow_decomposition(a, width, max_levels=2,
                                 block_diagonal=True, seed=3)
    mesh = make_mesh((n_dev,), ("blocks",))
    sm = SellMultiLevel(levels, width, mesh)
    x = random_dense(n, 4, seed=1)
    got = sm.gather_result(sm.step(sm.set_features(x)))
    np.testing.assert_allclose(got, decomposition_spmm(levels, x),
                               rtol=1e-4, atol=1e-4)


def test_sell_slim_duplicate_ones_go_weighted():
    """Duplicate all-ones entries sum to 2.0 under canonicalization —
    binary auto-detection must run on the CANONICAL values (regression:
    raw-data detection silently halved such entries)."""
    row = np.array([5, 5, 40, 3])
    col = np.array([7, 7, 2, 60])
    a = sparse.coo_matrix((np.ones(4, np.float32), (row, col)),
                          shape=(128, 128)).tocsr()
    assert not a.has_canonical_format or np.any(a.data != 1.0) or True
    mesh = make_mesh((4,), ("blocks",))
    d = SellSlim(a, 32, mesh)
    assert not d.binary
    x = random_dense(128, 4, seed=0)
    got = d.gather_result(d.spmm(d.set_features(x)))
    a2 = a.copy(); a2.sum_duplicates()
    np.testing.assert_allclose(got, a2 @ x, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("routing", ["gather", "a2a"])
def test_sell_multi_level_routing_modes(routing):
    """Explicit a2a routing for the feature-major carriage must equal
    the GSPMD-gather lowering (and the golden)."""
    from arrow_matrix_tpu.decomposition.decompose import decomposition_spmm
    from arrow_matrix_tpu.parallel.sell_slim import SellMultiLevel

    n, width = 768, 32
    a = barabasi_albert(n, 4, seed=13)
    levels = arrow_decomposition(a, width, max_levels=3,
                                 block_diagonal=True, seed=2)
    mesh = make_mesh((4,), ("blocks",))
    sm = SellMultiLevel(levels, width, mesh, routing=routing)
    x = random_dense(n, 8, seed=3)
    got = sm.gather_result(sm.step(sm.set_features(x)))
    np.testing.assert_allclose(got, decomposition_spmm(levels, x),
                               rtol=1e-4, atol=1e-4)
    # iterated run through the scan path too
    x2 = sm.gather_result(sm.run(sm.set_features(x), 2))
    want = np.asarray(a @ np.asarray(a @ x))
    np.testing.assert_allclose(x2, want, rtol=1e-3, atol=1e-3)


def test_sell_multi_level_k128_and_16dev():
    """BASELINE's 128-feature configs and the largest virtual pool."""
    from arrow_matrix_tpu.decomposition.decompose import decomposition_spmm
    from arrow_matrix_tpu.parallel.sell_slim import SellMultiLevel

    n, width = 1024, 32
    a = barabasi_albert(n, 3, seed=31)
    levels = arrow_decomposition(a, width, max_levels=3,
                                 block_diagonal=True, seed=4)
    mesh = make_mesh((16,), ("blocks",))
    sm = SellMultiLevel(levels, width, mesh, routing="a2a")
    x = random_dense(n, 128, seed=2)
    got = sm.gather_result(sm.step(sm.set_features(x)))
    np.testing.assert_allclose(got, decomposition_spmm(levels, x),
                               rtol=1e-4, atol=1e-4)


def test_sell_multi_level_from_artifact(tmp_path):
    """Memmapped artifact triplets flow into the feature-major mesh
    orchestration (as_canonical_csr materializes per level)."""
    from arrow_matrix_tpu.decomposition.decompose import decomposition_spmm
    from arrow_matrix_tpu.io import (
        as_levels,
        load_decomposition,
        load_level_widths,
        save_decomposition,
    )
    from arrow_matrix_tpu.parallel.sell_slim import SellMultiLevel

    a = barabasi_albert(600, 3, seed=5)
    levels = arrow_decomposition(a, 64, max_levels=3, block_diagonal=True,
                                 seed=5)
    base = str(tmp_path / "g")
    save_decomposition(levels, base)
    widths = load_level_widths(base, 64)
    stream_levels = as_levels(load_decomposition(base, 64, mem_map=True),
                              widths if widths is not None else 64,
                              materialize=False)
    assert not hasattr(stream_levels[0].matrix, "nnz")

    sm = SellMultiLevel(stream_levels, 64, make_mesh((4,), ("blocks",)))
    assert sm.binary
    x = random_dense(600, 8, seed=2)
    got = sm.gather_result(sm.step(sm.set_features(x)))
    np.testing.assert_allclose(got, decomposition_spmm(levels, x),
                               rtol=1e-4, atol=1e-4)


def test_sell_multi_level_feat_axis():
    """k-dimension tiling: feature rows sharded over a second mesh axis
    compose with the sell orchestration under BOTH routings (the a2a
    tables are per-device and feature-row-independent, so each feature
    slice runs its own identical exchange)."""
    from arrow_matrix_tpu.decomposition.decompose import decomposition_spmm
    from arrow_matrix_tpu.parallel.sell_slim import SellMultiLevel

    n, width = 512, 32
    a = barabasi_albert(n, 3, seed=41)
    levels = arrow_decomposition(a, width, max_levels=2,
                                 block_diagonal=True, seed=1)
    mesh = make_mesh((4, 2), ("blocks", "feat"))
    x = random_dense(n, 8, seed=2)
    want = decomposition_spmm(levels, x)
    for routing in ("gather", "a2a"):
        sm = SellMultiLevel(levels, width, mesh, routing=routing,
                            feat_axis="feat")
        got = sm.gather_result(sm.step(sm.set_features(x)))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_directed_graph_through_fold_and_sell():
    """Asymmetric adjacency end-to-end (reference supports directed via
    symmetrize-before-linearize; the runtime operators must be exact on
    the asymmetric matrix itself)."""
    from arrow_matrix_tpu.decomposition.decompose import decomposition_spmm
    from arrow_matrix_tpu.parallel import MultiLevelArrow
    from arrow_matrix_tpu.parallel.sell_slim import SellMultiLevel

    n, width = 512, 32
    a = barabasi_albert(n, 3, seed=43, directed=True)
    assert (abs(a - a.T)).nnz > 0   # genuinely asymmetric
    levels = arrow_decomposition(a, width, max_levels=3,
                                 block_diagonal=True, seed=2)
    x = random_dense(n, 4, seed=1)
    want = decomposition_spmm(levels, x)

    mlf = MultiLevelArrow(levels, width, mesh=None, fmt="fold")
    np.testing.assert_allclose(
        mlf.gather_result(mlf.step(mlf.set_features(x))), want,
        rtol=1e-4, atol=1e-4)

    sm = SellMultiLevel(levels, width, make_mesh((4,), ("blocks",)))
    np.testing.assert_allclose(
        sm.gather_result(sm.step(sm.set_features(x))), want,
        rtol=1e-4, atol=1e-4)


def test_sell_bf16_feature_carriage():
    """feature_dtype='bf16' on the mesh sell paths: results track f32
    to bf16 rounding, the carriage dtype is bf16, and the LOWERED HLO
    shows exactly half the collective bytes of the f32 twin (the CPU
    backend upcasts compiled collectives, so the lowered module is the
    honest dtype accounting — commstats.lowered_collective_stats)."""
    import ml_dtypes

    from arrow_matrix_tpu.decomposition.decompose import decomposition_spmm
    from arrow_matrix_tpu.parallel.sell_slim import SellMultiLevel
    from arrow_matrix_tpu.utils import commstats

    n, width = 1024, 64
    a = barabasi_albert(n, 4, seed=7)
    levels = arrow_decomposition(a, width, max_levels=3,
                                 block_diagonal=True, seed=7)
    x = random_dense(n, 8, seed=1)
    want = decomposition_spmm(levels, x)
    mesh = make_mesh((8,), ("blocks",))

    sm16 = SellMultiLevel(levels, width, mesh, routing="a2a",
                          feature_dtype="bf16")
    xt = sm16.set_features(x)
    assert xt.dtype == ml_dtypes.bfloat16
    out = sm16.gather_result(sm16.step(xt))
    assert out.dtype == np.float32
    rel = np.linalg.norm(out - want) / np.linalg.norm(want)
    assert rel < 2e-2, rel

    smf = SellMultiLevel(levels, width, mesh, routing="a2a")
    s16 = commstats.lowered_collective_stats(
        sm16._step, xt, sm16._level_args, sm16.fwd, sm16.bwd)
    sf = commstats.lowered_collective_stats(
        smf._step, smf.set_features(x), smf._level_args, smf.fwd,
        smf.bwd)
    assert s16["total_bytes"] > 0
    assert s16["total_bytes"] * 2 == sf["total_bytes"]

    # feature_dtype='f32' (and None) stay the exact default.
    assert smf.feature_dtype is None
    assert SellMultiLevel(levels, width, mesh, routing="a2a",
                          feature_dtype="f32").feature_dtype is None


def test_sell_slim_bf16_halo_bytes_halved():
    """bf16 carriage on the single-matrix SellSlim path: the halo
    ppermute exchanges must CARRY bf16 (lowered HLO shows exactly half
    the f32 twin's collective bytes — VERDICT r4 item 7: the bytes
    must ride the exchanges, not just the resident features), and the
    result stays within bf16 rounding of the golden."""
    import ml_dtypes

    from arrow_matrix_tpu.utils import commstats

    n, w = 768, 32
    a = barabasi_albert(n, 4, seed=13).astype(np.float32)
    mesh = make_mesh((4,), ("blocks",))
    d16 = SellSlim(a, w, mesh, feature_dtype="bf16")
    df = SellSlim(a, w, mesh)
    assert np.max(d16.ops.hops) > 0   # the halo exchange must exist
    x = random_dense(n, 8, seed=2)
    xt = d16.set_features(x)
    assert xt.dtype == ml_dtypes.bfloat16
    out = d16.gather_result(d16.spmm(xt))
    assert out.dtype == np.float32
    want = a @ x
    rel = np.linalg.norm(out - want) / np.linalg.norm(want)
    assert rel < 2e-2, rel

    def stats(d, xt):
        o = d.ops
        return commstats.lowered_collective_stats(
            d._step, o.body, o.head, o.head_unsort, o.orig_pos, xt)

    s16 = stats(d16, xt)
    sf = stats(df, df.set_features(x))
    assert s16["total_bytes"] > 0
    assert s16["total_bytes"] * 2 == sf["total_bytes"]


def test_per_host_build_equivalence():
    """The per-host build (_slim_shares materialize=subset) must agree
    with the full build on every global decision — tier ladder, shared
    tier shapes, orderings — and bit-match the full stacks on the
    materialized shards (remote slices stay zero)."""
    from arrow_matrix_tpu.parallel.sell_slim import (
        _DegreesOnly,
        _pack_shard_tiers,
        _SliceSource,
        _banded_reach,
        _hops_rem,
        _slim_shares,
        degree_ladder,
    )

    n, w, n_dev = 512, 32, 4
    a = barabasi_albert(n, 4, seed=11).astype(np.float32)
    src = _SliceSource(a, n_dev, w)
    hops, _ = _hops_rem(_banded_reach(src, w), src.shard_len,
                        n_dev)

    full_b, full_h = _slim_shares(src, w, hops)
    part_b, part_h = _slim_shares(src, w, hops, materialize={0, 2})

    for d in (1, 3):
        assert isinstance(part_b[d], _DegreesOnly)
        np.testing.assert_array_equal(np.diff(part_b[d].indptr),
                                      np.diff(full_b[d].indptr))
    for d in (0, 2):
        assert (part_b[d] != full_b[d]).nnz == 0

    ladder = degree_ladder(
        max(int(np.diff(s.indptr).max()) if s.nnz else 0
            for s in full_b))
    sf, of, rf = _pack_shard_tiers(full_b, ladder, False, np.float32)
    sp, op, rp = _pack_shard_tiers(part_b, ladder, False, np.float32)
    assert rf == rp
    np.testing.assert_array_equal(of, op)          # orderings identical
    for cf, cp in zip(sf.cols, sp.cols):
        np.testing.assert_array_equal(cf[[0, 2]], cp[[0, 2]])
        assert not np.any(cp[[1, 3]])              # remote = zero pages
    for df, dp in zip(sf.deg, sp.deg):
        np.testing.assert_array_equal(df[[0, 2]], dp[[0, 2]])


def test_tight_ladder_matches_default_with_fewer_slots():
    """ladder='tight' (growth 1.3, align 1): same results to f32
    reassociation, strictly fewer padded gather slots (the align-8
    floor pads block-diagonal levels ~3.4x nnz — slots ARE the gather
    cost, PERFORMANCE.md)."""
    from arrow_matrix_tpu.decomposition import arrow_decomposition
    from arrow_matrix_tpu.decomposition.decompose import decomposition_spmm
    from arrow_matrix_tpu.parallel.sell_slim import SellMultiLevel

    n, width = 512, 32
    a = barabasi_albert(n, 4, seed=23)
    levels = arrow_decomposition(a, width, max_levels=3,
                                 block_diagonal=True, seed=3)
    mesh = make_mesh((8,), ("blocks",))
    x = random_dense(n, 8, seed=5)

    base = SellMultiLevel(levels, width, mesh)
    tight = SellMultiLevel(levels, width, mesh, ladder="tight")
    slots = lambda sm: sum(o.body.n_slots + o.head.n_slots
                           for o in sm.ops)
    assert slots(tight) < slots(base)
    got_t = tight.gather_result(tight.step(tight.set_features(x)))
    np.testing.assert_allclose(got_t, decomposition_spmm(levels, x),
                               rtol=1e-4, atol=1e-4)
    got_b = base.gather_result(base.step(base.set_features(x)))
    np.testing.assert_allclose(got_t, got_b, rtol=1e-5, atol=1e-5)


def test_tight_ladder_space_shared_matches():
    from arrow_matrix_tpu.decomposition import arrow_decomposition
    from arrow_matrix_tpu.decomposition.decompose import decomposition_spmm
    from arrow_matrix_tpu.parallel.sell_space import SellSpaceShared

    n, width = 384, 32
    a = barabasi_albert(n, 3, seed=29)
    levels = arrow_decomposition(a, width, max_levels=2,
                                 block_diagonal=True, seed=4)
    assert len(levels) == 2
    mesh = make_mesh((2, 4), ("lvl", "blocks"))
    x = random_dense(n, 4, seed=6)
    sp = SellSpaceShared(levels, width, mesh=mesh, ladder="tight")
    got = sp.gather_result(sp.step(sp.set_features(x)))
    np.testing.assert_allclose(got, decomposition_spmm(levels, x),
                               rtol=1e-4, atol=1e-4)


def test_resolve_ladder_validation():
    from arrow_matrix_tpu.parallel.sell_slim import resolve_ladder

    assert resolve_ladder(None) == resolve_ladder("default")
    assert resolve_ladder("tight") == (1.3, 1)
    assert resolve_ladder((1.2, 2)) == (1.2, 2)
    import pytest as _pytest
    with _pytest.raises(ValueError):
        resolve_ladder((0.9, 2))
    with _pytest.raises(ValueError):
        resolve_ladder((1.2, 0))


def test_sliced_halo_exchange_fewer_bytes():
    """The farthest halo hop carries only `reach` rows: versus a
    whole-shard step (rem=0 compatibility mode) the collective-permute
    bytes strictly drop while outputs stay identical."""
    from arrow_matrix_tpu.parallel.sell_slim import (
        SellSlim,
        make_sharded_step,
    )
    from arrow_matrix_tpu.utils import commstats
    from arrow_matrix_tpu.utils.graphs import grid_graph, random_dense

    g = grid_graph(32).astype(np.float32)    # bandwidth 32 << shard
    mesh = make_mesh((4,), ("blocks",))
    sl = SellSlim(g, 32, mesh)
    o = sl.ops
    assert o.hops == 1 and 0 < o.rem < sl.shard_len

    x = random_dense(g.shape[0], 4, seed=1)
    xt = sl.set_features(x)
    want = sl.gather_result(sl.spmm(xt))
    np.testing.assert_allclose(want, np.asarray(g @ x), rtol=1e-5,
                               atol=1e-5)

    import jax

    whole = jax.jit(make_sharded_step(mesh, sl.axis, sl.width,
                                      o.rows_out, hops=o.hops, rem=0))
    got_whole = whole(o.body, o.head, o.head_unsort, o.orig_pos, xt)
    np.testing.assert_allclose(np.asarray(got_whole),
                               np.asarray(sl.spmm(xt)), rtol=1e-6,
                               atol=1e-6)

    sliced_stats = commstats.collective_stats(
        sl._step, o.body, o.head, o.head_unsort, o.orig_pos, xt)
    whole_stats = commstats.collective_stats(
        whole, o.body, o.head, o.head_unsort, o.orig_pos, xt)
    assert (sliced_stats["collective-permute"]["bytes"]
            < whole_stats["collective-permute"]["bytes"])
