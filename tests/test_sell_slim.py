"""SellSlim: the padding-free distributed slim layout (single matrix)
vs the scipy golden and the stacked slim layout."""

import numpy as np
import pytest
from scipy import sparse

from arrow_matrix_tpu.decomposition import arrow_decomposition
from arrow_matrix_tpu.parallel import make_mesh
from arrow_matrix_tpu.parallel.sell_slim import SellSlim, degree_ladder
from arrow_matrix_tpu.utils import barabasi_albert, random_dense


def test_degree_ladder():
    lad = degree_ladder(100)
    assert lad[0] == 0 and lad[1] == 8
    assert lad[-1] >= 100
    assert all(b % 8 == 0 for b in lad)
    assert degree_ladder(0) == [0]


def slim_level(n, width, seed):
    a = barabasi_albert(n, 4, seed=seed)
    levels = arrow_decomposition(a, width, max_levels=4,
                                 block_diagonal=True, seed=seed)
    return levels[0]   # one arrow matrix, block-diagonal slim structure


@pytest.mark.parametrize("n_dev", [2, 4, 8])
def test_sell_slim_matches_golden(n_dev):
    lvl = slim_level(1024, 64, seed=3)
    mesh = make_mesh((n_dev,), ("blocks",))
    d = SellSlim(lvl.matrix, 64, mesh)
    assert d.binary
    n = lvl.matrix.shape[0]
    x = random_dense(n, 8, seed=1)
    got = d.gather_result(d.spmm(d.set_features(x)))
    want = lvl.matrix @ x
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_sell_slim_weighted_and_iterated():
    lvl = slim_level(640, 32, seed=9)
    aw = (lvl.matrix * 0.25).tocsr().astype(np.float32)
    mesh = make_mesh((4,), ("blocks",))
    d = SellSlim(aw, 32, mesh)
    assert not d.binary
    n = aw.shape[0]
    x = random_dense(n, 4, seed=2)
    xt = d.set_features(x)
    for _ in range(3):
        xt = d.spmm(xt)
    want = x
    for _ in range(3):
        want = aw @ want
    np.testing.assert_allclose(d.gather_result(xt), want,
                               rtol=1e-4, atol=1e-5)


def test_sell_slim_rejects_out_of_pattern():
    # An entry outside shard-diagonal + head arm must be caught.
    a = sparse.csr_matrix((256, 256), dtype=np.float32).tolil()
    a[200, 100] = 1.0    # far off-diagonal, outside head arm at w=32
    a = a.tocsr()
    mesh = make_mesh((4,), ("blocks",))
    with pytest.raises(ValueError, match="captured"):
        SellSlim(a, 32, mesh)
