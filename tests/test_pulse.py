"""graft-pulse unit tests: deterministic window rotation (boundary
arithmetic, bounded gap fill), mergeable-histogram exactness
(merge == pooled samples), SLO-burn watchdog hysteresis (no flapping,
one cleared event), crash-readable ring + Prometheus exposition
validators, the stdlib scrape endpoint, flight-recorder thread safety
under concurrent writers, request-id correlation on every serve span,
and stream-vs-report consistency (the pooled window series reproduces
the final SLO report).  The chaos-level watchdog-to-ladder scenario
lives in tools/serve_gate.py:scenario_slo_burn_degrade."""

import json
import threading
import urllib.request

import pytest

from arrow_matrix_tpu import faults
from arrow_matrix_tpu.faults import RetryPolicy
from arrow_matrix_tpu.obs import Tracer, flight, pulse
from arrow_matrix_tpu.obs.metrics import Histogram
from arrow_matrix_tpu.obs.pulse import (
    BurnRule,
    PulseEndpoint,
    PulseMonitor,
    SloWatchdog,
)
from arrow_matrix_tpu.serve import (
    ArrowServer,
    ExecConfig,
    ba_executor_factory,
    run_trace,
    slo_summary,
    synthetic_trace,
)

N, WIDTH, K, SEED = 64, 16, 2, 5


@pytest.fixture(autouse=True)
def _no_leftover_plan():
    faults.clear_plan()
    yield
    faults.clear_plan()


@pytest.fixture(scope="module")
def factory():
    """One BA decomposition shared by every server in this module."""
    return ba_executor_factory(N, WIDTH, SEED, fmt="fold")


def _mon(**kw):
    """A monitor on a manual clock: tests advance ``now[0]``."""
    now = [0.0]
    kw.setdefault("window_s", 1.0)
    return PulseMonitor(clock=lambda: now[0], **kw), now


# ---------------------------------------------------------------------------
# Window rotation (pure clock arithmetic)
# ---------------------------------------------------------------------------

def test_window_boundary_event_at_edge_goes_to_next_window():
    m, now = _mon()
    m.observe("completed", latency_ms=1.0)          # t=0.0 -> window 0
    now[0] = 0.999
    m.observe("completed", latency_ms=2.0)          # still window 0
    now[0] = 1.0
    m.observe("completed", latency_ms=3.0)   # exactly t0+w -> window 1
    m.close()
    s = m.series()
    assert [w["window"] for w in s] == [0, 1]
    assert s[0]["completed"] == 2 and s[1]["completed"] == 1
    assert s[1]["start_s"] == pytest.approx(1.0)
    # The boundary event's latency landed in window 1's histogram.
    assert s[1]["latency_ms"]["max"] == pytest.approx(3.0)


def test_idle_gap_fill_is_bounded():
    m, now = _mon()
    m.observe("completed", latency_ms=1.0)
    now[0] = 1000.0                       # ~1000 windows of pure idle
    m.observe("completed", latency_ms=2.0)
    m.close()
    s = m.series()
    assert len(s) <= pulse._MAX_GAP_FILL + 3
    assert m.dropped_windows > 0
    idxs = [w["window"] for w in s]
    assert idxs == sorted(idxs) and len(set(idxs)) == len(idxs)
    assert m.totals_dict()["completed"] == 2   # totals never drop events
    assert pulse.validate_ring(m.snapshot()) == []


def test_partial_final_window_keeps_rate_honest():
    m, now = _mon()
    now[0] = 0.25
    m.observe("completed", latency_ms=1.0)
    now[0] = 0.5
    m.close()
    (w,) = m.series()
    assert w["duration_s"] == pytest.approx(0.5)
    assert w["requests_per_s"] == pytest.approx(2.0)


# ---------------------------------------------------------------------------
# Mergeable histograms (obs/metrics.py)
# ---------------------------------------------------------------------------

def test_histogram_merge_equals_pooled():
    a, b, pooled = Histogram(), Histogram(), Histogram()
    for i, v in enumerate([5.0, 1.0, 9.0, 3.0, 7.0, 2.0, 8.0]):
        (a if i % 2 else b).observe(v)
        pooled.observe(v)
    a.merge(b)
    assert sorted(a.values) == sorted(pooled.values)
    for q in (0.0, 0.5, 0.9, 0.99, 1.0):
        assert a.quantile(q) == pooled.quantile(q)


def test_monitor_merged_latency_is_exactly_pooled():
    m, now = _mon()
    pooled = Histogram()
    for i, ms in enumerate([3.0, 1.0, 4.0, 1.5, 9.0, 2.6]):
        now[0] = float(i)                        # one window per event
        m.observe("completed", latency_ms=ms)
        pooled.observe(ms)
    merged = m.merged_latency()
    assert sorted(merged.values) == sorted(pooled.values)
    for q in (0.5, 0.9, 0.99):
        assert merged.quantile(q) == pooled.quantile(q)


# ---------------------------------------------------------------------------
# SLO-burn watchdog hysteresis
# ---------------------------------------------------------------------------

def test_burn_hysteresis_never_flaps():
    wd = SloWatchdog([BurnRule.fault_rate(0.0, min_windows=2)])
    # One isolated bad window (w0) must NOT trip; two consecutive
    # (w2, w3) trip once; staying bad (w4) adds nothing; the first
    # healthy window (w5) clears once.
    for i, f in enumerate([1, 0, 1, 1, 1, 0, 0]):
        wd.on_window({"window": i, "faults_seen": f})
    ev = [(e["event"], e["window"]) for e in wd.events]
    assert ev == [("slo_burn", 3), ("slo_burn_cleared", 5)]
    assert wd.burning() == []


def test_burn_callback_and_flight_event(tmp_path):
    rec = flight.FlightRecorder(str(tmp_path / "flight.json"))
    flight.set_recorder(rec)
    try:
        hits = []
        wd = SloWatchdog(
            [BurnRule.fault_rate(0.0, min_windows=1)],
            on_burn=lambda rule, w, ev: hits.append(
                (rule.name, w["window"], ev["value"])))
        wd.on_window({"window": 0, "faults_seen": 3})
        assert hits == [("fault_rate", 0, 3.0)]
        assert "slo_burn" in {e.get("kind") for e in rec.events}
    finally:
        flight.set_recorder(None)


def test_burn_rule_missing_metric_is_not_burning():
    r = BurnRule.p99_latency(10.0)
    assert r.value({"window": 0}) is None
    assert not r.burning({"window": 0,
                          "latency_ms": {"p99": None}})


# ---------------------------------------------------------------------------
# Ring + exposition (artifacts and validators)
# ---------------------------------------------------------------------------

def test_ring_is_crash_readable_without_close(tmp_path):
    ring = tmp_path / "pulse_ring.json"
    m, now = _mon(ring_path=str(ring))
    for i in range(3):
        now[0] = float(i)
        m.observe("completed", tenant="t0", latency_ms=1.0 + i)
    now[0] = 3.0
    m.advance()
    # No close(): the last flush (window close) must already have left
    # a complete, schema-valid document on disk — the SIGKILL story.
    doc = pulse.load_ring(str(ring))
    assert pulse.validate_ring(doc) == []
    assert doc["closed"] is None
    assert [w["window"] for w in doc["windows"]] == [0, 1, 2]
    assert doc["totals"]["per_tenant"]["t0"]["completed"] == 3


def test_exposition_parses_and_validator_catches_garbage():
    m, now = _mon()
    m.observe("submitted", tenant="t0")
    m.observe("admitted", tenant="t0", queue_depth=1)
    m.observe("completed", tenant="t0", latency_ms=2.5)
    now[0] = 1.0
    m.close()
    text = m.exposition_text()
    assert pulse.validate_exposition(text) == []
    assert 'pulse_requests_total{status="completed"} 1' in text
    bad = 'pulse_requests_total{status="ok" 12\nnot a line\n'
    problems = pulse.validate_exposition(bad)
    assert any("unparseable" in p for p in problems)
    assert any("missing required family" in p for p in problems)


def test_endpoint_scrapes_metrics_and_ring():
    m, now = _mon()
    m.observe("completed", tenant="t0", latency_ms=1.0)
    now[0] = 1.0
    m.advance()
    ep = PulseEndpoint(m, port=0).start()
    try:
        with urllib.request.urlopen(f"{ep.url}/metrics",
                                    timeout=10) as resp:
            assert pulse.validate_exposition(
                resp.read().decode()) == []
        with urllib.request.urlopen(f"{ep.url}/pulse.json",
                                    timeout=10) as resp:
            doc = json.loads(resp.read().decode())
        assert pulse.validate_ring(doc) == []
        assert doc["totals"]["completed"] == 1
        with urllib.request.urlopen(f"{ep.url}/healthz",
                                    timeout=10) as resp:
            assert resp.read() == b"ok\n"
    finally:
        ep.stop()


# ---------------------------------------------------------------------------
# Flight recorder: request context + concurrent writers
# ---------------------------------------------------------------------------

def test_request_context_nests_and_restores():
    assert flight.current_request() is None
    with flight.request_context("r1", "tenantA"):
        assert flight.current_request() == {"request_id": "r1",
                                            "tenant": "tenantA"}
        with flight.request_context("r2"):
            assert flight.current_request()["request_id"] == "r2"
        assert flight.current_request()["request_id"] == "r1"
    assert flight.current_request() is None


def test_flight_concurrent_writers_lose_nothing(tmp_path):
    path = tmp_path / "flight.json"
    rec = flight.FlightRecorder(str(path))
    n_threads, per = 8, 25

    def work(t):
        with flight.request_context(f"r{t:02d}", tenant=f"t{t}"):
            for i in range(per):
                rec.record("serve", f"ev{t}-{i}", i=i)

    threads = [threading.Thread(target=work, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(rec.events) == n_threads * per
    by_req = {}
    for e in rec.events:
        assert e["thread"]                    # writer thread stamped
        assert e["request_id"].startswith("r")
        by_req.setdefault(e["request_id"], []).append(e)
    assert len(by_req) == n_threads
    for evs in by_req.values():
        assert len(evs) == per                # no cross-thread bleed
    rec.seal("concurrency test done")
    doc = json.loads(path.read_text(encoding="utf-8"))
    assert len(doc["events"]) == n_threads * per


# ---------------------------------------------------------------------------
# Serve integration: correlation + stream-vs-report consistency
# ---------------------------------------------------------------------------

def test_every_serve_span_carries_request_id(factory):
    fac, n_rows = factory
    tracer = Tracer("pulse-test")
    srv = ArrowServer(fac, ExecConfig(),
                      policy=RetryPolicy(backoff_s=0.001),
                      tracer=tracer, name="pulse-span-test")
    run_trace(srv, synthetic_trace(n_rows, tenants=2, requests=3,
                                   k=K, iterations=2, seed=SEED))
    assert srv.summary()["completed"] == 3
    assert tracer.spans
    names = {s.name for s in tracer.spans}
    assert {"admission", "batch", "attempt", "finalize"} <= names
    for s in tracer.spans:
        assert s.args.get("request_id"), \
            f"span {s.name!r} lacks request_id"


def test_pulse_series_matches_slo_report(factory):
    fac, n_rows = factory
    now = [0.0]
    mon = PulseMonitor(window_s=1.0, clock=lambda: now[0],
                       name="pulse-report-test")
    srv = ArrowServer(fac, ExecConfig(),
                      policy=RetryPolicy(backoff_s=0.001),
                      name="pulse-report-test")
    srv.attach_pulse(mon)
    trace = synthetic_trace(n_rows, tenants=2, requests=4, k=K,
                            iterations=2, seed=SEED)
    tickets = []
    for r in trace:                       # one window per request
        tickets.append(srv.submit(r))
        srv.drain()
        now[0] += 1.0
        mon.advance()
    mon.close("test done")
    report = slo_summary(srv, tickets, now[0], pulse=mon)
    pt = report["pulse"]
    assert pt["totals"]["completed"] == report["completed"] == 4
    assert [w["completed"] for w in pt["windows"][:4]] == [1, 1, 1, 1]
    # The pooled stream reproduces the report's quantiles up to the
    # scheduler's ms rounding of the completed event.
    for q in ("p50", "p90", "p99"):
        assert pt["totals"]["latency_ms"][q] == pytest.approx(
            report["latency_ms"][q], abs=1e-2)
    assert pulse.validate_ring(mon.snapshot()) == []
    # HBM was sampled from the live accountant via attach_pulse.
    assert pt["totals"]["hbm"]["occupancy"] is not None


# ---------------------------------------------------------------------------
# Ring merging (graft-fleet: per-worker rings -> one exact fleet view)
# ---------------------------------------------------------------------------

def test_window_dicts_carry_raw_samples():
    """Window-level latency serializes its raw samples — the payload
    that makes cross-process ring merging lossless."""
    m, now = _mon()
    for i, ms in enumerate([3.0, 1.0, 4.0]):
        now[0] = float(i)
        m.observe("completed", latency_ms=ms)
    m.close()
    doc = m.snapshot()
    pooled = sorted(v for w in doc["windows"]
                    for v in w["latency_ms"]["samples"])
    assert pooled == [1.0, 3.0, 4.0]
    assert pulse.validate_ring(doc) == []


def _ring_doc(latencies, shed=0):
    m, now = _mon()
    for i, ms in enumerate(latencies):
        now[0] = float(i)
        m.observe("completed", latency_ms=ms)
    for _ in range(shed):
        m.observe("shed")
    m.close()
    return m.snapshot()


def test_merge_rings_is_exactly_pooled_and_asserts_per_ring():
    a = [3.0, 1.0, 4.0, 1.5]
    b = [9.0, 2.6, 5.3]
    merged = pulse.merge_rings([_ring_doc(a, shed=2), _ring_doc(b)])
    assert merged["problems"] == []
    assert merged["rings"] == 2
    assert merged["totals"]["completed"] == 7
    assert merged["totals"]["shed"] == 2
    pooled = Histogram()
    for v in a + b:
        pooled.observe(v)
    lat = merged["totals"]["latency_ms"]
    assert lat["count"] == 7
    for q, field in ((0.5, "p50"), (0.9, "p90"), (0.99, "p99")):
        assert lat[field] == pooled.quantile(q)
    assert [r["pooled_samples"] for r in merged["per_ring"]] == [4, 3]


def test_merge_rings_flags_sample_less_windows():
    doc = _ring_doc([3.0, 1.0])
    victim = next(w for w in doc["windows"]
                  if w["latency_ms"]["count"])
    del victim["latency_ms"]["samples"]
    merged = pulse.merge_rings([doc])
    assert any("sample" in p for p in merged["problems"])


def test_merge_rings_flags_pooled_streamed_mismatch():
    doc = _ring_doc([3.0, 1.0, 4.0])
    victim = next(w for w in doc["windows"]
                  if w["latency_ms"]["count"])
    victim["latency_ms"]["samples"] = [999.0]   # tampered window
    merged = pulse.merge_rings([doc])
    assert any("pooled" in p and "streamed" in p
               for p in merged["problems"])


def test_graft_pulse_merge_cli_round_trips(tmp_path, capsys):
    from arrow_matrix_tpu.cli import graft_pulse

    paths = []
    for i, lats in enumerate(([3.0, 1.0, 4.0, 1.5], [9.0, 2.6, 5.3])):
        p = tmp_path / f"ring{i}.json"
        with open(p, "w", encoding="utf-8") as fh:
            json.dump(_ring_doc(lats), fh)
        paths.append(str(p))
    out = str(tmp_path / "merged.json")
    assert graft_pulse.main(["merge", *paths, "--out", out]) == 0
    text = capsys.readouterr().out
    assert "2 ring(s), 7 pooled samples" in text
    with open(out, encoding="utf-8") as fh:
        merged = json.load(fh)
    assert merged["kind"] == "pulse_merge"
    assert merged["problems"] == []
    assert merged["totals"]["latency_ms"]["count"] == 7
    # A tampered source makes the CLI exit non-zero, loudly.
    with open(paths[0], encoding="utf-8") as fh:
        doc = json.load(fh)
    next(w for w in doc["windows"]
         if w["latency_ms"]["count"])["latency_ms"]["samples"] = [1e9]
    with open(paths[0], "w", encoding="utf-8") as fh:
        json.dump(doc, fh)
    assert graft_pulse.main(["merge", *paths]) == 1
