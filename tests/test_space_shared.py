"""Space-shared multi-matrix execution (parallel/space_shared.py) vs the
time-shared path and the scipy golden (reference semantics:
arrow/arrow_dec_mpi.py step(), tested there by tests/test_arrowmpi.py
test_decomposition / test_decomposition_on_graph)."""

import numpy as np
import pytest

from arrow_matrix_tpu.decomposition.decompose import (
    arrow_decomposition,
    decomposition_spmm,
)
from arrow_matrix_tpu.parallel.mesh import make_mesh
from arrow_matrix_tpu.parallel.multi_level import MultiLevelArrow
from arrow_matrix_tpu.parallel.space_shared import SpaceSharedArrow
from arrow_matrix_tpu.utils import numerics
from arrow_matrix_tpu.utils.graphs import barabasi_albert, random_dense


def _problem(n=512, w=32, max_levels=2, seed=0):
    a = barabasi_albert(n, 3, seed=seed)
    levels = arrow_decomposition(a, arrow_width=w, max_levels=max_levels,
                                 block_diagonal=True, seed=seed)
    return a, levels


def _tol(levels, iters=1):
    nnz = sum(l.matrix.nnz for l in levels)
    n = levels[0].matrix.shape[0]
    return numerics.relative_tolerance(nnz / n, iters)


@pytest.mark.parametrize("fmt", ["dense", "ell"])
def test_space_shared_matches_golden(fmt):
    _, levels = _problem()
    ss = SpaceSharedArrow(levels, 32, fmt=fmt)
    x_host = random_dense(512, 8, seed=1)

    got = ss.gather_result(ss.step(ss.set_features(x_host)))
    want = decomposition_spmm(levels, x_host)
    assert numerics.relative_error(got, want) < _tol(levels)


def test_space_shared_matches_time_shared_iterated():
    _, levels = _problem()
    x_host = random_dense(512, 8, seed=2)
    iters = 4

    ss = SpaceSharedArrow(levels, 32)
    got_space = ss.gather_result(ss.run(ss.set_features(x_host), iters))

    ml = MultiLevelArrow(levels, 32, mesh=None)
    got_time = ml.gather_result(ml.run(ml.set_features(x_host), iters))

    want = x_host.copy()
    for _ in range(iters):
        want = decomposition_spmm(levels, want)
    assert numerics.relative_error(got_space, want) < _tol(levels, iters)
    assert numerics.relative_error(got_time, want) < _tol(levels, iters)


def test_space_shared_four_groups_grown_last_level():
    # K=4 levels on a (4, 2) mesh; narrow base width forces a last level
    # whose achieved width exceeds the requested one (uniform banded
    # tiling must still capture every nonzero — checked structurally at
    # construction, numerically here).
    _, levels = _problem(w=16, max_levels=4)
    if len(levels) < 4:
        pytest.skip("decomposition terminated early")
    ss = SpaceSharedArrow(levels, 16, fmt="ell")
    x_host = random_dense(512, 4, seed=3)
    got = ss.gather_result(ss.step(ss.set_features(x_host)))
    want = decomposition_spmm(levels, x_host)
    assert numerics.relative_error(got, want) < _tol(levels)


def test_space_shared_explicit_mesh_and_validation():
    _, levels = _problem()
    mesh = make_mesh((2, 4), ("lvl", "blocks"))
    ss = SpaceSharedArrow(levels, 32, mesh=mesh)
    assert ss.mesh is mesh

    # Mesh whose lvl axis does not match the level count is rejected.
    bad = make_mesh((4, 2), ("lvl", "blocks"))
    with pytest.raises(ValueError, match="one slice per level"):
        SpaceSharedArrow(levels, 32, mesh=bad)


def test_directed_level_matrices():
    # Asymmetric (directed) adjacency through the space-shared path.
    rng = np.random.default_rng(0)
    from scipy import sparse

    n = 256
    a = sparse.random(n, n, density=0.02, random_state=rng,
                      format="csr", dtype=np.float32)
    levels = arrow_decomposition(a, arrow_width=32, max_levels=2,
                                 block_diagonal=True, seed=0)
    ss = SpaceSharedArrow(levels, 32)
    x_host = random_dense(n, 8, seed=4)
    got = ss.gather_result(ss.step(ss.set_features(x_host)))
    want = decomposition_spmm(levels, x_host)
    assert numerics.relative_error(got, want) < _tol(levels)
