"""graft-stream chunked overlap schedule: S static feature sub-slabs
per step, each running the full exchange+compute independently so the
latency-hiding scheduler can overlap slab i+1's collectives with slab
i's compute.

The contracts pinned here:
  * bit-identical f32 results for S in {1, 2, 4} on an 8-device CPU
    mesh (per-element addends never regroup — the split is along the
    feature axis, orthogonal to every accumulation);
  * S is STATIC: zero recompiles across iterations (the trace-time
    audit from analysis/audit.py);
  * validation — S must divide k, and overlap composes only with the
    unsharded feature axis (feat_axis=None);
  * the exposed_comm_ms model (obs/comm.py): modeled wire time / S,
    always present in a comm account.
"""

import numpy as np
import pytest

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from arrow_matrix_tpu.decomposition.decompose import arrow_decomposition
from arrow_matrix_tpu.parallel.mesh import make_mesh
from arrow_matrix_tpu.parallel.multi_level import MultiLevelArrow
from arrow_matrix_tpu.parallel.routing import (
    build_route,
    overlap_slices,
    routed_take_t,
)
from arrow_matrix_tpu.parallel.sell_slim import SellMultiLevel
from arrow_matrix_tpu.utils.graphs import barabasi_albert, random_dense


def test_overlap_slices_values_and_validation():
    assert overlap_slices(16, 1) == [(0, 16)]
    assert overlap_slices(16, 0) == [(0, 16)]
    assert overlap_slices(16, 4) == [(0, 4), (4, 8), (8, 12), (12, 16)]
    assert overlap_slices(8, 2) == [(0, 4), (4, 8)]
    with pytest.raises(ValueError, match="must divide"):
        overlap_slices(16, 3)
    with pytest.raises(ValueError, match="must divide"):
        overlap_slices(4, 8)


@pytest.fixture(scope="module")
def mesh():
    return make_mesh((8,), ("blocks",))


@pytest.fixture(scope="module")
def problem():
    a = barabasi_albert(1 << 10, 4, seed=0)
    levels = arrow_decomposition(a, 64, max_levels=3,
                                 block_diagonal=True, seed=0)
    x = random_dense(a.shape[0], 8, seed=1)
    return levels, x


@pytest.mark.parametrize("s", [1, 2, 4])
def test_sell_multi_level_overlap_bit_identical(mesh, problem, s):
    """The overlapped sell executor must be BIT-identical (f32) to the
    serial one: the schedule changes collective/compute interleaving,
    never the arithmetic."""
    levels, x = problem
    base = SellMultiLevel(levels, 64, mesh)
    ref = np.asarray(base.gather_result(base.step(base.set_features(x))))
    sm = SellMultiLevel(levels, 64, mesh, overlap_slabs=s)
    assert sm.overlap_slabs == s
    got = np.asarray(sm.gather_result(sm.step(sm.set_features(x))))
    np.testing.assert_array_equal(got, ref)


@pytest.mark.parametrize("s", [2, 4])
def test_multi_level_a2a_overlap_bit_identical(mesh, problem, s):
    levels, x = problem
    base = MultiLevelArrow(levels, 64, mesh=mesh, routing="a2a")
    ref = np.asarray(base.gather_result(base.step(base.set_features(x))))
    ml = MultiLevelArrow(levels, 64, mesh=mesh, routing="a2a",
                         overlap_slabs=s)
    got = np.asarray(ml.gather_result(ml.step(ml.set_features(x))))
    np.testing.assert_array_equal(got, ref)


def test_fold_overlap_bit_identical_and_pallas_sell(problem):
    """Single-chip fold: the overlap split slices the feature-major
    carriage — bit-identical under the XLA kernel; the fused
    pallas_sell kernel composes with the split within the numerics
    gate (different accumulation order is allowed across KERNELS,
    never across S)."""
    from arrow_matrix_tpu.utils import numerics

    levels, x = problem
    base = MultiLevelArrow(levels, 64, mesh=None, fmt="fold")
    ref = np.asarray(base.gather_result(base.step(base.set_features(x))))
    f2 = MultiLevelArrow(levels, 64, mesh=None, fmt="fold",
                         overlap_slabs=2)
    got = np.asarray(f2.gather_result(f2.step(f2.set_features(x))))
    np.testing.assert_array_equal(got, ref)

    fp = MultiLevelArrow(levels, 64, mesh=None, fmt="fold",
                         kernel="pallas_sell", overlap_slabs=2)
    gotp = np.asarray(fp.gather_result(fp.step(fp.set_features(x))))
    nnz = sum(int(lvl.matrix.nnz) for lvl in levels)
    err = numerics.relative_error(gotp, ref)
    assert err <= numerics.relative_tolerance(nnz / max(len(ref), 1))


def test_overlap_zero_recompiles(mesh, problem):
    """S is a static schedule: iterating the overlapped step must not
    recompile (the recompile audit is the acceptance gate — a dynamic
    slab boundary would retrace per call)."""
    from arrow_matrix_tpu.analysis.audit import audit_entry

    levels, x = problem
    for name, obj in (
            ("sell_multi_level_s2",
             SellMultiLevel(levels, 64, mesh, overlap_slabs=2)),
            ("multi_level_a2a_s2",
             MultiLevelArrow(levels, 64, mesh=mesh, routing="a2a",
                             overlap_slabs=2))):
        xt = obj.set_features(x)
        rec = audit_entry(
            name, obj.step_fn,
            lambda o=obj, v=xt: jax.block_until_ready(o.step(v)),
            lambda o=obj, v=xt: jax.eval_shape(o.step, v))
        assert rec["recompiles_second_call"] == 0, rec
        assert rec["compiles_first_call"] >= 1, rec


def test_overlap_must_divide_k(mesh, problem):
    levels, x = problem
    sm = SellMultiLevel(levels, 64, mesh, overlap_slabs=3)
    with pytest.raises(ValueError, match="must divide"):
        sm.step(sm.set_features(x))   # k=8, S=3: raised at trace time


def test_overlap_rejects_feat_axis(problem):
    levels, _ = problem
    mesh2 = make_mesh((4, 2), ("blocks", "feat"))
    with pytest.raises(ValueError, match="feat_axis"):
        SellMultiLevel(levels, 64, mesh2, routing="a2a",
                       feat_axis="feat", overlap_slabs=2)


def test_routed_take_t_overlap_matches_serial(mesh):
    rng = np.random.default_rng(0)
    total, k = 1024, 8
    table = rng.permutation(total)
    route = build_route(table, 8)
    x_host = rng.standard_normal((k, total)).astype(np.float32)
    xt = jax.device_put(x_host, NamedSharding(mesh, P(None, "blocks")))
    ref = np.asarray(jax.jit(
        lambda v: routed_take_t(v, route, mesh, "blocks"))(xt))
    got = np.asarray(jax.jit(
        lambda v: routed_take_t(v, route, mesh, "blocks",
                                overlap_slabs=2))(xt))
    np.testing.assert_array_equal(got, ref)
    with pytest.raises(ValueError, match="feat_axis"):
        routed_take_t(xt, route, mesh, "blocks", feat_axis="blocks",
                      overlap_slabs=2)


def test_exposed_comm_ms_model():
    """Exact at both ends: 0 bytes -> 0 ms; S=1 -> full wire time;
    S slabs -> 1/S of it (only the first slab's exchange is
    structurally un-hideable)."""
    from arrow_matrix_tpu.obs.comm import exposed_comm_ms

    assert exposed_comm_ms(0) == 0.0
    full = exposed_comm_ms(45_000_000, link_bytes_per_s=45e9)
    assert full == pytest.approx(1.0)   # 45 MB over 45 GB/s = 1 ms
    assert exposed_comm_ms(45_000_000, overlap_slabs=4,
                           link_bytes_per_s=45e9) == pytest.approx(0.25)
    # degenerate S values clamp to 1
    assert exposed_comm_ms(45_000_000, overlap_slabs=0,
                           link_bytes_per_s=45e9) == pytest.approx(1.0)


def test_account_collectives_always_reports_exposed(mesh, problem):
    """The comm account must carry exposed_comm_ms for every
    algorithm (tools/obs_gate.py rejects reports without it), scaled
    by the executor's overlap_slabs."""
    from arrow_matrix_tpu.obs.comm import account_collectives, ideal_bytes_for

    levels, x = problem
    reports = {}
    for s in (1, 2):
        sm = SellMultiLevel(levels, 64, mesh, overlap_slabs=s)
        xt = sm.set_features(x)
        rep = account_collectives(
            f"sell_s{s}", sm.step_fn, xt, *sm.step_operands(),
            ideal_bytes=ideal_bytes_for(sm, x.shape[1]),
            overlap_slabs=sm.overlap_slabs)
        assert "exposed_comm_ms" in rep
        assert rep["overlap_slabs"] == s
        reports[s] = rep
    assert reports[1]["measured_bytes"] == reports[2]["measured_bytes"]
    assert reports[2]["exposed_comm_ms"] == pytest.approx(
        reports[1]["exposed_comm_ms"] / 2)


def test_dryrun_multichip_mid_records_exposed(monkeypatch):
    """The opt-in mid-scale rung (VERDICT r4 item 7) at
    logic-validation size: both algorithms golden-gated, each record
    carrying the exposed_comm_ms field (fold proves the zero end)."""
    import __graft_entry__ as ge

    monkeypatch.setenv("AMT_DRYRUN_MID_LOGN", "11")
    out = ge.dryrun_multichip(8, scale="mid")
    assert set(out["algorithms"]) == {"fold", "sell_a2a"}
    fold, a2a = out["algorithms"]["fold"], out["algorithms"]["sell_a2a"]
    assert fold["exposed_comm_ms"] == 0.0
    assert a2a["exposed_comm_ms"] > 0
    assert a2a["overlap_slabs"] == 2
    assert out["host_load"] is not None
    with pytest.raises(ValueError, match="unknown scale"):
        ge.dryrun_multichip(8, scale="huge")
