"""graft-repl: 2.5D replicated arrow/SELL executors and the
model-driven replication planner.

The contracts pinned here (Lazzaro et al., arxiv 1705.10218, adapted
to the arrow decomposition):

  * the HONEST bit-identity deal — with the block count B fixed,
    buying replicas with extra devices (``make_repl_mesh(B*c, c)``)
    yields ``np.array_equal`` results at every c AND per-device
    measured collective bytes divided by EXACTLY c (each replica
    group runs the identical exchange program on a static k/c
    feature slab);
  * the single-chip ``fold`` column-group schedule (``repl=c`` with
    ``mesh=None``) is bit-identical by construction at zero comm;
  * validation — c must divide the device count and the feature
    width, ``repl_axis`` composes with ``feat_axis=None`` and
    ``routing="a2a"`` only (the GSPMD gather lowering assumes a
    replicated carriage and corrupts the divergent 2.5D slabs);
  * the planner — ``auto_repl`` certifies base×c against the HBM
    budget, minimizes the T(c) model, and degrades LOUDLY to c=1;
  * the checkpoint contract — ``merge_carries`` canonicalizes the
    divergent carriage into a fully replicated bit-exact resume
    state, and the Supervisor's ``canonicalize`` hook applies it
    before every save;
  * accounting — comm reports always carry ``repl``/``reduce_bytes``
    and tools/obs_gate.py rejects repl>1 reports without them.
"""

import numpy as np
import pytest

import jax

from arrow_matrix_tpu.decomposition.decompose import (
    arrow_decomposition,
    decomposition_spmm,
)
from arrow_matrix_tpu.parallel.mesh import (
    largest_replication,
    make_mesh,
    make_repl_mesh,
)
from arrow_matrix_tpu.parallel.multi_level import MultiLevelArrow
from arrow_matrix_tpu.parallel.routing import repl_slab_width
from arrow_matrix_tpu.parallel.sell_slim import SellMultiLevel, SellSlim
from arrow_matrix_tpu.utils.graphs import barabasi_albert, random_dense


# ---------------------------------------------------------------- mesh


def test_largest_replication_values():
    assert largest_replication(1) == 1
    assert largest_replication(2) == 1
    assert largest_replication(4) == 2
    assert largest_replication(8) == 2     # 8 % 16 != 0
    assert largest_replication(12) == 2
    assert largest_replication(16) == 4


def test_make_repl_mesh_shapes_and_validation():
    m = make_repl_mesh(8, 2)
    assert dict(m.shape) == {"blocks": 4, "repl": 2}
    # repl=1 degenerates to a trailing axis of extent 1 so one mesh
    # shape threads through both the replicated and baseline paths.
    m1 = make_repl_mesh(4, 1)
    assert dict(m1.shape) == {"blocks": 4, "repl": 1}
    with pytest.raises(ValueError, match="must divide"):
        make_repl_mesh(8, 3)
    with pytest.raises(ValueError, match=">= 1"):
        make_repl_mesh(8, 0)


def test_repl_slab_width_validation():
    assert repl_slab_width(16, 1) == 16
    assert repl_slab_width(16, 4) == 4
    with pytest.raises(ValueError, match="must divide"):
        repl_slab_width(16, 3)
    with pytest.raises(ValueError, match="must divide"):
        repl_slab_width(2, 4)


# ------------------------------------------------------------- planner


def test_repl_predict_ms_model():
    from arrow_matrix_tpu.obs.comm import repl_predict_ms

    bw = 45e9
    t1 = repl_predict_ms(1, 45_000_000, link_bytes_per_s=bw,
                         latency_s=0.0)
    assert t1 == pytest.approx(1.0)   # 45 MB over 45 GB/s = 1 ms
    # The wire term divides by exactly c; latency does not.
    assert repl_predict_ms(2, 45_000_000, link_bytes_per_s=bw,
                           latency_s=0.0) == pytest.approx(0.5)
    lat = repl_predict_ms(2, 0, n_coll=3, link_bytes_per_s=bw,
                          latency_s=1e-3)
    assert lat == pytest.approx(3.0)
    # The final-merge term is amortized over iterations and absent
    # at c=1 — the term that makes T(c) non-monotone.
    r = repl_predict_ms(2, 0, reduce_bytes=45_000_000, iterations=10,
                        link_bytes_per_s=bw, latency_s=0.0)
    assert r == pytest.approx(0.1)
    assert repl_predict_ms(1, 0, reduce_bytes=45_000_000,
                           link_bytes_per_s=bw, latency_s=0.0) == 0.0


def test_auto_repl_picks_certified_c():
    from arrow_matrix_tpu.obs.comm import auto_repl

    plan = auto_repl(8, 8, base_hbm_bytes=100,
                     budget_bytes=1000, exchange_bytes=1 << 20,
                     quiet=True)
    # Wire-dominated and everything fits: the largest c wins.
    assert plan["c"] == 4
    assert plan["feasible"] == [1, 2, 4]
    assert not plan["degraded"]
    assert plan["predicted_ms"][4] < plan["predicted_ms"][1]
    # Zero-comm problem: ties break toward c=1 (don't pay memory
    # for nothing).
    free = auto_repl(8, 8, base_hbm_bytes=100, budget_bytes=1000,
                     exchange_bytes=0, quiet=True)
    assert free["c"] == 1 and not free["degraded"]


def test_auto_repl_divisibility_rejections():
    from arrow_matrix_tpu.obs.comm import auto_repl

    plan = auto_repl(6, 8, base_hbm_bytes=100, budget_bytes=1000,
                     exchange_bytes=1 << 20, quiet=True)
    assert plan["c"] == 2
    assert "n_dev" in plan["rejected"][4]
    odd_k = auto_repl(8, 7, base_hbm_bytes=100, budget_bytes=1000,
                      exchange_bytes=1 << 20, quiet=True)
    assert odd_k["c"] == 1
    assert "feature width" in odd_k["rejected"][2]


def test_auto_repl_degrades_loudly(monkeypatch, capsys):
    from arrow_matrix_tpu.obs.comm import auto_repl

    monkeypatch.setenv("AMT_HBM_GB", "0.0000001")   # ~107 bytes
    plan = auto_repl(8, 8, base_hbm_bytes=100,
                     exchange_bytes=1 << 20)
    assert plan["c"] == 1
    assert plan["degraded"] is True
    assert "DEGRADED" in capsys.readouterr().err
    # c=1 stays feasible even when the base footprint itself is over
    # budget — the baseline is a capacity problem, not a plan choice.
    assert 1 in plan["feasible"]


def test_hbm_budget_env_override(monkeypatch):
    from arrow_matrix_tpu.obs.comm import hbm_budget_bytes

    monkeypatch.setenv("AMT_HBM_GB", "2")
    assert hbm_budget_bytes() == 2 * 2**30
    monkeypatch.delenv("AMT_HBM_GB")
    assert hbm_budget_bytes(default=123) == 123


def test_largest_fitting_repl_and_predicted_bytes():
    from arrow_matrix_tpu.obs.memview import (
        largest_fitting_repl,
        predicted_bytes_for,
    )

    assert largest_fitting_repl(100, 250) == 2
    assert largest_fitting_repl(100, 1000) == 8
    assert largest_fitting_repl(100, 50) == 1
    assert largest_fitting_repl(100, 250, choices=(1, 2, 4)) == 2

    class _NoRepl:
        def predicted_hbm_bytes(self, k, itemsize=4):
            return 100 * k * itemsize

    # Executors without the repl kwarg get the ×c planning multiplier.
    assert predicted_bytes_for(_NoRepl(), 2) == 800
    assert predicted_bytes_for(_NoRepl(), 2, repl=3) == 2400


# ----------------------------------------------------------- executors


@pytest.fixture(scope="module")
def problem():
    a = barabasi_albert(1 << 9, 4, seed=0)
    levels = arrow_decomposition(a, 32, max_levels=3,
                                 block_diagonal=True, seed=0)
    x = random_dense(a.shape[0], 8, seed=1)
    return levels, x


def test_fold_repl_bit_identical(problem):
    """The single-chip column-group schedule: repl=c sweeps c static
    k/c slabs through the same fold step — column-separable SpMM, so
    bit-identical to repl=1 at every c."""
    levels, x = problem
    want = decomposition_spmm(levels, x)
    base = None
    for c in (1, 2, 4):
        ml = MultiLevelArrow(levels, 32, mesh=None, fmt="fold", repl=c)
        got = ml.gather_result(ml.step(ml.set_features(x)))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
        if base is None:
            base = got
        assert np.array_equal(got, base), f"fold repl c={c} diverged"


def test_fold_repl_validation(problem):
    levels, _ = problem
    with pytest.raises(ValueError, match="fold"):
        MultiLevelArrow(levels, 32, mesh=None, fmt="ell", repl=2)
    with pytest.raises(ValueError, match="mesh"):
        MultiLevelArrow(levels, 32, mesh=make_mesh((4,), ("blocks",)),
                        fmt="ell", repl=2)
    with pytest.raises(ValueError, match=">= 1"):
        MultiLevelArrow(levels, 32, mesh=None, fmt="fold", repl=0)


def test_sell_repl_same_B_bit_identical_and_bytes_div_c(problem):
    """The honest 2.5D deal at fixed B=2 block shards: c replicas on
    B*c devices give np.array_equal results and measured per-device
    collective bytes divided by EXACTLY c — the identical exchange
    program runs on a k/c feature slab within each replica group."""
    from arrow_matrix_tpu.obs.comm import (
        account_collectives,
        ideal_bytes_for,
        reduce_bytes_for,
    )

    levels, x = problem
    k = x.shape[1]
    want = decomposition_spmm(levels, x)
    devs = jax.devices()
    base = None
    base_bytes = None
    for c in (1, 2, 4):
        mesh = make_repl_mesh(2 * c, c, devices=devs[:2 * c])
        sm = SellMultiLevel(levels, 32, mesh, routing="a2a",
                            repl_axis=("repl" if c > 1 else None))
        xt = sm.set_features(x)
        got = sm.gather_result(sm.step(xt))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
        if base is None:
            base = got
        assert np.array_equal(got, base), f"sell repl c={c} diverged"
        rep = account_collectives(
            f"sell_repl_c{c}", sm.step_fn, xt, *sm.step_operands(),
            ideal_bytes=ideal_bytes_for(sm, k), repl=sm.repl,
            reduce_bytes=reduce_bytes_for(sm, k))
        if base_bytes is None:
            base_bytes = rep["measured_bytes"]
        assert rep["measured_bytes"] * c == base_bytes, (
            f"c={c}: {rep['measured_bytes']} * {c} != {base_bytes}")
        assert rep["repl"] == c
        if c == 1:
            assert reduce_bytes_for(sm, k) == 0
        else:
            assert reduce_bytes_for(sm, k) > 0


def test_sell_slim_single_matrix_repl(problem):
    """SellSlim (one arrow matrix) carries the same repl_axis mode."""
    levels, x = problem
    lvl = levels[0]
    devs = jax.devices()
    mesh1 = make_mesh((2,), ("blocks",), devices=devs[:2])
    d1 = SellSlim(lvl.matrix, 32, mesh1)
    want = d1.gather_result(d1.spmm(d1.set_features(x)))
    mesh2 = make_repl_mesh(4, 2, devices=devs[:4])
    d2 = SellSlim(lvl.matrix, 32, mesh2, repl_axis="repl")
    assert d2.repl == 2
    got = d2.gather_result(d2.spmm(d2.set_features(x)))
    assert np.array_equal(got, want)


def test_repl_axis_validation(problem):
    levels, _ = problem
    mesh = make_repl_mesh(8, 2)
    with pytest.raises(ValueError, match="not a mesh axis"):
        SellMultiLevel(levels, 32, mesh, repl_axis="replicas")
    with pytest.raises(ValueError, match="must differ"):
        SellMultiLevel(levels, 32, mesh, axis="blocks",
                       repl_axis="blocks")
    with pytest.raises(ValueError, match="feat_axis"):
        SellMultiLevel(levels, 32, mesh, repl_axis="repl",
                       feat_axis="repl")
    # The GSPMD gather lowering assumes a replicated carriage; the
    # divergent 2.5D slabs corrupt under it (verified), so it is
    # forbidden outright rather than warned about.
    with pytest.raises(ValueError, match="a2a"):
        SellMultiLevel(levels, 32, mesh, routing="gather",
                       repl_axis="repl")


# --------------------------------------------------- checkpoint merge


def test_merge_carries_canonical_resume(problem):
    """merge_carries folds the divergent per-group slabs into the
    fully replicated canonical carriage: same gathered result, and
    stepping from the merged state is bit-identical to stepping from
    the divergent one (each group re-extracts its own slab, whose
    values only it contributed) — the bit-exact resume contract."""
    levels, x = problem
    devs = jax.devices()
    mesh = make_repl_mesh(4, 2, devices=devs[:4])
    sm = SellMultiLevel(levels, 32, mesh, routing="a2a",
                        repl_axis="repl")
    ct = sm.step(sm.set_features(x))
    merged = sm.merge_carries(ct)
    assert np.array_equal(sm.gather_result(merged),
                          sm.gather_result(ct))
    assert np.array_equal(sm.gather_result(sm.step(merged)),
                          sm.gather_result(sm.step(ct)))
    # Without a replica axis merge_carries is the identity.
    mesh1 = make_mesh((2,), ("blocks",), devices=devs[:2])
    s1 = SellMultiLevel(levels, 32, mesh1)
    c1 = s1.step(s1.set_features(x))
    assert s1.merge_carries(c1) is c1 or np.array_equal(
        np.asarray(s1.merge_carries(c1)), np.asarray(c1))


def test_supervisor_canonicalize_hook(tmp_path):
    """The Supervisor applies the executor-supplied canonicalize
    before every save — checkpoints of a replicated run hold the
    merged carriage, never replica 0's partial view."""
    from arrow_matrix_tpu.faults import Supervisor
    from arrow_matrix_tpu.utils.checkpoint import load_state

    calls = []

    def canon(x):
        calls.append(1)
        return x * 2.0

    ck = str(tmp_path / "ck")
    sup = Supervisor("t", carry=True, checkpoint_path=ck,
                     checkpoint_every=1, verbose=False,
                     canonicalize=canon)
    x0 = jax.numpy.ones((4, 4), np.float32)
    y, ok = sup.run(lambda x, it: x + 1.0, x0, 0, 2)
    assert ok and calls
    saved = load_state(ck)
    assert saved is not None and saved[1] == 2
    np.testing.assert_array_equal(np.asarray(saved[0]),
                                  np.asarray(y) * 2.0)


# ---------------------------------------------------------- accounting


def test_obs_gate_flags_incomplete_repl_report():
    import importlib

    obs_gate = importlib.import_module("tools.obs_gate")

    good = {"algorithms": {"a": {"exposed_comm_ms": 0.1, "repl": 2,
                                 "reduce_bytes": 64}}}
    assert obs_gate.comm_problems(good) == []
    bad = {"algorithms": {"a": {"exposed_comm_ms": 0.1, "repl": 2,
                                "reduce_bytes": None}}}
    assert any("reduce_bytes" in p for p in obs_gate.comm_problems(bad))
    ok1 = {"algorithms": {"a": {"exposed_comm_ms": 0.1, "repl": 1,
                                "reduce_bytes": 0}}}
    assert obs_gate.comm_problems(ok1) == []


def test_account_collectives_defaults_carry_repl_fields():
    from arrow_matrix_tpu import obs

    def f(x):
        return x * 2

    rep = obs.account_collectives(
        "plain", jax.jit(f), np.ones((4,), np.float32))
    assert rep["repl"] == 1
    assert rep["reduce_bytes"] == 0


# --------------------------------------------------------- scale rungs


def test_dryrun_repl_rung_enforces_contract(monkeypatch):
    """The scale-ladder repl rung at logic-validation size: fold and
    sell ladders both bit-identical at every c, sell bytes exactly
    ÷c, plus the 8-device c=1 production reference."""
    import __graft_entry__ as ge

    monkeypatch.setenv("AMT_DRYRUN_MID_LOGN", "11")
    out = ge.dryrun_multichip(8, scale="repl")
    assert out["scale"] == "repl" and out["B"] == 2
    fold = out["algorithms"]["fold_repl"]
    sell = out["algorithms"]["sell_a2a_repl"]
    for c in ("1", "2", "4"):
        assert fold[c]["bit_identical_to_c1"]
        assert fold[c]["measured_bytes"] == 0
        assert sell[c]["bit_identical_to_c1"]
    b1 = sell["1"]["measured_bytes"]
    assert sell["2"]["measured_bytes"] * 2 == b1
    assert sell["4"]["measured_bytes"] * 4 == b1
    assert sell["4"]["bytes_exactly_div_c"]
    assert "sell_a2a_8dev_reference" in out["algorithms"]
    with pytest.raises(ValueError, match="repl"):
        ge.dryrun_multichip(8, scale="huge")


def test_scale_ladder_registers_repl_rung():
    import importlib

    sl = importlib.import_module("tools.scale_ladder")

    assert "dryrun_repl_sweep" in sl.RUNGS
    assert "dryrun_repl_sweep" not in sl.DEFAULT_RUNGS


# ---------------------------------------------------------------- CLI


def test_spmm_arrow_repl_cli_validates(tmp_path, monkeypatch):
    from arrow_matrix_tpu.cli import spmm_arrow

    monkeypatch.chdir(tmp_path)
    rc = spmm_arrow.main([
        "--vertices", "400", "--width", "32", "--features", "4",
        "--iterations", "2", "--validate", "true", "--device", "cpu",
        "--devices", "4", "--fmt", "sell", "--repl", "2",
        "--logdir", str(tmp_path / "logs"),
    ])
    assert rc == 0


def test_spmm_arrow_repl_flag_errors(tmp_path, monkeypatch):
    from arrow_matrix_tpu.cli import spmm_arrow

    monkeypatch.chdir(tmp_path)
    base = ["--vertices", "300", "--width", "32", "--features", "4",
            "--iterations", "1", "--device", "cpu",
            "--logdir", str(tmp_path / "logs")]
    with pytest.raises(SystemExit, match="slim"):
        spmm_arrow.main(base + ["--repl", "2", "--slim", "false"])
    with pytest.raises(SystemExit, match="time"):
        spmm_arrow.main(base + ["--repl", "2", "--mode", "space"])
    with pytest.raises(SystemExit, match="a2a"):
        spmm_arrow.main(base + ["--repl", "2", "--routing", "gather"])
