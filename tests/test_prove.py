"""graft-prove (arrow_matrix_tpu.analysis.prove) — the HLO-level
collective-contract verifier.

Covers the three layers of the gate:

* **Fixture verdicts** (host-only, no lowering): the checked-in repl=2
  HLO fixture conforms to the pinned fixture contract and the
  intentionally-broken sibling (planted surprise all-gather) fails
  H1-H3 — the demonstration that ``tools/proof_gate.py`` exits nonzero
  when a surprise collective or a broken repl byte contract appears.
* **The live prover at reduced scale**: every contracted executor over
  the (c, S) grid lowers on the shared CPU pool and proves H1-H6, and
  the fresh run does not drift from the checked-in
  ``bench_cache/hlo_manifest.json``.
* **The H5 donation sweep** (the bugfix-sweep satellite): the donated
  scan entry points must show real input-output aliasing in compiled
  HLO, and every exempt executor must carry a recorded skip reason —
  no silent coverage shrink.
"""

import json
import os
import subprocess
import sys

import pytest

from arrow_matrix_tpu.analysis import prove

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXDIR = os.path.join(REPO, "tests", "fixtures")
GOOD = os.path.join(FIXDIR, "collectives_repl2.hlo")
BROKEN = os.path.join(FIXDIR, "collectives_repl2_broken.hlo")
MANIFEST = os.path.join(REPO, "bench_cache", "hlo_manifest.json")


def _read(path):
    with open(path, encoding="utf-8") as fh:
        return fh.read()


# ---------------------------------------------------------------------------
# Host-only: selftest + fixture verdicts (H1-H3)
# ---------------------------------------------------------------------------


def test_selftest_trips_on_planted_surprise():
    assert prove.selftest()


def test_repl2_fixture_conforms():
    results = prove.verify_fixture(_read(GOOD))
    assert results["ok"], results
    for rule in ("H1", "H2", "H3"):
        assert results[rule]["status"] == "pass", results[rule]


def test_broken_fixture_fails_h1_h2_h3():
    """The planted all-gather must trip all three: an undeclared kind
    (H1), 8192 extra bytes blowing the ratio band (H2), and an 8-row
    output violating the k/(c*S)=4 slab law (H3)."""
    results = prove.verify_fixture(_read(BROKEN))
    assert not results["ok"]
    for rule in ("H1", "H2", "H3"):
        assert results[rule]["status"] == "fail", results[rule]
    assert "all-gather" in results["H1"]["detail"]


def test_fixture_contract_matches_good_fixture_bytes():
    """The pinned contract and the checked-in fixture must agree
    exactly: 2048 B tuple all-to-all + 1024 B all-reduce."""
    c = prove.fixture_contract()
    summ = prove.summarize_hlo(_read(GOOD))
    assert summ.total_bytes == c.step_bytes == 3072
    assert c.expected_slab(8) == 4


def test_proof_gate_fixture_mode_exit_codes():
    """tools/proof_gate.py --fixture is the CLI demonstration that the
    gate exits nonzero on a planted surprise all-gather."""
    for path, rc in ((GOOD, 0), (BROKEN, 1)):
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "proof_gate.py"),
             "--fixture", path],
            capture_output=True, text=True, timeout=120, cwd=REPO)
        assert proc.returncode == rc, (path, proc.stdout, proc.stderr)
    assert "VIOLATES" in proc.stdout


# ---------------------------------------------------------------------------
# The live prover at reduced scale + drift against the checked-in
# manifest (the tier-1 invariant tools/proof_gate.py runs standalone).
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def fresh_manifest():
    return prove.run_prove(write=False, **prove.PROVE_SCALE)


def test_prover_proves_all_contracts(fresh_manifest):
    assert fresh_manifest["ok"], json.dumps(
        [e for e in fresh_manifest["entries"] if not e["ok"]], indent=2)
    names = {e["entry"] for e in fresh_manifest["entries"]}
    # The (c, S) grid: every executor at c in {1,2} and S in {1,2}
    # proves or records a skip reason — never silently disappears.
    for algo in ("spmm_1d", "spmm_15d", "sell_slim", "sell_multi",
                 "multi_level"):
        assert any(algo in n for n in names), (algo, sorted(names))
    for combo in ("[c=1", "[c=2", "S=1]", "S=2]"):
        assert any(combo in n for n in names), (combo, sorted(names))
    assert all(s["reason"] for s in fresh_manifest["skipped"]), (
        "every skipped grid cell must record a reason")


def test_manifest_checked_in_ok_and_no_drift(fresh_manifest):
    with open(MANIFEST, encoding="utf-8") as fh:
        checked_in = json.load(fh)
    assert checked_in["ok"]
    drift = prove.manifest_drift(checked_in, fresh_manifest)
    assert drift == [], "\n".join(drift)


def test_repl2_entries_obey_div_c_and_priced_merge(fresh_manifest):
    """H3 on the real executors: every repl=2 sell entry's merge
    program prices exactly reduce_comm_bytes (deferred psum), and the
    rule records a pass (slab ÷c law held in every lowered shape)."""
    repl2 = [e for e in fresh_manifest["entries"]
             if e["contract"]["repl"] == 2 and not e["contract"]["h3_exempt"]]
    assert repl2, "no repl=2 entries proved"
    for e in repl2:
        assert e["rules"]["H3"]["status"] == "pass", e["rules"]["H3"]
        assert (e["measured"]["merge_bytes"]
                == e["contract"]["reduce_bytes"]), e["entry"]


def test_h5_donation_sweep(fresh_manifest):
    """The bugfix-sweep satellite, pinned: the donated scan entry
    points (SellMultiLevel._scan_donated, MultiLevelArrow.
    _scan_steps_donated) must alias their donated carry (param 0) in
    compiled HLO; executors without a donated entry point must record
    an explicit skip, not a hollow pass."""
    donated = skipped = 0
    for e in fresh_manifest["entries"]:
        h5 = e["rules"]["H5"]
        if e["contract"]["donated_params"]:
            assert h5["status"] == "pass", (e["entry"], h5)
            assert 0 in e["measured"]["aliased_params"], e["entry"]
            donated += 1
        else:
            assert h5["status"] == "skip", (e["entry"], h5)
            skipped += 1
    assert donated >= 4 and skipped >= 1, (donated, skipped)


def test_h1_h6_statuses_recorded_for_every_entry(fresh_manifest):
    for e in fresh_manifest["entries"]:
        assert set(e["rules"]) == set(prove.RULE_IDS), e["entry"]
        for rule, r in e["rules"].items():
            assert r["status"] in ("pass", "fail", "skip"), (e["entry"], rule)
