"""graft-kcert tests: the static Pallas kernel certifier (KC1-KC5).

Covers the certifier's own selftest twins, the planted-broken-kernel
fixtures (each fires EXACTLY its rule), the shipped two-kernel
manifest (clean + drift-free against the checked-in
bench_cache/kernel_manifest.json), the ONE streaming-gate predicate
shared by the kernel and the tuner (they can never disagree), tune
pruning of uncertifiable candidates BEFORE any child spawns, the
generated-program registration hook, and the kind="kcert" ledger
record the drift gate bands on rule counts.
"""

import json
import os
import subprocess
import sys

import pytest

from arrow_matrix_tpu.analysis import kernels as kcert
from arrow_matrix_tpu.ledger import gate as ledger_gate
from arrow_matrix_tpu.ledger.store import Ledger
from arrow_matrix_tpu.ops.kernel_contract import (
    KernelContract,
    KernelEntry,
    builtin_kernels,
    register_kernel,
    registered_kernels,
    unregister_kernel,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE_DIR = os.path.join(REPO, "tests", "fixtures", "kernels")
MANIFEST = os.path.join(REPO, "bench_cache", "kernel_manifest.json")
FIXTURES = sorted(
    os.path.join(FIXTURE_DIR, f) for f in os.listdir(FIXTURE_DIR)
    if f.startswith("kc") and f.endswith(".py"))


# ---------------------------------------------------------------------------
# Selftest + fixtures (host-only: no jax)
# ---------------------------------------------------------------------------

def test_selftest_green():
    ok, lines = kcert.selftest()
    assert ok, "\n".join(lines)


def test_fixtures_exist_one_per_rule():
    got = sorted(kcert.fixture_contract(p) for p in FIXTURES)
    assert got == sorted(kcert.RULE_IDS)


@pytest.mark.parametrize("path", FIXTURES,
                         ids=[os.path.basename(p) for p in FIXTURES])
def test_fixture_fires_exactly_its_rule(path):
    ok, detail = kcert.verify_fixture(path)
    assert ok, detail
    # Exclusivity: the planted violation trips its own rule and ONLY
    # its own rule — collateral findings would mean the fixture (or a
    # checker) is sloppier than it claims.
    expected = kcert.fixture_contract(path)
    fired = {f.rule for f in kcert.certify_paths([path])}
    assert fired == {expected}, (expected, sorted(fired))


def test_kernel_gate_paths_nonzero_on_fixture():
    # The CI wrapper treats a planted fixture as a real kernel file:
    # certification must FAIL loudly (nonzero exit).
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "kernel_gate.py"),
         "--paths", FIXTURES[0]],
        capture_output=True, text=True, timeout=120, cwd=REPO)
    assert proc.returncode != 0, proc.stdout + proc.stderr


# ---------------------------------------------------------------------------
# Shipped kernels: clean + drift-free manifest
# ---------------------------------------------------------------------------

def test_shipped_kernels_certify_clean_and_drift_free():
    records = kcert.certify_all()
    fresh = kcert.build_manifest(records)
    assert fresh["ok"], [r["findings"] for r in records]
    # 2 built-in kernels + the persisted graft-synth program the lazy
    # registry loads from bench_cache/synth_programs.json.
    assert fresh["counts"]["kernels"] == 3
    with open(MANIFEST, encoding="utf-8") as fh:
        committed = json.load(fh)
    problems = kcert.manifest_drift(committed, fresh)
    assert not problems, problems


def test_manifest_volatile_keys_do_not_drift():
    records = kcert.certify_all()
    a = kcert.build_manifest(records)
    b = dict(kcert.build_manifest(records))
    b["timestamp"] = "1970-01-01T00:00:00"
    b["platform"] = "somewhere-else"
    assert not kcert.manifest_drift(a, b)


# ---------------------------------------------------------------------------
# The ONE streaming-gate predicate (kernel == tuner, never disagree)
# ---------------------------------------------------------------------------

def test_streaming_gate_predicate_is_shared():
    from arrow_matrix_tpu.ops.pallas_sell import (
        KERNEL_CONTRACT,
        supported_feature_width,
    )

    for k in range(1, 257):
        assert supported_feature_width(k) == KERNEL_CONTRACT.supports_k(k)


@pytest.mark.parametrize("k", [16, 17, 32, 48, 100, 128])
def test_tune_pruning_agrees_with_kernel_predicate(k):
    from arrow_matrix_tpu.ops.pallas_sell import supported_feature_width
    from arrow_matrix_tpu.tune.space import enumerate_candidates

    fp = {"ladder": {"slots": [1024], "rows": [128]},
          "total_rows": 128, "binary": False, "n": 128}
    cands, pruned = enumerate_candidates(fp, k, platform="tpu")
    kept = {c.name for c in cands}
    if supported_feature_width(k):
        assert "pallas_sell" in kept
    else:
        assert "pallas_sell" not in kept
        assert "k % 16 == 0" in pruned["pallas_sell"]


# ---------------------------------------------------------------------------
# kcert pruning: uncertifiable candidates die before any child spawns
# ---------------------------------------------------------------------------

def test_uncertifiable_candidate_pruned_with_kcert_reason():
    from arrow_matrix_tpu.tune.space import Candidate, enumerate_candidates

    fp = {"ladder": {"slots": [1024], "rows": [128]},
          "total_rows": 128, "binary": False, "n": 128}
    bad = Candidate("pallas_bad_ring",
                    build={"kernel": "pallas_sell"},
                    kernel_opts={"ring": 0})
    cands, pruned = enumerate_candidates(fp, 16, platform="tpu",
                                         extra=[bad])
    # Pruned at enumeration time — the search loop only spawns child
    # processes for surviving candidates, so this is the zero-children
    # guarantee.
    assert "pallas_bad_ring" not in {c.name for c in cands}
    assert pruned["pallas_bad_ring"].startswith("kcert:")


def test_certify_candidate_opts_reasons():
    assert kcert.certify_candidate_opts({}, 16) is None
    assert kcert.certify_candidate_opts({}, 16,
                                        feature_dtype="bf16") is None
    reason = kcert.certify_candidate_opts({"ring": 0}, 16)
    assert reason is not None and reason.startswith("kcert:")
    reason = kcert.certify_candidate_opts({}, 17)
    assert reason is not None and "k % 16" in reason
    # Interpret evaluators run the vectorized body: k is not gated.
    assert kcert.certify_candidate_opts({}, 17, interpret=True) is None
    reason = kcert.certify_candidate_opts({}, 16, feature_dtype="f64")
    assert reason is not None and reason.startswith("kcert:")


def test_bf16_pallas_candidate_is_approx_class_only():
    from arrow_matrix_tpu.tune.space import enumerate_candidates

    fp = {"ladder": {"slots": [1024], "rows": [128]},
          "total_rows": 128, "binary": False, "n": 128}
    for traffic_class, eligible in (("exact", False), ("approx", True)):
        cands, _ = enumerate_candidates(fp, 16, platform="tpu",
                                        traffic_class=traffic_class)
        by_name = {c.name: c for c in cands}
        assert "pallas_sell_bf16" in by_name
        assert by_name["pallas_sell_bf16"].eligible is eligible


# ---------------------------------------------------------------------------
# Generated-program hook
# ---------------------------------------------------------------------------

def test_registered_kernel_rides_certification():
    broken = kcert._broken_meta(grid=[["i", 5]])
    broken = dict(broken, kernel="generated_oob")
    contract = KernelContract(name="generated_oob", module="<gen>",
                              kind="sell_stream",
                              smem_cols_budget=1 << 20,
                              vmem_budget_bytes=8 << 20)
    entry = KernelEntry(contract=contract, metas=lambda: [broken],
                        source_path=None)
    register_kernel(entry)
    try:
        names = [e.name for e in registered_kernels()]
        assert "generated_oob" in names
        rec = kcert.certify_entry(entry)
        assert not rec["ok"]
        assert rec["rules"]["KC1"]["status"] == "fail"
    finally:
        unregister_kernel("generated_oob")
    assert all(e.name != "generated_oob" for e in registered_kernels())
    assert len(builtin_kernels()) == 2


# ---------------------------------------------------------------------------
# Ledger: kind="kcert" rule-count drift gate
# ---------------------------------------------------------------------------

def test_kcert_ledger_record_and_count_regression_gate(tmp_path):
    lg = Ledger(str(tmp_path))
    rec = lg.record("kcert", "rules_pass", 10.0, unit="count",
                    host_load=None,
                    knobs={"kernels": 2, "points": 11},
                    payload={"findings": 0, "ok": True})
    baseline = ledger_gate.build_baseline([rec])
    same = dict(rec, value=10.0)
    failures, _ = ledger_gate.check_records([same], baseline)
    assert not failures, failures
    worse = lg.record("kcert", "rules_pass", 9.0, unit="count",
                      host_load=None,
                      knobs={"kernels": 2, "points": 11},
                      payload={"findings": 1, "ok": False})
    failures, _ = ledger_gate.check_records([worse], baseline)
    assert failures and "kcert regression" in failures[0]


def test_run_kernels_records_rule_count(tmp_path, monkeypatch):
    monkeypatch.setenv("AMT_LEDGER", "1")
    out = str(tmp_path / "manifest.json")
    manifest = kcert.run_kernels(out_path=out, write=True,
                                 ledger_dir=str(tmp_path), record=True)
    assert os.path.exists(out)
    recs = Ledger(str(tmp_path)).read_all()
    assert len(recs) == 1 and recs[0]["kind"] == "kcert"
    assert recs[0]["value"] == float(manifest["counts"]["rules_pass"])


# ---------------------------------------------------------------------------
# CLI round trip
# ---------------------------------------------------------------------------

def test_cli_check_mode_green():
    proc = subprocess.run(
        [sys.executable, "-m", "arrow_matrix_tpu.analysis", "kernels",
         "--check"],
        capture_output=True, text=True, timeout=300, cwd=REPO,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "kernel certification passed" in proc.stdout
