"""Unit tests for the tunnel-recovery machinery (utils.platform):
probe-error classification, the stale-holder kill guards, the
preemptible-job registry, and bench.py's on-chip evidence selection.
This code only runs for real against a wedged accelerator, so the
deterministic pieces must be pinned here."""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from arrow_matrix_tpu.utils import platform as plat

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_classify_probe_error():
    assert plat.classify_probe_error(None) is None
    assert plat.classify_probe_error(
        "backend probe timed out after 60s (PJRT plugin init hang)"
    ) == "init-hang"
    assert plat.classify_probe_error(
        "rc=1: Backend 'axon' is not in the list of known backends"
    ) == "no-device"
    assert plat.classify_probe_error("rc=1: ImportError: boom") == "error"


def test_reset_noop_under_fresh_busy_lock(tmp_path, monkeypatch):
    """A fresh tpu_busy.lock means an on-chip stage is in flight:
    recovery must refuse to touch anything."""
    lock = os.path.join(REPO, "bench_cache", "tpu_busy.lock")
    existed = os.path.exists(lock)
    try:
        with open(lock, "w") as f:
            f.write("test\n")
        assert plat.reset_tunnel_state(min_flat_s=0.1) == []
    finally:
        if not existed:
            try:
                os.remove(lock)
            except OSError:
                pass


def test_preemptible_registry_roundtrip():
    """register/read via a child process: the token self-cleans at
    exit, a dead pid's stale token never matches, malformed tokens are
    skipped individually."""
    path = plat.preempt_registry_path()
    code = (
        "import os, sys, time; "
        f"sys.path.insert(0, {REPO!r}); "
        "from arrow_matrix_tpu.utils import platform as p; "
        "p.register_preemptible(); "
        "print(os.getpid(), flush=True); "
        "time.sleep(10)")
    proc = subprocess.Popen([sys.executable, "-c", code],
                            stdout=subprocess.PIPE, text=True)
    try:
        child_pid = int(proc.stdout.readline().split()[0])
        deadline = time.time() + 5
        while time.time() < deadline:
            if child_pid in plat.read_preemptible():
                break
            time.sleep(0.1)
        assert child_pid in plat.read_preemptible()
    finally:
        proc.terminate()
        proc.wait(timeout=10)
    # stale token (if atexit didn't fire on terminate) must not match:
    # the pid is dead, so starttime verification rejects it.
    assert child_pid not in plat.read_preemptible()
    # malformed tokens are skipped, valid ones survive
    me = os.getpid()
    start = plat.proc_starttime(me)
    try:
        with open(path, "a") as f:
            f.write(f"garbage\n12x:34\n{me}:{start}\n")
        assert me in plat.read_preemptible()
    finally:
        # remove our test tokens
        with open(path) as f:
            toks = [t for t in f.read().split()
                    if t not in ("garbage", "12x:34", f"{me}:{start}")]
        with open(path, "w") as f:
            f.write("\n".join(toks) + ("\n" if toks else ""))


def test_cpu_ticks_and_starttime():
    assert plat._cpu_ticks(os.getpid()) >= 0
    assert plat.proc_starttime(os.getpid()) is not None
    assert plat._cpu_ticks(2**22 + 12345) is None   # unlikely pid


def test_last_onchip_evidence_selection(tmp_path, monkeypatch):
    """Newest spmm_iter_ms artifact wins; non-headline metrics are
    skipped; same-config k128 merges in with provenance; a different
    config's k128 does NOT."""
    sys.path.insert(0, REPO)
    import bench

    bdir = tmp_path / "bench_results"
    bdir.mkdir()
    (tmp_path / "bench_cache").mkdir()
    cfg = {"n": 1024, "width": 64, "features": 16}
    other_cfg = {"n": 2048, "width": 64, "features": 16}

    def write(name, payload, age_s):
        p = bdir / name
        p.write_text(json.dumps(payload) + "\n")
        t = time.time() - age_s
        os.utime(p, (t, t))
        return p

    write("onchip_full.json",
          {"metric": "spmm_iter_ms", "value": 100.0, "config": cfg,
           "k128_ms": 110.0, "k128_err": 1e-7}, age_s=300)
    write("onchip_ladder.json",
          {"metric": "ladder_race", "value": 55.0}, age_s=100)
    write("onchip_foldonly.json",
          {"metric": "spmm_iter_ms", "value": 99.0, "config": cfg},
          age_s=200)
    monkeypatch.chdir(tmp_path)
    ev = bench._last_onchip_evidence()
    assert ev["path"].endswith("onchip_foldonly.json")   # newest headline
    assert ev["summary"]["value"] == 99.0
    assert ev["summary"]["k128_ms"] == 110.0             # merged
    assert ev["summary"]["k128_from"].endswith("onchip_full.json")
    # different-config k128 must not merge
    write("onchip_other.json",
          {"metric": "spmm_iter_ms", "value": 98.0,
           "config": other_cfg}, age_s=50)
    ev2 = bench._last_onchip_evidence()
    assert ev2["path"].endswith("onchip_other.json")
    assert "k128_ms" not in ev2["summary"]


def test_signal_job_descendants():
    """The watcher's _signal_job pauses a job's subprocess child too."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "tw_test", os.path.join(REPO, "tools", "tunnel_watcher.py"))
    tw = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(tw)

    code = ("import subprocess, sys, time; "
            "c = subprocess.Popen([sys.executable, '-c', "
            "'import time; time.sleep(30)']); "
            "print(c.pid, flush=True); time.sleep(30)")
    proc = subprocess.Popen([sys.executable, "-c", code],
                            stdout=subprocess.PIPE, text=True)
    try:
        grandchild = int(proc.stdout.readline().split()[0])
        tw._signal_job(proc.pid, signal.SIGSTOP)
        time.sleep(0.3)

        def state(pid):
            with open(f"/proc/{pid}/stat") as f:
                return f.read().split(")")[-1].split()[0]

        assert state(proc.pid) == "T", "parent not stopped"
        assert state(grandchild) == "T", "child not stopped"
        tw._signal_job(proc.pid, signal.SIGCONT)
        time.sleep(0.3)
        assert state(proc.pid) in ("S", "R")
        assert state(grandchild) in ("S", "R")
    finally:
        for p in (proc.pid, ):
            try:
                os.kill(p, signal.SIGCONT)
            except OSError:
                pass
        proc.kill()
        proc.wait(timeout=10)
        try:
            os.kill(grandchild, signal.SIGKILL)
        except OSError:
            pass
