"""Multi-process distributed execution: 2 REAL processes x 2 virtual
CPU devices each, gloo cross-process collectives, one global 4-device
mesh — the framework's multi-host story exercised end-to-end.

The reference emulates multi-node with ``mpiexec --oversubscribe``
(reference scripts/run_tests.sh, tests/test_arrowmpi.py:11-17); the
in-process virtual meshes elsewhere in this suite cover many-device
semantics but share one process and one backend.  This test is the
process-boundary analog: ``jax.distributed.initialize`` + gloo, builder
placement via ``put_global`` (each process materializes only its
addressable shards), result collection via ``fetch_replicated`` (one
cross-host all-gather).
"""

import os
import socket
import subprocess
import sys

import pytest

CHILD = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "_multihost_child.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


FAIL_CHILD = r"""
import os, sys, time
sys.path.insert(0, {repo!r})
pid, port = int(sys.argv[1]), sys.argv[2]
from arrow_matrix_tpu.parallel.mesh import initialize_multihost
try:
    initialize_multihost(f"127.0.0.1:{{port}}", 2, pid, cpu_devices=2,
                         heartbeat_timeout_seconds=10)
except Exception as e:
    print(f"CHILD_SKIP {{type(e).__name__}}: {{e}}", flush=True)
    sys.exit(0)
import jax, numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P
from jax.experimental.shard_map import shard_map
from arrow_matrix_tpu.parallel.mesh import make_mesh, put_global
mesh = make_mesh((4,), ("blocks",))
f = jax.jit(shard_map(lambda v: jax.lax.psum(v, "blocks"), mesh=mesh,
            in_specs=P("blocks"), out_specs=P()))
x = put_global(np.arange(8, dtype=np.float32),
               NamedSharding(mesh, P("blocks")))
for it in range(1000):
    if pid == 1 and it == 3:
        os._exit(17)              # simulated host crash mid-run
    float(np.asarray(f(x).addressable_data(0))[0])
    time.sleep(0.2)
"""


@pytest.mark.slow
def test_peer_death_aborts_whole_job():
    """Failure detection across processes: when one process dies
    mid-iteration, the coordination service's missed-heartbeat fatal
    aborts the survivor within ~2x the heartbeat timeout — the
    whole-job abort of the reference's collective failure flag
    (arrow_bench.py:128-134), provided by the runtime instead of a
    per-iteration allreduce.  The survivor must EXIT (nonzero), never
    hang."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    port = _free_port()
    procs = [subprocess.Popen(
        [sys.executable, "-u", "-c", FAIL_CHILD.format(repo=repo),
         str(i), str(port)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        for i in range(2)]
    try:
        try:
            out1, _ = procs[1].communicate(timeout=120)
        except subprocess.TimeoutExpired:
            # Proc 1 can be stuck in the 300s init barrier because the
            # COORDINATOR failed to start (port TOCTOU etc.) — that is
            # an environment skip, not a detection failure.
            if (procs[0].poll() == 0
                    and "CHILD_SKIP" in (procs[0].stdout.read() or "")):
                pytest.skip("distributed runtime unavailable "
                            "(coordinator failed to start)")
            raise
        if procs[1].returncode == 0 and "CHILD_SKIP" in out1:
            pytest.skip(f"distributed runtime unavailable: "
                        f"{out1.strip()}")
        assert procs[1].returncode == 17      # the simulated crash
        # communicate (not wait): the survivor's fatal pours JAX/gloo
        # error output into the PIPEs, and an undrained pipe would
        # block it in write() — a false "hang".
        out0, _ = procs[0].communicate(timeout=120)
        if procs[0].returncode == 0 and "CHILD_SKIP" in out0:
            pytest.skip(f"distributed runtime unavailable: "
                        f"{out0.strip()}")
        assert procs[0].returncode != 0       # abort loudly, not hang
    except subprocess.TimeoutExpired:
        raise AssertionError(
            "survivor hung after peer death (no failure detection)")
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()


import functools


@functools.lru_cache(maxsize=1)
def _distributed_available() -> bool:
    """One cached 2-process init probe (the CLI raises rather than
    printing CHILD_SKIP, so CLI-based tests need their own skip
    signal)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    code = (f"import sys; sys.path.insert(0, {repo!r})\n"
            "from arrow_matrix_tpu.parallel.mesh import "
            "initialize_multihost\n"
            "initialize_multihost(f'127.0.0.1:{port}', 2, "
            "int(__import__('sys').argv[1]), cpu_devices=1)\n"
            "print('INIT_OK')")
    port = _free_port()
    procs = [subprocess.Popen(
        [sys.executable, "-c", code.replace("{port}", str(port)),
         str(i)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for i in range(2)]
    try:
        import concurrent.futures as cf

        with cf.ThreadPoolExecutor(2) as ex:
            outs = list(ex.map(lambda p: p.communicate(timeout=90),
                               procs))
        return all(p.returncode == 0 and "INIT_OK" in out
                   for p, (out, _) in zip(procs, outs))
    except subprocess.TimeoutExpired:
        return False
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()


def _run_cli_pair(args: list, cwd: str, timeout: float = 420):
    """Launch the spmm_arrow CLI as 2 coordinated processes from the
    same cwd, drain both concurrently, return [(rc, out+err), ...]."""
    import concurrent.futures as cf

    port = _free_port()
    env = dict(os.environ,
               PYTHONPATH=os.pathsep.join(
                   [os.path.dirname(os.path.dirname(
                       os.path.abspath(__file__)))]
                   + [p for p in os.environ.get(
                       "PYTHONPATH", "").split(os.pathsep) if p]))
    cmd = [sys.executable, "-m", "arrow_matrix_tpu.cli.spmm_arrow",
           *args, "--device", "cpu", "--devices", "2",
           "--coordinator", f"127.0.0.1:{port}", "--num-processes", "2"]
    procs = [subprocess.Popen(cmd + ["--process-id", str(i)],
                              stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True,
                              env=env, cwd=cwd) for i in range(2)]
    try:
        with cf.ThreadPoolExecutor(2) as ex:
            outs = list(ex.map(lambda p: p.communicate(timeout=timeout),
                               procs))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return [(p.returncode, out) for p, (out, _) in zip(procs, outs)]


@pytest.mark.slow
def test_distributed_checkpoint_resume(tmp_path):
    """Crash recovery across processes through the real CLI: a
    2-process run checkpoints its carried state, 'crashes' (run ends),
    and a fresh 2-process launch RESUMES from the checkpoint and
    validates every remaining iteration — the reference has no runtime
    recovery at all (detection only, SURVEY.md §5); this is the full
    story the per-iteration validation + checkpoint/resume + multihost
    placement add up to."""
    base = ["--vertices", "1024", "--ba_neighbors", "3", "--width",
            "64", "--features", "4", "--fmt", "sell", "--carry",
            "--checkpoint", "ckpt", "--checkpoint_every", "1",
            "--validate", "true"]
    if not _distributed_available():
        pytest.skip("distributed runtime unavailable")
    first = _run_cli_pair(base + ["--iterations", "2"], str(tmp_path))
    for rc, out in first:
        assert rc == 0, out[-2000:]

    second = _run_cli_pair(base + ["--iterations", "4"], str(tmp_path))
    for rc, out in second:
        assert rc == 0, out[-2000:]
        assert "resumed from ckpt at iteration 2" in out, out[-2000:]


def _run_children(nproc: int, timeout: float):
    port = _free_port()
    env = dict(os.environ)
    # The children pin their own platform/device count (the parent's
    # pytest pins 16 virtual devices; force_cpu_devices replaces it).
    procs = [subprocess.Popen(
        [sys.executable, "-u", CHILD, str(i), str(nproc), str(port)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env) for i in range(nproc)]
    outs = []
    try:
        # Drain all children concurrently: they advance in lockstep
        # through gloo collectives, so serially draining one while the
        # other fills its pipe would stall both.
        import concurrent.futures as cf

        with cf.ThreadPoolExecutor(nproc) as ex:
            pairs = list(ex.map(lambda p: p.communicate(timeout=timeout),
                                procs))
        outs = [(p.returncode, out, err)
                for p, (out, err) in zip(procs, pairs)]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for rc, out, err in outs:
        if "CHILD_SKIP" in out:
            pytest.skip(f"distributed runtime unavailable: {out.strip()}")
        assert rc == 0, f"child failed rc={rc}\n{out}\n{err[-2000:]}"
        assert "CHILD_OK" in out, f"{out}\n{err[-2000:]}"
        errval = float(out.split("err=")[1].split()[0])
        assert errval < 1e-5, out


@pytest.mark.slow
def test_two_process_sell_multilevel():
    _run_children(2, timeout=420)


@pytest.mark.slow
def test_four_process_skewed_a2a():
    """4 REAL processes x 2 virtual devices = 8 global devices: the
    >2-peer regime where a2a pair counts skew (the child asserts the
    skew), per-slice 1D loads split 8 slices over 4 processes, and the
    1.5D triplet build runs a (4, 2) grid — the reference's 4- and
    6-rank PETSc coverage (reference scripts/run_tests.sh)."""
    _run_children(4, timeout=600)
