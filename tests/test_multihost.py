"""Multi-process distributed execution: 2 REAL processes x 2 virtual
CPU devices each, gloo cross-process collectives, one global 4-device
mesh — the framework's multi-host story exercised end-to-end.

The reference emulates multi-node with ``mpiexec --oversubscribe``
(reference scripts/run_tests.sh, tests/test_arrowmpi.py:11-17); the
in-process virtual meshes elsewhere in this suite cover many-device
semantics but share one process and one backend.  This test is the
process-boundary analog: ``jax.distributed.initialize`` + gloo, builder
placement via ``put_global`` (each process materializes only its
addressable shards), result collection via ``fetch_replicated`` (one
cross-host all-gather).
"""

import os
import socket
import subprocess
import sys

import pytest

CHILD = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "_multihost_child.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_sell_multilevel():
    port = _free_port()
    env = dict(os.environ)
    # The children pin their own platform/device count (the parent's
    # pytest pins 16 virtual devices; force_cpu_devices replaces it).
    procs = [subprocess.Popen(
        [sys.executable, "-u", CHILD, str(i), "2", str(port)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env) for i in range(2)]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=420)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for rc, out, err in outs:
        if "CHILD_SKIP" in out:
            pytest.skip(f"distributed runtime unavailable: {out.strip()}")
        assert rc == 0, f"child failed rc={rc}\n{out}\n{err[-2000:]}"
        assert "CHILD_OK" in out, f"{out}\n{err[-2000:]}"
        errval = float(out.split("err=")[1].split()[0])
        assert errval < 1e-5, out
