"""graft-reshard (parallel/reshard.py + routing staged exchange) —
plan edge cases (non-divisible chunks, no-op, starvation budgets,
determinism), the bounded-scratch invariant, staged-vs-one-shot f32
bit-identity on a live mesh, cross-worker handoff plans, and the
memview satellite: ``predicted_hbm_bytes`` pricing the a2a exchange
scratch, pinned against XLA's ``memory_analysis`` measurement."""

import os

import numpy as np
import pytest

from arrow_matrix_tpu.parallel.reshard import (
    Layout,
    apply_plan_host,
    default_table,
    handoff_plan,
    layout_tag,
    plan_route_table,
    redistribution_plan,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _expected(table, x):
    """The plan's semantic ground truth: dst row i is src row table[i]
    (or zeros for -1), independent of chunking/staging."""
    out = np.zeros((len(table),) + x.shape[1:], dtype=x.dtype)
    real = table >= 0
    out[real] = x[table[real]]
    return out


# ---------------------------------------------------------------------------
# plan construction edge cases
# ---------------------------------------------------------------------------


def test_src_eq_dst_is_noop():
    lay = Layout(64, n_dev=4)
    plan = redistribution_plan(lay, lay, 1 << 20, k=2)
    assert plan.is_noop and plan.n_stages == 0
    x = np.arange(64 * 2, dtype=np.float32).reshape(64, 2)
    y = apply_plan_host(plan, x)
    np.testing.assert_array_equal(y, x)
    assert y is not x  # a no-op still returns fresh carriage


def test_budget_below_one_row_raises_loudly():
    src, dst = Layout(64, n_dev=2), Layout(64, n_dev=4)
    # One staged row costs 2 * k * itemsize = 16 B sent + received.
    with pytest.raises(ValueError, match="budget"):
        redistribution_plan(src, dst, 15, k=2)
    with pytest.raises(ValueError, match="row"):
        handoff_plan(64, 2, 7)  # one handoff row costs 8 B


def test_non_divisible_chunks_cover_exactly():
    """rows_max that divides nothing: every move is chunked into
    uneven tails, yet the applied plan equals the semantic table."""
    rng = np.random.default_rng(5)
    src = Layout(96, n_dev=2, tag="s")
    dst = Layout(96, n_dev=4, tag="d")
    perm = rng.permutation(96).astype(np.int64)
    # budget 56 B at row_bytes 8 -> rows_max = 3; 3 divides neither
    # the 48-row src shards nor the 24-row dst shards' move runs.
    plan = redistribution_plan(src, dst, 56, k=1, perm_map=perm)
    assert plan.max_stage_scratch_bytes <= 56
    assert plan.n_stages >= 2
    table = default_table(src, dst, perm)
    x = rng.standard_normal((src.stored_rows, 1)).astype(np.float32)
    np.testing.assert_array_equal(apply_plan_host(plan, x),
                                  _expected(table, x))


def test_plan_is_deterministic():
    rng = np.random.default_rng(11)
    src = Layout(128, n_dev=4)
    dst = Layout(128, n_dev=4, repl=2)
    perm = rng.permutation(128).astype(np.int64)
    a = redistribution_plan(src, dst, 640, k=4, perm_map=perm)
    b = redistribution_plan(src, dst, 640, k=4, perm_map=perm)
    assert a.describe() == b.describe()
    assert a.stages == b.stages
    assert a.local_ops == b.local_ops and a.fill_ops == b.fill_ops


@pytest.mark.parametrize("budget", [16, 56, 256, 1 << 20])
def test_every_stage_within_budget(budget):
    rng = np.random.default_rng(budget)
    src = Layout(96, n_dev=2)
    dst = Layout(96, n_dev=4)
    perm = rng.permutation(96).astype(np.int64)
    plan = redistribution_plan(src, dst, budget, k=1, perm_map=perm)
    for i in range(plan.n_stages):
        # stage_device_bytes already charges a chunk to BOTH its
        # endpoints — it IS the per-device send+recv scratch.
        assert plan.stage_device_bytes(i) <= budget
    assert plan.max_stage_scratch_bytes <= budget


def test_repl_growth_replicates_rows():
    """repl 1 -> 2: every logical row lands in BOTH replica copies."""
    src = Layout(32, n_dev=4, repl=1)
    dst = Layout(32, n_dev=4, repl=2)
    plan = redistribution_plan(src, dst, 1 << 16, k=2)
    x = np.arange(32 * 2, dtype=np.float32).reshape(32, 2)
    y = apply_plan_host(plan, x)
    assert y.shape[0] == dst.stored_rows == 64
    np.testing.assert_array_equal(y[:32], x)
    np.testing.assert_array_equal(y[32:], x)


def test_layout_tags_distinguish_shapes():
    a = layout_tag("x", Layout(64, n_dev=2))
    b = layout_tag("x", Layout(64, n_dev=4))
    c = layout_tag("x", Layout(64, n_dev=4, repl=2))
    assert len({a, b, c}) == 3


# ---------------------------------------------------------------------------
# cross-worker handoff plans (FleetRouter.migrate)
# ---------------------------------------------------------------------------


def test_handoff_plan_carries_every_row_once():
    plan = handoff_plan(100, 2, 64, src_tag="w0", dst_tag="w1")
    # rows_max = 64 // 8 = 8 -> ceil(100/8) = 13 single-chunk stages.
    assert plan.n_stages == 13
    assert plan.max_stage_scratch_bytes <= 64
    x = np.random.default_rng(0).standard_normal(
        (100, 2)).astype(np.float32)
    np.testing.assert_array_equal(apply_plan_host(plan, x), x)


def test_handoff_plan_deterministic_and_tagged():
    a = handoff_plan(37, 3, 128, src_tag="a", dst_tag="b")
    b = handoff_plan(37, 3, 128, src_tag="a", dst_tag="b")
    assert a.describe() == b.describe()
    assert a.src.tag == "a" and a.dst.tag == "b"


# ---------------------------------------------------------------------------
# staged exchange on a live mesh: f32 bit-identity with one-shot
# ---------------------------------------------------------------------------


def test_staged_exchange_bit_identical_to_one_shot():
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    from arrow_matrix_tpu.parallel import routing
    from arrow_matrix_tpu.parallel.mesh import make_mesh, put_global

    n, n_dev, k, budget = 64, 4, 2, 256
    mesh = make_mesh((n_dev,), ("blocks",),
                     devices=np.asarray(jax.devices()[:n_dev]))
    rng = np.random.default_rng(17)
    src = Layout(n, n_dev=n_dev)
    dst = Layout(n, n_dev=n_dev)
    plan = redistribution_plan(src, dst, budget, k=k,
                               perm_map=rng.permutation(n)
                               .astype(np.int64))
    tbl, mask = plan_route_table(plan)
    route = routing.build_route(tbl, n_dev, src_total=src.stored_rows,
                                pad_mask=mask)
    sroute = routing.split_route_stages(route, k, budget)
    assert sroute.n_stages >= 2
    assert 2 * sroute.device_bytes_per_exchange(k, 4) <= budget
    x = put_global(
        rng.standard_normal((n, k)).astype(np.float32),
        NamedSharding(mesh, PartitionSpec("blocks")))
    one = np.asarray(routing.routed_take(
        x, routing.shard_route(route, mesh, "blocks"), mesh, "blocks"))
    staged = np.asarray(routing.staged_routed_take(
        x, routing.shard_route(sroute, mesh, "blocks"), mesh,
        "blocks"))
    assert one.tobytes() == staged.tobytes()
    # Both match the host-side plan semantics.
    np.testing.assert_array_equal(one,
                                  apply_plan_host(plan, np.asarray(x)))


def test_take_dispatches_staged_routes():
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    from arrow_matrix_tpu.parallel import routing
    from arrow_matrix_tpu.parallel.mesh import make_mesh, put_global

    n, n_dev, k = 32, 4, 2
    mesh = make_mesh((n_dev,), ("blocks",),
                     devices=np.asarray(jax.devices()[:n_dev]))
    rng = np.random.default_rng(23)
    tbl = rng.permutation(n).astype(np.int64)
    route = routing.build_route(tbl, n_dev)
    sroute = routing.split_route_stages(route, k, 128)
    x = put_global(rng.standard_normal((n, k)).astype(np.float32),
                   NamedSharding(mesh, PartitionSpec("blocks")))
    srt = routing.shard_route(sroute, mesh, "blocks")
    got = np.asarray(routing.take(x, srt, mesh, "blocks"))
    np.testing.assert_array_equal(got, np.asarray(x)[tbl])


# ---------------------------------------------------------------------------
# memview satellite: exchange scratch is priced, and the price is sane
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def a2a_pair(ba_256_3_base):
    """(one-shot, staged) a2a executors over the ba_256_3 decomposition
    artifact (regenerated on demand by conftest) on a 4-device
    sub-mesh."""
    import jax

    from arrow_matrix_tpu.io import load_decomposition
    from arrow_matrix_tpu.io.graphio import as_levels
    from arrow_matrix_tpu.parallel.mesh import make_mesh
    from arrow_matrix_tpu.parallel.multi_level import MultiLevelArrow

    levels = as_levels(
        load_decomposition(ba_256_3_base, 32, block_diagonal=True), 32)
    mesh = make_mesh((4,), ("blocks",), devices=jax.devices()[:4])
    one = MultiLevelArrow(levels, 32, mesh=mesh, routing="a2a")
    budget = max(one.exchange_scratch_bytes(4) // 2, 4 * 2 * 4 * 4)
    staged = MultiLevelArrow(levels, 32, mesh=mesh, routing="a2a",
                             exchange_scratch_budget=budget,
                             exchange_k=4)
    return one, staged


def test_predicted_hbm_prices_exchange_scratch(a2a_pair):
    one, staged = a2a_pair
    k = 4
    scratch = one.exchange_scratch_bytes(k)
    assert scratch > 0
    # The model's total carries the scratch term on top of operator
    # slices and carriage.
    n_dev = 4
    assert one.predicted_hbm_bytes(k) >= (
        2 * (one.total_rows // n_dev) * k * 4 + scratch)
    # Staging shrinks the priced scratch to the bounded per-stage
    # payload — strictly below the one-shot exchange.
    assert 0 < staged.exchange_scratch_bytes(k) < scratch
    assert staged.exchange_scratch_bytes(k) \
        <= staged.exchange_scratch_budget
    assert staged.predicted_hbm_bytes(k) < one.predicted_hbm_bytes(k)


def test_predicted_vs_memory_analysis_on_a2a(a2a_pair):
    from arrow_matrix_tpu import obs

    one, _ = a2a_pair
    k = 4
    x = one.set_features(np.random.default_rng(3).standard_normal(
        (one.total_rows, k)).astype(np.float32))
    pred = obs.predicted_bytes_for(one, k)
    assert pred and pred > 0
    mem = obs.account_memory("a2a", one.step_fn, x,
                             *one.step_operands(),
                             predicted_bytes=pred)
    assert mem["measured_bytes"] > 0
    # With the exchange scratch priced, the static model must stay the
    # same order of magnitude as XLA's own memory_analysis of the
    # compiled step — the band the obs ratio metric alarms on.
    assert 0.25 <= mem["ratio"] <= 10.0


def test_staged_a2a_executor_matches_one_shot(a2a_pair):
    one, staged = a2a_pair
    k = 4
    xh = np.random.default_rng(9).standard_normal(
        (one.total_rows, k)).astype(np.float32)
    y_one = np.asarray(one.step(one.set_features(xh)))
    y_staged = np.asarray(staged.step(staged.set_features(xh)))
    assert y_one.tobytes() == y_staged.tobytes()
