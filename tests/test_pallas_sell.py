"""Fused SELL-SpMM Pallas kernel tests (ops/pallas_sell.py,
graft-stream): the interpret=True correctness pins against the
``ops/sell.py`` golden, at the protocol shape the acceptance criteria
name (n=2^20 feature table, k=16 and k=128)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from arrow_matrix_tpu.ops import pallas_sell
from arrow_matrix_tpu.ops.pallas_sell import (
    GRANULE,
    pack_features_t,
    sell_spmm_t_pallas,
    sell_tier_spmm_packed,
    slab_rows,
    supported_feature_width,
)
from arrow_matrix_tpu.ops.sell import SellMatrix, sell_from_csr, sell_spmm_t
from arrow_matrix_tpu.utils import barabasi_albert, random_dense
from arrow_matrix_tpu.utils.numerics import (
    relative_error,
    relative_tolerance,
)


def _synthetic_binary(n_table: int, rows: int, m_t: int, k: int, seed=0):
    """A single-tier binary SellMatrix over an n_table-row feature
    table, built directly (no decomposition — the kernel contract is
    per-tier)."""
    rng = np.random.default_rng(seed)
    cols = rng.integers(0, n_table, size=(m_t, rows)).astype(np.int32)
    deg = rng.integers(0, m_t + 1, size=rows).astype(np.int32)
    m = SellMatrix(cols=(jnp.asarray(cols),), data=None,
                   deg=(jnp.asarray(deg),), n_rows=rows,
                   row_starts=(0,))
    x_t = jnp.asarray(rng.standard_normal((k, n_table)), dtype=jnp.float32)
    return m, x_t


@pytest.mark.parametrize("k,rows,m_t", [(16, 1 << 14, 16),
                                        (128, 1 << 13, 8)])
def test_matches_golden_protocol_shape(k, rows, m_t):
    # The acceptance shape: a 2^20-row feature table gathered by a
    # binary tier slab; vectorized interpret body (the CPU tier-1 path).
    m, x_t = _synthetic_binary(1 << 20, rows, m_t, k, seed=k)
    want = np.asarray(sell_spmm_t(m, x_t, gather_budget=1 << 28))
    got = np.asarray(sell_spmm_t_pallas(m, x_t))
    assert got.shape == want.shape == (k, rows)
    assert relative_error(got, want) <= relative_tolerance(m_t)


def test_weighted_matches_golden():
    rng = np.random.default_rng(3)
    rows, m_t, k, n_table = 512, 12, 16, 4096
    cols = rng.integers(0, n_table, size=(m_t, rows)).astype(np.int32)
    deg = rng.integers(0, m_t + 1, size=rows)
    data = rng.standard_normal((m_t, rows)).astype(np.float32)
    data *= (np.arange(m_t)[:, None] < deg[None, :])  # explicit zeros
    m = SellMatrix(cols=(jnp.asarray(cols),),
                   data=(jnp.asarray(data),), deg=None,
                   n_rows=rows, row_starts=(0,))
    x_t = jnp.asarray(rng.standard_normal((k, n_table)), dtype=jnp.float32)
    want = np.asarray(sell_spmm_t(m, x_t, gather_budget=1 << 26))
    got = np.asarray(sell_spmm_t_pallas(m, x_t))
    assert relative_error(got, want) <= relative_tolerance(m_t)


def test_full_matrix_via_sell_from_csr():
    # End-to-end against the packed multi-tier format the fold executor
    # actually carries (zero tier + growth ladder + alignment padding).
    a = barabasi_albert(3000, 5, seed=7)
    sell, order = sell_from_csr(a, pad_rows_to=3072)
    x = random_dense(3072, 16, seed=8)[order]
    want = np.asarray(sell_spmm_t(sell, jnp.asarray(x.T)))
    got = np.asarray(sell_spmm_t_pallas(sell, jnp.asarray(x.T)))
    max_deg = max((c.shape[0] for c in sell.cols), default=1)
    assert relative_error(got, want) <= relative_tolerance(max_deg)


def test_stream_dma_path_matches_vectorized():
    # The double-buffered async-copy body at a tiny shape under
    # interpret: the DMA addressing/wave logic must agree bit-for-bit
    # with the vectorized gather (identical accumulation order).
    m, x_t = _synthetic_binary(1024, 64, 5, 16, seed=11)
    x_packed = pack_features_t(x_t)
    cols, deg = m.cols[0], m.deg[0]
    ref = sell_tier_spmm_packed(cols, x_packed, deg=deg,
                                stream=False, interpret=True)
    got = sell_tier_spmm_packed(cols, x_packed, deg=deg,
                                stream=True, wave=4, interpret=True)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_slab_streaming_bounded_smem(monkeypatch):
    # A tier whose cols exceed the scalar-prefetch budget streams
    # through multiple pallas_calls; the concatenated result is the
    # same answer.
    m, x_t = _synthetic_binary(2048, 1024, 6, 16, seed=13)
    want = np.asarray(sell_spmm_t_pallas(m, x_t))
    # 6 slots * 4 B = 24 B/row -> a few row blocks per slab at most.
    monkeypatch.setattr(pallas_sell, "SMEM_COLS_BUDGET", 64 * 24 * 4)
    got = np.asarray(sell_spmm_t_pallas(m, x_t, row_block=64))
    np.testing.assert_array_equal(got, want)


def test_slab_rows_degenerate_cases():
    # A tier so wide one row exceeds the whole budget still makes
    # forward progress: exactly one row block per slab.
    assert slab_rows(10**9, 64, smem_cols_budget=1 << 18) == 64
    # Normal case: the slab is a whole multiple of the row block and
    # fits the budget (per_row = m_t * 4 bytes of int32 cols).
    s = slab_rows(6, 64, smem_cols_budget=64 * 24 * 4)
    assert s % 64 == 0 and s * 6 * 4 <= 64 * 24 * 4
    # Explicit budget wins over the module-level env default, and the
    # arithmetic is exact: budget 512 B / (4 slots * 4 B) = 32 rows.
    assert slab_rows(4, 8, smem_cols_budget=512) == 32
    # m_t = 0 (the zero tier) must not divide by zero.
    assert slab_rows(0, 64, smem_cols_budget=1024) >= 64


def test_explicit_smem_budget_matches_unbounded():
    # The per-call budget argument (graft-tune's knob) forces slab
    # streaming without touching the module attribute; same answer.
    m, x_t = _synthetic_binary(2048, 1024, 6, 16, seed=13)
    want = np.asarray(sell_spmm_t_pallas(m, x_t))
    got = np.asarray(sell_spmm_t_pallas(m, x_t, row_block=64,
                                        smem_cols_budget=64 * 24 * 4))
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("ring", [1, 3, 4])
def test_ring_depth_variants_match_double_buffer(ring):
    # The generalized DMA ring at every depth must agree bit-for-bit
    # with the ring=2 double buffer (identical accumulation order —
    # the ring only changes how many copies are in flight).
    m, x_t = _synthetic_binary(1024, 64, 5, 16, seed=11)
    x_packed = pack_features_t(x_t)
    cols, deg = m.cols[0], m.deg[0]
    ref = sell_tier_spmm_packed(cols, x_packed, deg=deg,
                                stream=True, wave=4, interpret=True)
    got = sell_tier_spmm_packed(cols, x_packed, deg=deg,
                                stream=True, wave=4, ring=ring,
                                interpret=True)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_ring_validation():
    m, x_t = _synthetic_binary(256, 64, 3, 16, seed=1)
    x_packed = pack_features_t(x_t)
    with pytest.raises(ValueError, match="ring"):
        sell_tier_spmm_packed(m.cols[0], x_packed, deg=m.deg[0],
                              stream=True, ring=0, interpret=True)


def test_pack_features_granule_lines():
    x_t = jnp.arange(2 * 10, dtype=jnp.float32).reshape(2, 10)
    packed = pack_features_t(x_t)
    n_pad = ((10 + GRANULE - 1) // GRANULE) * GRANULE
    assert packed.shape == (n_pad // GRANULE, GRANULE * 2)
    # Line 0 holds rows 0..7 of the row-major view, contiguous.
    np.testing.assert_array_equal(
        np.asarray(packed)[0], np.asarray(x_t.T[:GRANULE]).reshape(-1))


def test_validation():
    m, x_t = _synthetic_binary(256, 64, 3, 10, seed=1)
    x_packed = pack_features_t(x_t)
    with pytest.raises(ValueError, match="k % 16"):
        sell_tier_spmm_packed(m.cols[0], x_packed, deg=m.deg[0],
                              stream=True, interpret=True)
    with pytest.raises(ValueError, match="interpret-only"):
        sell_tier_spmm_packed(m.cols[0], x_packed, deg=m.deg[0],
                              stream=False, interpret=False)
    with pytest.raises(ValueError, match="requires deg"):
        sell_tier_spmm_packed(m.cols[0], x_packed, interpret=True)
    assert supported_feature_width(16)
    assert supported_feature_width(128)
    assert not supported_feature_width(8)


def test_empty_and_zero_tier():
    # The packed format's zero tier (m_t = 0) and an empty matrix.
    k = 16
    x_t = jnp.zeros((k, 32), dtype=jnp.float32)
    empty = SellMatrix(cols=(), data=None, deg=(), n_rows=0,
                       row_starts=())
    assert sell_spmm_t_pallas(empty, x_t).shape == (k, 0)
    zero_tier = SellMatrix(
        cols=(jnp.zeros((0, 24), dtype=jnp.int32),), data=None,
        deg=(jnp.zeros((24,), dtype=jnp.int32),), n_rows=24,
        row_starts=(0,))
    out = sell_spmm_t_pallas(zero_tier, x_t)
    np.testing.assert_array_equal(np.asarray(out),
                                  np.zeros((k, 24), dtype=np.float32))


def test_jit_wrapper_no_retrace():
    m, x_t = _synthetic_binary(512, 128, 4, 16, seed=21)
    fn = pallas_sell.sell_spmm_t_pallas_jit
    out1 = fn(m, x_t)
    n0 = fn._cache_size()
    out2 = fn(m, x_t * 2)
    assert fn._cache_size() == n0
    np.testing.assert_allclose(np.asarray(out2), 2 * np.asarray(out1),
                               rtol=1e-6)


# ---------------------------------------------------------------------------
# graft-kcert certified parity matrix: every (ring, row_block, k,
# carriage) cell of the contract's representative space, interpret
# stream vs the ops/sell.py golden.
# ---------------------------------------------------------------------------

def _parity_problem(k, seed):
    rng = np.random.default_rng(seed)
    rows, m_t, n_table = 256, 4, 256
    cols = rng.integers(0, n_table, size=(m_t, rows)).astype(np.int32)
    deg = rng.integers(0, m_t + 1, size=rows).astype(np.int32)
    x_t = jnp.asarray(rng.standard_normal((k, n_table)),
                      dtype=jnp.float32)
    m = SellMatrix(cols=(jnp.asarray(cols),), data=None,
                   deg=(jnp.asarray(deg),), n_rows=rows,
                   row_starts=(0,))
    return m, x_t


@pytest.mark.parametrize("feature_dtype", ["f32", "bf16"])
@pytest.mark.parametrize("k", [16, 128])
@pytest.mark.parametrize("row_block", [64, 128])
@pytest.mark.parametrize("ring", [1, 2, 3, 4])
def test_certified_parity_matrix(ring, row_block, k, feature_dtype):
    from arrow_matrix_tpu.analysis.kernels import certify_candidate_opts
    from arrow_matrix_tpu.classes import BF16_TOLERANCE

    # Every cell raced here is a cell the certifier admits: the tuner
    # prunes with the same call, so a red cell could never ship.
    assert certify_candidate_opts(
        {"ring": ring, "row_block": row_block}, k,
        feature_dtype=feature_dtype) is None

    m, x_t = _parity_problem(k, seed=ring * 1000 + row_block + k)
    x_packed = pack_features_t(x_t)
    cols, deg = m.cols[0], m.deg[0]
    got = np.asarray(sell_tier_spmm_packed(
        cols, x_packed, deg=deg, stream=True, interpret=True,
        row_block=row_block, wave=4, ring=ring,
        feature_dtype=feature_dtype))
    if feature_dtype == "f32":
        # f32 carriage: the golden is the unfused gather kernel; only
        # accumulation order differs.
        want = np.asarray(sell_spmm_t(m, x_t,
                                      gather_budget=1 << 24)).T
        assert relative_error(got, want) <= relative_tolerance(4)
    else:
        # bf16 carriage: the emulated-bf16 golden quantizes the
        # features exactly like the kernel's carriage cast, then
        # accumulates in f32 (KC4) — agreement must land within the
        # committed approx-class certificate tolerance.
        xq = x_t.astype(jnp.bfloat16).astype(jnp.float32)
        want = np.asarray(sell_spmm_t(m, xq,
                                      gather_budget=1 << 24)).T
        assert relative_error(got, want) <= BF16_TOLERANCE


@pytest.mark.parametrize("k", [16, 128])
def test_bf16_stream_bitwise_matches_vectorized(k):
    # Same accumulation order on both interpret bodies -> the bf16
    # carriage answers bit-identically regardless of the DMA path.
    m, x_t = _parity_problem(k, seed=31 + k)
    x_packed = pack_features_t(x_t)
    cols, deg = m.cols[0], m.deg[0]
    vec = sell_tier_spmm_packed(cols, x_packed, deg=deg, stream=False,
                                interpret=True, feature_dtype="bf16")
    st = sell_tier_spmm_packed(cols, x_packed, deg=deg, stream=True,
                               interpret=True, wave=4, ring=2,
                               feature_dtype="bf16")
    np.testing.assert_array_equal(np.asarray(st), np.asarray(vec))
    assert st.dtype == jnp.float32  # f32 accumulator surfaces f32


def test_bf16_full_matrix_and_jit_static_dtype():
    # The SellMatrix entry point + jit wrapper thread feature_dtype as
    # a static arg: retargeting the carriage recompiles exactly once
    # and lands within the approx-class tolerance of the f32 answer.
    from arrow_matrix_tpu.classes import BF16_TOLERANCE

    m, x_t = _synthetic_binary(512, 128, 4, 16, seed=21)
    fn = pallas_sell.sell_spmm_t_pallas_jit
    f32 = fn(m, x_t)
    n0 = fn._cache_size()
    bf = fn(m, x_t, feature_dtype="bf16")
    assert fn._cache_size() == n0 + 1
    bf2 = fn(m, x_t, feature_dtype="bf16")
    assert fn._cache_size() == n0 + 1
    np.testing.assert_array_equal(np.asarray(bf), np.asarray(bf2))
    assert relative_error(np.asarray(bf),
                          np.asarray(f32)) <= BF16_TOLERANCE
