"""Artifact format round-trip and block-materialization tests
(reference analog: save->load->subtract-to-zero round-trip in
tests/test_arrowdecomposition.py:114-137)."""

import numpy as np
import pytest
from scipy import sparse

from arrow_matrix_tpu.decomposition import arrow_decomposition, reconstruct
from arrow_matrix_tpu.io import (
    arrow_block_coords,
    as_levels,
    format_path,
    FileKind,
    load_block,
    load_decomposition,
    number_of_blocks,
    save_decomposition,
)
from arrow_matrix_tpu.utils import barabasi_albert


def test_path_scheme_matches_reference():
    # Exact strings the reference produces (graphio.py:38-70).
    assert (format_path("g", 100, 2, True, FileKind.indptr)
            == "g_B_100_2_bd_indptr.npy")
    assert (format_path("g", 100, 0, False, FileKind.permutation)
            == "g_B_100_0_permutation.npy")
    assert format_path("g", 100, 1, True, FileKind.npz) == "g_B_100_1_bd.npz"


@pytest.mark.parametrize("mem_map", [False, True])
def test_roundtrip(tmp_path, mem_map):
    a = barabasi_albert(300, 4, seed=2)
    width = 60
    levels = arrow_decomposition(a, width, max_levels=10, block_diagonal=True,
                                 seed=0)
    base = str(tmp_path / "graph")
    save_decomposition(levels, base, block_diagonal=True)

    loaded = load_decomposition(base, width, block_diagonal=True,
                                mem_map=mem_map)
    assert len(loaded) == len(levels)
    for (m, perm), lvl in zip(loaded, levels):
        assert np.array_equal(perm, lvl.permutation)
        if mem_map:
            m = sparse.csr_matrix((np.asarray(m[0]), np.asarray(m[1]),
                                   np.asarray(m[2])), shape=lvl.matrix.shape)
        diff = (m - lvl.matrix.astype(np.float32)).tocsr()
        assert diff.nnz == 0 or np.max(np.abs(diff.data)) < 1e-7

    relevels = as_levels(loaded, width)
    diff = (reconstruct(relevels) - a).tocsr()
    assert diff.nnz == 0 or np.max(np.abs(diff.data)) < 1e-5


def test_load_block_padding():
    rng = np.random.default_rng(0)
    a = sparse.random(25, 25, density=0.3, format="csr", random_state=rng,
                      dtype=np.float32)
    w = 10
    # Bottom-right block: 5x5 data padded to 10x10.
    blk = load_block(a, 20, 30, 20, 30, w)
    assert blk.shape == (w, w)
    np.testing.assert_allclose(blk.toarray()[:5, :5], a.toarray()[20:, 20:])
    assert np.all(blk.toarray()[5:, :] == 0)

    # Full tiling reassembles the matrix.
    dense = np.zeros((30, 30), dtype=np.float32)
    for i in range(3):
        for j in range(3):
            b = load_block(a, i * w, (i + 1) * w, j * w, (j + 1) * w, w)
            dense[i * w:(i + 1) * w, j * w:(j + 1) * w] = b.toarray()
    np.testing.assert_allclose(dense[:25, :25], a.toarray())


def test_number_of_blocks_truncates_zero_rows():
    rows = np.zeros((50, 50), dtype=np.float32)
    rows[:23, :23] = np.eye(23)
    a = sparse.csr_matrix(rows)
    assert number_of_blocks(a, 10) == 3
    assert number_of_blocks(a, 23) == 1
    assert number_of_blocks(sparse.csr_matrix((50, 50), dtype=np.float32), 10) == 1


def test_arrow_block_coords():
    coords = set(arrow_block_coords(4, banded=False))
    assert coords == {(0, 0), (0, 1), (0, 2), (0, 3),
                      (1, 0), (2, 0), (3, 0),
                      (1, 1), (2, 2), (3, 3)}
    banded = set(arrow_block_coords(4, banded=True))
    assert banded == coords | {(2, 1), (1, 2), (3, 2), (2, 3)}


def test_missing_data_file_means_ones(tmp_path):
    a = barabasi_albert(100, 3, seed=4)
    levels = arrow_decomposition(a, 20, max_levels=4, block_diagonal=True,
                                 seed=0)
    base = str(tmp_path / "g")
    save_decomposition(levels, base, block_diagonal=True)
    import os
    os.remove(format_path(base, 20, 0, True, FileKind.data))
    loaded = load_decomposition(base, 20, block_diagonal=True)
    m0 = loaded[0][0]
    assert np.all(m0.data == 1.0)


def test_grown_last_level_roundtrips(tmp_path):
    # A max_levels-capped decomposition can have a last level wider than
    # requested; saving must not silently drop it on reload (a latent
    # reference bug this framework fixes).
    from arrow_matrix_tpu.io import load_level_widths
    a = barabasi_albert(300, 6, seed=0)
    levels = arrow_decomposition(a, 32, max_levels=2, block_diagonal=True,
                                 seed=0)
    base = str(tmp_path / "g")
    save_decomposition(levels, base, block_diagonal=True)
    loaded = load_decomposition(base, 32, block_diagonal=True)
    assert len(loaded) == len(levels)
    widths = load_level_widths(base, 32, block_diagonal=True)
    assert widths is not None
    assert [int(w) for w in widths] == [l.arrow_width for l in levels]
    relevels = as_levels(loaded, widths)
    diff = (reconstruct(relevels) - a).tocsr()
    assert diff.nnz == 0 or np.max(np.abs(diff.data)) < 1e-5


def test_npz_grown_last_level_roundtrips(tmp_path):
    # The legacy npz scheme must also name all levels by the level-0
    # width so a grown last level is found on reload (code-review fix).
    from arrow_matrix_tpu.io.graphio import save_decomposition_npz
    a = barabasi_albert(300, 6, seed=0)
    levels = arrow_decomposition(a, 32, max_levels=2, block_diagonal=True,
                                 seed=0)
    assert levels[-1].arrow_width > 32  # the scenario under test
    base = str(tmp_path / "g")
    save_decomposition_npz(levels, base, block_diagonal=True)
    loaded = load_decomposition(base, levels[0].arrow_width,
                                block_diagonal=True)
    assert len(loaded) == len(levels)


def _write_reference_layout(levels, base, block_diagonal=True,
                            requested_width=None):
    """Write an artifact byte-for-byte the way the *reference* writer
    does (reference graphio.py:131-191): one npy per CSR component with
    each level named by its OWN achieved width (``arrow_m.arrow_width``),
    float32 data, scipy-default int32 indptr/indices, int64 permutation,
    and the convenience ``_nnzrows`` file under (level-0 width, index 0).
    """
    for i, lvl in enumerate(levels):
        m = lvl.matrix.tocsr().astype(np.float32)
        w = lvl.arrow_width
        np.save(format_path(base, w, i, block_diagonal, FileKind.indptr),
                m.indptr.astype(np.int32))
        np.save(format_path(base, w, i, block_diagonal, FileKind.indices),
                m.indices.astype(np.int32))
        np.save(format_path(base, w, i, block_diagonal, FileKind.data),
                m.data)
        np.save(format_path(base, w, i, block_diagonal, FileKind.permutation),
                np.asarray(lvl.permutation, dtype=np.int64))
    np.save(format_path(base, levels[0].arrow_width, 0, block_diagonal,
                        FileKind.nnzrows),
            np.asarray([l.nonzero_rows for l in levels], dtype=np.int64))


def test_reference_layout_fixture_loads_fully(tmp_path):
    """Cross-implementation fixture (VERDICT r1 missing #3): an artifact
    laid out the way the reference writes it — including the per-level-
    achieved-width naming quirk that silently truncates a grown last
    level under the reference's own loader — must load completely here,
    with widths recovered from the filenames."""
    from arrow_matrix_tpu.decomposition import decomposition_spmm
    from arrow_matrix_tpu.io import load_level_widths
    from arrow_matrix_tpu.utils import random_dense

    a = barabasi_albert(300, 6, seed=0)
    requested = 32
    levels = arrow_decomposition(a, requested, max_levels=2,
                                 block_diagonal=True, seed=0)
    assert levels[-1].arrow_width > requested  # the quirk scenario
    base = str(tmp_path / "ref")
    _write_reference_layout(levels, base)

    # Enumerating under the requested width still finds the grown last
    # level (the reference loader would stop at it, graphio.py:251-314).
    loaded = load_decomposition(base, requested, block_diagonal=True)
    assert len(loaded) == len(levels)
    for (m, perm), lvl in zip(loaded, levels):
        assert np.array_equal(perm, lvl.permutation)
        diff = (m - lvl.matrix.astype(np.float32)).tocsr()
        assert diff.nnz == 0 or np.max(np.abs(diff.data)) < 1e-7

    # No _widths.npy metadata: widths come from the filenames.
    widths = load_level_widths(base, requested, block_diagonal=True)
    assert [int(w) for w in widths] == [l.arrow_width for l in levels]

    # Golden end-to-end check through the loaded artifact.
    relevels = as_levels(loaded, widths)
    diff = (reconstruct(relevels) - a).tocsr()
    assert diff.nnz == 0 or np.max(np.abs(diff.data)) < 1e-5
    x = random_dense(a.shape[0], 8, seed=3)
    np.testing.assert_allclose(decomposition_spmm(relevels, x),
                               decomposition_spmm(levels, x),
                               rtol=1e-5, atol=1e-5)


def test_reference_layout_memmap_and_missing_data(tmp_path):
    # Same fixture loaded memmapped, and with the optional _data files
    # removed (implicit unit values, reference graphio.py:298).
    import os
    a = barabasi_albert(200, 4, seed=1)
    levels = arrow_decomposition(a, 24, max_levels=2, block_diagonal=True,
                                 seed=0)
    base = str(tmp_path / "ref")
    _write_reference_layout(levels, base)
    for i, lvl in enumerate(levels):
        os.remove(format_path(base, lvl.arrow_width, i, True, FileKind.data))
    loaded = load_decomposition(base, 24, block_diagonal=True, mem_map=True)
    assert len(loaded) == len(levels)
    assert loaded[0][0][0] is None  # data stays lazy
    lvls = as_levels(loaded, [l.arrow_width for l in levels])
    assert np.all(lvls[0].matrix.data == 1.0)


def test_load_missing_artifacts_raises(tmp_path):
    with pytest.raises(FileNotFoundError, match="no decomposition"):
        load_decomposition(str(tmp_path / "nothing"), 32)


def test_number_of_blocks_asymmetric_columns():
    # Directed-graph level matrix: head row reaches a column beyond the
    # last nonzero row; truncation must keep that column's block.
    n, w = 60, 10
    m = sparse.lil_matrix((n, n), dtype=np.float32)
    m[0, 55] = 1.0   # head-row entry in the last block
    m[5, 3] = 1.0    # rows end early
    a = m.tocsr()
    assert number_of_blocks(a, w) == 6


def test_memmap_missing_data_stays_lazy(tmp_path):
    # mem_map + absent _data file: loader returns data=None (implicit
    # ones) instead of materializing an nnz-sized array.
    import os
    a = barabasi_albert(100, 3, seed=4)
    levels = arrow_decomposition(a, 20, max_levels=4, block_diagonal=True,
                                 seed=0)
    base = str(tmp_path / "g")
    save_decomposition(levels, base, block_diagonal=True)
    os.remove(format_path(base, 20, 0, True, FileKind.data))
    loaded = load_decomposition(base, 20, block_diagonal=True, mem_map=True)
    data, indices, indptr = loaded[0][0]
    assert data is None
    blk = load_block(loaded[0][0], 0, 20, 0, 20, 20)
    assert np.all(blk.data == 1.0)
    # as_levels also materializes ones.
    lvls = as_levels(loaded, 20)
    assert np.all(lvls[0].matrix.data == 1.0)


def test_convert_decomposition_roundtrip(tmp_path):
    """npz -> npy triplet -> npz round trip (reference
    convert_decomposition, graphio.py:317-358)."""
    from arrow_matrix_tpu.io import (
        as_levels,
        convert_decomposition,
        load_decomposition,
        save_decomposition_npz,
    )
    from arrow_matrix_tpu.decomposition import (
        arrow_decomposition,
        decomposition_spmm,
    )
    from arrow_matrix_tpu.utils import barabasi_albert, random_dense

    a = barabasi_albert(200, 3, seed=2)
    levels = arrow_decomposition(a, 32, max_levels=3, block_diagonal=True,
                                 seed=0)
    base = str(tmp_path / "conv")
    width0 = levels[0].arrow_width
    save_decomposition_npz(levels, base)

    n = convert_decomposition(base, width0, to="npy")
    assert n == len(levels)
    loaded = as_levels(load_decomposition(base, width0), width0)
    x = random_dense(200, 4, seed=1)
    np.testing.assert_allclose(decomposition_spmm(loaded, x),
                               decomposition_spmm(levels, x),
                               rtol=1e-5, atol=1e-5)

    # Reverse direction rewrites identical npz levels.
    assert convert_decomposition(base, width0, to="npz") == n

    with pytest.raises(FileNotFoundError):
        convert_decomposition(str(tmp_path / "missing"), 32, to="npy")
    with pytest.raises(ValueError):
        convert_decomposition(base, width0, to="parquet")


@pytest.mark.parametrize("width", [5, 9, 13, 19])
def test_save_load_width_sweep(tmp_path, width):
    """Loader smoke across odd small widths (reference
    test_load_graph_distributed, tests/test_arrowmpi.py:170-203 sweeps
    widths 5-19)."""
    a = barabasi_albert(150, 3, seed=width)
    levels = arrow_decomposition(a, width, max_levels=6,
                                 block_diagonal=True, seed=0)
    base = str(tmp_path / f"w{width}")
    save_decomposition(levels, base, block_diagonal=True)
    loaded = load_decomposition(base, width, block_diagonal=True)
    assert len(loaded) == len(levels)
    from arrow_matrix_tpu.io import load_level_widths
    widths = load_level_widths(base, width, block_diagonal=True)
    relevels = as_levels(loaded, widths)
    diff = (reconstruct(relevels) - a).tocsr()
    assert diff.nnz == 0 or np.max(np.abs(diff.data)) < 1e-5


def test_coexisting_widths_do_not_splice(tmp_path):
    """Two decompositions of different widths under ONE base path must
    load independently — discovery must not splice a foreign trailing
    level in (code-review r2 repro)."""
    from arrow_matrix_tpu.decomposition import decomposition_spmm
    from arrow_matrix_tpu.io import load_level_widths
    from arrow_matrix_tpu.utils import random_dense

    a = barabasi_albert(300, 5, seed=3)
    base = str(tmp_path / "shared")
    lv16 = arrow_decomposition(a, 16, max_levels=4, block_diagonal=True,
                               seed=0)
    lv32 = arrow_decomposition(a, 32, max_levels=6, block_diagonal=True,
                               seed=0)
    assert len(lv32) != len(lv16)
    save_decomposition(lv16, base, block_diagonal=True)
    save_decomposition(lv32, base, block_diagonal=True)

    for width, lv in ((16, lv16), (32, lv32)):
        loaded = load_decomposition(base, width, block_diagonal=True)
        assert len(loaded) == len(lv)
        widths = load_level_widths(base, width, block_diagonal=True)
        x = random_dense(300, 4, seed=1)
        np.testing.assert_allclose(
            decomposition_spmm(as_levels(loaded, widths), x),
            decomposition_spmm(lv, x), rtol=1e-4, atol=1e-4)


def test_discovery_stops_after_grown_level(tmp_path):
    # Reference-layout artifact (no metadata) with a grown last level,
    # PLUS a foreign larger-width artifact sharing the base: the
    # discovered grown level terminates enumeration.
    a = barabasi_albert(300, 6, seed=0)
    levels = arrow_decomposition(a, 32, max_levels=2, block_diagonal=True,
                                 seed=0)
    assert levels[-1].arrow_width > 32
    base = str(tmp_path / "g")
    _write_reference_layout(levels, base)
    # Foreign artifact at width 90 with MORE levels.
    foreign = arrow_decomposition(a, 90, max_levels=4, block_diagonal=True,
                                  seed=1)
    _write_reference_layout(foreign, base)
    loaded = load_decomposition(base, 32, block_diagonal=True)
    assert len(loaded) == len(levels)


def test_reference_artifact_roundtrip_feeds_executors(tmp_path):
    """Cross-implementation round trip, artifact -> EXECUTOR (VERDICT
    r5 item 8): an artifact in the reference ``save_decomposition_new``
    shape — per-level achieved-width naming, int32 triplets, no
    ``_widths.npy`` metadata, no integrity manifest, and the binary
    case's omitted ``_data`` files — must load through io/graphio.py,
    rebuild as ArrowLevels, and drive both the folded single-chip
    operator and the feature-major mesh executor to the golden SpMM."""
    from arrow_matrix_tpu.decomposition import decomposition_spmm
    from arrow_matrix_tpu.io import load_level_widths
    from arrow_matrix_tpu.parallel.mesh import make_mesh
    from arrow_matrix_tpu.parallel.multi_level import MultiLevelArrow
    from arrow_matrix_tpu.parallel.sell_slim import SellMultiLevel
    from arrow_matrix_tpu.utils import numerics, random_dense
    import os

    a = barabasi_albert(1024, 4, seed=2)
    levels = arrow_decomposition(a, 64, max_levels=3,
                                 block_diagonal=True, seed=0)
    base = str(tmp_path / "ref_exec")
    _write_reference_layout(levels, base)
    # The reference writer omits _data for binary adjacencies
    # (reference graphio.py:298: missing data file => implicit ones).
    for i, lvl in enumerate(levels):
        if np.all(lvl.matrix.data == 1.0):
            os.remove(format_path(base, lvl.arrow_width, i, True,
                                  FileKind.data))

    loaded = load_decomposition(base, 64, block_diagonal=True)
    widths = load_level_widths(base, 64, block_diagonal=True)
    relevels = as_levels(loaded, widths)
    assert len(relevels) == len(levels)

    x = random_dense(a.shape[0], 8, seed=3)
    want = decomposition_spmm(levels, x)
    tol = numerics.relative_tolerance(
        sum(int(lvl.matrix.nnz) for lvl in levels) / a.shape[0])

    ml = MultiLevelArrow(relevels, 64, mesh=None, fmt="fold")
    got = ml.gather_result(ml.step(ml.set_features(x)))
    assert numerics.relative_error(got, want) <= tol

    sm = SellMultiLevel(relevels, 64, make_mesh((8,), ("blocks",)),
                        routing="a2a")
    got = sm.gather_result(sm.step(sm.set_features(x)))
    assert numerics.relative_error(got, want) <= tol
