"""Device-kernel tests: ELL/flat SpMM vs scipy, and the single-device
arrow SpMM vs the dense golden product (the reference gates its kernels
the same way: distributed result vs ``A @ X``,
reference tests/test_arrowmpi.py:342-398)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from scipy import sparse

from arrow_matrix_tpu.decomposition import arrow_decomposition
from arrow_matrix_tpu.ops import (
    ArrowBlocks,
    arrow_blocks_from_csr,
    arrow_spmm,
    block_features,
    csr_flat_pack,
    csr_flat_spmm,
    ell_pack,
    ell_spmm,
    unblock_features,
)
from arrow_matrix_tpu.utils import barabasi_albert, random_dense


@pytest.mark.parametrize("chunk", [None, 4])
@pytest.mark.parametrize("density", [0.02, 0.2])
def test_ell_spmm_matches_scipy(chunk, density):
    rng = np.random.default_rng(0)
    a = sparse.random(100, 80, density=density, format="csr", random_state=rng,
                      dtype=np.float32)
    x = random_dense(80, 16, seed=1)
    cols, data = ell_pack(a)
    out = ell_spmm(jnp.asarray(cols), jnp.asarray(data), jnp.asarray(x),
                   chunk=chunk)
    np.testing.assert_allclose(np.asarray(out), a @ x, rtol=1e-4, atol=1e-5)


def test_ell_spmm_empty():
    cols = jnp.zeros((5, 0), dtype=jnp.int32)
    data = jnp.zeros((5, 0), dtype=jnp.float32)
    out = ell_spmm(cols, data, jnp.ones((7, 3)))
    assert out.shape == (5, 3)
    assert np.all(np.asarray(out) == 0)


def test_csr_flat_spmm_matches_scipy():
    rng = np.random.default_rng(3)
    a = sparse.random(64, 64, density=0.1, format="csr", random_state=rng,
                      dtype=np.float32)
    x = random_dense(64, 8, seed=2)
    rows, cols, data = csr_flat_pack(a, pad_to=a.nnz + 13)
    out = csr_flat_spmm(jnp.asarray(rows), jnp.asarray(cols),
                        jnp.asarray(data), jnp.asarray(x), 64)
    np.testing.assert_allclose(np.asarray(out), a @ x, rtol=1e-4, atol=1e-5)


def _dense_padded(m: sparse.csr_matrix, total: int) -> np.ndarray:
    d = np.zeros((total, total), dtype=np.float32)
    arr = m.toarray()
    n = min(total, arr.shape[0])
    d[:n, :n] = arr[:n, :n]
    return d


@pytest.mark.parametrize("banded", [False, True])
def test_arrow_spmm_matches_dense(banded):
    a = barabasi_albert(400, 4, seed=13)
    width = 80
    levels = arrow_decomposition(a, width, max_levels=100,
                                 block_diagonal=not banded, seed=3)
    for lvl in levels:
        blocks = arrow_blocks_from_csr(lvl.matrix.astype(np.float32), width,
                                       banded=banded)
        nb = blocks.n_blocks
        x_host = random_dense(400, 16, seed=7)
        xb = block_features(x_host, width, nb)

        total = nb * width  # zero-row truncation can make this < n
        m = min(total, 400)
        out = jax.jit(arrow_spmm)(blocks, jnp.asarray(xb))
        got = unblock_features(out, m)

        b_dense = _dense_padded(lvl.matrix.astype(np.float32), total)
        x_pad = np.zeros((total, 16), dtype=np.float32)
        x_pad[:m] = x_host[:m]
        want = (b_dense @ x_pad)[:m]
        np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-4)


def test_arrow_spmm_padded_blocks():
    # Padding the block count with empty block-rows must not change results.
    a = barabasi_albert(256, 4, seed=5)
    width = 64
    levels = arrow_decomposition(a, width, max_levels=100, block_diagonal=True)
    lvl = levels[0]
    x_host = random_dense(256, 8, seed=9)

    b1 = arrow_blocks_from_csr(lvl.matrix, width)
    out1 = unblock_features(arrow_spmm(b1, jnp.asarray(
        block_features(x_host, width, b1.n_blocks))), 256)

    b2 = arrow_blocks_from_csr(lvl.matrix, width, pad_blocks_to=b1.n_blocks + 3)
    out2 = unblock_features(arrow_spmm(b2, jnp.asarray(
        block_features(x_host, width, b2.n_blocks))), 256)
    np.testing.assert_allclose(out1, out2, rtol=1e-5, atol=1e-6)


def test_arrow_blocks_is_pytree():
    a = barabasi_albert(128, 3, seed=8)
    blocks = arrow_blocks_from_csr(
        arrow_decomposition(a, 32, max_levels=100, block_diagonal=True)[0].matrix,
        32)
    leaves = jax.tree_util.tree_leaves(blocks)
    assert len(leaves) >= 6
    rebuilt = jax.tree_util.tree_map(lambda v: v, blocks)
    assert isinstance(rebuilt, ArrowBlocks)
    assert rebuilt.width == blocks.width


def test_arrow_blocks_rejects_out_of_pattern():
    # A matrix wider than the requested width must raise, not silently
    # drop nonzeros (reference behavior: silent drop).
    a = barabasi_albert(300, 6, seed=0)
    levels = arrow_decomposition(a, 32, max_levels=2, block_diagonal=True,
                                 seed=0)
    last = levels[-1]
    if last.arrow_width > 32:
        with pytest.raises(ValueError, match="captured"):
            arrow_blocks_from_csr(last.matrix, 32)
        # With its own achieved width it tiles fine in banded mode only if
        # within band; block-diagonal needs the block criterion, so use
        # the banded layout which covers |i-j|<=1 blocks.
        arrow_blocks_from_csr(last.matrix, last.arrow_width, banded=True)


def test_dense_format_matches_ell():
    """Dense (MXU) block format computes the same SpMM as ELL."""
    import numpy as np
    from arrow_matrix_tpu.ops.arrow_blocks import (
        arrow_blocks_from_csr, arrow_spmm, block_features)
    from arrow_matrix_tpu.utils.graphs import random_dense
    from arrow_matrix_tpu.decomposition.decompose import arrow_decomposition
    from arrow_matrix_tpu.utils.graphs import barabasi_albert

    w, n = 8, 96
    a = barabasi_albert(n, 3, seed=11)
    # Level 0 of a block-diagonal decomposition fits both the block and
    # the (superset) banded tiling patterns.
    lvl = arrow_decomposition(a, arrow_width=w, max_levels=2,
                              block_diagonal=True, seed=11)[0]
    for banded in (False, True):
        ell = arrow_blocks_from_csr(lvl.matrix, w, banded=banded, fmt="ell")
        dense = arrow_blocks_from_csr(lvl.matrix, w, banded=banded,
                                      fmt="dense")
        x = block_features(random_dense(n, 4, seed=1), ell.width,
                           ell.n_blocks)
        np.testing.assert_allclose(np.asarray(arrow_spmm(dense, x)),
                                   np.asarray(arrow_spmm(ell, x)),
                                   rtol=1e-5, atol=1e-5)


def test_gell_head_matches_golden():
    """Global-row ELL head (head_fmt='gell'): one gather+reduce over
    the flat feature array replaces the flat head's scatter-add."""
    import jax

    from arrow_matrix_tpu.ops import arrow_blocks_from_csr, arrow_spmm
    from arrow_matrix_tpu.ops.arrow_blocks import head_block_spmm

    from helpers import arrow_csr

    nb, w, k = 6, 32, 8
    a = arrow_csr(nb, w, seed=31, density=0.3)
    x_host = random_dense(nb * w, k, seed=5)
    xb = jnp.asarray(x_host.reshape(nb, w, k))

    g = arrow_blocks_from_csr(a, w, head_fmt="gell")
    assert g.head_gell and g.head_cols.shape[0] == w
    got = np.asarray(jax.jit(arrow_spmm)(g, xb)).reshape(nb * w, k)
    np.testing.assert_allclose(got, a @ x_host, rtol=1e-5, atol=1e-5)

    # Chunked slot axis agrees with unchunked.
    got_c = np.asarray(arrow_spmm(g, xb, chunk=8)).reshape(nb * w, k)
    np.testing.assert_allclose(got_c, got, rtol=1e-6, atol=1e-6)

    # The per-block head API rejects gell blocks with a clear error.
    with pytest.raises(ValueError, match="gell"):
        head_block_spmm(g, xb)


def test_gell_head_rejected_on_mesh():
    from arrow_matrix_tpu.decomposition import arrow_decomposition
    from arrow_matrix_tpu.parallel import MultiLevelArrow, make_mesh
    from arrow_matrix_tpu.utils import barabasi_albert

    a = barabasi_albert(128, 3, seed=1)
    levels = arrow_decomposition(a, 16, max_levels=2, block_diagonal=True,
                                 seed=0)
    with pytest.raises(ValueError, match="single-chip"):
        MultiLevelArrow(levels, 16, mesh=make_mesh((8,), ("blocks",)),
                        head_fmt="gell")


def test_gell_head_multi_level_end_to_end():
    from arrow_matrix_tpu.decomposition import arrow_decomposition
    from arrow_matrix_tpu.decomposition.decompose import decomposition_spmm
    from arrow_matrix_tpu.parallel import MultiLevelArrow
    from arrow_matrix_tpu.utils import barabasi_albert

    n, width = 480, 32
    a = barabasi_albert(n, 4, seed=17)
    levels = arrow_decomposition(a, width, max_levels=3,
                                 block_diagonal=True, seed=2)
    ml = MultiLevelArrow(levels, width, mesh=None, fmt="ell",
                        head_fmt="gell")
    assert all(b.head_gell for b in ml.blocks)
    x_host = random_dense(n, 8, seed=3)
    out = ml.gather_result(ml.step(ml.set_features(x_host)))
    np.testing.assert_allclose(out, decomposition_spmm(levels, x_host),
                               rtol=1e-3, atol=1e-3)


def test_head_auto_prefers_gell_on_tpu(monkeypatch):
    """Platform-aware head auto-rule: single-chip TPU ELL levels pick
    the gather-based gell head (scatter-adds serialize on TPU)."""
    from arrow_matrix_tpu.decomposition import arrow_decomposition
    from arrow_matrix_tpu.parallel import MultiLevelArrow
    from arrow_matrix_tpu.utils import barabasi_albert

    n, width = 480, 32
    a = barabasi_albert(n, 4, seed=23)
    levels = arrow_decomposition(a, width, max_levels=3,
                                 block_diagonal=True, seed=2)
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    ml = MultiLevelArrow(levels, width, mesh=None, fmt="ell")
    assert all(b.head_gell for b in ml.blocks)
    x_host = random_dense(n, 8, seed=4)
    out = ml.gather_result(ml.step(ml.set_features(x_host)))
    np.testing.assert_allclose(out, a @ x_host, rtol=1e-3, atol=1e-3)


def test_arrow_blocks_binary_matches_weighted():
    """Binary (degree-mask) stacked ELL must be bit-identical to the
    weighted layout on 0/1 data, with the value stacks gone."""
    import jax.numpy as jnp

    from arrow_matrix_tpu.decomposition import arrow_decomposition
    from arrow_matrix_tpu.ops.arrow_blocks import (
        arrow_blocks_from_csr,
        arrow_spmm,
        block_features,
    )
    from arrow_matrix_tpu.utils import barabasi_albert, random_dense

    a = barabasi_albert(600, 4, seed=9)
    lvl = arrow_decomposition(a, 64, max_levels=1, block_diagonal=False,
                              seed=1)[0]
    # One level keeps every edge: tile at the achieved width (the
    # multi-level builder's grown-last-level rule).
    w = -(-lvl.arrow_width // 64) * 64
    nb = -(-lvl.matrix.shape[0] // w)
    x = random_dense(nb * w, 8, seed=2)
    for head_fmt in ("ell", "flat", "gell"):
        bb = arrow_blocks_from_csr(lvl.matrix, w, banded=True,
                                   head_fmt=head_fmt)
        bw = arrow_blocks_from_csr(lvl.matrix, w, banded=True,
                                   head_fmt=head_fmt, binary=False)
        assert bb.binary and not bw.binary
        assert bb.diag_data is None and bw.diag_data is not None
        xb = jnp.asarray(block_features(x[:bb.n_rows], w, bb.n_blocks))
        out_b = np.asarray(arrow_spmm(bb, xb))
        out_w = np.asarray(arrow_spmm(bw, xb))
        np.testing.assert_array_equal(out_b, out_w, err_msg=head_fmt)
        # chunked path too
        out_bc = np.asarray(arrow_spmm(bb, xb, chunk=8))
        np.testing.assert_allclose(out_bc, out_w, rtol=1e-6, atol=1e-6)


def test_mixed_value_levels_resolve_weighted():
    """Decomposition-wide binary rule: if ANY level has non-unit values,
    every level packs weighted (mixed layouts cannot stack)."""
    from arrow_matrix_tpu.decomposition import arrow_decomposition
    from arrow_matrix_tpu.parallel import MultiLevelArrow
    from arrow_matrix_tpu.parallel.multi_level import resolve_levels_binary
    from arrow_matrix_tpu.utils import barabasi_albert, random_dense

    a = barabasi_albert(320, 4, seed=3)
    levels = arrow_decomposition(a, 32, max_levels=3, block_diagonal=True,
                                 seed=1)
    assert resolve_levels_binary(levels, "auto")
    # Scale ONE level's values: the whole decomposition goes weighted.
    levels[0].matrix.data *= 0.5
    assert not resolve_levels_binary(levels, "auto")
    ml = MultiLevelArrow(levels, 32, mesh=None, fmt="ell")
    assert not ml.binary
    assert all(b.diag_data is not None for b in ml.blocks)
    x = random_dense(320, 4, seed=2)
    got = ml.gather_result(ml.step(ml.set_features(x)))
    from arrow_matrix_tpu.decomposition.decompose import decomposition_spmm
    np.testing.assert_allclose(got, decomposition_spmm(levels, x),
                               rtol=1e-4, atol=1e-5)


def test_block_index_dtype_selection():
    from arrow_matrix_tpu.decomposition import arrow_decomposition
    from arrow_matrix_tpu.ops.ell import block_index_dtype
    from arrow_matrix_tpu.utils import barabasi_albert

    assert block_index_dtype(2048) == np.int16
    assert block_index_dtype(32766) == np.int16
    assert block_index_dtype(32767) == np.int32
    assert block_index_dtype(100_000) == np.int32

    a = barabasi_albert(256, 4, seed=5)
    lvl = arrow_decomposition(a, 64, max_levels=2, block_diagonal=True,
                              seed=1)[0]
    b = arrow_blocks_from_csr(lvl.matrix, 64)
    assert b.diag_cols.dtype == jnp.int16     # block-local columns


def test_auto_chunk_accounts_for_lane_padding():
    from arrow_matrix_tpu.ops.ell import auto_chunk

    # Logical fit, physical 8x overflow on 128-lane hardware.
    rows, k, m = 1 << 20, 16, 64
    budget = rows * k * 4 * m // 2            # logical: chunk = m//2
    c_cpu = auto_chunk(rows, k, m, budget, lanes=1)
    c_tpu = auto_chunk(rows, k, m, budget, lanes=128)
    assert c_cpu == m // 2
    assert c_tpu is not None and c_tpu <= max(m // 16, 8)
    # k >= lanes: no padding difference.
    assert auto_chunk(rows, 128, m, budget * 8, lanes=128) == \
        auto_chunk(rows, 128, m, budget * 8, lanes=1)
