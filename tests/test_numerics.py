"""Numerics policy (utils/numerics.py) and hardware-derived budgets
(utils/platform.py)."""

import numpy as np
import pytest

from arrow_matrix_tpu.utils import numerics
from arrow_matrix_tpu.utils.platform import (
    device_memory_budget,
    force_cpu_devices,
)


def test_tolerance_scales_with_terms_and_iters():
    t1 = numerics.relative_tolerance(16, 1)
    assert t1 == pytest.approx(64 * numerics.EPS_F32 * 4.0)
    assert numerics.relative_tolerance(64, 1) == pytest.approx(2 * t1)
    assert numerics.relative_tolerance(16, 10) == pytest.approx(10 * t1)
    # Degenerate inputs clamp instead of vanishing.
    assert numerics.relative_tolerance(0) > 0
    assert numerics.relative_tolerance(1, 0) > 0


def test_relative_error():
    a = np.ones((4, 4), np.float32)
    assert numerics.relative_error(a, a) == 0.0
    assert numerics.relative_error(2 * a, a) == pytest.approx(1.0)
    # Zero reference does not divide by zero.
    assert np.isfinite(numerics.relative_error(a, np.zeros_like(a)))


def test_device_memory_budget_positive():
    # On the virtual-CPU test fixture this resolves via host RAM (or the
    # backend's memory_stats); either way it must be a usable number.
    budget = device_memory_budget()
    assert budget > 0


def test_force_cpu_devices_replaces_existing_count(monkeypatch):
    import os

    # The request must win over an inherited flag value (ADVICE r1).
    monkeypatch.setenv(
        "XLA_FLAGS", "--xla_force_host_platform_device_count=4")
    with pytest.warns(UserWarning, match="replacing"):
        force_cpu_devices(8)
    assert "--xla_force_host_platform_device_count=8" in os.environ["XLA_FLAGS"]
    # Same count: no warning, value untouched.
    force_cpu_devices(8)
    assert os.environ["XLA_FLAGS"].count("device_count") == 1
