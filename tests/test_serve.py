"""graft-serve unit tests: the shared RetryPolicy (deterministic
seeded backoff jitter), HBM admission-control edge cases
(exactly-at-budget, zero-headroom, burst shedding — all with
deterministic censuses), dynamic feature-axis batching bit-identity,
the graceful-degradation ladder, legacy-checkpoint resume events, and
the deterministic load generator / SLO report.  The full chaos-under-
load matrix lives in tools/serve_gate.py (wired into the chaos-gate
tests in test_faults.py)."""

import argparse
import json
import os

import jax.numpy as jnp
import numpy as np
import pytest

from arrow_matrix_tpu import faults
from arrow_matrix_tpu.faults import RetryPolicy, Supervisor
from arrow_matrix_tpu.obs import flight
from arrow_matrix_tpu.serve import (
    ArrowServer,
    ExecConfig,
    HBMAccountant,
    ba_executor_factory,
    degradation_ladder,
    request_price_bytes,
    run_trace,
    slo_summary,
    smoke_serve,
    synthetic_trace,
)

N, WIDTH, K, SEED = 64, 16, 2, 5


@pytest.fixture(autouse=True)
def _no_leftover_plan():
    faults.clear_plan()
    yield
    faults.clear_plan()


@pytest.fixture(scope="module")
def factory():
    """One BA decomposition shared by every server in this module."""
    return ba_executor_factory(N, WIDTH, SEED, fmt="fold")


def _serve(factory_pair, trace, **kw):
    fac, n_rows = factory_pair
    base = kw.pop("base_config", ExecConfig())
    srv = ArrowServer(fac, base, policy=RetryPolicy(backoff_s=0.001),
                      **kw)
    return srv, run_trace(srv, trace)


def _trace(n_rows, requests=4, tenants=2, iterations=2, **kw):
    return synthetic_trace(n_rows, tenants=tenants, requests=requests,
                           k=K, iterations=iterations, seed=SEED, **kw)


# ---------------------------------------------------------------------------
# RetryPolicy (faults/policy.py)
# ---------------------------------------------------------------------------

def test_policy_jitter_deterministic():
    p = RetryPolicy(max_retries=3, backoff_s=0.1, jitter=0.5, seed=7)
    assert p.delay_s(1, salt="a") == p.delay_s(1, salt="a")
    assert p.schedule(salt="a") == p.schedule(salt="a")
    # Different salts / attempts draw different jitter.
    assert p.delay_s(1, salt="a") != p.delay_s(1, salt="b")
    # Jitter stays within the +/- fraction around the exponential base.
    for a in (1, 2, 3):
        base = 0.1 * 2.0 ** (a - 1)
        assert abs(p.delay_s(a, salt="x") - base) <= 0.5 * base + 1e-12


def test_policy_no_jitter_is_pure_exponential():
    p = RetryPolicy(max_retries=3, backoff_s=0.05, backoff_factor=3.0)
    assert p.schedule() == pytest.approx((0.05, 0.15, 0.45))


def test_policy_validation():
    with pytest.raises(ValueError, match="max_retries"):
        RetryPolicy(max_retries=-1)
    with pytest.raises(ValueError, match="jitter"):
        RetryPolicy(jitter=1.5)
    with pytest.raises(ValueError, match="backoff"):
        RetryPolicy(backoff_factor=0.5)


def test_policy_from_args_reads_heal_flags():
    ns = argparse.Namespace(max_retries=5, watchdog=2.0,
                            retry_jitter=0.25, seed=9)
    p = RetryPolicy.from_args(ns)
    assert (p.max_retries, p.watchdog_s, p.jitter, p.seed) == \
        (5, 2.0, 0.25, 9)
    q = RetryPolicy.from_args(ns, max_retries=1)
    assert q.max_retries == 1   # explicit override wins


def test_supervisor_legacy_kwargs_and_policy():
    sup = Supervisor("t", max_retries=5, backoff_s=0.5, verbose=False)
    assert sup.policy.max_retries == 5 and sup.max_retries == 5
    pol = RetryPolicy(max_retries=1, watchdog_s=3.0)
    sup2 = Supervisor("t", max_retries=9, policy=pol, verbose=False)
    assert sup2.max_retries == 1 and sup2.watchdog_s == 3.0


# ---------------------------------------------------------------------------
# HBM accountant + admission edge cases
# ---------------------------------------------------------------------------

def test_accountant_exact_at_budget():
    acc = HBMAccountant(100)
    acc.charge_resident(40)
    assert acc.reserve(60)          # exactly at budget admits
    assert not acc.reserve(1)       # one byte over is rejected
    acc.release(60)
    assert acc.headroom_bytes() == 60
    acc.release(10 ** 9)            # release floors at resident
    snap = acc.snapshot()
    assert snap["in_use_bytes"] == 40 == snap["resident_bytes"]
    assert snap["peak_in_use_bytes"] == 100


def test_accountant_rejects_resident_over_budget():
    from arrow_matrix_tpu.serve import ServeCapacityError

    acc = HBMAccountant(10)
    with pytest.raises(ServeCapacityError):
        acc.charge_resident(11)


def test_admission_exactly_at_budget(factory):
    """A budget with headroom for exactly one request admits exactly
    one (<=, not <) and explicitly rejects the second."""
    fac, n_rows = factory
    from arrow_matrix_tpu.obs.memview import predicted_bytes_for

    ex = fac(ExecConfig())
    resident = predicted_bytes_for(ex, 0) or 0
    price = request_price_bytes(ex, K)
    assert price > 0
    srv, tickets = _serve(factory, _trace(n_rows, requests=2),
                          hbm_budget_bytes=resident + price)
    s = srv.summary()
    assert (s["admitted"], s["rejected"], s["completed"]) == (1, 1, 1)
    assert tickets[0].status == "completed"
    assert tickets[1].status == "rejected"
    assert tickets[1].reason == "hbm_budget"
    assert "headroom" in tickets[1].error


def test_admission_zero_headroom_budget(factory):
    """A budget covering only the resident operator rejects every
    request — deterministically, never silently."""
    fac, n_rows = factory
    from arrow_matrix_tpu.obs.memview import predicted_bytes_for

    resident = predicted_bytes_for(fac(ExecConfig()), 0) or 0
    srv, tickets = _serve(factory, _trace(n_rows, requests=3),
                          hbm_budget_bytes=resident)
    s = srv.summary()
    assert (s["admitted"], s["rejected"]) == (0, 3)
    assert all(t.status == "rejected" and t.reason == "hbm_budget"
               for t in tickets)
    assert s["hbm"]["peak_in_use_bytes"] <= resident


def test_burst_shedding_deterministic(factory):
    """A burst past the bounded queue sheds exactly the overflow with
    an explicit reason, and the census replays identically."""
    fac, n_rows = factory

    def burst():
        srv = ArrowServer(fac, ExecConfig(), queue_capacity=2,
                          policy=RetryPolicy(backoff_s=0.001))
        tickets = [srv.submit(r)
                   for r in _trace(n_rows, requests=6)]   # no draining
        srv.drain()
        return srv.summary(), [(t.status, t.reason) for t in tickets]

    s1, census1 = burst()
    s2, census2 = burst()
    assert (s1["completed"], s1["shed"]) == (2, 4)
    assert census1 == census2
    assert census1.count(("shed", "queue_full")) == 4
    assert all(status in ("completed", "shed") for status, _ in census1)


def test_deadline_expired_requests_shed_explicitly(factory):
    fac, n_rows = factory
    srv, tickets = _serve(factory,
                          _trace(n_rows, requests=2, deadline_s=1e-9))
    assert all(t.status == "shed" and t.reason == "deadline"
               for t in tickets)
    assert srv.summary()["shed"] == 2


def test_submit_after_shutdown_sheds_explicitly(factory):
    fac, n_rows = factory
    srv = ArrowServer(fac, ExecConfig(), queue_capacity=4)
    srv.start()
    srv.shutdown(wait=True)
    t = srv.submit(_trace(n_rows, requests=1)[0])
    assert t.status == "shed" and t.reason == "server_stopped"


# ---------------------------------------------------------------------------
# Dynamic batching + worker-thread mode
# ---------------------------------------------------------------------------

def test_batching_bit_identical(factory):
    """Feature-axis batching (SpMM column separability) returns each
    request exactly the bytes it gets when run alone."""
    fac, n_rows = factory
    trace = _trace(n_rows, requests=4)
    solo_srv, solo = _serve(factory, trace)
    trace2 = _trace(n_rows, requests=4)
    batched_srv, batched = _serve(factory, trace2, max_batch_k=4 * K)
    assert solo_srv.batches == 4
    assert batched_srv.batches < 4
    assert batched_srv.batched_requests == 4
    for a, b in zip(solo, batched):
        assert a.status == b.status == "completed"
        assert a.result.tobytes() == b.result.tobytes()


def test_worker_thread_mode_completes(factory):
    fac, n_rows = factory
    srv = ArrowServer(fac, ExecConfig(), queue_capacity=8,
                      policy=RetryPolicy(backoff_s=0.001))
    srv.start()
    try:
        tickets = run_trace(srv, _trace(n_rows, requests=3))
    finally:
        srv.shutdown(wait=True)
    assert all(t.status == "completed" for t in tickets)


# ---------------------------------------------------------------------------
# Graceful degradation
# ---------------------------------------------------------------------------

def test_degradation_ladder_order():
    ladder = degradation_ladder(
        ExecConfig(kernel="pallas_sell", repl=2, overlap_slabs=2))
    assert [(c.kernel, c.repl, c.overlap_slabs) for c in ladder] == [
        ("pallas_sell", 2, 2), ("xla", 2, 2), ("xla", 1, 2),
        ("xla", 1, 1)]
    assert degradation_ladder(ExecConfig()) == (ExecConfig(),)


def test_exec_config_divisibility():
    cfg = ExecConfig(repl=2, overlap_slabs=2)
    assert cfg.accepts_k(4) and cfg.accepts_k(8)
    assert not cfg.accepts_k(2)   # S=2 does not divide k/c = 1
    assert not cfg.accepts_k(3)   # c=2 does not divide 3
    assert ExecConfig().accepts_k(1)


def test_repeated_faults_degrade_then_complete(factory):
    """Retries exhausted on the base rung: the tenant degrades one
    rung (overlap S=2 -> 1) and the request completes there,
    bit-identical — only a terminal-rung tenant can fail."""
    fac, n_rows = factory
    trace = _trace(n_rows, requests=1, tenants=1)
    _, ref = _serve(factory, _trace(n_rows, requests=1, tenants=1),
                    base_config=ExecConfig(overlap_slabs=2))
    faults.set_plan({"scenario": "error", "site": "multi_level.step",
                     "after": 0, "count": 2})
    srv = ArrowServer(fac, ExecConfig(overlap_slabs=2),
                      policy=RetryPolicy(max_retries=1,
                                         backoff_s=0.001),
                      degrade_after=1)
    tickets = run_trace(srv, trace)
    faults.clear_plan()
    s = srv.summary()
    assert tickets[0].status == "completed"
    assert s["faults_seen"] >= 2
    tenant = s["tenants"][trace[0].tenant]
    assert tenant["rung"] == 1
    assert tenant["config"] == {"kernel": "xla", "repl": 1,
                                "overlap_slabs": 1,
                                "feature_dtype": None}
    assert tenant["degradations"]
    assert tickets[0].result.tobytes() == ref[0].result.tobytes()
    assert tickets[0].attempts == 2


# ---------------------------------------------------------------------------
# Legacy checkpoint resume (satellite: resumed event + loud warning)
# ---------------------------------------------------------------------------

def test_legacy_checkpoint_resume_warns_and_events(tmp_path, capsys):
    """A pre-version (untagged) checkpoint loads with a LOUD warning
    and a ``resumed`` flight event carrying the supervisor's name and
    ``legacy=True`` — never a crash."""
    ck = str(tmp_path / "legacy_ck")
    like = jnp.zeros((8, 2), dtype=jnp.float32)
    x_mid = np.arange(16, dtype=np.float32).reshape(8, 2)
    np.savez(ck + ".npz", x=x_mid, step=np.int64(3))   # no version tag
    rec = flight.FlightRecorder(str(tmp_path / "flight.json"))
    flight.set_recorder(rec)
    try:
        sup = Supervisor("req-legacy", checkpoint_path=ck,
                         layout="serve/test", verbose=False)
        state = sup.resume(like)
    finally:
        flight.set_recorder(None)
    assert state is not None and state[1] == 3
    assert np.asarray(state[0]).tobytes() == x_mid.tobytes()
    err = capsys.readouterr().err
    assert "WARNING" in err and "legacy" in err
    resumed = [e["data"] for e in rec.events
               if e.get("name") == "resumed"
               and e.get("data", {}).get("supervisor") == "req-legacy"]
    assert resumed and resumed[-1]["legacy"] is True
    assert resumed[-1]["step"] == 3


def test_tagged_checkpoint_resume_not_legacy(tmp_path):
    from arrow_matrix_tpu.utils.checkpoint import checkpoint_meta

    ck = str(tmp_path / "tagged_ck")
    like = jnp.ones((4, 2), dtype=jnp.float32)
    sup = Supervisor("req-tagged", checkpoint_path=ck,
                     layout="serve/test", verbose=False)
    sup._save(like, 2)
    meta = checkpoint_meta(ck)
    assert meta is not None and meta["version"] >= 1
    rec = flight.FlightRecorder(str(tmp_path / "flight.json"))
    flight.set_recorder(rec)
    try:
        state = sup.resume(like)
    finally:
        flight.set_recorder(None)
    assert state is not None and state[1] == 2
    resumed = [e["data"] for e in rec.events
               if e.get("name") == "resumed"
               and e.get("data", {}).get("supervisor") == "req-tagged"]
    assert resumed and resumed[-1]["legacy"] is False


# ---------------------------------------------------------------------------
# Load generator + SLO report
# ---------------------------------------------------------------------------

def test_synthetic_trace_deterministic():
    a = synthetic_trace(32, tenants=3, requests=5, k=2, seed=4)
    b = synthetic_trace(32, tenants=3, requests=5, k=2, seed=4)
    assert [r.tenant for r in a] == [r.tenant for r in b]
    assert all(x.x.tobytes() == y.x.tobytes() for x, y in zip(a, b))
    c = synthetic_trace(32, tenants=3, requests=5, k=2, seed=5)
    assert any(x.x.tobytes() != y.x.tobytes() for x, y in zip(a, c))


def test_slo_summary_and_artifacts(factory, tmp_path):
    fac, n_rows = factory
    srv, tickets = _serve(factory, _trace(n_rows, requests=3))
    summary = slo_summary(srv, tickets, wall_s=1.0)
    lat = summary["latency_ms"]
    assert summary["completed"] == 3
    assert lat["p50"] is not None and lat["p99"] is not None
    assert lat["p50"] <= lat["p99"]
    assert summary["requests_per_s"] == 3.0
    for field in ("shed", "rejected", "hbm", "per_tenant",
                  "faults_seen", "checkpoint_corruptions"):
        assert field in summary
    for rec in summary["per_tenant"].values():
        assert "latency_ms" in rec and "rung" in rec
    from arrow_matrix_tpu.serve import write_serve_artifacts

    path = write_serve_artifacts(str(tmp_path), summary)
    with open(path, encoding="utf-8") as fh:
        assert json.load(fh)["completed"] == 3


def test_smoke_serve_round_trip(tmp_path):
    out = str(tmp_path / "serve")
    s = smoke_serve(out, n=N, width=WIDTH)
    assert s["completed"] == s["requests"] and s["failed"] == 0
    assert s["latency_ms"]["p99"] is not None
    assert os.path.isfile(os.path.join(out, "serve_summary.json"))
    assert os.path.isfile(os.path.join(out, "metrics.jsonl"))


def test_request_price_matches_carriage_model(factory):
    fac, _ = factory
    ex = fac(ExecConfig())
    price = request_price_bytes(ex, K)
    assert price == ex.carriage_hbm_bytes(K)
    assert price > 0
    assert ex.predicted_hbm_bytes(K) - ex.predicted_hbm_bytes(0) == price
