"""Pallas kernel tests (interpret mode on the CPU fixture; the same
code compiles via Mosaic on TPU — verified on hardware, see
ops/pallas_blocks.py).

Gate: exact agreement with the XLA dense path — the same cpu-vs-device
numerics gate the reference applies between its scipy and cuSPARSE
kernels (reference tests/test_arrowmpi.py:342-398 runs both devices)."""

import numpy as np
import jax.numpy as jnp
import pytest
from scipy import sparse

from arrow_matrix_tpu.ops import arrow_blocks_from_csr, arrow_spmm
from arrow_matrix_tpu.ops.pallas_blocks import (
    _row_tile,
    arrow_spmm_pallas,
    column_spmm_pallas,
    head_spmm_pallas,
)
from arrow_matrix_tpu.parallel.multi_level import MultiLevelArrow
from arrow_matrix_tpu.decomposition import arrow_decomposition
from arrow_matrix_tpu.utils import barabasi_albert, random_dense


from helpers import arrow_csr as _shared_arrow_csr


def _arrow_csr(nb, w, banded, seed, density=0.25):
    return _shared_arrow_csr(nb, w, banded=banded, seed=seed,
                             density=density)


@pytest.mark.parametrize("banded", [False, True])
def test_arrow_spmm_pallas_matches_xla(banded):
    nb, w, k = 6, 32, 8
    a = _arrow_csr(nb, w, banded, seed=1)
    blocks = arrow_blocks_from_csr(a, w, banded=banded, fmt="dense")
    x = jnp.asarray(np.random.default_rng(0)
                    .standard_normal((nb, w, k)).astype(np.float32))
    want = np.asarray(arrow_spmm(blocks, x))
    got = np.asarray(arrow_spmm_pallas(blocks, x))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_head_kernel_accumulates_all_blocks():
    nb, w, k = 5, 16, 4
    rng = np.random.default_rng(3)
    head = rng.standard_normal((nb, w, w)).astype(np.float32)
    x = rng.standard_normal((nb, w, k)).astype(np.float32)
    got = np.asarray(head_spmm_pallas(jnp.asarray(head), jnp.asarray(x)))
    want = sum(head[b] @ x[b] for b in range(nb))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_column_kernel_block_diagonal():
    nb, w, k = 4, 24, 4
    rng = np.random.default_rng(5)
    diag = rng.standard_normal((nb, w, w)).astype(np.float32)
    col = rng.standard_normal((nb, w, w)).astype(np.float32)
    x = rng.standard_normal((nb, w, k)).astype(np.float32)
    got = np.asarray(column_spmm_pallas(jnp.asarray(diag), jnp.asarray(col),
                                        jnp.asarray(x), jnp.asarray(x[0])))
    want = np.stack([diag[b] @ x[b] + col[b] @ x[0] for b in range(nb)])
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_row_tile_divides_and_budgets():
    for w in (16, 200, 512, 2000, 2048):
        for stacks in (1, 2, 4):
            t = _row_tile(w, stacks)
            assert w % t == 0
            assert stacks * t * w * 4 * 2 <= max(8 << 20, stacks * 8 * w * 8)


def test_pallas_rejects_ell_format():
    a = _arrow_csr(4, 16, False, seed=2)
    blocks = arrow_blocks_from_csr(a, 16, fmt="ell")
    x = jnp.zeros((4, 16, 4), dtype=jnp.float32)
    with pytest.raises(ValueError):
        arrow_spmm_pallas(blocks, x)


def test_multi_level_pallas_kernel_end_to_end():
    n, width = 512, 64
    a = barabasi_albert(n, 3, seed=4)
    levels = arrow_decomposition(a, width, max_levels=4,
                                 block_diagonal=True, seed=0)
    x = random_dense(n, 8, seed=1)
    ml_x = MultiLevelArrow(levels, width, mesh=None, fmt="dense")
    ml_p = MultiLevelArrow(levels, width, mesh=None, fmt="dense",
                           kernel="pallas")
    want = ml_x.gather_result(ml_x.step(ml_x.set_features(x)))
    got = ml_p.gather_result(ml_p.step(ml_p.set_features(x)))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("n_dev", [4, 8])
def test_multi_level_pallas_distributed_matches(n_dev):
    """Per-shard Pallas under shard_map == XLA GSPMD path on a mesh
    (VERDICT r1 item 6: the distributed Pallas integration)."""
    from arrow_matrix_tpu.parallel.mesh import make_mesh

    n, width = 512, 64
    a = barabasi_albert(n, 3, seed=4)
    levels = arrow_decomposition(a, width, max_levels=3,
                                 block_diagonal=True, seed=0)
    x = random_dense(n, 8, seed=1)
    mesh = make_mesh((n_dev,), ("blocks",))
    ml_x = MultiLevelArrow(levels, width, mesh=mesh, fmt="dense")
    ml_p = MultiLevelArrow(levels, width, mesh=mesh, fmt="dense",
                           kernel="pallas")
    want = ml_x.gather_result(ml_x.step(ml_x.set_features(x)))
    got = ml_p.gather_result(ml_p.step(ml_p.set_features(x)))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("banded", [False, True])
def test_slim_spmm_pallas_kernel_matches(banded):
    """make_slim_spmm(kernel='pallas') == kernel='xla' on a mesh,
    including the banded ppermute halos feeding the fused kernel."""
    import jax.numpy as jnp

    from arrow_matrix_tpu.ops import block_features, unblock_features
    from arrow_matrix_tpu.parallel import make_slim_spmm, shard_blocked
    from arrow_matrix_tpu.parallel.mesh import make_mesh, shard_arrow_blocks

    nb, w, k = 8, 32, 8
    a = _arrow_csr(nb, w, banded, seed=9)
    blocks = arrow_blocks_from_csr(a, w, banded=banded, fmt="dense")
    mesh = make_mesh((8,), ("blocks",))
    x_host = random_dense(nb * w, k, seed=2)
    xb = shard_blocked(jnp.asarray(block_features(x_host, w, nb)), mesh)
    bs = shard_arrow_blocks(blocks, mesh)

    want = unblock_features(make_slim_spmm(blocks, mesh)(bs, xb), nb * w)
    got = unblock_features(
        make_slim_spmm(blocks, mesh, kernel="pallas")(bs, xb), nb * w)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(got, a @ x_host, rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("kernel", ["xla", "pallas"])
def test_bf16_block_storage_matches_f32(kernel):
    """bf16 block storage with f32 accumulation: halves resident-block
    HBM bytes; result within bf16 rounding of the f32 path."""
    n, width = 512, 64
    a = barabasi_albert(n, 3, seed=6)
    levels = arrow_decomposition(a, width, max_levels=3,
                                 block_diagonal=True, seed=0)
    x = random_dense(n, 8, seed=2)
    ml32 = MultiLevelArrow(levels, width, mesh=None, fmt="dense")
    ml16 = MultiLevelArrow(levels, width, mesh=None, fmt="dense",
                           dtype="bf16", kernel=kernel)
    want = ml32.gather_result(ml32.step(ml32.set_features(x)))
    got = ml16.gather_result(ml16.step(ml16.set_features(x)))
    # bf16 has ~8 mantissa bits: 2^-8 per rounded operand.
    np.testing.assert_allclose(got, want, rtol=2e-2, atol=2e-2)
    blk = ml16.blocks[0]
    assert blk.diag_data.dtype == jnp.bfloat16


def test_unknown_dtype_rejected():
    from arrow_matrix_tpu.parallel.multi_level import resolve_block_dtype

    with pytest.raises(ValueError, match="unknown block dtype"):
        resolve_block_dtype("fp8")
