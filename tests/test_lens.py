"""graft-lens tests (arrow_matrix_tpu/obs/lens.py + obs/costmodel.py):
static counter invariants over the kcert metas and the fingerprint
ladder, cost-model fit/round-trip/versioning, ratio + coverage
bookkeeping (below-resolution exclusion), the ledger gate's lens
calibration band, the tune-space compute screen, the xray compute
subdivision, and the tools/lens_gate.py fixture discipline."""

import copy
import importlib.util
import json
import os
import sys

import numpy as np
import pytest

from arrow_matrix_tpu.decomposition import arrow_decomposition
from arrow_matrix_tpu.obs import lens
from arrow_matrix_tpu.obs.costmodel import (
    GRANULE,
    CostModel,
    fit_cost_model,
    ladder_padded_slots,
    meta_dma_copies,
    meta_grid_programs,
    meta_padded_rows,
    meta_smem_bytes,
    meta_stream_bytes,
    meta_wave_count,
    predict_candidate_ms,
    tier_counters,
    tier_family,
    tier_stream_bytes,
)
from arrow_matrix_tpu.tune import (
    enumerate_candidates,
    structure_fingerprint,
)
from arrow_matrix_tpu.utils import barabasi_albert

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _levels(n=120, width=16, seed=3, m=3, max_levels=4):
    a = barabasi_albert(n, m, seed=seed)
    return arrow_decomposition(a, width, max_levels=max_levels,
                               block_diagonal=True, seed=seed)


def _profile(tiers, *, full_ms=0.1, attributed=None, coverage=None,
             dtype="f32", kernel="xla"):
    """Minimal schema-valid profile around one tier list."""
    att = (sum(t.get("measured_ms") or 0.0 for t in tiers)
           if attributed is None else attributed)
    return {
        "schema": lens.LENS_PROFILE_SCHEMA, "kind": "lens_profile",
        "structure_hash": "testhash", "platform": "cpu",
        "device_kind": "cpu", "width": 16, "k": 8, "kernel": kernel,
        "iters": 10, "kernel_opts": {}, "n": 100,
        "dtypes": {dtype: {
            "full_ms": full_ms, "chain_floor_ms": 0.001,
            "resolution_ms": 0.005, "attributed_ms": att,
            "coverage": (att / full_ms if coverage is None
                         else coverage),
            "tiers": tiers, "dma_wait_ms": {}}},
    }


def _tier(t, family, *, rows=100, nnz=500, slots=800, width=8,
          ms=0.05, **extra):
    return {"tier": t, "family": family, "rows": rows, "nnz": nnz,
            "slots": slots, "slot_width": width,
            "padded_slots": slots - nnz, "streamed_bytes": 4096,
            "measured_ms": ms, **extra}


# ---------------------------------------------------------------------------
# Static counters (satellite: pure functions over kcert metas)
# ---------------------------------------------------------------------------

def test_granule_pinned_to_kernel():
    # The cost model prices the fused kernel's granule-line streaming;
    # a GRANULE drift would silently misprice every pallas tier.
    from arrow_matrix_tpu.ops import pallas_sell
    assert GRANULE == pallas_sell.GRANULE


def test_tier_family_bounds():
    assert tier_family(0) == "zero"
    assert tier_family(1) == "tail"
    assert tier_family(GRANULE) == "tail"
    assert tier_family(GRANULE + 1) == "mid"
    assert tier_family(64) == "mid"
    assert tier_family(65) == "head"


def test_counters_over_sell_kcert_metas():
    from arrow_matrix_tpu.ops import pallas_sell
    metas = pallas_sell.kcert_metas()
    assert isinstance(metas, list) and metas
    for meta in metas:
        assert meta_grid_programs(meta) >= 1
        bytes_ = meta_stream_bytes(meta)
        assert bytes_ > 0
        if meta.get("kind") in ("sell_stream", "sell_vectorized"):
            # Every slot of every slab row fetches one granule line.
            m_t, slab = (int(v) for v in meta["ins"][0]["shape"])
            assert bytes_ % (m_t * slab) == 0
            assert meta_padded_rows(meta) == slab
        if meta.get("stream"):
            assert meta_wave_count(meta) >= int(meta["stream"]["m_t"])
            assert meta_dma_copies(meta) == (
                int(meta["stream"]["m_t"]) * int(meta["stream"]["slab"]))
        else:
            assert meta_wave_count(meta) == 0
        assert meta_smem_bytes(meta) >= 0


def test_counters_over_dense_kcert_metas():
    # dense_blocks metas have no gather: the declared operand blocks
    # ARE the traffic, scaled by the grid.
    from arrow_matrix_tpu.ops import pallas_blocks
    for meta in pallas_blocks.kcert_metas():
        assert meta_stream_bytes(meta) > 0
        assert meta_wave_count(meta) == 0
        assert meta_grid_programs(meta) >= 1


def test_tier_counters_from_fingerprint():
    fp = structure_fingerprint(_levels(), 16)
    ladder = fp["ladder"]
    for kernel in ("xla", "pallas"):
        counters = tier_counters(fp, 8, kernel=kernel)
        assert len(counters) == len(ladder["rows"])
        for t, c in enumerate(counters):
            assert c["family"].startswith(f"{kernel}:")
            assert c["padded_slots"] == c["slots"] - c["nnz"]
            assert c["family"].split(":")[1] == tier_family(
                c["slot_width"])
        assert ([c["padded_slots"] for c in counters]
                == ladder_padded_slots(fp))
    xla = tier_counters(fp, 8, kernel="xla")
    pallas = tier_counters(fp, 8, kernel="pallas")
    for cx, cp in zip(xla, pallas):
        # Granule-line streaming never moves fewer bytes than the
        # per-row XLA gather (padding up to granule multiples).
        assert cp["streamed_bytes"] >= cx["streamed_bytes"]
        assert cx["streamed_bytes"] == tier_stream_bytes(
            cx["slot_width"], cx["rows"], 8)
    bf16 = tier_counters(fp, 8, kernel="xla", feature_dtype="bf16")
    for cx, cb in zip(xla, bf16):
        assert cb["streamed_bytes"] * 2 == cx["streamed_bytes"]


def test_imbalance_report_carries_padded_slots():
    from arrow_matrix_tpu.obs.imbalance import summarize_units
    rep = summarize_units([10, 20], [30, 50], [40, 80], units="tier")
    assert rep["padded_slots"] == [10, 30]
    assert rep["padded_slot_waste"] == pytest.approx(40 / 120)
    assert rep["padded_slot_waste_per_unit"][0] == pytest.approx(0.25)


# ---------------------------------------------------------------------------
# Cost model fit / round trip / versioning
# ---------------------------------------------------------------------------

def test_costmodel_fit_roundtrip_and_version_skew():
    pts = [_tier(0, "xla:tail", nnz=900, rows=200, ms=0.06),
           _tier(1, "xla:mid", nnz=1200, rows=100, width=16, ms=0.04)]
    model = fit_cost_model(pts, structure_hash="h", platform="cpu")
    assert set(model.coeffs) == {"xla:tail", "xla:mid"}
    # The fit is exact in aggregate per family (global rescale).
    for p in pts:
        pred = model.predict_point(p["family"], p["nnz"], p["rows"],
                                   p["streamed_bytes"])
        assert pred == pytest.approx(p["measured_ms"], rel=1e-6)
    doc = model.to_dict()
    assert CostModel.from_dict(doc).to_dict() == doc
    bad = dict(doc, version=doc["version"] + 1)
    with pytest.raises(ValueError, match="version"):
        CostModel.from_dict(bad)


def test_unseen_family_falls_back_to_kernel_pool():
    model = fit_cost_model([_tier(0, "xla:tail", ms=0.05)])
    # Same-kernel fallback prices what it has never seen — the tune
    # screen must never raise on a candidate.
    assert model.predict_point("xla:head", 500, 100, 4096) > 0.0
    assert model.predict_point("pallas:mid", 500, 100, 4096) > 0.0


# ---------------------------------------------------------------------------
# Profile bookkeeping: fit exclusion, ratios, attribution, explain
# ---------------------------------------------------------------------------

def test_below_resolution_excluded_from_fit_and_ratios():
    tiers = [_tier(0, "xla:tail", ms=0.06),
             _tier(1, "xla:mid", width=16, ms=0.04),
             _tier(2, "xla:tail", ms=0.002, below_resolution=True)]
    profile = _profile(tiers, full_ms=0.102)
    model = lens.fit_from_profile(profile)
    pts = lens.ratio_points(profile, model)
    assert all(p["tier"] != 2 for p in pts)
    # One full-iteration point per dtype rides along.
    full = [p for p in pts if p["tier"] is None]
    assert len(full) == 1 and full[0]["family"] == "full"
    # The sub-resolution tier still counts toward attribution.
    frac = lens.attribution_fractions(profile, "f32")
    assert "L2:tail" in frac
    assert sum(frac.values()) == pytest.approx(1.0)
    assert not lens.check_profile(profile, model)


def test_attribution_fractions_other_and_renormalize():
    profile = _profile([_tier(0, "xla:tail", ms=0.06)], full_ms=0.1)
    frac = lens.attribution_fractions(profile, "f32")
    assert frac["other"] == pytest.approx(0.4)
    over = _profile([_tier(0, "xla:tail", ms=0.08),
                     _tier(1, "xla:mid", width=16, ms=0.06)],
                    full_ms=0.1)
    frac = lens.attribution_fractions(over, "f32")
    assert "other" not in frac
    assert sum(frac.values()) == pytest.approx(1.0)


def _gap_profile(f32_bytes=1000, bf16_bytes=500, f32_ms=0.1,
                 bf16_ms=0.3):
    prof = _profile([_tier(0, "xla:tail", ms=f32_ms)], full_ms=f32_ms)
    prof["dtypes"]["f32"]["tiers"][0]["streamed_bytes"] = f32_bytes
    b = copy.deepcopy(prof["dtypes"]["f32"])
    b["full_ms"] = bf16_ms
    b["tiers"][0]["measured_ms"] = bf16_ms
    b["tiers"][0]["streamed_bytes"] = bf16_bytes
    prof["dtypes"]["bf16"] = b
    return prof


def test_explain_gap_segments():
    prof = _gap_profile()
    out = lens.explain_gap(prof)
    assert out["dominant"] == "L0:tail"
    assert out["gap_ms"] == pytest.approx(0.2)
    # Without a model the residual is decode/accumulate by default.
    assert out["dominant_segment"] == "decode/accumulate"
    # A byte coefficient large enough to explain >= half the delta
    # reclassifies it as the gather/stream term.
    gather = CostModel(structure_hash="h", platform="cpu",
                       coeffs={"xla:tail": {"streamed_bytes": 4e-4}})
    out = lens.explain_gap(prof, model=gather)
    assert out["dominant_segment"] == "gather-bytes"
    tiny = CostModel(structure_hash="h", platform="cpu",
                     coeffs={"xla:tail": {"streamed_bytes": 1e-9}})
    out = lens.explain_gap(prof, model=tiny)
    assert out["dominant_segment"] == "decode/accumulate"


def test_explain_gap_dma_wait_dominates():
    prof = _gap_profile(bf16_ms=0.11)
    prof["dtypes"]["bf16"]["dma_wait_ms"] = {"pallas:tail": 1.0}
    out = lens.explain_gap(prof)
    assert out["dominant"] == "dma_wait"
    assert out["dominant_segment"] == "dma-wait"


# ---------------------------------------------------------------------------
# Ledger: record validity + the gate's lens calibration band
# ---------------------------------------------------------------------------

def test_lens_constants_pinned_to_ledger_gate():
    from arrow_matrix_tpu.ledger import gate
    assert gate.LENS_RATIO_MIN == lens.LENS_RATIO_MIN
    assert gate.LENS_RATIO_MAX == lens.LENS_RATIO_MAX


def test_record_profile_validates_and_pins_ratio_host_load(tmp_path):
    tiers = [_tier(0, "xla:tail", ms=0.06),
             _tier(1, "xla:mid", width=16, ms=0.04)]
    profile = _profile(tiers, full_ms=0.1)
    model = lens.fit_from_profile(profile)
    d = str(tmp_path / "ledger")
    ids = lens.record_profile(profile, model, directory=d)
    assert ids
    from arrow_matrix_tpu.ledger.store import Ledger
    led = Ledger(d)
    assert led.validate() == []
    recs = led.read_all()
    assert {r["kind"] for r in recs} == {"lens"}
    for r in recs:
        # Ratios are load-invariant and recorded unpinned to any
        # loadavg; millisecond metrics keep the live stamp.
        if r["unit"] == "ratio":
            assert r["host_load"] is None
        else:
            assert r["host_load"] is not None


def _lens_rec(tmp_path, value, metric="lens_ratio_t0"):
    from arrow_matrix_tpu.ledger.store import Ledger
    led = Ledger(str(tmp_path / "l"))
    return led.record("lens", metric, value, unit="ratio",
                      structure_hash="h", platform="cpu",
                      host_load=None)


def test_gate_lens_absolute_band(tmp_path):
    from arrow_matrix_tpu.ledger.gate import baseline_key, check_records
    bad = _lens_rec(tmp_path, 3.0)
    failures, _ = check_records([bad], {"metrics": {}})
    assert any("lens miscalibration" in f for f in failures)
    ok = _lens_rec(tmp_path, 1.0, metric="lens_ratio_t1")
    failures, notes = check_records([ok], {"metrics": {}})
    assert failures == []
    assert any("no baseline" in n for n in notes)
    assert baseline_key(ok) == "lens|lens_ratio_t1|h|cpu"


def test_gate_lens_drift_band(tmp_path):
    from arrow_matrix_tpu.ledger.gate import baseline_key, check_records
    rec = _lens_rec(tmp_path, 1.8)
    base = {"metrics": {baseline_key(rec): {"median": 1.0,
                                            "unit": "ratio"}}}
    failures, _ = check_records([rec], base)
    # 1.8 is inside the absolute band but > 1.5x the baseline median.
    assert any("drifted" in f for f in failures)
    ok = _lens_rec(tmp_path, 1.2, metric="lens_ratio_t1")
    base = {"metrics": {baseline_key(ok): {"median": 1.0,
                                           "unit": "ratio"}}}
    assert check_records([ok], base)[0] == []


# ---------------------------------------------------------------------------
# Consumers: tune compute screen, xray compute subdivision
# ---------------------------------------------------------------------------

def test_tune_screen_prunes_on_lens_prediction():
    fp = structure_fingerprint(_levels(), 16)
    cheap = {r: 1e-9 for r in ("nnz", "rows", "streamed_bytes")}
    model = CostModel(
        structure_hash=fp.get("structure_hash", ""), platform="cpu",
        coeffs={**{f"xla:{f}": dict(cheap)
                   for f in ("zero", "tail", "mid", "head")},
                **{f"pallas:{f}": {"nnz": 1.0, "rows": 1.0,
                                   "streamed_bytes": 0.0}
                   for f in ("zero", "tail", "mid", "head")}})
    plain, plain_pruned = enumerate_candidates(fp, 16, platform="cpu")
    cands, pruned = enumerate_candidates(fp, 16, platform="cpu",
                                         lens_model=model)
    lens_pruned = {n: r for n, r in pruned.items()
                   if r.startswith("lens: ")}
    # The screen prunes before any child spawn, with a "lens: " reason.
    assert lens_pruned
    assert all("predicted compute" in r for r in lens_pruned.values())
    names = [c.name for c in cands]
    assert "default" in names
    # The screen only prunes; it never touches eligibility — the f32
    # bit-identity contract is unchanged for surviving candidates.
    plain_elig = {c.name: c.eligible for c in plain}
    for c in cands:
        assert c.eligible == plain_elig[c.name]
    # Predicting a lens-pruned candidate confirms the 3x margin.
    name = next(iter(lens_pruned))
    cand = {c.name: c for c in plain}[name]
    base = predict_candidate_ms(model, fp, 16, {}, {})
    assert predict_candidate_ms(model, fp, 16, cand.build,
                                cand.kernel_opts) > 3.0 * base


def test_xray_subdivide_compute():
    from arrow_matrix_tpu.obs.xray import subdivide_compute
    cp = {"per_class": {"exact": {"segments_mean_ms":
                                  {"compute": 10.0, "wire": 1.0}}}}
    out = subdivide_compute(cp, {"exact": {"L0:tail": 0.6,
                                           "other": 0.4}})
    bd = out["per_class"]["exact"]["compute_breakdown_ms"]
    assert bd == {"L0:tail": 6.0, "other": 4.0}
    # Unmatched classes pass through untouched.
    assert "compute_breakdown_ms" not in subdivide_compute(
        cp, {})["per_class"]["exact"]


# ---------------------------------------------------------------------------
# tools/: the lens gate, its fixtures, the obs-gate validator
# ---------------------------------------------------------------------------

def test_lens_gate_selftest_and_committed_artifacts():
    gate = _load_tool("lens_gate")
    assert gate.selftest() == 0
    # The committed ba_256_3 calibration must pass its own gate.
    assert gate.main([]) == 0


def test_planted_miscalibration_fixture_trips_the_gate():
    gate = _load_tool("lens_gate")
    path = os.path.join(REPO, "tests", "fixtures", "lens",
                        "miscalibrated.json")
    with open(path, encoding="utf-8") as fh:
        doc = json.load(fh)
    problems = gate.check_pair(doc["profile"], doc["model"])
    assert problems, "planted miscalibration passed clean"
    assert any("ratio" in p for p in problems)
    assert any("cover" in p for p in problems)
    # --fixture treats it as real data: nonzero exit.
    assert gate.main(["--fixture", path]) == 1
    # --fixtures is the detection-loss direction over the shipped set.
    assert gate.main(["--fixtures"]) == 0


def test_committed_profile_model_hashes_agree():
    gate = _load_tool("lens_gate")
    with open(gate.PROFILE_PATH, encoding="utf-8") as fh:
        profile = json.load(fh)
    with open(gate.MODEL_PATH, encoding="utf-8") as fh:
        model = json.load(fh)
    assert profile["structure_hash"] == model["structure_hash"]
    # Both carriage dtypes are committed — the attribution table in
    # PERFORMANCE.md reads straight off this artifact.
    assert set(profile["dtypes"]) == {"f32", "bf16"}
    # A hash-mismatched model is the silent miscalibration the gate
    # names explicitly.
    problems = gate.check_pair(dict(profile, structure_hash="other"),
                               model)
    assert any("structure hash mismatch" in p for p in problems)


def test_obs_gate_lens_problems_validator():
    og = _load_tool("obs_gate")
    tiers = [_tier(0, "xla:tail", ms=0.06)]
    profile = _profile(tiers, full_ms=0.1)
    assert og.lens_problems(profile) == []
    wrong_kernel = copy.deepcopy(profile)
    wrong_kernel["dtypes"]["f32"]["tiers"][0]["family"] = "pallas:tail"
    assert og.lens_problems(wrong_kernel)
    missing = copy.deepcopy(profile)
    del missing["dtypes"]["f32"]["tiers"][0]["nnz"]
    assert og.lens_problems(missing)
