"""Contract tests for the end-of-round bench (bench.py).

The bench is a driver gate: whatever happens — healthy accelerator,
wedged tunnel, no accelerator at all — it must print exactly one JSON
line with the metric contract and exit 0 iff a headline value exists
(mirrors the reference's bench always reporting through wb_logging,
arrow/arrow_bench.py:12-137).  These tests drive the real CLI in a
subprocess in degraded (CPU-pinned) mode with the probe
short-circuited, exercising the candidate-subprocess race end to end.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")


def _run_bench(tmp_path, extra_env, timeout=420):
    env = dict(os.environ)
    env.update({
        "AMT_BENCH_PLATFORM": "cpu",   # skip the 2x60s dead-plugin probe
        "AMT_BENCH_N": "32768",
        "AMT_BENCH_COMPARE": "0",
        "AMT_BENCH_K128": "0",
        "AMT_BENCH_DEADLINE": str(timeout - 60),
    })
    env.update(extra_env)
    return subprocess.run(
        [sys.executable, BENCH], capture_output=True, text=True,
        timeout=timeout, cwd=tmp_path, env=env)


@pytest.fixture(scope="module")
def bench_success(tmp_path_factory):
    """One shared successful degraded run (the subprocess race is the
    expensive part; both contract tests read the same record)."""
    return _run_bench(tmp_path_factory.mktemp("bench"), {})


def test_degraded_run_succeeds_with_contract(bench_success):
    proc = bench_success
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, f"exactly one JSON line expected: {lines}"
    out = json.loads(lines[0])
    assert out["metric"] == "spmm_iter_ms"
    assert out["unit"] == "ms"
    assert out["value"] > 0
    assert out["vs_baseline"] > 0
    assert out["degraded"] is True
    assert out["fmt_used"] in out["device_runs"]
    win = out["device_runs"][out["fmt_used"]]
    assert win["err"] <= out["frobenius_gate"]
    assert out["scipy_cpu_ms"] > 0


def test_degraded_run_reports_roofline_inputs(bench_success):
    out = json.loads(bench_success.stdout.strip().splitlines()[-1])
    assert out["bytes_per_iter_gb"] > 0
    assert out["achieved_gbps"] > 0
    assert out["config"]["levels"] >= 1
    assert out["config"]["edges_nnz"] > 0


def test_onchip_evidence_skips_degraded_artifacts(tmp_path,
                                                  monkeypatch):
    """A degraded CPU bench captured into the onchip_* namespace (the
    watcher's stage runner writes its artifact on rc=0 even when the
    bench inside fell back to CPU mid-window) must never be embedded
    as the "most recent on-chip capture" — only platform=tpu,
    non-degraded artifacts qualify."""
    sys.path.insert(0, REPO)
    import bench as bench_mod

    cache = tmp_path / "bench_cache"
    cache.mkdir()
    older = {"metric": "spmm_iter_ms", "value": 200.0,
             "platform": "tpu", "device_kind": "TPU v5 lite",
             "config": {"n": 64, "width": 16, "features": 16}}
    newer_degraded = {"metric": "spmm_iter_ms", "value": 1500.0,
                      "platform": "cpu", "degraded": True,
                      "config": {"n": 64, "width": 16, "features": 16}}
    (cache / "onchip_bench_old.json").write_text(json.dumps(older))
    os.utime(cache / "onchip_bench_old.json", (1000, 1000))
    (cache / "onchip_bench_quick_new.json").write_text(
        json.dumps(newer_degraded))
    monkeypatch.chdir(tmp_path)
    ev = bench_mod._last_onchip_evidence()
    assert ev is not None
    assert ev["summary"]["platform"] == "tpu"
    assert ev["summary"]["value"] == 200.0
    # nothing but degraded artifacts -> no evidence at all
    os.remove(cache / "onchip_bench_old.json")
    assert bench_mod._last_onchip_evidence() is None


def test_failed_race_exits_nonzero_with_error_json(tmp_path):
    """An impossible format must produce the diagnosable error line and
    rc=1 — the round-1 postmortem contract (no silent rc without
    JSON)."""
    proc = _run_bench(tmp_path, {"AMT_BENCH_FMT": "no_such_format"},
                      timeout=240)
    assert proc.returncode == 1
    lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1
    out = json.loads(lines[0])
    assert out["value"] is None
    assert "error" in out
    assert "no_such_format" in json.dumps(out["device_runs"])


def test_onchip_evidence_skips_stray_verification_artifacts(tmp_path,
                                                            monkeypatch):
    """A driver/doctor probe artifact (VERIFYDRIVE-style name) in the
    onchip_* namespace is smoke exhaust, never the evidence trail —
    even when its record claims platform=tpu (VERDICT r5 item 9)."""
    sys.path.insert(0, REPO)
    import bench as bench_mod

    cache = tmp_path / "bench_cache"
    cache.mkdir()
    stray = {"metric": "spmm_iter_ms", "value": 1.0, "platform": "tpu",
             "config": {"n": 64, "width": 16, "features": 16}}
    (cache / "onchip_bench_quick_VERIFYDRIVE.json").write_text(
        json.dumps(stray))
    monkeypatch.chdir(tmp_path)
    assert bench_mod._last_onchip_evidence() is None
    real = dict(stray, value=42.0)
    (cache / "onchip_bench_real.json").write_text(json.dumps(real))
    ev = bench_mod._last_onchip_evidence()
    assert ev is not None and ev["summary"]["value"] == 42.0


def test_bench_config_overlap_and_pallas_sell_candidate(monkeypatch):
    """graft-stream bench surface: the pallas_sell race candidate
    exists (fold build + fused kernel), and AMT_BENCH_OVERLAP_SLABS
    threads the static slab count into the candidate config."""
    sys.path.insert(0, REPO)
    import bench as bench_mod

    kw = bench_mod.CANDIDATE_KWARGS["pallas_sell"]
    assert kw["fmt"] == "fold" and kw["kernel"] == "pallas_sell"
    monkeypatch.setenv("AMT_BENCH_PLATFORM", "cpu")
    monkeypatch.setenv("AMT_BENCH_OVERLAP_SLABS", "4")
    cfg = bench_mod._bench_config("cpu")
    assert cfg["overlap_slabs"] == 4
    monkeypatch.delenv("AMT_BENCH_OVERLAP_SLABS")
    assert bench_mod._bench_config("cpu")["overlap_slabs"] == 1
