"""Tests for the two distributed SpMM baselines (1.5D and PETSc-style
1-D), mirroring the reference's baseline test strategy: results compared
against ``A @ X`` computed redundantly on the host
(reference tests/test_spmmPETSc.py:11-42, scripts/spmm_15d_main.py
--validate, :156-223), including unequal slices and zero-row slices
(test_spmmPETSc.py:44-71)."""

import jax
import numpy as np
import pytest
from scipy import sparse

from arrow_matrix_tpu.parallel.mesh import make_mesh
from arrow_matrix_tpu.parallel.spmm_15d import SpMM15D, largest_replication
from arrow_matrix_tpu.parallel.spmm_1d import MatrixSlice1D, equal_slices
from arrow_matrix_tpu.utils.graphs import random_csr, random_dense


def _random_square(n, nnz_per_row, seed):
    a = random_csr(n, n, nnz_per_row, seed=seed)
    return a.astype(np.float32)


class TestSpMM15D:
    @pytest.mark.parametrize("c", [1, 2])
    @pytest.mark.parametrize("n,k", [(64, 8), (97, 5)])
    def test_matches_host(self, c, n, k):
        n_dev = 8
        mesh = make_mesh((n_dev // c, c), ("rows", "repl"))
        a = _random_square(n, 4, seed=n + c)
        x = random_dense(n, k, seed=1)

        dist = SpMM15D(a, mesh)
        y = dist.spmm(dist.set_features(x))
        got = dist.gather_result(y)
        want = a @ x
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    @pytest.mark.parametrize("implicit_ones", [False, True])
    def test_memmapped_triplet_build_matches_scipy(self, tmp_path,
                                                   implicit_ones):
        """SpMM15D built from a memmapped npy CSR triplet (the
        reference's generate_15d_decomposition_new ingest,
        spmm_15d.py:158-309) is bit-identical to the in-memory build
        and never needs the whole matrix in RAM."""
        mesh = make_mesh((4, 2), ("rows", "repl"))
        a = _random_square(96, 4, seed=17)
        if implicit_ones:
            a.data[:] = 1.0
        np.save(tmp_path / "d.npy", a.data)
        np.save(tmp_path / "i.npy", a.indices)
        np.save(tmp_path / "p.npy", a.indptr)
        triplet = (
            None if implicit_ones
            else np.load(tmp_path / "d.npy", mmap_mode="r"),
            np.load(tmp_path / "i.npy", mmap_mode="r"),
            np.load(tmp_path / "p.npy", mmap_mode="r"))
        x = random_dense(96, 4, seed=3)

        mem = SpMM15D(a, mesh)
        mm = SpMM15D(triplet, mesh)
        np.testing.assert_array_equal(np.asarray(mm.a_cols),
                                      np.asarray(mem.a_cols))
        np.testing.assert_array_equal(np.asarray(mm.a_data),
                                      np.asarray(mem.a_data))
        got = mm.gather_result(mm.spmm(mm.set_features(x)))
        np.testing.assert_allclose(got, a @ x, rtol=1e-5, atol=1e-5)

    def test_replicas_identical(self):
        mesh = make_mesh((4, 2), ("rows", "repl"))
        a = _random_square(64, 3, seed=3)
        x = random_dense(64, 4, seed=2)
        dist = SpMM15D(a, mesh)
        y = np.asarray(dist.spmm(dist.set_features(x)))
        for j in range(1, dist.c):
            np.testing.assert_array_equal(y[:, 0], y[:, j])

    def test_iterated(self):
        mesh = make_mesh((4, 2), ("rows", "repl"))
        a = _random_square(48, 3, seed=5)
        # Scale to keep iterates bounded.
        a = (a / max(abs(a).sum(axis=1).max(), 1.0)).tocsr().astype(np.float32)
        x = random_dense(48, 4, seed=4)
        dist = SpMM15D(a, mesh)
        xd = dist.set_features(x)
        want = x
        for _ in range(3):
            xd = dist.as_features(dist.spmm(xd))
            want = a @ want
        got = dist.gather_result(dist.spmm(xd))
        np.testing.assert_allclose(got, a @ want, rtol=1e-4, atol=1e-5)

    def test_replication_validation(self):
        mesh = make_mesh((8,), ("rows",))
        mesh2 = make_mesh((2, 4), ("rows", "repl"))
        a = _random_square(32, 3, seed=1)
        # rows=2 not divisible by repl=4: the reference's P % c**2 rule
        # (spmm_15d.py:38-40).
        with pytest.raises(ValueError):
            SpMM15D(a, mesh2)

    def test_largest_replication(self):
        assert largest_replication(1) == 1
        assert largest_replication(4) == 2
        assert largest_replication(8) == 2
        assert largest_replication(16) == 4
        assert largest_replication(6) == 1


class TestMatrixSlice1D:
    @pytest.mark.parametrize("n,k,seed", [(64, 8, 0), (97, 5, 1), (33, 3, 2)])
    def test_matches_host(self, n, k, seed):
        mesh = make_mesh((8,), ("slices",))
        a = _random_square(n, 4, seed=seed)
        x = random_dense(n, k, seed=seed)
        dist = MatrixSlice1D(a, mesh)
        got = dist.gather_result(dist.spmm(dist.set_features(x)))
        np.testing.assert_allclose(got, a @ x, rtol=1e-5, atol=1e-5)

    def test_per_slice_sources_match_global_build(self, tmp_path):
        """Built from per-slice npz files (the reference's
        .part.P.slice.r.npz scheme, spmm_petsc.py:421-440: each rank
        loads only its own slice) == the global-matrix build,
        table-for-table."""
        mesh = make_mesh((8,), ("slices",))
        n, k = 97, 5
        a = _random_square(n, 4, seed=21)
        x = random_dense(n, k, seed=21)
        ref = MatrixSlice1D(a, mesh)

        paths = []
        for d, (lo, hi) in enumerate(ref.slices):
            p = str(tmp_path / f"g.part.8.slice.{d}.npz")
            sparse.save_npz(p, a[lo:hi].tocsr())
            paths.append(p)
        dist = MatrixSlice1D(paths, mesh)

        assert dist.slices == ref.slices and dist.slot == ref.slot
        for name in ("l_cols", "l_data", "nl_cols", "nl_data", "send_idx"):
            np.testing.assert_array_equal(
                np.asarray(getattr(dist, name)),
                np.asarray(getattr(ref, name)), err_msg=name)
        got = dist.gather_result(dist.spmm(dist.set_features(x)))
        np.testing.assert_allclose(got, a @ x, rtol=1e-5, atol=1e-5)

    def test_per_slice_sources_unequal_with_empty(self):
        """Per-slice sources with ragged and zero-row slices (the
        reference's unequal-slice stress, test_spmmPETSc.py:44-71),
        slices derived from the source row counts."""
        mesh = make_mesh((8,), ("slices",))
        n, k = 33, 4
        bounds = [0, 0, 5, 5, 20, 21, 33, 33, 33]
        a = _random_square(n, 5, seed=9)
        x = random_dense(n, k, seed=9)
        sources = [a[bounds[i]:bounds[i + 1]].tocsr() for i in range(8)]
        dist = MatrixSlice1D(sources, mesh)
        got = dist.gather_result(dist.spmm(dist.set_features(x)))
        np.testing.assert_allclose(got, a @ x, rtol=1e-5, atol=1e-5)

    def test_per_slice_sources_width_mismatch_raises(self):
        mesh = make_mesh((8,), ("slices",))
        a = _random_square(64, 4, seed=3)
        srcs = [a[lo:hi, :32].tocsr()
                for lo, hi in equal_slices(64, 8)]
        with pytest.raises(ValueError):
            MatrixSlice1D(srcs, mesh)

    def test_identity(self):
        # Identity result == X (reference test_spmmPETSc.py:95-121).
        mesh = make_mesh((8,), ("slices",))
        n, k = 40, 6
        a = sparse.identity(n, format="csr", dtype=np.float32)
        x = random_dense(n, k, seed=3)
        dist = MatrixSlice1D(a, mesh)
        got = dist.gather_result(dist.spmm(dist.set_features(x)))
        np.testing.assert_allclose(got, x, rtol=1e-6, atol=1e-6)
        # Identity has no off-slice columns: no exchange slots at all.
        assert dist.slot == 0

    def test_unequal_slices_with_empty(self):
        # Unequal slice sizes incl. zero-row slices stress the exchange
        # tables (reference test_spmmPETSc.py:44-71).
        mesh = make_mesh((8,), ("slices",))
        n, k = 33, 4
        bounds = [0, 0, 5, 5, 20, 21, 33, 33, 33]
        slices = [(bounds[i], bounds[i + 1]) for i in range(8)]
        a = _random_square(n, 5, seed=7)
        x = random_dense(n, k, seed=7)
        dist = MatrixSlice1D(a, mesh, slices=slices)
        got = dist.gather_result(dist.spmm(dist.set_features(x)))
        np.testing.assert_allclose(got, a @ x, rtol=1e-5, atol=1e-5)

    def test_density_sweep(self):
        # Seeds x densities sweep (reference test_spmmPETSc.py:74-92).
        mesh = make_mesh((8,), ("slices",))
        n, k = 56, 4
        for seed in range(2):
            for nnz_per_row in (1, 3, 8):
                a = _random_square(n, nnz_per_row, seed=seed)
                x = random_dense(n, k, seed=seed)
                dist = MatrixSlice1D(a, mesh)
                got = dist.gather_result(dist.spmm(dist.set_features(x)))
                np.testing.assert_allclose(got, a @ x, rtol=1e-5, atol=1e-5)

    def test_iterated(self):
        mesh = make_mesh((8,), ("slices",))
        n, k = 64, 4
        a = _random_square(n, 3, seed=9)
        a = (a / max(abs(a).sum(axis=1).max(), 1.0)).tocsr().astype(np.float32)
        x = random_dense(n, k, seed=9)
        dist = MatrixSlice1D(a, mesh)
        xd = dist.set_features(x)
        want = x
        for _ in range(3):
            xd = dist.spmm(xd)
            want = a @ want
        np.testing.assert_allclose(dist.gather_result(xd), want,
                                   rtol=1e-4, atol=1e-5)


def test_matrix_slice_1d_auto_chunk_and_validation():
    """chunk='auto' sizes the gather bound inside the layout (budget
    net of resident blocks, shared-pool division on CPU meshes) and
    still computes exactly; bad fractions are rejected."""
    from arrow_matrix_tpu.parallel.spmm_1d import MatrixSlice1D
    from arrow_matrix_tpu.utils.graphs import random_csr

    a = random_csr(256, 256, 6, seed=5)
    mesh = make_mesh((4,), ("slices",))
    d = MatrixSlice1D(a, mesh, chunk="auto")
    x = random_dense(256, 8, seed=1)
    got = d.gather_result(d.spmm(d.set_features(x)))
    np.testing.assert_allclose(got, a @ x, rtol=1e-4, atol=1e-5)

    with pytest.raises(ValueError, match="memory_fraction"):
        MatrixSlice1D(a, mesh, chunk="auto", memory_fraction=0.0)
    with pytest.raises(ValueError, match="memory_fraction"):
        MatrixSlice1D(a, mesh, chunk="auto", memory_fraction=1.5)


def test_spmm_15d_auto_chunk():
    from arrow_matrix_tpu.parallel.spmm_15d import SpMM15D
    from arrow_matrix_tpu.utils.graphs import random_csr

    a = random_csr(256, 256, 6, seed=8)
    mesh = make_mesh((4, 2), ("rows", "repl"))
    d = SpMM15D(a, mesh, chunk="auto")
    x = random_dense(256, 8, seed=2)
    got = d.gather_result(d.spmm(d.set_features(x)))
    np.testing.assert_allclose(got, a @ x, rtol=1e-4, atol=1e-5)
    with pytest.raises(ValueError, match="memory_fraction"):
        SpMM15D(a, mesh, chunk="auto", memory_fraction=2.0)
