"""graft-sync tests: the runtime lock-order witness (off-by-default
zero overhead, raises on inverted acquisition orders, full Condition
protocol, flock vertices in the same graph), the static RC1-RC5
analyzer (selftest twins, planted-violation fixtures per rule, the
shipped package proves clean, no drift against the checked-in
bench_cache/sync_manifest.json), regression tests for the true
findings the analyzer caught in serve//obs//fleet/, and the threaded
stress test: submit + health + pulse hammered concurrently under
AMT_LOCK_WITNESS semantics with exact pooled quantiles and a green
ledger at the end."""

import json
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

from arrow_matrix_tpu import sync
from arrow_matrix_tpu.analysis import sync as gsync
from arrow_matrix_tpu.fleet.health import HealthMonitor
from arrow_matrix_tpu.fleet.router import FleetRouter, WorkerHandle
from arrow_matrix_tpu.fleet.worker import FleetWorker, serve_worker
from arrow_matrix_tpu.ledger.store import Ledger
from arrow_matrix_tpu.obs.metrics import Histogram
from arrow_matrix_tpu.obs.pulse import PulseMonitor
from arrow_matrix_tpu.serve.loadgen import synthetic_trace

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE_DIR = os.path.join(REPO, "tests", "fixtures", "sync")
MANIFEST = os.path.join(REPO, "bench_cache", "sync_manifest.json")
FIXTURES = sorted(
    os.path.join(FIXTURE_DIR, f) for f in os.listdir(FIXTURE_DIR)
    if f.startswith("rc") and f.endswith(".py"))


@pytest.fixture(autouse=True)
def _witness_restored():
    """Every test starts witness-off and leaves the global registry
    exactly as it found it (the suite must not depend on whether the
    developer exported AMT_LOCK_WITNESS)."""
    prev = sync.witness_registry()
    sync.disable_witness()
    yield
    if prev is not None:
        sync.enable_witness(prev)
    else:
        sync.disable_witness()


@pytest.fixture
def witness():
    yield sync.enable_witness()
    sync.disable_witness()


# ---------------------------------------------------------------------------
# Runtime witness
# ---------------------------------------------------------------------------

def test_witness_off_by_default_is_zero_overhead():
    # witnessed() hands back the very same lock object — not even a
    # proxy allocation — and flock regions get a shared no-op context.
    assert sync.witness_registry() is None
    lock = threading.Lock()
    assert sync.witnessed("arrow_server", lock) is lock
    cm = sync.flock_witness("sidecar")
    assert cm is sync.flock_witness("preempt_registry")  # shared null
    with cm:
        pass


def test_witness_raises_on_declared_order_inversion(witness):
    la = sync.witnessed("a", threading.Lock())
    lb = sync.witnessed("b", threading.Lock())
    witness.declare("a", "b")
    with la:
        with lb:
            pass
    with lb:
        with pytest.raises(sync.LockOrderViolation, match="a"):
            la.acquire()
    snap = witness.snapshot()
    assert snap["violations"] and snap["acquisitions"] >= 3
    # The a->b traversal matched the declaration, so it is not
    # re-recorded as a new observed edge.
    assert ["a", "b"] in [list(e) for e in snap["declared_edges"]]
    assert snap["observed_edges"] == []


def test_witness_raises_on_observed_order_inversion(witness):
    # No declaration at all: the first observed order becomes law.
    lx = sync.witnessed("x", threading.Lock())
    ly = sync.witnessed("y", threading.Lock())
    with lx:
        with ly:
            pass
    with ly:
        with pytest.raises(sync.LockOrderViolation, match="observed"):
            lx.acquire()


def test_witness_reentrancy_adds_no_edge(witness):
    lr = sync.witnessed("r", threading.RLock())
    with lr:
        with lr:
            pass
    snap = witness.snapshot()
    assert snap["reentries"] == 1
    assert snap["observed_edges"] == []


def test_witness_contradictory_declaration_is_rejected():
    with pytest.raises(ValueError, match="contradicts"):
        sync.LockRegistry(declared=(("a", "b"), ("b", "a")))
    with pytest.raises(ValueError, match="self-edge"):
        sync.LockRegistry(declared=(("a", "a"),))


def test_witness_condition_protocol_round_trips(witness):
    # Condition(witnessed RLock) exercises _release_save /
    # _acquire_restore / _is_owned — a wait() must fully release the
    # witnessed stack so the notifier can acquire in order.
    lock = sync.witnessed("cond", threading.RLock())
    cond = threading.Condition(lock)
    box = {"ready": False}
    done = threading.Event()

    def waiter():
        with cond:
            while not box["ready"]:
                cond.wait(timeout=30)
        done.set()

    t = threading.Thread(target=waiter, daemon=True)
    t.start()
    with cond:
        box["ready"] = True
        cond.notify_all()
    assert done.wait(30)
    t.join(30)
    snap = witness.snapshot()
    assert snap["violations"] == []
    assert snap["acquisitions"] >= 2
    assert len(snap["threads"]) == 2


def test_flock_witness_is_a_graph_vertex(witness):
    inner = sync.witnessed("inner", threading.Lock())
    with sync.flock_witness("sidecar"):
        with inner:
            pass
    with inner:
        with pytest.raises(sync.LockOrderViolation):
            with sync.flock_witness("sidecar"):
                pass
    assert "flock:sidecar" in {a for a, _ in
                               witness.snapshot()["observed_edges"]}


def test_declared_order_matches_package_constants():
    reg = sync.LockRegistry()   # must not raise: acyclic by design
    snap = reg.snapshot()
    assert sorted(tuple(e) for e in snap["declared_edges"]) == sorted(
        sync.DECLARED_ORDER)
    assert set(sync.FLOCK_NODES) == {"flock:sidecar",
                                     "flock:preempt_registry"}


# ---------------------------------------------------------------------------
# Static analyzer: twins, fixtures, the shipped package, the manifest
# ---------------------------------------------------------------------------

def test_analyzer_selftest_is_green():
    ok, lines = gsync.selftest()
    assert ok, "\n".join(lines)


def test_fixture_set_is_complete():
    rules = sorted(gsync.fixture_contract(p) for p in FIXTURES)
    assert rules == ["RC1", "RC2", "RC3", "RC4", "RC5"]


@pytest.mark.parametrize("path", FIXTURES,
                         ids=[os.path.basename(p) for p in FIXTURES])
def test_each_planted_fixture_fires_its_rule(path):
    ok, detail = gsync.verify_fixture(path)
    assert ok, detail
    # ...and the gate's --paths mode would reject it: any finding is
    # a nonzero exit, which is how a planted violation fails CI.
    report = gsync.analyze_paths([path])
    assert report.findings and not report.ok


def test_sync_gate_cli_rejects_planted_fixtures():
    """The actual tools/sync_gate.py process exits nonzero when fed
    the planted violations, naming every rule."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "sync_gate.py"),
         "--paths", *FIXTURES],
        capture_output=True, text=True, timeout=300, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode != 0, proc.stdout + proc.stderr
    for rule in ("RC1", "RC2", "RC3", "RC4", "RC5"):
        assert rule in proc.stdout, (rule, proc.stdout)


def test_shipped_package_proves_clean():
    report = gsync.analyze_package()
    assert report.findings == [], "\n".join(
        f.format() for f in report.findings)
    assert report.ok
    nodes = {c.node for c in report.contracts}
    assert {"arrow_server", "fleet_router", "health_monitor",
            "pulse_monitor", "slo_watchdog", "flight_recorder",
            "metrics_registry", "hbm_accountant"} <= nodes


def test_manifest_checked_in_ok_and_no_drift():
    with open(MANIFEST, encoding="utf-8") as fh:
        checked_in = json.load(fh)
    assert checked_in["ok"], "checked-in sync manifest records findings"
    fresh = gsync.run_sync(write=False)
    drift = gsync.manifest_drift(checked_in, fresh)
    assert drift == [], "\n".join(drift)


# ---------------------------------------------------------------------------
# Regressions for the true findings graft-sync caught
# ---------------------------------------------------------------------------

def test_health_racing_failures_each_count():
    """The HealthMonitor lost-update fix: N racing record_failure
    calls must produce a streak of exactly N (two racing threads used
    to each observe N-1 and neither bury the worker)."""
    hm = HealthMonitor(timeout_s=1.0, max_failures=10**6)
    threads = [threading.Thread(
        target=lambda: [hm.record_failure("w", "boom")
                        for _ in range(250)])
        for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    assert hm.snapshot()["w"]["consecutive_failures"] == 8 * 250


def test_pulse_hbm_sampler_runs_before_the_monitor_lock(witness):
    """The RC3 fix in PulseMonitor.observe: the sampler (a user
    callback that takes other locks — here the declared-higher
    arrow_server lock) must run BEFORE the pulse lock is taken.  If it
    ran under the lock, acquiring arrow_server inside pulse_monitor
    would close the declared arrow_server -> pulse_monitor cycle and
    the witness would raise."""
    server_lock = sync.witnessed("arrow_server", threading.Lock())

    def sampler():
        with server_lock:
            return (1 << 20, 0.5)

    m = PulseMonitor(window_s=10.0, hbm_sampler=sampler)
    for _ in range(4):
        m.observe("completed", latency_ms=1.0)
    snap = witness.snapshot()
    assert snap["violations"] == []
    assert m.totals_dict()["completed"] == 4


def test_pulse_concurrent_observe_never_drops_events():
    """The RC1 fix (burn_events/totals folded under the lock): T
    threads hammering observe() concurrently lose nothing."""
    m = PulseMonitor(window_s=0.01)
    per_thread = 300

    def hammer(tid):
        for i in range(per_thread):
            m.observe("completed", tenant=f"t{tid}",
                      latency_ms=float(i % 7))
            if i % 50 == 0:
                m.advance()

    threads = [threading.Thread(target=hammer, args=(tid,))
               for tid in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(120)
    # merged_latency pools closed windows + the in-progress one, so
    # sample it before close() seals the final window into the ring.
    assert len(m.merged_latency().values) == 6 * per_thread
    m.close()
    assert m.totals_dict()["completed"] == 6 * per_thread


# ---------------------------------------------------------------------------
# The threaded stress test (satellite): fleet + health + pulse under
# the witness, exact quantiles and a green ledger at the end.
# ---------------------------------------------------------------------------

def _start_worker(worker_id, checkpoint_dir):
    worker = FleetWorker(worker_id, vertices=64, width=16, seed=5,
                         checkpoint_dir=checkpoint_dir,
                         checkpoint_every=1)
    ready = threading.Event()
    box = {}

    def announce(port):
        box["port"] = port
        ready.set()

    th = threading.Thread(target=serve_worker, args=(worker,),
                          kwargs={"port": 0, "announce": announce},
                          daemon=True)
    th.start()
    assert ready.wait(120), f"{worker_id} never bound"
    return worker, WorkerHandle(worker_id, "127.0.0.1", box["port"])


def test_threaded_stress_under_witness(tmp_path):
    """N threads hammer FleetRouter.submit, the HealthMonitor's
    ok/failure transitions, and PulseMonitor.observe simultaneously
    with the lock-order witness armed.  Every request completes, the
    fleet quantiles are still EXACTLY the pooled nearest-rank over the
    workers' raw samples, the pulse ledger validates clean, and the
    witness saw a multi-threaded run with zero order violations."""
    registry = sync.enable_witness()
    ledger_dir = str(tmp_path / "ledger")
    ckpt = str(tmp_path / "ckpt")
    w0, h0 = _start_worker("w0", ckpt)
    w1, h1 = _start_worker("w1", ckpt)
    router = FleetRouter(
        handles=[h0, h1],
        health=HealthMonitor(timeout_s=5.0, max_failures=3))
    pm = PulseMonitor(window_s=0.02, ledger_dir=ledger_dir)
    tickets = []
    tickets_lock = threading.Lock()
    try:
        trace = synthetic_trace(router.n_rows, tenants=4, requests=12,
                                k=2, iterations=1, seed=7)
        chunks = [trace[i::3] for i in range(3)]

        def submitter(chunk):
            for req in chunk:
                t = router.submit(req)
                with tickets_lock:
                    tickets.append(t)

        def health_flapper():
            # Sub-lethal failure streaks interleaved with oks and
            # snapshots: the burial read-modify-write races against
            # every dispatch thread's record_ok.
            for _ in range(150):
                router.health.record_failure("w0", "flap")
                router.health.record_ok("w0")
                router.health.snapshot()
                router.live_workers()

        def pulser(tid):
            for i in range(200):
                pm.observe("completed", tenant=f"t{tid % 4}",
                           latency_ms=float(i % 11))
                if i % 40 == 0:
                    pm.advance()

        threads = ([threading.Thread(target=submitter, args=(c,))
                    for c in chunks]
                   + [threading.Thread(target=health_flapper)]
                   + [threading.Thread(target=pulser, args=(tid,))
                      for tid in range(3)])
        for t in threads:
            t.start()
        for t in threads:
            t.join(180)
        router.drain(timeout_s=180)

        assert [t.status for t in tickets] == ["completed"] * 12

        report = router.fleet_summary()
        assert report["completed"] == 12
        assert report["failed"] == 0 and report["shed"] == 0
        pooled = Histogram()
        for rec in report["workers"].values():
            for v in rec.get("latency_samples_ms") or ():
                pooled.observe(v)
        lat = report["latency_ms"]
        assert lat["count"] == len(pooled.values) == 12
        for q, field in ((0.5, "p50"), (0.9, "p90"), (0.99, "p99")):
            assert lat[field] == pooled.quantile(q)

        pm.close()
        assert pm.totals_dict()["completed"] == 3 * 200
        assert Ledger(ledger_dir).validate() == []

        snap = registry.snapshot()
        assert snap["violations"] == [], "\n".join(snap["violations"])
        assert snap["acquisitions"] > 0
        assert len(snap["threads"]) >= 4
    finally:
        sync.disable_witness()
        router.shutdown()
        for w in (w0, w1):
            try:
                w.close()
            except Exception:
                pass
