"""Many-"rank" correctness matrix: every distributed layout swept over
mesh sizes 2..16 devices, including non-power-of-two sizes.

The TPU analog of the reference's oversubscribed many-rank test fixture
(reference scripts/run_tests.sh runs mpiexec at 4, 6 and 30 ranks;
tests/test_arrowmpi.py:11-17 documents the rank-count matrix).  The
conftest provides 16 virtual CPU devices; ``make_mesh`` carves
sub-meshes of any size out of them.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from scipy import sparse

from arrow_matrix_tpu.decomposition import arrow_decomposition
from arrow_matrix_tpu.decomposition.decompose import decomposition_spmm
from arrow_matrix_tpu.ops import (
    arrow_blocks_from_csr,
    block_features,
    unblock_features,
)
from arrow_matrix_tpu.parallel import (
    MatrixSlice1D,
    MultiLevelArrow,
    SpMM15D,
    make_mesh,
    make_slim_spmm,
    shard_blocked,
)
from arrow_matrix_tpu.parallel.mesh import shard_arrow_blocks
from arrow_matrix_tpu.utils import barabasi_albert, random_dense
from arrow_matrix_tpu.utils.graphs import random_csr
from helpers import arrow_csr as _arrow_csr_shared


def _arrow_csr(n_blocks, width, seed, banded=False, density=0.25):
    return _arrow_csr_shared(n_blocks, width, banded=banded, seed=seed,
                             density=density)

# 2/4/8/16 mirror power-of-two pods; 3/5/6 are the non-power-of-two
# sizes the reference's odd-rank wide tests exercise.
SIZES = [2, 3, 4, 5, 6, 8, 16]


def test_pool_is_large_enough():
    assert jax.device_count() >= 16, "conftest must provide 16 devices"


@pytest.mark.parametrize("n_dev", SIZES)
def test_slim_spmm_all_sizes(n_dev):
    width = 8
    n_blocks = n_dev  # one block row per device, like the slim layout
    a = _arrow_csr(n_blocks, width, seed=n_dev)
    blocks = arrow_blocks_from_csr(a, width)
    mesh = make_mesh((n_dev,), ("blocks",))

    x_host = random_dense(n_blocks * width, 4, seed=1)
    xb = shard_blocked(jnp.asarray(block_features(x_host, width, n_blocks)),
                       mesh)
    step = make_slim_spmm(blocks, mesh)
    out = step(shard_arrow_blocks(blocks, mesh), xb)
    got = unblock_features(out, n_blocks * width)
    np.testing.assert_allclose(got, a @ x_host, rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("n_dev", SIZES)
def test_multi_level_all_sizes(n_dev):
    # Block count not divisible by the device count: exercises padding.
    n, width = 330, 32
    a = barabasi_albert(n, 4, seed=n_dev)
    levels = arrow_decomposition(a, width, max_levels=3, block_diagonal=True,
                                 seed=1)
    mesh = make_mesh((n_dev,), ("blocks",))
    ml = MultiLevelArrow(levels, width, mesh=mesh)
    x_host = random_dense(n, 4, seed=2)
    out = ml.gather_result(ml.step(ml.set_features(x_host)))
    np.testing.assert_allclose(out, decomposition_spmm(levels, x_host),
                               rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(out, a @ x_host, rtol=1e-3, atol=1e-3)


@pytest.mark.parametrize("rows,repl", [(2, 1), (3, 1), (2, 2), (6, 2),
                                       (4, 2), (4, 4)])
def test_spmm_15d_all_grids(rows, repl):
    n, k = 60, 4
    mesh = make_mesh((rows, repl), ("rows", "repl"))
    a = random_csr(n, n, 4, seed=rows * 10 + repl).astype(np.float32)
    x = random_dense(n, k, seed=3)
    dist = SpMM15D(a, mesh)
    got = dist.gather_result(dist.spmm(dist.set_features(x)))
    np.testing.assert_allclose(got, a @ x, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("n_dev", SIZES)
def test_matrix_slice_1d_all_sizes(n_dev):
    n, k = 47, 4  # prime row count: ragged slices on every mesh size
    mesh = make_mesh((n_dev,), ("slices",))
    a = random_csr(n, n, 4, seed=n_dev).astype(np.float32)
    x = random_dense(n, k, seed=4)
    dist = MatrixSlice1D(a, mesh)
    got = dist.gather_result(dist.spmm(dist.set_features(x)))
    np.testing.assert_allclose(got, a @ x, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("n_dev", [2, 3, 8])
def test_routing_all_sizes(n_dev):
    from jax.sharding import NamedSharding, PartitionSpec as P

    from arrow_matrix_tpu.parallel.routing import build_route, routed_take

    rows_per_dev = 6
    total = n_dev * rows_per_dev
    rng = np.random.default_rng(n_dev)
    table = rng.permutation(total)
    mesh = make_mesh((n_dev,), ("blocks",))
    route = build_route(table, n_dev)
    x_host = random_dense(total, 3, seed=5)
    x = jax.device_put(x_host, NamedSharding(mesh, P("blocks")))
    got = routed_take(x, route, mesh)
    np.testing.assert_allclose(np.asarray(got), x_host[table], rtol=0, atol=0)


def test_features_128_mesh_and_fold():
    """BASELINE configs 3/5 run 128 features; drive k=128 through the
    sharded multi-level step and the folded single-chip executor."""
    import numpy as np

    from arrow_matrix_tpu.decomposition import arrow_decomposition
    from arrow_matrix_tpu.decomposition.decompose import decomposition_spmm
    from arrow_matrix_tpu.parallel import MultiLevelArrow, make_mesh
    from arrow_matrix_tpu.utils import barabasi_albert, random_dense
    from arrow_matrix_tpu.utils import numerics

    n, width, k = 1024, 64, 128
    a = barabasi_albert(n, 4, seed=17)
    levels = arrow_decomposition(a, width, max_levels=4,
                                 block_diagonal=True, seed=3)
    x = random_dense(n, k, seed=4)
    want = decomposition_spmm(levels, x)
    tol = numerics.relative_tolerance(
        sum(l.matrix.nnz for l in levels) / n, iters=1)
    for ml in (MultiLevelArrow(levels, width,
                               mesh=make_mesh((8,), ("blocks",)),
                               fmt="ell"),
               MultiLevelArrow(levels, width, mesh=None, fmt="fold")):
        got = ml.gather_result(ml.step(ml.set_features(x)))
        assert numerics.relative_error(got, want) < tol
