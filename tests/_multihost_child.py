"""Child process of the multi-process distributed test (the reference's
``mpiexec -n`` analog with REAL process boundaries, reference
scripts/run_tests.sh): joins a 2-process gloo-backed JAX runtime, builds
the feature-major multi-level executor over the GLOBAL mesh (devices
spanning both processes), iterates, and checks against the host golden.

Run by tests/test_multihost.py; usable standalone:

    python tests/_multihost_child.py <pid> <nproc> <port> &
    python tests/_multihost_child.py <pid+1> <nproc> <port>
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    from arrow_matrix_tpu.parallel.mesh import initialize_multihost

    try:
        initialize_multihost(f"127.0.0.1:{port}", nproc, pid,
                             cpu_devices=2)
    except Exception as e:  # no gloo in this jaxlib, firewalled, ...
        print(f"CHILD_SKIP {type(e).__name__}: {e}", flush=True)
        return

    import jax
    import numpy as np

    assert jax.process_count() == nproc
    n_global = len(jax.devices())
    assert n_global == 2 * nproc, n_global
    assert len(jax.local_devices()) == 2

    from arrow_matrix_tpu.decomposition.decompose import (
        arrow_decomposition,
        decomposition_spmm,
    )
    from arrow_matrix_tpu.parallel.mesh import fetch_replicated, make_mesh
    from arrow_matrix_tpu.parallel.sell_slim import SellMultiLevel
    from arrow_matrix_tpu.utils.graphs import barabasi_albert
    from arrow_matrix_tpu.utils.numerics import relative_error

    # Every process derives the same inputs from the seed (the reference
    # likewise regenerates rank-deterministic test data per rank).
    n, width, k, iters = 256, 32, 8, 2
    a = barabasi_albert(n, 4, seed=5)
    levels = arrow_decomposition(a, arrow_width=width, max_levels=3,
                                 block_diagonal=True, seed=5)
    x = np.random.default_rng(3).uniform(-1, 1, (n, k)).astype(np.float32)

    want = x
    for _ in range(iters):
        want = decomposition_spmm(levels, want)

    mesh = make_mesh((n_global,), ("blocks",))
    errs = {}

    ml = SellMultiLevel(levels, width, mesh, routing="a2a")
    xt = ml.set_features(x)
    assert not xt.is_fully_addressable   # the point of this test
    errs["sell_a2a"] = relative_error(ml.gather_result(ml.run(xt, iters)),
                                      want)

    if nproc >= 4 and ml.fwd:
        # The >2-peer coverage this fixture exists for (reference
        # 4/6-rank PETSc tests, scripts/run_tests.sh): with many peers
        # the a2a per-pair row counts are UNEQUAL (pair-count skew —
        # padding slots route from the dummy row), so the padded
        # fixed-shape all_to_all exercises its masking across real
        # process boundaries.  Assert the skew is present, not
        # incidental.
        import numpy as _np

        rt = ml.fwd[0]
        send = fetch_replicated(rt.send_idx)   # sharded across processes
        real = (send != rt.rows_src).sum(axis=2)
        off_diag = real[~_np.eye(rt.n_dev, dtype=bool)]
        assert off_diag.size and off_diag.max() > off_diag.min(), (
            f"a2a pair counts unexpectedly uniform: {real.tolist()}")

    from arrow_matrix_tpu.parallel.multi_level import MultiLevelArrow

    ml2 = MultiLevelArrow(levels, width, mesh=mesh, fmt="ell",
                          routing="a2a")
    x2 = ml2.set_features(x)
    for _ in range(iters):
        x2 = ml2.step(x2)
    errs["stacked_ell_a2a"] = relative_error(ml2.gather_result(x2), want)

    # Space-shared sell: levels concurrent on disjoint groups of a
    # (lvl, blocks) mesh spanning both processes (per-host build).  A
    # SEPARATE 2-level decomposition fits the (2, n/2) grid without
    # weakening the 3-level coverage of the time-shared checks above.
    if n_global % 2 == 0:
        from arrow_matrix_tpu.parallel.sell_space import SellSpaceShared

        levels2 = arrow_decomposition(a, arrow_width=width,
                                      max_levels=2,
                                      block_diagonal=True, seed=5)
        assert len(levels2) == 2, len(levels2)
        want2 = x
        for _ in range(iters):
            want2 = decomposition_spmm(levels2, want2)
        sp = SellSpaceShared(
            levels2, width,
            make_mesh((2, n_global // 2), ("lvl", "blocks")))
        xs = sp.set_features(x)
        errs["sell_space"] = relative_error(
            sp.gather_result(sp.run(xs, iters)), want2)

    # The two baseline layouts over the same multi-process mesh
    # (single-matrix semantics: one SpMM vs a @ x).
    from arrow_matrix_tpu.parallel.spmm_15d import SpMM15D
    from arrow_matrix_tpu.parallel.spmm_1d import MatrixSlice1D

    af = a.astype(np.float32)
    want1 = np.asarray(af @ x)
    d1 = MatrixSlice1D(af, mesh, axis="blocks")
    errs["petsc_1d"] = relative_error(
        d1.gather_result(d1.spmm(d1.set_features(x))), want1)

    # Per-slice sources across the process boundary: each process
    # loads ONLY the slices of devices it owns (the reference's
    # per-rank slice files, spmm_petsc.py:421-440); the cross-slice
    # metadata exchange (_exchange_sum / _exchange_ragged — the
    # Alltoall/Alltoallv of counts/indices) runs its REAL
    # process_allgather branch here, identity elsewhere in the suite.
    from arrow_matrix_tpu.parallel.spmm_1d import (
        _owned_slice_ids,
        equal_slices,
    )

    slc = equal_slices(n, n_global)
    mine = _owned_slice_ids(mesh, "blocks")
    loaded_ids = []

    def src(d, lo, hi):
        def load():
            loaded_ids.append(d)
            return af[lo:hi].tocsr()
        return load

    d1s = MatrixSlice1D([src(d, lo, hi) for d, (lo, hi) in enumerate(slc)],
                        mesh, axis="blocks")
    assert set(loaded_ids) == mine, (sorted(loaded_ids), sorted(mine))
    errs["petsc_1d_per_slice"] = relative_error(
        d1s.gather_result(d1s.spmm(d1s.set_features(x))), want1)

    if n_global % 2 == 0:   # replication needs an even device grid
        m15 = make_mesh((n_global // 2, 2), ("rows", "repl"))
        d15 = SpMM15D(af, m15)
        errs["15d"] = relative_error(
            d15.gather_result(d15.spmm(d15.set_features(x))), want1)
        # Triplet build: build_global_parts constructs only THIS
        # process's shards from the (memmap-shaped) CSR triplet.
        trip = (af.data, af.indices, af.indptr)
        d15t = SpMM15D(trip, m15)
        errs["15d_triplet"] = relative_error(
            d15t.gather_result(d15t.spmm(d15t.set_features(x))), want1)

    # Distributed training THROUGH the process boundary: GCN gradients
    # cross the same multi-process collectives (psum / ppermute /
    # routed all_to_all) the forward uses — the backprop property the
    # single-process suite verifies, now with real process boundaries.
    from arrow_matrix_tpu.models.propagation import GCNCarried

    rngm = np.random.default_rng(9)
    ym = rngm.standard_normal((n, 4)).astype(np.float32)
    gcn = GCNCarried(ml, dims=(k, 6, 4), seed=0)
    losses = gcn.fit(x, ym, steps=25)
    assert np.isfinite(losses).all(), losses[:3]
    assert losses[-1] < 0.9 * losses[0], (losses[0], losses[-1])
    errs["gcn_fit"] = 0.0   # convergence asserted above

    # Checkpoint roundtrip across the process boundary: the save is a
    # collective fetch + single-writer npz; restore re-places onto the
    # (multi-process) sharding of the running executor.
    import tempfile

    from arrow_matrix_tpu.utils import checkpoint as ckpt

    state = ml.run(xt, 1)

    # Orbax path first (it coordinates multi-process saves natively,
    # writing each process's shards without a host gather).
    if ckpt._orbax() is not None:
        opath = os.path.join(tempfile.gettempdir(),
                             f"mh_ckpt_orbax_{port}")
        try:
            ckpt.save_state(opath, state, step=2)
            r2, s2 = ckpt.load_state(opath, like=state)
            assert s2 == 2 and r2.sharding == state.sharding
            errs["ckpt_orbax"] = relative_error(
                ml.gather_result(r2), ml.gather_result(state))
        finally:
            if pid == 0:
                import shutil

                shutil.rmtree(opath, ignore_errors=True)

    path = os.path.join(tempfile.gettempdir(), f"mh_ckpt_{port}")
    ckpt._orbax = lambda: None   # force the npz single-writer path
    ckpt.save_state(path, state, step=1)   # barrier lives in save_state
    try:
        restored, step = ckpt.load_state(path, like=state)
        assert step == 1
        # The restore must land on the RUNNING executor's
        # multi-process sharding, not a replicated/host fallback.
        assert restored.sharding == state.sharding
        assert not restored.is_fully_addressable
        errs["ckpt"] = relative_error(ml.gather_result(restored),
                                      ml.gather_result(state))
    finally:
        if pid == 0:   # shared tempdir must not accumulate
            try:
                os.remove(path + ".npz")
            except OSError:
                pass

    assert not any(np.isnan(v) for v in errs.values()), errs
    worst = max(errs.values())
    print(f"CHILD_OK pid={pid} devices={n_global} err={worst:.2e} "
          + " ".join(f"{k}={v:.1e}" for k, v in errs.items()),
          flush=True)


if __name__ == "__main__":
    main()
