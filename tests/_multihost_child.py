"""Child process of the multi-process distributed test (the reference's
``mpiexec -n`` analog with REAL process boundaries, reference
scripts/run_tests.sh): joins a 2-process gloo-backed JAX runtime, builds
the feature-major multi-level executor over the GLOBAL mesh (devices
spanning both processes), iterates, and checks against the host golden.

Run by tests/test_multihost.py; usable standalone:

    python tests/_multihost_child.py <pid> <nproc> <port> &
    python tests/_multihost_child.py <pid+1> <nproc> <port>
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    from arrow_matrix_tpu.parallel.mesh import initialize_multihost

    try:
        initialize_multihost(f"127.0.0.1:{port}", nproc, pid,
                             cpu_devices=2)
    except Exception as e:  # no gloo in this jaxlib, firewalled, ...
        print(f"CHILD_SKIP {type(e).__name__}: {e}", flush=True)
        return

    import jax
    import numpy as np

    assert jax.process_count() == nproc
    n_global = len(jax.devices())
    assert n_global == 2 * nproc, n_global
    assert len(jax.local_devices()) == 2

    from arrow_matrix_tpu.decomposition.decompose import (
        arrow_decomposition,
        decomposition_spmm,
    )
    from arrow_matrix_tpu.parallel.mesh import fetch_replicated, make_mesh
    from arrow_matrix_tpu.parallel.sell_slim import SellMultiLevel
    from arrow_matrix_tpu.utils.graphs import barabasi_albert
    from arrow_matrix_tpu.utils.numerics import relative_error

    # Every process derives the same inputs from the seed (the reference
    # likewise regenerates rank-deterministic test data per rank).
    n, width, k, iters = 256, 32, 8, 2
    a = barabasi_albert(n, 4, seed=5)
    levels = arrow_decomposition(a, arrow_width=width, max_levels=3,
                                 block_diagonal=True, seed=5)
    x = np.random.default_rng(3).uniform(-1, 1, (n, k)).astype(np.float32)

    mesh = make_mesh((n_global,), ("blocks",))
    ml = SellMultiLevel(levels, width, mesh, routing="a2a")
    xt = ml.set_features(x)
    assert not xt.is_fully_addressable   # the point of this test
    out = ml.gather_result(ml.run(xt, iters))

    want = x
    for _ in range(iters):
        want = decomposition_spmm(levels, want)
    err = relative_error(out, want)
    print(f"CHILD_OK pid={pid} devices={n_global} err={err:.2e}",
          flush=True)


if __name__ == "__main__":
    main()
