"""graft-flight (obs.memview / obs.imbalance / obs.flight) — executable
memory accounting vs the formats' static predictors on the checked-in
``ba_256_3`` decomposition fixtures, shard imbalance summaries for
skewed vs uniform layouts, and the flight recorder's crash-artifact
contract (the black box a SIGKILLed bench candidate leaves behind)."""

import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from arrow_matrix_tpu import obs
from arrow_matrix_tpu.obs import flight
from arrow_matrix_tpu.obs.__main__ import main as trace_main
from arrow_matrix_tpu.obs.imbalance import summarize_units

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# memory_report / account_memory
# ---------------------------------------------------------------------------


def _toy_jit():
    import jax
    import jax.numpy as jnp

    return jax.jit(lambda v: v @ v.T), jnp.ones((32, 16), jnp.float32)


def test_memory_report_components_and_total():
    f, x = _toy_jit()
    rep = obs.memory_report(f, x)
    assert rep["source"] in ("memory_analysis", "avals")
    # 32x16 f32 argument and 32x32 f32 output are known exactly.
    assert rep["argument_bytes"] == 32 * 16 * 4
    assert rep["output_bytes"] == 32 * 32 * 4
    known = [v for v in (rep["argument_bytes"], rep["output_bytes"],
                         rep["temp_bytes"], rep["generated_code_bytes"])
             if v is not None]
    assert rep["total_bytes"] <= sum(known)
    assert rep["total_bytes"] >= rep["output_bytes"]


def test_account_memory_gauges_and_ratio():
    f, x = _toy_jit()
    reg = obs.MetricsRegistry()
    rep = obs.account_memory("toy", f, x, predicted_bytes=1024,
                             registry=reg)
    assert rep["measured_bytes"] > 0
    assert rep["ratio"] == rep["measured_bytes"] / 1024
    assert reg.gauge("hbm_measured_bytes",
                     algorithm="toy").value == rep["measured_bytes"]
    assert reg.gauge("hbm_vs_predicted_ratio",
                     algorithm="toy").value == pytest.approx(rep["ratio"])
    # Human rendering carries the ratio line.
    text = obs.format_memory_report(rep)
    assert "measured vs format-model prediction" in text


def test_account_memory_without_predictor_has_no_ratio():
    f, x = _toy_jit()
    rep = obs.account_memory("toy", f, x)
    assert rep["predicted_bytes"] is None and rep["ratio"] is None
    assert obs.predicted_bytes_for(object(), 4) is None


def test_tree_device_bytes_counts_array_leaves_only():
    tree = {"a": np.zeros((8, 4), np.float32),
            "b": (np.zeros(3, np.int32), None, "label", 7)}
    assert obs.tree_device_bytes(tree) == 8 * 4 * 4 + 3 * 4


# ---------------------------------------------------------------------------
# Static predictor + imbalance on the checked-in decomposition fixture
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def fixture_multi(ba_256_3_base):
    import jax

    from arrow_matrix_tpu.io import load_decomposition
    from arrow_matrix_tpu.io.graphio import as_levels
    from arrow_matrix_tpu.parallel.mesh import make_mesh
    from arrow_matrix_tpu.parallel.multi_level import MultiLevelArrow

    levels = as_levels(
        load_decomposition(ba_256_3_base, 32, block_diagonal=True), 32)
    mesh = make_mesh((4,), ("blocks",), devices=jax.devices()[:4])
    return MultiLevelArrow(levels, 32, mesh=mesh), levels


def test_predictor_vs_measured_on_ba_fixture(fixture_multi):
    multi, _ = fixture_multi
    k = 4
    x = multi.set_features(np.random.default_rng(0).standard_normal(
        (multi.total_rows, k)).astype(np.float32))
    pred = obs.predicted_bytes_for(multi, k)
    assert pred and pred > 0
    mem = obs.account_memory("fixture", multi.step_fn, x,
                             *multi.step_operands(),
                             predicted_bytes=pred)
    assert mem["measured_bytes"] > 0
    # The model predicts the per-shard resident bytes from format
    # metadata alone; the compiled executable may add workspace but
    # must stay the same order of magnitude — a blowout here is the
    # OOM-in-waiting the ratio metric exists to catch.
    assert 0.25 <= mem["ratio"] <= 10.0


def test_shard_report_nnz_conserved_on_ba_fixture(fixture_multi):
    multi, levels = fixture_multi
    reg = obs.MetricsRegistry()
    rep = obs.account_imbalance("fixture", multi, registry=reg)
    assert rep is not None and rep["n_units"] > 1
    # Every stored nonzero is attributed to exactly one unit.
    assert rep["nnz_total"] == sum(l.matrix.nnz for l in levels)
    assert rep["slots_total"] >= rep["nnz_total"]
    assert 0.0 <= rep["padded_slot_waste"] <= 1.0
    assert rep["nnz_max_over_mean"] >= 1.0
    assert reg.gauge("shard_nnz_total",
                     algorithm="fixture").value == rep["nnz_total"]


def test_account_imbalance_none_without_shard_report():
    assert obs.shard_report_for(object()) is None
    assert obs.account_imbalance("x", object()) is None


def test_summarize_units_skewed_vs_uniform():
    uniform = summarize_units(rows=[64] * 4, nnz=[100] * 4,
                              slots=[128] * 4, units="device")
    assert uniform["nnz_max_over_mean"] == pytest.approx(1.0)
    assert uniform["rows_max_over_mean"] == pytest.approx(1.0)
    assert uniform["padded_slot_waste"] == pytest.approx(1 - 400 / 512)

    skewed = summarize_units(rows=[64] * 4, nnz=[10, 10, 10, 370],
                             slots=[128] * 4, units="device")
    assert skewed["nnz_total"] == uniform["nnz_total"]
    assert skewed["nnz_max_over_mean"] == pytest.approx(370 / 100)
    # Same totals -> same waste: skew and padding are separate axes.
    assert (skewed["padded_slot_waste"]
            == uniform["padded_slot_waste"])
    text = obs.format_imbalance_report(skewed)
    assert "paper imbalance bound" in text

    empty = summarize_units(rows=[], nnz=[], slots=[])
    assert empty["nnz_max_over_mean"] is None
    assert empty["padded_slot_waste"] is None


# ---------------------------------------------------------------------------
# Flight recorder
# ---------------------------------------------------------------------------


def test_flight_ring_bounds_and_roundtrip(tmp_path):
    path = str(tmp_path / "ring.json")
    rec = flight.FlightRecorder(path, capacity=4)
    rec.note_memory_report({"algorithm": "toy", "measured_bytes": 7})
    for i in range(10):
        rec.record("test", f"event{i}", i=i)
    rec.seal("done")
    snap = flight.load(path)
    assert len(snap["events"]) == 4            # bounded ring
    # 11 events total (memreport + 10): 4 kept, 7 dropped.
    assert snap["dropped"] == 7
    assert [e["name"] for e in snap["events"]] == [
        f"event{i}" for i in range(6, 10)]
    assert snap["sealed"] == "done"
    assert snap["last_memory_report"]["measured_bytes"] == 7
    # Seal is first-wins: a later reason must not overwrite the cause.
    rec.seal("exit")
    assert flight.load(path)["sealed"] == "done"
    lines = flight.format_events(snap)
    assert any("event9" in ln for ln in lines)


def test_flight_module_record_is_noop_without_recorder():
    flight.set_recorder(None)
    flight.record("test", "nobody-listening")   # must not raise
    assert flight.get_recorder() is None


def test_metrics_and_spans_mirror_into_flight(tmp_path):
    rec = flight.FlightRecorder(str(tmp_path / "m.json"))
    flight.set_recorder(rec)
    try:
        reg = obs.MetricsRegistry()
        reg.gauge("hbm_measured_bytes", algorithm="a").set(123)
        tr = obs.Tracer("run", registry=reg)
        with tr.span("phase"):
            pass
        kinds = [(e["kind"], e["name"]) for e in rec.snapshot()["events"]]
        assert ("gauge", "hbm_measured_bytes") in kinds
        assert ("span", "phase") in kinds
        # Spans are mirrored ONCE (by the tracer), not a second time
        # through their span_ms histogram observation.
        assert not any(name == "span_ms" for _, name in kinds)
    finally:
        flight.set_recorder(None)


def test_flight_seals_on_unhandled_exception(tmp_path):
    """install() chains sys.excepthook: a crashing process leaves a
    sealed artifact naming the exception."""
    path = str(tmp_path / "crash.json")
    code = textwrap.dedent(f"""
        from arrow_matrix_tpu.obs import flight
        flight.install({path!r})
        flight.record("test", "about-to-crash")
        raise RuntimeError("boom")
    """)
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, timeout=120,
                          cwd=REPO)
    assert proc.returncode != 0
    snap = flight.load(path)
    assert snap["sealed"].startswith("exception: RuntimeError: boom")
    assert [e["name"] for e in snap["events"]] == ["about-to-crash"]


def test_flight_artifact_survives_hard_kill(tmp_path):
    """The eager per-event flush is the whole point: a process dying
    with no exit handlers (os._exit stands in for the bench driver's
    SIGKILL-on-timeout) still leaves the ring on disk, unsealed."""
    path = str(tmp_path / "killed.json")
    code = textwrap.dedent(f"""
        import os
        from arrow_matrix_tpu.obs import flight
        flight.install({path!r})
        flight.record("progress", "built", stage=1)
        flight.record("progress", "uploading", stage=2)
        os._exit(1)
    """)
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, timeout=120,
                          cwd=REPO)
    assert proc.returncode == 1
    snap = flight.load(path)
    assert not snap.get("sealed")              # nothing ran at death
    assert [e["name"] for e in snap["events"]] == ["built", "uploading"]
    assert flight.newest_artifact(str(tmp_path)) == path


def test_blackbox_cli_prints_artifact(tmp_path, capsys):
    rec = flight.FlightRecorder(str(tmp_path / "bb.json"))
    rec.record("progress", "step-one")
    rec.seal("exit")
    assert trace_main(["blackbox", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "step-one" in out and "sealed: exit" in out
    assert trace_main(["blackbox",
                       str(tmp_path / "nothing-here")]) == 1


def test_memreport_cli_on_summary(tmp_path, capsys):
    run = tmp_path / "run"
    run.mkdir()
    (run / "summary.json").write_text(json.dumps({"algorithms": {
        "algo": {
            "memory": {"source": "memory_analysis",
                       "argument_bytes": 100, "output_bytes": 50,
                       "temp_bytes": 0, "generated_code_bytes": 0,
                       "alias_bytes": 0, "total_bytes": 150},
            "hbm_measured_bytes": 150, "hbm_predicted_bytes": 100,
            "hbm_vs_predicted": 1.5, "hbm_source": "memory_analysis",
            "imbalance": {"units": "device", "n_units": 2,
                          "rows_total": 8, "nnz_total": 6,
                          "slots_total": 12, "nnz_max_over_mean": 1.2,
                          "rows_max_over_mean": 1.0,
                          "padded_slot_waste": 0.5},
        }}}), encoding="utf-8")
    assert trace_main(["memreport", str(run)]) == 0
    out = capsys.readouterr().out
    assert "1.50x" in out and "paper imbalance bound" in out

    (run / "summary.json").write_text(
        json.dumps({"algorithms": {"algo": {"memory": None}}}),
        encoding="utf-8")
    assert trace_main(["memreport", str(run)]) == 1
