"""SellSpaceShared: K levels concurrent on disjoint device groups in
the padding-free feature-major layouts — against the decomposition
golden, the time-shared SellMultiLevel, and under iteration (the
feature-major counterpart of the stacked SpaceSharedArrow tests;
reference semantics arrow/arrow_dec_mpi.py:283-307)."""

import numpy as np
import pytest

from arrow_matrix_tpu.decomposition import arrow_decomposition
from arrow_matrix_tpu.decomposition.decompose import decomposition_spmm
from arrow_matrix_tpu.parallel import (
    SellMultiLevel,
    SellSpaceShared,
    make_mesh,
)
from arrow_matrix_tpu.utils import barabasi_albert, random_dense


def two_levels(n=1024, width=64, m=4, seed=7, dseed=2):
    """Exactly two levels; the capped recursion leaves a grown banded
    last level, so the unified-halo path is exercised."""
    a = barabasi_albert(n, m, seed=seed)
    levels = arrow_decomposition(a, width, max_levels=2,
                                 block_diagonal=True, seed=dseed)
    assert len(levels) == 2
    return a, levels


def test_bf16_carriage_matches_golden():
    """feature_dtype='bf16' on the space-shared sell path: carriage
    dtype bf16, gather returns f32, result within bf16 rounding of the
    decomposition golden (completing the bf16 coverage across all four
    feature-major executors — VERDICT r4 item 7)."""
    import ml_dtypes

    n, width = 1024, 64
    a, levels = two_levels(n, width)
    mesh = make_mesh((2, 4), ("lvl", "blocks"))
    ss = SellSpaceShared(levels, width, mesh, feature_dtype="bf16")
    x = random_dense(n, 8, seed=3)
    xt = ss.set_features(x)
    assert xt.dtype == ml_dtypes.bfloat16
    got = ss.gather_result(ss.step(xt))
    assert got.dtype == np.float32
    want = decomposition_spmm(levels, x)
    rel = np.linalg.norm(got - want) / np.linalg.norm(want)
    assert rel < 2e-2, rel


def test_matches_golden_and_time_shared():
    n, width = 1024, 64
    a, levels = two_levels(n, width)
    mesh = make_mesh((2, 4), ("lvl", "blocks"))
    ss = SellSpaceShared(levels, width, mesh)
    assert ss.binary
    x = random_dense(n, 8, seed=3)
    got = ss.gather_result(ss.step(ss.set_features(x)))
    want = decomposition_spmm(levels, x)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    sm = SellMultiLevel(levels, width, make_mesh((4,), ("blocks",)))
    ref = sm.gather_result(sm.step(sm.set_features(x)))
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)


def test_iterated_weighted_run():
    """Weighted matrices + the scan path: 3 chained iterations match
    3 host applications (the carried orderings round-trip through the
    cross-group exchange tables every step)."""
    n, width = 640, 32
    a = (barabasi_albert(n, 4, seed=11) * 0.25).tocsr().astype(np.float32)
    levels = arrow_decomposition(a, width, max_levels=2,
                                 block_diagonal=True, seed=5)
    assert len(levels) == 2
    mesh = make_mesh((2, 2), ("lvl", "blocks"))
    ss = SellSpaceShared(levels, width, mesh)
    assert not ss.binary
    x = random_dense(n, 4, seed=9)
    got = ss.gather_result(ss.run(ss.set_features(x), 3))
    want = x
    for _ in range(3):
        want = decomposition_spmm(levels, want)
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3)


def test_binary_forced_weighted_bit_identical():
    """binary=False stores explicit value arrays; on 0/1 adjacency the
    results must be BIT-identical to the degree-mask binary layout."""
    n, width = 512, 32
    a, levels = two_levels(n, width, seed=13)
    mesh = make_mesh((2, 4), ("lvl", "blocks"))
    ss_bin = SellSpaceShared(levels, width, mesh)
    ss_wgt = SellSpaceShared(levels, width, mesh, binary=False)
    assert ss_bin.binary and not ss_wgt.binary
    x = random_dense(n, 4, seed=2)
    got_b = ss_bin.gather_result(ss_bin.step(ss_bin.set_features(x)))
    got_w = ss_wgt.gather_result(ss_wgt.step(ss_wgt.set_features(x)))
    np.testing.assert_array_equal(got_b, got_w)


def test_three_levels_uneven_groups():
    """K=3 on a (3, 2) mesh — non-power-of-two level count, converged
    AND grown levels sharing the unified tier shapes and halo reach."""
    n, width = 768, 32
    a = barabasi_albert(n, 3, seed=17)
    levels = arrow_decomposition(a, width, max_levels=3,
                                 block_diagonal=True, seed=4)[:3]
    if len(levels) < 3:
        pytest.skip("decomposition converged under 3 levels")
    mesh = make_mesh((3, 2), ("lvl", "blocks"))
    ss = SellSpaceShared(levels, width, mesh)
    x = random_dense(n, 4, seed=6)
    got = ss.gather_result(ss.step(ss.set_features(x)))
    np.testing.assert_allclose(got, decomposition_spmm(levels, x),
                               rtol=1e-4, atol=1e-4)


def test_feat_axis_three_axis_mesh():
    """3-axis sharding: levels x block-rows x feature columns on a
    (2, 2, 2) mesh — k-dimension tiling composes with the concurrent
    groups."""
    n, width = 512, 32
    a, levels = two_levels(n, width, seed=23)
    mesh = make_mesh((2, 2, 2), ("lvl", "blocks", "feat"))
    ss = SellSpaceShared(levels, width, mesh, feat_axis="feat")
    x = random_dense(n, 8, seed=4)
    got = ss.gather_result(ss.step(ss.set_features(x)))
    np.testing.assert_allclose(got, decomposition_spmm(levels, x),
                               rtol=1e-4, atol=1e-4)


def test_directed_graph_space_shared():
    """Asymmetric adjacency through the concurrent groups (the runtime
    operators must be exact on the asymmetric matrix itself)."""
    n, width = 512, 32
    a = barabasi_albert(n, 3, seed=43, directed=True)
    assert (abs(a - a.T)).nnz > 0
    levels = arrow_decomposition(a, width, max_levels=2,
                                 block_diagonal=True, seed=2)
    assert len(levels) == 2
    ss = SellSpaceShared(levels, width,
                         make_mesh((2, 4), ("lvl", "blocks")))
    x = random_dense(n, 4, seed=1)
    np.testing.assert_allclose(
        ss.gather_result(ss.step(ss.set_features(x))),
        decomposition_spmm(levels, x), rtol=1e-4, atol=1e-4)


def test_mesh_level_mismatch_raises():
    n, width = 512, 32
    _, levels = two_levels(n, width, seed=19)
    mesh = make_mesh((4, 2), ("lvl", "blocks"))
    with pytest.raises(ValueError, match="lvl"):
        SellSpaceShared(levels, width, mesh)
