"""Distributed-layer tests on the virtual 8-device CPU mesh.

The TPU analog of the reference's ``mpiexec --oversubscribe`` many-rank
fixture (reference scripts/run_tests.sh, tests/test_arrowmpi.py): the
conftest forces ``xla_force_host_platform_device_count=8`` so every
collective path (psum broadcast/reduce, ppermute halos, permutation
all-to-alls) executes across real device boundaries.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from scipy import sparse

from arrow_matrix_tpu.decomposition import arrow_decomposition
from arrow_matrix_tpu.decomposition.decompose import decomposition_spmm
from arrow_matrix_tpu.ops import (
    arrow_blocks_from_csr,
    arrow_spmm,
    block_features,
    unblock_features,
)
from arrow_matrix_tpu.parallel import (
    MultiLevelArrow,
    make_mesh,
    make_slim_spmm,
    shard_blocked,
)
from arrow_matrix_tpu.parallel.mesh import pad_to_multiple, shard_arrow_blocks
from arrow_matrix_tpu.utils import barabasi_albert, random_dense


def _arrow_csr(n_blocks: int, width: int, banded: bool, seed: int,
               density: float = 0.2) -> sparse.csr_matrix:
    """Random matrix with exact arrow structure (reference
    tests/test_arrowmpi.py:407-421 uses a dense structured analog)."""
    rng = np.random.default_rng(seed)
    n = n_blocks * width

    def blk():
        return sparse.random(width, width, density=density, random_state=rng,
                             dtype=np.float32)

    grid = [[None] * n_blocks for _ in range(n_blocks)]
    for j in range(n_blocks):
        grid[0][j] = blk()
    for i in range(1, n_blocks):
        grid[i][0] = blk()
        grid[i][i] = blk()
        if banded:
            if i - 1 >= 1:
                grid[i][i - 1] = blk()
            if i + 1 < n_blocks:
                grid[i][i + 1] = blk()
    a = sparse.bmat(grid, format="csr").astype(np.float32)
    a.sum_duplicates()
    a.sort_indices()
    assert a.shape == (n, n)
    return a


@pytest.fixture(scope="module")
def mesh():
    assert jax.device_count() >= 8, "conftest must provide 8 virtual devices"
    return make_mesh((8,), ("blocks",))


@pytest.mark.parametrize("banded", [False, True])
@pytest.mark.parametrize("n_blocks", [8, 16])
def test_slim_spmm_matches_dense(mesh, banded, n_blocks):
    width = 16
    a = _arrow_csr(n_blocks, width, banded, seed=n_blocks)
    blocks = arrow_blocks_from_csr(a, width, banded=banded)
    assert blocks.n_blocks == n_blocks

    x_host = random_dense(n_blocks * width, 8, seed=1)
    xb = shard_blocked(jnp.asarray(block_features(x_host, width, n_blocks)),
                       mesh)
    blocks_sharded = shard_arrow_blocks(blocks, mesh)

    step = make_slim_spmm(blocks, mesh)
    out = step(blocks_sharded, xb)
    got = unblock_features(out, n_blocks * width)
    np.testing.assert_allclose(got, a @ x_host, rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("banded", [False, True])
def test_slim_matches_single_device(mesh, banded):
    """shard_map path == single-device arrow_spmm (same numerics gate the
    reference applies between its cpu and gpu kernels)."""
    width, n_blocks = 16, 8
    a = _arrow_csr(n_blocks, width, banded, seed=3)
    blocks = arrow_blocks_from_csr(a, width, banded=banded)
    x = jnp.asarray(block_features(random_dense(n_blocks * width, 4, seed=2),
                                   width, n_blocks))

    single = arrow_spmm(blocks, x)
    step = make_slim_spmm(blocks, mesh)
    dist = step(shard_arrow_blocks(blocks, mesh), shard_blocked(x, mesh))
    np.testing.assert_allclose(np.asarray(dist), np.asarray(single),
                               rtol=1e-5, atol=1e-5)


def test_gspmd_path_matches(mesh):
    """jit-with-shardings (GSPMD) path == explicit shard_map path."""
    from arrow_matrix_tpu.parallel import distributed_arrow_spmm

    width, n_blocks = 16, 8
    a = _arrow_csr(n_blocks, width, banded=False, seed=5)
    blocks = arrow_blocks_from_csr(a, width)
    x = jnp.asarray(block_features(random_dense(n_blocks * width, 8, seed=4),
                                   width, n_blocks))
    got = distributed_arrow_spmm(shard_arrow_blocks(blocks, mesh),
                                 shard_blocked(x, mesh), mesh)
    np.testing.assert_allclose(unblock_features(got, n_blocks * width),
                               a @ np.asarray(x).reshape(-1, 8),
                               rtol=1e-4, atol=1e-4)


# ---------------------------------------------------------------------------
# Multi-level orchestration
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("use_mesh", [False, True])
def test_multi_level_single_step(mesh, use_mesh):
    """One step() == A @ X == golden decomposition SpMM (reference
    tests/test_arrowmpi.py:96-168 two-matrix decomposition test)."""
    n, width = 480, 32
    a = barabasi_albert(n, 4, seed=11)
    levels = arrow_decomposition(a, width, max_levels=4, block_diagonal=True,
                                 seed=1)
    assert len(levels) >= 2

    ml = MultiLevelArrow(levels, width, mesh=mesh if use_mesh else None)
    x_host = random_dense(n, 8, seed=6)

    x_dev = ml.set_features(x_host)
    out = ml.gather_result(ml.step(x_dev))

    golden = decomposition_spmm(levels, x_host)
    np.testing.assert_allclose(out, golden, rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(out, a @ x_host, rtol=1e-3, atol=1e-3)


def test_multi_level_iterated(mesh):
    """Three iterations X := A @ X match the host loop (reference
    _iterate_and_test, tests/test_arrowmpi.py:311-340)."""
    n, width = 320, 32
    a = barabasi_albert(n, 3, seed=21)
    # Normalize so iterated powers stay in range.
    a = a.multiply(1.0 / 8.0).tocsr().astype(np.float32)
    levels = arrow_decomposition(a, width, max_levels=3, block_diagonal=True,
                                 seed=2)
    ml = MultiLevelArrow(levels, width, mesh=mesh)
    x_host = random_dense(n, 4, seed=8)

    x_dev = ml.set_features(x_host)
    x_dev = ml.run(x_dev, 3)
    got = ml.gather_result(x_dev)

    want = x_host
    for _ in range(3):
        want = a @ want
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-4)


def test_multi_level_banded(mesh):
    n, width = 320, 32
    a = barabasi_albert(n, 3, seed=31)
    levels = arrow_decomposition(a, width, max_levels=4, block_diagonal=False,
                                 seed=3)
    ml = MultiLevelArrow(levels, width, mesh=mesh, banded=True)
    x_host = random_dense(n, 8, seed=9)
    out = ml.gather_result(ml.step(ml.set_features(x_host)))
    np.testing.assert_allclose(out, a @ x_host, rtol=1e-3, atol=1e-3)


def test_multi_level_single_level_identity_routing(mesh):
    """K=1 decompositions skip routing entirely."""
    n, width = 256, 32
    a = _arrow_csr(8, width, banded=False, seed=41)
    lvl_levels = arrow_decomposition(a, width, max_levels=1, seed=4)
    assert len(lvl_levels) == 1
    ml = MultiLevelArrow(lvl_levels, width, mesh=mesh)
    x_host = random_dense(n, 4, seed=10)
    out = ml.gather_result(ml.step(ml.set_features(x_host)))
    np.testing.assert_allclose(out, a @ x_host, rtol=1e-3, atol=1e-3)


def test_set_features_gather_roundtrip(mesh):
    n, width = 320, 32
    a = barabasi_albert(n, 3, seed=51)
    levels = arrow_decomposition(a, width, max_levels=2, block_diagonal=True)
    ml = MultiLevelArrow(levels, width, mesh=mesh)
    x_host = random_dense(n, 8, seed=12)
    round_trip = ml.gather_result(ml.set_features(x_host))
    np.testing.assert_allclose(round_trip, x_host, rtol=0, atol=0)


def test_pad_to_multiple():
    assert pad_to_multiple(8, 8) == 8
    assert pad_to_multiple(9, 8) == 16
    assert pad_to_multiple(1, 8) == 8


# ---------------------------------------------------------------------------
# Wide layout (disjoint row-arm / column-arm device groups).
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("banded", [False, True])
def test_wide_spmm_matches_dense(banded):
    """Wide layout on a (2, 4) mesh == A @ X (reference wide-mode
    test_spmm, tests/test_arrowmpi.py:342-398 at 2t-1 ranks)."""
    from arrow_matrix_tpu.parallel.arrow_layout import make_wide_spmm

    wide_mesh = make_mesh((2, 4), ("arm", "blocks"))
    width, n_blocks = 16, 8
    a = _arrow_csr(n_blocks, width, banded, seed=11)
    blocks = arrow_blocks_from_csr(a, width, banded=banded)
    x_host = random_dense(n_blocks * width, 8, seed=5)
    xb = jnp.asarray(block_features(x_host, width, n_blocks))

    step = make_wide_spmm(blocks, wide_mesh)
    out = step(blocks, xb)
    got = unblock_features(np.asarray(out)[0], n_blocks * width)
    np.testing.assert_allclose(got, a @ x_host, rtol=1e-4, atol=1e-4)


def test_wide_matches_slim():
    from arrow_matrix_tpu.parallel.arrow_layout import make_wide_spmm

    wide_mesh = make_mesh((2, 4), ("arm", "blocks"))
    slim_mesh = make_mesh((8,), ("blocks",))
    width, n_blocks = 16, 8
    a = _arrow_csr(n_blocks, width, banded=True, seed=13)
    blocks = arrow_blocks_from_csr(a, width, banded=True)
    x = jnp.asarray(block_features(random_dense(n_blocks * width, 4, seed=6),
                                   width, n_blocks))

    slim = make_slim_spmm(blocks, slim_mesh)(
        shard_arrow_blocks(blocks, slim_mesh), shard_blocked(x, slim_mesh))
    wide = make_wide_spmm(blocks, wide_mesh)(blocks, x)
    np.testing.assert_allclose(np.asarray(wide)[0], np.asarray(slim),
                               rtol=1e-5, atol=1e-5)


def test_wide_requires_two_arms():
    from arrow_matrix_tpu.parallel.arrow_layout import make_wide_spmm

    bad_mesh = make_mesh((4, 2), ("arm", "blocks"))
    blocks = arrow_blocks_from_csr(_arrow_csr(4, 8, False, seed=1), 8)
    with pytest.raises(ValueError):
        make_wide_spmm(blocks, bad_mesh)


# ---------------------------------------------------------------------------
# Wide layout composed into the multi-level orchestrator (VERDICT r2
# item 7: the reference runs wide *inside* ArrowDecompositionMPI,
# arrow_dec_mpi.py:134,165 — so must we).
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("fmt", ["auto", "ell"])
def test_multi_level_wide_layout_golden(fmt):
    """MultiLevelArrow(layout='wide') on a (2, 4) mesh: step() and a
    3-iteration run match the host golden through the decomposition."""
    n, width = 480, 32
    a = barabasi_albert(n, 4, seed=11)
    levels = arrow_decomposition(a, width, max_levels=4,
                                 block_diagonal=True, seed=1)
    assert len(levels) >= 2
    wide_mesh = make_mesh((2, 4), ("arm", "blocks"))

    ml = MultiLevelArrow(levels, width, mesh=wide_mesh, layout="wide",
                         fmt=fmt)
    x_host = random_dense(n, 8, seed=6)
    out = ml.gather_result(ml.step(ml.set_features(x_host)))
    np.testing.assert_allclose(out, decomposition_spmm(levels, x_host),
                               rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(out, a @ x_host, rtol=1e-3, atol=1e-3)

    want = x_host
    for _ in range(3):
        want = decomposition_spmm(levels, want)
    got = ml.gather_result(ml.run(ml.set_features(x_host), 3))
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=2e-3)


def test_multi_level_wide_matches_slim():
    """Same decomposition, wide (2,4) vs slim (8,) orchestration: equal
    to f32 tolerance (the reference's layouts agree the same way)."""
    n, width = 320, 32
    a = barabasi_albert(n, 3, seed=9)
    levels = arrow_decomposition(a, width, max_levels=3,
                                 block_diagonal=True, seed=2)
    x_host = random_dense(n, 4, seed=3)

    slim = MultiLevelArrow(levels, width, mesh=make_mesh((8,), ("blocks",)))
    wide = MultiLevelArrow(levels, width,
                           mesh=make_mesh((2, 4), ("arm", "blocks")),
                           layout="wide")
    got_s = slim.gather_result(slim.step(slim.set_features(x_host)))
    got_w = wide.gather_result(wide.step(wide.set_features(x_host)))
    np.testing.assert_allclose(got_w, got_s, rtol=1e-5, atol=1e-5)


def test_multi_level_wide_validation():
    levels = arrow_decomposition(barabasi_albert(128, 3, seed=5), 16,
                                 max_levels=2, block_diagonal=True, seed=0)
    with pytest.raises(ValueError, match="wide"):
        MultiLevelArrow(levels, 16, mesh=None, layout="wide")
    with pytest.raises(ValueError, match="arm"):
        MultiLevelArrow(levels, 16, mesh=make_mesh((8,), ("blocks",)),
                        layout="wide")
    with pytest.raises(ValueError, match="routing"):
        MultiLevelArrow(levels, 16,
                        mesh=make_mesh((2, 4), ("arm", "blocks")),
                        layout="wide", routing="a2a")
    with pytest.raises(ValueError, match="layout"):
        MultiLevelArrow(levels, 16, mesh=None, layout="chubby")


def test_hybrid_mesh_single_granule_fallback():
    from arrow_matrix_tpu.parallel.mesh import make_hybrid_mesh

    m = make_hybrid_mesh((8,), (1,), ("blocks",))
    assert m.shape["blocks"] == 8


@pytest.mark.parametrize("head_fmt", ["ell", "flat"])
def test_multi_level_head_fmt_matches(mesh, head_fmt):
    """Explicit head formats (gather-ELL vs scatter-flat) agree with the
    golden — the two kernels bench.py races on the chip."""
    n, width = 320, 32
    a = barabasi_albert(n, 4, seed=61)
    levels = arrow_decomposition(a, width, max_levels=3,
                                 block_diagonal=True, seed=5)
    ml = MultiLevelArrow(levels, width, mesh=mesh, fmt="ell",
                         head_fmt=head_fmt)
    x_host = random_dense(n, 8, seed=13)
    out = ml.gather_result(ml.step(ml.set_features(x_host)))
    np.testing.assert_allclose(out, a @ x_host, rtol=1e-3, atol=1e-3)
    assert all(b.head_flat == (head_fmt == "flat") for b in ml.blocks)
