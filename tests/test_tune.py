"""graft-tune tests (arrow_matrix_tpu/tune/): structure-hash
invariances, TunePlan persistence + version skew, candidate-space
pruning, the subprocess search with its pure-cache-hit property,
``plan="auto"`` consumption (loud TunePlanMiss fallback), the serve
pickup event, and the tools/tune_gate.py CI gate."""

import json
import os
import shutil
import subprocess
import sys

import numpy as np
import pytest

from arrow_matrix_tpu.decomposition import arrow_decomposition
from arrow_matrix_tpu.tune import (
    TunePlan,
    TunePlanMiss,
    enumerate_candidates,
    load_plan,
    save_plans,
    structure_fingerprint,
    structure_hash,
)
from arrow_matrix_tpu.tune.plan import resolve_plan
from arrow_matrix_tpu.tune.space import predicted_operator_bytes
from arrow_matrix_tpu.utils import barabasi_albert, random_dense

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _levels(n=120, width=16, seed=3, m=3, max_levels=4):
    a = barabasi_albert(n, m, seed=seed)
    return arrow_decomposition(a, width, max_levels=max_levels,
                               block_diagonal=True, seed=seed)


# ---------------------------------------------------------------------------
# Structure fingerprint + hash
# ---------------------------------------------------------------------------

def test_hash_deterministic_across_redecomposition():
    # Same graph, same seed, two independent decompositions: the hash
    # reads structure, not object identity.
    h1 = structure_hash(_levels(), 16)
    h2 = structure_hash(_levels(), 16)
    assert h1 == h2 and len(h1) == 16


def test_hash_stable_across_graphio_roundtrip(tmp_path):
    # CSR levels and loaded CsrLike-triplet levels must fingerprint
    # identically — plans tuned on a live decomposition apply to the
    # committed artifact and vice versa.
    from arrow_matrix_tpu.io import save_decomposition
    from arrow_matrix_tpu.io.graphio import (
        as_levels,
        load_decomposition,
        load_level_widths,
    )

    levels = _levels()
    base = str(tmp_path / "g")
    save_decomposition(levels, base, block_diagonal=True)
    loaded = load_decomposition(base, 16, block_diagonal=True)
    widths = load_level_widths(base, 16, len(loaded))
    relevels = as_levels(loaded, widths)
    assert structure_hash(relevels, 16) == structure_hash(levels, 16)


def test_hash_sensitive_to_knobs_that_change_the_operator():
    levels = _levels()
    base = structure_hash(levels, 16)
    assert structure_hash(levels, 32) != base          # fold width
    assert structure_hash(levels, 16, growth=1.5) != base   # tier split
    assert structure_hash(levels, 16, slot_align=1) != base
    assert structure_hash(levels, 16, dtype="bf16") != base  # carriage


def test_fingerprint_schema_and_k_independence():
    levels = _levels()
    fp = structure_fingerprint(levels, 16)
    # The operator is k-independent: one plan file carries per-k
    # entries, so k must NOT appear anywhere in the hashed record.
    assert "k" not in fp
    assert fp["n"] == 120
    ladder = fp["ladder"]
    assert (len(ladder["rows"]) == len(ladder["nnz"])
            == len(ladder["slots"]) == len(ladder["slot_width"]))
    assert sum(ladder["rows"]) == fp["total_rows"]
    assert sum(ladder["nnz"]) == sum(lvl["nnz"] for lvl in fp["levels"])
    assert sum(fp["slot_hist"]["count"]) == fp["total_rows"]


# ---------------------------------------------------------------------------
# TunePlan persistence
# ---------------------------------------------------------------------------

def test_plan_file_merges_per_k_and_selects_largest(tmp_path):
    d = str(tmp_path / "plans")
    p16 = TunePlan(structure_hash="h", k=16, candidate="chunk_4096",
                   chunk=4096)
    p128 = TunePlan(structure_hash="h", k=128, candidate="default")
    save_plans("h", {16: p16}, directory=d)
    save_plans("h", {128: p128}, directory=d)   # merge, not overwrite
    got = load_plan("h", 16, d)
    assert got.candidate == "chunk_4096" and got.chunk == 4096
    # k=None is the amortized regime: largest cached k wins.
    assert load_plan("h", None, d).k == 128
    with pytest.warns(TunePlanMiss, match="no entry for k=64"):
        assert load_plan("h", 64, d) is None


def test_plan_version_skew_is_a_loud_miss(tmp_path):
    d = str(tmp_path / "plans")
    save_plans("h", {16: TunePlan(structure_hash="h", k=16)},
               directory=d)
    path = os.path.join(d, "h.json")
    with open(path, encoding="utf-8") as fh:
        record = json.load(fh)
    record["version"] = 999
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(record, fh)
    with pytest.warns(TunePlanMiss, match="version skew"):
        assert load_plan("h", 16, d) is None
    # A stale in-memory plan object is rejected the same way.
    stale = TunePlan(structure_hash="h", k=16, version=999)
    with pytest.warns(TunePlanMiss, match="version skew"):
        assert resolve_plan(stale) is None


def test_resolve_plan_forms():
    p = TunePlan(structure_hash="h", k=16)
    assert resolve_plan(None) is None
    assert resolve_plan(p) is p
    assert resolve_plan(p.to_dict()) == p
    with pytest.raises(ValueError, match="levels and width"):
        resolve_plan("auto")
    with pytest.raises(ValueError, match="unknown plan"):
        resolve_plan("yes please")


# ---------------------------------------------------------------------------
# Candidate space + feasibility pruning
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def small_fp():
    return structure_fingerprint(_levels(), 16)


def test_pruning_divisibility_and_interpret(small_fp):
    cands, pruned = enumerate_candidates(small_fp, 7, platform="cpu")
    names = {c.name for c in cands}
    assert "default" in names
    assert "repl2" in pruned and "repl | k" in pruned["repl2"]
    assert "overlap2" in pruned and "S | (k/c)" in pruned["overlap2"]
    # DMA-ring depth is stream-only; the interpret evaluator runs the
    # vectorized body, so racing it would measure nothing.
    assert "pallas_sell_ring1" in pruned and "pallas_sell_ring4" in pruned
    assert "stream-only" in pruned["pallas_sell_ring1"]
    # ...but the fused kernel itself races fine under interpret.
    assert "pallas_sell" in names


def test_pruning_onchip_needs_k16(small_fp):
    _, pruned = enumerate_candidates(small_fp, 20, platform="tpu")
    assert "pallas_sell" in pruned and "k % 16" in pruned["pallas_sell"]
    cands, pruned = enumerate_candidates(small_fp, 32, platform="tpu")
    names = {c.name for c in cands}
    assert "pallas_sell" in names and "pallas_sell_ring4" in names
    assert "repl2" in names


def test_pruning_hbm_certificate(small_fp):
    base = predicted_operator_bytes(small_fp, 16)
    _, pruned = enumerate_candidates(small_fp, 16, platform="tpu",
                                     budget_bytes=int(base * 1.5))
    assert "repl2" in pruned and "HBM certificate" in pruned["repl2"]


def test_pruning_restrict_and_int8_optin(small_fp):
    cands, pruned = enumerate_candidates(
        small_fp, 16, platform="cpu",
        restrict=["default", "fold_tight"])
    assert {c.name for c in cands} == {"default", "fold_tight"}
    assert all("restricted" in why for why in pruned.values())
    names = {c.name for c in
             enumerate_candidates(small_fp, 16, allow_int8=True)[0]}
    assert "int8" in names
    int8 = [c for c in enumerate_candidates(
        small_fp, 16, allow_int8=True)[0] if c.name == "int8"][0]
    bf16 = [c for c in enumerate_candidates(small_fp, 16)[0]
            if c.name == "bf16"][0]
    # Carriage-dtype experiments are diagnostics: never f32
    # bit-identical, so never eligible to win.
    assert not int8.eligible and not bf16.eligible


# ---------------------------------------------------------------------------
# The search itself (subprocess race + pure cache hit)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def smoke_reports(tmp_path_factory):
    """ONE smoke search (3 children) + an immediate second search of
    the unchanged structure, shared by the consumption/gate tests."""
    from arrow_matrix_tpu.tune import smoke_tune

    d = str(tmp_path_factory.mktemp("tune_smoke"))
    old_flight = os.environ.get("AMT_FLIGHT_DIR")
    os.environ["AMT_FLIGHT_DIR"] = os.path.join(d, "flight")
    try:
        r1 = smoke_tune(d)
        r2 = smoke_tune(d)
    finally:
        if old_flight is None:
            os.environ.pop("AMT_FLIGHT_DIR", None)
        else:
            os.environ["AMT_FLIGHT_DIR"] = old_flight
    return d, r1, r2


def test_search_races_children_and_persists_winner(smoke_reports):
    d, r1, _ = smoke_reports
    assert r1["ok"] and not r1["cache_hit"]
    assert r1["children_spawned"] == 3     # restricted smoke space
    assert r1["winner"] in r1["results"]
    plan = r1["plan"]
    # A winner must have proven f32 bit-identity vs the golden
    # ops/sell.py fold path; its margin vs the default is recorded.
    assert plan["bit_identical"] is True
    assert plan["measured_ms"] is not None
    assert plan["margin"] is not None and plan["margin"] >= 0.0
    assert plan["host_load"] is not None
    assert os.path.exists(r1["plan_path"])
    # The default is always raced and always trivially bit-identical.
    assert r1["results"]["default"]["bit_identical"] is True


def test_second_search_is_pure_cache_hit(smoke_reports):
    # THE acceptance property: an unchanged structure's second search
    # spawns ZERO bench children.
    _, r1, r2 = smoke_reports
    assert r2["ok"] and r2["cache_hit"]
    assert r2["children_spawned"] == 0
    assert r2["plan"]["candidate"] == r1["plan"]["candidate"]


# ---------------------------------------------------------------------------
# Consumption: plan="auto", loud miss, serve pickup
# ---------------------------------------------------------------------------

def _smoke_levels():
    # Exactly the structure smoke_tune searches (tune/search.py).
    return _levels(n=96, width=16, seed=3, m=3, max_levels=4)


def test_plan_auto_consumption_bitwise(smoke_reports, monkeypatch):
    from arrow_matrix_tpu.parallel import MultiLevelArrow

    d, r1, _ = smoke_reports
    monkeypatch.setenv("AMT_TUNE_PLAN_DIR",
                       os.path.join(d, "tune_plans"))
    levels = _smoke_levels()
    tuned = MultiLevelArrow(levels, 16, plan="auto")
    assert tuned.tune_plan is not None
    assert tuned.tune_plan.structure_hash == r1["structure_hash"]
    # The tuned executor must still be bit-identical to the golden
    # fold path AT THE PLAN'S k (that is exactly what made its
    # candidate eligible to win — reduction order is shape-dependent,
    # so the promise is per-k and per-format, fmt="fold").
    default = MultiLevelArrow(levels, 16, fmt="fold")
    x = random_dense(default.n, int(r1["k"]), seed=5)
    want = np.asarray(default.gather_result(
        default.step(default.set_features(x))), dtype=np.float32)
    got = np.asarray(tuned.gather_result(
        tuned.step(tuned.set_features(x))), dtype=np.float32)
    np.testing.assert_array_equal(got, want)


def test_plan_auto_miss_is_loud(tmp_path, monkeypatch):
    from arrow_matrix_tpu.parallel import MultiLevelArrow

    monkeypatch.setenv("AMT_TUNE_PLAN_DIR", str(tmp_path / "empty"))
    with pytest.warns(TunePlanMiss, match="no plan file"):
        multi = MultiLevelArrow(_smoke_levels(), 16, plan="auto")
    assert multi.tune_plan is None         # defaults, loudly


def test_sell_multi_level_consumes_plan_dict(smoke_reports):
    from arrow_matrix_tpu.parallel import make_mesh
    from arrow_matrix_tpu.parallel.sell_slim import SellMultiLevel

    _, r1, _ = smoke_reports
    mesh = make_mesh((2,), ("blocks",))
    sml = SellMultiLevel(_smoke_levels(), 16, mesh, plan=r1["plan"])
    assert sml.tune_plan is not None
    assert sml.tune_plan.candidate == r1["plan"]["candidate"]


def test_serve_applies_tune_plan_as_base_rung(smoke_reports, tmp_path):
    from arrow_matrix_tpu.obs import flight
    from arrow_matrix_tpu.serve import (
        ArrowServer,
        ExecConfig,
        ba_executor_factory,
    )

    _, r1, _ = smoke_reports
    fac, _n = ba_executor_factory(64, 16, 3, fmt="fold")
    rec = flight.FlightRecorder(str(tmp_path / "flight.json"))
    flight.set_recorder(rec)
    try:
        srv = ArrowServer(fac, ExecConfig(), name="tuned",
                          tune_plan=r1["plan"])
    finally:
        flight.set_recorder(None)
    assert srv.tune_plan is not None
    applied = [e["data"] for e in rec.events
               if e.get("name") == "tune_plan_applied"
               and e.get("data", {}).get("server") == "tuned"]
    assert applied
    assert applied[-1]["structure_hash"] == r1["structure_hash"]
    assert (applied[-1]["base_config"]["kernel"]
            == r1["plan"]["kernel"])


# ---------------------------------------------------------------------------
# The CI gate
# ---------------------------------------------------------------------------

def test_tune_gate_passes_on_fresh_cache(smoke_reports):
    d, _, _ = smoke_reports
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "tune_gate.py"),
         "--plan-dir", os.path.join(d, "tune_plans"),
         "--iters", "2", "--repeats", "1", "--quiet"],
        capture_output=True, text=True, cwd=REPO, timeout=300)
    assert proc.returncode == 0, proc.stderr[-800:]
    assert "tune-gate OK" in proc.stdout
    assert "cache-purity" in proc.stdout
    assert "bit-identity" in proc.stdout


def test_tune_gate_detects_hash_drift(smoke_reports, tmp_path):
    from arrow_matrix_tpu.tune.gate import check_structure

    d, r1, _ = smoke_reports
    drifted = str(tmp_path / "drifted")
    shutil.copytree(os.path.join(d, "tune_plans"), drifted)
    path = os.path.join(drifted, f"{r1['structure_hash']}.json")
    with open(path, encoding="utf-8") as fh:
        record = json.load(fh)
    record["structure_hash"] = "0" * 16    # tampered artifact
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(record, fh)
    source = record["context"]["source"]
    res = check_structure(source, directory=drifted, timing=False,
                          quiet=True)
    assert not res["ok"]
    assert any("hash drift" in f for f in res["failures"])


def test_tune_gate_empty_cache_is_failure(tmp_path):
    from arrow_matrix_tpu.tune.gate import run_gate

    assert run_gate(directory=str(tmp_path / "nothing")) == 1


def test_save_plans_concurrent_writers_drop_no_entry(tmp_path):
    """The fleet-workers race: N writers merge DIFFERENT k entries
    into the same plan file concurrently.  Without the advisory file
    lock around the read-merge-write, two writers read the same stale
    file and the slower rewrite drops the faster one's entry; with it,
    every entry survives."""
    import threading

    d = str(tmp_path / "plans")
    h = "f" * 16
    ks = list(range(1, 9))
    errors = []

    def write(k):
        try:
            save_plans(h, {k: TunePlan(h, k)}, directory=d)
        except Exception as e:          # surfaced below, not swallowed
            errors.append(e)

    threads = [threading.Thread(target=write, args=(k,)) for k in ks]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert errors == []
    from arrow_matrix_tpu.tune.plan import load_plan_file

    doc = load_plan_file(h, d)
    assert sorted(int(s) for s in doc["plans"]) == ks
    for k in ks:                        # every entry loads cleanly too
        assert load_plan(h, k, directory=d).k == k
