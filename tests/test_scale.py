"""Scale-ladder guard tests (VERDICT r2 item 3).

The full rungs (2^24 decompose + streamed ingest, 2^26-row planar
decompose) take tens of minutes on one host core, so they run via
``tools/scale_ladder.py`` and are guarded here:

* always: the ladder tool's registry and recorded results stay sane
  (a recorded run must have passed its golden gate);
* ``AMT_SLOW=1``: re-run the streamed-ingest rung end-to-end (needs
  the 2^24 artifact in bench_cache — the decompose rung creates it).
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LADDER = os.path.join(REPO, "tools", "scale_ladder.py")
RESULTS = os.path.join(REPO, "bench_results", "scale_ladder.json")


def _ladder_module():
    import importlib.util

    spec = importlib.util.spec_from_file_location("ladder", LADDER)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_ladder_registry_importable():
    mod = _ladder_module()
    assert set(mod.RUNGS) == {
        "decompose24", "ingest24", "decompose26_grid",
        "decompose_1e8_grid", "decompose_1e8_ba",
        "rehearse_1e8_ba_step",
        "backend_race22", "backend_race23",
        "dryrun_multichip_mid", "dryrun_repl_sweep"}
    # The 1e8 rungs are opt-in: a bare `python tools/scale_ladder.py`
    # must stay bounded (the BA 2^27 rungs need ~hours and tens of GB).
    # The mid multichip dryrun and the repl sweep are opt-in too.
    assert set(mod.DEFAULT_RUNGS) == set(mod.RUNGS) - {
        "decompose_1e8_grid", "decompose_1e8_ba",
        "rehearse_1e8_ba_step", "dryrun_multichip_mid",
        "dryrun_repl_sweep"}


def test_recorded_ladder_results_pass_their_gates():
    """A committed scale_ladder.json must hold gate-passing numbers —
    a recorded run that failed its golden is not a result."""
    if not os.path.exists(RESULTS):
        pytest.skip("no recorded ladder results yet")
    with open(RESULTS) as f:
        results = json.load(f)
    for rung, r in results.items():
        if rung.endswith("_retry_error"):
            continue   # parked failed re-run; recorded numbers intact
        assert "error" not in r, f"{rung} recorded a failure: {r}"
    ing = results.get("ingest24")
    if ing:
        assert ing["golden_err"] <= ing["golden_gate"]
        # Build RSS bound (measured 31.6 GB at 2^24): blocks stream
        # per-slice, but the 12 inter-level routing tables compose on
        # the host at O(K * n) — the known non-streamed remainder
        # (PERFORMANCE.md scale ladder).  The bound guards against
        # regression to a fully-materialized build (~41 GB decompose
        # RSS) while the table composition stays host-global.
        assert ing["build_peak_rss_gb"] < 36.0
    grid = results.get("decompose26_grid")
    if grid:
        assert grid["one_level_fast_path"] is True


@pytest.mark.skipif(os.environ.get("AMT_SLOW") != "1",
                    reason="2^24 streamed-ingest rung (minutes); "
                           "set AMT_SLOW=1")
def test_streamed_ingest_2_24_end_to_end():
    artifact = _ladder_module()._artifact24() + ".complete"
    if not os.path.exists(artifact):
        pytest.skip("2^24 artifact missing; run "
                    "tools/scale_ladder.py decompose24 first")
    proc = subprocess.run(
        [sys.executable, LADDER, "--rung", "ingest24"],
        capture_output=True, text=True, timeout=3600, cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-1500:]
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["golden_err"] <= out["golden_gate"]
