"""Explicit all_to_all permutation routing (parallel/routing.py) and the
HLO communication accounting behind it (utils/commstats.py) — the
TPU-native counterpart of the reference's precomputed Alltoallv tables
(reference arrow/arrow_dec_mpi.py:210-281, unit-tested there by
tests/test_arrowmpi.py test_all_to_all)."""

import numpy as np
import pytest

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from arrow_matrix_tpu.decomposition.decompose import (
    arrow_decomposition,
    decomposition_spmm,
)
from arrow_matrix_tpu.parallel.mesh import make_mesh
from arrow_matrix_tpu.parallel.multi_level import MultiLevelArrow
from arrow_matrix_tpu.parallel.routing import build_route, routed_take
from arrow_matrix_tpu.utils import commstats, numerics
from arrow_matrix_tpu.utils.graphs import barabasi_albert, random_dense


@pytest.fixture(scope="module")
def mesh():
    return make_mesh((8,), ("blocks",))


@pytest.mark.parametrize("make_table", [
    lambda rng, n: rng.permutation(n),            # fully random
    lambda rng, n: np.arange(n),                  # identity: zero comm
    lambda rng, n: np.roll(np.arange(n), n // 8),  # one-device shift
], ids=["random", "identity", "shift"])
def test_routed_take_matches_table(mesh, make_table):
    rng = np.random.default_rng(0)
    total, k = 1024, 8
    table = make_table(rng, total)
    route = build_route(table, 8)
    x_host = rng.standard_normal((total, k)).astype(np.float32)
    x = jax.device_put(x_host, NamedSharding(mesh, P("blocks")))
    got = np.asarray(jax.jit(
        lambda x: routed_take(x, route, mesh, "blocks"))(x))
    np.testing.assert_array_equal(got, x_host[table])


def test_identity_route_moves_nothing(mesh):
    route = build_route(np.arange(1024), 8)
    assert route.send_idx.shape[2] == 0  # no cross-device slots at all


def test_build_route_rejects_indivisible():
    with pytest.raises(ValueError, match="divisible"):
        build_route(np.arange(10), 8)


@pytest.mark.parametrize("masked", [False, True])
@pytest.mark.parametrize("rectangular", [False, True])
def test_streamed_build_identical(masked, rectangular):
    """The chunked two-pass build must produce ELEMENTWISE identical
    tables to the in-memory build for any chunk size (both enumerate j
    ascending within every group, so slot assignment is partition-
    independent) — the order-identity contract of VERDICT r4 item 4.
    A small chunk forces many boundary crossings; a repeats-allowed
    table (gather, not permutation) is the harsher case."""
    rng = np.random.default_rng(5)
    total = 1 << 14
    src_total = (1 << 13) if rectangular else total
    table = rng.integers(0, src_total, total)
    pm = (rng.random(total) < 0.1) if masked else None
    mem = build_route(table, 8, src_total=src_total, pad_mask=pm,
                      stream_chunk=1 << 62)   # force in-memory
    st = build_route(table, 8, src_total=src_total, pad_mask=pm,
                     stream_chunk=1 << 10)    # 16 chunks
    for name in ("local_src", "local_dst", "send_idx", "recv_dst"):
        a, b = np.asarray(getattr(mem, name)), np.asarray(getattr(st, name))
        assert a.shape == b.shape
        np.testing.assert_array_equal(a, b, err_msg=name)
    # The streamed path validates per chunk — same loud failure.
    bad = table.copy()
    bad[-1] = src_total + 7
    with pytest.raises(ValueError, match="outside"):
        build_route(bad, 8, src_total=src_total, stream_chunk=1 << 10)


def _problem(n=2048, w=64, seed=3):
    a = barabasi_albert(n, 4, seed=seed)
    levels = arrow_decomposition(a, arrow_width=w, max_levels=2,
                                 block_diagonal=True, seed=seed)
    return a, levels


def test_multi_level_a2a_matches_gather(mesh):
    a, levels = _problem()
    x_host = random_dense(a.shape[0], 8, seed=1)

    ml_g = MultiLevelArrow(levels, 64, mesh=mesh, routing="gather")
    ml_r = MultiLevelArrow(levels, 64, mesh=mesh, routing="a2a")
    got_g = ml_g.gather_result(ml_g.run(ml_g.set_features(x_host), 3))
    got_r = ml_r.gather_result(ml_r.run(ml_r.set_features(x_host), 3))
    want = x_host.copy()
    for _ in range(3):
        want = decomposition_spmm(levels, want)

    tol = numerics.relative_tolerance(a.nnz / a.shape[0], 3)
    assert numerics.relative_error(got_r, want) < tol
    # Same additions in both modes, only the exchange lowering differs.
    np.testing.assert_allclose(got_r, got_g, rtol=1e-6, atol=1e-6)


def test_a2a_reduces_exchange_volume(mesh):
    # The headline property (reference README.md:3 "communication-
    # efficient"): explicit routing must move less than GSPMD's
    # all-gather lowering of the same step.
    a, levels = _problem()
    x_host = random_dense(a.shape[0], 8, seed=1)

    ml_g = MultiLevelArrow(levels, 64, mesh=mesh, routing="gather")
    ml_r = MultiLevelArrow(levels, 64, mesh=mesh, routing="a2a")
    xg = ml_g.set_features(x_host)
    xr = ml_r.set_features(x_host)
    st_g = commstats.collective_stats(ml_g._step, xg, ml_g.fwd, ml_g.bwd,
                                      ml_g.blocks)
    st_r = commstats.collective_stats(ml_r._step, xr, ml_r.fwd, ml_r.bwd,
                                      ml_r.blocks)
    assert st_r["all-to-all"]["count"] >= 1
    assert st_r["total_bytes"] < st_g["total_bytes"]


def test_ideal_routing_bytes():
    # Identity permutations on both levels: nothing should move.
    perms = [np.arange(64), np.arange(64)]
    assert commstats.ideal_routing_bytes(perms, 8, 4) == 0
    # A shift by one device's rows moves every row, both directions.
    perms = [np.arange(64), np.roll(np.arange(64), 8)]
    assert commstats.ideal_routing_bytes(perms, 8, 4) == 2 * 64 * 4 * 4


def test_multi_level_a2a_iterated_scan(mesh):
    # routing='a2a' under run() (lax.scan): RouteTables pytrees must
    # thread through the scan carry machinery like plain arrays.
    a, levels = _problem()
    a = (a / 8.0).tocsr().astype(np.float32)
    levels = arrow_decomposition(a, 64, max_levels=3, block_diagonal=True,
                                 seed=1)
    x_host = random_dense(a.shape[0], 4, seed=2)
    ml = MultiLevelArrow(levels, 64, mesh=mesh, routing="a2a")
    got = ml.gather_result(ml.run(ml.set_features(x_host), 3))
    want = x_host
    for _ in range(3):
        want = a @ want
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-4)
