"""graft-xray tests: wire accounting (measured stats, registry
labels, byte conservation across a socketpair), the near-limit
warning and the hard frame refusal, merge-inheriting request
contexts, per-process trace docs and the clock-offset-aligned fleet
merge, flight-ring recovery with explicit ``truncated`` markers, the
per-class critical-path decomposition (segment math pinned on a
synthetic trace), report diffing, the per-class ledger bands (a
planted byte-cheap/time-slow approx record must trip the drift gate,
and ``wire_bytes`` bands as lower-is-better), and one in-process
two-worker fleet end to end: worker spans carry the router-minted
trace_id, the merged trace has one track per process, and the
router's per-frame wire ledger sums exactly to its totals."""

import json
import socket
import threading
import time

import numpy as np
import pytest

from arrow_matrix_tpu.fleet import wire
from arrow_matrix_tpu.ledger import Ledger, gate
from arrow_matrix_tpu.obs import flight
from arrow_matrix_tpu.obs import metrics as metrics_mod
from arrow_matrix_tpu.obs import xray
from arrow_matrix_tpu.obs.tracer import Tracer


@pytest.fixture
def fresh_registry():
    old = metrics_mod.get_registry()
    reg = metrics_mod.MetricsRegistry()
    metrics_mod.set_registry(reg)
    yield reg
    metrics_mod.set_registry(old)


# ---------------------------------------------------------------------------
# Wire accounting
# ---------------------------------------------------------------------------

def test_wire_stats_measured_and_conserved(fresh_registry):
    a, b = socket.socketpair()
    try:
        out = wire.send_msg(
            a, {"op": "submit", "x": np.arange(6, dtype=np.float32)},
            role="client")
        msg, back = wire.recv_msg_stats(b, role="server")
    finally:
        a.close()
        b.close()
    assert msg["op"] == "submit"
    assert out["dir"] == "send" and back["dir"] == "recv"
    assert out["op"] == back["op"] == "submit"
    assert out["frame_bytes"] == back["frame_bytes"] > 0
    assert out["serialize_ms"] >= 0.0 and out["wire_ms"] >= 0.0
    # recv splits header wait (server think time) from payload
    # transfer — both present, neither negative.
    assert back["wait_ms"] >= 0.0 and back["wire_ms"] >= 0.0
    hists = {(h["labels"].get("role"), h["labels"].get("dir")):
             h["summary"]
             for h in fresh_registry.snapshot()["histograms"]
             if h["name"] == "wire_frame_bytes"}
    assert hists[("client", "send")]["count"] == 1
    assert hists[("server", "recv")]["count"] == 1
    # byte conservation, measured independently on both sides
    assert (hists[("client", "send")]["mean"]
            == hists[("server", "recv")]["mean"])


def test_near_limit_warns_and_oversize_refuses(fresh_registry,
                                               monkeypatch):
    msg = {"op": "pad", "pad": "x" * 1000}
    blob = len(json.dumps(wire.encode_payload(msg)).encode("utf-8"))
    a, b = socket.socketpair()
    try:
        # Exactly at the limit: delivered, but LOUD.
        monkeypatch.setattr(wire, "MAX_FRAME_BYTES", blob)
        with pytest.warns(wire.WireNearLimitWarning):
            wire.send_msg(a, msg)
        assert wire.recv_msg(b)["pad"] == msg["pad"]
        # One byte over: refused before any bytes hit the socket.
        monkeypatch.setattr(wire, "MAX_FRAME_BYTES", blob - 1)
        with pytest.raises(wire.WireError, match="exceeds"):
            wire.send_msg(a, msg)
    finally:
        a.close()
        b.close()
    counters = {c["labels"].get("op"): c["value"]
                for c in fresh_registry.snapshot()["counters"]
                if c["name"] == "wire_near_limit_total"}
    assert counters.get("pad") == 1


# ---------------------------------------------------------------------------
# Trace context + per-process docs
# ---------------------------------------------------------------------------

def test_request_context_merge_inherits():
    with flight.request_context("rq1", "tenantA", trace_id="abc"):
        # The scheduler re-enters the context for a batch; the fleet
        # keys entered at the wire must survive the nesting.
        with flight.request_context("b1+b2", "tenantA"):
            ctx = flight.current_request()
            assert ctx["request_id"] == "b1+b2"
            assert ctx["trace_id"] == "abc"
        assert flight.current_request()["request_id"] == "rq1"
    assert flight.current_request() is None


def test_process_trace_doc_carries_context_and_epoch():
    tr = Tracer(name="t")
    assert tr.epoch_unix == pytest.approx(time.time(), abs=60.0)
    with flight.request_context("rq9", "t0", trace_id="deadbeef"):
        with tr.span("work"):
            pass
    doc = xray.process_trace(tr, "w9")
    assert doc["process"] == "w9" and doc["truncated"] is False
    (s,) = doc["spans"]
    assert s["args"]["request_id"] == "rq9"
    assert s["args"]["trace_id"] == "deadbeef"
    assert s["dur_us"] >= 0.0


# ---------------------------------------------------------------------------
# Merge + flight-ring recovery
# ---------------------------------------------------------------------------

def _doc(process, epoch, spans, truncated=False):
    return {"schema": 1, "process": process, "pid": 1,
            "epoch_unix": epoch, "truncated": truncated,
            "spans": [{"name": n, "ts_us": ts, "dur_us": d, "tid": 0,
                       "args": dict(a)} for (n, ts, d, a) in spans]}


def test_merge_aligns_clocks_and_orders_router_first():
    router = _doc("router", 1000.0,
                  [("dispatch", 0.0, 100.0, {"request_id": "r1"})])
    # The worker's clock reads 0.5 s AHEAD of the router's; the ping
    # handshake measured exactly that, so the tracks must align.
    worker = _doc("w0", 1000.5,
                  [("batch", 20.0, 50.0, {"request_id": "r1"})])
    merged = xray.merge_process_traces(
        [worker, router], offsets_ns={"w0": {"offset_ns": 500_000_000}})
    evs = [e for e in merged["traceEvents"] if e["ph"] == "X"]
    by = {e["name"]: e for e in evs}
    assert by["dispatch"]["pid"] == 0          # router track is pid 0
    assert by["batch"]["pid"] == 1
    assert min(e["ts"] for e in evs) == 0.0    # rebased
    assert (by["batch"]["ts"] - by["dispatch"]["ts"]
            == pytest.approx(20.0, abs=1e-3))
    names = {m["args"]["name"] for m in merged["traceEvents"]
             if m["ph"] == "M"}
    assert names == {"router", "w0"}
    assert merged["xray"]["truncated"] == []


def test_merge_marks_truncated_tracks():
    merged = xray.merge_process_traces([
        _doc("router", 0.0, [("dispatch", 0.0, 1.0, {})]),
        _doc("w1", 0.0, [("batch", 0.0, 1.0, {"truncated": True})],
             truncated=True)])
    assert merged["xray"]["truncated"] == ["w1"]
    meta = {m["pid"]: m["args"]["name"]
            for m in merged["traceEvents"] if m["ph"] == "M"}
    assert meta[1] == "w1 (truncated)"


def test_recover_from_flight_marks_every_span_truncated(tmp_path):
    path = str(tmp_path / "flight.json")
    rec = flight.FlightRecorder(path)
    flight.set_recorder(rec)
    try:
        with flight.request_context("rq7", "tz", trace_id="feed"):
            flight.record("span", "batch", ms=12.5)
        flight.record("fleet", "router_up")    # non-span: ignored
    finally:
        flight.set_recorder(None)
    doc = xray.recover_from_flight(path, "worker-1")
    assert doc["truncated"] is True and doc["process"] == "worker-1"
    (s,) = doc["spans"]
    assert s["name"] == "batch"
    assert s["args"]["truncated"] is True
    assert s["args"]["recovered_from"] == "flight_ring"
    assert s["args"]["request_id"] == "rq7"
    assert s["args"]["trace_id"] == "feed"
    assert s["dur_us"] == pytest.approx(12_500.0)
    # missing artifact or no spans -> None, never a fabricated track
    assert xray.recover_from_flight(str(tmp_path / "no.json"),
                                    "x") is None


# ---------------------------------------------------------------------------
# Critical path
# ---------------------------------------------------------------------------

def _ev(name, ts_us, dur_us, pid, args):
    return {"name": name, "ph": "X", "ts": float(ts_us),
            "dur": float(dur_us), "pid": pid, "tid": 0, "args": args}


def test_critical_path_segment_math_is_pinned():
    rid = "rq1"
    events = [
        _ev("dispatch", 0, 100_000, 0, {"request_id": rid}),
        _ev("rpc", 10_000, 80_000, 0,
            {"request_id": rid, "serialize_ms": 2.0, "wire_ms": 3.0}),
        _ev("worker_submit", 12_000, 70_000, 1, {"request_id": rid}),
        _ev("admission", 12_000, 1_000, 1, {"request_id": rid}),
        _ev("batch", 20_000, 40_000, 1,
            {"request_id": rid, "traffic_class": "approx"}),
        _ev("checkpoint", 30_000, 5_000, 1, {"request_id": rid}),
        _ev("finalize", 61_000, 2_000, 1, {"request_id": rid}),
    ]
    cp = xray.critical_path({"traceEvents": events})
    r = cp["requests"][rid]
    seg = r["segments"]
    assert r["class"] == "approx"              # from the batch span
    assert r["total_ms"] == pytest.approx(100.0)
    assert seg["queue"] == pytest.approx(10.0)
    assert seg["admission"] == pytest.approx(1.0)
    assert seg["serialize"] == pytest.approx(2.0)
    assert seg["wire"] == pytest.approx(3.0)
    assert seg["worker_queue"] == pytest.approx(7.0)   # 20 - (12+1)
    assert seg["checkpoint"] == pytest.approx(5.0)
    assert seg["compute"] == pytest.approx(35.0)       # batch - ckpt
    assert seg["response"] == pytest.approx(12.0)      # 2 + tail 10
    agg = cp["per_class"]["approx"]
    assert agg["count"] == 1
    assert agg["segments_mean_ms"]["compute"] == pytest.approx(35.0)
    # an explicit class map (the fleet report's served_class) wins
    cp2 = xray.critical_path({"traceEvents": events},
                             classes={rid: "exact"})
    assert cp2["requests"][rid]["class"] == "exact"


def test_critical_path_splits_batch_shared_spans_evenly():
    events = [
        _ev("dispatch", 0, 50_000, 0, {"request_id": "a"}),
        _ev("rpc", 0, 50_000, 0, {"request_id": "a"}),
        _ev("dispatch", 0, 50_000, 0, {"request_id": "b"}),
        _ev("rpc", 0, 50_000, 0, {"request_id": "b"}),
        _ev("batch", 10_000, 20_000, 1, {"request_id": "a+b"}),
    ]
    cp = xray.critical_path({"traceEvents": events})
    assert cp["requests"]["a"]["segments"]["compute"] \
        == pytest.approx(10.0)
    assert cp["requests"]["b"]["segments"]["compute"] \
        == pytest.approx(10.0)


def test_diff_reports_flags_grown_segment_only():
    base = {"per_class": {"exact": {"segments_mean_ms":
                                    {"wire": 10.0, "compute": 50.0}}}}
    worse = {"per_class": {"exact": {"segments_mean_ms":
                                     {"wire": 20.0, "compute": 50.0}}}}
    d = xray.diff_reports(base, worse)
    assert len(d["regressions"]) == 1
    assert "exact/wire" in d["regressions"][0]
    assert xray.diff_reports(base, base)["regressions"] == []
    # a shrink is not a regression
    assert xray.diff_reports(worse, base)["regressions"] == []


# ---------------------------------------------------------------------------
# Per-class ledger bands (graft-xray satellite on class_bench)
# ---------------------------------------------------------------------------

def _cls_rec(lg, value, *, ts, carriage=1 << 20, degraded=False):
    """One per-class bench record shaped like tools/class_bench.py's
    class-suffixed rows."""
    return lg.record(
        "bench", "spmm_iter_ms_n4096_w64_bf16", value, unit="ms",
        platform="cpu", device_kind="host", host_load=0.2,
        git_rev=None, ts_unix=ts,
        knobs={"traffic_class": "bf16"},
        payload={"parsed": {"metric": "spmm_iter_ms_bf16",
                            "class": "bf16",
                            "carriage_bytes": carriage,
                            "degraded": degraded}})


def test_planted_byte_cheap_time_slow_class_trips_gate(tmp_path):
    lg = Ledger(str(tmp_path / "lg"))
    for i, v in enumerate([100.0, 100.5, 99.5, 100.2]):
        _cls_rec(lg, v, ts=1000.0 + i)
    baseline = gate.build_baseline(lg.read_all())
    # Half the carriage bytes but 30% slower: the class-suffixed band
    # must fail it — byte-cheap may not hide time-slow behind the f32
    # headline metric.
    slow = _cls_rec(lg, 130.0, ts=2000.0, carriage=1 << 19)
    failures, _ = gate.check_records([slow], baseline)
    assert any("perf regression" in f for f in failures)
    rc, lines = gate.run_gate(
        ledger_dir=lg.directory,
        baseline_file=gate.save_baseline(
            gate.baseline_path(lg.directory), baseline))
    assert rc == 1 and any("FAIL" in ln for ln in lines)
    # a degraded (host-fallback) class round is a note, never a fail
    soft = _cls_rec(lg, 130.0, ts=2001.0, degraded=True)
    failures, notes = gate.check_records([soft], baseline)
    assert failures == []
    assert any("degraded" in n for n in notes)


def test_wire_bytes_band_is_lower_is_better(tmp_path):
    lg = Ledger(str(tmp_path / "lg"))
    for i, v in enumerate([21000.0, 21100.0, 20900.0, 21050.0]):
        lg.record("fleet", "wire_bytes", v, unit="B",
                  structure_hash="fleet_w3", platform="cpu",
                  host_load=0.2, git_rev=None, ts_unix=1000.0 + i)
    baseline = gate.build_baseline(lg.read_all())
    bloat = lg.record("fleet", "wire_bytes", 42000.0, unit="B",
                      structure_hash="fleet_w3", platform="cpu",
                      host_load=0.2, git_rev=None, ts_unix=2000.0)
    failures, _ = gate.check_records([bloat], baseline)
    assert any("perf regression" in f for f in failures)
    fine = lg.record("fleet", "wire_bytes", 21010.0, unit="B",
                     structure_hash="fleet_w3", platform="cpu",
                     host_load=0.2, git_rev=None, ts_unix=2001.0)
    failures, _ = gate.check_records([fine], baseline)
    assert failures == []


# ---------------------------------------------------------------------------
# In-process fleet end to end
# ---------------------------------------------------------------------------

def _start_worker(worker_id, obs_dir):
    from arrow_matrix_tpu.fleet.worker import FleetWorker, serve_worker

    worker = FleetWorker(worker_id, vertices=64, width=16, seed=5,
                         obs_dir=obs_dir)
    ready = threading.Event()
    box = {}

    def announce(port):
        box["port"] = port
        ready.set()

    threading.Thread(target=serve_worker, args=(worker,),
                     kwargs={"port": 0, "announce": announce},
                     daemon=True).start()
    assert ready.wait(120), f"{worker_id} never bound"
    return worker, box["port"]


def test_fleet_trace_merges_with_shared_trace_ids(tmp_path,
                                                  fresh_registry):
    from arrow_matrix_tpu.fleet.health import HealthMonitor
    from arrow_matrix_tpu.fleet.router import FleetRouter, WorkerHandle
    from arrow_matrix_tpu.serve.loadgen import synthetic_trace

    run_dir = str(tmp_path)
    workers, handles = [], []
    for wid in ("w0", "w1"):
        w, port = _start_worker(wid, str(tmp_path / wid))
        workers.append(w)
        handles.append(WorkerHandle(wid, "127.0.0.1", port))
    router = FleetRouter(
        handles=handles,
        health=HealthMonitor(timeout_s=5.0, max_failures=3))
    try:
        trace = synthetic_trace(router.n_rows, tenants=2, requests=3,
                                k=2, iterations=1, seed=9)
        tickets = [router.submit(r) for r in trace]
        router.drain(timeout_s=180)
        assert [t.status for t in tickets] == ["completed"] * 3
        report = router.fleet_summary()
        xray.save_router_trace(router.tracer, run_dir)
    finally:
        router.shutdown()
        for w in workers:
            try:
                w.close()
            except Exception:
                pass

    # Router-side wire ledger: per-frame records sum EXACTLY to the
    # totals (byte conservation at the accounting layer).
    totals = report["wire"]["totals"]
    frames = report["wire"]["frames"]
    assert totals["frames"] == 2 * len(frames) > 0
    assert sum(f["bytes_out"] for f in frames) == totals["bytes_out"]
    assert sum(f["bytes_in"] for f in frames) == totals["bytes_in"]
    # A ping-measured clock offset per worker, sane for one host.
    offs = report["clock_offsets_ns"]
    assert set(offs) == {"w0", "w1"}
    assert all(abs(o["offset_ns"]) < 1e9 for o in offs.values())

    merged = xray.merge_run_dir(run_dir, report=report)
    procs = {p["process"] for p in merged["xray"]["processes"]}
    assert procs == {"router", "w0", "w1"}
    assert merged["xray"]["truncated"] == []
    events = [e for e in merged["traceEvents"] if e.get("ph") == "X"]
    pid_of = {p["process"]: p["pid"]
              for p in merged["xray"]["processes"]}
    for t in tickets:
        rid = t.request.request_id
        trace_id = (t.trace or {}).get("trace_id")
        assert trace_id
        mine = [e for e in events if rid in
                str(e["args"].get("request_id", "")).split("+")]
        pids = {e["pid"] for e in mine}
        # the span tree closes across the wire: router AND one worker
        assert pid_of["router"] in pids and len(pids) >= 2
        remote = [e for e in mine if e["pid"] != pid_of["router"]]
        assert any(trace_id in
                   str(e["args"].get("trace_id", "")).split("+")
                   for e in remote)
    # and the decomposition covers every request with nonzero compute
    cp = xray.critical_path(merged)
    assert set(cp["requests"]) == {t.request.request_id
                                   for t in tickets}
    for rec in cp["requests"].values():
        assert rec["segments"]["compute"] > 0.0
