"""Planted RC3 violation: a user callback invoked under the lock.

``on_burn`` is a declared callback — user code that may re-enter this
class (the SloWatchdog ladder does exactly that) or block
indefinitely.  ``trip`` fires it while still holding ``_lock``:
hold-and-wait on arbitrary user code.  tools/sync_gate.py --fixture
must exit nonzero on this file.
"""

import threading

from arrow_matrix_tpu.sync import guarded_by


@guarded_by("_lock", node="fixture_rc3", attrs=("trips",),
            callbacks=("on_burn",))
class Watchdog:
    def __init__(self, on_burn):
        self._lock = threading.Lock()
        self.on_burn = on_burn
        self.trips = []

    def trip(self, rule):
        with self._lock:
            self.trips.append(rule)
            # BUG: user callback runs inside the critical section.
            self.on_burn(rule)
