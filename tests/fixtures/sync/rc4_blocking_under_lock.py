"""Planted RC4 violation: blocking I/O inside a critical section.

``recv_reply`` holds ``_lock`` across ``socket.recv`` — a stalled
peer wedges every thread that needs the lock, which is how one dead
worker used to freeze a whole router before the health-monitor
probes moved their wire I/O off-lock.  tools/sync_gate.py --fixture
must exit nonzero on this file.
"""

import threading

from arrow_matrix_tpu.sync import guarded_by


@guarded_by("_lock", node="fixture_rc4", attrs=("replies",))
class WireFront:
    def __init__(self, sock):
        self._lock = threading.Lock()
        self.sock = sock
        self.replies = []

    def recv_reply(self):
        with self._lock:
            # BUG: unbounded socket read while holding the lock.
            data = self.sock.recv(4096)
            self.replies.append(data)
        return data
