"""Planted RC1 violation: a guarded attribute mutated off-lock.

``pending`` and ``completed`` are declared guarded by ``_lock``, but
``finish`` bumps ``completed`` without taking it — the lost-update
race the HealthMonitor fix closed for real.  tools/sync_gate.py
--fixture must exit nonzero on this file.
"""

import threading

from arrow_matrix_tpu.sync import guarded_by


@guarded_by("_lock", node="fixture_rc1",
            attrs=("pending", "completed"))
class RequestLog:
    def __init__(self):
        self._lock = threading.Lock()
        self.pending = []
        self.completed = 0

    def add(self, req):
        with self._lock:
            self.pending.append(req)

    def finish(self, req):
        # BUG: read-modify-write of a guarded counter with no lock.
        self.completed += 1
        with self._lock:
            if req in self.pending:
                self.pending.remove(req)
