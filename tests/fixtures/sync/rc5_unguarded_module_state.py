"""Planted RC5 violation: mutable module state shared across threads.

``_RESULTS`` is a module-level dict mutated by ``worker`` (a Thread
target — one entry point) and by ``harvest`` (registered with atexit
— a second entry point) with no lock anywhere.  tools/sync_gate.py
--fixture must exit nonzero on this file.
"""

import atexit
import threading

_RESULTS = {}


def worker(job_id):
    _RESULTS[job_id] = "done"


def harvest():
    _RESULTS.clear()


def start(job_id):
    t = threading.Thread(target=worker, args=(job_id,), daemon=True)
    t.start()
    return t


atexit.register(harvest)
