"""Planted RC2 violation: two locks acquired in both orders.

``flush`` holds the ring lock while taking the index lock;
``compact`` holds the index lock while taking the ring lock.  Two
threads running one of each deadlock — the acquisition graph has the
cycle ring_lock -> index_lock -> ring_lock.  tools/sync_gate.py
--fixture must exit nonzero on this file.
"""

import threading

RING_LOCK = threading.Lock()
INDEX_LOCK = threading.Lock()

RING = []
INDEX = {}


def flush():
    with RING_LOCK:
        with INDEX_LOCK:
            INDEX.clear()
            RING.clear()


def compact():
    with INDEX_LOCK:
        with RING_LOCK:
            del RING[: len(RING) // 2]
