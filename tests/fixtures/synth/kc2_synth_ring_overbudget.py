"""Planted generated-program violation for tools/kernel_gate.py --paths.

This is the tail tier of a REAL graft-synth program (synthesized from
the ba/n=96/m=3/width=16/seed=5 degree ladder, k=16) with one knob
corrupted: the DMA ring deepened from 4 to 4096 slabs, so the
ring-proportional scratch (64 x 131072 f32 = 32 MiB) exceeds the
kernel's declared 8 MiB VMEM budget.  Row-block/wave/coverage all
hold, so exactly KC2 fires -- the same prune reason
certify_candidate_opts gives an over-deep synthesized schedule before
it ever races.
"""

METAS = [
    {   'kernel': 'kc2_synth_ring_overbudget',
        'kind': 'sell_stream',
        'grid': [['i', 2]],
        'out': {   'shape': [16, 128],
                   'block': [8, 128],
                   'index': ['i', 0],
                   'itemsize': 4},
        'ins': [   {   'name': 'cols_vmem',
                       'shape': [8, 128],
                       'block': [8, 64],
                       'index': [0, 'i'],
                       'space': 'vmem',
                       'itemsize': 4},
                   {   'name': 'weights',
                       'shape': [1, 128],
                       'block': [1, 64],
                       'index': [0, 'i'],
                       'space': 'vmem',
                       'itemsize': 4},
                   {   'name': 'x_packed',
                       'shape': [12, 128],
                       'block': None,
                       'index': None,
                       'space': 'any',
                       'itemsize': 4}],
        'smem': {   'name': 'cols_prefetch',
                    'bytes': 4096,
                    'budget': 8192,
                    'single_block': False},
        'scratch': [   {   'name': 'dma_scratch',
                           'shape': [64, 131072],
                           'itemsize': 4}],
        'sems': {'shape': [4096, 8]},
        'vmem_budget': 8388608,
        'accum_dtype': 'f32',
        'carriage_dtype': 'f32',
        'revisit_axes': [],
        'stream': {   'ring': 4096,
                      'wave': 8,
                      'n_waves': 8,
                      'row_block': 64,
                      'granule': 8,
                      'slab': 128,
                      'm_t': 8,
                      'lines': 12,
                      'table_rows': 96}},
]
