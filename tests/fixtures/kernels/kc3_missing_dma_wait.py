"""Planted KC3 violation: the streaming kernel issues async copies
(.start()) but never waits on any of them — the accumulate reads the
scratch slab while the DMA engine may still be writing it.  Exactly
KC3 fires (the accumulator stays f32, budgets and indices are not
declared here).
"""


def kernel_stream_broken(cols_smem, x_any, out_ref, scratch, sems,
                         pltpu, jax, jnp, pl, wave, ring, n_waves):
    def copy(j, w, r):
        rr = w * wave + r
        g = cols_smem[j, rr]
        return pltpu.make_async_copy(
            x_any.at[g], scratch.at[rr], sems.at[w % ring, r])

    def issue(j, w):
        jax.lax.fori_loop(
            0, wave, lambda r, _: (copy(j, w, r).start(), 0)[1], 0)

    def slot_body(j, acc):
        for p in range(min(ring - 1, n_waves)):
            issue(j, p)

        def wave_body(w, carry):
            @pl.when(w + ring - 1 < n_waves)
            def _():
                issue(j, w + ring - 1)
            # BROKEN: no copy(...).wait() anywhere — the scratch read
            # below races the in-flight DMA.
            return carry

        jax.lax.fori_loop(0, n_waves, wave_body, 0)
        return acc + scratch[...].astype(jnp.float32).sum()

    out_ref[...] = slot_body(0, 0)
