"""Planted KC4 violation: the kernel's accumulator is initialized at
bf16, so every partial sum rounds to 8 mantissa bits — the carriage
may narrow, the accumulator may not (H4' at the kernel level).
Exactly KC4 fires: both the declared META accum dtype and the
in-source ``jnp.zeros(dtype=jnp.bfloat16)`` are narrow.
"""

META = {
    "kernel": "kc4_bf16_accumulator", "kind": "sell_vectorized",
    "grid": [["i", 2]],
    "out": {"shape": [32, 128], "block": [16, 128],
            "index": ["i", 0], "itemsize": 4},
    "ins": [
        {"name": "cols_vmem", "shape": [8, 256], "block": [8, 128],
         "index": [0, "i"], "space": "vmem", "itemsize": 4},
        {"name": "weights", "shape": [1, 256], "block": [1, 128],
         "index": [0, "i"], "space": "vmem", "itemsize": 4},
        {"name": "x_packed", "shape": [512, 128], "block": None,
         "index": None, "space": "any", "itemsize": 4},
    ],
    "smem": {"name": "cols_prefetch", "bytes": 8192,
             "budget": 1048576, "single_block": False},
    "scratch": [],
    "sems": None,
    "vmem_budget": 8388608,
    "accum_dtype": "bf16",
    "carriage_dtype": "bf16",
    "revisit_axes": [],
}


def kernel_vectorized_broken(cols_vmem, x_any, out_ref, jnp, m_t):
    # BROKEN: bf16 accumulator — every slot's contribution is rounded
    # before the next one lands.
    acc = jnp.zeros((16, 128), dtype=jnp.bfloat16)
    for j in range(m_t):
        acc = acc + x_any[j].astype(jnp.bfloat16)
    out_ref[...] = acc
