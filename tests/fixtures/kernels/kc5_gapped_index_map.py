"""Planted KC5 violation: the output holds 4 row blocks of 32 packed
rows but the grid only walks 3 — output block (3, 0) is never
written and serves stale memory.  All indices stay in bounds (3 x 32
<= 128) and budgets hold, so exactly KC5 fires.
"""

META = {
    "kernel": "kc5_gapped_index_map", "kind": "sell_stream",
    "grid": [["i", 3]],
    "out": {"shape": [128, 128], "block": [32, 128],
            "index": ["i", 0], "itemsize": 4},
    "ins": [
        {"name": "cols_vmem", "shape": [8, 1024], "block": [8, 256],
         "index": [0, "i"], "space": "vmem", "itemsize": 4},
        {"name": "weights", "shape": [1, 1024], "block": [1, 256],
         "index": [0, "i"], "space": "vmem", "itemsize": 4},
        {"name": "x_packed", "shape": [512, 128], "block": None,
         "index": None, "space": "any", "itemsize": 4},
    ],
    "smem": {"name": "cols_prefetch", "bytes": 24576,
             "budget": 1048576, "single_block": False},
    "scratch": [{"name": "dma_scratch", "shape": [256, 128],
                 "itemsize": 4}],
    "sems": {"shape": [2, 16]},
    "vmem_budget": 8388608,
    "accum_dtype": "f32",
    "carriage_dtype": "f32",
    "revisit_axes": [],
    "stream": {"ring": 2, "wave": 16, "n_waves": 16,
               "row_block": 256, "granule": 8, "slab": 768,
               "m_t": 8, "lines": 512, "table_rows": 4096},
}
