"""Planted KC1 violation: the cols VMEM operand is indexed past its
extent.  The grid walks 3 row blocks of 128 (slab = 384) but the
column array only holds 256 rows — the third program's slot indices
read out of bounds.  Everything else (output tiling, budgets, ring
discipline, coverage) is consistent, so exactly KC1 fires.
"""

META = {
    "kernel": "kc1_oob_slot_index", "kind": "sell_stream",
    "grid": [["i", 3]],
    "out": {"shape": [48, 128], "block": [16, 128],
            "index": ["i", 0], "itemsize": 4},
    "ins": [
        {"name": "cols_vmem", "shape": [8, 256], "block": [8, 128],
         "index": [0, "i"], "space": "vmem", "itemsize": 4},
        {"name": "weights", "shape": [1, 384], "block": [1, 128],
         "index": [0, "i"], "space": "vmem", "itemsize": 4},
        {"name": "x_packed", "shape": [512, 128], "block": None,
         "index": None, "space": "any", "itemsize": 4},
    ],
    "smem": {"name": "cols_prefetch", "bytes": 12288,
             "budget": 1048576, "single_block": False},
    "scratch": [{"name": "dma_scratch", "shape": [128, 128],
                 "itemsize": 4}],
    "sems": {"shape": [2, 16]},
    "vmem_budget": 8388608,
    "accum_dtype": "f32",
    "carriage_dtype": "f32",
    "revisit_axes": [],
    "stream": {"ring": 2, "wave": 16, "n_waves": 8,
               "row_block": 128, "granule": 8, "slab": 384,
               "m_t": 8, "lines": 512, "table_rows": 4096},
}
