"""Planted KC2 violation: the DMA scratch slab alone (128 x 8192 f32
= 4 MiB) exceeds the kernel's declared 2 MiB VMEM budget.  Indexing,
ring discipline, and coverage all hold, so exactly KC2 fires.
"""

META = {
    "kernel": "kc2_overbudget_scratch", "kind": "sell_stream",
    "grid": [["i", 2]],
    "out": {"shape": [32, 8192], "block": [16, 8192],
            "index": ["i", 0], "itemsize": 4},
    "ins": [
        {"name": "cols_vmem", "shape": [8, 256], "block": [8, 128],
         "index": [0, "i"], "space": "vmem", "itemsize": 4},
        {"name": "weights", "shape": [1, 256], "block": [1, 128],
         "index": [0, "i"], "space": "vmem", "itemsize": 4},
        {"name": "x_packed", "shape": [512, 8192], "block": None,
         "index": None, "space": "any", "itemsize": 4},
    ],
    "smem": {"name": "cols_prefetch", "bytes": 8192,
             "budget": 1048576, "single_block": False},
    "scratch": [{"name": "dma_scratch", "shape": [128, 8192],
                 "itemsize": 4}],
    "sems": {"shape": [2, 16]},
    "vmem_budget": 2097152,
    "accum_dtype": "f32",
    "carriage_dtype": "f32",
    "revisit_axes": [],
    "stream": {"ring": 2, "wave": 16, "n_waves": 8,
               "row_block": 128, "granule": 8, "slab": 256,
               "m_t": 8, "lines": 512, "table_rows": 4096},
}
