"""Iteration-state checkpoint/resume (utils/checkpoint.py): runtime
state persists beyond the reference's artifact-only resume point."""

import numpy as np
import pytest

from arrow_matrix_tpu.decomposition import arrow_decomposition
from arrow_matrix_tpu.parallel import MultiLevelArrow, make_mesh
from arrow_matrix_tpu.utils import barabasi_albert, random_dense
from arrow_matrix_tpu.utils.checkpoint import load_state, save_state


@pytest.fixture()
def small(tmp_path):
    a = barabasi_albert(256, 4, seed=3)
    levels = arrow_decomposition(a, 32, max_levels=3, block_diagonal=True,
                                 seed=1)
    return a, levels, tmp_path


def test_checkpoint_roundtrip_sharded(small):
    _, levels, tmp = small
    ml = MultiLevelArrow(levels, 32, mesh=make_mesh((8,), ("blocks",)),
                         fmt="ell")
    x = ml.set_features(random_dense(256, 8, seed=2))
    x3 = ml.run(x, 3)
    save_state(str(tmp / "ck"), x3, 3)
    restored = load_state(str(tmp / "ck"), like=x)
    assert restored is not None
    xr, step = restored
    assert step == 3
    np.testing.assert_array_equal(np.asarray(xr), np.asarray(x3))
    assert xr.sharding == x.sharding     # restored sharded, not host


def test_checkpoint_roundtrip_fold(small):
    _, levels, tmp = small
    ml = MultiLevelArrow(levels, 32, mesh=None, fmt="fold")
    x = ml.set_features(random_dense(256, 8, seed=2))
    x2 = ml.run(x, 2)
    save_state(str(tmp / "ckf"), x2, 2)
    xr, step = load_state(str(tmp / "ckf"), like=x)
    np.testing.assert_array_equal(np.asarray(xr), np.asarray(x2))


def test_checkpoint_shape_mismatch_raises(small):
    _, levels, tmp = small
    ml = MultiLevelArrow(levels, 32, mesh=None, fmt="ell")
    x = ml.set_features(random_dense(256, 8, seed=2))
    save_state(str(tmp / "ckm"), x, 1)
    wrong = ml.set_features(random_dense(256, 4, seed=2))
    with pytest.raises(ValueError, match="shape"):
        load_state(str(tmp / "ckm"), like=wrong)


def test_load_state_absent_returns_none(tmp_path):
    assert load_state(str(tmp_path / "nope")) is None


def test_cli_carry_checkpoint_resume(tmp_path, monkeypatch):
    """CLI: a carried run checkpoints, and a rerun resumes mid-stream
    producing the same final state as one uninterrupted run."""
    from arrow_matrix_tpu.cli import spmm_arrow

    monkeypatch.chdir(tmp_path)
    common = ["--vertices", "300", "--width", "32", "--features", "4",
              "--device", "cpu", "--carry", "true",
              "--seed", "11", "--logdir", str(tmp_path / "logs")]
    # Uninterrupted 6-iteration run (no checkpoint interference).
    rc = spmm_arrow.main(common + ["--iterations", "6"])
    assert rc == 0
    # Run 4 iterations with checkpointing every 2, then resume to 6.
    ck = str(tmp_path / "ck")
    rc = spmm_arrow.main(common + ["--iterations", "4",
                                   "--checkpoint", ck,
                                   "--checkpoint_every", "2"])
    assert rc == 0
    rc = spmm_arrow.main(common + ["--iterations", "6",
                                   "--checkpoint", ck,
                                   "--checkpoint_every", "2",
                                   "--validate", "true"])
    assert rc == 0
    # The resumed run's final state must be bit-identical to an
    # uninterrupted checkpointing run of the same 6 iterations.
    ck2 = str(tmp_path / "ck2")
    rc = spmm_arrow.main(common + ["--iterations", "6",
                                   "--checkpoint", ck2,
                                   "--checkpoint_every", "2"])
    assert rc == 0
    xa, sa = load_state(ck)
    xb, sb = load_state(ck2)
    assert sa == sb == 6
    assert np.asarray(xa).tobytes() == np.asarray(xb).tobytes()


def test_cli_checkpoint_requires_carry(tmp_path, monkeypatch):
    from arrow_matrix_tpu.cli import spmm_arrow

    monkeypatch.chdir(tmp_path)
    with pytest.raises(SystemExit, match="carry"):
        spmm_arrow.main(["--vertices", "200", "--width", "32",
                         "--device", "cpu",
                         "--checkpoint", str(tmp_path / "x")])


def test_checkpoint_roundtrip_sell_multilevel(small):
    """Feature-major sharded carriage (SellMultiLevel) through the
    checkpoint: restore lands on the executor's sharding."""
    from arrow_matrix_tpu.parallel.sell_slim import SellMultiLevel

    _, levels, tmp = small
    sm = SellMultiLevel(levels, 32, make_mesh((8,), ("blocks",)))
    x = sm.set_features(random_dense(256, 8, seed=2))
    x2 = sm.run(x, 2)
    save_state(str(tmp / "cks"), x2, 2)
    xr, step = load_state(str(tmp / "cks"), like=x)
    assert step == 2
    np.testing.assert_array_equal(np.asarray(xr), np.asarray(x2))
    assert xr.sharding == x.sharding


def test_checkpoint_roundtrip_sell_space_shared(small):
    """The concurrent-group carriage (K carried orderings on the 2-D
    (lvl, blocks) mesh) through the checkpoint."""
    from arrow_matrix_tpu.parallel import SellSpaceShared

    _, levels, tmp = small
    if len(levels) < 2:
        pytest.skip("need >=2 levels for a lvl axis")
    sp = SellSpaceShared(levels[:2], 32,
                         make_mesh((2, 4), ("lvl", "blocks")))
    x = sp.set_features(random_dense(256, 8, seed=2))
    x2 = sp.run(x, 2)
    save_state(str(tmp / "cksp"), x2, 2)
    xr, step = load_state(str(tmp / "cksp"), like=x)
    assert step == 2
    np.testing.assert_array_equal(np.asarray(xr), np.asarray(x2))
    assert xr.sharding == x.sharding


def test_checkpoint_layout_mismatch_raises(tmp_path):
    """A checkpoint tagged with one carriage layout must refuse to
    resume under another — silently permuted rows are worse than a
    crash."""
    x = np.arange(12, dtype=np.float32).reshape(4, 3)
    save_state(str(tmp_path / "ckl"), x, 2, layout="fold/ell/f32")
    with pytest.raises(RuntimeError, match="layout"):
        load_state(str(tmp_path / "ckl"), layout="sell/slim/f32")
    # matching layout (and layout-agnostic load) both succeed
    xr, step = load_state(str(tmp_path / "ckl"), layout="fold/ell/f32")
    assert step == 2
    xr, step = load_state(str(tmp_path / "ckl"))
    np.testing.assert_array_equal(np.asarray(xr), x)


def test_checkpoint_layout_mismatch_npz_fallback(tmp_path, monkeypatch):
    """Same layout guard on the npz fallback path (no orbax)."""
    from arrow_matrix_tpu.utils import checkpoint as ckpt

    monkeypatch.setattr(ckpt, "_orbax", lambda: None)
    x = np.arange(12, dtype=np.float32).reshape(4, 3)
    ckpt.save_state(str(tmp_path / "ckn"), x, 3, layout="petsc/1d_sliced")
    with pytest.raises(RuntimeError, match="layout"):
        ckpt.load_state(str(tmp_path / "ckn"), layout="15d/c2")
    xr, step = ckpt.load_state(str(tmp_path / "ckn"),
                               layout="petsc/1d_sliced")
    assert step == 3
    np.testing.assert_array_equal(np.asarray(xr), x)


def test_checkpoint_untagged_legacy_npz_tolerated(tmp_path, monkeypatch):
    """A pre-versioning npz checkpoint (no version/layout fields) still
    loads; a checkpoint from a NEWER format version fails loudly."""
    from arrow_matrix_tpu.utils import checkpoint as ckpt

    monkeypatch.setattr(ckpt, "_orbax", lambda: None)
    x = np.ones((4, 2), dtype=np.float32)
    np.savez(str(tmp_path / "legacy.npz"), x=x, step=np.int64(5))
    xr, step = ckpt.load_state(str(tmp_path / "legacy"),
                               layout="fold/ell/f32")
    assert step == 5
    np.savez(str(tmp_path / "future.npz"), x=x, step=np.int64(5),
             version=np.int64(ckpt.CHECKPOINT_VERSION + 1),
             layout=np.str_(""))
    with pytest.raises(RuntimeError, match="newer"):
        ckpt.load_state(str(tmp_path / "future"))


def test_load_state_emits_resumed_flight_event(tmp_path):
    from arrow_matrix_tpu.obs import flight

    x = np.ones((3, 2), dtype=np.float32)
    save_state(str(tmp_path / "ckev"), x, 7, layout="t")
    rec = flight.FlightRecorder(str(tmp_path / "flight.json"))
    old = flight.get_recorder()
    flight.set_recorder(rec)
    try:
        load_state(str(tmp_path / "ckev"))
    finally:
        flight.set_recorder(old)
    ev = [e for e in rec.events if e.get("name") == "resumed"]
    assert ev and ev[0]["kind"] == "heal"
    assert ev[0]["data"]["step"] == 7
