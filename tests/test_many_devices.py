"""30-"rank" parity test (subprocess): the reference's largest test runs
mpiexec -n 30 (reference tests/test_arrowmpi.py:11-17, run_tests.sh);
the JAX device count is fixed per process, so a fresh interpreter pins
a 30-device virtual CPU pool and drives the distributed paths there."""

import os

import pytest
import subprocess
import sys

_SCRIPT = r"""
import os
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=30"
from arrow_matrix_tpu.utils.platform import force_cpu_devices
force_cpu_devices(30)
import numpy as np
from arrow_matrix_tpu.decomposition import arrow_decomposition
from arrow_matrix_tpu.decomposition.decompose import decomposition_spmm
from arrow_matrix_tpu.parallel import MultiLevelArrow, SellMultiLevel, make_mesh
from arrow_matrix_tpu.utils import barabasi_albert, random_dense

n, width = 1200, 32
a = barabasi_albert(n, 3, seed=30)
levels = arrow_decomposition(a, width, max_levels=3, block_diagonal=True,
                             seed=1)
x = random_dense(n, 4, seed=2)
want = decomposition_spmm(levels, x)
mesh = make_mesh((30,), ("blocks",))
for build in (lambda: MultiLevelArrow(levels, width, mesh=mesh, fmt="ell"),
              lambda: MultiLevelArrow(levels, width, mesh=mesh, fmt="ell",
                                      routing="a2a"),
              lambda: SellMultiLevel(levels, width, mesh, routing="a2a")):
    ml = build()
    got = ml.gather_result(ml.step(ml.set_features(x)))
    err = np.linalg.norm(got - want) / np.linalg.norm(want)
    assert err < 1e-5, err

# Concurrent groups at 30 "ranks": K level groups x 30/K devices
# (non-power-of-two group width, the reference's odd-rank shapes).
# Loud divisibility guard: if the decomposition's level count ever
# stops dividing 30, this coverage must not vanish silently.
from arrow_matrix_tpu.parallel import SellSpaceShared
K = len(levels)
assert 30 % K == 0, (
    f"level count {K} no longer divides 30 - pick a config whose "
    f"K does, or the concurrent-group parity coverage is lost")
sp = SellSpaceShared(levels, width,
                     make_mesh((K, 30 // K), ("lvl", "blocks")))
got = sp.gather_result(sp.step(sp.set_features(x)))
err = np.linalg.norm(got - want) / np.linalg.norm(want)
assert err < 1e-5, err
print("OK30")
"""


@pytest.mark.slow
def test_thirty_virtual_devices():
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run([sys.executable, "-c", _SCRIPT],
                          capture_output=True, text=True, timeout=600,
                          env=env, cwd=os.path.dirname(
                              os.path.dirname(os.path.abspath(__file__))))
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "OK30" in proc.stdout
