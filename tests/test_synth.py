"""graft-synth tests (arrow_matrix_tpu/tune/synth.py): per-level
schedule synthesis from the degree-ladder fingerprint, KC1-KC5
certification of generated schedules (uncertifiable ones pruned with
``kcert:`` reasons before any child spawns), TunePlan schedule
persistence, f32 bit-identity of the scheduled executor vs the golden
fold path, the fused int8 (q, scale) carriage, the synth search with
its pure-cache-hit purity, the committed program store + lazy registry
round trip, and the planted generated-program fixture that must fail
``tools/kernel_gate.py --paths``.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from arrow_matrix_tpu.analysis import kernels as kcert
from arrow_matrix_tpu.decomposition import arrow_decomposition
from arrow_matrix_tpu.ops.kernel_contract import (
    builtin_kernels,
    registered_kernels,
    unregister_kernel,
)
from arrow_matrix_tpu.tune import synth
from arrow_matrix_tpu.tune.fingerprint import (
    structure_fingerprint,
    fingerprint_hash,
)
from arrow_matrix_tpu.tune.plan import TunePlan, load_plan, save_plans
from arrow_matrix_tpu.tune.space import enumerate_candidates
from arrow_matrix_tpu.utils import barabasi_albert, random_dense

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SYNTH_FIXTURE = os.path.join(REPO, "tests", "fixtures", "synth",
                             "kc2_synth_ring_overbudget.py")

#: A hand-built 4-tier fingerprint: zero-degree prefix + one tier per
#: ladder family — the smallest structure that exercises every branch
#: of the synthesis policy.
LADDER_FP = {
    "n": 96, "binary": True, "total_rows": 120,
    "ladder": {
        "rows": [24, 64, 24, 8],
        "nnz": [0, 180, 300, 400],
        "slots": [0, 256, 384, 512],
        "slot_width": [0, 4, 16, 80],
    },
}


def _levels(n=96, width=16, seed=3, m=3, max_levels=4):
    a = barabasi_albert(n, m, seed=seed)
    return arrow_decomposition(a, width, max_levels=max_levels,
                               block_diagonal=True, seed=seed)


# ---------------------------------------------------------------------------
# Synthesis policy
# ---------------------------------------------------------------------------

def test_synthesis_policy_families_and_knobs():
    sched = synth.synthesize_schedule(LADDER_FP)
    # The zero-width prefix launches no kernel, so no entry.
    assert [e["tier"] for e in sched] == [1, 2, 3]
    assert [e["family"] for e in sched] == ["tail", "mid", "head"]
    by_fam = {e["family"]: e for e in sched}
    # Head levels dense-ish: wide row block, shallow ring; tail levels
    # scatter-ish: narrow row block, deep ring (ISSUE 20's tentpole
    # policy, FAMILY_POLICY).
    assert by_fam["head"]["row_block"] > by_fam["tail"]["row_block"]
    assert by_fam["tail"]["ring"] > by_fam["head"]["ring"]
    # Tail/mid slabs are budget-bounded; head rides the full default
    # scalar-prefetch budget (no per-tier override).
    assert "smem_cols_budget" in by_fam["tail"]
    assert "smem_cols_budget" not in by_fam["head"]
    # Deterministic: the store and the cache key on this.
    assert sched == synth.synthesize_schedule(LADDER_FP)


def test_synthesis_empty_ladder_and_bad_policy():
    empty = {"n": 8, "binary": True, "total_rows": 8,
             "ladder": {"rows": [8], "nnz": [0], "slots": [0],
                        "slot_width": [0]}}
    assert synth.synthesize_schedule(empty) == []
    assert synth.synth_candidates(empty) == []
    with pytest.raises(ValueError, match="carriage policy"):
        synth.synthesize_schedule(LADDER_FP, carriage_policy="fp8")


def test_mixed_policy_narrows_head_mid_keeps_tail_exact():
    mixed = synth.synthesize_schedule(LADDER_FP,
                                      carriage_policy="mixed")
    carr = {e["family"]: e["carriage"] for e in mixed}
    assert carr == {"tail": "f32", "mid": "bf16", "head": "bf16"}


def test_synth_candidates_traffic_classes():
    exact = {c.name: c for c in synth.synth_candidates(LADDER_FP)}
    assert exact["synth_ladder"].eligible is True
    assert all(e["carriage"] == "f32" for e in
               exact["synth_ladder"].kernel_opts["schedule"])
    # The mixed-carriage program can never win the f32 bit-identity
    # race — approx class only, like pallas_sell_bf16.
    assert exact["synth_ladder_mixed"].eligible is False
    approx = {c.name: c for c in synth.synth_candidates(
        LADDER_FP, traffic_class="approx")}
    assert approx["synth_ladder_mixed"].eligible is True


# ---------------------------------------------------------------------------
# Certification: generated schedules through KC1-KC5
# ---------------------------------------------------------------------------

def test_synthesized_schedule_certifies():
    sched = synth.synthesize_schedule(LADDER_FP)
    assert kcert.certify_candidate_opts({"schedule": sched}, 16) is None
    assert kcert.certify_candidate_opts({"schedule": sched}, 16,
                                        interpret=True) is None


def test_bad_schedule_pruned_with_kcert_tier_reason():
    sched = synth.synthesize_schedule(LADDER_FP)
    bad = [dict(sched[0], ring=0)]
    why = kcert.certify_candidate_opts({"schedule": bad}, 16)
    assert why is not None and why.startswith("kcert: tier 1")
    # Per-tier int8 carriage is not schedulable (the (q, scale) pair
    # is a whole-call transform) — pruned, not silently cast.
    bad = [dict(sched[0], carriage="int8")]
    why = kcert.certify_candidate_opts({"schedule": bad}, 16)
    assert why is not None and "int8" in why
    # A malformed entry (no tier key) is a loud kcert reason too.
    why = kcert.certify_candidate_opts(
        {"schedule": [{"ring": 2}]}, 16)
    assert why is not None and why.startswith("kcert:")


def test_enumeration_screens_generated_candidates():
    from arrow_matrix_tpu.tune.space import Candidate

    cands, pruned = enumerate_candidates(
        LADDER_FP, 16, platform="cpu",
        extra=synth.synth_candidates(LADDER_FP))
    assert "synth_ladder" in {c.name for c in cands}
    bad = Candidate("synth_bad",
                    build={"kernel": "pallas_sell"},
                    kernel_opts={"schedule": [dict(
                        synth.synthesize_schedule(LADDER_FP)[0],
                        ring=0)]})
    cands, pruned = enumerate_candidates(LADDER_FP, 16,
                                         platform="cpu", extra=[bad])
    assert "synth_bad" not in {c.name for c in cands}
    assert pruned["synth_bad"].startswith("kcert:")


def test_planted_synth_fixture_fires_exactly_kc2():
    fired = {f.rule for f in kcert.certify_paths([SYNTH_FIXTURE])}
    assert fired == {"KC2"}


def test_planted_synth_fixture_fails_kernel_gate_paths():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "kernel_gate.py"),
         "--paths", SYNTH_FIXTURE],
        capture_output=True, text=True, timeout=120, cwd=REPO)
    assert proc.returncode != 0, proc.stdout + proc.stderr
    assert "KC2" in proc.stdout


# ---------------------------------------------------------------------------
# TunePlan persistence of schedules
# ---------------------------------------------------------------------------

def test_plan_schedule_round_trip(tmp_path):
    sched = synth.synthesize_schedule(LADDER_FP)
    plan = TunePlan(structure_hash="h", k=16, candidate="synth_ladder",
                    kernel="pallas_sell", schedule=sched)
    assert plan.kernel_opts()["schedule"] == sched
    d = str(tmp_path / "plans")
    save_plans("h", {16: plan}, directory=d)
    got = load_plan("h", 16, d)
    assert got.schedule == sched
    assert got.kernel_opts()["schedule"] == sched
    # Uniform-knob plans keep their shape: no schedule key at all.
    assert "schedule" not in TunePlan(structure_hash="h",
                                      k=16).kernel_opts()


# ---------------------------------------------------------------------------
# Executor semantics: bit-identity + the fused int8 carriage
# ---------------------------------------------------------------------------

def _golden_fold(levels, width, x_host):
    from arrow_matrix_tpu.parallel import MultiLevelArrow

    multi = MultiLevelArrow(levels, width, mesh=None, fmt="fold")
    x = multi.set_features(x_host)
    return np.asarray(multi.gather_result(multi.step(x)),
                      dtype=np.float32)


def test_scheduled_executor_bit_identical_to_uniform_pallas():
    from arrow_matrix_tpu.parallel import MultiLevelArrow

    levels, width = _levels(), 16
    fp = structure_fingerprint(levels, width, np.float32)
    sched = synth.synthesize_schedule(fp)
    assert sched, "live BA ladder must synthesize a schedule"

    base = MultiLevelArrow(levels, width, mesh=None, fmt="fold",
                           kernel="pallas_sell",
                           kernel_opts={"interpret": True})
    x_host = random_dense(base.n, 16, seed=7)

    def run(m):
        return np.asarray(m.gather_result(
            m.step(m.set_features(x_host))), dtype=np.float32)

    scheduled = MultiLevelArrow(
        levels, width, mesh=None, fmt="fold", kernel="pallas_sell",
        kernel_opts={"interpret": True, "schedule": sched})
    got = run(scheduled)
    # The all-f32 schedule's numeric claim: per-tier knobs repartition
    # slabs, the per-row accumulation order is unchanged — BITWISE
    # equal to the uniform-knob pallas path.  (Vs the XLA golden fold
    # the pallas gather order differs, so on the cpu-interpret
    # evaluator the race records the honest tolerance-close result.)
    np.testing.assert_array_equal(got, run(base))
    want = _golden_fold(levels, width, x_host)
    gn = float(np.linalg.norm(want.astype(np.float64)))
    rel = float(np.linalg.norm(got.astype(np.float64)
                               - want.astype(np.float64))) / gn
    assert rel < 1e-5, rel


def test_int8_fused_carriage_accuracy_and_dtype():
    from arrow_matrix_tpu.parallel import MultiLevelArrow

    levels, width = _levels(), 16
    multi = MultiLevelArrow(
        levels, width, mesh=None, fmt="fold", kernel="pallas_sell",
        feature_dtype="int8", kernel_opts={"interpret": True})
    x_host = random_dense(multi.n, 16, seed=7)
    got = np.asarray(multi.gather_result(
        multi.step(multi.set_features(x_host))), dtype=np.float32)
    want = _golden_fold(levels, width, x_host)
    # (q, scale) carriage with f32 accumulate: quantization noise only
    # — never bit-identical, always within the int8 class tolerance.
    gn = float(np.linalg.norm(want.astype(np.float64)))
    rel = float(np.linalg.norm(got.astype(np.float64)
                               - want.astype(np.float64))) / gn
    assert 0.0 < rel < 0.05, rel


def test_pallas_sell_int8_candidate_is_approx_class_only():
    for tc, eligible in (("exact", None), ("approx", True)):
        cands, _ = enumerate_candidates(LADDER_FP, 16, platform="tpu",
                                        traffic_class=tc)
        by_name = {c.name: c for c in cands}
        if eligible is None:
            assert "pallas_sell_int8" not in by_name
        else:
            assert by_name["pallas_sell_int8"].eligible is eligible
    # allow_int8 surfaces it in the exact class as a diagnostic.
    cands, _ = enumerate_candidates(LADDER_FP, 16, platform="tpu",
                                    allow_int8=True)
    by_name = {c.name: c for c in cands}
    assert by_name["pallas_sell_int8"].eligible is False


# ---------------------------------------------------------------------------
# The synth search: race, persist, pure cache hit
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def synth_reports(tmp_path_factory):
    """ONE bounded synth search (default + synth_ladder children) plus
    an immediate second search of the unchanged structure, against a
    tmp program store and plan cache."""
    from arrow_matrix_tpu.tune.search import search

    # Pin the lazy registry load to the COMMITTED store before the env
    # override — the one-shot loader must not capture the tmp store.
    registered_kernels()
    d = str(tmp_path_factory.mktemp("synth_search"))
    store = os.path.join(d, "synth_programs.json")
    source = {"kind": "ba", "n": 96, "m": 3, "width": 16, "seed": 3,
              "max_levels": 4}
    saved = {k: os.environ.get(k)
             for k in ("AMT_SYNTH_STORE", "AMT_FLIGHT_DIR")}
    os.environ["AMT_SYNTH_STORE"] = store
    os.environ["AMT_FLIGHT_DIR"] = os.path.join(d, "flight")
    try:
        kwargs = dict(k=16, iters=1, timeout_s=180.0,
                      plan_dir=os.path.join(d, "tune_plans"),
                      run_dir=os.path.join(d, "tune_runs"),
                      ledger_dir=os.path.join(d, "ledger"),
                      restrict=["default", "synth_ladder"],
                      synth=True, quiet=True)
        p1, r1 = search(source, **kwargs)
        p2, r2 = search(source, **kwargs)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        if r1.get("synth_program"):
            unregister_kernel(r1["synth_program"])
    return d, store, (p1, r1), (p2, r2)


def test_synth_search_races_and_persists_program(synth_reports):
    d, store, (p1, r1), _ = synth_reports
    assert p1 is not None and not r1["cache_hit"]
    assert r1["children_spawned"] == 2
    assert "synth_ladder" in r1["results"]
    # The generated schedule raced under the f32 bit-identity win
    # rule.  On cpu-interpret the pallas gather order differs from the
    # XLA golden, so the honest recorded result is tolerance-close —
    # bit_identical is an explicit False, never an error.
    sr = r1["results"]["synth_ladder"]
    assert sr.get("error") is None and sr["ms"] is not None
    assert sr["bit_identical"] in (True, False)
    assert sr["rel_frobenius"] is not None and sr["rel_frobenius"] < 1e-5
    # The surviving program landed in the store, named by structure
    # hash, schedule intact.
    name = r1["synth_program"]
    assert name == synth.program_name(r1["structure_hash"])
    doc = synth.load_store(store)
    assert name in doc["programs"]
    prog = doc["programs"][name]
    assert prog["structure_hash"] == r1["structure_hash"]
    assert prog["schedule"] and prog["summary"]
    # And certifies clean straight off the stored record.
    rec = kcert.certify_entry(synth.entry_from_program(name, prog))
    assert rec["ok"], rec["findings"]


def test_second_synth_search_is_pure_hit_zero_children(synth_reports):
    _, _, (p1, r1), (p2, r2) = synth_reports
    assert r2["cache_hit"] and r2["children_spawned"] == 0
    assert p2.candidate == p1.candidate
    # Purity includes synthesis: a cache hit never re-synthesizes or
    # re-persists (the report carries no program on the hit path).
    assert "synth_program" not in r2


def test_synth_winner_plan_replays_bitwise(synth_reports, monkeypatch):
    from arrow_matrix_tpu.parallel import MultiLevelArrow

    d, _, (p1, r1), _ = synth_reports
    monkeypatch.setenv("AMT_TUNE_PLAN_DIR",
                       os.path.join(d, "tune_plans"))
    levels = _levels()
    tuned = MultiLevelArrow(levels, 16, plan="auto")
    assert tuned.tune_plan is not None
    assert tuned.tune_plan.candidate == p1.candidate
    if p1.candidate == "synth_ladder":
        assert tuned.tune_plan.schedule == p1.schedule
    x_host = random_dense(tuned.n, 16, seed=11)
    got = np.asarray(tuned.gather_result(
        tuned.step(tuned.set_features(x_host))), dtype=np.float32)
    np.testing.assert_array_equal(got, _golden_fold(levels, 16, x_host))


# ---------------------------------------------------------------------------
# Store + registry round trip
# ---------------------------------------------------------------------------

def test_store_version_skew_is_loud(tmp_path):
    p = str(tmp_path / "store.json")
    with open(p, "w", encoding="utf-8") as fh:
        json.dump({"version": 999, "programs": {}}, fh)
    with pytest.raises(ValueError, match="version skew"):
        synth.load_store(p)
    with open(p, "w", encoding="utf-8") as fh:
        json.dump({"nope": 1}, fh)
    with pytest.raises(ValueError, match="not a program store"):
        synth.load_store(p)
    assert synth.load_store(str(tmp_path / "absent.json")) == {
        "version": synth.STORE_VERSION, "programs": {}}


def test_registry_lazy_loads_store_in_fresh_process(tmp_path):
    # persist into a tmp store WITHOUT touching this process's
    # registry state beyond the explicit unregister below.
    fp = LADDER_FP
    h = fingerprint_hash(fp)
    store = str(tmp_path / "store.json")
    name = synth.persist_program(fp, h, 16,
                                 synth.synthesize_schedule(fp),
                                 path=store)
    unregister_kernel(name)
    code = ("import os; "
            "from arrow_matrix_tpu.ops.kernel_contract import "
            "builtin_kernels, registered_kernels; "
            "names = [e.name for e in registered_kernels()]; "
            "print('REG', len(builtin_kernels()), "
            f"{name!r} in names)")
    proc = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=120, cwd=REPO,
        env=dict(os.environ, AMT_SYNTH_STORE=store))
    assert proc.returncode == 0, proc.stderr
    line = [ln for ln in proc.stdout.splitlines()
            if ln.startswith("REG")][-1]
    # Builtins stay 2; the generated program rides the registry via
    # the one-shot lazy store load — host-only, no jax needed.
    assert line == "REG 2 True"


def test_committed_store_programs_certify_clean():
    path = synth.store_path()
    if not os.path.isfile(path):
        pytest.skip("no committed synth store yet")
    doc = synth.load_store(path)
    assert doc["programs"], "committed store must carry >= 1 program"
    names = {e.name for e in registered_kernels()}
    for name, prog in doc["programs"].items():
        assert name in names
        rec = kcert.certify_entry(synth.entry_from_program(name, prog))
        assert rec["ok"], (name, rec["findings"])
