"""CLI smoke tests (in-process; the conftest's 8-device CPU platform is
already pinned, so setup_platform's env pinning is a no-op here).

Mirrors the reference's end-to-end bench test
(reference tests/test_arrowmpi.py:423-436 test_larger_ranks runs
bench_spmm at several widths/features)."""

import os

import numpy as np
import pytest
from scipy import sparse

from arrow_matrix_tpu.cli import arrow_decompose, spmm_15d, spmm_arrow, spmm_petsc
from arrow_matrix_tpu.cli.common import str2bool
from arrow_matrix_tpu.utils.graphs import barabasi_albert


def test_str2bool():
    assert str2bool("yes") and str2bool("True") and str2bool(True)
    assert not str2bool("no") and not str2bool("0")
    with pytest.raises(Exception):
        str2bool("maybe")


def test_arrow_decompose_then_spmm_arrow(tmp_path, monkeypatch):
    a = barabasi_albert(300, 3, seed=1)
    sparse.save_npz(tmp_path / "tiny.npz", a)

    arrow_decompose.main([
        "--dataset_dir", str(tmp_path), "--dataset_name", "tiny.npz",
        "--width", "32", "--levels", "4", "--seed", "0",
    ])
    produced = sorted(os.listdir(tmp_path))
    assert any("_indptr.npy" in p for p in produced)
    assert any("_permutation.npy" in p for p in produced)

    monkeypatch.chdir(tmp_path)
    rc = spmm_arrow.main([
        "--path", str(tmp_path / "tiny"), "--width", "32",
        "--features", "4", "--iterations", "2", "--validate", "true",
        "--device", "cpu", "--logdir", str(tmp_path / "logs"),
    ])
    assert rc == 0
    assert os.path.isdir(tmp_path / "logs")


def test_spmm_arrow_generated_graph(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    rc = spmm_arrow.main([
        "--vertices", "300", "--width", "32", "--features", "4",
        "--iterations", "1", "--validate", "true", "--device", "cpu",
        "--logdir", str(tmp_path / "logs"),
    ])
    assert rc == 0


def test_spmm_15d_random_validates(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    rc = spmm_15d.main([
        "--vertices", "256", "--edges", "1024", "--columns", "4",
        "--iterations", "2", "--validate", "true", "--device", "cpu",
        "--logdir", str(tmp_path / "logs"),
    ])
    assert rc == 0


def test_spmm_petsc_random_validates(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    rc = spmm_petsc.main([
        "--vertices", "256", "--edges", "1024", "--columns", "4",
        "--iterations", "2", "--validate", "true", "--device", "cpu",
        "--logdir", str(tmp_path / "logs"),
    ])
    assert rc == 0


def test_spmm_petsc_dryrun_and_slices(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    # Reference slice-file scheme: {name}.part.P.slice.r.npz.
    a = barabasi_albert(64, 2, seed=3).astype(np.float32)
    p = 4
    bounds = np.linspace(0, 64, p + 1).astype(int)
    for r in range(p):
        sparse.save_npz(tmp_path / f"g.part.{p}.slice.{r}.npz",
                        a[bounds[r]:bounds[r + 1]])
    rc = spmm_petsc.main([
        "--file", str(tmp_path / f"g.part.{p}.slice.0.npz"),
        "--dryrun", "true", "--device", "cpu",
        "--logdir", str(tmp_path / "logs"),
    ])
    assert rc == 0
