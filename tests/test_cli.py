"""CLI smoke tests (in-process; the conftest's 8-device CPU platform is
already pinned, so setup_platform's env pinning is a no-op here).

Mirrors the reference's end-to-end bench test
(reference tests/test_arrowmpi.py:423-436 test_larger_ranks runs
bench_spmm at several widths/features)."""

import os

import numpy as np
import pytest
from scipy import sparse

from arrow_matrix_tpu.cli import arrow_decompose, spmm_15d, spmm_arrow, spmm_petsc
from arrow_matrix_tpu.cli.common import str2bool
from arrow_matrix_tpu.utils.graphs import barabasi_albert


def test_str2bool():
    assert str2bool("yes") and str2bool("True") and str2bool(True)
    assert not str2bool("no") and not str2bool("0")
    with pytest.raises(Exception):
        str2bool("maybe")


def test_arrow_decompose_then_spmm_arrow(tmp_path, monkeypatch):
    a = barabasi_albert(300, 3, seed=1)
    sparse.save_npz(tmp_path / "tiny.npz", a)

    arrow_decompose.main([
        "--dataset_dir", str(tmp_path), "--dataset_name", "tiny.npz",
        "--width", "32", "--levels", "4", "--seed", "0",
    ])
    produced = sorted(os.listdir(tmp_path))
    assert any("_indptr.npy" in p for p in produced)
    assert any("_permutation.npy" in p for p in produced)

    monkeypatch.chdir(tmp_path)
    rc = spmm_arrow.main([
        "--path", str(tmp_path / "tiny"), "--width", "32",
        "--features", "4", "--iterations", "2", "--validate", "true",
        "--device", "cpu", "--logdir", str(tmp_path / "logs"),
    ])
    assert rc == 0
    assert os.path.isdir(tmp_path / "logs")


def test_spmm_arrow_generated_graph(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    rc = spmm_arrow.main([
        "--vertices", "300", "--width", "32", "--features", "4",
        "--iterations", "1", "--validate", "true", "--device", "cpu",
        "--logdir", str(tmp_path / "logs"),
    ])
    assert rc == 0


def test_spmm_15d_random_validates(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    rc = spmm_15d.main([
        "--vertices", "256", "--edges", "1024", "--columns", "4",
        "--iterations", "2", "--validate", "true", "--device", "cpu",
        "--logdir", str(tmp_path / "logs"),
    ])
    assert rc == 0


def test_spmm_15d_memmap_triplet_validates(tmp_path, monkeypatch):
    """--memmap builds from a memmapped npy CSR triplet (reference
    generate_15d_decomposition_new, spmm_15d.py:158-309) and validates
    against the streaming golden."""
    monkeypatch.chdir(tmp_path)
    a = barabasi_albert(128, 3, seed=7).astype(np.float32).tocsr()
    np.save(tmp_path / "t_data.npy", a.data)
    np.save(tmp_path / "t_indices.npy", a.indices)
    np.save(tmp_path / "t_indptr.npy", a.indptr)
    rc = spmm_15d.main([
        "--file", str(tmp_path / "t"), "--memmap", "true",
        "--columns", "4", "--iterations", "1", "--validate", "true",
        "--device", "cpu", "--logdir", str(tmp_path / "logs"),
    ])
    assert rc == 0


def test_spmm_petsc_random_validates(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    rc = spmm_petsc.main([
        "--vertices", "256", "--edges", "1024", "--columns", "4",
        "--iterations", "2", "--validate", "true", "--device", "cpu",
        "--logdir", str(tmp_path / "logs"),
    ])
    assert rc == 0


def test_spmm_petsc_dryrun_and_slices(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    # Reference slice-file scheme: {name}.part.P.slice.r.npz.
    a = barabasi_albert(64, 2, seed=3).astype(np.float32)
    p = 4
    bounds = np.linspace(0, 64, p + 1).astype(int)
    for r in range(p):
        sparse.save_npz(tmp_path / f"g.part.{p}.slice.{r}.npz",
                        a[bounds[r]:bounds[r + 1]])
    rc = spmm_petsc.main([
        "--file", str(tmp_path / f"g.part.{p}.slice.0.npz"),
        "--dryrun", "true", "--device", "cpu",
        "--logdir", str(tmp_path / "logs"),
    ])
    assert rc == 0


def test_spmm_petsc_per_slice_ingest_validates(tmp_path, monkeypatch):
    """Slice count == device count takes the per-slice ingest path (no
    global reassembly; reference spmm_petsc.py:421-440) and validates
    against the per-slice golden."""
    import jax

    monkeypatch.chdir(tmp_path)
    p = len(jax.devices())
    n = 16 * p
    a = barabasi_albert(n, 2, seed=5).astype(np.float32)
    bounds = np.linspace(0, n, p + 1).astype(int)
    for r in range(p):
        sparse.save_npz(tmp_path / f"g.part.{p}.slice.{r}.npz",
                        a[bounds[r]:bounds[r + 1]])
    rc = spmm_petsc.main([
        "--file", str(tmp_path / f"g.part.{p}.slice.0.npz"),
        "--columns", "4", "--iterations", "1", "--validate", "true",
        "--device", "cpu", "--logdir", str(tmp_path / "logs"),
    ])
    assert rc == 0


def test_log_upload_marks_and_lists(tmp_path, monkeypatch):
    # A run written by the benchmark CLIs is discovered; without wandb
    # it stays pending (no .logged marker), and empty runs are skipped
    # (reference wb_logging.py:135-160 semantics).  wandb is forced
    # absent so the test never performs real uploads.
    import json
    import sys

    monkeypatch.setitem(sys.modules, "wandb", None)

    from arrow_matrix_tpu.cli import log_upload
    from arrow_matrix_tpu.utils.logging import log_local_runs

    logdir = tmp_path / "logs"
    logdir.mkdir()
    run = {"algorithm": "ArrowTPU_test", "dataset": "tiny",
           "config": {"width": 4}, "entries": [{"spmm_time": 0.1}]}
    (logdir / "ArrowTPU_test.tiny.abc.json").write_text(json.dumps(run))
    empty = dict(run, entries=[])
    (logdir / "ArrowTPU_test.tiny.def.json").write_text(json.dumps(empty))

    handled = log_local_runs(str(logdir))
    assert len(handled) == 1 and handled[0].endswith(".abc")

    assert log_upload.main(["--path", str(logdir)]) == 0
    with pytest.raises(SystemExit):
        log_upload.main(["--path", str(logdir / "nope")])


def test_segment_log_and_trace(tmp_path):
    import jax.numpy as jnp

    from arrow_matrix_tpu.utils import logging as wb

    wb.init("algo", "ds", {"k": 1})
    with wb.segment("phase_a"):
        pass
    wb.set_iteration_data({"iteration": 3})
    wb.log({"spmm_time": 0.5})
    s = wb.get_log().summarize()
    assert "phase_a" in s and s["spmm_time"]["count"] == 1
    base = wb.finish(str(tmp_path / "logs"))
    assert base and os.path.exists(base + ".json")

    with wb.trace(str(tmp_path / "traces")):
        jnp.ones(8).sum().block_until_ready()
    assert os.path.isdir(tmp_path / "traces")


def _write_mat73(path, m):
    """Craft a MATLAB v7.3 file: HDF5 with a 512-byte MATLAB userblock
    (text header + version 0x0200 + 'IM' endianness at offset 124) and
    the SuiteSparse Problem/A group layout the reference loads
    (reference decomposition_main.py:18-34)."""
    import h5py

    csc = sparse.csc_matrix(m)
    with h5py.File(path, "w", userblock_size=512) as f:
        g = f.create_group("Problem").create_group("A")
        g.create_dataset("data", data=csc.data.astype(np.float64))
        g.create_dataset("ir", data=csc.indices.astype(np.uint64))
        g.create_dataset("jc", data=csc.indptr.astype(np.uint64))
        g.attrs["MATLAB_sparse"] = np.uint64(csc.shape[0])
    header = b"MATLAB 7.3 MAT-file, written by arrow_matrix_tpu tests"
    block = header.ljust(116, b" ") + b"\x00" * 8
    block = block.ljust(124, b" ") + b"\x00\x02IM"
    with open(path, "r+b") as fh:
        fh.write(block.ljust(512, b"\x00"))


def test_load_matlab_v73(tmp_path):
    """MATLAB v7.3 input via the h5py fallback (VERDICT r1 missing #5)."""
    pytest.importorskip("h5py")
    from arrow_matrix_tpu.cli.common import load_sparse_matrix

    a = barabasi_albert(50, 3, seed=7)
    path = str(tmp_path / "graph.mat")
    _write_mat73(path, a)
    loaded = load_sparse_matrix(path)
    diff = (loaded - sparse.csr_matrix(a, dtype=np.float32)).tocsr()
    assert diff.nnz == 0 or np.max(np.abs(diff.data)) < 1e-7


def test_load_matlab_v73_pattern_only(tmp_path):
    # Pattern (logical) sparse matrices omit the data dataset => ones.
    pytest.importorskip("h5py")
    import h5py
    from arrow_matrix_tpu.cli.common import load_sparse_matrix

    a = sparse.csc_matrix(np.eye(5, dtype=np.float64))
    path = str(tmp_path / "pat.mat")
    _write_mat73(path, a)
    with h5py.File(path, "r+") as f:
        del f["Problem"]["A"]["data"]
    loaded = load_sparse_matrix(path)
    np.testing.assert_allclose(loaded.toarray(), np.eye(5))


def test_spmm_arrow_fold_single_chip(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    rc = spmm_arrow.main([
        "--vertices", "300", "--width", "32", "--features", "4",
        "--iterations", "2", "--validate", "true", "--device", "cpu",
        "--devices", "1", "--fmt", "fold",
        "--logdir", str(tmp_path / "logs"),
    ])
    assert rc == 0


def test_spmm_arrow_fold_rejects_mesh(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    with pytest.raises(SystemExit, match="single-chip"):
        spmm_arrow.main([
            "--vertices", "300", "--width", "32", "--features", "4",
            "--iterations", "1", "--device", "cpu", "--devices", "4",
            "--fmt", "fold", "--logdir", str(tmp_path / "logs"),
        ])


def test_spmm_arrow_aborts_on_poisoned_artifact(tmp_path, monkeypatch):
    """Failure detection: a NaN in the artifact data must fail the
    validated run with nonzero rc (the reference's collective
    allreduce(LOR) abort, arrow_bench.py:128-134 — here the gate is
    the per-iteration validation)."""
    import glob

    import numpy as np

    from arrow_matrix_tpu.decomposition import arrow_decomposition
    from arrow_matrix_tpu.io import save_decomposition
    from arrow_matrix_tpu.utils.graphs import barabasi_albert

    monkeypatch.chdir(tmp_path)
    a = (barabasi_albert(300, 3, seed=2) * 0.5).tocsr()
    levels = arrow_decomposition(a, 32, max_levels=2, block_diagonal=True,
                                 seed=0)
    base = str(tmp_path / "g")
    save_decomposition(levels, base, block_diagonal=True)
    data_files = sorted(glob.glob(base + "*_data.npy"))
    assert data_files
    d = np.load(data_files[0])
    d[0] = np.nan
    np.save(data_files[0], d)

    rc = spmm_arrow.main([
        "--path", base, "--width", "32", "--features", "4",
        "--iterations", "2", "--validate", "true", "--device", "cpu",
        "--logdir", str(tmp_path / "logs"),
    ])
    assert rc != 0


def test_spmm_arrow_sell_mesh(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    rc = spmm_arrow.main([
        "--vertices", "400", "--width", "32", "--features", "4",
        "--iterations", "2", "--validate", "true", "--device", "cpu",
        "--devices", "4", "--fmt", "sell",
        "--logdir", str(tmp_path / "logs"),
    ])
    assert rc == 0


def test_spmm_arrow_auto_mode_single_chip(tmp_path, monkeypatch, capsys):
    """No --fmt on one device runs the measured-best single-chip mode
    (fold) and validates (VERDICT r2 item 4)."""
    monkeypatch.chdir(tmp_path)
    rc = spmm_arrow.main([
        "--vertices", "300", "--width", "32", "--features", "4",
        "--iterations", "1", "--validate", "true", "--device", "cpu",
        "--devices", "1", "--logdir", str(tmp_path / "logs"),
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "auto-selected --fmt fold" in out


def test_spmm_arrow_auto_mode_mesh(tmp_path, monkeypatch, capsys):
    """No --fmt/--routing on a mesh runs sell + a2a (the measured
    winner on wall-clock AND collective bytes) and validates."""
    monkeypatch.chdir(tmp_path)
    rc = spmm_arrow.main([
        "--vertices", "400", "--width", "32", "--features", "4",
        "--iterations", "1", "--validate", "true", "--device", "cpu",
        "--devices", "4", "--logdir", str(tmp_path / "logs"),
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "auto-selected --fmt sell" in out
    assert "auto-selected --routing a2a" in out


def test_spmm_arrow_explicit_flags_override_auto(tmp_path, monkeypatch,
                                                 capsys):
    monkeypatch.chdir(tmp_path)
    rc = spmm_arrow.main([
        "--vertices", "300", "--width", "32", "--features", "4",
        "--iterations", "1", "--validate", "true", "--device", "cpu",
        "--devices", "4", "--fmt", "ell", "--routing", "gather",
        "--logdir", str(tmp_path / "logs"),
    ])
    assert rc == 0
    assert "auto-selected" not in capsys.readouterr().out


@pytest.mark.parametrize("blocked", ["true", "false"])
def test_spmm_arrow_wide_layout(tmp_path, monkeypatch, blocked):
    """--slim false runs the wide layout inside the orchestrated path
    on a (2, t) mesh and validates (VERDICT r2 item 7: behavior must
    match the help text, not silently run slim) — in both the
    block-diagonal and banded (±1 halo) tilings, like the reference's
    wide ArrowMPI (arrow_mpi.py:123-175)."""
    monkeypatch.chdir(tmp_path)
    rc = spmm_arrow.main([
        "--vertices", "400", "--width", "32", "--features", "4",
        "--iterations", "2", "--validate", "true", "--device", "cpu",
        "--devices", "8", "--slim", "false", "--blocked", blocked,
        "--logdir", str(tmp_path / "logs"),
    ])
    assert rc == 0


def test_spmm_arrow_wide_layout_flag_errors(tmp_path, monkeypatch):
    """Wide-layout precondition violations fail loudly before any work."""
    monkeypatch.chdir(tmp_path)
    base = ["--vertices", "300", "--width", "32", "--features", "4",
            "--iterations", "1", "--device", "cpu",
            "--logdir", str(tmp_path / "logs")]
    with pytest.raises(SystemExit, match="wide"):
        spmm_arrow.main(base + ["--slim", "false", "--fmt", "sell"])
    with pytest.raises(SystemExit, match="wide"):
        spmm_arrow.main(base + ["--slim", "false", "--mode", "space"])
    with pytest.raises(SystemExit, match="wide"):
        spmm_arrow.main(base + ["--slim", "false", "--routing", "a2a"])
    with pytest.raises(SystemExit, match="even device count"):
        spmm_arrow.main(base + ["--slim", "false", "--devices", "3"])


def test_spmm_arrow_feature_dtype_bf16(tmp_path, monkeypatch):
    """--feature_dtype bf16 on the sell mesh path validates under the
    widened (bf16-epsilon) gate; on the stacked formats it is rejected
    up front."""
    monkeypatch.chdir(tmp_path)
    rc = spmm_arrow.main([
        "--vertices", "400", "--width", "32", "--features", "4",
        "--iterations", "2", "--validate", "true", "--device", "cpu",
        "--devices", "4", "--fmt", "sell", "--feature_dtype", "bf16",
        "--logdir", str(tmp_path / "logs"),
    ])
    assert rc == 0
    with pytest.raises(SystemExit, match="fold or sell"):
        spmm_arrow.main([
            "--vertices", "400", "--width", "32", "--features", "4",
            "--iterations", "1", "--device", "cpu", "--devices", "4",
            "--fmt", "ell", "--feature_dtype", "bf16",
            "--logdir", str(tmp_path / "logs"),
        ])


def test_spmm_arrow_sell_space_shared(tmp_path, monkeypatch):
    """--mode space --fmt sell = SellSpaceShared: levels concurrent on
    disjoint groups in the feature-major layouts, validated against the
    host golden through the full CLI (artifact pre-saved so the level
    count divides the device count)."""
    from arrow_matrix_tpu.decomposition import arrow_decomposition
    from arrow_matrix_tpu.io import save_decomposition
    from arrow_matrix_tpu.utils.graphs import barabasi_albert

    monkeypatch.chdir(tmp_path)
    a = barabasi_albert(400, 3, seed=2)
    levels = arrow_decomposition(a, 32, max_levels=2,
                                 block_diagonal=True, seed=0)
    assert len(levels) == 2
    base = str(tmp_path / "g")
    save_decomposition(levels, base, block_diagonal=True)
    rc = spmm_arrow.main([
        "--path", base, "--width", "32", "--features", "4",
        "--iterations", "2", "--validate", "true", "--device", "cpu",
        "--devices", "4", "--fmt", "sell", "--mode", "space",
        "--logdir", str(tmp_path / "logs"),
    ])
    assert rc == 0


def test_spmm_arrow_memmap_streaming(tmp_path, monkeypatch):
    """--memmap streams the artifact to the builders (no level
    materialized) and still validates: stacked mesh, sell mesh, and
    single-chip fold all consume the triplet path."""
    from arrow_matrix_tpu.decomposition import arrow_decomposition
    from arrow_matrix_tpu.io import save_decomposition
    from arrow_matrix_tpu.utils.graphs import barabasi_albert

    monkeypatch.chdir(tmp_path)
    a = barabasi_albert(400, 3, seed=3)
    levels = arrow_decomposition(a, 32, max_levels=2,
                                 block_diagonal=True, seed=0)
    base = str(tmp_path / "g")
    save_decomposition(levels, base, block_diagonal=True)
    for extra in (["--devices", "4"],
                  ["--devices", "4", "--fmt", "sell"],
                  ["--devices", "1", "--fmt", "fold"]):
        rc = spmm_arrow.main([
            "--path", base, "--width", "32", "--features", "4",
            "--iterations", "1", "--validate", "true", "--device", "cpu",
            "--memmap", "true", "--logdir", str(tmp_path / "logs"),
        ] + extra)
        assert rc == 0, extra


def test_doctor():
    """Environment doctor: runs read-only checks and exits 0 in this
    (known-good) environment; the accelerator probe is bounded and
    never gates."""
    from arrow_matrix_tpu.cli import doctor

    rc = doctor.main(["--probe-timeout", "5", "--devices", "2"])
    assert rc == 0


def test_spmm_arrow_trace(tmp_path, monkeypatch):
    """--trace writes a jax.profiler trace directory for the loop."""
    monkeypatch.chdir(tmp_path)
    rc = spmm_arrow.main([
        "--vertices", "300", "--width", "32", "--features", "4",
        "--iterations", "1", "--device", "cpu",
        "--trace", str(tmp_path / "trc"),
        "--logdir", str(tmp_path / "logs"),
    ])
    assert rc == 0
    # The trace must be FLUSHED, not just the directory created on
    # context entry: profiler output lands under plugins/profile.
    found = []
    for root, _, files in os.walk(tmp_path / "trc"):
        found += [os.path.join(root, f) for f in files]
    assert found, "trace directory contains no profiler output"


def test_spmm_arrow_comm_report(tmp_path, monkeypatch, capsys):
    """--comm_report prints per-iteration collective bytes from the
    compiled step's HLO (mesh) or the zero-by-construction note
    (single chip)."""
    monkeypatch.chdir(tmp_path)
    rc = spmm_arrow.main([
        "--vertices", "400", "--width", "32", "--features", "4",
        "--iterations", "1", "--device", "cpu", "--devices", "4",
        "--fmt", "sell", "--routing", "a2a", "--comm_report",
        "--logdir", str(tmp_path / "logs"),
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "collective" in out and "TOTAL" in out


def test_baseline_comm_reports(tmp_path, monkeypatch, capsys):
    """--comm_report on both baseline CLIs (the paper's comparison:
    arrow modes vs 1.5D vs PETSc comm volume, all CLI-printable)."""
    monkeypatch.chdir(tmp_path)
    for mod in (spmm_15d, spmm_petsc):
        rc = mod.main([
            "--vertices", "256", "--edges", "1024", "--columns", "4",
            "--iterations", "1", "--validate", "true", "--device",
            "cpu", "--devices", "4", "--comm_report",
            "--logdir", str(tmp_path / "logs"),
        ])
        assert rc == 0
        out = capsys.readouterr().out
        assert "collective" in out and "TOTAL" in out
