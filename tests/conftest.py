"""Test configuration: force a 16-device virtual CPU platform so the
multi-chip sharding paths are exercised without TPU hardware (the TPU
analog of the reference's ``mpiexec --oversubscribe`` many-rank fixture,
reference scripts/run_tests.sh runs at up to 30 ranks).  Most tests use
an 8-device sub-mesh; tests/test_mesh_sizes.py sweeps sub-meshes of
2..16 devices including non-power-of-two sizes."""

import os
import tempfile

# Force CPU even when the environment selects a TPU platform: the test
# suite must be hermetic and must exercise the virtual multi-device mesh.
os.environ["JAX_PLATFORMS"] = "cpu"

# Redirect the default graft-ledger store to a throwaway directory so
# no test (or code under test that emits telemetry) ever appends to the
# committed bench_results/ledger history.
os.environ.setdefault("AMT_LEDGER_DIR",
                      tempfile.mkdtemp(prefix="amt_test_ledger_"))
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=16").strip()

# Some environments (axon TPU tunnels) register an out-of-tree PJRT
# plugin for every interpreter via sitecustomize; initializing it can
# block on a remote service.  Tests never want it — the shared helper
# drops the factory and repins the platform before the first backend
# init.
from arrow_matrix_tpu.utils.platform import force_cpu_devices

force_cpu_devices()


def ensure_ba_256_3(repo_root):
    """Regenerate the loose ba_256_3 decomposition artifact if absent.

    tests/test_memview.py and tests/test_reshard.py load it as a real
    npy-triplet artifact from the repo root; the files are deliberately
    gitignored (ba_*.npy), so a fresh checkout — or anything that
    sweeps loose files — must not take those tests down with it.  The
    tests only depend on the artifact's shape (BA n=256 m=3, width 32,
    block-diagonal), not its bytes, so a deterministic rebuild is a
    faithful replacement.
    """
    base = os.path.join(repo_root, "ba_256_3")
    from arrow_matrix_tpu.io.graphio import FileKind, format_path
    marker = format_path(base, 32, 0, True, FileKind.widths)
    if os.path.exists(marker):
        return base
    from arrow_matrix_tpu.decomposition import arrow_decomposition
    from arrow_matrix_tpu.io import save_decomposition
    from arrow_matrix_tpu.utils import barabasi_albert
    a = barabasi_albert(256, 3, seed=0)
    levels = arrow_decomposition(a, 32, max_levels=10,
                                 block_diagonal=True, seed=0)
    save_decomposition(levels, base, block_diagonal=True)
    return base


import pytest


@pytest.fixture(scope="session")
def ba_256_3_base():
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return ensure_ba_256_3(repo_root)
