"""Test configuration: force a 16-device virtual CPU platform so the
multi-chip sharding paths are exercised without TPU hardware (the TPU
analog of the reference's ``mpiexec --oversubscribe`` many-rank fixture,
reference scripts/run_tests.sh runs at up to 30 ranks).  Most tests use
an 8-device sub-mesh; tests/test_mesh_sizes.py sweeps sub-meshes of
2..16 devices including non-power-of-two sizes."""

import os
import tempfile

# Force CPU even when the environment selects a TPU platform: the test
# suite must be hermetic and must exercise the virtual multi-device mesh.
os.environ["JAX_PLATFORMS"] = "cpu"

# Redirect the default graft-ledger store to a throwaway directory so
# no test (or code under test that emits telemetry) ever appends to the
# committed bench_results/ledger history.
os.environ.setdefault("AMT_LEDGER_DIR",
                      tempfile.mkdtemp(prefix="amt_test_ledger_"))
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=16").strip()

# Some environments (axon TPU tunnels) register an out-of-tree PJRT
# plugin for every interpreter via sitecustomize; initializing it can
# block on a remote service.  Tests never want it — the shared helper
# drops the factory and repins the platform before the first backend
# init.
from arrow_matrix_tpu.utils.platform import force_cpu_devices

force_cpu_devices()
