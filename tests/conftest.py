"""Test configuration: force an 8-device virtual CPU platform so the
multi-chip sharding paths are exercised without TPU hardware (the TPU
analog of the reference's ``mpiexec --oversubscribe`` many-rank fixture,
reference scripts/run_tests.sh)."""

import os

# Force CPU even when the environment selects a TPU platform: the test
# suite must be hermetic and must exercise the virtual 8-device mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

# Some environments (axon TPU tunnels) register an out-of-tree PJRT
# plugin for every interpreter via sitecustomize; initializing it can
# block on a remote service.  Tests never want it — drop the factory and
# repin the platform config (the env var was already latched at the
# sitecustomize-time jax import) before the first backend init.
try:
    import jax
    from jax._src import xla_bridge as _xb

    _xb._backend_factories.pop("axon", None)
    jax.config.update("jax_platforms", "cpu")
except Exception:  # pragma: no cover - jax internals moved; harmless
    pass
