"""graft-fleet unit + integration tests: wire framing (bit-identical
ndarray round trips, torn/oversized frames loud), consistent-hash
placement (deterministic, surgical re-homing on death), first-fit
bin packing with explicit unplaced tenants, the heartbeat death
verdict (streak-gated, per-worker deterministic backoff), and an
in-process two-worker fleet end to end — every request completed,
fleet quantiles EXACTLY the pooled nearest-rank over all workers'
raw samples, and a request aimed at a dead worker requeued onto the
survivor.  The full multi-process SIGKILL scenario lives in
tools/fleet_gate.py (run by the slow chaos-gate tier)."""

import socket
import threading

import numpy as np
import pytest

from arrow_matrix_tpu import faults
from arrow_matrix_tpu.fleet import health as health_mod
from arrow_matrix_tpu.fleet import wire
from arrow_matrix_tpu.fleet.health import HealthMonitor
from arrow_matrix_tpu.fleet.placement import (
    ConsistentHashRing,
    pack_tenants,
)
from arrow_matrix_tpu.fleet.router import FleetRouter, WorkerHandle
from arrow_matrix_tpu.fleet.worker import FleetWorker, serve_worker
from arrow_matrix_tpu.obs.metrics import Histogram
from arrow_matrix_tpu.serve.loadgen import synthetic_trace
from arrow_matrix_tpu.serve.request import Request


@pytest.fixture(autouse=True)
def _no_leftover_plan():
    faults.clear_plan()
    yield
    faults.clear_plan()


# ---------------------------------------------------------------------------
# Wire protocol
# ---------------------------------------------------------------------------

def test_wire_roundtrip_is_bit_identical():
    a, b = socket.socketpair()
    try:
        x = (np.arange(24, dtype=np.float32).reshape(6, 4)
             * np.float32(0.1))
        msg = {"op": "submit", "x": x,
               "nested": [{"y": x[:2].astype(np.float64)}, 3, "s"],
               "f": 0.125, "none": None}
        wire.send_msg(a, msg)
        got = wire.recv_msg(b)
    finally:
        a.close()
        b.close()
    assert got["x"].dtype == x.dtype and got["x"].shape == x.shape
    assert got["x"].tobytes() == x.tobytes()
    y = got["nested"][0]["y"]
    assert y.dtype == np.float64
    assert y.tobytes() == x[:2].astype(np.float64).tobytes()
    assert got["nested"][1:] == [3, "s"]
    assert got["f"] == 0.125 and got["none"] is None


def test_wire_torn_frame_is_loud():
    a, b = socket.socketpair()
    try:
        a.sendall(b"\x00\x00\x00")       # 3 of 8 header bytes
        a.close()
        with pytest.raises(wire.WireError, match="mid-frame"):
            wire.recv_msg(b)
    finally:
        b.close()


def test_wire_torn_frame_at_exact_header_boundary_both_sides():
    """The 8-byte length header is the recovery pivot: a peer dying
    ONE byte short of it and a peer dying EXACTLY on it (header
    delivered, zero payload bytes) must both be loud, and the raw-
    framing path must be as loud as the json path."""
    # One byte short of the boundary: the header read itself tears.
    a, b = socket.socketpair()
    try:
        a.sendall(wire._HEADER.pack(64)[:7])
        a.close()
        with pytest.raises(wire.WireError, match=r"mid-frame \(7/8"):
            wire.recv_msg(b)
    finally:
        b.close()
    # Exactly on the boundary: full header, then the payload tears
    # at 0 of the promised 64 bytes.
    a, b = socket.socketpair()
    try:
        a.sendall(wire._HEADER.pack(64))
        a.close()
        with pytest.raises(wire.WireError, match=r"mid-frame \(0/64"):
            wire.recv_msg(b)
    finally:
        b.close()
    # Same boundary on the raw-framing side (RAW_FLAG set): the json
    # sub-header read is the first casualty.
    a, b = socket.socketpair()
    try:
        a.sendall(wire._HEADER.pack(64 | wire.RAW_FLAG))
        a.close()
        with pytest.raises(wire.WireError, match="mid-frame"):
            wire.recv_msg(b)
    finally:
        b.close()


def test_wire_oversized_header_is_refused():
    a, b = socket.socketpair()
    try:
        a.sendall(wire._HEADER.pack(wire.MAX_FRAME_BYTES + 1))
        with pytest.raises(wire.WireError, match="corrupted"):
            wire.recv_msg(b)
    finally:
        a.close()
        b.close()


# ---------------------------------------------------------------------------
# Placement
# ---------------------------------------------------------------------------

def test_ring_is_deterministic_and_rehoming_is_surgical():
    tenants = [f"t{i}" for i in range(64)]
    ring = ConsistentHashRing(["w0", "w1", "w2"])
    again = ConsistentHashRing(["w2", "w0", "w1"])   # order-free
    before = {t: ring.lookup(t) for t in tenants}
    assert before == {t: again.lookup(t) for t in tenants}
    assert len(set(before.values())) == 3            # all workers used
    # Removing one worker re-homes ONLY its tenants; exclude= (the
    # requeue path) agrees with actual removal.
    excluded = {t: ring.lookup(t, exclude=("w1",)) for t in tenants}
    ring.remove("w1")
    after = {t: ring.lookup(t) for t in tenants}
    assert after == excluded
    for t in tenants:
        if before[t] != "w1":
            assert after[t] == before[t]
        else:
            assert after[t] in ("w0", "w2")


def test_empty_ring_and_full_exclusion_return_none():
    assert ConsistentHashRing().lookup("t") is None
    ring = ConsistentHashRing(["w0", "w1"])
    assert ring.lookup("t", exclude=("w0", "w1")) is None


def test_pack_tenants_first_fit_decreasing_with_explicit_unplaced():
    assignment, unplaced = pack_tenants(
        {"big": 80, "mid": 60, "small": 30},
        {"w0": 100, "w1": 64})
    assert assignment == {"big": "w0", "mid": "w1"}
    assert unplaced == ["small"]          # fits NO remaining budget
    # Deterministic under dict-order permutation.
    a2, u2 = pack_tenants({"small": 30, "big": 80, "mid": 60},
                          {"w1": 64, "w0": 100})
    assert (a2, u2) == (assignment, unplaced)


def test_pack_tenants_edge_cases():
    # A zero-capacity worker is never assigned anything…
    a, u = pack_tenants({"t": 1}, {"w0": 0, "w1": 10})
    assert a == {"t": "w1"} and u == []
    # …and when it is the ONLY worker, the tenant is explicitly
    # unplaced, not silently admitted.
    a, u = pack_tenants({"big": 5}, {"w0": 0})
    assert a == {} and u == ["big"]
    # A tenant larger than EVERY bin is unplaced without poisoning
    # the placement of tenants that do fit.
    a, u = pack_tenants({"huge": 1000, "ok": 10},
                        {"w0": 64, "w1": 32})
    assert a == {"ok": "w0"} and u == ["huge"]
    # Equal-size ties break on tenant name, deterministically under
    # dict-order permutation of BOTH inputs.
    a1, u1 = pack_tenants({"b": 10, "a": 10, "c": 10},
                          {"w0": 20, "w1": 10})
    a2, u2 = pack_tenants({"c": 10, "a": 10, "b": 10},
                          {"w1": 10, "w0": 20})
    assert a1 == a2 == {"a": "w0", "b": "w0", "c": "w1"}
    assert u1 == u2 == []


# ---------------------------------------------------------------------------
# Health: streak-gated death verdict, deterministic per-worker backoff
# ---------------------------------------------------------------------------

def test_health_death_needs_a_full_streak_and_is_sticky():
    clock = [0.0]
    hm = HealthMonitor(max_failures=3, clock=lambda: clock[0],
                       sleep=lambda s: None)
    hm.record_failure("w0", "boom")
    hm.record_failure("w0", "boom")
    assert hm.alive_workers() == ["w0"]   # 2 < 3: still alive
    hm.record_ok("w0")                    # success resets the streak
    assert hm.state["w0"].consecutive_failures == 0
    clock[0] = 7.0
    for _ in range(3):
        hm.record_failure("w0", "down")
    assert hm.dead_workers() == ["w0"]
    assert hm.state["w0"].declared_dead_s == 7.0
    hm.record_ok("w0")                    # dead is sticky
    assert hm.dead_workers() == ["w0"]


def test_health_probe_backoff_is_per_worker_deterministic(monkeypatch):
    def down(host, port, obj, *, timeout_s=None):
        raise wire.WireError("connection refused")

    monkeypatch.setattr(health_mod.wire, "request_call", down)

    def ladder(worker_id):
        sleeps = []
        hm = HealthMonitor(max_failures=3, sleep=sleeps.append)
        h = hm.probe(worker_id, "127.0.0.1", 1)
        assert not h.alive and h.consecutive_failures == 3
        return sleeps

    s0 = ladder("worker-0")
    assert s0 == ladder("worker-0")       # reproducible per worker
    assert s0 != ladder("worker-1")       # but not herd-synchronized
    assert len(s0) == 2                   # sleeps BETWEEN 3 attempts


def test_health_readmit_is_the_only_way_back():
    """Death is sticky (record_ok never resurrects); readmit() is the
    one explicit way back, resets the streak, and counts the
    readmission so the fleet report shows a worker that died and came
    back as exactly that."""
    clock = [0.0]
    hm = HealthMonitor(max_failures=2, clock=lambda: clock[0],
                       sleep=lambda s: None)
    hm.record_failure("w0", "down")
    hm.record_failure("w0", "down")
    assert hm.dead_workers() == ["w0"]
    hm.record_ok("w0")                    # sticky: no resurrection
    assert hm.dead_workers() == ["w0"]
    clock[0] = 11.0
    h = hm.readmit("w0")
    assert h.alive and h.consecutive_failures == 0
    assert h.last_error is None and h.declared_dead_s is None
    assert h.readmissions == 1 and h.readmitted_s == 11.0
    assert hm.alive_workers() == ["w0"]
    assert hm.snapshot()["w0"]["readmissions"] == 1
    # A readmitted worker needs a FRESH full streak to die again —
    # and a second death + readmission counts separately.
    hm.record_failure("w0", "blip")
    assert hm.alive_workers() == ["w0"]
    hm.record_failure("w0", "blip")
    assert hm.dead_workers() == ["w0"]
    assert hm.readmit("w0").readmissions == 2


# ---------------------------------------------------------------------------
# In-process fleet: serve_worker on threads + FleetRouter(handles=...)
# ---------------------------------------------------------------------------

def _start_worker(worker_id, checkpoint_dir):
    worker = FleetWorker(worker_id, vertices=64, width=16, seed=5,
                         checkpoint_dir=checkpoint_dir,
                         checkpoint_every=1)
    ready = threading.Event()
    box = {}

    def announce(port):
        box["port"] = port
        ready.set()

    th = threading.Thread(target=serve_worker, args=(worker,),
                          kwargs={"port": 0, "announce": announce},
                          daemon=True)
    th.start()
    assert ready.wait(120), f"{worker_id} never bound"
    return worker, WorkerHandle(worker_id, "127.0.0.1", box["port"])


def test_fleet_completes_pools_exactly_and_requeues(tmp_path):
    """One in-process fleet exercises the whole contract: routed
    requests complete with results, the fleet summary's quantiles are
    EXACTLY the pooled nearest-rank over the workers' raw samples,
    and after one worker goes dark a request aimed at it is requeued
    onto the survivor (same shared checkpoint dir — the idempotent
    resume path)."""
    ckpt = str(tmp_path / "ckpt")
    w0, h0 = _start_worker("w0", ckpt)
    w1, h1 = _start_worker("w1", ckpt)
    router = FleetRouter(
        handles=[h0, h1],
        health=HealthMonitor(timeout_s=5.0, max_failures=3))
    try:
        trace = synthetic_trace(router.n_rows, tenants=3, requests=6,
                                k=2, iterations=2, seed=7)
        tickets = [router.submit(r) for r in trace]
        router.drain(timeout_s=180)
        assert [t.status for t in tickets] == ["completed"] * 6
        assert all(t.result is not None for t in tickets)

        report = router.fleet_summary()
        assert report["completed"] == 6
        assert report["shed"] == 0 and report["failed"] == 0
        pooled = Histogram()
        n_samples = 0
        for rec in report["workers"].values():
            for v in rec["latency_samples_ms"]:
                pooled.observe(v)
                n_samples += 1
        lat = report["latency_ms"]
        assert lat["count"] == n_samples == 6
        for q, field in ((0.5, "p50"), (0.9, "p90"), (0.99, "p99")):
            assert lat[field] == pooled.quantile(q)

        # Kill w0's wire front; a request for one of its tenants must
        # be requeued onto w1 — not lost, not failed.
        victim_tenant = next(t for t in (f"t{i}" for i in range(256))
                             if router.ring.lookup(t) == "w0")
        wire.request_call(h0.host, h0.port, {"op": "shutdown"})
        x = np.ones((router.n_rows, 2), dtype=np.float32)
        t = router.submit(Request("rq-dead", victim_tenant, x, 1))
        router.drain(timeout_s=180)
        assert t.status == "completed"
        assert getattr(t, "requeues", 0) >= 1
        assert t.worker_id == "w1"
        assert router.live_workers() == ["w1"]
        assert not router.health.snapshot()["w0"]["alive"]
    finally:
        router.shutdown()
        for w in (w0, w1):
            try:
                w.close()
            except Exception:
                pass


@pytest.mark.slow
def test_fleet_spawned_processes_roundtrip(tmp_path):
    """The real subprocess path: spawn 2 worker processes, route a
    trace, fold their run-dir ledgers, and shut down cleanly.  (The
    SIGKILL-mid-batch scenario is tools/fleet_gate.py.)"""
    router = FleetRouter(spawn=2, vertices=64, width=16, seed=5,
                         run_dir=str(tmp_path))
    try:
        trace = synthetic_trace(router.n_rows, tenants=2, requests=4,
                                k=2, iterations=2, seed=3)
        tickets = [router.submit(r) for r in trace]
        router.drain(timeout_s=240)
        assert [t.status for t in tickets] == ["completed"] * 4
    finally:
        router.shutdown()
    # Workers write their run-dir ledgers on close, so fold AFTER the
    # graceful shutdown (as graft_fleet does).
    assert router.fold_ledgers() > 0
    from arrow_matrix_tpu.ledger import Ledger

    assert Ledger(str(tmp_path / "ledger")).validate() == []
