"""Streaming (memmap) ingestion: the builder that never materializes a
level on the host (ops/arrow_blocks.arrow_blocks_streamed + the
MultiLevelArrow triplet path), vs the eager builder and the in-memory
end-to-end result (reference loader role: arrow/arrow_dec_mpi.py:629-887,
arrow/common/graphio.py:449-495)."""

import numpy as np
import pytest

import jax

from arrow_matrix_tpu.decomposition.decompose import (
    arrow_decomposition,
    decomposition_spmm,
)
from arrow_matrix_tpu.io.graphio import (
    as_levels,
    load_decomposition,
    load_level_widths,
    save_decomposition,
)
from arrow_matrix_tpu.ops.arrow_blocks import (
    arrow_blocks_from_csr,
    arrow_blocks_streamed,
)
from arrow_matrix_tpu.parallel.mesh import make_mesh
from arrow_matrix_tpu.parallel.multi_level import MultiLevelArrow
from arrow_matrix_tpu.utils import numerics
from arrow_matrix_tpu.utils.graphs import barabasi_albert, random_dense


@pytest.fixture()
def decomp(tmp_path):
    a = barabasi_albert(600, 3, seed=5)
    levels = arrow_decomposition(a, arrow_width=64, max_levels=2,
                                 block_diagonal=True, seed=5)
    base = str(tmp_path / "g")
    save_decomposition(levels, base)
    return a, levels, base


@pytest.mark.parametrize("fmt,banded", [("ell", False), ("ell", True),
                                        ("dense", False)])
def test_streamed_builder_matches_eager(decomp, fmt, banded):
    _, levels, base = decomp
    loaded = load_decomposition(base, 64, mem_map=True)
    triplet = loaded[0][0]
    assert not hasattr(triplet, "nnz")  # really a (data, indices, indptr)

    mesh = make_mesh((8,), ("blocks",))
    eager = arrow_blocks_from_csr(levels[0].matrix, 64, pad_blocks_to=16,
                                  banded=banded, fmt=fmt)
    streamed = arrow_blocks_streamed(triplet, 64, mesh, pad_blocks_to=16,
                                     banded=banded, fmt=fmt)
    # Binary (implicit-ones) levels drop data for deg stacks; the two
    # builders must agree on which leaves exist AND their exact bytes.
    names = ("head", "diag", "col") + (("lo", "hi") if banded else ())
    leaves = [f"{n}_{leaf}" for n in names
              for leaf in ("cols", "data", "deg")] + ["head_rows"]
    for leaf in leaves:
        e, s = getattr(eager, leaf), getattr(streamed, leaf)
        assert (e is None) == (s is None), leaf
        if e is not None:
            np.testing.assert_array_equal(np.asarray(e), np.asarray(s),
                                          err_msg=leaf)
    if fmt == "ell":   # adjacency data is all ones -> binary layout
        assert eager.binary and streamed.binary
    # The streamed arrays really are sharded over the mesh.
    assert len(streamed.diag_cols.sharding.device_set) == 8


def test_multi_level_streamed_end_to_end(decomp):
    a, levels, base = decomp
    widths = load_level_widths(base, 64)
    loaded = load_decomposition(base, 64, mem_map=True)
    stream_levels = as_levels(loaded, widths, materialize=False)
    assert not hasattr(stream_levels[0].matrix, "nnz")

    mesh = make_mesh((8,), ("blocks",))
    ml_stream = MultiLevelArrow(stream_levels, 64, mesh=mesh, fmt="ell")
    ml_mem = MultiLevelArrow(levels, 64, mesh=mesh, fmt="ell")

    x_host = random_dense(600, 8, seed=6)
    got_stream = ml_stream.gather_result(
        ml_stream.step(ml_stream.set_features(x_host)))
    got_mem = ml_mem.gather_result(ml_mem.step(ml_mem.set_features(x_host)))
    want = decomposition_spmm(levels, x_host)

    np.testing.assert_array_equal(got_stream, got_mem)
    tol = numerics.relative_tolerance(a.nnz / a.shape[0], 1)
    assert numerics.relative_error(got_stream, want) < tol


def test_streamed_capture_check(decomp):
    # A matrix wider than the tiling must be rejected, same as the eager
    # builder's nnz-capture defense.
    _, levels, base = decomp
    loaded = load_decomposition(base, 64, mem_map=True)
    mesh = make_mesh((8,), ("blocks",))
    with pytest.raises(ValueError, match="captured"):
        arrow_blocks_streamed(loaded[-1][0], 8, mesh, pad_blocks_to=80)


def test_sell_paths_streamed_end_to_end(decomp):
    """The feature-major orchestrations build from memmapped triplets
    (sell_slim._SliceSource streams device slices) — bit-identical to
    the in-memory build."""
    from arrow_matrix_tpu.parallel import SellMultiLevel, SellSpaceShared

    a, levels, base = decomp
    widths = load_level_widths(base, 64)
    loaded = load_decomposition(base, 64, mem_map=True)
    stream_levels = as_levels(loaded, widths, materialize=False)
    assert not hasattr(stream_levels[0].matrix, "nnz")
    x_host = random_dense(600, 8, seed=6)
    want = decomposition_spmm(levels, x_host)
    tol = numerics.relative_tolerance(a.nnz / a.shape[0], 1)

    mesh = make_mesh((4,), ("blocks",))
    sm_s = SellMultiLevel(stream_levels, 64, mesh, routing="a2a")
    sm_m = SellMultiLevel(levels, 64, mesh, routing="a2a")
    got_s = sm_s.gather_result(sm_s.step(sm_s.set_features(x_host)))
    got_m = sm_m.gather_result(sm_m.step(sm_m.set_features(x_host)))
    np.testing.assert_array_equal(got_s, got_m)
    assert numerics.relative_error(got_s, want) < tol
    assert sm_s.binary == sm_m.binary

    if len(stream_levels) == 2:
        mesh2 = make_mesh((2, 4), ("lvl", "blocks"))
        sp_s = SellSpaceShared(stream_levels, 64, mesh2)
        sp_m = SellSpaceShared(levels, 64, mesh2)
        got_s = sp_s.gather_result(sp_s.step(sp_s.set_features(x_host)))
        got_m = sp_m.gather_result(sp_m.step(sp_m.set_features(x_host)))
        np.testing.assert_array_equal(got_s, got_m)
        assert numerics.relative_error(got_s, want) < tol
