"""Native (C++) decomposer backend tests.

The native kernels are the compiled-performance layer (the reference's
Julia-module role, reference julia/arrow/GraphAlgorithms.jl tested by
julia/arrow/test/test_graph.jl: union-find semantics, MSF edge counts,
degenerate graphs).  Tested here the same way the Python backend is:
permutation validity, decomposition invariants, and cross-backend
equivalence of the deterministic BFS path.
"""

import numpy as np
import pytest
from scipy import sparse

from arrow_matrix_tpu.decomposition import arrow_decomposition, native
from arrow_matrix_tpu.decomposition.decompose import (
    decomposition_spmm,
    reconstruct,
)
from arrow_matrix_tpu.decomposition.linearize import bfs_order as py_bfs
from arrow_matrix_tpu.utils import barabasi_albert, erdos_renyi, random_dense
from arrow_matrix_tpu.utils.graphs import symmetrize

pytestmark = pytest.mark.skipif(
    not native.available(),
    reason=f"native decomposer unavailable: {native.load_error()}")


def test_forest_order_is_permutation():
    a = symmetrize(barabasi_albert(500, 3, seed=1))
    rng = np.random.default_rng(0)
    order = native.random_forest_order(a, rng)
    assert np.array_equal(np.sort(order), np.arange(500))


def test_bfs_order_is_permutation_and_component_contiguous():
    # Two disjoint components: BFS must emit each contiguously, smaller
    # component ids first (the MSF edge-count/degenerate-graph checks of
    # the Julia tests, test_graph.jl:81-107).
    a1 = symmetrize(barabasi_albert(40, 2, seed=2))
    a2 = symmetrize(barabasi_albert(30, 2, seed=3))
    a = sparse.block_diag([a1, a2], format="csr")
    order = native.bfs_order(a)
    assert np.array_equal(np.sort(order), np.arange(70))
    first = order[:40]
    assert np.all(first < 40), "component 0 must be emitted first"


def test_bfs_matches_python_backend():
    # BFS is deterministic: both backends must produce identical orders
    # on a connected graph.
    a = symmetrize(barabasi_albert(300, 3, seed=5))
    np.testing.assert_array_equal(native.bfs_order(a), py_bfs(a))


def test_degenerate_graphs():
    # No edges at all: every component is a singleton.
    empty = sparse.csr_matrix((16, 16), dtype=np.float32)
    assert np.array_equal(native.bfs_order(empty), np.arange(16))
    order = native.random_forest_order(empty, np.random.default_rng(0))
    assert np.array_equal(np.sort(order), np.arange(16))
    # Empty matrix.
    zero = sparse.csr_matrix((0, 0), dtype=np.float32)
    assert native.bfs_order(zero).size == 0


@pytest.mark.parametrize("block_diagonal", [True, False])
def test_native_backend_invariants(block_diagonal):
    """Full decomposition invariant suite with backend='native'
    (reference test_arrowdecomposition.py:24-112 protocol)."""
    a = barabasi_albert(512, 4, seed=77)
    n = a.shape[0]
    width = 64
    levels = arrow_decomposition(a, width, max_levels=100,
                                 block_diagonal=block_diagonal, seed=3,
                                 backend="native")
    for lvl in levels:
        assert np.array_equal(np.sort(lvl.permutation), np.arange(n))
        w = lvl.arrow_width
        coo = lvl.matrix.tocoo()
        ok = (np.abs(coo.row - coo.col) <= w) | (coo.row < w) | (coo.col < w)
        assert bool(np.all(ok))
    diff = (reconstruct(levels) - a).tocsr()
    assert diff.nnz == 0 or np.max(np.abs(diff.data)) < 1e-6
    x = random_dense(n, 8, seed=1)
    np.testing.assert_allclose(decomposition_spmm(levels, x), a @ x,
                               rtol=1e-4, atol=1e-4)


def test_backend_quality_parity():
    """Native linearization must not degrade arrangement quality: the
    number of levels produced at a fixed width stays comparable."""
    a = erdos_renyi(512, 0.05, seed=9)
    ln = arrow_decomposition(a, 80, max_levels=100, block_diagonal=True,
                             seed=1, backend="native")
    lp = arrow_decomposition(a, 80, max_levels=100, block_diagonal=True,
                             seed=1, backend="numpy")
    assert len(ln) <= len(lp) + 2


def test_backend_validation():
    a = barabasi_albert(64, 2, seed=1)
    with pytest.raises(ValueError):
        arrow_decomposition(a, 8, backend="julia")


def test_masked_forest_order_matches_submatrix_contract():
    """random_forest_order_masked(A, active) == a valid forest order of
    A[active][:, active] in submatrix positions (a permutation; the
    induced-subgraph edges drive it — isolated actives become size-1
    components), without materializing the submatrix."""
    import numpy as np

    from arrow_matrix_tpu.decomposition import native
    from arrow_matrix_tpu.utils.graphs import barabasi_albert, symmetrize

    if not native.available():
        pytest.skip(f"native unavailable: {native.load_error()}")
    a = symmetrize(barabasi_albert(3000, 4, seed=5))
    deg = np.diff(a.indptr)
    middle = np.argsort(-deg, kind="stable")[64:2900]
    rng = np.random.default_rng(3)
    order = native.random_forest_order_masked(a, middle, rng)
    assert np.array_equal(np.sort(order), np.arange(middle.size))
    # An out-of-range or duplicated subset must be rejected.
    with pytest.raises(RuntimeError):
        native.random_forest_order_masked(
            a, np.array([0, 0], dtype=np.int64), rng)
    with pytest.raises(RuntimeError):
        native.random_forest_order_masked(
            a, np.array([-1], dtype=np.int64), rng)


def test_symmetrize_structure_matches_scipy():
    """Native structure-only symmetrize == scipy A + A.T pattern,
    including non-canonical input rows (unsorted, duplicated)."""
    if not native.available():
        pytest.skip(f"native unavailable: {native.load_error()}")
    rng = np.random.default_rng(11)
    n = 4096
    rows = rng.integers(0, n, 30000)
    cols = rng.integers(0, n, 30000)
    a = sparse.csr_matrix(
        (np.ones(30000, np.float32), (rows, cols)), shape=(n, n))
    # Genuinely non-canonical input: REVERSE every row's within-row
    # order and append each row's first column a second time
    # (duplicate entry) — the kernel's per-row sort + dedup paths must
    # both fire.
    mi, md = [], []
    indptr_m = [0]
    for r in range(n):
        lo, hi = a.indptr[r], a.indptr[r + 1]
        cols_r = a.indices[lo:hi][::-1].tolist()
        if cols_r:
            cols_r.append(cols_r[-1])   # duplicate
        mi.extend(cols_r)
        md.extend([1.0] * len(cols_r))
        indptr_m.append(len(mi))
    a_messy = sparse.csr_matrix(
        (np.asarray(md, np.float32), np.asarray(mi, np.int32),
         np.asarray(indptr_m)), shape=(n, n))
    assert not a_messy.has_sorted_indices or n == 0
    want = symmetrize(a)
    indptr, indices = native.symmetrize_structure(a_messy)
    assert np.array_equal(indptr, want.indptr.astype(np.int64))
    assert np.array_equal(indices, want.indices.astype(np.int32))
    # raw-pair input drives the masked forest identically to the
    # scipy-matrix input (same seed -> same order)
    deg = np.diff(indptr)
    middle = np.argsort(-deg, kind="stable")[128:]
    middle = middle[deg[middle] > 0]
    o_pair = native.random_forest_order_masked(
        (indptr, indices), middle, np.random.default_rng(7))
    o_mat = native.random_forest_order_masked(
        want, middle, np.random.default_rng(7))
    assert np.array_equal(o_pair, o_mat)


@pytest.mark.slow
def test_parallel_decomposer_thread_invariance_at_scale():
    """The parallel MSF (filter-Kruskal), parallel forest-adjacency
    fill, and level-synchronous linearization (VERDICT r4 item 3) must
    be BIT-identical to the single-thread stream for every thread
    count.  n=2^20 crosses every parallel threshold: m >= 2^19
    (filter-Kruskal), n >= 2^18 (adjacency fill), comp >= 2^16 with
    BFS levels >= 2^13 wide (level-sync sweeps' parallel branch —
    widest level ~28k on this graph).  Covers both graph classes and
    the masked path."""
    import os

    n = 1 << 20
    prior = os.environ.get("AMT_DECOMP_THREADS")
    try:
        for gen, kw in ((barabasi_albert, dict(m=4)),
                        (erdos_renyi, dict(p=8 / n))):
            a = symmetrize(gen(n, seed=9, **kw))
            outs = {}
            for t in (1, 2, 8):
                os.environ["AMT_DECOMP_THREADS"] = str(t)
                outs[t] = native.random_forest_order(
                    a, np.random.default_rng(4))
            assert np.array_equal(np.sort(outs[1]), np.arange(n))
            for t in (2, 8):
                assert np.array_equal(outs[1], outs[t]), (gen.__name__, t)
            deg = np.diff(a.indptr)
            middle = np.argsort(-deg, kind="stable")[256:]
            middle = middle[deg[middle] > 0]
            os.environ["AMT_DECOMP_THREADS"] = "1"
            m1 = native.random_forest_order_masked(
                a, middle, np.random.default_rng(7))
            os.environ["AMT_DECOMP_THREADS"] = "8"
            m8 = native.random_forest_order_masked(
                a, middle, np.random.default_rng(7))
            assert np.array_equal(m1, m8)
    finally:
        if prior is None:
            os.environ.pop("AMT_DECOMP_THREADS", None)
        else:
            os.environ["AMT_DECOMP_THREADS"] = prior


@pytest.mark.slow
def test_symmetrize_bucketed_fill_non_pow2_n():
    """The bucketed transpose fill (input nnz >= 2^22) with a
    NON-power-of-two n: the max column id n-1 must map to a valid
    bucket.  Regression for ADVICE r4 (high): the bucket shift was
    derived from n instead of n-1, so for any n in (256*2^s,
    257*2^s] id n-1 landed in bucket 256 of a 256-bucket table —
    out-of-bounds b_count/bf heap writes (observed SIGABRT at
    n=2^22+1) and a 257th bucket pass B never scattered."""
    rng = np.random.default_rng(13)
    n = (1 << 22) + 1          # in (256*2^14, 257*2^14]
    nnz = 1 << 23              # >= the 2^22 bucketed-path cutoff
    rows = rng.integers(0, n, nnz)
    cols = rng.integers(0, n, nnz)
    # Ensure the overflowing ids actually occur in the index stream.
    cols[:16] = n - 1
    a = sparse.csr_matrix(
        (np.ones(nnz, np.float32), (rows, cols)), shape=(n, n))
    assert a.indptr[-1] >= (1 << 22)
    want = symmetrize(a)
    indptr, indices = native.symmetrize_structure(a)
    assert np.array_equal(indptr, want.indptr.astype(np.int64))
    assert np.array_equal(indices, want.indices.astype(np.int32))


def test_threaded_native_parity():
    """AMT_DECOMP_THREADS must not change any native output (per-range
    buffers merge in deterministic order).  n must exceed
    parallel_ranges' 1<<16 parallelization threshold or both runs
    execute the identical single-thread path and the assertion is
    vacuous."""
    import os

    if not native.available():
        pytest.skip(f"native unavailable: {native.load_error()}")
    a = symmetrize(barabasi_albert(1 << 17, 6, seed=9))
    deg = np.diff(a.indptr)
    middle = np.argsort(-deg, kind="stable")[256:]
    middle = middle[deg[middle] > 0]
    prior = os.environ.get("AMT_DECOMP_THREADS")
    try:
        os.environ["AMT_DECOMP_THREADS"] = "1"
        o1 = native.random_forest_order_masked(
            a, middle, np.random.default_rng(4))
        s1 = native.symmetrize_structure(a)
        os.environ["AMT_DECOMP_THREADS"] = "4"
        o4 = native.random_forest_order_masked(
            a, middle, np.random.default_rng(4))
        s4 = native.symmetrize_structure(a)
    finally:
        if prior is None:
            os.environ.pop("AMT_DECOMP_THREADS", None)
        else:
            os.environ["AMT_DECOMP_THREADS"] = prior
    assert np.array_equal(o1, o4)
    assert np.array_equal(s1[0], s4[0]) and np.array_equal(s1[1], s4[1])


def test_level_split_matches_numpy_path():
    """The fused native split must produce the same levels as the
    numpy tocoo/select/build chain (canonical CSR is unique)."""
    if not native.available():
        pytest.skip(f"native unavailable: {native.load_error()}")
    rng = np.random.default_rng(2)
    n, width = 4096, 256
    a = barabasi_albert(n, 6, seed=2).astype(np.float32)
    inv = np.argsort(rng.permutation(n)).astype(np.int32)
    for bd, prune in ((True, True), (False, True), (True, False)):
        lvl, rest = native.level_split(a, inv, width, bd, prune)
        # numpy reference
        coo = a.tocoo()
        r, c = inv[coo.row], inv[coo.col]
        if bd:
            in_level = (r // width) == (c // width)
        else:
            in_level = np.abs(r.astype(np.int64)
                              - c.astype(np.int64)) <= width
        if prune:
            in_level |= (r < width) | (c < width)
        b = sparse.csr_matrix(
            (coo.data[in_level], (r[in_level], c[in_level])),
            shape=(n, n))
        b.sum_duplicates()
        b.sort_indices()
        assert (abs(lvl - b)).nnz == 0, (bd, prune)
        rest_ref = sparse.csr_matrix(
            (coo.data[~in_level],
             (coo.row[~in_level], coo.col[~in_level])), shape=(n, n))
        if rest is None:
            assert rest_ref.nnz == 0
        else:
            d = rest.tocsr() - rest_ref
            assert abs(d).nnz == 0 or abs(d).max() == 0, (bd, prune)


def test_level_split_weighted_f64_and_duplicates():
    if not native.available():
        pytest.skip(f"native unavailable: {native.load_error()}")
    rng = np.random.default_rng(4)
    n = 2048
    rows = rng.integers(0, n, 20000)
    cols = rng.integers(0, n, 20000)
    vals = rng.standard_normal(20000)
    a = sparse.csr_matrix((vals, (rows, cols)), shape=(n, n))
    lvl, rest = native.level_split(a, np.arange(n, dtype=np.int32),
                                   256, True, True)
    total = lvl + (rest if rest is not None else 0)
    want = a.tocsr()
    want.sum_duplicates()
    err = abs(total - want)
    assert err.nnz == 0 or err.max() < 1e-12
