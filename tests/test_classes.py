"""graft-classes tests: tolerance-certified traffic classes.

Covers the class model (``arrow_matrix_tpu/classes.py`` — itemsizes,
tolerances, certificate derivation and lookup), class-aware admission
(approx priced below exact at the same (structure, k);
exactly-at-budget admits; the per-GB economics), the loud-fallback
contract (certificate miss / short curve -> served exact with an
explicit reason, unknown class -> rejected), class-pure batching, the
reduced-precision executors (bf16 carriage, int8 ``(q, scale)`` fold
carriage), the real-int8 error probe, and the H4' prover relaxation
(declared accumulator widening allowed, reduced collective operands
required).  The end-to-end chaos form lives in tools/serve_gate.py's
``serve_classes`` scenario.
"""

import dataclasses

import numpy as np
import pytest

from arrow_matrix_tpu import classes as cls
from arrow_matrix_tpu.classes import (
    BF16_TOLERANCE,
    INT8_TOLERANCE,
    Certificate,
    certificate_from_record,
    class_itemsize,
    find_certificate,
    resolve_class,
    tolerance_for,
)
from arrow_matrix_tpu.serve import (
    ArrowServer,
    ExecConfig,
    ba_executor_factory,
    request_price_bytes,
    run_trace,
    slo_summary,
    synthetic_trace,
)

N, WIDTH, K, SEED = 64, 16, 2, 5
CURVE_ITERS = 4


@pytest.fixture(scope="module")
def factory():
    """One BA decomposition shared by every server in this module."""
    return ba_executor_factory(N, WIDTH, SEED, fmt="fold")


@pytest.fixture(scope="module")
def curves():
    """Real probed error curves for the module's structure — the
    certificate source (never hand-declared)."""
    from arrow_matrix_tpu.ledger.probe import error_curves_for_source

    source = {"kind": "ba", "n": N, "m": 3, "width": WIDTH,
              "seed": SEED}
    return error_curves_for_source(source, k=K,
                                   iterations=CURVE_ITERS, seed=SEED,
                                   dtypes=("f32", "bf16", "int8"))


@pytest.fixture(scope="module")
def cert(curves):
    c = certificate_from_record(
        next(r for r in curves if r["knobs"]["dtype"] == "bf16"))
    assert c is not None and c.covers(CURVE_ITERS)
    return c


def _trace(n_rows, requests=2, iterations=2, traffic_class="exact"):
    trace = synthetic_trace(n_rows, tenants=1, requests=requests,
                            k=K, iterations=iterations, seed=SEED)
    return [dataclasses.replace(r, traffic_class=traffic_class)
            for r in trace]


# ---------------------------------------------------------------------------
# The class model (classes.py)
# ---------------------------------------------------------------------------

def test_resolve_class_and_itemsize():
    assert resolve_class("exact").itemsize == 4
    assert resolve_class("exact").feature_dtype is None
    assert not resolve_class("exact").needs_certificate
    bf16 = resolve_class("approx")
    assert (bf16.feature_dtype, bf16.itemsize,
            bf16.tolerance) == ("bf16", 2, BF16_TOLERANCE)
    int8 = resolve_class("approx", int8=True)
    assert (int8.feature_dtype, int8.itemsize,
            int8.tolerance) == ("int8", 1, INT8_TOLERANCE)
    with pytest.raises(ValueError, match="unknown traffic class"):
        resolve_class("bogus")
    assert class_itemsize(None) == class_itemsize("f32") == 4
    assert class_itemsize("bf16") == 2 and class_itemsize("int8") == 1
    with pytest.raises(ValueError, match="no class itemsize"):
        class_itemsize("f64")
    assert tolerance_for(None) == tolerance_for("f32") == 0.0
    with pytest.raises(ValueError):
        tolerance_for("f16")


def test_certificate_bound_is_prefix_max_and_never_extrapolates():
    c = Certificate(structure_hash="s", dtype="bf16",
                    rel_frobenius=(1e-3, 5e-3, 2e-3),
                    tolerance=BF16_TOLERANCE)
    assert c.iterations == 3
    assert c.bound_at(1) == 1e-3
    assert c.bound_at(3) == 5e-3          # max over the prefix
    assert c.bound_at(0) is None          # degenerate
    assert c.bound_at(4) is None          # measured, not modeled
    assert c.covers(3) and not c.covers(4)
    tight = Certificate(structure_hash="s", dtype="bf16",
                        rel_frobenius=(1e-3, 3e-2),
                        tolerance=BF16_TOLERANCE)
    assert tight.covers(1) and not tight.covers(2)


def test_certificate_from_record_rejects_noncurves_and_f32():
    rec = {"kind": "bench", "payload": {"rel_frobenius": [1e-3]},
           "knobs": {"dtype": "bf16"}}
    assert certificate_from_record(rec) is None
    rec = {"kind": "error_curve", "payload": {"rel_frobenius": [0.0]},
           "knobs": {"dtype": "f32"}, "structure_hash": "s"}
    assert certificate_from_record(rec) is None   # golden certifies nothing
    rec = {"kind": "error_curve", "payload": {},
           "knobs": {"dtype": "bf16"}, "structure_hash": "s"}
    assert certificate_from_record(rec) is None   # no curve payload


def _curve_record(shash, dtype, curve, emulated=False, rid="r"):
    return {"kind": "error_curve", "structure_hash": shash,
            "record_id": rid,
            "knobs": {"dtype": dtype, "emulated": emulated, "seed": 0},
            "payload": {"rel_frobenius": list(curve)}}


def test_find_certificate_newest_wins_and_emulated_rejected():
    recs = [
        _curve_record("s", "bf16", [1e-3], rid="old"),
        _curve_record("s", "bf16", [2e-3], rid="new"),
        _curve_record("other", "bf16", [9e-1], rid="other"),
        _curve_record("s", "int8", [5e-2], emulated=True, rid="emu"),
    ]
    c = find_certificate("s", "bf16", records=recs)
    assert c is not None and c.record_id == "new"
    # An emulated curve never certifies the real carriage by default.
    assert find_certificate("s", "int8", records=recs) is None
    emu = find_certificate("s", "int8", records=recs,
                           allow_emulated=True)
    assert emu is not None and emu.emulated
    assert find_certificate("missing", "bf16", records=recs) is None


# ---------------------------------------------------------------------------
# Class-aware admission (the per-GB economics)
# ---------------------------------------------------------------------------

def test_approx_priced_below_exact_same_structure_k(factory, cert):
    """Approx admission reserves the TRUE (bf16) carriage bytes —
    exactly half the exact price at the same (structure, k)."""
    fac, n_rows = factory
    srv = ArrowServer(fac, ExecConfig(), certificates=[cert],
                      name="price")
    tickets = run_trace(
        srv, _trace(n_rows, traffic_class="approx")
        + _trace(n_rows, traffic_class="exact"))
    approx, exact = tickets[0], tickets[-1]
    assert approx.served_class == "approx"
    assert exact.served_class == "exact"
    assert 0 < approx.predicted_bytes < exact.predicted_bytes
    assert approx.predicted_bytes * 2 == exact.predicted_bytes
    ex = fac(ExecConfig())
    assert exact.predicted_bytes == request_price_bytes(ex, K)
    assert approx.predicted_bytes == request_price_bytes(ex, K,
                                                         itemsize=2)


def test_approx_admits_exactly_at_budget_and_more_per_gb(factory,
                                                         cert):
    """A budget with headroom for exactly one EXACT request admits two
    concurrent approx requests (<=, not <) — and the same budget
    admits only one exact + one explicit rejection."""
    from arrow_matrix_tpu.obs.memview import predicted_bytes_for

    fac, n_rows = factory
    ex = fac(ExecConfig())
    resident = predicted_bytes_for(ex, 0) or 0
    exact_price = request_price_bytes(ex, K)
    budget = resident + exact_price

    srv = ArrowServer(fac, ExecConfig(), certificates=[cert],
                      hbm_budget_bytes=budget, name="budget-approx")
    tickets = [srv.submit(r) for r in
               _trace(n_rows, requests=2, traffic_class="approx")]
    srv.drain()
    s = srv.summary()
    assert (s["admitted"], s["rejected"]) == (2, 0)
    assert all(t.status == "completed" for t in tickets)
    assert s["hbm"]["peak_in_use_bytes"] <= budget

    srv = ArrowServer(fac, ExecConfig(), certificates=[cert],
                      hbm_budget_bytes=budget, name="budget-exact")
    tickets = [srv.submit(r) for r in
               _trace(n_rows, requests=2, traffic_class="exact")]
    srv.drain()
    s = srv.summary()
    assert (s["admitted"], s["rejected"]) == (1, 1)
    assert tickets[1].status == "rejected"
    assert tickets[1].reason == "hbm_budget"


def test_unknown_class_rejected_explicitly(factory):
    fac, n_rows = factory
    srv = ArrowServer(fac, ExecConfig(), name="unknown")
    t = srv.submit(dataclasses.replace(
        _trace(n_rows, requests=1)[0], traffic_class="turbo"))
    srv.drain()
    assert t.status == "rejected"
    assert t.reason == "unknown_class"


# ---------------------------------------------------------------------------
# The loud-fallback contract: never silent approx, never silent exact
# ---------------------------------------------------------------------------

def test_certificate_miss_falls_back_exact_loudly(factory):
    """No certificate -> the approx request is served EXACT with an
    explicit reason and bit-identical results — never silently served
    reduced precision."""
    fac, n_rows = factory
    ref_srv = ArrowServer(fac, ExecConfig(), name="ref")
    ref = run_trace(ref_srv, _trace(n_rows))

    srv = ArrowServer(fac, ExecConfig(), name="nocert")   # no certs
    tickets = run_trace(srv, _trace(n_rows, traffic_class="approx"))
    for t, r in zip(tickets, ref):
        assert t.status == "completed"
        assert t.served_class == "exact"
        assert t.class_fallback == "no_certificate"
        assert t.certified_bound is None
        assert t.result.tobytes() == r.result.tobytes()
    assert srv.summary()["class_fallback"] == len(tickets)


def test_curve_shorter_than_request_falls_back_exact(factory, cert):
    fac, n_rows = factory
    srv = ArrowServer(fac, ExecConfig(), certificates=[cert],
                      name="short")
    deep = _trace(n_rows, requests=1, iterations=CURVE_ITERS + 2,
                  traffic_class="approx")
    t = run_trace(srv, deep)[0]
    assert t.status == "completed"
    assert t.served_class == "exact"
    assert t.class_fallback == "curve_shorter_than_request"


def test_exact_requests_never_served_approx(factory, cert):
    """Certificates present is not permission: exact traffic on a
    certificate-holding server stays bit-identical f32."""
    fac, n_rows = factory
    ref = run_trace(ArrowServer(fac, ExecConfig(), name="ref2"),
                    _trace(n_rows))
    srv = ArrowServer(fac, ExecConfig(), certificates=[cert],
                      name="exact-beside-cert")
    tickets = run_trace(srv, _trace(n_rows))
    for t, r in zip(tickets, ref):
        assert t.served_class == "exact" and t.class_fallback is None
        assert t.result.tobytes() == r.result.tobytes()


def test_approx_served_within_tolerance_not_bitwise(factory, cert):
    """A certified approx request actually runs the bf16 carriage:
    the result drifts from the f32 replay (nonzero) but stays within
    the class tolerance, and the ticket carries the certified bound."""
    fac, n_rows = factory
    ref = run_trace(ArrowServer(fac, ExecConfig(), name="ref3"),
                    _trace(n_rows))
    srv = ArrowServer(fac, ExecConfig(), certificates=[cert],
                      name="approx")
    tickets = run_trace(srv, _trace(n_rows, traffic_class="approx"))
    for t, r in zip(tickets, ref):
        assert t.status == "completed"
        assert t.served_class == "approx"
        assert t.class_fallback is None
        assert t.certified_bound == cert.bound_at(2)
        assert t.exec_config.feature_dtype == "bf16"
        d = t.result.astype(np.float64) - r.result.astype(np.float64)
        rel = float(np.linalg.norm(d)
                    / np.linalg.norm(r.result.astype(np.float64)))
        assert 0.0 < rel <= cert.tolerance


# ---------------------------------------------------------------------------
# Class-pure batching
# ---------------------------------------------------------------------------

def test_mixed_class_batch_never_merged(factory, cert):
    """With feature-axis batching on and both classes queued, batches
    stay class-pure: same-class neighbors merge, classes never do —
    every exact result stays bit-identical beside approx traffic."""
    fac, n_rows = factory
    ref = run_trace(ArrowServer(fac, ExecConfig(), name="ref4"),
                    _trace(n_rows))

    srv = ArrowServer(fac, ExecConfig(), certificates=[cert],
                      max_batch_k=2 * K, name="batch")
    trace = (_trace(n_rows, traffic_class="approx")
             + _trace(n_rows, traffic_class="exact"))
    tickets = [srv.submit(r) for r in trace]    # burst, then drain
    srv.drain()
    s = srv.summary()
    # Same-class neighbors DID merge (batching is on and working)...
    assert s["batches"] >= 1 and s["batched_requests"] >= 2
    # ...but across classes never: exact results are f32-bit-identical
    # and approx results drifted (each class ran its own carriage).
    for t, r in zip(tickets[2:], ref):
        assert t.result.tobytes() == r.result.tobytes()
    for t, r in zip(tickets[:2], ref):
        assert t.served_class == "approx"
        assert t.result.tobytes() != r.result.tobytes()


# ---------------------------------------------------------------------------
# SLO report + pulse: the class dimension
# ---------------------------------------------------------------------------

def test_slo_summary_and_pulse_carry_per_class(factory, cert):
    from arrow_matrix_tpu.obs import pulse as pulse_mod

    fac, n_rows = factory
    srv = ArrowServer(fac, ExecConfig(), certificates=[cert],
                      name="slo")
    mon = pulse_mod.PulseMonitor(window_s=60.0, name="slo")
    srv.attach_pulse(mon)
    tickets = run_trace(
        srv, _trace(n_rows, traffic_class="approx") + _trace(n_rows))
    mon.close()
    summary = slo_summary(srv, tickets, wall_s=1.0, pulse=mon)
    pc = summary["per_class"]
    assert set(pc) == {"exact", "approx"}
    assert pc["approx"]["completed"] == 2
    assert pc["exact"]["completed"] == 2
    assert pc["approx"]["latency_ms"]["count"] == 2
    assert summary["class_fallback"] == 0
    assert "bf16" in summary["certificates"]
    totals = mon.totals_dict()
    assert totals["per_class"]["approx"]["completed"] == 2
    assert totals["per_class"]["exact"]["completed"] == 2
    assert pulse_mod.validate_exposition(mon.exposition_text()) == []


# ---------------------------------------------------------------------------
# Reduced-precision executors (the carriage the classes serve)
# ---------------------------------------------------------------------------

def _fold_pair(feature_dtype):
    from arrow_matrix_tpu.decomposition import arrow_decomposition
    from arrow_matrix_tpu.parallel import MultiLevelArrow
    from arrow_matrix_tpu.utils import barabasi_albert

    a = barabasi_albert(N, 3, seed=SEED)
    levels = arrow_decomposition(a, WIDTH, max_levels=6,
                                 block_diagonal=True, seed=SEED)
    f32 = MultiLevelArrow(levels, WIDTH, mesh=None, fmt="fold")
    probed = MultiLevelArrow(levels, WIDTH, mesh=None, fmt="fold",
                             feature_dtype=feature_dtype)
    return f32, probed


def _run_steps(multi, x_host, steps):
    import jax

    x = multi.set_features(x_host)
    for _ in range(steps):
        x = multi.step(x)
    jax.block_until_ready(x)
    return multi.gather_result(x), x


def test_bf16_fold_carriage_halves_bytes_within_tolerance():
    f32, bf16 = _fold_pair("bf16")
    x_host = np.random.default_rng(SEED).standard_normal(
        (f32.n, K)).astype(np.float32)
    gold, xg = _run_steps(f32, x_host, 2)
    got, xb = _run_steps(bf16, x_host, 2)
    assert xb.dtype.itemsize * 2 == xg.dtype.itemsize
    assert got.dtype == np.float32 and got.shape == gold.shape
    rel = np.linalg.norm(got.astype(np.float64) - gold.astype(
        np.float64)) / np.linalg.norm(gold.astype(np.float64))
    assert 0.0 < rel <= BF16_TOLERANCE


def test_int8_fold_carriage_is_quantized_pair_within_tolerance():
    f32, int8 = _fold_pair("int8")
    x_host = np.random.default_rng(SEED).standard_normal(
        (f32.n, K)).astype(np.float32)
    gold, _ = _run_steps(f32, x_host, 2)
    got, carry = _run_steps(int8, x_host, 2)
    assert isinstance(carry, tuple) and len(carry) == 2
    q, scale = carry
    assert q.dtype == np.int8
    assert scale.dtype == np.float32
    # 4x fewer carriage bytes than f32 (+ the per-row f32 scale).
    assert q.size == np.prod(np.asarray(
        (int8.total_rows if hasattr(int8, "total_rows")
         else q.shape[0], K)))
    assert got.dtype == np.float32 and got.shape == gold.shape
    rel = np.linalg.norm(got.astype(np.float64) - gold.astype(
        np.float64)) / np.linalg.norm(gold.astype(np.float64))
    assert 0.0 < rel <= INT8_TOLERANCE


def test_sell_slim_rejects_int8_carriage():
    from arrow_matrix_tpu.decomposition import arrow_decomposition
    from arrow_matrix_tpu.parallel import make_mesh
    from arrow_matrix_tpu.parallel.sell_slim import SellMultiLevel
    from arrow_matrix_tpu.utils import barabasi_albert

    a = barabasi_albert(N, 3, seed=SEED)
    levels = arrow_decomposition(a, WIDTH, max_levels=4,
                                 block_diagonal=True, seed=SEED)
    mesh = make_mesh((4,), ("blocks",))
    with pytest.raises(ValueError, match="int8"):
        SellMultiLevel(levels, WIDTH, mesh, routing="a2a",
                       feature_dtype="int8")


# ---------------------------------------------------------------------------
# The probe: real int8, golden-zero f32
# ---------------------------------------------------------------------------

def test_error_curves_real_int8_and_golden_zero(curves):
    by_dtype = {r["knobs"]["dtype"]: r for r in curves}
    assert set(by_dtype) == {"f32", "bf16", "int8"}
    # The f32 curve is identically zero BY CONSTRUCTION.
    assert all(p == 0.0
               for p in by_dtype["f32"]["payload"]["rel_frobenius"])
    # int8 records the REAL device carriage, not the emulation.
    assert by_dtype["int8"]["knobs"]["emulated"] is False
    bf16_curve = by_dtype["bf16"]["payload"]["rel_frobenius"]
    assert len(bf16_curve) == CURVE_ITERS
    assert all(0.0 < p <= BF16_TOLERANCE for p in bf16_curve)


# ---------------------------------------------------------------------------
# H4' (analysis/prove.py): declared widening, reduced operands
# ---------------------------------------------------------------------------

_BF16_STEP = """\
HloModule classed_step
ENTRY %main (p0: bf16[4,8]) -> bf16[4,8] {
  %p0 = bf16[4,8]{1,0} parameter(0)
  %acc = f32[4,8]{1,0} convert(bf16[4,8]{1,0} %p0)
  ROOT %a2a = bf16[4,8]{1,0} all-to-all(bf16[4,8]{1,0} %p0), replica_groups={{0,1}}
}
"""

_BF16_STEP_F32_COLLECTIVE = _BF16_STEP.replace(
    "ROOT %a2a = bf16[4,8]{1,0} all-to-all(bf16[4,8]{1,0} %p0)",
    "ROOT %a2a = f32[4,8]{1,0} all-to-all(f32[4,8]{1,0} %acc)")


def _contract(dtype):
    from arrow_matrix_tpu.analysis.contracts import CollectiveContract

    return CollectiveContract(
        algorithm="t", step_bytes=64, reduce_bytes=0, repl=1,
        overlap_slabs=1, dtype=dtype, lowered_kinds=("all-to-all",),
        compiled_kinds=("all-to-all",), ratio_band=(0.1, 4.0))


def test_h4_prime_allows_declared_accumulator_widening():
    from arrow_matrix_tpu.analysis import prove

    summ = prove.summarize_hlo(_BF16_STEP)
    assert summ.collective_dtypes == ["bf16"]
    r = prove.check_h4(summ, _contract("bf16"))
    assert r["status"] == "pass", r
    assert "H4'" in r["detail"]
    # The SAME program under an exact contract: the bf16->f32 convert
    # is an undeclared widening — original H4 still trips.
    r = prove.check_h4(summ, _contract("f32"))
    assert r["status"] == "fail"
    assert "bf16->f32" in r["detail"]


def test_h4_prime_requires_reduced_collective_operands():
    from arrow_matrix_tpu.analysis import prove

    summ = prove.summarize_hlo(_BF16_STEP_F32_COLLECTIVE)
    r = prove.check_h4(summ, _contract("bf16"))
    assert r["status"] == "fail"
    assert "never earned" in r["detail"]


def test_contract_ideal_bytes_scale_with_carriage_dtype():
    """The executor contract's ideal band halves at bf16 by default
    (itemsize resolves to the carried dtype), and the explicit
    itemsize override still wins."""
    from arrow_matrix_tpu.decomposition import arrow_decomposition
    from arrow_matrix_tpu.parallel import make_mesh
    from arrow_matrix_tpu.parallel.sell_slim import SellMultiLevel
    from arrow_matrix_tpu.utils import barabasi_albert

    a = barabasi_albert(N, 3, seed=SEED)
    levels = arrow_decomposition(a, WIDTH, max_levels=4,
                                 block_diagonal=True, seed=SEED)
    mesh = make_mesh((4,), ("blocks",))
    f32 = SellMultiLevel(levels, WIDTH, mesh, routing="a2a")
    bf16 = SellMultiLevel(levels, WIDTH, mesh, routing="a2a",
                          feature_dtype="bf16")
    cf, cb = f32.collective_contract(K), bf16.collective_contract(K)
    assert cf.dtype == "f32" and cb.dtype == "bf16"
    assert cf.step_bytes == 2 * cb.step_bytes > 0
    assert bf16.collective_contract(K, itemsize=4).step_bytes \
        == cf.step_bytes
