"""Model-family tests: SGC forward/train, power iteration, pagerank,
label propagation — all against dense numpy goldens."""

import numpy as np
import optax
import pytest
from scipy import sparse

import jax
import jax.numpy as jnp

from arrow_matrix_tpu.decomposition.decompose import (
    arrow_decomposition,
    decomposition_spmm,
)
from arrow_matrix_tpu.models.propagation import (
    SGCModel,
    label_propagation,
    make_train_step,
    pagerank,
    power_iteration,
    sgc_init,
)
from arrow_matrix_tpu.parallel.mesh import make_mesh
from arrow_matrix_tpu.parallel.multi_level import MultiLevelArrow
from arrow_matrix_tpu.utils.graphs import barabasi_albert, random_dense


WIDTH = 8


def _problem(n=128, seed=0):
    a = barabasi_albert(n, 3, seed=seed)
    levels = arrow_decomposition(a, arrow_width=WIDTH, max_levels=2,
                                 block_diagonal=True, seed=seed)
    return a, levels


def test_sgc_forward_matches_dense():
    n, k_in, k_out, hops = 128, 8, 4, 2
    a, levels = _problem(n)
    multi = MultiLevelArrow(levels, WIDTH, mesh=None)
    model = SGCModel(multi, k_in, k_out, hops=hops, seed=1)

    x = random_dense(n, k_in, seed=2)
    got = model.predict(x)

    ad = a.toarray()
    want = x
    for _ in range(hops):
        want = ad @ want
    w = np.asarray(model.params.w)
    b = np.asarray(model.params.b)
    np.testing.assert_allclose(got, want @ w + b, rtol=1e-4, atol=1e-4)


def test_sgc_forward_sharded_matches_single():
    n, k_in, k_out, hops = 128, 8, 4, 2
    _, levels = _problem(n)
    x = random_dense(n, k_in, seed=2)

    single = SGCModel(MultiLevelArrow(levels, WIDTH, mesh=None),
                      k_in, k_out, hops=hops, seed=1)
    mesh = make_mesh()
    sharded = SGCModel(MultiLevelArrow(levels, WIDTH, mesh=mesh),
                       k_in, k_out, hops=hops, seed=1)
    np.testing.assert_allclose(single.predict(x), sharded.predict(x),
                               rtol=1e-4, atol=1e-4)


def test_sgc_training_decreases_loss():
    n, k_in, k_out, hops = 128, 8, 4, 1
    a, levels = _problem(n)
    multi = MultiLevelArrow(levels, WIDTH, mesh=None)

    rng = np.random.default_rng(0)
    x_host = random_dense(n, k_in, seed=3)
    # Learnable target: a fixed linear map of the propagated features.
    w_true = rng.standard_normal((k_in, k_out)).astype(np.float32)
    y_host = (np.asarray(a @ x_host) @ w_true)

    x = multi.set_features(x_host)
    y_pad = np.zeros((multi.total_rows, k_out), np.float32)
    y_pad[:n] = y_host
    y = multi.place_features(y_pad[multi.perm0])
    mask = multi.real_row_mask()[:, 0]

    params = sgc_init(jax.random.key(0), k_in, k_out)
    optimizer = optax.adam(5e-2)
    opt_state = optimizer.init(params)
    step = make_train_step(tuple(multi.widths), hops, optimizer)

    losses = []
    for _ in range(200):
        params, opt_state, loss = step(params, opt_state, x, y, mask,
                                       multi.fwd, multi.bwd, multi.blocks)
        losses.append(float(loss))
    assert losses[-1] < 0.2 * losses[0], losses[::10]


def test_power_iteration_dominant_eigenpair():
    n = 96
    a, levels = _problem(n, seed=4)
    multi = MultiLevelArrow(levels, WIDTH, mesh=None)
    v, lam = power_iteration(multi, np.ones((n, 1), np.float32),
                             iterations=150)

    w = np.linalg.eigvalsh(a.toarray())
    lam_true = w[np.argmax(np.abs(w))]
    assert abs(lam - lam_true) / abs(lam_true) < 1e-2
    # Eigenvector residual ||Av - lam v|| small relative to |lam|.
    res = np.linalg.norm(a @ v - lam * v) / (abs(lam) * np.linalg.norm(v))
    assert res < 5e-2


def test_power_iteration_on_sell_orchestrations():
    """power_iteration on the feature-major mesh orchestrations: their
    tier pads hold routed filler after a step and the space-shared
    carriage holds K copies of the vector — carried_mask weights the
    reductions so the eigenpair still comes out right."""
    from arrow_matrix_tpu.parallel import SellMultiLevel, SellSpaceShared
    from arrow_matrix_tpu.parallel.mesh import make_mesh

    n = 96
    a, levels = _problem(n, seed=4)
    assert len(levels) == 2
    w = np.linalg.eigvalsh(a.toarray())
    lam_true = w[np.argmax(np.abs(w))]
    for multi in (
        SellMultiLevel(levels, WIDTH, make_mesh((4,), ("blocks",))),
        SellSpaceShared(levels, WIDTH,
                        make_mesh((2, 2), ("lvl", "blocks"))),
    ):
        v, lam = power_iteration(multi, np.ones((n, 1), np.float32),
                                 iterations=150)
        assert abs(lam - lam_true) / abs(lam_true) < 1e-2, type(multi)
        res = (np.linalg.norm(a @ v - lam * v)
               / (abs(lam) * np.linalg.norm(v)))
        assert res < 5e-2, type(multi)


def test_pagerank_matches_dense_iteration():
    n, d, iters = 96, 0.85, 40
    a, _ = _problem(n, seed=5)
    # Column-normalize then decompose the normalized operator.
    deg = np.maximum(np.asarray(a.sum(axis=0)).ravel(), 1.0)
    a_norm = (a @ sparse.diags(1.0 / deg)).tocsr()
    levels = arrow_decomposition(a_norm, arrow_width=WIDTH, max_levels=2,
                                 block_diagonal=True, seed=5)
    multi = MultiLevelArrow(levels, WIDTH, mesh=None)

    got = pagerank(multi, damping=d, iterations=iters)

    an = a_norm.toarray()
    r = np.full((n, 1), 1.0 / n)
    for _ in range(iters):
        r = d * (an @ r) + (1 - d) / n
    np.testing.assert_allclose(got, r, rtol=1e-4, atol=1e-6)


def test_label_propagation_matches_dense_iteration():
    n, c, iters = 96, 3, 15
    a, _ = _problem(n, seed=6)
    deg = np.maximum(np.asarray(a.sum(axis=1)).ravel(), 1.0)
    a_norm = (sparse.diags(1.0 / deg) @ a).tocsr()
    levels = arrow_decomposition(a_norm, arrow_width=WIDTH, max_levels=2,
                                 block_diagonal=True, seed=6)
    multi = MultiLevelArrow(levels, WIDTH, mesh=None)

    rng = np.random.default_rng(1)
    labels = np.eye(c, dtype=np.float32)[rng.integers(0, c, n)]
    seed_mask = rng.random(n) < 0.2

    got = label_propagation(multi, labels, seed_mask, iterations=iters)

    an = a_norm.toarray()
    seeds = labels * seed_mask[:, None]
    y = labels.copy()
    for _ in range(iters):
        y = an @ y
        y = np.where(seed_mask[:, None], seeds, y)
    np.testing.assert_allclose(got, y, rtol=1e-4, atol=1e-5)


def test_graft_entry_and_dryrun():
    import __graft_entry__ as ge

    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out)
    assert out.ndim == 2 and np.all(np.isfinite(np.asarray(out)))

    ge.dryrun_multichip(jax.device_count())


def test_gcn_forward_matches_dense_golden():
    """GCN layers vs an explicit numpy reimplementation on A."""
    import optax

    from arrow_matrix_tpu.models.propagation import (
        GCNModel,
        gcn_init,
        make_gcn_train_step,
    )

    n, width = 320, 32
    a = barabasi_albert(n, 4, seed=21)
    levels = arrow_decomposition(a, width, max_levels=3,
                                 block_diagonal=True, seed=2)
    ml = MultiLevelArrow(levels, width, mesh=None, fmt="ell")
    model = GCNModel(ml, dims=(8, 16, 4), seed=3)
    x = random_dense(n, 8, seed=4)
    got = model.predict(x)

    ad = np.asarray(a.todense()).astype(np.float32)
    h = x
    for i, p in enumerate(model.params):
        h = ad @ h
        h = h @ np.asarray(p.w) + np.asarray(p.b)
        if i < len(model.params) - 1:
            h = np.maximum(h, 0.0)
    np.testing.assert_allclose(got, h, rtol=1e-3, atol=1e-3)

    # Training step reduces the masked loss on the sharded path too.
    mesh = make_mesh((8,), ("blocks",))
    mls = MultiLevelArrow(levels, width, mesh=mesh, fmt="ell")
    params = gcn_init(jax.random.key(0), [8, 16, 4])
    opt = optax.adam(1e-2)
    opt_state = opt.init(params)
    step = make_gcn_train_step(tuple(mls.widths), opt)
    xs = mls.set_features(x)
    y = mls.set_features(random_dense(n, 4, seed=5))
    mask = np.asarray(mls.real_row_mask())[:, 0]
    import jax as _jax
    mask = _jax.device_put(mask, xs.sharding)
    losses = []
    for _ in range(5):
        params, opt_state, loss = step(params, opt_state, xs, y, mask,
                                       mls.fwd, mls.bwd, mls.blocks)
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_sgc_carried_on_feature_major_executors():
    """SGCCarried == flat SGCModel forward (same seed/hops) on every
    feature-major executor — fold single-chip, SellMultiLevel,
    SellSpaceShared — and its head fit converges with the carried
    mask."""
    from arrow_matrix_tpu.models.propagation import SGCCarried, SGCModel
    from arrow_matrix_tpu.parallel import (
        SellMultiLevel,
        SellSpaceShared,
        make_mesh,
    )

    n, k_in, k_out, hops = 128, 8, 4, 2
    a, levels = _problem(n)
    assert len(levels) == 2
    x = random_dense(n, k_in, seed=2)

    flat = SGCModel(MultiLevelArrow(levels, WIDTH, mesh=None),
                    k_in, k_out, hops=hops, seed=0)
    want = flat.predict(x)

    executors = [
        MultiLevelArrow(levels, WIDTH, mesh=None, fmt="fold"),
        SellMultiLevel(levels, WIDTH, make_mesh((4,), ("blocks",))),
        SellSpaceShared(levels, WIDTH,
                        make_mesh((2, 2), ("lvl", "blocks"))),
    ]
    for multi in executors:
        m = SGCCarried(multi, k_in, k_out, hops=hops, seed=0)
        got = m.predict(x)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    # Head fit converges (same contract as the flat training test).
    rng = np.random.default_rng(5)
    y = rng.standard_normal((n, k_out)).astype(np.float32)
    m = SGCCarried(executors[1], k_in, k_out, hops=hops, seed=0)
    losses = m.fit(x, y, steps=60)
    assert losses[-1] < 0.5 * losses[0], losses[::15]

    # Flat executors are the sibling class's job - rejected up front.
    with pytest.raises(ValueError, match="feature-major"):
        SGCCarried(MultiLevelArrow(levels, WIDTH, mesh=None),
                   k_in, k_out)


def test_gcn_carried_on_feature_major_executors():
    """GCNCarried forward parity with the flat GCNModel (same seed) on
    fold / sell / sell-space, and training THROUGH the distributed
    step (grads across shard_map psum/ppermute/gathers) converges."""
    from arrow_matrix_tpu.models.propagation import GCNCarried, GCNModel
    from arrow_matrix_tpu.parallel import (
        SellMultiLevel,
        SellSpaceShared,
        make_mesh,
    )

    n, dims = 128, (8, 12, 4)
    a, levels = _problem(n)
    assert len(levels) == 2
    x = random_dense(n, dims[0], seed=2)

    flat = GCNModel(MultiLevelArrow(levels, WIDTH, mesh=None),
                    dims=dims, seed=0)
    want = flat.predict(x)

    executors = [
        MultiLevelArrow(levels, WIDTH, mesh=None, fmt="fold"),
        SellMultiLevel(levels, WIDTH, make_mesh((4,), ("blocks",))),
        SellSpaceShared(levels, WIDTH,
                        make_mesh((2, 2), ("lvl", "blocks"))),
    ]
    for multi in executors:
        m = GCNCarried(multi, dims=dims, seed=0)
        np.testing.assert_allclose(m.predict(x), want,
                                   rtol=1e-4, atol=1e-4)

    rng = np.random.default_rng(5)
    y = rng.standard_normal((n, dims[-1])).astype(np.float32)
    m = GCNCarried(executors[2], dims=dims, seed=0)
    losses = m.fit(x, y, steps=60)
    assert losses[-1] < 0.5 * losses[0], losses[::15]

    with pytest.raises(ValueError, match="feature-major"):
        GCNCarried(MultiLevelArrow(levels, WIDTH, mesh=None), dims=dims)


def test_pagerank_and_labelprop_on_carried_executors():
    """pagerank_carried / label_propagation_carried match the flat
    drivers bit-for-tolerance on fold, sell, and sell-space — the
    teleport/seed vectors ride set_features, so every carriage
    (including the space-shared K-copy one) clamps correctly."""
    from arrow_matrix_tpu.models.propagation import (
        label_propagation,
        label_propagation_carried,
        pagerank,
        pagerank_carried,
    )
    from arrow_matrix_tpu.parallel import (
        SellMultiLevel,
        SellSpaceShared,
        make_mesh,
    )

    n, iters = 96, 25
    a, _ = _problem(n, seed=5)
    deg = np.maximum(np.asarray(a.sum(axis=0)).ravel(), 1.0)
    a_norm = (a @ sparse.diags(1.0 / deg)).tocsr()
    levels = arrow_decomposition(a_norm, arrow_width=WIDTH, max_levels=2,
                                 block_diagonal=True, seed=5)
    assert len(levels) == 2

    flat = MultiLevelArrow(levels, WIDTH, mesh=None)
    want_pr = pagerank(flat, damping=0.85, iterations=iters)

    rng = np.random.default_rng(1)
    labels = np.eye(3, dtype=np.float32)[rng.integers(0, 3, n)]
    seed_mask = rng.random(n) < 0.2
    want_lp = label_propagation(flat, labels, seed_mask,
                                iterations=iters)

    executors = [
        MultiLevelArrow(levels, WIDTH, mesh=None, fmt="fold"),
        SellMultiLevel(levels, WIDTH, make_mesh((4,), ("blocks",))),
        SellSpaceShared(levels, WIDTH,
                        make_mesh((2, 2), ("lvl", "blocks"))),
    ]
    for multi in executors:
        got_pr = pagerank_carried(multi, damping=0.85, iterations=iters)
        np.testing.assert_allclose(got_pr, want_pr, rtol=1e-4,
                                   atol=1e-6, err_msg=str(type(multi)))
        got_lp = label_propagation_carried(multi, labels, seed_mask,
                                           iterations=iters)
        np.testing.assert_allclose(got_lp, want_lp, rtol=1e-4,
                                   atol=1e-5, err_msg=str(type(multi)))


def test_appnp_flat_and_carried():
    """APPNP: dense numpy golden (head then (1-a)AZ + aH hops) vs the
    flat model and every feature-major executor; carried fit converges
    with gradients crossing the distributed step."""
    from arrow_matrix_tpu.models.propagation import (
        APPNPCarried,
        APPNPModel,
    )
    from arrow_matrix_tpu.parallel import (
        SellMultiLevel,
        SellSpaceShared,
        make_mesh,
    )

    n, k_in, k_out, hops, alpha = 128, 6, 3, 4, 0.15
    a, levels = _problem(n)
    x = random_dense(n, k_in, seed=2)

    flat = APPNPModel(MultiLevelArrow(levels, WIDTH, mesh=None),
                      k_in, k_out, hops=hops, alpha=alpha, seed=0)
    w = np.asarray(flat.params.w)
    b = np.asarray(flat.params.b)
    h = x @ w + b[None, :]
    z = h.copy()
    ad = np.asarray(a.todense()).astype(np.float32)
    for _ in range(hops):
        z = (1.0 - alpha) * (ad @ z) + alpha * h
    got = flat.predict(x)
    np.testing.assert_allclose(got, z, rtol=1e-4, atol=1e-4)

    executors = [
        MultiLevelArrow(levels, WIDTH, mesh=None, fmt="fold"),
        SellMultiLevel(levels, WIDTH, make_mesh((4,), ("blocks",))),
        SellSpaceShared(levels, WIDTH,
                        make_mesh((2, 2), ("lvl", "blocks"))),
    ]
    for multi in executors:
        m = APPNPCarried(multi, k_in, k_out, hops=hops, alpha=alpha,
                         seed=0)
        np.testing.assert_allclose(m.predict(x), z, rtol=1e-4,
                                   atol=1e-4)

    rng = np.random.default_rng(5)
    y = rng.standard_normal((n, k_out)).astype(np.float32)
    m = APPNPCarried(executors[1], k_in, k_out, hops=hops, alpha=alpha,
                     seed=0)
    losses = m.fit(x, y, steps=60)
    assert losses[-1] < 0.5 * losses[0], losses[::15]

    with pytest.raises(ValueError, match="feature-major"):
        APPNPCarried(MultiLevelArrow(levels, WIDTH, mesh=None), k_in,
                     k_out)
    with pytest.raises(ValueError, match="fold"):
        APPNPModel(MultiLevelArrow(levels, WIDTH, mesh=None,
                                   fmt="fold"), k_in, k_out)


def test_appnp_train_step_flat():
    """make_appnp_train_step: masked-MSE loss decreases through the
    propagation on the flat executor."""
    import optax

    from arrow_matrix_tpu.models.propagation import (
        APPNPModel,
        make_appnp_train_step,
    )

    n, k_in, k_out = 128, 6, 3
    a, levels = _problem(n)
    multi = MultiLevelArrow(levels, WIDTH, mesh=None)
    model = APPNPModel(multi, k_in, k_out, hops=3, alpha=0.2, seed=0)
    x = multi.set_features(random_dense(n, k_in, seed=2))
    rng = np.random.default_rng(5)
    y = multi.set_features(
        rng.standard_normal((n, k_out)).astype(np.float32))
    mask = multi.real_row_mask()[:, 0]
    opt = optax.adam(1e-2)
    step = make_appnp_train_step(tuple(multi.widths), hops=3, alpha=0.2,
                                 optimizer=opt)
    opt_state = opt.init(model.params)
    params = model.params
    losses = []
    for _ in range(40):
        params, opt_state, loss = step(params, opt_state, x, y, mask,
                                       multi.fwd, multi.bwd, multi.blocks)
        losses.append(float(loss))
    assert losses[-1] < 0.5 * losses[0], losses[::10]


def test_conjugate_gradient_on_feature_major_executors():
    """CG solves (shift*I + A) x = b on fold, sell/a2a, and sell-space
    executors, against scipy's direct solve.  shift > max degree makes
    the system strictly diagonally dominant (PD for symmetric A)."""
    import scipy.sparse as sp
    import scipy.sparse.linalg as spla

    from arrow_matrix_tpu.models import conjugate_gradient
    from arrow_matrix_tpu.parallel.sell_slim import SellMultiLevel
    from arrow_matrix_tpu.parallel.sell_space import SellSpaceShared

    n, width, k = 4096, 256, 4
    from arrow_matrix_tpu.utils.graphs import symmetrize

    a = symmetrize(barabasi_albert(n, 4, seed=8)).astype(np.float32)
    levels = arrow_decomposition(a, arrow_width=width, max_levels=2,
                                 block_diagonal=True, seed=8)
    shift = float(a.sum(axis=1).max()) + 1.0
    rng = np.random.default_rng(1)
    b = rng.standard_normal((n, k)).astype(np.float32)
    want = spla.spsolve(
        (shift * sp.identity(n, format="csr", dtype=np.float32)
         + a).tocsc(), b)

    execs = {
        "fold": MultiLevelArrow(levels, width, mesh=None, fmt="fold"),
        "sell_a2a": SellMultiLevel(levels, width,
                                   make_mesh((8,), ("blocks",)),
                                   routing="a2a"),
        "sell_space": SellSpaceShared(
            levels, width, make_mesh((2, 4), ("lvl", "blocks"))),
    }
    for name, ex in execs.items():
        got, rnorm = conjugate_gradient(ex, b, shift=shift,
                                        iterations=80, tol=1e-7)
        err = np.linalg.norm(got - want) / np.linalg.norm(want)
        assert err < 1e-4, (name, err, rnorm)
