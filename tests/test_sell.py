"""SELL (sliced-ELL) kernel tests (ops/sell.py): the degree-sorted
tiered format behind the folded single-chip execution."""

import jax.numpy as jnp
import numpy as np
import pytest
from scipy import sparse

from arrow_matrix_tpu.ops.sell import (
    SellMatrix,
    sell_from_csr,
    sell_spmm_t,
    tier_boundaries,
)
from arrow_matrix_tpu.utils import barabasi_albert, random_dense
from arrow_matrix_tpu.utils.graphs import random_csr


def spmm_via_sell(a, x, **kw):
    sell, order = sell_from_csr(a, **kw)
    y = x[order] if x.shape[0] == sell.n_rows else None
    assert y is not None
    out_sorted = np.asarray(sell_spmm_t(sell, jnp.asarray(y.T)))
    out = np.empty_like(out_sorted.T)
    out[order] = out_sorted.T
    return out, sell


def test_tier_boundaries():
    deg = np.array([0, 0, 8, 8, 8, 16, 24, 64, 64])
    starts = tier_boundaries(deg, growth=1.5)
    # zero tier, [8..8], [16..24], [64..64]
    assert starts == [0, 2, 5, 7]
    assert tier_boundaries(np.array([], dtype=np.int64)) == [0]
    assert tier_boundaries(np.array([8, 8, 8])) == [0]


def test_sell_matches_scipy_weighted():
    rng = np.random.default_rng(0)
    a = sparse.random(300, 300, density=0.03, format="csr",
                      random_state=rng, dtype=np.float32)
    a = a.tolil()
    a[7, :] = rng.standard_normal(300).astype(np.float32)  # hub row
    a[0, :] = 0.0                                          # empty row
    a = a.tocsr()
    a.sum_duplicates()
    a.sort_indices()
    x = random_dense(300, 8, seed=1)
    out, sell = spmm_via_sell(a, x)
    assert not sell.binary
    np.testing.assert_allclose(out, a @ x, rtol=1e-4, atol=1e-5)


def test_sell_binary_detection_and_padding_bound():
    a = barabasi_albert(2000, 6, seed=3)
    x = random_dense(2048, 8, seed=2)
    out, sell = spmm_via_sell(a, x[:2000], pad_rows_to=None)
    assert sell.binary
    np.testing.assert_allclose(out, a @ x[:2000], rtol=1e-5, atol=1e-5)
    # Padded gather slots bounded by growth x nnz (+ slot alignment).
    align_bound = 8 * 2000
    assert sell.n_slots <= 1.5 * a.nnz + align_bound


def test_sell_pad_rows_and_budget_chunking():
    a = barabasi_albert(100, 3, seed=4)
    trip = (None, a.indices, a.indptr)   # implicit-ones triplet
    sell, order = sell_from_csr(trip, pad_rows_to=128)
    assert sell.n_rows == 128
    x = random_dense(128, 4, seed=3)
    y = x[order]
    # Tiny budget forces slot chunking inside every tier.
    out_sorted = np.asarray(sell_spmm_t(sell, jnp.asarray(y.T),
                                        gather_budget=1 << 12))
    out = np.empty_like(x)
    out[order] = out_sorted.T
    np.testing.assert_allclose(out[:100], a @ x[:100], rtol=1e-5, atol=1e-5)
    assert np.all(out[100:] == 0)


def test_sell_binary_forced_on_weighted_raises():
    a = random_csr(64, 64, 4, seed=3)
    with pytest.raises(ValueError, match="binary"):
        sell_from_csr(a, binary=True)


def test_fold_rejected_by_propagation_models():
    """fold is step/run-only: the flat-feature model drivers must
    reject it up front instead of mis-broadcasting."""
    from arrow_matrix_tpu.decomposition import arrow_decomposition
    from arrow_matrix_tpu.models.propagation import pagerank
    from arrow_matrix_tpu.parallel import MultiLevelArrow

    a = barabasi_albert(128, 3, seed=1)
    levels = arrow_decomposition(a, 16, max_levels=2, block_diagonal=True,
                                 seed=0)
    ml = MultiLevelArrow(levels, 16, mesh=None, fmt="fold")
    with pytest.raises(ValueError, match="fold"):
        pagerank(ml, iterations=1)
    with pytest.raises(ValueError, match="fold"):
        ml.real_row_mask()


def test_power_iteration_on_fold():
    """power_iteration is layout-agnostic: the folded executor's
    feature-major carriage works through step + whole-array reductions."""
    from arrow_matrix_tpu.decomposition import arrow_decomposition
    from arrow_matrix_tpu.models.propagation import power_iteration
    from arrow_matrix_tpu.parallel import MultiLevelArrow

    a = barabasi_albert(200, 4, seed=7)
    levels = arrow_decomposition(a, 16, max_levels=3, block_diagonal=True,
                                 seed=0)
    x0 = np.ones((200, 1), dtype=np.float32)
    mlf = MultiLevelArrow(levels, 16, mesh=None, fmt="fold")
    mle = MultiLevelArrow(levels, 16, mesh=None, fmt="ell")
    vf, lf = power_iteration(mlf, x0, iterations=30)
    ve, le = power_iteration(mle, x0, iterations=30)
    assert abs(lf - le) < 1e-3 * abs(le)
    np.testing.assert_allclose(np.abs(vf), np.abs(ve), rtol=1e-3, atol=1e-4)


def test_fold_from_memmapped_artifact(tmp_path):
    """fold consumes memmapped CsrLike triplet levels (implicit-ones
    data) straight from an on-disk artifact."""
    from arrow_matrix_tpu.decomposition import arrow_decomposition
    from arrow_matrix_tpu.io import (
        as_levels,
        load_decomposition,
        load_level_widths,
        save_decomposition,
    )
    from arrow_matrix_tpu.parallel import MultiLevelArrow

    a = barabasi_albert(600, 3, seed=5)
    levels = arrow_decomposition(a, 64, max_levels=3, block_diagonal=True,
                                 seed=5)
    base = str(tmp_path / "g")
    save_decomposition(levels, base)
    loaded = load_decomposition(base, 64, mem_map=True)
    widths = load_level_widths(base, 64)
    stream_levels = as_levels(loaded, widths if widths is not None else 64,
                              materialize=False)
    assert not hasattr(stream_levels[0].matrix, "nnz")  # triplet, not CSR

    ml = MultiLevelArrow(stream_levels, 64, mesh=None, fmt="fold")
    assert ml.blocks[0].binary          # implicit-ones artifact data
    x = random_dense(600, 8, seed=2)
    out = ml.gather_result(ml.step(ml.set_features(x)))
    np.testing.assert_allclose(out, a @ x, rtol=1e-4, atol=1e-4)
