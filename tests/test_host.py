"""graft-host unit + integration tests: host fault-domain slicing
(contiguous blocks, per-rank jax.distributed env plans), the
inter-host byte slice of a collective contract (priced + checked),
the zero-copy shm data plane's LOUD failure modes (generation
recycling, torn writes, leaks, pool exhaustion), and the
shared-nothing router quorum (agreement proven, planted splits raise,
router death fails accepted requests over to survivors with zero
loss).  The full multi-process SIGKILL-a-host scenario lives in
tools/fleet_gate.py (slow chaos-gate tier); the two-process
jax.distributed mesh rehearsal here mirrors tests/test_multihost.py's
CHILD_SKIP discipline.
"""

import os
import socket
import subprocess
import sys
import threading

import numpy as np
import pytest

from arrow_matrix_tpu.analysis.prove import (
    check_host_bytes,
    fixture_contract,
)
from arrow_matrix_tpu.fleet import shm
from arrow_matrix_tpu.fleet.health import HealthMonitor
from arrow_matrix_tpu.fleet.host import (
    QuorumDisagreement,
    RouterQuorum,
    host_of,
    plan_host_mesh,
)
from arrow_matrix_tpu.fleet.router import FleetRouter, WorkerHandle
from arrow_matrix_tpu.fleet.worker import FleetWorker, serve_worker
from arrow_matrix_tpu.serve.request import Request


# ---------------------------------------------------------------------------
# Host fault-domain slicing
# ---------------------------------------------------------------------------

def test_host_of_contiguous_blocks():
    # 4 ranks over 2 hosts: [0,1] -> host-0, [2,3] -> host-1.
    assert [host_of(r, 4, 2) for r in range(4)] == \
        ["host-0", "host-0", "host-1", "host-1"]
    # Uneven split stays contiguous and uses every host.
    doms = [host_of(r, 5, 2) for r in range(5)]
    assert doms == ["host-0", "host-0", "host-0", "host-1", "host-1"]
    # One host swallows everything; hosts == ranks is one rank each.
    assert {host_of(r, 3, 1) for r in range(3)} == {"host-0"}
    assert [host_of(r, 3, 3) for r in range(3)] == \
        ["host-0", "host-1", "host-2"]
    with pytest.raises(ValueError):
        host_of(4, 4, 2)              # rank out of range
    with pytest.raises(ValueError):
        host_of(0, 2, 3)              # more hosts than ranks


def test_plan_host_mesh_is_one_global_job_with_stamped_domains():
    plan = plan_host_mesh(2, 2, coordinator="10.0.0.1", port=4321)
    assert len(plan) == 4
    for r, env in enumerate(plan):
        assert env["AMT_FLEET_COORDINATOR"] == "10.0.0.1:4321"
        assert env["AMT_FLEET_NUM_PROCESSES"] == "4"
        assert env["AMT_FLEET_PROCESS_ID"] == str(r)
    assert [env["AMT_HOST_ID"] for env in plan] == \
        ["host-0", "host-0", "host-1", "host-1"]
    with pytest.raises(ValueError):
        plan_host_mesh(0, 2)


def test_inter_host_bytes_pricing():
    c = fixture_contract()               # step_bytes == 3072
    # One host (or one device): nothing crosses a domain boundary.
    assert c.inter_host_bytes(1, 8) == 0
    # Ring: exactly the block-edge hops leave their host.
    assert c.inter_host_bytes(2, 8) == round(3072 * 2 / 8)
    assert c.inter_host_bytes(4, 8) == round(3072 * 4 / 8)
    # All-to-all: 1 - (d/h - 1)/(d - 1) of the traffic is cross-host.
    assert c.inter_host_bytes(2, 8, pattern="alltoall") == \
        round(3072 * (1.0 - 3 / 7))
    # Every device its own host: ALL traffic is inter-host.
    assert c.inter_host_bytes(8, 8, pattern="alltoall") == 3072
    with pytest.raises(ValueError):
        c.inter_host_bytes(3, 8)         # uneven split
    with pytest.raises(ValueError):
        c.inter_host_bytes(2, 8, pattern="butterfly")


def test_check_host_bytes_pass_and_fail():
    c = fixture_contract()               # ratio_band (0.5, 2.0)
    ideal = c.inter_host_bytes(2, 8)
    assert check_host_bytes(c, 2, 8, ideal)["status"] == "pass"
    assert check_host_bytes(c, 2, 8, 3 * ideal)["status"] == "fail"
    # Zero promised: zero measured passes, anything else is loud.
    assert check_host_bytes(c, 1, 8, 0)["status"] == "pass"
    res = check_host_bytes(c, 1, 8, 100)
    assert res["status"] == "fail" and "zero inter-host" in res["detail"]


# ---------------------------------------------------------------------------
# shm data plane: LOUD failure modes
# ---------------------------------------------------------------------------

def test_shm_roundtrip_is_bit_identical():
    pool = shm.SegmentPool(slots=2, name="t_rt")
    try:
        x = (np.arange(4096, dtype=np.float32).reshape(64, 64)
             * np.float32(0.25))
        desc = pool.publish(x)
        assert shm.is_descriptor(desc)
        got = shm.read_descriptor(desc)
        assert got.dtype == x.dtype and got.shape == x.shape
        assert got.tobytes() == x.tobytes()
        assert pool.release(desc)
        assert not pool.release(desc)    # second release is a no-op
    finally:
        pool.close()


def test_shm_recycled_generation_is_loud():
    pool = shm.SegmentPool(slots=1, name="t_gen")
    try:
        stale = pool.publish(np.ones(8, dtype=np.float32), pin=False)
        # pin=False: the single slot is immediately recyclable, so the
        # next publish overwrites it with a bumped generation…
        pool.publish(np.zeros(8, dtype=np.float32), pin=False)
        # …and the stale descriptor must refuse, never hand over the
        # other payload's bytes.
        with pytest.raises(shm.ShmGenerationError, match="recycled"):
            shm.read_descriptor(stale)
    finally:
        pool.close()


def test_shm_torn_write_is_loud_on_read_and_close():
    pool = shm.SegmentPool(slots=1, name="t_torn")
    desc = pool.publish(np.ones(8, dtype=np.float32))
    # Simulate a writer SIGKILLed mid-copy: the header carries the
    # tear sentinel (publish stamps it before the payload move).
    slot = pool._slots[0]
    slot.seg.buf[:shm._SHM_HEADER.size] = shm._SHM_HEADER.pack(
        shm._MAGIC, shm.TEAR_SENTINEL, 32)
    with pytest.raises(shm.ShmGenerationError, match="torn write"):
        shm.read_descriptor(desc)
    # close() reports the torn segment (and the still-pinned leak).
    problems = pool.close(strict=False)
    assert any("torn segment" in p for p in problems)
    assert any("leaked segment" in p for p in problems)
    assert desc  # descriptor itself outlives the pool harmlessly


def test_shm_leak_is_loud_under_strict_close():
    pool = shm.SegmentPool(slots=2, name="t_leak")
    pool.publish(np.ones(16, dtype=np.float32))   # pinned, never released
    with pytest.raises(shm.ShmLeakError, match="leaked segment"):
        pool.close(strict=True)
    # close() is idempotent after the strict failure already unlinked.
    assert pool.close(strict=True) == []


def test_shm_pool_exhaustion_is_loud_not_silent():
    pool = shm.SegmentPool(slots=1, name="t_full")
    try:
        pool.publish(np.ones(8, dtype=np.float32))    # pins the slot
        with pytest.raises(shm.ShmError, match="exhausted"):
            pool.publish(np.ones(8, dtype=np.float32))
    finally:
        pool.close(strict=False)


def test_buffer_ring_recycles_and_grows():
    ring = shm.BufferRing(slots=2, slot_bytes=16)
    a = ring.take(8)
    a[:] = b"\x01" * 8
    b = ring.take(8)
    assert ring.takes == 2 and ring.grown == 0
    # Slot 0 comes back around; a frame over every slab grows one.
    c = ring.take(64)
    assert len(c) == 64 and ring.grown == 1
    assert bytes(b[:1]) == b"\x00"       # distinct slab, untouched


# ---------------------------------------------------------------------------
# Router quorum over one in-process worker fleet
# ---------------------------------------------------------------------------

def _start_worker(worker_id, checkpoint_dir):
    worker = FleetWorker(worker_id, vertices=64, width=16, seed=5,
                         checkpoint_dir=checkpoint_dir,
                         checkpoint_every=1)
    ready = threading.Event()
    box = {}

    def announce(port):
        box["port"] = port
        ready.set()

    th = threading.Thread(target=serve_worker, args=(worker,),
                          kwargs={"port": 0, "announce": announce},
                          daemon=True)
    th.start()
    assert ready.wait(120), f"{worker_id} never bound"
    return worker, box["port"]


@pytest.fixture()
def two_router_quorum(tmp_path):
    """Two shared-nothing routers attached to the same two in-process
    workers (fresh WorkerHandle instances per router — routers share
    NOTHING but the worker endpoints and the checkpoint dir)."""
    ckpt = str(tmp_path / "ckpt")
    workers, ports = [], {}
    for wid in ("w0", "w1"):
        w, port = _start_worker(wid, ckpt)
        workers.append(w)
        ports[wid] = port
    routers = {
        name: FleetRouter(
            handles=[WorkerHandle(wid, "127.0.0.1", ports[wid])
                     for wid in ports],
            health=HealthMonitor(timeout_s=5.0, max_failures=3),
            name=f"quorum-{name}")
        for name in ("A", "B")}
    try:
        yield RouterQuorum(routers), routers
    finally:
        for r in routers.values():
            r.shutdown()
        for w in workers:
            try:
                w.close()
            except Exception:
                pass


def test_quorum_rejects_bad_membership():
    class _Fake:
        def __init__(self, workers):
            self.workers = workers

    with pytest.raises(ValueError, match=">= 2 routers"):
        RouterQuorum({"A": _Fake({"w0": 1})})
    with pytest.raises(ValueError, match="different worker sets"):
        RouterQuorum({"A": _Fake({"w0": 1}), "B": _Fake({"w1": 1})})


def test_quorum_agreement_and_planted_splits(two_router_quorum,
                                             monkeypatch):
    quorum, routers = two_router_quorum
    tenants = [f"t{i}" for i in range(16)]
    ks = {t: 2 for t in tenants}

    doc = quorum.verify_agreement(tenants, tenant_ks=ks)
    assert doc["agreed"] and doc["routers"] == ["A", "B"]
    assert set(doc["placement"].values()) <= {"w0", "w1"}
    assert doc["packing"] is not None

    # Planted membership split: B loses a worker from its ring, so
    # the two routers place SOME tenant differently — loud.
    routers["B"].ring.remove("w0")
    with pytest.raises(QuorumDisagreement, match="placement split"):
        quorum.verify_agreement(tenants)
    routers["B"].ring.add("w0")
    quorum.verify_agreement(tenants)     # restored: agreement again

    # Planted packing split: B computes a different FFD assignment.
    real_plan = routers["A"].plan_packing(ks)
    forged = {"assignment": dict(real_plan["assignment"]),
              "unplaced": list(real_plan["unplaced"])}
    if forged["assignment"]:
        t0 = sorted(forged["assignment"])[0]
        forged["assignment"][t0] = (
            "w1" if forged["assignment"][t0] == "w0" else "w0")
    monkeypatch.setattr(routers["B"], "plan_packing",
                        lambda tenant_ks: forged)
    with pytest.raises(QuorumDisagreement, match="packing split"):
        quorum.verify_agreement(tenants, tenant_ks=ks)


def test_quorum_failover_loses_nothing(two_router_quorum):
    quorum, routers = two_router_quorum
    n = routers["A"].n_rows
    x = np.ones((n, 2), dtype=np.float32)
    tickets = [quorum.submit(Request(f"q{i:02d}", f"t{i % 3}", x, 8))
               for i in range(6)]
    # Round-robin fan-in: both members accepted requests.
    assert all(quorum.summary()["accepted_per_router"][m] == 3
               for m in ("A", "B"))

    moved = quorum.fail_router("B")
    assert quorum.live_routers() == ["A"]
    assert quorum.fail_router("B") == []      # idempotent
    quorum.drain(timeout_s=180)

    results = quorum.results()
    assert sorted(results) == [f"q{i:02d}" for i in range(6)]
    assert all(t.status == "completed" for t in results.values())
    s = quorum.summary()
    assert s["lost_requests"] == []
    assert s["failed_routers"] == ["B"]
    assert s["failovers"] == len(moved)
    assert s["status_counts"] == {"completed": 6}
    assert len(tickets) == 6
    with pytest.raises(RuntimeError, match="last quorum member"):
        quorum.fail_router("A")


# ---------------------------------------------------------------------------
# Two-process jax.distributed mesh rehearsal (CHILD_SKIP discipline of
# tests/test_multihost.py: environments without working gloo skip).
# ---------------------------------------------------------------------------

MESH_CHILD = r"""
import os, sys
sys.path.insert(0, {repo!r})
from arrow_matrix_tpu.utils.platform import force_cpu_devices
force_cpu_devices(1)
from arrow_matrix_tpu.fleet.worker import maybe_init_distributed
try:
    joined = maybe_init_distributed()
except Exception as e:
    print(f"CHILD_SKIP {{type(e).__name__}}: {{e}}", flush=True)
    sys.exit(0)
import jax
print("JOINED", joined, jax.process_count(), jax.device_count(),
      os.environ.get("AMT_HOST_ID"), flush=True)
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_plan_host_mesh_two_process_rehearsal():
    """Each rank of a 2-host x 1-proc plan joins ONE global mesh via
    the AMT_FLEET_* env hooks and sees both hosts' devices — the
    jax.distributed rehearsal behind FleetRouter(hosts=2)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    plan = plan_host_mesh(2, 1, port=_free_port())
    procs = []
    for env_extra in plan:
        env = dict(os.environ)
        env.update(env_extra)
        procs.append(subprocess.Popen(
            [sys.executable, "-u", "-c",
             MESH_CHILD.format(repo=repo)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True))
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=240)
            outs.append((out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    if any("CHILD_SKIP" in out for out, _ in outs):
        pytest.skip(f"jax.distributed unavailable here: {outs}")
    for rank, (out, err) in enumerate(outs):
        want = f"JOINED True 2 2 host-{rank}"
        assert want in out, (rank, out, err)
