"""graft-ledger: store integrity, drift gate, error probe, export.

Pins the ledger's whole contract surface:

* schema round-trip and per-field validation (``store.schema_problems``);
* the append-only promise is VERIFIED, not assumed — an edited line
  fails its own ``record_id``, a deleted line breaks the successor's
  ``prev``, a torn trailing line is tolerated by readers but reported
  by ``validate()``;
* the drift gate's band math: a planted 10% perf regression trips, an
  in-band value does not, host-load normalization absorbs a loaded
  host, degraded records never band;
* accuracy curves: a planted bf16 cliff trips at ``2×`` the baseline,
  any nonzero f32 point trips the zero-baseline watchdog, a shortened
  curve is a regression;
* error-probe determinism at a fixed seed (same source ⇒ identical
  curves), f32 identically zero by construction;
* legacy export: re-exporting from the committed store reproduces the
  checked-in ``BENCH_r06.json`` byte-for-byte, with ``degraded`` and
  ``backend_probe_class`` surviving the round trip;
* the committed ``tests/fixtures/ledger`` store gates green (the same
  fixture the doctor LEDGER probe uses);
* ``utils/artifacts`` crash-window contract: a failed atomic write
  leaves the previous artifact intact and no tmp litter.
"""

import copy
import json
import os

import pytest

from arrow_matrix_tpu.ledger import (
    Ledger,
    canonical_record_id,
    schema_problems,
)
from arrow_matrix_tpu.ledger import export, gate, store
from arrow_matrix_tpu.utils.artifacts import (
    append_jsonl,
    atomic_write_json,
    parse_last_json_line,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE_DIR = os.path.join(REPO, "tests", "fixtures", "ledger")
COMMITTED_LEDGER = os.path.join(REPO, "bench_results", "ledger")
BENCH_R06 = os.path.join(REPO, "BENCH_r06.json")


def _mk(tmp_path, name="lg"):
    return Ledger(str(tmp_path / name))


def _bench(lg, value, *, host_load=0.2, metric="t_ms", ts=None,
           payload=None):
    """One banded ms record with PINNED provenance.  Host loads are
    held steady on purpose: varying loads spread the normalized values
    and widen the MAD band (a real effect the gate is designed around,
    but here the band must stay tight enough for the planted 10%
    regression to trip)."""
    return lg.record("bench", metric, value, unit="ms",
                     structure_hash="s0", platform="cpu",
                     device_kind="host", host_load=host_load,
                     git_rev=None, ts_unix=ts,
                     payload=payload or {})


# ---------------------------------------------------------------------------
# schema round-trip + validation


def test_record_round_trip(tmp_path):
    lg = _mk(tmp_path)
    rec = lg.record("bench", "spmm_ms", 1.25, unit="ms",
                    structure_hash="abc", platform="cpu",
                    device_kind="host", host_load=0.1, git_rev="deadbee",
                    knobs={"k": 16}, payload={"note": "x"})
    assert rec["record_id"].startswith("lr")
    assert rec["prev"] is None
    back = lg.read_all()
    assert back == [rec]
    assert lg.validate() == []
    # second record chains onto the first
    rec2 = _bench(lg, 2.0)
    assert rec2["prev"] == rec["record_id"]
    assert lg.validate() == []


def test_schema_problems_catch_drift(tmp_path):
    lg = _mk(tmp_path)
    rec = _bench(lg, 1.0)
    assert schema_problems(rec) == []
    bad = dict(rec)
    bad["kind"] = "vibes"
    bad["record_id"] = canonical_record_id(bad)
    assert any("unknown kind" in p for p in schema_problems(bad))
    bad = dict(rec)
    bad["schema"] = store.SCHEMA_VERSION + 1
    bad["record_id"] = canonical_record_id(bad)
    assert any("schema version" in p for p in schema_problems(bad))
    bad = dict(rec)
    del bad["metric"]
    assert any("missing field 'metric'" in p for p in schema_problems(bad))
    # bool is an int subclass — it must NOT pass as a numeric value
    bad = dict(rec)
    bad["value"] = True
    assert any("field 'value'" in p for p in schema_problems(bad))
    assert not isinstance(schema_problems("not a dict"), dict)


def test_record_refuses_invalid(tmp_path):
    lg = _mk(tmp_path)
    with pytest.raises(ValueError):
        lg.record("vibes", "m", 1.0)
    # the refused record must not have been appended
    assert lg.read_all() == []


def test_module_record_disabled_and_redirected(tmp_path, monkeypatch):
    monkeypatch.setenv("AMT_LEDGER", "0")
    assert store.record("bench", "m", 1.0,
                        directory=str(tmp_path / "x")) is None
    monkeypatch.delenv("AMT_LEDGER")
    rec = store.record("bench", "m", 1.0, directory=str(tmp_path / "x"),
                       host_load=None, git_rev=None)
    assert rec is not None
    assert Ledger(str(tmp_path / "x")).read_all() == [rec]


# ---------------------------------------------------------------------------
# append-only / tamper evidence


def test_edited_line_breaks_own_id(tmp_path):
    lg = _mk(tmp_path)
    _bench(lg, 1.0)
    _bench(lg, 2.0)
    lines = open(lg.path, encoding="utf-8").read().splitlines()
    doctored = json.loads(lines[0])
    doctored["value"] = 0.5  # rewrite history to look faster
    lines[0] = json.dumps(doctored, separators=(",", ":"))
    with open(lg.path, "w", encoding="utf-8") as fh:
        fh.write("\n".join(lines) + "\n")
    problems = lg.validate()
    assert any("does not match its content" in p for p in problems)


def test_deleted_line_breaks_chain(tmp_path):
    lg = _mk(tmp_path)
    _bench(lg, 1.0)
    _bench(lg, 2.0)
    _bench(lg, 3.0)
    lines = open(lg.path, encoding="utf-8").read().splitlines()
    del lines[1]
    with open(lg.path, "w", encoding="utf-8") as fh:
        fh.write("\n".join(lines) + "\n")
    problems = lg.validate()
    assert any("breaks the chain" in p for p in problems)


def test_torn_trailing_line_tolerated_but_reported(tmp_path):
    lg = _mk(tmp_path)
    r1 = _bench(lg, 1.0)
    with open(lg.path, "a", encoding="utf-8") as fh:
        fh.write('{"schema": 1, "kind": "ben')  # crash mid-append
    # readers still see the intact prefix…
    assert lg.read_all() == [r1]
    # …and validate() names the torn line
    assert any("torn trailing line" in p for p in lg.validate())
    # a non-trailing corrupt line is a different (worse) report
    with open(lg.path, "w", encoding="utf-8") as fh:
        fh.write('garbage\n' + json.dumps(r1) + "\n")
    assert any("edited in place" in p for p in lg.validate())


# ---------------------------------------------------------------------------
# drift gate: bands


def _steady_baseline(lg):
    for i, v in enumerate([10.0, 10.05, 9.95, 10.02]):
        _bench(lg, v, ts=1000.0 + i)
    return gate.build_baseline(lg.read_all())


def test_planted_10pct_regression_trips(tmp_path):
    lg = _mk(tmp_path)
    baseline = _steady_baseline(lg)
    fresh = _bench(lg, 11.0, ts=2000.0)  # +10%
    failures, _ = gate.check_records([fresh], baseline)
    assert any("perf regression" in f for f in failures)


def test_in_band_value_does_not_trip(tmp_path):
    lg = _mk(tmp_path)
    baseline = _steady_baseline(lg)
    fresh = _bench(lg, 10.2, ts=2000.0)  # +2%: inside the 5% floor
    failures, notes = gate.check_records([fresh], baseline)
    assert failures == []


def test_host_load_normalization_absorbs_loaded_host(tmp_path):
    lg = _mk(tmp_path)
    baseline = _steady_baseline(lg)
    # 30% slower wall time on a host with loadavg 0.6 normalizes to
    # 13.0/1.6 = 8.1 — under the band, not a regression.
    fresh = _bench(lg, 13.0, host_load=0.6, ts=2000.0)
    failures, _ = gate.check_records([fresh], baseline)
    assert failures == []
    # the same value at the baseline's load IS a regression
    fresh = _bench(lg, 13.0, ts=2001.0)
    failures, _ = gate.check_records([fresh], baseline)
    assert any("perf regression" in f for f in failures)


def test_degraded_records_never_band(tmp_path):
    lg = _mk(tmp_path)
    baseline = _steady_baseline(lg)
    # a degraded CPU-fallback round 5x over the band: noted, not failed
    slow = _bench(lg, 50.0, ts=2000.0,
                  payload={"parsed": {"degraded": True}})
    failures, notes = gate.check_records([slow], baseline)
    assert failures == []
    assert any("degraded" in n for n in notes)
    # and degraded history must not widen the band for clean numbers
    lg2 = _mk(tmp_path, "lg2")
    for i, v in enumerate([10.0, 10.05]):
        _bench(lg2, v, ts=1000.0 + i)
    _bench(lg2, 500.0, ts=1002.0,
           payload={"parsed": {"degraded": True}})
    base2 = gate.build_baseline(lg2.read_all())
    key = "bench|t_ms|s0|cpu"
    assert base2["metrics"][key]["count"] == 2
    assert base2["metrics"][key]["median"] < 10.0  # load-normalized


def test_new_key_and_unbanded_unit_are_notes(tmp_path):
    lg = _mk(tmp_path)
    lg.record("serve", "requests_per_s", 5.0, unit="req/s",
              platform="cpu", host_load=0.2, git_rev=None,
              ts_unix=999.0)
    baseline = _steady_baseline(lg)
    novel = _bench(lg, 99.0, metric="never_seen_ms", ts=2000.0)
    # req/s is higher-is-better: the gate has no band for it, so even a
    # collapsed throughput is a note (the serve SLO gate owns that axis)
    rps = lg.record("serve", "requests_per_s", 3.0, unit="req/s",
                    platform="cpu", host_load=0.2, git_rev=None,
                    ts_unix=2001.0)
    failures, notes = gate.check_records([novel, rps], baseline)
    assert failures == []
    assert any("new metric key" in n for n in notes)
    assert any("unbanded unit" in n for n in notes)


def test_gate_cli_trips_on_chain_tamper(tmp_path):
    lg = _mk(tmp_path)
    _steady_baseline(lg)
    bpath = gate.baseline_path(lg.directory)
    gate.save_baseline(bpath, gate.build_baseline(lg.read_all()))
    assert gate.main(["--check", "--ledger-dir", lg.directory]) == 0
    lines = open(lg.path, encoding="utf-8").read().splitlines()
    doctored = json.loads(lines[0])
    doctored["value"] = 0.5
    lines[0] = json.dumps(doctored, separators=(",", ":"))
    with open(lg.path, "w", encoding="utf-8") as fh:
        fh.write("\n".join(lines) + "\n")
    assert gate.main(["--check", "--ledger-dir", lg.directory]) == 1


# ---------------------------------------------------------------------------
# drift gate: accuracy curves


def _curve_record(lg, dtype, rel, ts):
    return lg.record(
        "error_curve", f"error_curve_{dtype}", rel[-1],
        unit="rel_frobenius", structure_hash="s0", platform="cpu",
        device_kind="host", host_load=None, git_rev=None, ts_unix=ts,
        knobs={"dtype": dtype, "k": 2, "iterations": len(rel),
               "seed": 3, "emulated": False, "fmt": "fold"},
        payload={"frobenius": rel, "rel_frobenius": rel,
                 "max_abs": rel})


def test_bf16_cliff_trips_curve_gate(tmp_path):
    lg = _mk(tmp_path)
    _curve_record(lg, "bf16", [1e-3, 1.5e-3, 2e-3], ts=1000.0)
    baseline = gate.build_baseline(lg.read_all())
    ok = _curve_record(lg, "bf16", [1.1e-3, 1.6e-3, 2.1e-3], ts=2000.0)
    failures, _ = gate.check_records([ok], baseline)
    assert failures == []
    cliff = _curve_record(lg, "bf16", [1e-3, 1.5e-3, 5e-2], ts=2001.0)
    failures, _ = gate.check_records([cliff], baseline)
    assert any("accuracy regression" in f for f in failures)


def test_f32_zero_baseline_watchdog(tmp_path):
    lg = _mk(tmp_path)
    _curve_record(lg, "f32", [0.0, 0.0, 0.0], ts=1000.0)
    baseline = gate.build_baseline(lg.read_all())
    # the absolute floor makes "any f32 error" a bit-identity break
    broken = _curve_record(lg, "f32", [0.0, 1e-5, 1e-5], ts=2000.0)
    failures, _ = gate.check_records([broken], baseline)
    assert any("accuracy regression" in f for f in failures)
    clean = _curve_record(lg, "f32", [0.0, 0.0, 0.0], ts=2001.0)
    failures, _ = gate.check_records([clean], baseline)
    assert failures == []


def test_shortened_curve_is_regression(tmp_path):
    lg = _mk(tmp_path)
    _curve_record(lg, "bf16", [1e-3, 1.5e-3, 2e-3], ts=1000.0)
    baseline = gate.build_baseline(lg.read_all())
    short = _curve_record(lg, "bf16", [1e-3, 1.5e-3], ts=2000.0)
    failures, _ = gate.check_records([short], baseline)
    assert any("curve shortened" in f for f in failures)


# ---------------------------------------------------------------------------
# error probe


@pytest.mark.slow
def test_error_probe_deterministic_and_f32_zero():
    from arrow_matrix_tpu.ledger.probe import error_curves_for_source

    src = {"kind": "ba", "n": 96, "m": 3, "width": 16, "seed": 7}
    a = error_curves_for_source(src, k=2, iterations=3,
                                dtypes=("f32", "bf16"))
    b = error_curves_for_source(src, k=2, iterations=3,
                                dtypes=("f32", "bf16"))
    # fixed seed + no ledger ⇒ the records (ids included) are identical
    assert a == b
    f32, bf16 = a
    assert f32["knobs"]["dtype"] == "f32"
    assert f32["payload"]["rel_frobenius"] == [0.0, 0.0, 0.0]
    assert f32["value"] == 0.0
    assert all(p > 0 for p in bf16["payload"]["rel_frobenius"])
    assert f32["structure_hash"] == bf16["structure_hash"]
    assert schema_problems(f32) == [] and schema_problems(bf16) == []


# ---------------------------------------------------------------------------
# legacy export bridge


def test_legacy_ingest_and_export_round_trip(tmp_path):
    lg = _mk(tmp_path)
    parsed = {"metric": "spmm_iter_ms", "value": 120.0, "unit": "ms",
              "vs_baseline": None, "config": {"n": 64, "width": 8},
              "platform": "cpu", "device_kind": "host",
              "degraded": True, "backend_probe_class": "init-hang"}
    legacy = {"n": 2, "cmd": "python bench.py", "rc": 0,
              "tail": json.dumps(parsed) + "\n", "parsed": parsed}
    p = tmp_path / "BENCH_r02.json"
    p.write_text(json.dumps(legacy))
    # a pre-contract round (parsed null) is skipped with a note
    p1 = tmp_path / "BENCH_r01.json"
    p1.write_text(json.dumps({"n": 1, "cmd": "c", "rc": 0,
                              "tail": "", "parsed": None}))
    count, notes = export.ingest_legacy_bench(lg, [str(p1), str(p)])
    assert count == 1
    assert any("parsed is null" in n for n in notes)
    rec = lg.read_all()[-1]
    # shape rides in the metric name so scales never share a band
    assert rec["metric"] == "spmm_iter_ms_n64_w8"
    doc = export.compose_round(lg, 3)
    assert export.validate_legacy(doc) == []
    # the legacy vocabulary survives the round trip untouched
    assert doc["parsed"]["degraded"] is True
    assert doc["parsed"]["backend_probe_class"] == "init-hang"
    # tail contract: the last line IS the parsed record
    assert parse_last_json_line(doc["tail"]) == doc["parsed"]
    assert doc["parsed"]["ledger"]["records"] == 1


def test_export_matches_checked_in_bench_r06():
    """Re-exporting from the committed store must reproduce the
    checked-in BENCH_r06.json exactly — export reads only committed
    records and adds no fresh timestamps.  The round pins itself to
    its own recorded ``parsed.ledger.head`` (the chain prefix below a
    record id never changes in an append-only store), so this holds
    even after later rounds append records — the path
    ``export_legacy_round`` takes automatically when the round file
    exists."""
    if not os.path.exists(BENCH_R06):
        pytest.skip("no checked-in BENCH_r06.json")
    lg = Ledger(COMMITTED_LEDGER)
    assert lg.validate() == []
    head = json.load(open(BENCH_R06, encoding="utf-8"))[
        "parsed"]["ledger"]["head"]
    doc = export.compose_round(lg, 6, head=head)
    committed = json.load(open(BENCH_R06, encoding="utf-8"))
    # the committed file stores the run-relative ledger path
    doc["parsed"]["ledger"]["store"] = \
        committed["parsed"]["ledger"]["store"]
    doc["tail"] = json.dumps(doc["parsed"], sort_keys=True) + "\n"
    assert doc == committed


def test_export_without_bench_record_raises(tmp_path):
    lg = _mk(tmp_path)
    with pytest.raises(ValueError):
        export.compose_round(lg, 6)


# ---------------------------------------------------------------------------
# committed fixture store (the doctor LEDGER probe's target)


def test_fixture_store_gates_green():
    lg = Ledger(FIXTURE_DIR)
    assert lg.validate() == []
    rc, lines = gate.run_gate(
        FIXTURE_DIR, os.path.join(FIXTURE_DIR, "baseline.json"))
    assert rc == 0, "\n".join(lines)


def test_fixture_planted_regression_trips():
    lg = Ledger(FIXTURE_DIR)
    baseline = gate.load_baseline(
        os.path.join(FIXTURE_DIR, "baseline.json"))
    planted = None
    for rec in lg.read_all():
        if rec.get("unit") == "ms":
            planted = copy.deepcopy(rec)
            break
    assert planted is not None
    planted["value"] = float(planted["value"]) * 10.0
    planted["record_id"] = canonical_record_id(planted)
    failures, _ = gate.check_records([planted], baseline)
    assert any("perf regression" in f for f in failures)


# ---------------------------------------------------------------------------
# drift gate: cross-class iter_ms bands (graft-host satellite —
# "byte-cheaper but time-slower fails loudly")


def _xray(lg, metric, value, ts=2000.0):
    return lg.record("xray", metric, value, unit="ms",
                     structure_hash="s0", platform="cpu",
                     device_kind="host", host_load=0.0,
                     git_rev=None, ts_unix=ts, payload={})


def test_xray_class_band_trips_on_time_slower_class(tmp_path):
    """A traffic class that saves wire bytes must not quietly cost
    wall time: iter_ms_<cls> beyond XRAY_CLASS_FACTOR x the exact
    class's iter_ms in the SAME run fails the gate."""
    lg = _mk(tmp_path)
    baseline = _steady_baseline(lg)
    exact = _xray(lg, "iter_ms_exact", 10.0)
    fine = _xray(lg, "iter_ms_approx", 12.0, ts=2001.0)
    failures, _ = gate.check_records([exact, fine], baseline)
    assert failures == []                  # 1.2x: inside the band
    slow = _xray(lg, "iter_ms_approx", 20.0, ts=2002.0)
    failures, _ = gate.check_records([exact, slow], baseline)
    assert any("class regression" in f
               and "byte-cheaper but time-slower" in f
               for f in failures)


def test_xray_class_band_falls_back_to_baseline_exact(tmp_path):
    """With no fresh exact record, the reference is the baseline's
    iter_ms_exact median; with NO exact reference anywhere the check
    is skipped with a note, never silently passed as judged."""
    lg = _mk(tmp_path)
    for i, v in enumerate([10.0, 10.1, 9.9]):
        _xray(lg, "iter_ms_exact", v, ts=1000.0 + i)
    baseline = gate.build_baseline(lg.read_all())
    slow = _xray(lg, "iter_ms_approx", 30.0)
    failures, _ = gate.check_records([slow], baseline)
    assert any("class regression" in f for f in failures)
    # No exact reference at all: note, not a silent pass.
    lg2 = _mk(tmp_path, "lg2")
    lone = _xray(lg2, "iter_ms_approx", 30.0)
    failures, notes = gate.check_records(
        [lone], gate.build_baseline([]))
    assert failures == []
    assert any("class band skipped" in n for n in notes)


# ---------------------------------------------------------------------------
# crash-window contract (utils/artifacts)


def test_atomic_write_failure_preserves_previous_artifact(tmp_path,
                                                          monkeypatch):
    target = tmp_path / "artifact.json"
    atomic_write_json(str(target), {"v": 1})

    def boom(src, dst):
        raise OSError("simulated crash inside the replace window")

    monkeypatch.setattr(os, "replace", boom)
    with pytest.raises(OSError):
        atomic_write_json(str(target), {"v": 2})
    monkeypatch.undo()
    # the previous artifact is intact and no tmp litter remains
    assert json.load(open(target)) == {"v": 1}
    assert [p.name for p in tmp_path.iterdir()] == ["artifact.json"]


def test_atomic_write_unserializable_leaves_artifact(tmp_path):
    target = tmp_path / "artifact.json"
    atomic_write_json(str(target), {"v": 1})
    with pytest.raises(TypeError):
        atomic_write_json(str(target), {"v": object()})
    assert json.load(open(target)) == {"v": 1}
    assert [p.name for p in tmp_path.iterdir()] == ["artifact.json"]


def test_append_jsonl_serializes_before_touching_file(tmp_path):
    target = tmp_path / "log.jsonl"
    append_jsonl(str(target), {"a": 1})
    with pytest.raises(TypeError):
        append_jsonl(str(target), {"bad": object()})
    # the failed append wrote nothing — not even a partial line
    assert open(target).read() == '{"a":1}\n'


def test_append_jsonl_concurrent_appends_lose_nothing(tmp_path):
    """Cross-fd serialization: concurrent appenders (the shape of N
    fleet workers sharing one artifact) interleave whole lines, never
    tear them."""
    import threading

    path = str(tmp_path / "rows.jsonl")
    writers, rows = 6, 20
    errors = []

    def write(i):
        try:
            for j in range(rows):
                append_jsonl(path, {"w": i, "j": j})
        except Exception as e:
            errors.append(e)

    threads = [threading.Thread(target=write, args=(i,))
               for i in range(writers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert errors == []
    with open(path, encoding="utf-8") as fh:
        lines = fh.read().splitlines()
    assert len(lines) == writers * rows
    seen = {(d["w"], d["j"]) for d in map(json.loads, lines)}
    assert len(seen) == writers * rows


def test_concurrent_records_keep_the_chain_valid(tmp_path):
    """``Ledger.record``'s read-prev + append is one critical section
    under the file lock: concurrent recorders (fleet workers folding
    into one store) must leave a fully linked chain — every record
    present, ``validate()`` green."""
    import threading

    lg = Ledger(str(tmp_path))
    writers, rows = 6, 6
    errors = []

    def write(i):
        try:
            for j in range(rows):
                lg.record("probe", f"writer{i}_ms", float(j),
                          unit="ms", host_load=0.0, git_rev=None)
        except Exception as e:
            errors.append(e)

    threads = [threading.Thread(target=write, args=(i,))
               for i in range(writers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert errors == []
    assert lg.validate() == []
    recs = lg.read_all()
    assert len(recs) == writers * rows
    for i in range(writers):
        assert sum(r["metric"] == f"writer{i}_ms" for r in recs) == rows
