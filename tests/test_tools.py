"""Smoke tests for the round-4 perf tools' CPU fixtures: the watcher
runs these scripts unattended on a healed tunnel, so their non-chip
logic (decompose, golden gates, JSON contracts) must stay green in CI.
Each runs in a subprocess exactly as the watcher invokes it."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(script: str, env: dict, timeout: float = 300):
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", script)],
        capture_output=True, text=True, timeout=timeout,
        env={**os.environ, **env}, cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-2000:]
    return json.loads(proc.stdout.strip().splitlines()[-1])


def test_planar_bench_cpu_fixture():
    out = _run("planar_bench.py",
               {"AMT_PLANAR_CPU": "1", "AMT_PLANAR_SIDE": "96"})
    assert out["levels"] == 1          # banded fast path engaged
    assert out["gated"] and out["winner"] in ("fold", "fold_tight")
    # the tight packing's planar slot story: exactly 1.0x nnz
    assert out["runs"]["fold_tight"]["slots_over_nnz"] == 1.0
    assert out["comm_8dev"]["levels"] == 1


def test_planar_bench_bf16_fixture():
    out = _run("planar_bench.py",
               {"AMT_PLANAR_CPU": "1", "AMT_PLANAR_SIDE": "96",
                "AMT_PLANAR_DTYPE": "bf16"})
    assert out["feature_dtype"] == "bf16"
    assert list(out["runs"]) == ["fold_tight"]   # single resident build
    assert out["gated"] and out["err"] < 2e-2


def test_pallas_gather_probe_cpu_fixture():
    out = _run("pallas_gather_probe.py", {"AMT_PROBE_CPU": "1"})
    for name in ("xla_take", "xla_granule", "pallas_granule"):
        assert out["variants"][name].get("exact") is True, out


def test_ba27_bench_refuses_missing_and_toy_export(tmp_path):
    """The watcher fires ba27_bench unattended: it must exit nonzero
    (never bench garbage) when the export is absent, and refuse a
    logic-test toy export unless explicitly allowed — a regression
    here would let the watcher publish toy-scale numbers as the 2^27
    scale point."""
    def run_with(export_dir):
        return subprocess.run(
            [sys.executable,
             os.path.join(REPO, "tools", "ba27_bench.py")],
            capture_output=True, text=True, timeout=120,
            env={**os.environ, "AMT_BA27_EXPORT": str(export_dir)},
            cwd=REPO)

    missing = run_with(tmp_path / "nowhere")
    assert missing.returncode == 2
    assert "no export" in missing.stdout

    toy = tmp_path / "toy"
    toy.mkdir()
    (toy / "meta.json").write_text("{}")
    (toy / "rehearsal.json").write_text(
        json.dumps({"n": 1 << 16, "k": 16, "x_seed": 5}))
    refused = run_with(toy)
    assert refused.returncode == 2
    assert "logic-test toy" in refused.stdout


@pytest.mark.slow
def test_rehearse_rung_and_ba27_chain_cpu_fixture(tmp_path):
    """The offline rung -> online bench chain at logic-test scale:
    rung exports atomically, ba27_bench golden-gates from the export
    (AMT_BA27_FORCE_CPU).  Both ends honor AMT_BA27_EXPORT, so the
    chain runs entirely inside tmp_path — the live bench_cache export
    (possibly the real multi-hour 2^27 one) is never touched."""
    export = str(tmp_path / "ba27_fold")
    env = {**os.environ, "AMT_BA27_EXPORT": export}
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "scale_ladder.py"),
         "--rung", "rehearse_1e8_ba_step"],
        capture_output=True, text=True, timeout=900,
        env={**env, "AMT_BA27_LOGN": "16"}, cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-2000:]
    rung = json.loads(proc.stdout.strip().splitlines()[-1])
    assert rung["hbm_budget"]["fits"]
    assert rung["golden_sample_rel_err"] < 2e-2
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "ba27_bench.py")],
        capture_output=True, text=True, timeout=600,
        env={**env, "AMT_BA27_ALLOW_SMALL": "1",
             "AMT_BA27_FORCE_CPU": "1", "AMT_BA27_ITERS": "2"}, cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["golden_sample_rel_err"] < 2e-2
    assert out["ms_per_iter"] > 0


@pytest.mark.slow
def test_ladder_race_cpu_fixture():
    out = _run("ladder_race.py",
               {"AMT_LADDER_CPU": "1", "AMT_LADDER_N": "16384"},
               timeout=600)
    assert out["runs"]["default"]["gated"]
    assert out["runs"]["tight"]["gated"]
    assert (out["runs"]["tight"]["gather_slots"]
            < out["runs"]["default"]["gather_slots"])


# ---------------------------------------------------------------------------
# Shared on-chip artifact predicate + tunnel_watcher stage logic
# ---------------------------------------------------------------------------


def _watcher():
    """A fresh tunnel_watcher module instance per test (its per-stage
    completion set is module state)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "tunnel_watcher_under_test",
        os.path.join(REPO, "tools", "tunnel_watcher.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod.log = lambda msg: None        # never touch pipeline.log
    return mod


def test_obs_gate_memory_problems():
    """The gate's memory contract: absent report fails, sane ratio
    passes, blown ratio names the algorithm and the bytes."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "obs_gate_under_test", os.path.join(REPO, "tools", "obs_gate.py"))
    gate = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(gate)

    ok = {"algorithms": {"a": {"memory": {"total_bytes": 100},
                               "hbm_measured_bytes": 100,
                               "hbm_predicted_bytes": 80,
                               "hbm_vs_predicted": 1.25}}}
    assert gate.memory_problems(ok, 8.0) == []
    # No predictor -> no ratio to enforce, but the report must exist.
    no_model = {"algorithms": {"a": {"memory": {"total_bytes": 100},
                                     "hbm_measured_bytes": 100,
                                     "hbm_vs_predicted": None}}}
    assert gate.memory_problems(no_model, 8.0) == []
    absent = {"algorithms": {"a": {"memory": None}}}
    assert gate.memory_problems(absent, 8.0) == [
        "a: memory report absent"]
    blown = {"algorithms": {"a": {"memory": {"total_bytes": 800},
                                  "hbm_measured_bytes": 800,
                                  "hbm_predicted_bytes": 80,
                                  "hbm_vs_predicted": 10.0}}}
    problems = gate.memory_problems(blown, 8.0)
    assert len(problems) == 1 and "exceeds 8.00" in problems[0]


def test_parse_last_json_line_contract():
    """ONE parser for every bench/tune child's stdout (the final
    JSON-line protocol): noise above the record is fine, noise AFTER
    it — or no record at all — is an explicit None, never a guess."""
    from arrow_matrix_tpu.utils.artifacts import parse_last_json_line

    assert parse_last_json_line(
        'warming up...\ncompile cache miss\n{"ms": 1.5}\n'
    ) == {"ms": 1.5}
    assert parse_last_json_line('{"ms": 1.5}') == {"ms": 1.5}
    assert parse_last_json_line("") is None
    assert parse_last_json_line("   \n  ") is None
    assert parse_last_json_line(None) is None
    # The record must be the LAST line: trailing noise invalidates.
    assert parse_last_json_line('{"ms": 1.5}\nTraceback...') is None
    # A JSON scalar/array is not a record.
    assert parse_last_json_line("[1, 2]") is None
    assert parse_last_json_line("42") is None


def test_artifacts_shared_predicate(tmp_path):
    """ONE on-chip definition for bench.py and the watcher: explicit
    CPU/degraded labels disqualify, unlabeled records qualify, and a
    missing artifact is its own verdict — never 'degraded'."""
    from arrow_matrix_tpu.utils.artifacts import (
        classify_artifact,
        load_last_json_line,
        record_is_onchip,
    )

    assert record_is_onchip({"platform": "tpu", "value": 1.0})
    assert record_is_onchip({"value": 1.0})          # pre-label contract
    assert not record_is_onchip({"platform": "cpu"})
    assert not record_is_onchip({"degraded": True, "platform": "tpu"})

    p = tmp_path / "a.json"
    assert classify_artifact(str(p)) == "missing"
    p.write_text("not json at all")
    assert classify_artifact(str(p)) == "missing"
    assert load_last_json_line(str(p)) is None
    # JSON-lines: only the LAST line is the committed record.
    p.write_text('{"platform": "tpu"}\n{"platform": "cpu"}\n')
    assert load_last_json_line(str(p)) == {"platform": "cpu"}
    assert classify_artifact(str(p)) == "degraded"
    p.write_text('{"platform": "tpu", "value": 2.5}\n')
    assert classify_artifact(str(p)) == "onchip"
    p.write_text('{"metric": "spmm_iter_ms", "value": 2.5}\n')
    assert classify_artifact(str(p)) == "onchip"     # unlabeled


def test_watcher_bench_stage_missing_artifact_is_failed(tmp_path):
    """rc=0 with NO artifact means the stage failed (retriable) — the
    old code returned 'degraded' and bailed the whole pass as if the
    tunnel were proven down."""
    tw = _watcher()
    tw.REPO = str(tmp_path)
    (tmp_path / "bench_cache").mkdir()
    tw.run_stage = lambda *a, **k: True

    assert tw._bench_stage("s", {}, 1.0, "never_written.json") == "failed"

    art = tmp_path / "bench_cache" / "cpu.json"
    art.write_text('{"platform": "cpu", "degraded": true}\n')
    assert tw._bench_stage("s", {}, 1.0, "cpu.json") == "degraded"

    art = tmp_path / "bench_cache" / "chip.json"
    art.write_text('{"platform": "tpu", "value": 3.0}\n')
    assert tw._bench_stage("s", {}, 1.0, "chip.json") == "onchip"

    # Unlabeled artifacts follow bench.py's pre-label contract now —
    # the watcher used to reject these (opposite default).
    art = tmp_path / "bench_cache" / "old.json"
    art.write_text('{"value": 3.0}\n')
    assert tw._bench_stage("s", {}, 1.0, "old.json") == "onchip"

    # And a launch failure is a failure regardless of artifacts.
    tw.run_stage = lambda *a, **k: False
    assert tw._bench_stage("s", {}, 1.0, "chip.json") == "failed"


def test_watcher_per_stage_completion_retries_after_flap(tmp_path):
    """A tunnel flap mid-pass must not permanently skip the stages
    after it: the next healthy window retries exactly the pending
    stages and never re-runs a completed one."""
    tw = _watcher()
    bench_outcomes = {}
    bench_calls = []
    stage_calls = []

    def fake_bench_stage(name, env, timeout_s, json_name):
        bench_calls.append(name)
        return bench_outcomes[name]

    def fake_run_stage(name, cmd, env, timeout_s, json_name=None):
        stage_calls.append(name)
        return True

    tw._bench_stage = fake_bench_stage
    tw.run_stage = fake_run_stage

    # Window 1: headline lands, then the 2^24 stage comes back with an
    # explicit CPU fallback -> the pass bails before planar.
    bench_outcomes.update(bench_quick="onchip", bench_full="onchip",
                          bench_2e24="degraded")
    assert tw._healthy_pass_stages(False, "w1") is True
    assert "planar" not in stage_calls
    remaining = tw._stages_remaining(False)
    assert "bench_2e24" in remaining and "planar" in remaining
    assert "planar_1e8" in remaining
    assert "bench_full" not in remaining     # completed stages stick

    # Window 2: only the pending stages run; completed ones are never
    # re-run (duplicate chip minutes), and planar_1e8 fires gated on
    # the planar COMPLETION FLAG set earlier in the same window.
    bench_calls.clear()
    stage_calls.clear()
    bench_outcomes["bench_2e24"] = "onchip"
    assert tw._healthy_pass_stages(False, "w2") is True
    assert bench_calls == ["bench_2e24"]
    assert "planar" in stage_calls and "planar_1e8" in stage_calls
    assert "ladder_race" not in stage_calls
    assert "gather_probe" not in stage_calls
    assert tw._stages_remaining(False) == []

    # Window 3 is empty: every tracked stage (and any opportunistic
    # ba27 attempt from window 2) is done or still precondition-gated.
    bench_calls.clear()
    stage_calls.clear()
    assert tw._healthy_pass_stages(False, "w3") is True
    assert bench_calls == [] and stage_calls == []


def test_obs_gate_comm_problems():
    """Every algorithm's comm record must carry exposed_comm_ms
    (graft-stream): a missing or null field names the algorithm."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "obs_gate_under_test2", os.path.join(REPO, "tools", "obs_gate.py"))
    gate = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(gate)

    ok = {"algorithms": {"a": {"exposed_comm_ms": 0.0},
                         "b": {"exposed_comm_ms": 1.25}}}
    assert gate.comm_problems(ok) == []
    missing = {"algorithms": {"a": {}, "b": {"exposed_comm_ms": None}}}
    assert gate.comm_problems(missing) == [
        "a: comm report lacks exposed_comm_ms",
        "b: comm report lacks exposed_comm_ms"]


def test_artifacts_stray_verification_markers(tmp_path):
    """A VERIFYDRIVE/SMOKETEST/DRYRUN-named artifact is verification
    exhaust: classified 'missing' no matter how on-chip its record
    claims to be (VERDICT r5 item 9)."""
    import json as _json

    from arrow_matrix_tpu.utils.artifacts import (
        classify_artifact,
        is_stray_verification_artifact,
    )

    assert is_stray_verification_artifact(
        "bench_cache/onchip_bench_quick_VERIFYDRIVE.json")
    assert is_stray_verification_artifact("onchip_verifydrive.json")
    assert is_stray_verification_artifact("x_SMOKETEST.json")
    assert not is_stray_verification_artifact("onchip_bench_quick.json")

    p = tmp_path / "onchip_bench_VERIFYDRIVE.json"
    p.write_text(_json.dumps({"metric": "spmm_iter_ms", "value": 2.5,
                              "platform": "tpu"}))
    assert classify_artifact(str(p)) == "missing"
