"""Smoke tests for the round-4 perf tools' CPU fixtures: the watcher
runs these scripts unattended on a healed tunnel, so their non-chip
logic (decompose, golden gates, JSON contracts) must stay green in CI.
Each runs in a subprocess exactly as the watcher invokes it."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(script: str, env: dict, timeout: float = 300):
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", script)],
        capture_output=True, text=True, timeout=timeout,
        env={**os.environ, **env}, cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-2000:]
    return json.loads(proc.stdout.strip().splitlines()[-1])


def test_planar_bench_cpu_fixture():
    out = _run("planar_bench.py",
               {"AMT_PLANAR_CPU": "1", "AMT_PLANAR_SIDE": "96"})
    assert out["levels"] == 1          # banded fast path engaged
    assert out["gated"] and out["winner"] in ("fold", "fold_tight")
    # the tight packing's planar slot story: exactly 1.0x nnz
    assert out["runs"]["fold_tight"]["slots_over_nnz"] == 1.0
    assert out["comm_8dev"]["levels"] == 1


def test_planar_bench_bf16_fixture():
    out = _run("planar_bench.py",
               {"AMT_PLANAR_CPU": "1", "AMT_PLANAR_SIDE": "96",
                "AMT_PLANAR_DTYPE": "bf16"})
    assert out["feature_dtype"] == "bf16"
    assert list(out["runs"]) == ["fold_tight"]   # single resident build
    assert out["gated"] and out["err"] < 2e-2


def test_pallas_gather_probe_cpu_fixture():
    out = _run("pallas_gather_probe.py", {"AMT_PROBE_CPU": "1"})
    for name in ("xla_take", "xla_granule", "pallas_granule"):
        assert out["variants"][name].get("exact") is True, out


@pytest.mark.slow
def test_ladder_race_cpu_fixture():
    out = _run("ladder_race.py",
               {"AMT_LADDER_CPU": "1", "AMT_LADDER_N": "16384"},
               timeout=600)
    assert out["runs"]["default"]["gated"]
    assert out["runs"]["tight"]["gated"]
    assert (out["runs"]["tight"]["gather_slots"]
            < out["runs"]["default"]["gather_slots"])
