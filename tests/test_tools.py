"""Smoke tests for the round-4 perf tools' CPU fixtures: the watcher
runs these scripts unattended on a healed tunnel, so their non-chip
logic (decompose, golden gates, JSON contracts) must stay green in CI.
Each runs in a subprocess exactly as the watcher invokes it."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(script: str, env: dict, timeout: float = 300):
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", script)],
        capture_output=True, text=True, timeout=timeout,
        env={**os.environ, **env}, cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-2000:]
    return json.loads(proc.stdout.strip().splitlines()[-1])


def test_planar_bench_cpu_fixture():
    out = _run("planar_bench.py",
               {"AMT_PLANAR_CPU": "1", "AMT_PLANAR_SIDE": "96"})
    assert out["levels"] == 1          # banded fast path engaged
    assert out["gated"] and out["winner"] in ("fold", "fold_tight")
    # the tight packing's planar slot story: exactly 1.0x nnz
    assert out["runs"]["fold_tight"]["slots_over_nnz"] == 1.0
    assert out["comm_8dev"]["levels"] == 1


def test_planar_bench_bf16_fixture():
    out = _run("planar_bench.py",
               {"AMT_PLANAR_CPU": "1", "AMT_PLANAR_SIDE": "96",
                "AMT_PLANAR_DTYPE": "bf16"})
    assert out["feature_dtype"] == "bf16"
    assert list(out["runs"]) == ["fold_tight"]   # single resident build
    assert out["gated"] and out["err"] < 2e-2


def test_pallas_gather_probe_cpu_fixture():
    out = _run("pallas_gather_probe.py", {"AMT_PROBE_CPU": "1"})
    for name in ("xla_take", "xla_granule", "pallas_granule"):
        assert out["variants"][name].get("exact") is True, out


def test_ba27_bench_refuses_missing_and_toy_export(tmp_path):
    """The watcher fires ba27_bench unattended: it must exit nonzero
    (never bench garbage) when the export is absent, and refuse a
    logic-test toy export unless explicitly allowed — a regression
    here would let the watcher publish toy-scale numbers as the 2^27
    scale point."""
    def run_with(export_dir):
        return subprocess.run(
            [sys.executable,
             os.path.join(REPO, "tools", "ba27_bench.py")],
            capture_output=True, text=True, timeout=120,
            env={**os.environ, "AMT_BA27_EXPORT": str(export_dir)},
            cwd=REPO)

    missing = run_with(tmp_path / "nowhere")
    assert missing.returncode == 2
    assert "no export" in missing.stdout

    toy = tmp_path / "toy"
    toy.mkdir()
    (toy / "meta.json").write_text("{}")
    (toy / "rehearsal.json").write_text(
        json.dumps({"n": 1 << 16, "k": 16, "x_seed": 5}))
    refused = run_with(toy)
    assert refused.returncode == 2
    assert "logic-test toy" in refused.stdout


@pytest.mark.slow
def test_rehearse_rung_and_ba27_chain_cpu_fixture(tmp_path):
    """The offline rung -> online bench chain at logic-test scale:
    rung exports atomically, ba27_bench golden-gates from the export
    (AMT_BA27_FORCE_CPU).  Both ends honor AMT_BA27_EXPORT, so the
    chain runs entirely inside tmp_path — the live bench_cache export
    (possibly the real multi-hour 2^27 one) is never touched."""
    export = str(tmp_path / "ba27_fold")
    env = {**os.environ, "AMT_BA27_EXPORT": export}
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "scale_ladder.py"),
         "--rung", "rehearse_1e8_ba_step"],
        capture_output=True, text=True, timeout=900,
        env={**env, "AMT_BA27_LOGN": "16"}, cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-2000:]
    rung = json.loads(proc.stdout.strip().splitlines()[-1])
    assert rung["hbm_budget"]["fits"]
    assert rung["golden_sample_rel_err"] < 2e-2
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "ba27_bench.py")],
        capture_output=True, text=True, timeout=600,
        env={**env, "AMT_BA27_ALLOW_SMALL": "1",
             "AMT_BA27_FORCE_CPU": "1", "AMT_BA27_ITERS": "2"}, cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["golden_sample_rel_err"] < 2e-2
    assert out["ms_per_iter"] > 0


@pytest.mark.slow
def test_ladder_race_cpu_fixture():
    out = _run("ladder_race.py",
               {"AMT_LADDER_CPU": "1", "AMT_LADDER_N": "16384"},
               timeout=600)
    assert out["runs"]["default"]["gated"]
    assert out["runs"]["tight"]["gated"]
    assert (out["runs"]["tight"]["gather_slots"]
            < out["runs"]["default"]["gather_slots"])
