"""graft-heal unit tests: fault-plan parsing and hit-counter
semantics, injection-hook no-op behavior, supervisor retry / rollback /
watchdog / abort paths, artifact-integrity manifests, and the fast
chaos-gate scenario matrix (the full gate, with its subprocess SIGKILL
scenario, is marked slow)."""

import importlib.util
import json
import os
import sys
import time

import jax.numpy as jnp
import numpy as np
import pytest

from arrow_matrix_tpu import faults
from arrow_matrix_tpu.faults import plan as fault_plan
from arrow_matrix_tpu.faults.supervisor import (
    Abort,
    Supervisor,
    WatchdogTimeout,
    state_is_finite,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _no_leftover_plan():
    faults.clear_plan()
    yield
    faults.clear_plan()


# ---------------------------------------------------------------------------
# Plan parsing
# ---------------------------------------------------------------------------

def test_plan_from_json_roundtrip():
    p = fault_plan.FaultPlan.from_json(
        {"scenario": "hang", "site": "mesh.*", "after": 3, "hang_s": 2.5})
    assert p.scenario == "hang" and p.site == "mesh.*"
    assert p.after == 3 and p.count == 1 and p.hang_s == 2.5


def test_plan_rejects_unknown_scenario():
    with pytest.raises(ValueError, match="scenario"):
        fault_plan.FaultPlan.from_json({"scenario": "meteor"})


def test_plan_rejects_unknown_field():
    with pytest.raises(ValueError, match="unknown"):
        fault_plan.FaultPlan.from_json({"scenario": "nan", "when": 3})


def test_parse_plan_json_string_and_file(tmp_path):
    spec = {"scenario": "error", "site": "io.*", "after": 1}
    p = fault_plan.parse_plan(json.dumps(spec))
    assert p.scenario == "error" and p.after == 1
    f = tmp_path / "plan.json"
    f.write_text(json.dumps(spec))
    q = fault_plan.parse_plan(str(f))
    assert q == p


# ---------------------------------------------------------------------------
# Hit counters and firing windows
# ---------------------------------------------------------------------------

def test_inject_noop_without_plan():
    # must be a literal no-op: no exception, no state
    for _ in range(3):
        faults.inject("mesh.fetch_replicated")


def test_inject_fires_in_window_only():
    faults.set_plan({"scenario": "error", "site": "mesh.*", "after": 2,
                     "count": 1})
    faults.inject("mesh.put_global")          # hit 0
    faults.inject("mesh.put_global")          # hit 1
    with pytest.raises(faults.FaultInjected):
        faults.inject("mesh.put_global")      # hit 2: fires
    faults.inject("mesh.put_global")          # hit 3: window closed


def test_site_pattern_and_target_filtering():
    faults.set_plan({"scenario": "error", "site": "io.*",
                     "target": "ogbn"})
    faults.inject("mesh.put_global")                      # wrong site
    faults.inject("io.load_decomposition", target="ba")   # wrong target
    with pytest.raises(faults.FaultInjected):
        faults.inject("io.load_decomposition", target="/data/ogbn_arxiv")


def test_on_step_nan_burst_is_seeded_and_deterministic():
    faults.set_plan({"scenario": "nan", "site": "*.step", "after": 0,
                     "burst": 3, "seed": 9})
    x = jnp.zeros((8, 4), dtype=jnp.float32)
    y = faults.on_step("multi_level.step", x)
    assert int(np.isnan(np.asarray(y)).sum()) == 3
    faults.set_plan({"scenario": "nan", "site": "*.step", "after": 0,
                     "burst": 3, "seed": 9})
    y2 = faults.on_step("multi_level.step", x)
    assert np.array_equal(np.isnan(np.asarray(y)), np.isnan(np.asarray(y2)))


def test_on_step_passthrough_without_plan():
    x = jnp.ones((4, 2))
    assert faults.on_step("multi_level.step", x) is x


# ---------------------------------------------------------------------------
# Supervisor
# ---------------------------------------------------------------------------

def _count_body(fail_at, exc=RuntimeError("transient")):
    """Body: x + 1 per iteration; raises once at iteration fail_at."""
    tripped = []

    def body(x, it):
        if it == fail_at and not tripped:
            tripped.append(it)
            raise exc
        return x + 1.0

    return body


def test_supervisor_clean_run():
    sup = Supervisor("t", carry=True, verbose=False)
    y, ok = sup.run(lambda x, it: x + 1.0, jnp.zeros(3), 0, 5)
    assert ok and np.allclose(np.asarray(y), 5.0)
    assert sup.faults_seen == 0 and sup.recoveries == 0


def test_supervisor_retries_transient_error():
    sup = Supervisor("t", carry=True, verbose=False, backoff_s=0.01)
    y, ok = sup.run(_count_body(2), jnp.zeros(3), 0, 5)
    assert ok and np.allclose(np.asarray(y), 5.0)
    assert sup.faults_seen == 1 and sup.recoveries == 1


def test_supervisor_exhausts_retries():
    def body(x, it):
        raise RuntimeError("always")

    sup = Supervisor("t", carry=True, verbose=False, max_retries=2,
                     backoff_s=0.01)
    y, ok = sup.run(body, jnp.zeros(3), 0, 5)
    assert not ok
    assert sup.faults_seen >= 3   # initial + 2 retries


def test_supervisor_abort_is_not_retried():
    calls = []

    def body(x, it):
        calls.append(it)
        raise Abort("validation gate failed")

    sup = Supervisor("t", carry=True, verbose=False)
    _, ok = sup.run(body, jnp.zeros(3), 0, 5)
    assert not ok and calls == [0]


def test_supervisor_nan_rollback_to_checkpoint(tmp_path):
    ck = str(tmp_path / "ck")
    poisoned = []

    def body(x, it):
        if it == 3 and not poisoned:
            poisoned.append(it)
            return x.at[0].set(float("nan"))
        return x + 1.0

    sup = Supervisor("t", carry=True, verbose=False, backoff_s=0.01,
                     checkpoint_path=ck, checkpoint_every=2)
    y, ok = sup.run(body, jnp.zeros(3), 0, 5)
    assert ok and np.allclose(np.asarray(y), 5.0)
    assert sup.faults_seen == 1 and sup.recoveries == 1
    assert sup.last_checkpoint_step == 5   # final save


def test_supervisor_watchdog_retry():
    slow = []

    def body(x, it):
        if it == 1 and not slow:
            slow.append(it)
            time.sleep(0.6)
        return x + 1.0

    sup = Supervisor("t", carry=True, verbose=False, watchdog_s=0.15,
                     watchdog_grace_s=30.0, backoff_s=0.01)
    y, ok = sup.run(body, jnp.zeros(3), 0, 3)
    assert ok and np.allclose(np.asarray(y), 3.0)
    assert sup.faults_seen == 1 and sup.recoveries == 1


def test_state_is_finite():
    assert state_is_finite(jnp.ones((4, 2)))
    assert not state_is_finite(jnp.array([1.0, float("inf")]))
    assert not state_is_finite(jnp.array([1.0, float("nan")]))


def test_supervisor_resume_matches_uninterrupted(tmp_path):
    """Resume mid-run: final X bit-identical to a never-interrupted
    run of the same body."""
    body = lambda x, it: x * 1.5 + it
    x0 = jnp.arange(6, dtype=jnp.float32)

    ref, ok = Supervisor("ref", carry=True, verbose=False).run(
        body, x0, 0, 6)
    assert ok

    ck = str(tmp_path / "ck")
    sup1 = Supervisor("a", carry=True, verbose=False,
                      checkpoint_path=ck, checkpoint_every=2)
    _, ok = sup1.run(body, x0, 0, 4)
    assert ok
    sup2 = Supervisor("b", carry=True, verbose=False, checkpoint_path=ck)
    resumed = sup2.resume(like=x0)
    assert resumed is not None
    x_mid, start = resumed
    assert start == 4
    y, ok = sup2.run(body, x_mid, start, 6)
    assert ok
    assert np.asarray(y).tobytes() == np.asarray(ref).tobytes()


# ---------------------------------------------------------------------------
# Artifact integrity manifests
# ---------------------------------------------------------------------------

def _tiny_artifact(tmp_path):
    from arrow_matrix_tpu.decomposition import arrow_decomposition
    from arrow_matrix_tpu.io import save_decomposition
    from arrow_matrix_tpu.utils import barabasi_albert

    a = barabasi_albert(64, 2, seed=3)
    levels = arrow_decomposition(a, 16, max_levels=4,
                                 block_diagonal=True, seed=3)
    base = str(tmp_path / "tiny")
    save_decomposition(levels, base)
    return base, levels[0].arrow_width


def test_manifest_written_and_verifies(tmp_path):
    from arrow_matrix_tpu.io.graphio import manifest_path, verify_manifest

    base, w = _tiny_artifact(tmp_path)
    mp = manifest_path(base, w)
    assert os.path.exists(mp)
    entries = json.load(open(mp))["files"]
    assert entries and all("sha256" in v for v in entries.values())
    assert verify_manifest(base, w)


def test_corruption_detected_and_names_file(tmp_path):
    from arrow_matrix_tpu.io.graphio import (
        ArtifactIntegrityError,
        FileKind,
        format_path,
        load_decomposition,
    )

    base, w = _tiny_artifact(tmp_path)
    victim = format_path(base, w, 0, True, FileKind.data)
    with open(victim, "r+b") as fh:
        fh.seek(-4, os.SEEK_END)
        fh.write(b"\x00\x01\x02\x03")
    with pytest.raises(ArtifactIntegrityError,
                       match=os.path.basename(victim)):
        load_decomposition(base, w)


def test_truncation_reported_as_truncation(tmp_path):
    from arrow_matrix_tpu.io.graphio import (
        ArtifactIntegrityError,
        FileKind,
        format_path,
        load_decomposition,
    )

    base, w = _tiny_artifact(tmp_path)
    victim = format_path(base, w, 0, True, FileKind.indices)
    size = os.path.getsize(victim)
    with open(victim, "r+b") as fh:
        fh.truncate(size // 2)
    with pytest.raises(ArtifactIntegrityError, match="truncated"):
        load_decomposition(base, w)


def test_verify_opt_out(tmp_path, monkeypatch):
    from arrow_matrix_tpu.io.graphio import (
        FileKind,
        format_path,
        manifest_path,
        verify_manifest,
    )

    base, w = _tiny_artifact(tmp_path)
    victim = format_path(base, w, 0, True, FileKind.data)
    with open(victim, "r+b") as fh:
        fh.seek(-4, os.SEEK_END)
        fh.write(b"\xff\xff\xff\xff")
    # explicit env opt-out skips verification entirely
    monkeypatch.setenv("AMT_VERIFY_ARTIFACTS", "0")
    from arrow_matrix_tpu.io.graphio import load_decomposition

    load_decomposition(base, w)   # corrupt, but not checked
    monkeypatch.delenv("AMT_VERIFY_ARTIFACTS")
    # absent manifest -> verify_manifest is False, load proceeds
    os.remove(manifest_path(base, w))
    assert not verify_manifest(base, w)
    load_decomposition(base, w)


# ---------------------------------------------------------------------------
# The chaos gate scenario matrix (fast tier; full gate is slow)
# ---------------------------------------------------------------------------

def _load_gate():
    spec = importlib.util.spec_from_file_location(
        "chaos_gate_test", os.path.join(REPO, "tools", "chaos_gate.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_chaos_gate_fast_scenarios(tmp_path):
    gate = _load_gate()
    problems, scenarios = gate.run_gate(str(tmp_path), fast=True)
    assert problems == []
    assert scenarios == ["nan", "hang", "corrupt", "sync", "kcert",
                         "lens", "synth", "host_kill", "serve_hang",
                         "serve_corrupt", "serve_overflow", "serve_hbm",
                         "slo_burn_degrade", "serve_classes",
                         "reshard_h7"]


@pytest.mark.slow
def test_chaos_gate_full(tmp_path):
    """Subprocess tier: includes the SIGKILL + checkpoint-resume
    scenarios (batch and serving)."""
    gate = _load_gate()
    problems, scenarios = gate.run_gate(str(tmp_path), fast=False)
    assert problems == []
    assert "kill" in scenarios
    assert "serve_kill" in scenarios
    assert "fleet_kill" in scenarios


# ---------------------------------------------------------------------------
# Per-worker retry jitter seeding (graft-fleet satellite)
# ---------------------------------------------------------------------------

def test_retry_policy_for_worker_reseeds_deterministically():
    """``for_worker`` must give every fleet worker its OWN
    reproducible jitter schedule: same (seed, worker_id) -> identical
    delays across processes and reruns; different worker ids ->
    different delays (no thundering herd on a shared dependency)."""
    from arrow_matrix_tpu.faults import RetryPolicy

    base = RetryPolicy(max_retries=4, backoff_s=0.05, jitter=0.5,
                       seed=7)
    w0 = base.for_worker("worker-0")
    assert w0 == base.for_worker("worker-0")          # frozen + stable
    assert w0.schedule("heartbeat") == \
        base.for_worker("worker-0").schedule("heartbeat")
    # Only the seed is re-derived; the knobs are untouched.
    assert (w0.max_retries, w0.backoff_s, w0.jitter) == \
        (base.max_retries, base.backoff_s, base.jitter)
    assert w0.seed != base.seed
    schedules = {base.for_worker(f"worker-{i}").schedule("heartbeat")
                 for i in range(8)}
    assert len(schedules) == 8                        # all distinct
    # A different BASE seed moves every worker's schedule too.
    other = RetryPolicy(max_retries=4, backoff_s=0.05, jitter=0.5,
                        seed=8)
    assert other.for_worker("worker-0").schedule("heartbeat") != \
        w0.schedule("heartbeat")
