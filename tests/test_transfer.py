"""Bounded host->device transfers (utils/transfer.py)."""

import numpy as np

from arrow_matrix_tpu.utils.transfer import chunked_asarray


def test_chunked_equals_whole_upload():
    rng = np.random.default_rng(0)
    for shape in [(7,), (33, 5), (9, 4, 3)]:
        x = rng.standard_normal(shape).astype(np.float32)
        # max_bytes tiny: forces the multi-chunk path.
        np.testing.assert_array_equal(
            np.asarray(chunked_asarray(x, max_bytes=64)), x)
        # default path (single RPC) unchanged.
        np.testing.assert_array_equal(np.asarray(chunked_asarray(x)), x)


def test_chunked_matches_jnp_asarray_semantics():
    import jax.numpy as jnp

    # Same dtype policy as a plain jnp.asarray (incl. the x64-mode
    # int64 -> int32 downcast) — chunking must not change semantics.
    for x in [np.arange(10, dtype=np.int16),
              np.float32(3.5),
              np.arange(6, dtype=np.int64).reshape(2, 3)]:
        out = chunked_asarray(np.asarray(x), max_bytes=8)
        ref = jnp.asarray(np.asarray(x))
        assert out.dtype == ref.dtype
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_chunk_count_bounded_by_leading_dim():
    # More required chunks than rows: clamps to one chunk per row.
    x = np.arange(3 * 100, dtype=np.float32).reshape(3, 100)
    np.testing.assert_array_equal(
        np.asarray(chunked_asarray(x, max_bytes=1)), x)
