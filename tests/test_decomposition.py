"""Decomposition invariant tests.

Port of the reference's property suite
(reference tests/test_arrowdecomposition.py:24-156) to the numpy/scipy
decomposer: permutation validity, edge-disjointness and union coverage,
the band/block width bound, exact reconstruction A = sum_i P_i^T B_i P_i,
and the golden SpMM identity.
"""

import numpy as np
import pytest
from scipy import sparse

from arrow_matrix_tpu.decomposition import (
    arrow_decomposition,
    decomposition_spmm,
    reconstruct,
)
from arrow_matrix_tpu.utils import barabasi_albert, erdos_renyi, random_dense


def datasets():
    out = [barabasi_albert(2 ** i, 4, seed=503) for i in range(4, 8)]
    out += [barabasi_albert(2 ** i, 8, seed=3434) for i in range(5, 8)]
    out += [erdos_renyi(2 ** i, 0.1, seed=7) for i in range(5, 8)]
    out += [barabasi_albert(2 ** i, 3, seed=11, directed=True) for i in range(9, 11)]
    return out


WIDTH_DIVISORS = [4, 8, 10]


@pytest.mark.parametrize("g_index", range(len(datasets())))
def test_invariants(g_index):
    a = datasets()[g_index]
    n = a.shape[0]
    rng = np.random.default_rng(42)
    x = rng.random((n, 16), dtype=np.float32)

    for width_c in WIDTH_DIVISORS:
        width = n // width_c + 1
        levels = arrow_decomposition(a, width, max_levels=100,
                                     block_diagonal=True, seed=width_c)

        # Permutations are actual permutations.
        for lvl in levels:
            assert lvl.permutation.size == n
            assert np.array_equal(np.sort(lvl.permutation), np.arange(n))

        # Un-permuted levels are edge-disjoint and union to A's pattern.
        total_nnz = 0
        patterns = []
        for lvl in levels:
            p = lvl.permutation
            coo = lvl.matrix.tocoo()
            keys = set(zip(p[coo.row].tolist(), p[coo.col].tolist()))
            assert len(keys) == coo.nnz
            patterns.append(keys)
            total_nnz += coo.nnz
        union = set().union(*patterns)
        assert len(union) == total_nnz  # pairwise disjoint
        a_coo = a.tocoo()
        a_keys = set(zip(a_coo.row.tolist(), a_coo.col.tolist()))
        assert union == a_keys

        # Width bound holds edge-by-edge.
        for lvl in levels:
            w = lvl.arrow_width
            coo = lvl.matrix.tocoo()
            ok = (np.abs(coo.row - coo.col) <= w) | (coo.row < w) | (coo.col < w)
            assert bool(np.all(ok))

        # Exact reconstruction and golden SpMM.
        diff = (reconstruct(levels) - a).tocsr()
        assert diff.nnz == 0 or np.max(np.abs(diff.data)) < 1e-6
        ref_c = (a.astype(np.float32) @ x).astype(np.float32)
        val_c = decomposition_spmm(levels, x)
        np.testing.assert_allclose(val_c, ref_c, rtol=1e-4, atol=1e-4)


def test_band_mode_width_bound():
    a = barabasi_albert(256, 4, seed=1)
    levels = arrow_decomposition(a, 40, max_levels=100, block_diagonal=False,
                                 seed=0)
    for lvl in levels:
        w = lvl.arrow_width
        coo = lvl.matrix.tocoo()
        ok = (np.abs(coo.row - coo.col) <= w) | (coo.row < w) | (coo.col < w)
        assert bool(np.all(ok))


def test_last_level_keeps_everything():
    a = erdos_renyi(128, 0.2, seed=3)
    levels = arrow_decomposition(a, 16, max_levels=2, block_diagonal=True,
                                 seed=0)
    assert len(levels) <= 2
    assert sum(l.matrix.nnz for l in levels) == a.nnz


def test_values_preserved():
    rng = np.random.default_rng(0)
    a = sparse.random(100, 100, density=0.05, format="csr", random_state=rng,
                      dtype=np.float64)
    a = a + a.T
    levels = arrow_decomposition(a, 20, max_levels=10, block_diagonal=True,
                                 seed=5)
    diff = (reconstruct(levels) - a).tocsr()
    assert diff.nnz == 0 or np.max(np.abs(diff.data)) < 1e-12


def test_deterministic_fallback():
    a = barabasi_albert(200, 3, seed=9)
    l1 = arrow_decomposition(a, 30, max_levels=1, block_diagonal=True)
    l2 = arrow_decomposition(a, 30, max_levels=1, block_diagonal=True)
    assert np.array_equal(l1[0].permutation, l2[0].permutation)
    x = random_dense(200, 8, seed=1)
    np.testing.assert_allclose(decomposition_spmm(l1, x), a @ x, rtol=1e-4,
                               atol=1e-4)


def test_banded_input_fast_path():
    """An already-banded matrix (the planar-graph class under its
    natural order — e.g. a row-major 2-D grid) decomposes to ONE
    identity-permutation level: zero inter-level routing where the
    forest linearization would have scrambled it into several levels."""
    from arrow_matrix_tpu.decomposition.decompose import (
        arrow_decomposition,
        decomposition_spmm,
    )
    from arrow_matrix_tpu.utils.graphs import grid_graph, random_dense

    a = grid_graph(32)            # n=1024, bandwidth 32
    levels = arrow_decomposition(a, 64, max_levels=8,
                                 block_diagonal=True, seed=0)
    assert len(levels) == 1
    np.testing.assert_array_equal(levels[0].permutation,
                                  np.arange(1024))
    assert levels[0].arrow_width <= 64
    x = random_dense(1024, 4, seed=1)
    np.testing.assert_allclose(decomposition_spmm(levels, x),
                               np.asarray(a @ x), rtol=1e-5, atol=1e-5)

    # A hub graph must NOT take the fast path.
    from arrow_matrix_tpu.utils.graphs import barabasi_albert

    b = barabasi_albert(512, 3, seed=2)
    lv = arrow_decomposition(b, 32, max_levels=4, block_diagonal=True,
                             seed=0)
    assert len(lv) > 1


def test_bandable_input_rcm_fast_path():
    """A SCRAMBLED grid (planar graph in arbitrary input order) is
    recovered by the reverse-Cuthill-McKee gate: one level whose
    permutation re-bands it, exact SpMM, no linearization."""
    from arrow_matrix_tpu.decomposition.decompose import (
        arrow_decomposition,
        decomposition_spmm,
    )
    from arrow_matrix_tpu.utils.graphs import grid_graph, random_dense

    g = grid_graph(32)
    rng = np.random.default_rng(3)
    shuf = rng.permutation(g.shape[0])
    gs = g[shuf][:, shuf].tocsr()
    levels = arrow_decomposition(gs, 64, max_levels=8,
                                 block_diagonal=True, seed=0)
    assert len(levels) == 1
    lvl = levels[0]
    # The level really is banded in its own coordinates.
    coo = lvl.matrix.tocoo()
    assert int(np.abs(coo.row.astype(np.int64) - coo.col).max()) <= 64
    x = random_dense(gs.shape[0], 4, seed=1)
    np.testing.assert_allclose(decomposition_spmm(levels, x),
                               np.asarray(gs @ x), rtol=1e-5, atol=1e-5)

    # band_detect=False restores the plain recursion.
    lv2 = arrow_decomposition(gs, 64, max_levels=8,
                              block_diagonal=True, seed=0,
                              band_detect=False)
    assert len(lv2) > 1
