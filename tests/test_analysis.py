"""graft-lint (arrow_matrix_tpu.analysis) — one positive and one
negative fixture per rule R1-R9, the waiver machinery, the
package-clean gate (the shipped tree must lint clean, the same
invariant amt_doctor and tools/lint_gate.py enforce), and a
reduced-scale run of the trace-time recompile audit."""

import json
import os
import subprocess
import sys
import textwrap

import pytest

import arrow_matrix_tpu
from arrow_matrix_tpu.analysis import lint_paths, lint_source, rule_table

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _rules(source: str, path: str = "case.py"):
    findings, waived = lint_source(textwrap.dedent(source), path)
    return [f.rule for f in findings], [w.rule for w in waived]


# ---------------------------------------------------------------------------
# One (positive, negative) fixture pair per rule.  Positives must fire
# exactly the rule under test; negatives must be silent.
# ---------------------------------------------------------------------------

FIXTURES = {
    "R1": (
        # host sync inside a jitted function: float() forces a device
        # round-trip per trace.
        """
        import jax
        @jax.jit
        def f(x):
            return float(x) + 1
        """,
        # static shape access is host-side metadata, not a sync.
        """
        import jax
        @jax.jit
        def f(x):
            k = int(x.shape[-1])
            return x * k
        """,
    ),
    "R2": (
        # fresh jit per call: nothing caches across invocations.
        """
        import jax
        def g(f, x):
            return jax.jit(f)(x)
        """,
        # jit factory memoized by lru_cache — the mesh.py _replicator
        # idiom.
        """
        import jax, functools
        @functools.lru_cache(maxsize=8)
        def make(n):
            return jax.jit(lambda x: x * n)
        def g(x):
            return make(3)(x)
        """,
    ),
    "R3": (
        # scan over a carried buffer jitted without donation: the old
        # carry buffer doubles the footprint.
        """
        import jax
        from jax import lax
        def scan_steps(x, blocks, n):
            def body(c, _):
                return c @ blocks, None
            out, _ = lax.scan(body, x, None, length=n)
            return out
        step = jax.jit(scan_steps, static_argnames=("n",))
        """,
        # donated sibling present — the multi_level/sell_slim pairing.
        """
        import jax
        from jax import lax
        def scan_steps(x, blocks, n):
            def body(c, _):
                return c @ blocks, None
            out, _ = lax.scan(body, x, None, length=n)
            return out
        step = jax.jit(scan_steps, static_argnames=("n",))
        step_d = jax.jit(scan_steps, static_argnames=("n",),
                         donate_argnums=(0,))
        """,
    ),
    "R4": (
        # PartitionSpec names an axis no mesh in the module declares.
        """
        from jax.sharding import Mesh, PartitionSpec as P
        import numpy as np, jax
        mesh = Mesh(np.array(jax.devices()), ("blocks",))
        spec = P("blocka")
        """,
        """
        from jax.sharding import Mesh, PartitionSpec as P
        import numpy as np, jax
        mesh = Mesh(np.array(jax.devices()), ("blocks",))
        spec = P("blocks")
        """,
    ),
    "R5": (
        # bare float literal in traced arithmetic: weak-type promotion
        # can silently upcast bf16/f16 operands.
        """
        import jax
        @jax.jit
        def f(x):
            return x * 0.5
        """,
        # typed scalar (and int literals, which promote safely).
        """
        import jax
        @jax.jit
        def f(x):
            return x * x.dtype.type(0.5) + x * 2
        """,
    ),
    "R6": (
        # np.asarray on a device value outside any jit: an unguarded
        # blocking device_get.
        """
        import jax.numpy as jnp
        import numpy as np
        def f(cols):
            y = jnp.dot(cols, cols)
            return np.asarray(y)
        """,
        # host-only numpy pipeline: no device value involved.
        """
        import numpy as np
        def f(x):
            y = np.dot(x, x)
            return np.asarray(y)
        """,
    ),
    "R7": (
        # perf_counter around a jitted call without block_until_ready:
        # dispatch is async, so this times the launch, not the device.
        """
        import time
        import jax
        def bench(f0, x):
            f = jax.jit(f0)
            t0 = time.perf_counter()
            y = f(x)
            dt = time.perf_counter() - t0
            return y, dt
        """,
        # blocking on the result inside the region synchronises the
        # measurement — the obs/tracer.py harness idiom.
        """
        import time
        import jax
        def bench(f0, x):
            f = jax.jit(f0)
            t0 = time.perf_counter()
            y = jax.block_until_ready(f(x))
            dt = time.perf_counter() - t0
            return y, dt
        """,
    ),
    "R8": (
        # broad except with a body of only `pass`: device errors,
        # injected faults, and watchdog escapes vanish silently.
        """
        def f(step, x):
            try:
                return step(x)
            except Exception:
                pass
        """,
        # narrow type, and a broad handler that actually handles.
        """
        def f(step, x):
            try:
                return step(x)
            except ValueError:
                pass
            try:
                return step(x)
            except Exception as e:
                print(f"step failed: {e}")
                raise
        """,
    ),
    "R9": (
        # AMT_* environment read inside a jitted step function: the
        # value is baked at trace time, so flipping the knob after the
        # first compile silently does nothing.
        """
        import os
        import jax
        @jax.jit
        def step(x):
            if os.environ.get("AMT_FUSE", "1") == "1":
                return x @ x
            return x
        """,
        # the shipped idiom: module-level / build-time reads resolve
        # the knob once (pallas_sell.py, utils/comm.py).
        """
        import os
        FUSE = os.environ.get("AMT_FUSE", "1") == "1"
        CHUNK = int(os.getenv("AMT_CHUNK_MB", "64"))
        def build(x):
            mode = os.environ.get("AMT_MODE", "auto")
            return (x, mode, FUSE, CHUNK)
        """,
    ),
}


@pytest.mark.parametrize("rule", sorted(FIXTURES))
def test_rule_positive_fires(rule):
    fired, _ = _rules(FIXTURES[rule][0])
    assert rule in fired, f"{rule} positive fixture did not fire: {fired}"
    assert set(fired) == {rule}, (
        f"{rule} positive fixture fired extra rules: {fired}")


@pytest.mark.parametrize("rule", sorted(FIXTURES))
def test_rule_negative_silent(rule):
    fired, _ = _rules(FIXTURES[rule][1])
    assert rule not in fired, (
        f"{rule} negative fixture fired anyway: {fired}")


def test_all_shipped_rules_registered():
    ids = {spec.rule_id for spec in rule_table()}
    assert ids >= {"R1", "R2", "R3", "R4", "R5", "R6", "R7", "R8", "R9"}


def test_waiver_suppresses_and_records():
    fired, waived = _rules("""
        import jax.numpy as jnp
        import numpy as np
        def f(cols):
            y = jnp.dot(cols, cols)
            return np.asarray(y)  # graft-lint: disable=R6
        """)
    assert fired == [] and waived == ["R6"]


def test_r8_waiver_with_reason_text():
    """A deliberate broad swallow takes an inline waiver on the
    `except` line; trailing free-text reasons must not break parsing."""
    fired, waived = _rules("""
        def f(probe):
            try:
                return probe()
            except Exception:  # graft-lint: disable=R8 — best-effort probe
                pass
        """)
    assert fired == [] and waived == ["R8"]


def test_file_waiver_suppresses_all():
    fired, waived = _rules("""
        # graft-lint: disable-file=R6
        import jax.numpy as jnp
        import numpy as np
        def f(cols):
            y = jnp.dot(cols, cols)
            return np.asarray(y)
        """)
    assert fired == [] and waived == ["R6"]


def test_select_filters_rules():
    findings, _ = lint_source(textwrap.dedent(FIXTURES["R5"][0]),
                              "case.py", select=frozenset({"R1"}))
    assert findings == []


def test_finding_format_and_json():
    findings, _ = lint_source(textwrap.dedent(FIXTURES["R1"][0]), "p.py")
    assert findings
    f = findings[0]
    assert f.format().startswith(f"p.py:{f.line} R1 ")
    rec = f.to_json()
    assert rec["path"] == "p.py" and rec["rule"] == "R1"


# ---------------------------------------------------------------------------
# The package gate: the shipped tree must lint clean.
# ---------------------------------------------------------------------------


def test_shipped_package_lints_clean():
    pkg = os.path.dirname(os.path.abspath(arrow_matrix_tpu.__file__))
    findings, _ = lint_paths([pkg])
    assert not findings, "\n".join(f.format() for f in findings)


def test_graft_flight_obs_entry_points_lint_clean():
    """The graft-flight additions specifically: the memory/imbalance
    accounting and the flight recorder are observability code that
    runs INSIDE measured regions, so they above all must not introduce
    the hazards the linter hunts (host syncs, fresh jits, unblocked
    timing)."""
    obs_dir = os.path.join(os.path.dirname(
        os.path.abspath(arrow_matrix_tpu.__file__)), "obs")
    paths = [os.path.join(obs_dir, m)
             for m in ("memview.py", "imbalance.py", "flight.py")]
    findings, _ = lint_paths(paths)
    assert not findings, "\n".join(f.format() for f in findings)

    # The --mem_report CLI idiom: lower/compile/memory_analysis is
    # host-side executable introspection, not a device round-trip —
    # the accounting call pattern must stay silent under every rule.
    fired, _ = _rules("""
        from arrow_matrix_tpu import obs
        def report(dist, step_fn, x, k):
            mem = obs.account_memory(
                "algo", step_fn, x,
                predicted_bytes=obs.predicted_bytes_for(dist, k))
            imb = obs.account_imbalance("algo", dist)
            return obs.format_memory_report(mem), imb
    """)
    assert fired == []


def test_cli_exits_nonzero_on_violation(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent(FIXTURES["R1"][0]))
    proc = subprocess.run(
        [sys.executable, "-m", "arrow_matrix_tpu.analysis",
         str(bad), "--json"],
        capture_output=True, text=True, timeout=120, cwd=REPO)
    assert proc.returncode == 1
    out = json.loads(proc.stdout)
    assert out["count"] == 1
    assert out["findings"][0]["rule"] == "R1"


def test_cli_exits_zero_on_clean(tmp_path):
    good = tmp_path / "good.py"
    good.write_text("x = 1\n")
    proc = subprocess.run(
        [sys.executable, "-m", "arrow_matrix_tpu.analysis", str(good)],
        capture_output=True, text=True, timeout=120, cwd=REPO)
    assert proc.returncode == 0, proc.stderr


# ---------------------------------------------------------------------------
# Trace-time audit (engine 2) at reduced scale: every core SpMM entry
# point must compile once and reuse the cache on a same-shape call.
# ---------------------------------------------------------------------------


def test_audit_zero_recompiles_reduced_scale():
    from arrow_matrix_tpu.analysis.audit import run_audit

    manifest = run_audit(n=128, width=32, k=4, n_dev=4, write=False)
    assert manifest["ok"], json.dumps(manifest["entries"], indent=2)
    names = {e["entry"] for e in manifest["entries"]}
    assert names == {"spmm_1d.MatrixSlice1D", "spmm_15d.SpMM15D",
                     "sell_slim.SellSlim",
                     "multi_level.MultiLevelArrow"}
    for e in manifest["entries"]:
        assert e["recompiles_second_call"] == 0


def test_manifest_checked_in_and_ok():
    path = os.path.join(REPO, "bench_cache", "compile_manifest.json")
    with open(path, encoding="utf-8") as fh:
        manifest = json.load(fh)
    assert manifest["ok"]
    assert len(manifest["entries"]) == 4
