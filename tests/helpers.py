"""Shared test fixtures (imported by the suites; not collected)."""

import numpy as np
from scipy import sparse


def arrow_csr(n_blocks: int, width: int, banded: bool = False,
              seed: int = 0, density: float = 0.25) -> sparse.csr_matrix:
    """Random matrix with exact arrow block structure (the reference's
    dense structured analog, tests/test_arrowmpi.py:407-421)."""
    rng = np.random.default_rng(seed)

    def blk():
        return sparse.random(width, width, density=density,
                             random_state=rng, dtype=np.float32)

    grid = [[None] * n_blocks for _ in range(n_blocks)]
    for j in range(n_blocks):
        grid[0][j] = blk()
    for i in range(1, n_blocks):
        grid[i][0] = blk()
        grid[i][i] = blk()
        if banded:
            if i - 1 >= 1:
                grid[i][i - 1] = blk()
            if i + 1 < n_blocks:
                grid[i][i + 1] = blk()
    a = sparse.bmat(grid, format="csr").astype(np.float32)
    a.sum_duplicates()
    a.sort_indices()
    return a
