"""HYB (split-ELL) whole-level kernel tests (ops/hyb.py): the
single-chip general SpMM replacing arrow blocking within one device
(the role of the reference's per-rank cuSPARSE CSRMM, sp2cp.py:6-16)."""

import jax.numpy as jnp
import numpy as np
import pytest
from scipy import sparse

from arrow_matrix_tpu.decomposition import arrow_decomposition
from arrow_matrix_tpu.decomposition.decompose import decomposition_spmm
from arrow_matrix_tpu.ops.hyb import (
    HybLevel,
    choose_light_slots,
    hyb_from_csr,
    hyb_spmm,
)
from arrow_matrix_tpu.parallel import MultiLevelArrow, make_mesh
from arrow_matrix_tpu.utils import barabasi_albert, random_dense


def test_choose_light_slots():
    deg = np.array([1, 2, 3, 100, 200])
    # cap=2 heavy rows: m0 covers the 3rd largest (3), aligned to 8.
    assert choose_light_slots(deg, heavy_cap=2) == 8
    assert choose_light_slots(deg, heavy_cap=0) == 200
    assert choose_light_slots(np.array([], dtype=np.int64), 4) == 0


@pytest.mark.parametrize("chunk", [None, 8])
def test_hyb_spmm_matches_scipy(chunk):
    rng = np.random.default_rng(0)
    a = sparse.random(200, 200, density=0.05, format="csr",
                      random_state=rng, dtype=np.float32)
    # Inject two hub rows so the heavy path is exercised.
    a = a.tolil()
    a[7, :] = rng.standard_normal(200).astype(np.float32)
    a[123, ::2] = 1.0
    a = a.tocsr()
    a.sum_duplicates()
    a.sort_indices()

    h = hyb_from_csr(a, heavy_cap=4)
    assert h.heavy_idx.shape[0] >= 2
    x = random_dense(200, 8, seed=1)
    out = np.asarray(hyb_spmm(h, jnp.asarray(x), chunk=chunk))
    np.testing.assert_allclose(out, a @ x, rtol=1e-4, atol=1e-5)


def test_hyb_row_padding():
    a = sparse.identity(10, format="csr", dtype=np.float32)
    h = hyb_from_csr(a, pad_rows_to=16)
    x = random_dense(16, 4, seed=2)
    out = np.asarray(hyb_spmm(h, jnp.asarray(x)))
    assert out.shape == (16, 4)
    np.testing.assert_allclose(out[:10], x[:10], rtol=1e-6, atol=1e-6)
    assert np.all(out[10:] == 0)


def test_hyb_implicit_ones_triplet():
    a = barabasi_albert(100, 3, seed=4)
    trip = (None, a.indices, a.indptr)   # memmap-style implicit data
    h = hyb_from_csr(trip)
    x = random_dense(100, 4, seed=3)
    out = np.asarray(hyb_spmm(h, jnp.asarray(x)))
    np.testing.assert_allclose(out, a.astype(np.float32) @ x,
                               rtol=1e-5, atol=1e-5)


def test_multi_level_hyb_matches_golden():
    """fmt='hyb' end-to-end, including a grown last level whose arrow
    blocking would be pathological (the protocol-scale finding)."""
    n, width = 480, 32
    a = barabasi_albert(n, 6, seed=19)
    levels = arrow_decomposition(a, width, max_levels=2,
                                 block_diagonal=True, seed=2)
    assert levels[-1].arrow_width > width  # grown last level
    ml = MultiLevelArrow(levels, width, mesh=None, fmt="hyb")
    assert all(isinstance(b, HybLevel) for b in ml.blocks)
    x_host = random_dense(n, 8, seed=3)
    out = ml.gather_result(ml.step(ml.set_features(x_host)))
    np.testing.assert_allclose(out, decomposition_spmm(levels, x_host),
                               rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(out, a @ x_host, rtol=1e-3, atol=1e-3)

    # Iterated run (lax.scan) works over HybLevel pytrees too.
    a2 = (a / 8.0).tocsr().astype(np.float32)
    levels2 = arrow_decomposition(a2, width, max_levels=2,
                                  block_diagonal=True, seed=2)
    ml2 = MultiLevelArrow(levels2, width, mesh=None, fmt="hyb")
    xd = ml2.run(ml2.set_features(x_host), 3)
    want = x_host
    for _ in range(3):
        want = a2 @ want
    np.testing.assert_allclose(ml2.gather_result(xd), want,
                               rtol=1e-3, atol=1e-4)


def test_hyb_rejected_on_mesh():
    a = barabasi_albert(128, 3, seed=1)
    levels = arrow_decomposition(a, 16, max_levels=2, block_diagonal=True,
                                 seed=0)
    with pytest.raises(ValueError, match="single-chip"):
        MultiLevelArrow(levels, 16, mesh=make_mesh((8,), ("blocks",)),
                        fmt="hyb")


def test_binary_hyb_detected_and_exact():
    """Binary (implicit-ones) HYB: adjacency data is all ones, so the
    data arrays are dropped and a per-row degree mask replaces the
    multiply.  Must be bit-identical to the f32 path (the mask selects
    the same addends in the same slot order)."""
    a = barabasi_albert(300, 5, seed=11)
    assert np.all(a.data == 1.0)
    hb = hyb_from_csr(a)                      # auto-detects binary
    hf = hyb_from_csr(a, binary=False)
    assert hb.light_data is None and hb.light_deg is not None
    assert hf.light_data is not None and hf.light_deg is None
    # ~half the resident bytes on the light part.
    assert hb.device_nbytes() < 0.6 * hf.device_nbytes()
    x = random_dense(300, 8, seed=5)
    out_b = np.asarray(hyb_spmm(hb, jnp.asarray(x)))
    out_f = np.asarray(hyb_spmm(hf, jnp.asarray(x)))
    np.testing.assert_array_equal(out_b, out_f)
    np.testing.assert_allclose(out_b, a @ x, rtol=1e-5, atol=1e-5)


def test_binary_hyb_chunked_and_padded():
    a = barabasi_albert(200, 4, seed=13)
    h = hyb_from_csr(a, pad_rows_to=256, heavy_cap=4)
    assert h.light_data is None
    x = random_dense(256, 4, seed=6)
    out = np.asarray(hyb_spmm(h, jnp.asarray(x), chunk=8))
    np.testing.assert_allclose(out[:200], a @ x[:200], rtol=1e-5, atol=1e-5)
    assert np.all(out[200:] == 0)


def test_binary_rejected_on_weighted_matrix():
    """Non-unit data must NOT take the binary path under binary='auto',
    and must raise when binary is forced."""
    from arrow_matrix_tpu.utils.graphs import random_csr

    a = random_csr(64, 64, 4, seed=3)
    assert not np.all(a.data == 1.0)
    h = hyb_from_csr(a)
    assert h.light_data is not None
    with pytest.raises(ValueError, match="binary"):
        hyb_from_csr(a, binary=True)


def test_multi_level_hyb_binary_end_to_end():
    n, width = 480, 32
    a = barabasi_albert(n, 6, seed=19)
    levels = arrow_decomposition(a, width, max_levels=2,
                                 block_diagonal=True, seed=2)
    ml = MultiLevelArrow(levels, width, mesh=None, fmt="hyb")
    assert all(b.light_data is None for b in ml.blocks)
    x_host = random_dense(n, 8, seed=3)
    out = ml.gather_result(ml.step(ml.set_features(x_host)))
    np.testing.assert_allclose(out, a @ x_host, rtol=1e-3, atol=1e-3)


def test_fold_matches_golden_and_iterates():
    """fmt='fold': the whole decomposition composed into one operator
    (exact edge partition => A reconstructed in level-0 order)."""
    n, width = 480, 32
    a = barabasi_albert(n, 6, seed=19)
    levels = arrow_decomposition(a, width, max_levels=3,
                                 block_diagonal=True, seed=2)
    assert len(levels) >= 2
    ml = MultiLevelArrow(levels, width, mesh=None, fmt="fold")
    assert ml.fmts == ["fold"]
    assert ml.blocks[0].binary          # adjacency folds to binary
    x_host = random_dense(n, 8, seed=3)
    xd = ml.set_features(x_host)
    assert xd.shape[0] == 8             # feature-major carriage
    out = ml.gather_result(ml.step(xd))
    np.testing.assert_allclose(out, decomposition_spmm(levels, x_host),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(out, a @ x_host, rtol=1e-4, atol=1e-4)

    # Iterated scan run, weighted (non-binary) matrix.
    a2 = (a / 8.0).tocsr().astype(np.float32)
    levels2 = arrow_decomposition(a2, width, max_levels=3,
                                  block_diagonal=True, seed=2)
    ml2 = MultiLevelArrow(levels2, width, mesh=None, fmt="fold")
    assert not ml2.blocks[0].binary
    xd2 = ml2.run(ml2.set_features(x_host), 3)
    want = x_host
    for _ in range(3):
        want = a2 @ want
    np.testing.assert_allclose(ml2.gather_result(xd2), want,
                               rtol=1e-3, atol=1e-4)


def test_fold_export_load_roundtrip(tmp_path):
    """export_folded -> load_folded must rebuild a fold executor whose
    step is BIT-identical (same packed arrays, same carried
    permutation) without the source decomposition — the offline-pack /
    online-load split the 2^27 on-chip stage depends on.  Covers the
    binary, weighted, and bf16-carriage variants plus the donated-scan
    run path."""
    n, width = 480, 32
    a = barabasi_albert(n, 6, seed=19)
    levels = arrow_decomposition(a, width, max_levels=3,
                                 block_diagonal=True, seed=2)
    x_host = random_dense(n, 8, seed=3)
    for tag, kw, mat in (("bin", {}, a),
                         ("bf16", {"feature_dtype": "bf16"}, a),
                         ("wgt", {}, (a / 8.0).tocsr().astype(np.float32))):
        lv = levels if mat is a else arrow_decomposition(
            mat, width, max_levels=3, block_diagonal=True, seed=2)
        ml = MultiLevelArrow(lv, width, mesh=None, fmt="fold", **kw)
        d = tmp_path / tag
        ml.export_folded(str(d))
        ml2 = MultiLevelArrow.load_folded(str(d))
        assert ml2.feature_dtype == ml.feature_dtype
        assert ml2.blocks[0].binary == ml.blocks[0].binary
        np.testing.assert_array_equal(ml2.perm0, ml.perm0)
        want = np.asarray(ml.step(ml.set_features(x_host)))
        got = np.asarray(ml2.step(ml2.set_features(x_host)))
        np.testing.assert_array_equal(got, want, err_msg=tag)
        # donated scan run agrees with the plain run
        r1 = np.asarray(ml.run(ml.set_features(x_host), 2))
        r2 = np.asarray(ml2.run(ml2.set_features(x_host), 2,
                                donate=True))
        np.testing.assert_array_equal(r1, r2, err_msg=tag)


def test_fold_tight_packing_matches_golden():
    """fold_align=1 / fold_growth=1.1 (the 'fold_tight' bench
    candidate): fewer padded slots, BIT-equivalent math — tile
    padding costs no gathers, logical slots do (ops/sell.py)."""
    n, width = 480, 32
    a = barabasi_albert(n, 6, seed=19)
    levels = arrow_decomposition(a, width, max_levels=3,
                                 block_diagonal=True, seed=2)
    ml = MultiLevelArrow(levels, width, mesh=None, fmt="fold")
    tight = MultiLevelArrow(levels, width, mesh=None, fmt="fold",
                            fold_growth=1.1, fold_align=1)
    assert tight.blocks[0].n_slots < ml.blocks[0].n_slots
    x_host = random_dense(n, 8, seed=3)
    out = tight.gather_result(tight.step(tight.set_features(x_host)))
    np.testing.assert_allclose(out, decomposition_spmm(levels, x_host),
                               rtol=1e-4, atol=1e-4)
    # Same addends, different tiering: agree to f32 reassociation.
    ref = ml.gather_result(ml.step(ml.set_features(x_host)))
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


def test_fold_bf16_features():
    """feature_dtype='bf16' halves the carried-feature bytes (the
    k=128 amortization lever) with f32 accumulation: results track the
    f32 path to bf16 rounding, and the carriage dtype is bf16."""
    import ml_dtypes

    n, width = 480, 32
    a = barabasi_albert(n, 6, seed=19)
    levels = arrow_decomposition(a, width, max_levels=3,
                                 block_diagonal=True, seed=2)
    x_host = random_dense(n, 8, seed=3)
    want = decomposition_spmm(levels, x_host)

    ml = MultiLevelArrow(levels, width, mesh=None, fmt="fold",
                         feature_dtype="bf16")
    xd = ml.set_features(x_host)
    assert xd.dtype == ml_dtypes.bfloat16
    out = ml.gather_result(ml.step(xd))
    assert out.dtype == np.float32
    rel = (np.linalg.norm(out - want) / np.linalg.norm(want))
    assert rel < 2e-2, rel          # bf16 inputs: ~8-bit mantissa

    # Other formats must refuse (carriage stays f32 there).
    with pytest.raises(ValueError, match="feature_dtype"):
        MultiLevelArrow(levels, width, mesh=None, fmt="hyb",
                        feature_dtype="bf16")


def test_fold_equals_per_level_paths():
    """fold and the per-level hyb/ell paths are the same operator."""
    n, width = 320, 32
    a = barabasi_albert(n, 4, seed=23)
    levels = arrow_decomposition(a, width, max_levels=2,
                                 block_diagonal=True, seed=1)
    x_host = random_dense(n, 4, seed=9)
    outs = {}
    for f in ("fold", "hyb", "ell"):
        ml = MultiLevelArrow(levels, width, mesh=None, fmt=f)
        outs[f] = ml.gather_result(ml.step(ml.set_features(x_host)))
    np.testing.assert_allclose(outs["fold"], outs["ell"],
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(outs["hyb"], outs["ell"],
                               rtol=1e-4, atol=1e-5)


def test_fold_rejected_on_mesh():
    a = barabasi_albert(128, 3, seed=1)
    levels = arrow_decomposition(a, 16, max_levels=2, block_diagonal=True,
                                 seed=0)
    with pytest.raises(ValueError, match="single-chip"):
        MultiLevelArrow(levels, 16, mesh=make_mesh((8,), ("blocks",)),
                        fmt="fold")
