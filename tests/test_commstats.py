"""utils/commstats — the HLO shape-byte accountant and collective
parser, exercised on literal shape strings and a checked-in HLO
fixture (tests/fixtures/collectives.hlo) so the parsing contract is
pinned without compiling anything, plus the paper cost model's
moved-row count (``ideal_routing_bytes``)."""

import os

import numpy as np
import pytest

from arrow_matrix_tpu.utils import commstats

FIXTURE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "fixtures", "collectives.hlo")


# ---------------------------------------------------------------------------
# _shape_bytes: dtype x element-count over every bracketed shape in the
# string (tuples sum), unknown dtypes and unranked shapes count zero.
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("shape_str,expect", [
    ("f32[]", 4),                       # scalar: empty dims, one element
    ("f32[2,3]", 24),
    ("bf16[2,3]", 12),
    ("pred[8]", 8),
    ("(f32[8,16], s32[8,16])", 1024),   # tuple: elements sum
    ("f32[*]", 0),                      # unranked: no match, no bytes
    ("c64[4]", 0),                      # unknown dtype: skipped
    ("token[]", 0),
])
def test_shape_bytes(shape_str, expect):
    assert commstats._shape_bytes(shape_str) == expect


# ---------------------------------------------------------------------------
# _parse_hlo_collectives on the checked-in fixture: one all-gather
# (f32[32,16] output = 2048 B), one tuple-shaped all-to-all (2 x
# f32[8,16] = 1024 B), one async collective-permute whose -start
# carries the bytes (512 B) and whose -done is NOT double-counted.
# ---------------------------------------------------------------------------


def test_parse_hlo_fixture():
    with open(FIXTURE, encoding="utf-8") as fh:
        text = fh.read()
    stats = commstats._parse_hlo_collectives(text)

    assert stats["all-gather"] == {"count": 1, "bytes": 2048}
    assert stats["all-to-all"] == {"count": 1, "bytes": 1024}
    assert stats["collective-permute"] == {"count": 1, "bytes": 512}
    assert stats["all-reduce"] == {"count": 0, "bytes": 0}
    assert stats["reduce-scatter"] == {"count": 0, "bytes": 0}
    assert stats["total_bytes"] == 2048 + 1024 + 512


def test_format_stats_lists_only_nonzero_kinds():
    with open(FIXTURE, encoding="utf-8") as fh:
        stats = commstats._parse_hlo_collectives(fh.read())
    out = commstats.format_stats(stats)
    assert "all-gather" in out and "all-to-all" in out
    assert "all-reduce" not in out           # zero-count kinds elided
    assert "3,584" in out                    # TOTAL row


# ---------------------------------------------------------------------------
# ideal_routing_bytes: the paper model counts a row iff the adjacent-
# level position lands on a different device, both directions.
# ---------------------------------------------------------------------------


def test_ideal_routing_bytes_identity_is_zero():
    p = np.arange(8)
    assert commstats.ideal_routing_bytes([p, p], n_devices=2, k=4) == 0


def test_ideal_routing_bytes_counts_cross_device_rows():
    # 8 rows on 2 devices (4 rows each).  Swapping the two halves moves
    # every row across the boundary: 8 moved rows x 2 directions x k=1
    # x itemsize=1.
    p0 = np.arange(8)
    p1 = np.concatenate([np.arange(4, 8), np.arange(4)])
    assert commstats.ideal_routing_bytes(
        [p0, p1], n_devices=2, k=1, itemsize=1) == 16
    # Scales linearly in k and itemsize.
    assert commstats.ideal_routing_bytes(
        [p0, p1], n_devices=2, k=4, itemsize=4) == 16 * 16
