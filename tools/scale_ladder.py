"""Scale ladder toward the reference's "hundreds of millions of rows"
claim (VERDICT r2 item 3; reference README.md:3).

Rungs, each in its own subprocess so peak host RSS (ru_maxrss) is
attributable per phase:

  decompose24      BA-8 n=2^24 (16.7M rows, ~268M nnz) full native
                   decomposition -> artifact on disk (cached; the
                   offline/online split).
  ingest24         memmapped artifact -> SellMultiLevel on an 8-device
                   virtual CPU mesh via the STREAMING builder
                   (materialize=False): build seconds, peak RSS (must
                   stay far below the ~6.4 GB the in-memory levels
                   would hold), 2 iterations ms/iter, column-sliced
                   golden gate on one step.
  decompose26_grid planar 8192^2 grid (67M rows) decompose-only
                   through the banded fast path (the paper's
                   minor-excluded class): seconds + RSS; must return
                   ONE level.
  backend_race22   BA-8 n=2^22 full decomposition, native vs numpy
                   backend, same flags: the native decomposer's
                   raison d'etre measured at >=1e7-nnz scale.

Results append to bench_results/scale_ladder.json.  Everything is
host-side (decomposition + streaming ingest are the host's job); the
on-chip iterate at this scale is covered by the tunnel-heal pipeline.

Usage: PYTHONPATH=/root/repo python tools/scale_ladder.py [rung ...]
"""

from __future__ import annotations

import json
import os
import resource
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CACHE = os.path.join(REPO, "bench_cache")
OUT = os.path.join(REPO, "bench_results", "scale_ladder.json")
N24, N22 = 1 << 24, 1 << 22
WIDTH = 2048


def _rss_gb() -> float:
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 2**20


def _artifact24() -> str:
    return os.path.join(CACHE, f"ba_{N24}_8_w{WIDTH}_s7_L14")


def rung_decompose24() -> dict:
    from arrow_matrix_tpu.decomposition.decompose import arrow_decomposition
    from arrow_matrix_tpu.io import save_decomposition
    from arrow_matrix_tpu.utils.graphs import barabasi_albert

    base = _artifact24()
    cached = os.path.exists(base + ".complete")
    if cached and os.environ.get("AMT_LADDER_FORCE") != "1":
        return {"cached": True, "base": base}
    t0 = time.perf_counter()
    a = barabasi_albert(N24, 8, seed=7)
    gen_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    levels = arrow_decomposition(a, arrow_width=WIDTH, max_levels=14,
                                 block_diagonal=True, seed=7,
                                 backend="native")
    dec_s = time.perf_counter() - t0
    del a
    if not cached:
        # AMT_LADDER_FORCE re-MEASURES decompose (the native-kernel
        # speedup rung) without re-writing the multi-GB artifact.
        save_decomposition(levels, base, block_diagonal=True)
        with open(base + ".complete", "w") as f:
            f.write(f"{len(levels)} levels\n")
    return {"n": N24, "nnz": sum(int(l.matrix.nnz) for l in levels),
            "levels": len(levels), "generate_s": round(gen_s, 1),
            "decompose_s": round(dec_s, 1), "peak_rss_gb": round(_rss_gb(), 2),
            "backend": "native"}


def rung_ingest24() -> dict:
    from arrow_matrix_tpu.utils.platform import force_cpu_devices

    force_cpu_devices(8)
    import jax

    from arrow_matrix_tpu.io import (
        as_levels,
        load_decomposition,
        load_level_widths,
    )
    from arrow_matrix_tpu.parallel.mesh import make_mesh
    from arrow_matrix_tpu.parallel.sell_slim import SellMultiLevel
    from arrow_matrix_tpu.utils import numerics
    from arrow_matrix_tpu.utils.graphs import random_dense

    base = _artifact24()
    t0 = time.perf_counter()
    loaded = load_decomposition(base, WIDTH, block_diagonal=True,
                                mem_map=True)
    widths = load_level_widths(base, WIDTH, block_diagonal=True)
    if widths is None:
        widths = WIDTH
    levels = as_levels(loaded, widths, materialize=False)
    load_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    sm = SellMultiLevel(levels, WIDTH, make_mesh((8,), ("blocks",)),
                        routing="a2a")
    build_s = time.perf_counter() - t0
    build_rss = _rss_gb()

    k = 16
    x = random_dense(N24, k, seed=3)
    t0 = time.perf_counter()
    xt = sm.set_features(x)
    got = sm.gather_result(sm.step(xt))
    step1_s = time.perf_counter() - t0

    # Column-sliced golden (SpMM is column-separable): one host pass
    # over the memmapped levels at 4 columns gates the whole step.
    # (Each level's CSR materializes transiently here, so the
    # golden's RSS is excluded from the streaming-build claim —
    # build_peak_rss_gb above is captured before this block.)
    t0 = time.perf_counter()
    nnz = 0
    import numpy as np
    from scipy import sparse as sp

    x4 = np.ascontiguousarray(x[:, :4])
    want = np.zeros((N24, 4), np.float32)
    for lvl in levels:
        d, i, p = lvl.matrix
        nz = int(np.asarray(p[-1]))
        m = sp.csr_matrix(
            ((np.ones(nz, np.float32) if d is None
              else np.asarray(d[:nz], np.float32)),
             np.asarray(i[:nz]), np.asarray(p)),
            shape=(N24, N24))
        partial = m @ x4[lvl.permutation]
        want += partial[lvl.inverse_permutation]
        nnz += nz
        del m
    golden_s = time.perf_counter() - t0
    err = numerics.relative_error(got[:, :4], want)
    tol = numerics.relative_tolerance(nnz / N24)
    if not err <= tol:
        raise RuntimeError(f"2^24 streamed step misses golden: "
                           f"{err:.3e} > {tol:.3e}")

    # ms/iter, host CPU backend (the chip path is the heal pipeline's).
    t0 = time.perf_counter()
    xt2 = sm.run(xt, 2)
    jax.block_until_ready(xt2)
    iter_ms = (time.perf_counter() - t0) / 2 * 1e3
    return {"load_s": round(load_s, 1), "build_s": round(build_s, 1),
            "build_peak_rss_gb": round(build_rss, 2),
            "first_step_s": round(step1_s, 1),
            "iter_ms_cpu": round(iter_ms, 1),
            "golden_err": err, "golden_gate": tol,
            "golden_s": round(golden_s, 1),
            "device_bytes_gb": round(sum(
                o.device_nbytes() for o in sm.ops) / 2**30, 2),
            "peak_rss_gb": round(_rss_gb(), 2)}


def rung_decompose26_grid() -> dict:
    from arrow_matrix_tpu.decomposition.decompose import arrow_decomposition
    from arrow_matrix_tpu.utils.graphs import grid_graph

    side = 8192
    # A grid's RCM bandwidth is ~side, so the banded fast path needs
    # arrow_width >= side; 10240 matches the reference's own example
    # width scale (README.md:72 uses 10000).  At width 2048 the gate
    # correctly refuses and the recursion produces 2 levels instead
    # (measured 428.8 s) — the fast path must be driven at a width
    # the graph class actually fits.
    width = 10240
    t0 = time.perf_counter()
    a = grid_graph(side)
    gen_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    levels = arrow_decomposition(a, arrow_width=width, max_levels=14,
                                 block_diagonal=False, seed=7,
                                 backend="native")
    dec_s = time.perf_counter() - t0
    return {"n": side * side, "nnz": int(a.nnz), "width": width,
            "levels": len(levels),
            "one_level_fast_path": len(levels) == 1,
            "generate_s": round(gen_s, 1), "decompose_s": round(dec_s, 1),
            "peak_rss_gb": round(_rss_gb(), 2)}


def rung_decompose_1e8_grid() -> dict:
    """The reference's headline scale claim is "hundreds of millions
    of rows" (reference README.md:3).  A 10240^2 grid is 104.9M rows /
    ~419M nnz — the planar/minor-excluded class the paper's bound
    targets — decomposed through the banded RCM fast path to ONE
    level.  Scrambled first: the fast path must RECOVER the band, not
    inherit it from a convenient input order."""
    import numpy as np

    from arrow_matrix_tpu.decomposition.decompose import arrow_decomposition
    from arrow_matrix_tpu.utils.graphs import grid_graph

    side = 10240
    width = 12800           # >= RCM bandwidth (~side), same 1.25x rule
    t0 = time.perf_counter()
    a = grid_graph(side)
    rng = np.random.default_rng(3)
    scramble = rng.permutation(side * side)
    a = a[scramble][:, scramble].tocsr()
    gen_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    levels = arrow_decomposition(a, arrow_width=width, max_levels=14,
                                 block_diagonal=False, seed=7,
                                 backend="native")
    dec_s = time.perf_counter() - t0
    return {"n": side * side, "nnz": int(a.nnz), "width": width,
            "levels": len(levels),
            "one_level_fast_path": len(levels) == 1,
            "scrambled_input": True,
            "generate_s": round(gen_s, 1), "decompose_s": round(dec_s, 1),
            "peak_rss_gb": round(_rss_gb(), 2)}


def rung_decompose_1e8_ba() -> dict:
    """Power-law at the reference's headline scale: BA m=4 at n=2^27 =
    134.2M rows / ~1.07e9 nnz, full native recursion (the HARD class —
    no banded shortcut).  Decompose-only: the on-chip iterate at this
    scale exceeds one v5e's HBM at k=16 f32 (operator ~4.3 GB + two
    ~8.6 GB feature buffers); bf16 carriage or k-tiling would fit it,
    which is multi-chip territory by design."""
    from arrow_matrix_tpu.decomposition.decompose import arrow_decomposition
    from arrow_matrix_tpu.utils.graphs import barabasi_albert

    n = 1 << 27
    t0 = time.perf_counter()
    a = barabasi_albert(n, 4, seed=7)
    gen_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    levels = arrow_decomposition(a, arrow_width=WIDTH, max_levels=14,
                                 block_diagonal=True, seed=7,
                                 backend="native")
    dec_s = time.perf_counter() - t0
    return {"n": n, "nnz": sum(int(l.matrix.nnz) for l in levels),
            "levels": len(levels), "generate_s": round(gen_s, 1),
            "decompose_s": round(dec_s, 1),
            "peak_rss_gb": round(_rss_gb(), 2), "backend": "native"}


def rung_rehearse_1e8_ba_step() -> dict:
    """BA-2^27 single-chip STEP rehearsal, end-to-end in degraded
    (host CPU) mode — VERDICT r4 item 2.  Generate -> native decompose
    -> fold into ONE bf16-carriage SELL operator -> export the packed
    operator (offline/online split: the on-chip watcher stage
    `ba27` ingests the export and steps without redoing the ~2.2 h of
    host work) -> explicit HBM budget vs one 16 GB v5e -> ONE donated
    run() step golden-gated against scipy on sampled rows.

    Feasibility argument made concrete: at n=2^27, k=16 the f32
    carriage needs 2 x 8.6 GB buffers + ~5 GB operator (over 16 GB);
    bf16 carriage (2 x 4.3 GB) + scan-buffer donation (input aliased
    to the carry, so ONE carried buffer + the in-flight output) fits.
    """
    from arrow_matrix_tpu.utils.platform import force_cpu_devices

    force_cpu_devices()
    import numpy as np

    from arrow_matrix_tpu.decomposition.decompose import arrow_decomposition
    from arrow_matrix_tpu.parallel.multi_level import MultiLevelArrow
    from arrow_matrix_tpu.utils.graphs import barabasi_albert, random_dense

    # AMT_BA27_LOGN: logic-validation knob (tests run the identical
    # path at a small n; the recorded rung always runs the real 2^27).
    n = 1 << int(os.environ.get("AMT_BA27_LOGN", 27))
    k, x_seed = 16, 5
    out: dict = {"n": n, "k": k, "feature_dtype": "bf16"}
    t0 = time.perf_counter()
    a = barabasi_albert(n, 4, seed=7)
    out["generate_s"] = round(time.perf_counter() - t0, 1)
    t0 = time.perf_counter()
    levels = arrow_decomposition(a, arrow_width=WIDTH, max_levels=14,
                                 block_diagonal=True, seed=7,
                                 backend="native")
    out["decompose_s"] = round(time.perf_counter() - t0, 1)
    out["levels"] = len(levels)
    out["nnz"] = sum(int(lvl.matrix.nnz) for lvl in levels)
    t0 = time.perf_counter()
    # Tight packing (the fold_tight candidate): ~1.04x nnz logical
    # slots vs ~1.25x at the stacked default — at 2^27 that is the
    # difference between a ~5.4 GB and a ~4.5 GB operator, which the
    # 16 GB budget below needs.  dense_budget pins gather_budget to
    # 512 MB (2^31 // 4) so the scratch term is explicit, not
    # device-derived.
    ml = MultiLevelArrow(levels, WIDTH, mesh=None, fmt="fold",
                         feature_dtype="bf16", fold_growth=1.1,
                         fold_align=1, dense_budget=1 << 31)
    del levels
    out["fold_build_s"] = round(time.perf_counter() - t0, 1)
    # Write the export to a temp dir and swap it in at the END (the
    # tunnel watcher's ba27 stage gates on rehearsal.json — it must
    # never see a half-written operator).  AMT_BA27_EXPORT: same
    # override the consumer (tools/ba27_bench.py) honors — tests
    # point both at a scratch dir and never touch the live path.
    export_dir = os.environ.get("AMT_BA27_EXPORT",
                                os.path.join(CACHE, "ba27_fold"))
    tmp_dir = export_dir + ".tmp"
    import shutil

    shutil.rmtree(tmp_dir, ignore_errors=True)
    t0 = time.perf_counter()
    ml.export_folded(tmp_dir)
    out["export_s"] = round(time.perf_counter() - t0, 1)

    # HBM budget: what the REAL chip must hold.  Operator = int32 slot
    # tiles + per-tier degree vectors (binary adjacency: no data
    # array); carriage = ONE resident bf16 buffer thanks to donation,
    # plus the in-flight output; scratch = the auto-chunk gather bound.
    sell = ml.blocks[0]
    total = ml.total_rows
    cols_gb = sum(c.shape[0] * c.shape[1] * 4 for c in sell.cols) / 2**30
    deg_gb = sum(d.shape[0] * 4 for d in (sell.deg or ())) / 2**30
    buf_gb = k * total * 2 / 2**30          # bf16 carriage
    scratch_gb = ((1 << 31) // 4) / 2**30   # the pinned gather budget
    budget = {
        "operator_cols_gb": round(cols_gb, 2),
        "operator_deg_gb": round(deg_gb, 2),
        "carried_buffer_bf16_gb": round(buf_gb, 2),
        "in_flight_output_gb": round(buf_gb, 2),
        "gather_scratch_gb": round(scratch_gb, 2),
        "total_gb": round(cols_gb + deg_gb + 2 * buf_gb + scratch_gb, 2),
        "hbm_gb": 16.0,
    }
    budget["fits"] = budget["total_gb"] < budget["hbm_gb"]
    out["hbm_budget"] = budget
    print(f"[ba27] HBM budget: {json.dumps(budget)}", file=sys.stderr,
          flush=True)
    assert budget["fits"], "2^27 bf16 single-chip budget exceeded"

    x = random_dense(n, k, seed=x_seed)
    t0 = time.perf_counter()
    xt = ml.set_features(x)
    out["set_features_s"] = round(time.perf_counter() - t0, 1)
    t0 = time.perf_counter()
    y = np.asarray(ml.run(xt, 1, donate=True))
    out["host_step_s_inc_compile"] = round(time.perf_counter() - t0, 1)

    # Golden gate: scipy on sampled rows (the full 134M-row golden
    # would double peak RSS for no extra signal).
    rows = np.linspace(0, n - 1, 4096).astype(np.int64)
    res = y[:, ml.inv_perm0[rows]].astype(np.float32).T   # (4096, k)
    want = a[rows] @ x
    rel = float(np.linalg.norm(res - want) / np.linalg.norm(want))
    out["golden_sample_rel_err"] = round(rel, 6)
    assert rel < 2e-2, f"sampled golden off: {rel}"
    np.save(os.path.join(tmp_dir, "sample_rows.npy"), rows)
    np.save(os.path.join(tmp_dir, "sample_out.npy"),
            want.astype(np.float32))
    with open(os.path.join(tmp_dir, "rehearsal.json"), "w") as f:
        json.dump({**out, "x_seed": x_seed}, f, indent=1)
    shutil.rmtree(export_dir, ignore_errors=True)
    os.rename(tmp_dir, export_dir)
    out["peak_rss_gb"] = round(_rss_gb(), 2)
    out["export_dir"] = export_dir
    return out


def _backend_race(n: int) -> dict:
    from arrow_matrix_tpu.decomposition.decompose import arrow_decomposition
    from arrow_matrix_tpu.utils.graphs import barabasi_albert

    a = barabasi_albert(n, 8, seed=7)
    out = {"n": n, "nnz": int(a.nnz)}
    for backend in ("native", "numpy"):
        t0 = time.perf_counter()
        levels = arrow_decomposition(a, arrow_width=WIDTH, max_levels=14,
                                     block_diagonal=True, seed=7,
                                     backend=backend)
        out[backend + "_s"] = round(time.perf_counter() - t0, 1)
        out[backend + "_levels"] = len(levels)
    out["speedup"] = round(out["numpy_s"] / out["native_s"], 2)
    return out


def rung_dryrun_multichip_mid() -> dict:
    """Opt-in mid-scale multichip dry run (VERDICT r4 item 7): n=2^16
    BA-8 at width 512 on an 8-device virtual CPU mesh, fold + sell-a2a,
    each golden-gated, with a trace-time comm account per algorithm
    carrying the graft-stream ``exposed_comm_ms`` model — so MULTICHIP
    artifacts record more than toy-shape evidence."""
    import __graft_entry__ as ge

    return ge.dryrun_multichip(8, scale="mid")


def rung_dryrun_repl_sweep() -> dict:
    """2.5D replication sweep (graft-repl): fold + fixed-B sell-a2a at
    c in {1,2,4} on an 8-device virtual CPU mesh, enforcing the honest
    contract — bit-identical results at every c and measured wire
    bytes exactly 1/c — plus the 8-device c=1 production reference.
    The rung FAILS (non-zero exit) if either invariant breaks; the
    committed record is the evidence PERFORMANCE.md's 2.5D section
    cites."""
    import __graft_entry__ as ge

    return ge.dryrun_multichip(8, scale="repl")


def rung_backend_race22() -> dict:
    return _backend_race(N22)


def rung_backend_race23() -> dict:
    return _backend_race(1 << 23)


RUNGS = {"decompose24": rung_decompose24, "ingest24": rung_ingest24,
         "decompose26_grid": rung_decompose26_grid,
         "decompose_1e8_grid": rung_decompose_1e8_grid,
         "decompose_1e8_ba": rung_decompose_1e8_ba,
         "rehearse_1e8_ba_step": rung_rehearse_1e8_ba_step,
         "dryrun_multichip_mid": rung_dryrun_multichip_mid,
         "dryrun_repl_sweep": rung_dryrun_repl_sweep,
         "backend_race22": rung_backend_race22,
         "backend_race23": rung_backend_race23}

#: What a bare `python tools/scale_ladder.py` runs.  The 1e8 rungs are
#: opt-in by explicit name: the BA 2^27 decompose needs hour-plus wall
#: clock and tens of GB of RSS — a no-arg ladder run must stay bounded.
#: The mid-scale multichip dry run and the 2.5D repl sweep are opt-in
#: too: they are evidence gathering, not part of the bounded default
#: sweep.
DEFAULT_RUNGS = [r for r in RUNGS
                 if r not in ("decompose_1e8_grid", "decompose_1e8_ba",
                              "rehearse_1e8_ba_step",
                              "dryrun_multichip_mid",
                              "dryrun_repl_sweep")]


def main() -> None:
    # Register as preemptible: the tunnel watcher SIGSTOPs registered
    # host jobs (whole process groups) for the duration of on-chip
    # stages — host contention during a TPU bench was the round-3
    # wedge trigger.  One shared registry definition in
    # utils.platform (writer and reader must never drift).
    from arrow_matrix_tpu.utils.platform import register_preemptible

    register_preemptible()
    if len(sys.argv) == 3 and sys.argv[1] == "--rung":
        print(json.dumps(RUNGS[sys.argv[2]]()), flush=True)
        return
    rungs = sys.argv[1:] or list(DEFAULT_RUNGS)
    unknown = [r for r in rungs if r not in RUNGS]
    if unknown:
        raise SystemExit(f"unknown rung(s) {unknown}; "
                         f"valid: {sorted(RUNGS)}")
    os.makedirs(os.path.dirname(OUT), exist_ok=True)
    results = {}
    if os.path.exists(OUT):
        with open(OUT) as f:
            results = json.load(f)
    from arrow_matrix_tpu.utils.platform import host_load

    for rung in rungs:
        print(f"[ladder] {rung} ...", flush=True)
        load_before = host_load()
        t0 = time.perf_counter()
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--rung", rung],
            capture_output=True, text=True)
        wall = round(time.perf_counter() - t0, 1)
        if proc.returncode == 0 and proc.stdout.strip():
            new = json.loads(proc.stdout.strip().splitlines()[-1])
            new["wall_s"] = wall
            # Measurement hygiene (VERDICT item 6): each committed rung
            # records the host contention it ran under, both ends.
            new["host_load"] = {"before": load_before,
                                "after": host_load()}
            if new.get("cached"):
                # A cache hit never becomes the rung's RESULT: either
                # the recorded measured numbers stay (they are the
                # provenance PERFORMANCE.md cites), or — with no clean
                # prior entry — the stub is reported but NOT recorded
                # (delete the artifact to re-measure).
                prior_ok = (rung in results
                            and "error" not in results[rung]
                            and not results[rung].get("cached"))
                print(f"[ladder] {rung}: cached artifact; "
                      f"{'keeping recorded numbers' if prior_ok else 'no recorded numbers — delete ' + str(new.get('base')) + '* to re-measure'}",
                      flush=True)
                continue
            results[rung] = new
            print(f"[ladder] {rung}: {results[rung]}", flush=True)
            # graft-ledger: each measured rung also lands in the
            # append-only store (the committed scale_ladder.json stays
            # the human-facing artifact; the ledger is the queryable
            # history the drift gate bands on).
            try:
                from arrow_matrix_tpu.ledger import (
                    record as _ledger_record,
                )

                load_after = new.get("host_load", {}).get("after", {})
                _ledger_record(
                    "ladder", f"ladder_{rung}_wall_s", wall, unit="s",
                    host_load=load_after.get("loadavg_1m"),
                    knobs={"rung": rung},
                    payload={k: v for k, v in new.items()
                             if not isinstance(v, (dict, list))})
            except Exception as e:
                print(f"[ledger] ladder record not persisted: "
                      f"{type(e).__name__}: {e}", file=sys.stderr)
        else:
            failure = {"error": proc.stderr.strip()[-500:],
                       "wall_s": wall}
            if rung in results and "error" not in results[rung]:
                # A failed RE-run (e.g. resource exhaustion from
                # concurrent host load) must not destroy recorded
                # gate-passing provenance; park it alongside.
                results[rung + "_retry_error"] = failure
                print(f"[ladder] {rung} retry FAILED (recorded "
                      f"numbers kept): {failure['error'][-160:]}",
                      flush=True)
            else:
                results[rung] = failure
                print(f"[ladder] {rung} FAILED: {failure}", flush=True)
        with open(OUT, "w") as f:
            json.dump(results, f, indent=1)
    print(json.dumps(results, indent=1))


if __name__ == "__main__":
    main()
