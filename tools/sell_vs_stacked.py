"""Sell-vs-stacked evidence at 16 and 32 virtual devices (VERDICT r4
item 6): the host-side volume + scaling data behind the layout/routing
default decision, at protocol scale (n=2^20, width 2048, k=16 — the
bench's own problem, reloaded from its decomposition cache).

Per device count (each in its own subprocess — force_cpu_devices is
once-per-process), for each of {stacked, sell} x {gather, a2a}:

  * per-iteration collective bytes + op count from the COMPILED HLO
    (utils/commstats — the deterministic, core-count-independent
    signal);
  * ms/iter from a chained-run race (warm, RTT-subtracted).  On this
    ONE-core host the absolute numbers are not chip predictions — the
    trustworthy part is the ratio structure and how it MOVES from 16
    to 32 devices (per-device compute halves, exchange volume does
    not), which is exactly what the time-vs-space / sell-vs-stacked
    flip needs alongside tools/ici_model.py's parameterized model.

Results: bench_results/sell_vs_stacked.json + a printed table
(PERFORMANCE.md carries the committed copy).

Usage: PYTHONPATH=/root/repo python tools/sell_vs_stacked.py
       [--n 1048576] [--devices 16,32]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

CHILD = r"""
import json, os, sys, time
sys.path.insert(0, {repo!r})
from arrow_matrix_tpu.utils.platform import force_cpu_devices
force_cpu_devices({n_dev})
import numpy as np
from arrow_matrix_tpu.parallel import MultiLevelArrow, make_mesh
from arrow_matrix_tpu.parallel.sell_slim import SellMultiLevel
from arrow_matrix_tpu.utils import commstats
from arrow_matrix_tpu.utils.graphs import random_dense

n, width, k, n_dev = {n}, 2048, 16, {n_dev}
# The bench's own decomposition cache (load-or-decompose-AND-SAVE with
# a completion sentinel): one cold decompose serves every later child.
os.chdir({repo!r})
import bench
levels = bench._cached_levels(n, 8, width, seed=7, max_levels=12)
x_host = random_dense(n, k, seed=3)
mesh = make_mesh((n_dev,), ("blocks",))

def ms_per_iter(obj, x, iters=5):
    def chain(c):
        t0 = time.perf_counter()
        xd = obj.run(x, c) if c else x
        float(np.asarray(xd).ravel()[0])
        return time.perf_counter() - t0
    chain(iters)                       # compile + warm
    rtt = min(chain(0) for _ in range(3))
    return max((chain(iters) - rtt) / iters, 1e-9) * 1e3

out = {{"n_dev": n_dev, "n": n, "width": width, "k": k,
        "levels": len(levels), "modes": {{}}}}
for layout in ("stacked", "sell"):
    for routing in ("gather", "a2a"):
        t0 = time.perf_counter()
        if layout == "stacked":
            obj = MultiLevelArrow(levels, width, mesh=mesh,
                                  routing=routing)
            x = obj.set_features(x_host)
            build_s = round(time.perf_counter() - t0, 1)
            t0 = time.perf_counter()
            stats = commstats.collective_stats(
                obj._step, x, obj.fwd, obj.bwd, obj.blocks)
        else:
            obj = SellMultiLevel(levels, width, mesh, routing=routing)
            x = obj.set_features(x_host)
            build_s = round(time.perf_counter() - t0, 1)
            t0 = time.perf_counter()
            stats = commstats.collective_stats(
                obj._step, x, obj._level_args, obj.fwd, obj.bwd)
        compile_s = round(time.perf_counter() - t0, 1)
        ms = ms_per_iter(obj, x)
        n_ops = sum(v["count"] for v in stats.values()
                    if isinstance(v, dict))
        out["modes"][f"{{layout}}/{{routing}}"] = {{
            "bytes_per_iter": int(stats["total_bytes"]),
            "collective_ops": int(n_ops),
            "ms_per_iter_1core": round(ms, 1),
            "build_s": build_s,
            "compile_s": compile_s,
        }}
        print(f"[{{n_dev}}dev] {{layout}}/{{routing}}: "
              f"{{stats['total_bytes']:,}} B/iter, {{ms:.1f}} ms/iter",
              file=sys.stderr, flush=True)
print(json.dumps(out))
"""


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=1 << 20)
    ap.add_argument("--devices", default="16,32")
    args = ap.parse_args()

    path = os.path.join(REPO, "bench_results", "sell_vs_stacked.json")
    os.makedirs(os.path.dirname(path), exist_ok=True)

    def flush(res):
        # Incremental: a later device-count child failing/timing out
        # must not discard an earlier (possibly hour-long) result.
        with open(path, "w") as f:
            json.dump(res, f, indent=1)

    results = {}
    for n_dev in (int(d) for d in args.devices.split(",")):
        proc = subprocess.run(
            [sys.executable, "-c",
             CHILD.format(repo=REPO, n=args.n, n_dev=n_dev)],
            capture_output=True, text=True, timeout=7200)
        for ln in proc.stderr.strip().splitlines()[-8:]:
            print(ln, flush=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f"{n_dev}-device child failed:\n{proc.stderr[-3000:]}")
        results[f"devs{n_dev}"] = json.loads(
            proc.stdout.strip().splitlines()[-1])
        flush(results)

    # Scaling table: bytes and wall-clock, 16 -> 32 devices.
    print(f"\n{'mode':18s} " + " ".join(
        f"{d.removeprefix('devs') + ':B/iter':>14s} "
        f"{d.removeprefix('devs') + ':ms':>8s}"
        for d in results))
    first = next(iter(results.values()))
    for mode in first["modes"]:
        row = f"{mode:18s} "
        for dkey in results:
            m = results[dkey]["modes"][mode]
            row += f"{m['bytes_per_iter']:>14,} " \
                   f"{m['ms_per_iter_1core']:>8.1f} "
        print(row)
    print(json.dumps({"tool": "sell_vs_stacked",
                      "json": "bench_results/sell_vs_stacked.json"}))


if __name__ == "__main__":
    main()
