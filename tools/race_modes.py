"""Race the time-shared vs space-shared multi-matrix runtimes.

Produces the ms/iter table in README.md ("Time-sharing AND
space-sharing, raced") on an 8-device virtual CPU mesh; run it on real
TPU devices (unset JAX_PLATFORMS) before changing any mode default.

Usage: python tools/race_modes.py [n_vertices]
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from arrow_matrix_tpu.utils.platform import backend_initialized, force_cpu_devices  # noqa: E402

if not backend_initialized() and os.environ.get("AMT_RACE_REAL") != "1":
    force_cpu_devices(8)

import numpy as np  # noqa: E402

import jax  # noqa: E402

from arrow_matrix_tpu.decomposition.decompose import arrow_decomposition  # noqa: E402
from arrow_matrix_tpu.parallel.mesh import make_mesh  # noqa: E402
from arrow_matrix_tpu.parallel.multi_level import MultiLevelArrow  # noqa: E402
from arrow_matrix_tpu.parallel.space_shared import SpaceSharedArrow  # noqa: E402
from arrow_matrix_tpu.utils.graphs import barabasi_albert, random_dense  # noqa: E402


def ms_per_iter(obj, x, iters: int = 10) -> float:
    def chain(n):
        t0 = time.perf_counter()
        xd = obj.run(x, n) if n else x
        float(np.asarray(xd).ravel()[0])
        return time.perf_counter() - t0

    chain(iters)  # compile + warmup
    rtt = min(chain(0) for _ in range(3))
    return max((chain(iters) - rtt) / iters, 1e-9) * 1e3


def main() -> None:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 16384
    k = 16
    n_dev = len(jax.devices())
    a = barabasi_albert(n, 8, seed=7)
    x_host = random_dense(n, k, seed=3)
    print(f"n={n} nnz={a.nnz} k={k}, {n_dev} "
          f"{jax.devices()[0].platform} devices")
    for w, max_lvl in [(512, 2), (512, 4), (1024, 2)]:
        levels = arrow_decomposition(a, arrow_width=w, max_levels=max_lvl,
                                     block_diagonal=True, seed=7)
        k_lvl = len(levels)
        if n_dev % k_lvl:
            print(f"w={w} K={k_lvl}: skip (K does not divide {n_dev})")
            continue
        for fmt in ("ell", "dense"):
            mlm = MultiLevelArrow(levels, w,
                                  mesh=make_mesh((n_dev,), ("blocks",)),
                                  fmt=fmt)
            ss = SpaceSharedArrow(levels, w, fmt=fmt)
            t_ml = ms_per_iter(mlm, mlm.set_features(x_host))
            t_ss = ms_per_iter(ss, ss.set_features(x_host))
            print(f"w={w} K={k_lvl} fmt={fmt}: "
                  f"time-shared {t_ml:8.2f} ms/iter   "
                  f"space-shared {t_ss:8.2f} ms/iter   "
                  f"ratio {t_ml / t_ss:.2f}x")
        # Feature-major orchestration on the same mesh (a2a routing).
        from arrow_matrix_tpu.parallel.sell_slim import SellMultiLevel

        sm = SellMultiLevel(levels, w,
                            make_mesh((n_dev,), ("blocks",)),
                            routing="a2a")
        t_sm = ms_per_iter(sm, sm.set_features(x_host))
        print(f"w={w} K={k_lvl} sell/a2a:    {t_sm:8.2f} ms/iter")
        # Feature-major concurrent groups.
        from arrow_matrix_tpu.parallel.sell_space import SellSpaceShared

        sp = SellSpaceShared(levels, w)
        t_sp = ms_per_iter(sp, sp.set_features(x_host))
        print(f"w={w} K={k_lvl} sell/space:  {t_sp:8.2f} ms/iter")


if __name__ == "__main__":
    main()
