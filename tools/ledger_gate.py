#!/usr/bin/env python
"""Drift gate over the graft-ledger store (thin wrapper).

The engine lives in ``arrow_matrix_tpu/ledger/gate.py``; this wrapper
exists so CI and the Makefile-style workflow can call every gate as
``python tools/<name>_gate.py`` uniformly.  Exits nonzero on a perf
regression (median+MAD band, host-load normalized), an accuracy-curve
regression (error-vs-iteration point above the committed curve's
factor), or schema drift (invalid record, broken hash chain).

Usage:
    python tools/ledger_gate.py [--check] [--rebaseline]
                                [--ledger-dir DIR] [--baseline FILE]
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from arrow_matrix_tpu.ledger.gate import main

if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
