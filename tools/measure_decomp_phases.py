"""Phase attribution for the native decomposer's parallel paths
(VERDICT r4 item 3).

Round 4 declared the Kruskal scan and tree DFS "inherently
sequential"; round 5 parallelized both (filter-Kruskal with a parallel
read-only connectivity filter; level-synchronous linearization
reproducing the DFS emit positions — fast_decomp.cpp) with
bit-identical output for every thread count (pinned by
tests/test_native.py::test_parallel_decomposer_thread_invariance_at_scale).

This host has ONE core, so the tool cannot demonstrate wall-clock
scaling; what it measures and records is the ATTRIBUTION the claim
needs:

- per-phase native seconds at AMT_DECOMP_THREADS=1 vs 4 (the T=4 run
  proves the parallel code paths carry the real workload end-to-end —
  same output, phase labels switch to kruskal-filter /
  linearize-emit-par);
- the share of single-thread native time spent in phases that now
  have a parallel implementation (everything except the Fisher-Yates
  shuffle, which IS the seed contract) — the upper bound Amdahl gives
  a multi-core host;
- the T=4/T=1 per-phase overhead on one core (the price of the
  filter's second connectivity pass and the level-sync bookkeeping
  when no parallelism exists to pay for it).

Reference role match: julia/arrow/GraphAlgorithms.jl:45-80 (Kruskal +
union-find) exists precisely to make 10^8-row decomposition practical.

Usage: PYTHONPATH=/root/repo python tools/measure_decomp_phases.py
       [--logn 22] [--threads 4]
"""

from __future__ import annotations

import argparse
import json
import os
import re
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

CHILD = r"""
import os, sys, time
import numpy as np
sys.path.insert(0, {repo!r})
from arrow_matrix_tpu.utils.graphs import barabasi_albert, symmetrize
from arrow_matrix_tpu.decomposition import native
a = symmetrize(barabasi_albert(1 << {logn}, 4, seed=9))
t0 = time.perf_counter()
o = native.random_forest_order(a, np.random.default_rng(4))
print("WALL", time.perf_counter() - t0)
# Position-weighted digest: a plain sum is identical for EVERY
# permutation; this one changes if any element moves.
w = np.arange(1, o.size + 1, dtype=np.uint64)
print("SUM", int((np.asarray(o, dtype=np.uint64) * w).sum()))
"""

PHASE_RE = re.compile(r"\[decomp-native\] ([a-z\-]+(?:\(|[a-z])*[a-z)]*): "
                      r"([0-9.]+)s")

# Phases with a parallel implementation in fast_decomp.cpp.  The
# shuffle is the one deliberately sequential phase (the Fisher-Yates
# stream defines seed -> forest).
PARALLEL_PHASES = {
    "edge-extract", "edge-extract-masked",
    "kruskal", "kruskal-filter",
    "forest-adjacency",
    "linearize-emit", "linearize-emit-par",
}


def run_one(logn: int, threads: int) -> dict:
    env = {**os.environ,
           "AMT_DECOMP_PROFILE": "1",
           "AMT_DECOMP_THREADS": str(threads)}
    t0 = time.perf_counter()
    proc = subprocess.run(
        [sys.executable, "-c", CHILD.format(repo=REPO, logn=logn)],
        capture_output=True, text=True, timeout=3600, env=env)
    if proc.returncode != 0:
        raise RuntimeError(f"child failed:\n{proc.stderr[-2000:]}")
    phases: dict[str, float] = {}
    for m in PHASE_RE.finditer(proc.stderr):
        phases[m.group(1)] = phases.get(m.group(1), 0.0) + float(m.group(2))
    wall = float(proc.stdout.split("WALL")[1].split()[0])
    out_sum = int(proc.stdout.split("SUM")[1].split()[0])
    return {"threads": threads, "wall_s": round(wall, 3),
            "phases_s": {k: round(v, 3) for k, v in phases.items()},
            "native_s": round(sum(phases.values()), 3),
            "out_checksum": out_sum,
            "total_s": round(time.perf_counter() - t0, 1)}


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--logn", type=int, default=22)
    ap.add_argument("--threads", type=int, default=4)
    args = ap.parse_args()

    from arrow_matrix_tpu.utils.platform import host_load

    load_before = host_load()
    r1 = run_one(args.logn, 1)
    rT = run_one(args.logn, args.threads)
    load_after = host_load()
    assert r1["out_checksum"] == rT["out_checksum"], \
        "thread counts disagree — parity broken"

    par_s = sum(v for k, v in r1["phases_s"].items()
                if k in PARALLEL_PHASES)
    seq_s = r1["native_s"] - par_s
    result = {
        "tool": "measure_decomp_phases",
        "n": 1 << args.logn,
        "host_load": {"before": load_before, "after": load_after},
        "t1": r1, "tN": rT,
        "parallel_share_of_native": round(par_s / max(r1["native_s"], 1e-9),
                                          4),
        "sequential_native_s": round(seq_s, 3),
        "note": ("parallel_share_of_native = fraction of single-thread "
                 "native time in phases with a parallel implementation "
                 "(Amdahl ceiling for a multi-core host); this host has "
                 "1 core, so tN measures code-path overhead, not "
                 "speedup.  Checksum equality re-asserts thread parity."),
    }
    os.makedirs(os.path.join(REPO, "bench_results"), exist_ok=True)
    path = os.path.join(REPO, "bench_results", "decomp_phases.json")
    with open(path, "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps(result, indent=1))


if __name__ == "__main__":
    main()
