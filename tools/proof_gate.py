#!/usr/bin/env python
"""Tier-1 proof gate: re-run graft-prove and fail on any violated
collective contract OR on drift against the checked-in
bench_cache/hlo_manifest.json.

This is the CI wrapper around ``python -m arrow_matrix_tpu.analysis
prove --check`` (the pytest suite runs the same invariant in
tests/test_prove.py): it lowers every contracted executor on a virtual
CPU mesh and checks H1-H6 statically, so a GSPMD surprise all-gather,
a broken ÷c byte contract, a dropped donation alias, or hot-loop
layout thrash fails the push before anything executes.

Usage:
  python tools/proof_gate.py                 prove + drift check (CI)
  python tools/proof_gate.py --refresh       prove + rewrite manifest
  python tools/proof_gate.py --fixture F     run H1-H3 on an HLO
                                             fixture file (exits
                                             nonzero when the fixture
                                             violates the pinned
                                             fixture contract — how
                                             tests demonstrate the
                                             gate trips on a planted
                                             surprise all-gather)
  python tools/proof_gate.py --selftest      verify the gate itself
                                             trips on a broken program
                                             (no jax needed)
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--refresh", action="store_true",
                    help="rewrite bench_cache/hlo_manifest.json instead "
                         "of drift-checking against it")
    ap.add_argument("--fixture", default=None,
                    help="run H1-H3 on this HLO fixture file and exit "
                         "nonzero on any violation")
    ap.add_argument("--selftest", action="store_true",
                    help="verify the checkers trip on a planted "
                         "surprise all-gather (host-only, no jax)")
    args = ap.parse_args(argv)

    from arrow_matrix_tpu.analysis import prove

    if args.selftest:
        ok = prove.selftest()
        print("proof gate selftest:",
              "ok (broken program trips H1-H3)" if ok else "FAILED")
        return 0 if ok else 1

    if args.fixture is not None:
        with open(args.fixture, encoding="utf-8") as fh:
            results = prove.verify_fixture(fh.read())
        for rule in ("H1", "H2", "H3"):
            r = results[rule]
            mark = "ok  " if r["status"] == "pass" else "FAIL"
            print(f"[{mark}] {rule}: {r['detail']}")
        print("fixture conforms" if results["ok"]
              else "proof gate: FIXTURE VIOLATES THE CONTRACT")
        return 0 if results["ok"] else 1

    cli = [] if args.refresh else ["--check"]
    rc = prove.main(cli)
    if rc != 0:
        print("proof gate: FAILED (a collective contract is violated or "
              "the manifest drifted — rerun `python -m "
              "arrow_matrix_tpu.analysis prove` and review the diff)",
              file=sys.stderr)
        return rc
    print("proof gate: ok", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
