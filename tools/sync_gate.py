#!/usr/bin/env python
"""Tier-1 sync gate: re-run graft-sync and fail on any lock-discipline
violation OR on drift against the checked-in
bench_cache/sync_manifest.json.

This is the CI wrapper around ``python -m arrow_matrix_tpu.analysis
sync --check`` (the pytest suite runs the same invariant in
tests/test_sync.py): it reads every ``@guarded_by`` contract off the
AST and proves RC1-RC5 over the serving stack — guarded attributes
mutated only under their lock, an acyclic lock/flock acquisition
graph, no user callback and no blocking call under a lock, and no
unguarded module state reachable from two thread entries — so a
deadlock or lost-update regression fails the push before any thread
runs.

Usage:
  python tools/sync_gate.py                 prove + drift check (CI)
  python tools/sync_gate.py --refresh       prove + rewrite manifest
  python tools/sync_gate.py --fixture F     verify a planted-violation
                                            fixture (tests/fixtures/
                                            sync/rcN_*.py) fires its
                                            expected rule; exits
                                            nonzero when it does NOT —
                                            how tests demonstrate the
                                            gate trips on each planted
                                            discipline break
  python tools/sync_gate.py --fixtures      run every shipped fixture
  python tools/sync_gate.py --paths F...    analyze arbitrary files and
                                            exit nonzero on ANY
                                            finding (feeding a planted
                                            fixture here fails the
                                            gate, per rule)
  python tools/sync_gate.py --selftest      verify the analyzer itself
                                            trips on broken twins and
                                            the runtime witness raises
                                            on an inverted order
"""

import argparse
import glob
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

FIXTURE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tests", "fixtures", "sync")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--refresh", action="store_true",
                    help="rewrite bench_cache/sync_manifest.json "
                         "instead of drift-checking against it")
    ap.add_argument("--fixture", action="append", default=[],
                    help="verify this planted-violation fixture fires "
                         "its expected rule (repeatable)")
    ap.add_argument("--fixtures", action="store_true",
                    help="verify every tests/fixtures/sync/rc*_*.py")
    ap.add_argument("--paths", nargs="+", default=None,
                    help="analyze these files and exit nonzero on any "
                         "finding (a planted fixture fails the gate)")
    ap.add_argument("--selftest", action="store_true",
                    help="verify the analyzer trips on its broken "
                         "twins (host-only, no jax)")
    args = ap.parse_args(argv)

    from arrow_matrix_tpu.analysis import sync as graft_sync

    if args.selftest:
        return graft_sync.main(["--selftest"])

    if args.paths:
        report = graft_sync.analyze_paths(args.paths)
        for f in report.findings:
            print(f.format())
        if report.findings:
            print(f"sync gate: {len(report.findings)} finding(s) in "
                  f"{len(args.paths)} file(s)", file=sys.stderr)
            return 1
        print("sync gate: paths clean", file=sys.stderr)
        return 0

    fixtures = list(args.fixture)
    if args.fixtures:
        fixtures.extend(sorted(glob.glob(
            os.path.join(FIXTURE_DIR, "rc*_*.py"))))
    if fixtures:
        rc = graft_sync.main(
            [arg for p in fixtures for arg in ("--fixture", p)])
        if rc != 0:
            print("sync gate: FIXTURE FAILED TO TRIP ITS RULE — the "
                  "analyzer lost a detection", file=sys.stderr)
        return rc

    cli = [] if args.refresh else ["--check"]
    rc = graft_sync.main(cli)
    if rc != 0:
        print("sync gate: FAILED (a lock-discipline rule is violated "
              "or the manifest drifted — rerun `python -m "
              "arrow_matrix_tpu.analysis sync` and review the diff)",
              file=sys.stderr)
        return rc
    print("sync gate: ok", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
