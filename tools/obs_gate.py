#!/usr/bin/env python
"""Tier-1 obs gate: reduced-scale smoke trace on the CPU mesh.

Counterpart of tools/lint_gate.py for the observability layer: runs
all five parallel algorithms through arrow_matrix_tpu.obs.smoke on a
4-device virtual CPU pool, then validates the run directory (named
spans present per phase, trace JSON well-formed, per-iteration device
time and collective-byte metrics recorded).  Exits 0 on a valid run,
1 otherwise — the unattended pre-push / CI form of the same invariant
amt_doctor's OBS probe checks interactively.

Usage:
  python tools/obs_gate.py [run_dir]
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)

    from arrow_matrix_tpu.utils.platform import force_cpu_devices

    force_cpu_devices(4)

    from arrow_matrix_tpu.obs.smoke import run_smoke, validate_run_dir

    out = argv[0] if argv else tempfile.mkdtemp(prefix="obs_gate_")
    run_smoke(out, n=128, width=32, k=4, n_dev=4, iters=2)
    problems = validate_run_dir(out)
    if problems:
        for p in problems:
            print(f"obs gate: {p}", file=sys.stderr)
        print("obs gate: FAILED", file=sys.stderr)
        return 1
    print(f"obs gate: ok ({out})", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
