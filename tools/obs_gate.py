#!/usr/bin/env python
"""Tier-1 obs gate: reduced-scale smoke trace on the CPU mesh.

Counterpart of tools/lint_gate.py for the observability layer: runs
all five parallel algorithms through arrow_matrix_tpu.obs.smoke on a
4-device virtual CPU pool, then validates the run directory (named
spans present per phase, trace JSON well-formed, per-iteration device
time, collective-byte metrics, and the per-executable HBM memory
report).  On top of the structural validation the gate enforces the
memory contract: every algorithm must carry a memory report, and no
algorithm's measured/predicted HBM ratio may exceed
``OBS_GATE_MAX_HBM_RATIO`` (default 8.0 — the compiled executable
materializing ~an order of magnitude more than the format model
predicts is the OOM-in-waiting memview exists to catch; the smoke
ratios sit in 1.0-2.6x).  Also runs one graft-serve smoke
(serve/loadgen.py:smoke_serve) and requires the serving SLO report to
carry p50/p99 latency, shed/rejected counts, HBM occupancy, and the
per-tenant breakdown — plus a bounded graft-lens per-level profile
validated structurally (every measured tier paired with its static
counters; the calibration bands live in tools/lens_gate.py) and the
graft-pulse surfaces the smoke run
writes: a schema-valid crash-readable pulse ring
(``pulse_ring.json``), parseable Prometheus exposition text
(``pulse_metrics.prom``), the embedded window series using the shared
SLO field vocabulary, and window totals consistent with the final
report (same completed count; pooled window quantiles equal the
report's within the event rounding).  Exits 0 on a valid run, 1
otherwise — the unattended pre-push / CI form of the same invariants
amt_doctor's OBS, SERVE, and PULSE probes check interactively.

Usage:
  python tools/obs_gate.py [run_dir]
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def memory_problems(summary: dict, max_ratio: float) -> list:
    """Gate problems from the smoke summary's memory section: a report
    must exist per algorithm, and measured/predicted must stay under
    ``max_ratio`` wherever the format exposes a predictor."""
    problems = []
    for name, rec in sorted(summary.get("algorithms", {}).items()):
        if rec.get("memory") is None or not rec.get("hbm_measured_bytes"):
            problems.append(f"{name}: memory report absent")
            continue
        ratio = rec.get("hbm_vs_predicted")
        if ratio is not None and ratio > max_ratio:
            problems.append(
                f"{name}: measured/predicted HBM ratio {ratio:.2f} "
                f"exceeds {max_ratio:.2f} "
                f"({rec['hbm_measured_bytes']} vs "
                f"{rec.get('hbm_predicted_bytes')} bytes)")
    return problems


def comm_problems(summary: dict) -> list:
    """Gate problems from the comm section: every algorithm's comm
    report must carry the ``exposed_comm_ms`` field (graft-stream) —
    a comm account without the exposed-time model can't state whether
    the overlap schedule is doing its job.  A replicated run
    (``repl > 1``, graft-repl) must additionally carry its ``repl``
    and ``reduce_bytes`` fields: a 2.5D report that hides the final
    merge's cost (or the factor that bought the exchange cut) is not
    an account."""
    problems = []
    for name, rec in sorted(summary.get("algorithms", {}).items()):
        if rec.get("exposed_comm_ms") is None:
            problems.append(f"{name}: comm report lacks exposed_comm_ms")
        if rec.get("repl", 1) is None or rec.get("repl", 1) > 1:
            if "repl" not in rec or rec.get("repl") is None:
                problems.append(f"{name}: repl>1 run lacks repl field")
            if rec.get("reduce_bytes") is None:
                problems.append(
                    f"{name}: repl>1 comm report lacks reduce_bytes "
                    f"(the 2.5D final-merge cost)")
    return problems


def serve_problems(summary: dict) -> list:
    """Gate problems from a graft-serve SLO report
    (serve/loadgen.py:slo_summary): the serving layer's observability
    contract.  A serve run that cannot state its p50/p99 latency, its
    shed/rejected census, and its HBM occupancy is flying blind —
    admission control and load shedding are exactly the decisions
    these numbers justify."""
    problems = []
    lat = summary.get("latency_ms") or {}
    for q in ("p50", "p99"):
        if lat.get(q) is None:
            problems.append(f"serve: SLO report lacks {q} latency")
    for field in ("shed", "rejected", "completed", "requests_per_s"):
        if summary.get(field) is None:
            problems.append(f"serve: SLO report lacks the {field} "
                            f"field")
    hbm = summary.get("hbm") or {}
    for field in ("budget_bytes", "peak_in_use_bytes",
                  "peak_occupancy"):
        if hbm.get(field) is None:
            problems.append(f"serve: SLO report lacks hbm."
                            f"{field}")
    if not summary.get("per_tenant"):
        problems.append("serve: SLO report lacks the per-tenant "
                        "breakdown")
    # graft-classes: the report must band exact and approx separately
    # — per-class admission/completion counts plus latency quantiles
    # keyed by the class actually served — and carry the loud
    # fallback counter and the certificate registry it admitted
    # against.
    per_class = summary.get("per_class")
    if not per_class:
        problems.append("serve: SLO report lacks the per-class "
                        "breakdown")
    else:
        for cls in ("exact", "approx"):
            rec = per_class.get(cls)
            if rec is None:
                problems.append(f"serve: per_class lacks the {cls} "
                                f"class")
            elif not {"completed", "latency_ms"} <= set(rec):
                problems.append(f"serve: per_class[{cls}] lacks "
                                f"completed/latency_ms")
    if summary.get("class_fallback") is None:
        problems.append("serve: SLO report lacks the class_fallback "
                        "counter")
    if summary.get("certificates") is None:
        problems.append("serve: SLO report lacks the certificates "
                        "section")
    if summary.get("completed", 0) < 1:
        problems.append("serve: smoke serve completed no requests")
    run_dir = summary.get("_run_dir")
    if run_dir and not os.path.isfile(
            os.path.join(run_dir, "serve_summary.json")):
        problems.append("serve: serve_summary.json artifact missing")
    return problems


def pulse_problems(summary: dict) -> list:
    """Gate problems from the graft-pulse surfaces of a smoke serve
    run: the on-disk ring must be crash-readable and schema-valid, the
    exposition text parseable, and the embedded window series must be
    CONSISTENT with the final SLO report — same completed count, and
    pooled window latency quantiles equal to the report's within the
    completed-event rounding (1e-3 ms).  One schema, actually
    enforced."""
    from arrow_matrix_tpu.obs import pulse

    problems = []
    run_dir = summary.get("_run_dir")
    pt = summary.get("pulse")
    if not pt:
        return ["pulse: SLO report lacks the embedded pulse section"]
    if run_dir:
        ring_path = os.path.join(run_dir, "pulse_ring.json")
        if not os.path.isfile(ring_path):
            problems.append("pulse: pulse_ring.json artifact missing")
        else:
            try:
                doc = pulse.load_ring(ring_path)
            except Exception as e:
                problems.append(f"pulse: ring unreadable: {e}")
            else:
                problems += [f"pulse ring: {p}"
                             for p in pulse.validate_ring(doc)]
        prom_path = os.path.join(run_dir, "pulse_metrics.prom")
        if not os.path.isfile(prom_path):
            problems.append("pulse: pulse_metrics.prom artifact "
                            "missing")
        else:
            with open(prom_path, encoding="utf-8") as fh:
                problems += [f"pulse exposition: {p}" for p in
                             pulse.validate_exposition(fh.read())]
    for w in pt.get("windows", ()):
        missing = [f for f in pulse.SLO_SERIES_FIELDS if f not in w]
        if missing:
            problems.append(f"pulse: window {w.get('window')} missing "
                            f"fields {missing}")
            break
    totals = pt.get("totals") or {}
    if "per_class" not in totals:
        problems.append("pulse: window totals lack the per-class "
                        "breakdown (graft-classes)")
    if totals.get("completed") != summary.get("completed"):
        problems.append(
            f"pulse: window totals completed="
            f"{totals.get('completed')} != SLO report completed="
            f"{summary.get('completed')}")
    # Pooled window quantiles vs the report: the windows partition the
    # completed events, so the monitor's run-total histogram must
    # reproduce the report's quantiles up to the event's ms rounding.
    lat_total = (totals.get("latency_ms") or {})
    lat_report = (summary.get("latency_ms") or {})
    for q in ("p50", "p90", "p99"):
        a, b = lat_total.get(q), lat_report.get(q)
        if a is None or b is None:
            if (a is None) != (b is None):
                problems.append(f"pulse: {q} present in only one of "
                                f"series/report")
            continue
        if abs(a - b) > 1e-2:
            problems.append(f"pulse: pooled series {q}={a:.4f}ms "
                            f"diverges from report {q}={b:.4f}ms")
    return problems


def ledger_problems(smoke_summary: dict, serve_summary: dict) -> list:
    """Gate problems from the graft-ledger wiring of a smoke run: both
    the obs smoke summary and the serve SLO report must carry the id
    of the ledger record their run appended, and the record must
    actually exist (valid, chained) in the run-dir-local store — a
    measured number that never reached the ledger is exactly the
    unaccounted drift the ledger exists to end."""
    from arrow_matrix_tpu.ledger import Ledger

    problems = []
    for label, summary in (("smoke", smoke_summary),
                           ("serve", serve_summary)):
        rec_id = summary.get("ledger_record_id")
        if not rec_id:
            problems.append(f"ledger: {label} summary carries no "
                            f"ledger_record_id")
            continue
        run_dir = summary.get("_run_dir")
        if not run_dir:
            continue
        lg = Ledger(os.path.join(run_dir, "ledger"))
        recs = {r.get("record_id") for r in lg.read_all()}
        if rec_id not in recs:
            problems.append(f"ledger: {label} record {rec_id} absent "
                            f"from {lg.path}")
        problems += [f"ledger ({label}): {p}" for p in lg.validate()]
    return problems


def xray_problems(trace_doc: dict, tickets: list, wire=None,
                  registry=None) -> list:
    """Gate problems from a merged graft-xray fleet trace.

    Two invariants, both correctness properties of the tracer rather
    than style checks:

    * **Closed span trees.**  Every COMPLETED request must appear as a
      router-track ``dispatch`` span AND at least one worker-track
      span carrying the same request id and the router-minted
      ``trace_id`` — and the worker spans must land inside the
      dispatch interval (0.25 s slack for clock-offset residue).  A
      request the fleet says it served but the trace cannot follow
      across the wire is a broken context propagation, the exact bug
      this gate exists to catch.

    * **Byte conservation.**  The router's per-frame wire ledger must
      sum EXACTLY to its totals (a dropped frame record is silent
      undercounting), and — when a fresh process-local registry is
      passed — the bytes the client side sent must equal the bytes the
      server side received, and vice versa: the wire may not create or
      destroy bytes between the two measurement points.
    """
    problems = []
    xr = trace_doc.get("xray") or {}
    procs = {p["process"]: p["pid"] for p in xr.get("processes", [])}
    if "router" not in procs:
        problems.append("xray: merged trace lacks a router track")
    if len(procs) < 2:
        problems.append("xray: merged trace has no worker tracks")
    if problems:
        return problems
    router_pid = procs["router"]
    events = [e for e in trace_doc.get("traceEvents", [])
              if e.get("ph") == "X"]
    slack_us = 0.25e6
    for t in tickets:
        if t.get("status") != "completed":
            continue
        rid = t["request_id"]
        mine = [e for e in events if rid in
                str(e.get("args", {}).get("request_id", "")).split("+")]
        disp = [e for e in mine
                if e["pid"] == router_pid and e["name"] == "dispatch"]
        remote = [e for e in mine if e["pid"] != router_pid]
        if not disp:
            problems.append(f"xray: {rid}: no router dispatch span")
            continue
        if not remote:
            problems.append(f"xray: {rid}: no worker-side spans — "
                            f"span tree not closed across the wire")
            continue
        want = t.get("trace_id")
        if want and not any(
                want in str(e["args"].get("trace_id", "")).split("+")
                for e in remote):
            problems.append(f"xray: {rid}: worker spans lack the "
                            f"router-minted trace_id {want}")
        d0 = min(e["ts"] for e in disp)
        d1 = max(e["ts"] + e["dur"] for e in disp)
        stray = [e["name"] for e in remote
                 if e["ts"] < d0 - slack_us
                 or e["ts"] + e["dur"] > d1 + slack_us]
        if stray:
            problems.append(f"xray: {rid}: worker spans {stray} fall "
                            f"outside the dispatch interval")
    if wire:
        totals = wire.get("totals") or {}
        frames = wire.get("frames") or []
        out_sum = sum(int(f.get("bytes_out") or 0) for f in frames)
        in_sum = sum(int(f.get("bytes_in") or 0) for f in frames)
        if (out_sum != totals.get("bytes_out")
                or in_sum != totals.get("bytes_in")):
            problems.append(
                f"xray: wire ledger does not conserve bytes: frame "
                f"sums {out_sum}/{in_sum} (out/in) vs totals "
                f"{totals.get('bytes_out')}/{totals.get('bytes_in')}")
        if totals.get("frames") != 2 * len(frames):
            problems.append(
                f"xray: wire ledger frame count "
                f"{totals.get('frames')} != 2 x {len(frames)} "
                f"round trips")
    if registry is not None:
        sums: dict = {}
        for rec in registry.snapshot()["histograms"]:
            if rec["name"] != "wire_frame_bytes":
                continue
            lab = rec.get("labels") or {}
            s = rec.get("summary") or {}
            key = (lab.get("role"), lab.get("dir"))
            sums[key] = sums.get(key, 0) + int(round(
                s.get("mean", 0.0) * s.get("count", 0)))
        for a, b in ((("client", "send"), ("server", "recv")),
                     (("server", "send"), ("client", "recv"))):
            if sums.get(a, 0) != sums.get(b, 0):
                problems.append(
                    f"xray: bytes not conserved across the socket: "
                    f"{'/'.join(a)}={sums.get(a, 0)} != "
                    f"{'/'.join(b)}={sums.get(b, 0)}")
    return problems


def lens_problems(profile: dict) -> list:
    """Gate problems from a graft-lens profile document: structural
    validation of the per-level attribution contract.  Every measured
    tier must ride with its full static counter row (nnz / rows /
    streamed bytes — the pairing IS the point of graft-lens), the
    family label must match the profiled kernel, and the coverage
    bookkeeping must be finite and self-consistent.  The calibration
    BANDS (coverage tolerance, ratio range) are enforced against the
    committed artifact by tools/lens_gate.py — at this gate's reduced
    smoke scale per-tier times sit at the measurement floor, so only
    the structure is load-bearing here."""
    from arrow_matrix_tpu.obs import lens

    problems = []
    if profile.get("schema") != lens.LENS_PROFILE_SCHEMA:
        return [f"lens: profile schema {profile.get('schema')} != "
                f"{lens.LENS_PROFILE_SCHEMA}"]
    kernel = profile.get("kernel")
    if not profile.get("structure_hash"):
        problems.append("lens: profile lacks structure_hash")
    if not profile.get("dtypes"):
        problems.append("lens: profile has no dtype entries")
    for fd, entry in (profile.get("dtypes") or {}).items():
        full = entry.get("full_ms")
        if not isinstance(full, (int, float)) or not full > 0:
            problems.append(f"lens: {fd}: non-positive full_ms "
                            f"{full}")
        measured = 0
        for t in entry.get("tiers", ()):
            if not t.get("measured_ms"):
                continue
            measured += 1
            for field in ("nnz", "rows", "streamed_bytes", "slots",
                          "slot_width"):
                v = t.get(field)
                if not isinstance(v, (int, float)) or v < 0:
                    problems.append(
                        f"lens: {fd} tier {t.get('tier')}: measured "
                        f"tier lacks static counter {field}")
            fam = str(t.get("family", ""))
            if kernel and not fam.startswith(f"{kernel}:"):
                problems.append(
                    f"lens: {fd} tier {t.get('tier')}: family {fam!r}"
                    f" does not match profiled kernel {kernel!r}")
        if not measured:
            problems.append(f"lens: {fd}: no measured tiers")
        att = entry.get("attributed_ms")
        cov = entry.get("coverage")
        if (isinstance(att, (int, float))
                and isinstance(cov, (int, float))
                and isinstance(full, (int, float)) and full > 0
                and abs(att / full - cov) > 1e-6):
            problems.append(f"lens: {fd}: coverage {cov} inconsistent "
                            f"with attributed/full {att / full}")
    return problems


def run_lens_profile() -> list:
    """Bounded in-process graft-lens profile (small BA structure, XLA
    kernel) validated structurally — the obs-smoke form of the lens
    contract."""
    from arrow_matrix_tpu.obs import lens
    from arrow_matrix_tpu.tune.search import load_levels_from_source

    levels, width = load_levels_from_source(
        {"kind": "ba", "n": 96, "m": 3, "width": 16, "seed": 5,
         "max_levels": 10})
    profile = lens.profile_fold(levels, width, 8, kernel="xla",
                                feature_dtypes=("f32",), iters=20)
    return lens_problems(profile)


def run_xray_fleet(out: str) -> list:
    """In-process 2-worker fleet exercising the full graft-xray loop
    (trace context over the wire, per-process docs, clock-offset
    handshake, merge, conservation) and returning its gate problems."""
    import threading

    from arrow_matrix_tpu.fleet.health import HealthMonitor
    from arrow_matrix_tpu.fleet.router import FleetRouter, WorkerHandle
    from arrow_matrix_tpu.fleet.worker import FleetWorker, serve_worker
    from arrow_matrix_tpu.obs import metrics as metrics_mod
    from arrow_matrix_tpu.obs import xray
    from arrow_matrix_tpu.serve.loadgen import synthetic_trace

    # Fresh registry: the byte-symmetry check must see exactly this
    # fleet's frames, not the smoke runs' leftovers.
    metrics_mod.set_registry(metrics_mod.MetricsRegistry())
    xray_dir = os.path.join(out, "xray")
    workers, handles = [], []
    for wid in ("w0", "w1"):
        worker = FleetWorker(wid, vertices=96, width=16, seed=5,
                             obs_dir=os.path.join(xray_dir, wid))
        ready = threading.Event()
        box: dict = {}

        def announce(port, box=box, ready=ready):
            box["port"] = port
            ready.set()

        threading.Thread(target=serve_worker, args=(worker,),
                         kwargs={"port": 0, "announce": announce},
                         daemon=True).start()
        if not ready.wait(120):
            return [f"xray: worker {wid} never bound"]
        workers.append(worker)
        handles.append(WorkerHandle(wid, "127.0.0.1", box["port"]))
    router = FleetRouter(
        handles=handles,
        health=HealthMonitor(timeout_s=5.0, max_failures=3))
    try:
        trace = synthetic_trace(router.n_rows, tenants=2, requests=4,
                                k=2, iterations=2, seed=11)
        tickets = [router.submit(r) for r in trace]
        router.drain(timeout_s=180)
        report = router.fleet_summary()
        xray.save_router_trace(router.tracer, xray_dir)
    finally:
        router.shutdown()
        for w in workers:
            try:
                w.close()
            except Exception:
                pass
    bad = [t.request.request_id for t in tickets
           if t.status != "completed"]
    if bad:
        return [f"xray: fleet requests not completed: {bad}"]
    trace_doc = xray.merge_run_dir(xray_dir, report=report)
    xray.save_fleet_trace(trace_doc, xray_dir)
    tick = [{"request_id": t.request.request_id, "status": t.status,
             "trace_id": (t.trace or {}).get("trace_id")}
            for t in tickets]
    problems = xray_problems(trace_doc, tick,
                             wire=report.get("wire"),
                             registry=metrics_mod.get_registry())
    offs = report.get("clock_offsets_ns") or {}
    for wid in ("w0", "w1"):
        rec = offs.get(wid)
        if not isinstance(rec, dict):
            problems.append(f"xray: no clock offset measured for "
                            f"{wid}")
        elif abs(rec.get("offset_ns", 0)) > 1e9:
            problems.append(f"xray: implausible same-host clock "
                            f"offset for {wid}: {rec}")
    return problems


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)

    from arrow_matrix_tpu.utils.platform import force_cpu_devices

    force_cpu_devices(4)

    from arrow_matrix_tpu.obs.smoke import run_smoke, validate_run_dir
    from arrow_matrix_tpu.serve import smoke_serve

    out = argv[0] if argv else tempfile.mkdtemp(prefix="obs_gate_")
    summary = run_smoke(out, n=128, width=32, k=4, n_dev=4, iters=2)
    summary["_run_dir"] = out
    problems = validate_run_dir(out)
    max_ratio = float(os.environ.get("OBS_GATE_MAX_HBM_RATIO", "8.0"))
    problems += memory_problems(summary, max_ratio)
    problems += comm_problems(summary)
    serve_dir = os.path.join(out, "serve")
    s = smoke_serve(serve_dir)
    s["_run_dir"] = serve_dir
    problems += serve_problems(s)
    problems += pulse_problems(s)
    problems += ledger_problems(summary, s)
    problems += run_xray_fleet(out)
    problems += run_lens_profile()
    if problems:
        for p in problems:
            print(f"obs gate: {p}", file=sys.stderr)
        print("obs gate: FAILED", file=sys.stderr)
        return 1
    print(f"obs gate: ok ({out})", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
