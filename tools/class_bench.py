"""graft-classes round-7 bench: f32 vs bf16 vs int8 carriage at scale.

The class benchmark behind BENCH_r07.json: one Barabasi-Albert
operator at the r06 scale point (n=2^20, width=2048), decomposed and
folded once per carriage dtype — f32 (the exact class), bf16 and int8
(the approx classes) — timing iter_ms and measuring each class's final
relative-Frobenius drift against the f32 run.  A second, trace-time
section accounts the a2a exchange bytes of the mesh executor
(``SellMultiLevel`` over forced host devices) at f32 vs bf16 on a
committed bench_cache structure: the measured byte-reduction number of
the graft-classes PR (the issue's acceptance bar is >= 1.8x at the
same (structure, k, c)).  The lowered HLO module is the byte source —
it is dtype-honest, where the CPU backend's compiled module legalizes
bf16 collectives back to f32 (obs/comm docstring).

Appends ONE ``kind="bench"`` ledger record whose parsed payload keeps
the r02–r06 vocabulary (metric / value / unit / vs_baseline / config /
platform / device_kind) and adds the per-class sections;
``BENCH_r07.json`` is then ``graft_ledger export --round 7``, never
hand-written.

Usage: python tools/class_bench.py [--n 1048576] [--width 2048] ...
Prints ONE JSON line (the parsed payload) as its last stdout line.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from arrow_matrix_tpu.utils.platform import force_cpu_devices  # noqa: E402

#: Carriage dtypes benched, in class order (None = f32 exact).
CLASS_DTYPES = (("f32", None), ("bf16", "bf16"), ("int8", "int8"))


def _carriage_bytes(x) -> int:
    """On-device bytes of one carried feature state — a single array
    for f32/bf16, the (q, scale) pair for int8."""
    if isinstance(x, tuple):
        return sum(int(part.size) * part.dtype.itemsize for part in x)
    return int(x.size) * x.dtype.itemsize


def bench_fold_classes(levels, width: int, *, k: int, iterations: int,
                       seed: int) -> dict:
    """iter_ms + final drift per carriage dtype on the fold executor
    (single chip — the serving path)."""
    import jax
    import numpy as np

    from arrow_matrix_tpu.parallel import MultiLevelArrow

    rng = np.random.default_rng(seed)
    out: dict = {}
    golden = None
    x0_host = None
    for name, fd in CLASS_DTYPES:
        t0 = time.perf_counter()
        multi = MultiLevelArrow(levels, width, mesh=None, fmt="fold",
                                feature_dtype=fd)
        build_s = time.perf_counter() - t0
        if x0_host is None:   # every dtype iterates the same input
            x0_host = rng.standard_normal(
                (multi.n, k)).astype(np.float32)
        x = multi.set_features(x0_host)
        x = jax.block_until_ready(multi.step(x))   # compile + warmup
        t0 = time.perf_counter()
        for _ in range(iterations):
            x = multi.step(x)
        jax.block_until_ready(x)
        iter_ms = (time.perf_counter() - t0) / iterations * 1e3
        got = multi.gather_result(x)
        if golden is None:
            golden = got.astype(np.float64)
            rel = 0.0
        else:
            d = got.astype(np.float64) - golden
            rel = float(np.linalg.norm(d) / np.linalg.norm(golden))
        out[name] = {
            "iter_ms": round(iter_ms, 3),
            "build_s": round(build_s, 2),
            "carriage_bytes": _carriage_bytes(x),
            "rel_frobenius_vs_f32": rel,
        }
        del multi, x, got
    return out


def bench_exchange_bytes(base: str, *, k: int, n_dev: int,
                         exchange_width=None) -> dict:
    """Trace-time a2a exchange bytes of the mesh executor at f32 vs
    bf16 over one committed structure — same (structure, k, c), only
    the carriage dtype moves."""
    import numpy as np

    from arrow_matrix_tpu.obs.comm import (
        account_collectives,
        ideal_bytes_for,
    )
    from arrow_matrix_tpu.parallel.mesh import make_mesh
    from arrow_matrix_tpu.parallel.sell_slim import SellMultiLevel
    from arrow_matrix_tpu.tune.search import load_levels_from_source

    source = {"kind": "dir", "base": base}
    if exchange_width:
        source["width"] = int(exchange_width)
    levels, width = load_levels_from_source(source)
    mesh = make_mesh((n_dev,), ("blocks",))
    rng = np.random.default_rng(0)
    x_host = None

    out: dict = {"source": source, "k": k, "n_dev": n_dev, "repl": 1}
    for name, fd in (("f32", None), ("bf16", "bf16")):
        sm = SellMultiLevel(levels, width, mesh, routing="a2a",
                            feature_dtype=fd)
        if x_host is None:
            x_host = rng.standard_normal((sm.n, k)).astype(np.float32)
        xt = sm.set_features(x_host)
        itemsize = 2 if fd == "bf16" else 4
        rep = account_collectives(
            f"sell_a2a_{name}", sm.step_fn, xt, *sm.step_operands(),
            ideal_bytes=ideal_bytes_for(sm, k, itemsize=itemsize),
            mode="lowered", overlap_slabs=sm.overlap_slabs,
            repl=sm.repl)
        out[name] = {
            "measured_bytes": rep["measured_bytes"],
            "ideal_bytes": rep["ideal_bytes"],
            "ratio_vs_ideal": rep["ratio"],
            "source": rep["source"],
        }
        del sm, xt
    f32_b = out["f32"]["measured_bytes"]
    bf16_b = out["bf16"]["measured_bytes"]
    out["byte_reduction_f32_over_bf16"] = (
        round(f32_b / bf16_b, 4) if bf16_b else None)
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--n", type=int, default=1 << 20)
    ap.add_argument("--ba_m", type=int, default=8)
    ap.add_argument("--width", type=int, default=2048)
    ap.add_argument("--k", type=int, default=16)
    ap.add_argument("--iterations", type=int, default=10)
    ap.add_argument("--max_levels", type=int, default=10)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--scipy_iters", type=int, default=2,
                    help="iterations of the scipy per-iter baseline")
    ap.add_argument("--exchange_base", default=os.path.join(
        REPO, "bench_cache", "ba_16384_8_w512_s7_L12"),
        help="committed graphio artifact base for the a2a byte "
             "accounting")
    ap.add_argument("--exchange_width", type=int, default=512)
    ap.add_argument("--exchange_k", type=int, default=16)
    ap.add_argument("--devices", type=int, default=4)
    ap.add_argument("--ledger-dir", default=None)
    ap.add_argument("--no-ledger", action="store_true")
    args = ap.parse_args(argv)

    # Virtual host devices for the mesh section; must precede any
    # backend initialization.
    force_cpu_devices(args.devices)
    import jax
    import numpy as np

    from arrow_matrix_tpu.decomposition import arrow_decomposition
    from arrow_matrix_tpu.utils import barabasi_albert

    t0 = time.perf_counter()
    a = barabasi_albert(args.n, args.ba_m, seed=args.seed)
    gen_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    levels = arrow_decomposition(a, args.width,
                                 max_levels=args.max_levels,
                                 block_diagonal=True, seed=args.seed)
    decompose_s = time.perf_counter() - t0
    print(f"[class_bench] graph {gen_s:.1f}s decompose "
          f"{decompose_s:.1f}s levels={len(levels)} "
          f"nnz={a.nnz}", flush=True)

    # scipy per-iteration baseline (the r02-r06 vs_baseline anchor).
    acsr = a.tocsr()
    x = np.random.default_rng(args.seed).standard_normal(
        (args.n, args.k)).astype(np.float32)
    t0 = time.perf_counter()
    y = x
    for _ in range(args.scipy_iters):
        y = acsr @ y
    scipy_ms = (time.perf_counter() - t0) / args.scipy_iters * 1e3
    del y
    print(f"[class_bench] scipy {scipy_ms:.1f} ms/iter", flush=True)

    classes = bench_fold_classes(levels, args.width, k=args.k,
                                 iterations=args.iterations,
                                 seed=args.seed)
    for name, rec in classes.items():
        print(f"[class_bench] {name}: {rec['iter_ms']} ms/iter "
              f"carriage={rec['carriage_bytes']} rel_frob="
              f"{rec['rel_frobenius_vs_f32']:.3e}", flush=True)
    del a, acsr, levels, x

    exchange = bench_exchange_bytes(args.exchange_base,
                                    k=args.exchange_k,
                                    n_dev=args.devices,
                                    exchange_width=args.exchange_width)
    print(f"[class_bench] exchange f32/bf16 = "
          f"{exchange['byte_reduction_f32_over_bf16']}x", flush=True)

    value = classes["f32"]["iter_ms"]
    dev = jax.devices()[0]
    parsed = {
        "metric": "spmm_iter_ms",
        "value": value,
        "unit": "ms",
        "vs_baseline": round(scipy_ms / value, 3) if value else None,
        "scipy_cpu_ms": round(scipy_ms, 3),
        "platform": jax.default_backend(),
        "device_kind": "host" if jax.default_backend() == "cpu"
        else getattr(dev, "device_kind", dev.platform),
        "config": {
            "n": args.n, "ba_neighbors": args.ba_m,
            "width": args.width, "features": args.k,
            "iterations": args.iterations, "levels": args.max_levels,
            "fmts": ["fold"], "seed": args.seed,
            "decompose_s": round(decompose_s, 2),
            "build_s": classes["f32"]["build_s"],
        },
        # graft-classes: the round's reason to exist — one fold
        # timing + drift row per carriage class, and the mesh a2a
        # byte accounting at f32 vs bf16.
        "classes": classes,
        "exchange_bytes": exchange,
        # Host-backend round: no on-chip capture attempted (the class
        # comparison is dtype-relative, not an absolute-speed claim).
        "degraded": True,
        "backend_probe_class": "not-attempted",
    }

    if not args.no_ledger:
        from arrow_matrix_tpu.ledger import store

        rec = store.record(
            "bench",
            store.bench_metric(parsed["metric"], parsed["config"]),
            parsed["value"], directory=args.ledger_dir,
            unit=parsed["unit"], platform=parsed["platform"],
            device_kind=parsed["device_kind"],
            knobs={"config": parsed["config"],
                   "classes": sorted(classes)},
            payload={"parsed": parsed,
                     "cmd": "python tools/class_bench.py",
                     "rc": 0})
        if rec is not None:
            print(f"[class_bench] ledger {rec['record_id']}",
                  flush=True)
        # graft-xray satellite: one banded record PER CARRIAGE CLASS
        # (metric carries the class suffix, e.g.
        # ``spmm_iter_ms_n65536_w2048_bf16``), so the drift gate bands
        # each class's iter_ms separately — a class that gets
        # byte-cheaper but time-slower fails loudly instead of hiding
        # behind the f32 headline number.
        base_metric = store.bench_metric(parsed["metric"],
                                         parsed["config"])
        for cls_name in sorted(classes):
            cls_rec = classes[cls_name]
            crec = store.record(
                "bench", f"{base_metric}_{cls_name}",
                cls_rec["iter_ms"], directory=args.ledger_dir,
                unit="ms", platform=parsed["platform"],
                device_kind=parsed["device_kind"],
                knobs={"traffic_class": cls_name,
                       "config": parsed["config"]},
                payload={"parsed": {
                    "metric": f"{parsed['metric']}_{cls_name}",
                    "class": cls_name,
                    "carriage_bytes": cls_rec["carriage_bytes"],
                    "rel_frobenius_vs_f32":
                        cls_rec["rel_frobenius_vs_f32"],
                    "degraded": parsed["degraded"],
                }})
            if crec is not None:
                print(f"[class_bench] ledger {crec['record_id']} "
                      f"({cls_name})", flush=True)

    print(json.dumps(parsed, sort_keys=True))
    return 0


if __name__ == "__main__":
    sys.exit(main())
