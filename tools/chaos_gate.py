#!/usr/bin/env python
"""Tier-1 chaos gate: the graft-heal fault-injection scenario matrix.

Counterpart of tools/obs_gate.py for the recovery layer: builds a
small Barabasi-Albert decomposition artifact on a 4-device virtual CPU
mesh, computes the fault-free final X of a supervised iterated-SpMM
run, then replays the run under every scenario of the injection
matrix and asserts each fault is **detected** (supervisor fault event /
loud integrity error), **recovered** (retry, rollback, restore, or
checkpoint resume), and that the recovered run's final X is
**bit-identical** to the fault-free run:

  nan      — seeded NaN burst poisons the carried X at an executor
             step hook; the supervisor's jitted finite-check catches
             it and rolls back to the last checkpoint.
  hang     — an injected sleep outlasts the per-iteration watchdog;
             the stalled attempt drains during the grace join and the
             iteration is retried.
  corrupt  — real bytes of the on-disk npy triplet are overwritten;
             the sha256 sidecar manifest fails the load loudly naming
             the offending file; restoring the artifact recovers.
  kill     — (subprocess; skipped under ``--fast``) a SIGKILL lands
             mid-iteration in a checkpointing spmm_arrow run; a rerun
             resumes from the last checkpoint and finishes with the
             same final state as a never-killed run.
  kill_repl— (subprocess; skipped under ``--fast``) the same SIGKILL
             under 2.5D replication (--fmt sell --repl 2): the saved
             checkpoint must be the canonical merged carriage (the
             Supervisor ``canonicalize`` hook), so the resumed run is
             still bit-identical to the never-killed replicated run.
  sync     — graft-sync selftest twins trip + the static RC1-RC5
             lock-discipline proof holds over the shipped package.
  kcert    — graft-kcert selftest twins trip + both shipped Pallas
             kernels certify under KC1-KC5 (including the
             interpret-mode numeric witness).
  lens     — graft-lens cost model fit/predict/serialize round trip
             is exact on synthetic points, and a planted out-of-band
             calibration ratio record trips the ledger gate's lens
             band.
  host_kill— graft-host kill-a-host rung (fast list, bounded): a
             4-worker fleet split into two host fault domains loses
             ALL of host-1 to one simultaneous SIGKILL mid-batch;
             the router must bury exactly that domain, requeue its
             in-flight work onto host-0, and lose zero accepted
             requests.

Plus the graft-serve chaos-under-load matrix (tools/serve_gate.py):
serve_hang / serve_corrupt / serve_overflow / serve_hbm in-process
(and serve_kill in full mode) against a live multi-tenant
ArrowServer — mid-request faults detected and recovered (or cleanly,
explicitly shed), surviving requests bit-identical to a fault-free
replay, the server never restarted externally.

And the graft-fleet matrix (tools/fleet_gate.py, full mode only):
fleet_baseline + fleet_kill — SIGKILL one worker process of N=3
mid-batch; the router must bury exactly the victim, requeue its
accepted-but-unfinished requests onto survivors (checkpoint-resumed,
not recomputed), lose zero accepted requests, and report EXACT pooled
fleet quantiles.  xray_kill then inspects the merged graft-xray trace
that run left behind: the victim's partial spans must be recovered
from its eagerly-flushed flight ring with explicit ``truncated``
markers, still correlated to the router track by shared request ids.

Exits 0 when every scenario passes, 1 otherwise.  Determinism is the
whole contract: recovery re-runs the same compiled step from the same
state on CPU, so equality is exact (``tobytes()``), not approximate.

Usage:
  python tools/chaos_gate.py [--fast] [workdir]
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

ITERS = 6
N, WIDTH, K = 256, 32, 4
SEED = 11


def _build(workdir):
    """Artifact + executor + initial state shared by the in-process
    scenarios."""
    from arrow_matrix_tpu.decomposition import arrow_decomposition
    from arrow_matrix_tpu.io import (
        as_levels,
        load_decomposition,
        load_level_widths,
        save_decomposition,
    )
    from arrow_matrix_tpu.io.graphio import num_rows
    from arrow_matrix_tpu.parallel import MultiLevelArrow, make_mesh
    from arrow_matrix_tpu.utils import barabasi_albert, random_dense

    a = barabasi_albert(N, 3, seed=SEED)
    levels = arrow_decomposition(a, WIDTH, max_levels=10,
                                 block_diagonal=True, seed=SEED)
    base = os.path.join(workdir, "ba")
    save_decomposition(levels, base)
    width0 = levels[0].arrow_width
    loaded = load_decomposition(base, width0)   # manifest-verified
    widths = load_level_widths(base, width0)
    lv = as_levels(loaded, widths if widths is not None else width0)
    ml = MultiLevelArrow(lv, width0, mesh=make_mesh((4,), ("blocks",)),
                         fmt="ell")
    x0 = ml.set_features(random_dense(num_rows(lv[0].matrix), K, seed=7))
    return ml, x0, base, width0


def _final_bytes(x):
    import numpy as np

    return np.asarray(x).tobytes()


def _run(ml, x0, ck, **sup_kw):
    from arrow_matrix_tpu.faults import Supervisor

    sup = Supervisor("chaos", carry=True, checkpoint_path=ck,
                     checkpoint_every=2, verbose=False, **sup_kw)
    y, ok = sup.run(lambda x, it: ml.step(x), x0, 0, ITERS)
    return y, ok, sup


def scenario_nan(ml, x0, ref, workdir):
    from arrow_matrix_tpu import faults

    faults.set_plan({"scenario": "nan", "site": "multi_level.step",
                     "after": 3, "seed": 5})
    try:
        y, ok, sup = _run(ml, x0, os.path.join(workdir, "ck_nan"))
    finally:
        faults.clear_plan()
    problems = []
    if not ok:
        problems.append("nan: supervised run did not complete")
    if sup.faults_seen == 0:
        problems.append("nan: NaN burst was not detected")
    if sup.recoveries == 0:
        problems.append("nan: no recovery was taken")
    if ok and _final_bytes(y) != ref:
        problems.append("nan: recovered final X is not bit-identical "
                        "to the fault-free run")
    return problems


def scenario_hang(ml, x0, ref, workdir):
    from arrow_matrix_tpu import faults

    faults.set_plan({"scenario": "hang", "site": "multi_level.step",
                     "after": 2, "hang_s": 1.2})
    try:
        y, ok, sup = _run(ml, x0, os.path.join(workdir, "ck_hang"),
                          watchdog_s=0.3, watchdog_grace_s=60.0)
    finally:
        faults.clear_plan()
    problems = []
    if not ok:
        problems.append("hang: supervised run did not complete")
    if sup.faults_seen == 0:
        problems.append("hang: watchdog did not fire on the injected "
                        "stall")
    if sup.recoveries == 0:
        problems.append("hang: no recovery was taken")
    if ok and _final_bytes(y) != ref:
        problems.append("hang: recovered final X is not bit-identical "
                        "to the fault-free run")
    return problems


def scenario_corrupt(x0, ref, base, width0, workdir):
    from arrow_matrix_tpu.io import as_levels, load_decomposition
    from arrow_matrix_tpu.io import load_level_widths
    from arrow_matrix_tpu.io.graphio import (
        ArtifactIntegrityError,
        FileKind,
        format_path,
    )
    from arrow_matrix_tpu.parallel import MultiLevelArrow, make_mesh

    problems = []
    victim = format_path(base, width0, 0, True, FileKind.data)
    pristine = open(victim, "rb").read()
    with open(victim, "r+b") as fh:   # flip real bytes mid-file
        fh.seek(max(0, len(pristine) // 2))
        fh.write(b"\xff\x00\xff\x00\xff\x00\xff\x00")
    try:
        load_decomposition(base, width0)
        problems.append("corrupt: corrupted artifact loaded without "
                        "an integrity error")
    except ArtifactIntegrityError as e:
        if os.path.basename(victim) not in str(e):
            problems.append(f"corrupt: integrity error does not name "
                            f"the offending file: {e}")
    # Recovery: restore the artifact, reload (verified), rebuild, rerun.
    with open(victim, "wb") as fh:
        fh.write(pristine)
    loaded = load_decomposition(base, width0)
    widths = load_level_widths(base, width0)
    lv = as_levels(loaded, widths if widths is not None else width0)
    ml2 = MultiLevelArrow(lv, width0,
                          mesh=make_mesh((4,), ("blocks",)), fmt="ell")
    y, ok, _ = _run(ml2, x0, os.path.join(workdir, "ck_corrupt"))
    if not ok:
        problems.append("corrupt: post-restore run did not complete")
    elif _final_bytes(y) != ref:
        problems.append("corrupt: post-restore final X is not "
                        "bit-identical to the fault-free run")
    return problems


def scenario_kill(workdir):
    from arrow_matrix_tpu.utils.checkpoint import load_state

    problems = []
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("AMT_FAULT_PLAN", None)
    ck_ok = os.path.join(workdir, "ck_ref")
    ck_kill = os.path.join(workdir, "ck_kill")
    cmd = [sys.executable, "-m", "arrow_matrix_tpu.cli.spmm_arrow",
           "--vertices", str(N), "--width", str(WIDTH),
           "--features", str(K), "--device", "cpu", "--carry", "true",
           "--seed", str(SEED), "--iterations", str(ITERS),
           "--checkpoint_every", "2",
           "--logdir", os.path.join(workdir, "logs")]

    def run(extra, fault_env=None):
        e = dict(env)
        if fault_env:
            e["AMT_FAULT_PLAN"] = fault_env
        return subprocess.run(cmd + extra, env=e, cwd=workdir,
                              capture_output=True, text=True,
                              timeout=600)

    r = run(["--checkpoint", ck_ok])
    if r.returncode != 0:
        return [f"kill: fault-free reference run failed rc="
                f"{r.returncode}: {r.stderr[-500:]}"]
    # Warmup step is hit 0, so hit 5 is iteration 4 — after the step-2
    # and step-4 checkpoints exist.
    plan = json.dumps({"scenario": "kill", "site": "*.step",
                       "after": 5})
    r = run(["--checkpoint", ck_kill], fault_env=plan)
    if r.returncode == 0:
        return ["kill: injected SIGKILL did not terminate the run"]
    mid = load_state(ck_kill)
    if mid is None:
        return ["kill: no checkpoint survived the SIGKILL"]
    if mid[1] != 4:
        problems.append(f"kill: expected the step-4 checkpoint to "
                        f"survive, found step {mid[1]}")
    r = run(["--checkpoint", ck_kill])
    if r.returncode != 0:
        return problems + [f"kill: resume run failed rc={r.returncode}"
                           f": {r.stderr[-500:]}"]
    if "resumed" not in r.stdout:
        problems.append("kill: rerun did not report resuming from the "
                        "checkpoint")
    a = load_state(ck_ok)
    b = load_state(ck_kill)
    if a is None or b is None:
        return problems + ["kill: final checkpoints missing"]
    if a[1] != ITERS or b[1] != ITERS:
        problems.append(f"kill: final steps {a[1]}/{b[1]} != {ITERS}")
    if _final_bytes(a[0]) != _final_bytes(b[0]):
        problems.append("kill: resumed run's final X is not "
                        "bit-identical to the never-killed run")
    return problems


def scenario_kill_repl(workdir):
    """scenario_kill under 2.5D replication (``--repl 2`` on the
    4-device gate, k=4 so each replica group owns a 2-feature slab).
    Exercises the graft-repl checkpoint contract: the Supervisor's
    ``canonicalize`` hook must merge the per-replica-group partial
    carriage before saving, or the resumed run diverges."""
    from arrow_matrix_tpu.utils.checkpoint import load_state

    problems = []
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("AMT_FAULT_PLAN", None)
    ck_ok = os.path.join(workdir, "ck_ref_repl")
    ck_kill = os.path.join(workdir, "ck_kill_repl")
    cmd = [sys.executable, "-m", "arrow_matrix_tpu.cli.spmm_arrow",
           "--vertices", str(N), "--width", str(WIDTH),
           "--features", str(K), "--device", "cpu", "--carry", "true",
           "--seed", str(SEED), "--iterations", str(ITERS),
           "--checkpoint_every", "2", "--fmt", "sell", "--repl", "2",
           "--logdir", os.path.join(workdir, "logs_repl")]

    def run(extra, fault_env=None):
        e = dict(env)
        if fault_env:
            e["AMT_FAULT_PLAN"] = fault_env
        return subprocess.run(cmd + extra, env=e, cwd=workdir,
                              capture_output=True, text=True,
                              timeout=600)

    r = run(["--checkpoint", ck_ok])
    if r.returncode != 0:
        return [f"kill_repl: fault-free reference run failed rc="
                f"{r.returncode}: {r.stderr[-500:]}"]
    plan = json.dumps({"scenario": "kill", "site": "*.step",
                       "after": 5})
    r = run(["--checkpoint", ck_kill], fault_env=plan)
    if r.returncode == 0:
        return ["kill_repl: injected SIGKILL did not terminate the run"]
    mid = load_state(ck_kill)
    if mid is None:
        return ["kill_repl: no checkpoint survived the SIGKILL"]
    if mid[1] != 4:
        problems.append(f"kill_repl: expected the step-4 checkpoint to "
                        f"survive, found step {mid[1]}")
    r = run(["--checkpoint", ck_kill])
    if r.returncode != 0:
        return problems + [f"kill_repl: resume run failed rc="
                           f"{r.returncode}: {r.stderr[-500:]}"]
    if "resumed" not in r.stdout:
        problems.append("kill_repl: rerun did not report resuming from "
                        "the checkpoint")
    a = load_state(ck_ok)
    b = load_state(ck_kill)
    if a is None or b is None:
        return problems + ["kill_repl: final checkpoints missing"]
    if a[1] != ITERS or b[1] != ITERS:
        problems.append(f"kill_repl: final steps {a[1]}/{b[1]} != "
                        f"{ITERS}")
    if _final_bytes(a[0]) != _final_bytes(b[0]):
        problems.append("kill_repl: resumed replicated run's final X "
                        "is not bit-identical to the never-killed run")
    return problems


def scenario_sync():
    """graft-sync: the static RC1-RC5 proof must hold over the shipped
    package (no drift check here — tools/sync_gate.py owns that) and
    the analyzer's own broken twins must still trip."""
    from arrow_matrix_tpu.analysis import sync as graft_sync

    problems = []
    ok, lines = graft_sync.selftest()
    if not ok:
        problems += [f"sync: {ln}" for ln in lines]
    report = graft_sync.analyze_package()
    for f in report.findings:
        problems.append(f"sync: {f.format()}")
    return problems


def scenario_kcert():
    """graft-kcert: the KC1-KC5 certifier's broken twins must still
    trip (host-only selftest) and both shipped Pallas kernels must
    certify — grid/BlockSpec/budget proof plus the interpret-mode
    numeric witness (no drift check here — tools/kernel_gate.py owns
    that)."""
    from arrow_matrix_tpu.analysis import kernels as graft_kcert

    problems = []
    ok, lines = graft_kcert.selftest()
    if not ok:
        problems += [f"kcert: {ln}" for ln in lines]
    for rec in graft_kcert.certify_all():
        for f in rec["findings"]:
            problems.append(f"kcert: {f}")
    return problems


def scenario_lens(workdir):
    """graft-lens: the compute cost model must survive a host-side
    round trip — a fit over synthetic per-family points reproduces
    them, the model serializes and deserializes losslessly — and a
    planted out-of-band calibration record MUST trip the ledger
    gate's lens band (the detection the drift gate grew in PR 18)."""
    from arrow_matrix_tpu.ledger import gate as ledger_gate
    from arrow_matrix_tpu.ledger.store import Ledger
    from arrow_matrix_tpu.obs.costmodel import (
        CostModel,
        fit_cost_model,
    )

    problems = []
    pts = [
        {"family": "xla:tail", "nnz": 1000, "rows": 200,
         "streamed_bytes": 400000, "measured_ms": 0.05},
        {"family": "xla:tail", "nnz": 2000, "rows": 400,
         "streamed_bytes": 800000, "measured_ms": 0.10},
        {"family": "xla:mid", "nnz": 1500, "rows": 100,
         "streamed_bytes": 600000, "measured_ms": 0.06},
        {"family": "xla:mid", "nnz": 3000, "rows": 200,
         "streamed_bytes": 1200000, "measured_ms": 0.12},
    ]
    model = fit_cost_model(pts, structure_hash="chaos",
                           platform="cpu")
    for p in pts:
        pred = model.predict_point(p["family"], p["nnz"], p["rows"],
                                   p["streamed_bytes"])
        if pred <= 0 or not 0.5 <= p["measured_ms"] / pred <= 2.0:
            problems.append(
                f"lens: fit does not reproduce its own points: "
                f"{p['family']} measured {p['measured_ms']} vs "
                f"predicted {pred}")
    rt = CostModel.from_dict(model.to_dict())
    if rt.to_dict() != model.to_dict():
        problems.append("lens: CostModel round trip is lossy")
    lg = Ledger(os.path.join(workdir, "lens_ledger"))
    rec = lg.record("lens", "lens_ratio_chaos", 3.0, unit="ratio",
                    structure_hash="chaos", host_load=None)
    failures, _ = ledger_gate.check_records([rec], {"metrics": {}})
    if not any("lens miscalibration" in f for f in failures):
        problems.append(
            "lens: planted out-of-band ratio record (3.0) did NOT "
            "trip the ledger gate's lens band")
    return problems


def scenario_synth(workdir):
    """graft-synth: the structure-JIT schedule synthesizer must be
    deterministic over a hand-built 4-tier ladder, its output must
    certify under KC1-KC5, a planted-bad schedule (ring 0) must be
    pruned with a kcert: reason, and a persisted generated program
    must survive the store round trip — register cleanly, certify
    cleanly — while a corrupted record must trip the certifier."""
    from arrow_matrix_tpu.analysis import kernels as graft_kcert
    from arrow_matrix_tpu.ops.kernel_contract import unregister_kernel
    from arrow_matrix_tpu.tune import synth

    problems = []
    fp = {
        "n": 96, "binary": True, "total_rows": 120,
        "ladder": {
            "rows": [24, 64, 24, 8],
            "nnz": [0, 180, 300, 400],
            "slots": [0, 256, 384, 512],
            "slot_width": [0, 4, 16, 80],
        },
    }
    s1 = synth.synthesize_schedule(fp)
    s2 = synth.synthesize_schedule(fp)
    if s1 != s2:
        problems.append("synth: synthesize_schedule is not "
                        "deterministic over the same fingerprint")
    fams = [e["family"] for e in s1]
    if fams != ["tail", "mid", "head"]:
        problems.append(f"synth: 4-tier ladder (zero/tail/mid/head) "
                        f"synthesized families {fams}, expected "
                        f"['tail', 'mid', 'head']")
    why = graft_kcert.certify_candidate_opts({"schedule": s1}, 16,
                                             interpret=True)
    if why is not None:
        problems.append(f"synth: freshly synthesized schedule did "
                        f"not certify: {why}")
    bad = [dict(s1[0], ring=0)]
    why = graft_kcert.certify_candidate_opts({"schedule": bad}, 16,
                                             interpret=True)
    if why is None or not why.startswith("kcert:"):
        problems.append(f"synth: planted ring=0 schedule was NOT "
                        f"pruned with a kcert: reason (got {why!r})")
    store = os.path.join(workdir, "synth_store.json")
    name = synth.persist_program(fp, "chaos" + "0" * 11, 16, s1,
                                 path=store)
    try:
        names = synth.register_persisted_programs(store)
        if name not in names:
            problems.append(f"synth: persisted program {name} did "
                            f"not come back from the store "
                            f"(got {names})")
        progs = synth.load_store(store)["programs"]
        rec = graft_kcert.certify_entry(
            synth.entry_from_program(name, progs[name]))
        if rec["findings"]:
            problems.append(f"synth: persisted program does not "
                            f"certify: {rec['findings']}")
        corrupt = dict(progs[name])
        corrupt["schedule"] = [dict(e, ring=0)
                               for e in corrupt["schedule"]]
        rec = graft_kcert.certify_entry(
            synth.entry_from_program(name + "_corrupt", corrupt))
        if not rec["findings"]:
            problems.append("synth: corrupted store record (ring 0) "
                            "did NOT trip the certifier")
    finally:
        unregister_kernel(name)
    return problems


def scenario_host_kill(workdir):
    """graft-host kill-a-host rung (fast list): a bounded 2-domain
    fleet — 4 spawned workers split into host-0/host-1 — loses ALL of
    host-1 to one simultaneous SIGKILL mid-batch.  The router must
    bury exactly that domain (deaths probed to a verdict through the
    real heartbeat ladder), requeue its accepted-but-unfinished
    requests onto host-0, and lose zero accepted requests.  Bounded
    enough for the fast list: tiny operator, 8 requests; the
    full-size CLI twin with bit-identity + resume-log + shm-ledger
    checks is tools/fleet_gate.py:scenario_fleet_host_kill."""
    import time as time_mod

    import numpy as np

    from arrow_matrix_tpu.fleet.router import FleetRouter
    from arrow_matrix_tpu.serve import request as rq
    from arrow_matrix_tpu.serve.loadgen import synthetic_trace

    problems = []
    router = FleetRouter(
        spawn=4, hosts=2, vertices=96, width=16, seed=SEED,
        fmt="fold",
        checkpoint_dir=os.path.join(workdir, "host_kill_ckpt"),
        name="hostchaos")
    try:
        hm = router.host_map()
        if sorted(hm) != ["host-0", "host-1"] \
                or hm["host-1"] != ["worker-2", "worker-3"]:
            return [f"host_kill: 4 workers did not split into two "
                    f"contiguous domains: {hm}"]
        trace = synthetic_trace(router.n_rows, tenants=5, requests=8,
                                k=2, iterations=4, seed=5)
        tickets = [router.submit(r) for r in trace]
        # Mid-batch: let the fleet prove it accepted work, then take
        # the whole domain down at once and probe the victims to a
        # verdict (the same wire ladder a dispatch failure walks).
        deadline = time_mod.monotonic() + 120
        while time_mod.monotonic() < deadline:
            if any(t.status in rq.TERMINAL for t in tickets):
                break
            time_mod.sleep(0.02)
        victims = router.kill_host("host-1")
        for wid in victims:
            router._on_worker_failure(wid, "host-1 killed (chaos)")
        router.drain(timeout_s=240)
        summ = router.fleet_summary()
        if sorted(summ["dead_workers"]) != sorted(victims):
            problems.append(
                f"host_kill: buried {summ['dead_workers']} != the "
                f"whole killed domain {sorted(victims)} (and only "
                f"it)")
        if summ.get("live_hosts") != ["host-0"]:
            problems.append(f"host_kill: live hosts "
                            f"{summ.get('live_hosts')} != ['host-0']")
        lost = [t.request.request_id for t in tickets
                if t.status not in rq.TERMINAL]
        if lost:
            problems.append(f"host_kill: LOST requests {lost}")
        if summ["failed"]:
            problems.append(f"host_kill: {summ['failed']} request(s) "
                            f"failed instead of requeueing")
        if summ["completed"] + summ["shed"] + summ["rejected"] \
                != len(tickets):
            problems.append(
                f"host_kill: zero-loss violated — {summ['completed']}"
                f" completed + {summ['shed'] + summ['rejected']} "
                f"explicitly shed != {len(tickets)} accepted")
        if summ["requeues"] < 1:
            problems.append("host_kill: the domain died with no "
                            "request requeued — the kill landed "
                            "outside the in-flight window")
        # Deterministic completions even across the requeue: every
        # completed result is finite and the right shape (the full
        # bit-identity bar lives in the fleet gate's CLI twin).
        for t in tickets:
            if t.status == rq.COMPLETED:
                if t.result is None \
                        or not np.all(np.isfinite(t.result)):
                    problems.append(f"host_kill: completed request "
                                    f"{t.request.request_id} carries "
                                    f"a bad result")
    finally:
        router.shutdown()
    return problems


def scenario_xray_kill(workdir):
    """graft-xray under SIGKILL: the fleet_kill scenario's merged
    trace must still carry the victim's track — rebuilt from the
    flight ring the dead worker flushed eagerly per event — with an
    EXPLICIT ``truncated`` marker on the track and on every recovered
    span, and at least one request id shared with the router track
    (the kill must not sever the fleet-level correlation)."""
    path = os.path.join(workdir, "fleet_kill", "fleet_xray.json")
    try:
        with open(path, encoding="utf-8") as fh:
            trace = json.load(fh)
    except (OSError, ValueError) as e:
        return [f"xray_kill: merged fleet trace unreadable: {e}"]
    problems = []
    xr = trace.get("xray") or {}
    procs = {p["process"]: p for p in xr.get("processes", [])}
    victim = procs.get("worker-1")
    if victim is None:
        return ["xray_kill: the SIGKILLed worker-1 has no track in "
                "the merged trace (flight-ring recovery failed)"]
    if ("worker-1" not in (xr.get("truncated") or [])
            or not victim.get("truncated")):
        problems.append("xray_kill: worker-1's recovered track is "
                        "not marked truncated")
    events = [e for e in trace.get("traceEvents", [])
              if e.get("ph") == "X"]
    vic = [e for e in events if e.get("pid") == victim.get("pid")]
    if not vic:
        return problems + ["xray_kill: no spans recovered from "
                           "worker-1's flight ring"]
    untagged = sorted({e["name"] for e in vic
                       if not (e.get("args") or {}).get("truncated")})
    if untagged:
        problems.append(f"xray_kill: recovered spans lack the "
                        f"explicit truncated marker: {untagged}")

    def _rids(evs):
        return {m for e in evs
                for m in str((e.get("args") or {})
                             .get("request_id", "")).split("+") if m}

    vic_rids = _rids(vic)
    if not vic_rids:
        problems.append("xray_kill: no recovered victim span carries "
                        "a request id")
    router_pid = procs.get("router", {}).get("pid")
    shared = vic_rids & _rids(
        [e for e in events if e.get("pid") == router_pid])
    if vic_rids and not shared:
        problems.append("xray_kill: no request id shared between the "
                        "router track and the victim's recovered "
                        "track")
    return problems


def run_gate(workdir, fast=False):
    """Run the matrix; returns (problems, scenarios_run)."""
    from arrow_matrix_tpu import faults
    from arrow_matrix_tpu.obs import flight

    rec = flight.FlightRecorder(os.path.join(workdir, "flight.json"))
    flight.set_recorder(rec)
    faults.clear_plan()   # a stray AMT_FAULT_PLAN must not skew the gate
    try:
        ml, x0, base, width0 = _build(workdir)
        y_ref, ok, _ = _run(ml, x0, None)
        if not ok:
            return ["baseline: fault-free supervised run failed"], []
        ref = _final_bytes(y_ref)
        problems = []
        scenarios = ["nan", "hang", "corrupt"]
        problems += scenario_nan(ml, x0, ref, workdir)
        problems += scenario_hang(ml, x0, ref, workdir)
        problems += scenario_corrupt(x0, ref, base, width0, workdir)
        if not fast:
            scenarios.append("kill")
            problems += scenario_kill(workdir)
            scenarios.append("kill_repl")
            problems += scenario_kill_repl(workdir)
        # graft-sync rides the fast list: the static lock-discipline
        # proof is host-only AST work, and the serving scenarios below
        # all run under the runtime lock-order witness when
        # AMT_LOCK_WITNESS=1 is exported around this gate.
        scenarios.append("sync")
        problems += scenario_sync()
        # graft-kcert rides the fast list too: the certifier is
        # host-side meta/AST work and the witness is a small
        # interpret-mode round trip per kernel.
        scenarios.append("kcert")
        problems += scenario_kcert()
        # graft-lens rides the fast list: the cost-model round trip
        # is pure numpy and the planted-record check is a host-side
        # ledger-gate call.
        scenarios.append("lens")
        problems += scenario_lens(workdir)
        # graft-synth rides the fast list: schedule synthesis and
        # KC1-KC5 certification are host-side meta work, and the store
        # round trip is a couple of small JSON writes plus one
        # interpret-mode witness.
        scenarios.append("synth")
        problems += scenario_synth(workdir)
        # graft-host rides the fast list: the kill-a-host rung on a
        # BOUNDED 2-domain fleet (tiny operator, 8 requests) — losing
        # a whole fault domain at once must never lose an accepted
        # request, fast mode or not.
        scenarios.append("host_kill")
        problems += scenario_host_kill(workdir)
        # The serving matrix rides the same gate (tools/serve_gate.py):
        # chaos under multi-tenant load with the same detected/
        # recovered/bit-identical contract.
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        import serve_gate

        serve_problems, serve_scenarios = serve_gate.run_serve_scenarios(
            workdir, fast=fast)
        problems += serve_problems
        scenarios += serve_scenarios
        # And the fleet matrix (tools/fleet_gate.py): kill one worker
        # process of N and require zero accepted-request loss with
        # bit-identical surviving results.
        import fleet_gate

        fleet_problems, fleet_scenarios = fleet_gate.run_fleet_scenarios(
            workdir, fast=fast)
        problems += fleet_problems
        scenarios += fleet_scenarios
        # graft-xray piggybacks on the fleet_kill run: the SIGKILLed
        # worker's partial trace must be recovered (truncated, loudly)
        # in the merged fleet_xray.json that run left behind.
        if "fleet_kill" in fleet_scenarios:
            scenarios.append("xray_kill")
            problems += scenario_xray_kill(workdir)
        # And the reshard matrix (tools/reshard_gate.py): H7 bounded-
        # scratch staging plus SIGKILL mid staged-migration with zero
        # accepted-request loss and bit-identical resumed results.
        import reshard_gate

        reshard_problems, reshard_scenarios = \
            reshard_gate.run_reshard_scenarios(workdir, fast=fast)
        problems += reshard_problems
        scenarios += reshard_scenarios
        kinds = {e.get("kind") for e in rec.events}
        if "fault" not in kinds or "heal" not in kinds:
            problems.append(f"flight recorder saw kinds {sorted(kinds)}"
                            f" — fault and heal events are required")
        if "serve" not in kinds:
            problems.append(f"flight recorder saw kinds {sorted(kinds)}"
                            f" — serve events are required")
        return problems, scenarios
    finally:
        rec.seal("chaos gate done")
        flight.set_recorder(None)


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    fast = "--fast" in argv
    argv = [a for a in argv if a != "--fast"]

    from arrow_matrix_tpu.utils.platform import force_cpu_devices

    force_cpu_devices(4)

    import tempfile

    workdir = argv[0] if argv else tempfile.mkdtemp(prefix="chaos_gate_")
    os.makedirs(workdir, exist_ok=True)
    problems, scenarios = run_gate(workdir, fast=fast)
    if problems:
        for p in problems:
            print(f"chaos gate: {p}", file=sys.stderr)
        print("chaos gate: FAILED", file=sys.stderr)
        return 1
    print(f"chaos gate: ok — scenarios {'+'.join(scenarios)} detected, "
          f"recovered, bit-identical ({workdir})", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
