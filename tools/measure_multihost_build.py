"""Measure the per-host build: per-process CPU seconds (and wall) of
SellMultiLevel construction, single-process vs 2-process at the same
global device count, on one machine.

The per-host build constructs/fills/validates only the shards a
process's devices own (PERFORMANCE.md "Per-host builds"): the
nnz-proportional work halves per process; the O(total rows) metadata
every process must agree on does not.  CPU time is the honest
single-box metric — two processes share the cores, so wall conflates
them.

Usage: python tools/measure_multihost_build.py [n] [width] [n_dev]
"""

import json
import os
import socket
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

CHILD = r'''
import json, os, resource, sys, time
pid, nproc, port, n, width, n_dev = (int(sys.argv[1]), int(sys.argv[2]),
                                     sys.argv[3], int(sys.argv[4]),
                                     int(sys.argv[5]), int(sys.argv[6]))
sys.path.insert(0, {repo!r})
from arrow_matrix_tpu.parallel.mesh import initialize_multihost
from arrow_matrix_tpu.utils.platform import force_cpu_devices

per = n_dev // nproc
if nproc > 1:
    initialize_multihost(f"127.0.0.1:{{port}}", nproc, pid,
                         cpu_devices=per)
else:
    force_cpu_devices(n_dev)

import numpy as np
from arrow_matrix_tpu.decomposition import arrow_decomposition
from arrow_matrix_tpu.parallel import make_mesh
from arrow_matrix_tpu.parallel.sell_slim import SellMultiLevel
from arrow_matrix_tpu.utils.graphs import barabasi_albert

a = barabasi_albert(n, 8, seed=7)
levels = arrow_decomposition(a, width, max_levels=12,
                             block_diagonal=True, seed=7)
ru0 = resource.getrusage(resource.RUSAGE_SELF)
t0 = time.perf_counter()
ml = SellMultiLevel(levels, width, make_mesh((n_dev,), ("blocks",)),
                    routing="a2a")
build_s = time.perf_counter() - t0
ru1 = resource.getrusage(resource.RUSAGE_SELF)
# CPU seconds THIS PROCESS spent building — the per-host cost the
# build scales down (wall time on one shared box conflates the two
# processes; on separate hosts wall tracks cpu).
cpu_s = (ru1.ru_utime - ru0.ru_utime) + (ru1.ru_stime - ru0.ru_stime)
print("RESULT " + json.dumps({{
    "pid": pid, "nproc": nproc, "levels": len(levels),
    "build_wall_s": round(build_s, 2),
    "build_cpu_s": round(cpu_s, 2)}}), flush=True)
'''


def run(nproc: int, n: int, width: int, n_dev: int) -> list[dict]:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    code = CHILD.format(repo=REPO)
    procs = [subprocess.Popen(
        [sys.executable, "-c", code, str(i), str(nproc), str(port),
         str(n), str(width), str(n_dev)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        for i in range(nproc)]
    out = []
    try:
        # Drain every child concurrently: the processes advance in
        # lockstep through gloo collectives, so serially draining one
        # while the other fills its PIPE would stall both.
        import concurrent.futures as cf

        with cf.ThreadPoolExecutor(len(procs)) as ex:
            results = list(ex.map(
                lambda p: p.communicate(timeout=1800), procs))
        for p, (so, se) in zip(procs, results):
            if p.returncode != 0:
                raise RuntimeError(
                    f"child rc={p.returncode}: {se[-800:]}")
            line = [ln for ln in so.splitlines()
                    if ln.startswith("RESULT ")]
            out.append(json.loads(line[-1][len("RESULT "):]))
    finally:
        for p in procs:   # a crashed child must not orphan its peer
            if p.poll() is None:
                p.kill()
    return out


def main() -> None:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 200_000
    width = int(sys.argv[2]) if len(sys.argv) > 2 else 1024
    n_dev = int(sys.argv[3]) if len(sys.argv) > 3 else 4
    if n_dev % 2 != 0:
        raise SystemExit(f"n_dev={n_dev} must be even (the 2-process "
                         f"run pins n_dev/2 devices per process)")
    print(f"n={n} width={width} global devices={n_dev}")
    one = run(1, n, width, n_dev)
    print(f"1 process : build cpu {one[0]['build_cpu_s']}s  "
          f"wall {one[0]['build_wall_s']}s  "
          f"({one[0]['levels']} levels)")
    two = run(2, n, width, n_dev)
    for r in sorted(two, key=lambda r: r["pid"]):
        print(f"2 processes (proc {r['pid']}): build cpu "
              f"{r['build_cpu_s']}s  wall {r['build_wall_s']}s")


if __name__ == "__main__":
    main()
