"""Per-iteration communication-volume report on the virtual CPU mesh.

Communication volume is the reference paper's headline metric
(reference README.md:3); here the collectives are compiler-inserted, so
the report reads them back out of the compiled HLO (utils/commstats)
for every execution mode the framework offers, next to the O(moved
rows) analytic lower bound:

  * time-shared, routing="gather"  (GSPMD lowers the permutation
    gathers itself — may all-gather whole feature arrays)
  * time-shared, routing="a2a"     (explicit precomputed send/recv
    tables over one fixed-shape all_to_all per exchange — the
    reference's Alltoallv tables, arrow_dec_mpi.py:210-281)
  * space-shared (stacked)         (composed-gather + cross-group
    reduce, parallel/space_shared.py)
  * sell/gather, sell/a2a          (feature-major time-shared
    orchestration, parallel/sell_slim.py)
  * sell/space-shared              (feature-major concurrent groups,
    parallel/sell_space.py: within-level composed tables + one
    cross-group reduce)

Usage: python tools/comm_report.py [n] [width] [k] [n_dev]
"""

import sys

n = int(sys.argv[1]) if len(sys.argv) > 1 else 4096
width = int(sys.argv[2]) if len(sys.argv) > 2 else 128
k = int(sys.argv[3]) if len(sys.argv) > 3 else 16
n_dev = int(sys.argv[4]) if len(sys.argv) > 4 else 8

from arrow_matrix_tpu.utils.platform import force_cpu_devices  # noqa: E402

force_cpu_devices(n_dev)

import numpy as np  # noqa: E402

from arrow_matrix_tpu.decomposition import arrow_decomposition  # noqa: E402
from arrow_matrix_tpu.parallel import (  # noqa: E402
    MultiLevelArrow,
    make_mesh,
)
from arrow_matrix_tpu.parallel.multi_level import (  # noqa: E402
    pad_permutation,
)
from arrow_matrix_tpu.parallel.space_shared import (  # noqa: E402
    SpaceSharedArrow,
)
from arrow_matrix_tpu.utils import commstats  # noqa: E402
from arrow_matrix_tpu.utils.graphs import (  # noqa: E402
    barabasi_albert,
    random_dense,
)


def main() -> None:
    a = barabasi_albert(n, 4, seed=7)
    levels = arrow_decomposition(a, arrow_width=width, max_levels=4,
                                 block_diagonal=True, seed=7)
    x_host = random_dense(n, k, seed=1)
    print(f"n={n} width={width} k={k} devices={n_dev} "
          f"levels={len(levels)}\n")

    reports = {}
    mesh = make_mesh((n_dev,), ("blocks",))
    for routing in ("gather", "a2a"):
        ml = MultiLevelArrow(levels, width, mesh=mesh, routing=routing)
        x = ml.set_features(x_host)
        reports[f"time-shared/{routing}"] = (
            commstats.collective_stats(ml._step, x, ml.fwd, ml.bwd,
                                       ml.blocks),
            ml,
        )

    if n_dev % len(levels) == 0:
        ss = SpaceSharedArrow(levels, width)
        xs = ss.set_features(x_host)
        reports["space-shared"] = (
            commstats.collective_stats(ss._step, xs, ss.bwd0, ss.fwd0,
                                       ss.blocks),
            ss,
        )

    # Feature-major orchestration (sell): shard_map per level (psum X0
    # bcast + psum head reduce + reach-derived halo ppermutes) with
    # GSPMD-lowered inter-level reordering gathers.
    from arrow_matrix_tpu.parallel.sell_slim import SellMultiLevel

    for routing in ("gather", "a2a"):
        sm = SellMultiLevel(levels, width, mesh, routing=routing)
        xm = sm.set_features(x_host)
        reports[f"sell/{routing}"] = (
            commstats.collective_stats(sm._step, xm, sm._level_args,
                                       sm.fwd, sm.bwd),
            sm,
        )

    # bf16 feature carriage: every collective moves feature rows, so
    # halving the feature bytes halves the per-iteration volume.
    # Accounted on the LOWERED module (the CPU backend upcasts bf16
    # collectives to f32 in compiled HLO; TPUs run them natively), so
    # the f32 twin (the loop's a2a instance) is re-accounted the same
    # way for a fair pair.
    reports["sell/a2a/f32 (lowered)"] = (
        commstats.lowered_collective_stats(sm._step, xm, sm._level_args,
                                           sm.fwd, sm.bwd),
        sm,
    )
    sm16 = SellMultiLevel(levels, width, mesh, routing="a2a",
                          feature_dtype="bf16")
    xm16 = sm16.set_features(x_host)
    reports["sell/a2a/featbf16 (lowered)"] = (
        commstats.lowered_collective_stats(
            sm16._step, xm16, sm16._level_args, sm16.fwd, sm16.bwd),
        sm16,
    )

    if n_dev % len(levels) == 0:
        from arrow_matrix_tpu.parallel.sell_space import SellSpaceShared

        sp = SellSpaceShared(levels, width)
        xp = sp.set_features(x_host)
        reports["sell/space-shared"] = (
            commstats.collective_stats(sp._step, xp, *sp._args()),
            sp,
        )

    some_ml = next(iter(reports.values()))[1]
    perms = [pad_permutation(np.asarray(l.permutation), some_ml.total_rows)
             for l in levels]
    ideal = commstats.ideal_routing_bytes(perms, n_dev, k)
    for name, (stats, _) in reports.items():
        print(f"== {name}")
        print(commstats.format_stats(stats))
        print(f"{'ideal routing':20s} {'':6s} {ideal:14,d}\n")


if __name__ == "__main__":
    main()
