#!/usr/bin/env python
"""Tier-1 lint gate: run graft-lint over the package and fail loudly.

The pytest suite already gates on a clean lint
(tests/test_analysis.py::test_shipped_package_lints_clean); this script
is the same invariant as a standalone pre-push / CI step, matching the
other tools/*.py entry points the watcher runs unattended.  It prints
the findings (if any) and exits with graft-lint's status: 0 clean,
1 findings.  ``--audit`` additionally runs the trace-time recompile
audit and refreshes bench_cache/compile_manifest.json; ``--prove``
additionally runs the HLO collective-contract prover in check mode
(fails on any violated contract or drift against the checked-in
bench_cache/hlo_manifest.json — tools/proof_gate.py standalone);
``--ledger`` additionally runs the graft-ledger drift gate in check
mode against the committed store + baseline (tools/ledger_gate.py
standalone); ``--sync`` additionally runs the graft-sync
lock-discipline proof in check mode (fails on any RC1-RC5 violation
or drift against the checked-in bench_cache/sync_manifest.json —
tools/sync_gate.py standalone); ``--kernels`` additionally runs the
graft-kcert Pallas kernel certifier in check mode (fails on any
KC1-KC5 violation or drift against the checked-in
bench_cache/kernel_manifest.json — tools/kernel_gate.py standalone);
``--lens`` additionally runs the graft-lens calibration gate in check
mode against the committed bench_results/lens profile + cost model
(attribution coverage and measured/predicted ratio bands —
tools/lens_gate.py standalone).

Usage:
  python tools/lint_gate.py [--audit] [--prove] [--ledger] [--sync]
                            [--kernels] [--lens] [paths...]
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from arrow_matrix_tpu.analysis.__main__ import main as graft_lint_main


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    run_audit = "--audit" in argv
    if run_audit:
        argv.remove("--audit")
    run_prove = "--prove" in argv
    if run_prove:
        argv.remove("--prove")
    run_ledger = "--ledger" in argv
    if run_ledger:
        argv.remove("--ledger")
    run_sync = "--sync" in argv
    if run_sync:
        argv.remove("--sync")
    run_kernels = "--kernels" in argv
    if run_kernels:
        argv.remove("--kernels")
    run_lens = "--lens" in argv
    if run_lens:
        argv.remove("--lens")
    rc = graft_lint_main(argv)
    if rc != 0:
        print("lint gate: FAILED (fix the findings or waive them with "
              "`# graft-lint: disable=<rule>` and a justification)",
              file=sys.stderr)
        return rc
    if run_audit:
        rc = graft_lint_main(["audit"])
        if rc != 0:
            print("lint gate: trace-time audit FAILED", file=sys.stderr)
            return rc
    if run_prove:
        rc = graft_lint_main(["prove", "--check"])
        if rc != 0:
            print("lint gate: HLO contract proof FAILED",
                  file=sys.stderr)
            return rc
    if run_ledger:
        from arrow_matrix_tpu.ledger.gate import main as ledger_main

        rc = ledger_main(["--check"])
        if rc != 0:
            print("lint gate: ledger drift gate FAILED",
                  file=sys.stderr)
            return rc
    if run_sync:
        rc = graft_lint_main(["sync", "--check"])
        if rc != 0:
            print("lint gate: lock-discipline proof FAILED",
                  file=sys.stderr)
            return rc
    if run_kernels:
        rc = graft_lint_main(["kernels", "--check"])
        if rc != 0:
            print("lint gate: kernel certification FAILED",
                  file=sys.stderr)
            return rc
    if run_lens:
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from lens_gate import main as lens_main

        rc = lens_main([])
        if rc != 0:
            print("lint gate: lens calibration gate FAILED",
                  file=sys.stderr)
            return rc
    print("lint gate: ok", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
