"""On-chip race: sell-layout degree ladder "default" vs "tight".

VERDICT r3 item 3: the tight ladder (growth 1.3, align 1) cuts LOGICAL
gather slots ~3.4x on block-diagonal levels by the host-side slot
model, but the win was never measured on a real chip.  This script
builds the feature-major SellMultiLevel (the mesh-path layout,
a2a routing) on a 1-device mesh over the REAL accelerator, measures
ms/iter for both ladders at protocol scale, validates each against the
host golden, and prints one JSON line the watcher archives as
``onchip_ladder_*.json``.

A 1-device mesh is the honest single-chip proxy: the ladder's effect
is per-device gather-iteration count, which doesn't need multiple
devices to measure (routing is identity at n_dev=1).  Reference
anchor: block padding policy, /root/reference/arrow/common/graphio.py
(394-399) — the reference pads blocks; we pad gather slots, and this
race decides how tightly.
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> None:
    # AMT_LADDER_CPU=1 runs the race logic on the host CPU (test
    # fixture; AMT_LADDER_N shrinks the scale) — the watcher always
    # runs it chip-or-bust.
    cpu_ok = os.environ.get("AMT_LADDER_CPU") == "1"
    if cpu_ok:
        from arrow_matrix_tpu.utils.platform import force_cpu_devices

        force_cpu_devices()
    from arrow_matrix_tpu.utils.platform import probe_default_backend

    if cpu_ok:
        platform, kind, err = "cpu", "host", None
    else:
        platform, kind, err = probe_default_backend(timeout_s=120,
                                                    retries=1)
    out: dict = {"metric": "ladder_race", "platform": platform,
                 "device_kind": kind}
    if not cpu_ok and (err or platform == "cpu"):
        out["error"] = f"no accelerator: {err}"
        print(json.dumps(out), flush=True)
        raise SystemExit(1)

    import jax

    jax.config.update("jax_default_matmul_precision", "highest")
    cache_dir = os.environ.get(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(REPO, "bench_cache", "xla_cache"))
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)

    import numpy as np

    import bench  # repo-root bench: shared cached decomposition

    from arrow_matrix_tpu.decomposition.decompose import decomposition_spmm
    from arrow_matrix_tpu.parallel.mesh import make_mesh
    from arrow_matrix_tpu.parallel.sell_slim import SellMultiLevel
    from arrow_matrix_tpu.utils import numerics
    from arrow_matrix_tpu.utils.graphs import random_dense

    n = int(os.environ.get("AMT_LADDER_N", 1 << 20))
    m, width, k, iters = 8, 2048, 16, 10
    if n < (1 << 18):
        width, iters = 512, 5   # test-fixture scale
    os.chdir(REPO)
    levels = bench._cached_levels(n, m, width, seed=7, max_levels=12)
    nnz = sum(int(l.matrix.nnz) for l in levels)
    tol = numerics.relative_tolerance(nnz / n, iters=1)
    x_host = random_dense(n, k, seed=3)
    want = decomposition_spmm(levels, x_host)
    mesh = make_mesh((1,), ("blocks",))
    out.update({"n": n, "width": width, "k": k, "iters": iters,
                "gate": tol, "runs": {}})

    def measure(obj, x) -> float:
        def chain(cnt):
            t0 = time.perf_counter()
            xd = obj.run(x, cnt) if cnt else x
            np.asarray(jax.device_get(xd)).ravel()[0]
            return time.perf_counter() - t0

        chain(iters)  # compile + warm
        rtt = min(chain(0) for _ in range(3))
        return max((chain(iters) - rtt) / iters, 1e-9) * 1e3

    for name in ("default", "tight"):
        t0 = time.perf_counter()
        try:
            sm = SellMultiLevel(levels, width, mesh, routing="a2a",
                                ladder=name)
            build_s = time.perf_counter() - t0
            x = sm.set_features(x_host)
            ms = measure(sm, x)
            err_rel = numerics.relative_error(
                sm.gather_result(sm.step(x)), want)
            # Logical gather slots: every (tier-row, slot) pair the
            # gather kernels iterate — the ladder's cost model.
            slots = 0
            for op in sm.ops:
                for stack in (op.body, op.head):
                    slots += sum(int(np.prod(c.shape))
                                 for c in stack.cols)
            out["runs"][name] = {
                "ms": round(ms, 3), "err": err_rel,
                "build_s": round(build_s, 1),
                "gated": bool(np.isfinite(err_rel) and err_rel <= tol),
            }
            if slots:
                out["runs"][name]["gather_slots"] = slots
            print(f"[ladder_race] {name}: {ms:.1f} ms/iter "
                  f"err={err_rel:.2e}", file=sys.stderr, flush=True)
            del sm, x
        except Exception as e:
            out["runs"][name] = {
                "error": f"{type(e).__name__}: {str(e)[:300]}"}
    gated = {nm: r["ms"] for nm, r in out["runs"].items()
             if r.get("gated")}
    if gated:
        out["winner"] = min(gated, key=gated.get)
        out["value"] = gated[out["winner"]]
        out["unit"] = "ms"
    print(json.dumps(out), flush=True)
    if not gated:
        raise SystemExit(1)


if __name__ == "__main__":
    main()
