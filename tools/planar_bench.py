"""Planar-class on-chip headline (VERDICT r3 item 6).

The reference paper's communication bound targets planar /
minor-excluded graphs (reference README.md:3: "polynomial reduction in
communication volume ... for planar graphs"); the framework's banded
fast path (decompose.py band_detect) decomposes a 2-D grid to ONE
level — zero inter-level routing by construction.  This script

1. decomposes a scrambled ``side x side`` grid through the banded/RCM
   fast path (cached),
2. runs the fold iteration on the REAL chip, golden-gated,
3. reports the communication story from an 8-device virtual-CPU
   subprocess: per-iteration collective bytes of the sell/a2a layout
   on the grid (the halo-only exchange; inter-level volume is
   structurally zero at K=1).

Output: one JSON line the watcher archives as ``onchip_planar_*.json``.
AMT_PLANAR_CPU=1 runs the iteration on the host CPU at a reduced side
(test fixture).  AMT_PLANAR_SIDE overrides the grid side.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

_COMM_CHILD = r"""
import json, sys
sys.path.insert(0, %(repo)r)
from arrow_matrix_tpu.utils.platform import force_cpu_devices
force_cpu_devices(8)
import numpy as np
from arrow_matrix_tpu.decomposition import arrow_decomposition
from arrow_matrix_tpu.parallel.mesh import make_mesh
from arrow_matrix_tpu.parallel.sell_slim import SellMultiLevel
from arrow_matrix_tpu.utils import commstats
from arrow_matrix_tpu.utils.graphs import grid_graph, random_dense

side = %(side)d
rng = np.random.default_rng(3)
scramble = rng.permutation(side * side)
g = grid_graph(side)[scramble][:, scramble].tocsr()
levels = arrow_decomposition(g, arrow_width=%(width)d, max_levels=10,
                             block_diagonal=True, seed=7)
sm = SellMultiLevel(levels, %(width)d, make_mesh((8,), ("blocks",)),
                    routing="a2a")
xt = sm.set_features(random_dense(side * side, 16, seed=3))
stats = commstats.collective_stats(sm.step_fn, xt, *sm.step_operands())
print(json.dumps({
    "levels": len(levels),
    "hops": [int(op.hops) for op in sm.ops],
    "halo_rem_rows": [int(op.rem) for op in sm.ops],
    "collective_bytes_per_iter": int(stats["total_bytes"]),
    "collective_count": int(sum(v["count"] for kk, v in stats.items()
                                if isinstance(v, dict))),
}))
"""


def main() -> None:
    cpu = os.environ.get("AMT_PLANAR_CPU") == "1"
    if cpu:
        from arrow_matrix_tpu.utils.platform import force_cpu_devices

        force_cpu_devices()
    from arrow_matrix_tpu.utils.platform import probe_default_backend

    if cpu:
        platform, kind, err = "cpu", "host", None
    else:
        platform, kind, err = probe_default_backend(timeout_s=120,
                                                    retries=1)
    out: dict = {"metric": "planar_grid_iter_ms",
                 "platform": platform, "device_kind": kind}
    if not cpu and (err or platform == "cpu"):
        out["error"] = f"no accelerator: {err}"
        print(json.dumps(out), flush=True)
        raise SystemExit(1)

    side = int(os.environ.get("AMT_PLANAR_SIDE",
                              256 if cpu else 4096))
    # bf16 feature carriage halves the resident feature bytes — the
    # knob that fits the 10240^2 (10^8-row) grid on one 16 GB v5e
    # (operator ~1.7 GB + bf16 features ~6.7 GB).  f32 accumulation
    # throughout (ops/ell.py), so the one-step golden still gates,
    # against the documented bf16 carriage tolerance.
    feat_dtype = os.environ.get("AMT_PLANAR_DTYPE") or None
    if feat_dtype not in (None, "bf16"):
        raise SystemExit(f"AMT_PLANAR_DTYPE must be bf16 or unset, "
                         f"got {feat_dtype}")
    # The one-level fast path needs width >= the grid's RCM bandwidth
    # (~side); 1.25x matches the scale-ladder's 8192^2 rung (width
    # 10240).  THIS is the planar story: width covers the band, K=1,
    # zero inter-level routing.
    width = max(side * 5 // 4, 64)
    n = side * side
    out.update({"side": side, "n": n, "width": width, "k": 16})

    import jax

    jax.config.update("jax_default_matmul_precision", "highest")
    cache_dir = os.environ.get(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(REPO, "bench_cache", "xla_cache"))
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)

    import numpy as np

    from arrow_matrix_tpu.decomposition import arrow_decomposition
    from arrow_matrix_tpu.decomposition.decompose import decomposition_spmm
    from arrow_matrix_tpu.parallel.multi_level import MultiLevelArrow
    from arrow_matrix_tpu.utils import numerics
    from arrow_matrix_tpu.utils.graphs import grid_graph, random_dense

    # Scrambled grid: band_detect must RECOVER the band via RCM — the
    # honest planar case (a pre-ordered grid would trivially pass).
    t0 = time.perf_counter()
    rng = np.random.default_rng(3)
    scramble = rng.permutation(n)
    g = grid_graph(side)[scramble][:, scramble].tocsr()
    out["build_graph_s"] = round(time.perf_counter() - t0, 1)
    t0 = time.perf_counter()
    levels = arrow_decomposition(g, arrow_width=width, max_levels=10,
                                 block_diagonal=True, seed=7)
    out["decompose_s"] = round(time.perf_counter() - t0, 1)
    out["levels"] = len(levels)
    nnz = sum(int(l.matrix.nnz) for l in levels)
    out["nnz"] = nnz

    iters = 5 if cpu else 10
    x_host = random_dense(n, 16, seed=3)
    # bf16 carriage rounds the carried features once per step: the
    # documented tolerance is ~2e-2 relative (bf16 has ~3 decimal
    # digits; accumulation stays f32) vs the f32 gate formula.
    tol = (2e-2 if feat_dtype == "bf16"
           else numerics.relative_tolerance(nnz / n, iters=1))
    out["feature_dtype"] = feat_dtype or "f32"
    want = decomposition_spmm(levels, x_host)
    out["runs"] = {}
    # fold vs fold_tight: a degree-4 grid pads 2.0x under the default
    # align-8 slots and ~1.0x under tight packing — the planar case is
    # where tight packing's slot cut is LARGEST (cf. the BA-8 race
    # where it is -17%).
    for name, kwargs in (("fold", dict(fmt="fold")),
                         ("fold_tight", dict(fmt="fold",
                                             fold_growth=1.1,
                                             fold_align=1))):
        if feat_dtype == "bf16" and name == "fold":
            # The 10^8 bf16 config exists to FIT one chip: two
            # resident builds would not (and fold_tight is the known
            # slot winner on grids — 1.0x vs 2.0x nnz).
            continue
        t0 = time.perf_counter()
        multi = MultiLevelArrow(levels, width, mesh=None,
                                feature_dtype=feat_dtype, **kwargs)
        r = {"build_s": round(time.perf_counter() - t0, 1)}
        x = multi.set_features(x_host)

        def chain(cnt):
            t0 = time.perf_counter()
            xd = multi.run(x, cnt) if cnt else x
            np.asarray(jax.device_get(xd)).ravel()[0]
            return time.perf_counter() - t0

        chain(iters)   # compile + warm
        rtt = min(chain(0) for _ in range(3))
        ms = max((chain(iters) - rtt) / iters, 1e-9) * 1e3
        err = numerics.relative_error(
            multi.gather_result(multi.step(x)), want)
        r.update({"ms": round(ms, 3), "err": err,
                  "gated": bool(np.isfinite(err) and err <= tol)})
        slots = sum(int(b.n_slots) for b in multi.blocks
                    if hasattr(b, "n_slots"))
        if slots:
            r.update({"gather_slots": slots,
                      "slots_per_s": round(slots / (ms * 1e-3)),
                      "slots_over_nnz": round(slots / max(nnz, 1), 3)})
        out["runs"][name] = r
        del multi, x
    gated = {nm: r["ms"] for nm, r in out["runs"].items()
             if r.get("gated")}
    out["gate"] = tol
    if gated:
        winner = min(gated, key=gated.get)
        out.update({"winner": winner, "value": gated[winner],
                    "unit": "ms",
                    "err": out["runs"][winner]["err"], "gated": True})
    else:
        out["gated"] = False

    # Communication story (virtual 8-dev mesh, separate CPU process —
    # this process owns the accelerator).  Small fixed side: the comm
    # STRUCTURE (1 level, halo-only) is side-independent; bytes scale
    # linearly and the grid at full side would cost minutes of host
    # build for the same story.
    try:
        child = subprocess.run(
            [sys.executable, "-c",
             _COMM_CHILD % {"repo": REPO, "side": min(side, 256),
                            "width": max(min(side, 256) * 5 // 4, 64)}],
            capture_output=True, text=True, timeout=600,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        if child.returncode == 0 and child.stdout.strip():
            out["comm_8dev"] = json.loads(
                child.stdout.strip().splitlines()[-1])
        else:
            out["comm_error"] = child.stderr.strip()[-300:]
    except Exception as e:
        out["comm_error"] = f"{type(e).__name__}: {str(e)[:200]}"

    print(json.dumps(out), flush=True)
    if not out.get("gated"):
        raise SystemExit(1)


if __name__ == "__main__":
    main()
