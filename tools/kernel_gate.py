#!/usr/bin/env python
"""Tier-1 kernel gate: re-run graft-kcert and fail on any KC1-KC5
violation OR on drift against the checked-in
bench_cache/kernel_manifest.json.

This is the CI wrapper around ``python -m arrow_matrix_tpu.analysis
kernels --check`` (the pytest suite runs the same invariant in
tests/test_kernels.py): every Pallas kernel builder's declared
KernelContract and concretized call metas are proven against the five
kernel rules — indices in bounds at every grid point, VMEM/SMEM
budgets respected, DMA ring discipline replayed in a semaphore-slot
simulator, the accumulator >= f32 regardless of carriage dtype, and
the output index map gap- and overlap-free — so a kernel regression
fails the push before any TPU runs.

Usage:
  python tools/kernel_gate.py                 certify + drift check (CI)
  python tools/kernel_gate.py --refresh       certify + rewrite manifest
  python tools/kernel_gate.py --fixture F     verify a planted-broken-
                                              kernel fixture (tests/
                                              fixtures/kernels/
                                              kcN_*.py) fires its
                                              expected rule; exits
                                              nonzero when it does NOT
  python tools/kernel_gate.py --fixtures      run every shipped fixture
  python tools/kernel_gate.py --paths F...    certify arbitrary kernel
                                              files and exit nonzero on
                                              ANY finding (feeding a
                                              planted fixture here
                                              fails the gate, per rule)
  python tools/kernel_gate.py --selftest      verify the certifier
                                              itself trips on its
                                              broken twins (host-only)
"""

import argparse
import glob
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

FIXTURE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tests", "fixtures", "kernels")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--refresh", action="store_true",
                    help="rewrite bench_cache/kernel_manifest.json "
                         "instead of drift-checking against it")
    ap.add_argument("--fixture", action="append", default=[],
                    help="verify this planted-broken-kernel fixture "
                         "fires its expected rule (repeatable)")
    ap.add_argument("--fixtures", action="store_true",
                    help="verify every tests/fixtures/kernels/"
                         "kc*_*.py")
    ap.add_argument("--paths", nargs="+", default=None,
                    help="certify these files and exit nonzero on any "
                         "finding (a planted fixture fails the gate)")
    ap.add_argument("--selftest", action="store_true",
                    help="verify the certifier trips on its broken "
                         "twins (host-only, no jax)")
    args = ap.parse_args(argv)

    from arrow_matrix_tpu.analysis import kernels as graft_kcert

    if args.selftest:
        return graft_kcert.main(["--selftest"])

    if args.paths:
        findings = graft_kcert.certify_paths(args.paths)
        for f in findings:
            print(f.format())
        if findings:
            print(f"kernel gate: {len(findings)} finding(s) in "
                  f"{len(args.paths)} file(s)", file=sys.stderr)
            return 1
        print("kernel gate: paths certify clean", file=sys.stderr)
        return 0

    fixtures = list(args.fixture)
    if args.fixtures:
        fixtures.extend(sorted(glob.glob(
            os.path.join(FIXTURE_DIR, "kc*_*.py"))))
    if fixtures:
        rc = graft_kcert.main(
            [arg for p in fixtures for arg in ("--fixture", p)])
        if rc != 0:
            print("kernel gate: FIXTURE FAILED TO TRIP ITS RULE — "
                  "the certifier lost a detection", file=sys.stderr)
        return rc

    cli = [] if args.refresh else ["--check"]
    rc = graft_kcert.main(cli)
    if rc != 0:
        print("kernel gate: FAILED (a KC rule is violated or the "
              "manifest drifted — rerun `python -m arrow_matrix_tpu."
              "analysis kernels` and review the diff)",
              file=sys.stderr)
        return rc
    print("kernel gate: ok", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
