#!/usr/bin/env python
"""Reshard chaos gate: kill-mid-migration survival + the H7
bounded-scratch law (graft-reshard).

The acceptance bar for staged redistribution (parallel/reshard.py):

* **reshard_h7** — the static half.  Re-derives a staged shuffle at a
  tiny scale, lowers every stage of the split route, and requires
  ``check_h7`` to PASS (every stage's per-device send+recv collective
  buffers <= the declared scratch budget) while the UNSPLIT one-shot
  route, fed to the same checker as a single "stage", must FAIL — the
  checker has to trip on exactly the memory cliff staging removes.
  Also audits bench_cache/hlo_manifest.json: at least two
  ``reshard[...]`` entries with H7 ``pass``, one of them a replication
  (repl c) change.
* **kill_mid_migration** — the live half.  A driver subprocess seeds
  one mid-flight (step 2 of 4) layout-tagged checkpoint per request on
  a 2-device layout, then grows the server to a 4-device layout
  (``ArrowServer.grow``: every checkpoint replayed through a staged
  plan with per-stage scratch <= a deliberately tiny budget) and
  serves the trace to completion.  Run A is fault-free (the
  bit-identity reference).  Run B arms ``AMT_FAULT_PLAN`` with a kill
  on the ``reshard.stage`` seam and SIGKILLs itself mid-cutover, after
  at least one checkpoint has already migrated.  Run C reruns run B's
  directory fault-free: grow must migrate ONLY the stragglers
  (1 <= migrated < all — proving the kill landed mid-migration and the
  rerun neither redoes nor skips everything), every request must
  RESUME (the ``resumed request`` line) and complete — zero lost
  accepted requests — and every f32 result must be bit-identical to
  run A.

Registered in tools/chaos_gate.py's matrix (the subprocess scenario
skips under ``--fast``, like serve_kill/fleet_kill).  Standalone:
``python tools/reshard_gate.py [workdir]``.
"""

import json
import os
import re
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# Driver scale: small enough for the CPU gate budget, big enough that
# the 2-dev -> 4-dev migration is genuinely staged at the tiny budget.
N, WIDTH, K = 96, 16, 2
TENANTS, REQUESTS, ITERS = 3, 6, 4
SEED, TRACE_SEED = 3, 7
#: Grow-migration scratch budget: at K=2/f32 a row is 8 B, so a stage
#: carries at most 256 // (2*8) = 16 rows per device — several stages
#: per 96-row checkpoint, so a kill can land strictly inside one.
DRIVER_BUDGET = 256
#: reshard.stage hits before the armed driver SIGKILLs itself: at the
#: 256 B budget each 96-row checkpoint migrates in 2 stages, so hit 9
#: is checkpoint 5's SECOND stage — strictly inside a cutover, with
#: four checkpoints already migrated and two stragglers left.
KILL_AFTER = 9

# H7 scenario scale (in-process, runs even under --fast).
H7_N, H7_NDEV, H7_K = 64, 4, 2
#: Small enough that the one-shot route's send+recv overflows it (the
#: planted violation) while every split stage stays within it.
H7_BUDGET = 256

MANIFEST = os.path.join(REPO, "bench_cache", "hlo_manifest.json")


# -- driver (runs in a subprocess) ------------------------------------------

def driver(run_dir, results_npz):
    """Seed step-2 checkpoints on the 2-dev layout, grow to 4 devices
    (staged checkpoint migration — the kill site), serve the trace to
    completion, save results.  Exits nonzero if any request is lost."""
    from arrow_matrix_tpu.utils.platform import force_cpu_devices

    force_cpu_devices(4)
    import jax
    import numpy as np

    from arrow_matrix_tpu.parallel.mesh import make_mesh
    from arrow_matrix_tpu.serve.loadgen import (
        ba_executor_factory,
        synthetic_trace,
    )
    from arrow_matrix_tpu.serve.scheduler import ArrowServer, ExecConfig
    from arrow_matrix_tpu.utils.checkpoint import (
        list_checkpoints,
        save_state,
    )

    ck_dir = os.path.join(run_dir, "checkpoints")
    os.makedirs(ck_dir, exist_ok=True)
    devs = jax.devices()
    mesh2 = make_mesh((2,), ("blocks",), devices=np.asarray(devs[:2]))
    mesh4 = make_mesh((4,), ("blocks",), devices=np.asarray(devs))
    fac2, n_rows = ba_executor_factory(N, WIDTH, SEED, fmt="auto",
                                       mesh=mesh2)
    fac4, _ = ba_executor_factory(N, WIDTH, SEED, fmt="auto",
                                  mesh=mesh4)
    trace = synthetic_trace(n_rows, tenants=TENANTS,
                            requests=REQUESTS, k=K, iterations=ITERS,
                            seed=TRACE_SEED)

    # Seed a mid-flight checkpoint per request on the SOURCE layout —
    # but only for requests with no checkpoint at all: a rerun after a
    # kill must keep both already-migrated files and src-layout
    # stragglers exactly as the dead process left them.
    have = {os.path.basename(s) for s in list_checkpoints(ck_dir)}
    ex2 = fac2(ExecConfig())
    seeded = 0
    for r in trace:
        if f"ck_{r.request_id}" in have:
            continue
        x = ex2.set_features(r.x)
        for _ in range(2):
            x = ex2.step(x)
        save_state(os.path.join(ck_dir, f"ck_{r.request_id}"),
                   np.asarray(x), 2,
                   layout=f"serve/{r.request_id}/k{r.k}"
                          f"/it{r.iterations}")
        seeded += 1
    print(f"[reshard-driver] seeded {seeded} step-2 checkpoint(s) "
          f"on the 2-device layout", flush=True)

    server = ArrowServer(fac2, ExecConfig(), name="reshard",
                         checkpoint_dir=ck_dir, checkpoint_every=2,
                         max_batch_k=0, grow_factory=fac4,
                         reshard_budget_bytes=DRIVER_BUDGET)
    # The staged cutover — AMT_FAULT_PLAN's reshard.stage kill (if
    # armed) SIGKILLs this process somewhere inside this call.
    if not server.grow(reason="gate"):
        print("[reshard-driver] FAIL: grow refused", flush=True)
        return 1
    tickets = [server.submit(r) for r in trace]
    server.drain()
    lost = [t.request.request_id for t in tickets
            if t.result is None]
    if lost:
        print(f"[reshard-driver] FAIL: lost accepted request(s) "
              f"{lost}", flush=True)
        return 1
    not_resumed = [t.request.request_id for t in tickets
                   if t.resumed_step != 2]
    if not_resumed:
        print(f"[reshard-driver] FAIL: request(s) {not_resumed} did "
              f"not resume from the migrated step-2 checkpoint",
              flush=True)
        return 1
    np.savez(results_npz,
             **{t.request.request_id: np.asarray(t.result)
                for t in tickets})
    print(f"[reshard-driver] {len(tickets)} request(s) completed, "
          f"all resumed at iteration 2", flush=True)
    return 0


def _run_driver(workdir, tag, fault_plan=None):
    """One driver subprocess; returns (proc, run_dir, npz).  ``tag``
    also selects the run directory, so a rerun under the same tag
    resumes the previous run's checkpoints."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("AMT_FAULT_PLAN", None)
    if fault_plan is not None:
        env["AMT_FAULT_PLAN"] = json.dumps(fault_plan)
    run_dir = os.path.join(workdir, f"reshard_{tag}")
    os.makedirs(run_dir, exist_ok=True)
    npz = os.path.join(run_dir, "results.npz")
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--driver",
         run_dir, npz],
        env=env, capture_output=True, text=True, timeout=600)
    return proc, run_dir, npz


def _migrated_count(stdout):
    """Parse "N checkpoint(s) migrated" out of the grow line."""
    m = re.search(r"grew to .*?: (\d+) checkpoint\(s\) migrated "
                  r"through (\d+) staged plan step\(s\)", stdout)
    return (int(m.group(1)), int(m.group(2))) if m else (None, None)


# -- scenarios --------------------------------------------------------------

def scenario_kill_mid_migration(workdir):
    problems = []
    import numpy as np

    # Run A: fault-free reference.
    ref, _, ref_npz = _run_driver(workdir, "ref")
    if ref.returncode != 0:
        return [f"kill_mid_migration: fault-free reference run "
                f"failed (rc={ref.returncode}):\n{ref.stdout[-2000:]}"
                f"\n{ref.stderr[-2000:]}"]
    mig_a, stages_a = _migrated_count(ref.stdout)
    if mig_a != REQUESTS:
        problems.append(f"kill_mid_migration: reference grow migrated "
                        f"{mig_a} checkpoint(s), expected {REQUESTS}")
    if stages_a is not None and stages_a <= REQUESTS:
        problems.append(f"kill_mid_migration: reference migration ran "
                        f"{stages_a} total stage(s) for {REQUESTS} "
                        f"checkpoint(s) — not genuinely staged, the "
                        f"kill site cannot land mid-checkpoint")

    # Run B: SIGKILL on the KILL_AFTER-th reshard.stage crossing.
    kill, kill_dir, kill_npz = _run_driver(
        workdir, "kill",
        fault_plan={"scenario": "kill", "site": "reshard.stage",
                    "after": KILL_AFTER})
    if kill.returncode == 0:
        problems.append("kill_mid_migration: armed run exited 0 — the "
                        "injected SIGKILL never fired on the "
                        "reshard.stage seam")

    # Run C: rerun the killed run's directory fault-free.
    resume, _, _ = _run_driver(workdir, "kill")
    if resume.returncode != 0:
        problems.append(f"kill_mid_migration: resume rerun failed "
                        f"(rc={resume.returncode}):"
                        f"\n{resume.stdout[-2000:]}"
                        f"\n{resume.stderr[-2000:]}")
        return problems
    mig_c, _ = _migrated_count(resume.stdout)
    if mig_c is None or not (1 <= mig_c < REQUESTS):
        problems.append(f"kill_mid_migration: resume grow migrated "
                        f"{mig_c} checkpoint(s); the kill should have "
                        f"left between 1 and {REQUESTS - 1} "
                        f"stragglers (landed mid-migration)")
    if "resumed request" not in resume.stdout:
        problems.append("kill_mid_migration: resume run printed no "
                        "'resumed request' line — requests were "
                        "recomputed, not resumed")
    a = np.load(ref_npz)
    c = np.load(kill_npz)
    if sorted(a.files) != sorted(c.files):
        problems.append(f"kill_mid_migration: resume completed "
                        f"{sorted(c.files)} but the reference "
                        f"completed {sorted(a.files)} — lost "
                        f"accepted request(s)")
    else:
        for rid in a.files:
            if a[rid].tobytes() != c[rid].tobytes():
                problems.append(f"kill_mid_migration: result for "
                                f"{rid} is not bit-identical to the "
                                f"fault-free reference")
    return problems


def scenario_reshard_h7():
    problems = []
    import numpy as np

    # 1) Manifest audit: the proven H7 record this repo ships.
    if not os.path.exists(MANIFEST):
        problems.append(f"reshard_h7: {MANIFEST} missing — run "
                        f"tools/prove_collectives.py")
    else:
        with open(MANIFEST, encoding="utf-8") as fh:
            man = json.load(fh)
        entries = [e for e in man.get("entries", [])
                   if e.get("entry", "").startswith("reshard[")]
        passed = [e for e in entries
                  if e.get("rules", {}).get("H7", {})
                       .get("status") == "pass"]
        if len(passed) < 2:
            problems.append(f"reshard_h7: manifest has "
                            f"{len(passed)} reshard entr(ies) with "
                            f"H7 pass, need >= 2")
        if not any("repl" in e.get("entry", "") for e in passed):
            problems.append("reshard_h7: no H7-passing reshard entry "
                            "covers a replication (repl c) change")

    # 2) Live lowering: split stages must PASS, the one-shot route
    #    must FAIL the same checker (planted violation).
    import jax

    from arrow_matrix_tpu.analysis.contracts import CollectiveContract
    from arrow_matrix_tpu.analysis.prove import check_h7, summarize_hlo
    from arrow_matrix_tpu.parallel import routing as routing_mod
    from arrow_matrix_tpu.parallel.mesh import make_mesh, put_global
    from arrow_matrix_tpu.parallel.reshard import (
        Layout,
        plan_route_table,
        redistribution_plan,
    )
    from jax.sharding import NamedSharding, PartitionSpec

    devs = np.asarray(jax.devices()[:H7_NDEV])
    mesh = make_mesh((H7_NDEV,), ("blocks",), devices=devs)
    rng = np.random.default_rng(29)
    src = Layout(H7_N, n_dev=H7_NDEV, tag="gate_src")
    dst = Layout(H7_N, n_dev=H7_NDEV, tag="gate_dst")
    plan = redistribution_plan(src, dst, H7_BUDGET, k=H7_K,
                               perm_map=rng.permutation(H7_N)
                               .astype(np.int64))
    tbl, mask = plan_route_table(plan)
    route = routing_mod.build_route(tbl, H7_NDEV,
                                    src_total=src.stored_rows,
                                    pad_mask=mask)
    sroute = routing_mod.split_route_stages(route, H7_K, H7_BUDGET)
    contract = CollectiveContract(
        algorithm="gate_shuffle",
        step_bytes=route.device_bytes_per_exchange(H7_K, 4),
        reduce_bytes=0, repl=1, overlap_slabs=1, dtype="f32",
        lowered_kinds=("all-to-all",), compiled_kinds=("all-to-all",),
        ratio_band=(0.99, 1.01), scratch_budget_bytes=H7_BUDGET)
    x = put_global(
        rng.standard_normal((src.stored_rows, H7_K))
        .astype(np.float32),
        NamedSharding(mesh, PartitionSpec("blocks")))

    def _summ(rt):
        fn = jax.jit(lambda xx: routing_mod.routed_take(
            xx, routing_mod.shard_route(rt, mesh, "blocks"), mesh,
            "blocks"))
        return summarize_hlo(fn.lower(x).as_text(dialect="hlo"))

    staged = check_h7([_summ(st) for st in sroute.stages], contract)
    if staged["status"] != "pass":
        problems.append(f"reshard_h7: split route failed the checker "
                        f"it was built to satisfy: {staged['detail']}")
    one_shot = check_h7([_summ(route)], contract)
    if one_shot["status"] != "fail":
        problems.append(f"reshard_h7: one-shot route "
                        f"({route.device_bytes_per_exchange(H7_K, 4)}"
                        f" B/device) did NOT trip H7 at budget "
                        f"{H7_BUDGET} B — the checker cannot see the "
                        f"memory cliff (got {one_shot['status']}: "
                        f"{one_shot['detail']})")
    if sroute.n_stages < 2:
        problems.append(f"reshard_h7: split produced "
                        f"{sroute.n_stages} stage(s) — the gate "
                        f"scale no longer exercises staging")
    return problems


def run_reshard_scenarios(workdir, fast=False):
    """Chaos-gate entry point: returns (problems, scenario names)."""
    problems, scenarios = [], []

    scenarios.append("reshard_h7")
    problems += scenario_reshard_h7()

    if not fast:
        scenarios.append("kill_mid_migration")
        problems += scenario_kill_mid_migration(workdir)
    return problems, scenarios


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "--driver":
        return driver(argv[1], argv[2])
    from arrow_matrix_tpu.utils.platform import force_cpu_devices

    force_cpu_devices(4)
    fast = "--fast" in argv
    argv = [a for a in argv if a != "--fast"]

    from arrow_matrix_tpu import sync

    # Arm the lock-order witness so the migration scenarios (flock'd
    # preemption registry + live-grow server) run order-checked; the
    # kill_mid_migration driver subprocess inherits AMT_LOCK_WITNESS
    # from the environment.
    registry = sync.enable_witness()

    if argv:
        workdir = argv[0]
        os.makedirs(workdir, exist_ok=True)
    else:
        import tempfile

        workdir = tempfile.mkdtemp(prefix="reshard_gate_")
    problems, scenarios = run_reshard_scenarios(workdir, fast=fast)
    snap = registry.snapshot()
    if snap["violations"]:
        problems.extend(f"lock witness: {v}" for v in snap["violations"])
    print(f"reshard gate: lock witness — {snap['acquisitions']} "
          f"acquisitions, {len(snap['threads'])} threads, "
          f"{len(snap['violations'])} violations")
    print(f"reshard gate scenarios: {scenarios}")
    if problems:
        print("RESHARD GATE: FAIL")
        for p in problems:
            print(f"  - {p}")
        return 1
    print("RESHARD GATE: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
