"""graft-xray measurement bench: the numbers behind PERFORMANCE.md.

Three measured sections, one JSON line of output:

1. ``critical_path`` — the r07 scale point (n=2^20, width=2048)
   served through a real :class:`ArrowServer` holding the committed
   bf16 certificate (BENCH_r07.json's probed error curve — never
   hand-declared), one warmup request per class to absorb XLA
   compilation, then paired exact/approx requests decomposed into the
   graft-xray segments per served class.  The f32-vs-bf16 iter gap
   must land in a *named* segment (compute), not vanish into a
   blended mean — that is the whole point of the per-class report.

2. ``wire_per_mb`` — serialize and socket-transfer cost of MB-scale
   ndarray frames over a local socketpair, measured by the same
   ``send_msg`` / ``recv_msg_stats`` accounting the fleet uses
   (median of repeats, per-MB normalized).

3. ``tracing_overhead`` — the same synthetic trace served twice at a
   smaller scale point, tracer+registry attached vs detached,
   interleaved A/B repeats; plus the microbenchmarked cost of one
   span.  The ISSUE's acceptance bar is overhead <= 5%.

The big section decomposes a 2^20-row operator on the host backend
(~2.5 min), so the full run takes a few minutes; ``--n`` scales it
down for smoke runs (the certificate is then probed live instead of
read from BENCH_r07.json, since certificates bind to one structure).

Usage: python tools/xray_bench.py [--n 1048576] [--width 2048] ...
Prints ONE JSON line (the measured payload) as its last stdout line.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import socket
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from arrow_matrix_tpu.utils.platform import force_cpu_devices  # noqa: E402

#: The committed error-curve source for the r07 structure; used only
#: when the bench runs at exactly the r07 (n, width, seed) point.
BENCH_R07 = os.path.join(REPO, "BENCH_r07.json")


def _median(vals):
    s = sorted(vals)
    return s[len(s) // 2]


# ---------------------------------------------------------------------------
# Section 1: per-segment critical path, f32 vs bf16
# ---------------------------------------------------------------------------

def _bf16_certificate(n: int, width: int, seed: int):
    """The serving certificate for the bench structure.  At the r07
    point the committed BENCH_r07.json curve is the source (probed
    once, exported, reused); at any other point the curve is probed
    live — a certificate is only ever a measured artifact."""
    from arrow_matrix_tpu.classes import certificate_from_record

    if n == 1048576 and width == 2048 and seed == 7 \
            and os.path.exists(BENCH_R07):
        parsed = json.load(open(BENCH_R07))["parsed"]
        for cur in parsed.get("error_curves", []):
            if cur.get("dtype") == "bf16" and cur.get("rel_frobenius"):
                rec = {"kind": "error_curve",
                       "structure_hash": cur["structure_hash"],
                       "record_id": cur.get("record_id", "r07"),
                       "knobs": {"dtype": "bf16",
                                 "emulated": cur.get("emulated", False),
                                 "seed": seed},
                       "payload": {"rel_frobenius":
                                   list(cur["rel_frobenius"])}}
                cert = certificate_from_record(rec)
                if cert is not None:
                    return cert, "BENCH_r07.json"
    from arrow_matrix_tpu.ledger.probe import error_curves_for_source

    source = {"kind": "ba", "n": n, "m": 3, "width": width,
              "seed": seed}
    curves = error_curves_for_source(source)
    rec = next(r for r in curves if r["knobs"]["dtype"] == "bf16")
    cert = certificate_from_record(rec)
    assert cert is not None
    return cert, "probed"


def bench_critical_path(n: int, width: int, seed: int, *, k: int,
                        per_class: int, iterations: int) -> dict:
    """Serve paired exact/approx requests over one resident operator
    and decompose each served class into the graft-xray segments."""
    from arrow_matrix_tpu.obs import xray
    from arrow_matrix_tpu.obs.tracer import Tracer
    from arrow_matrix_tpu.serve import request as rq
    from arrow_matrix_tpu.serve.loadgen import (
        ba_executor_factory,
        run_trace,
        synthetic_trace,
    )
    from arrow_matrix_tpu.serve.scheduler import ArrowServer, ExecConfig

    t0 = time.perf_counter()
    factory, n_rows = ba_executor_factory(n, width, seed, fmt="fold")
    decompose_s = time.perf_counter() - t0
    cert, cert_source = _bf16_certificate(n, width, seed)
    assert cert.covers(iterations), \
        "bench iterations exceed the certified curve"

    tracer = Tracer("xray_bench")
    server = ArrowServer(factory, ExecConfig(), certificates=[cert],
                         tracer=tracer, name="xray_bench")

    def _paired(requests: int, trace_seed: int):
        trace = synthetic_trace(n_rows, tenants=1, requests=requests,
                                k=k, iterations=iterations,
                                seed=trace_seed)
        return [dataclasses.replace(
                    r, traffic_class=("exact" if i % 2 == 0
                                      else "approx"))
                for i, r in enumerate(trace)]

    # One warmup request per class absorbs XLA compilation so the
    # measured segments are steady-state (the honest per-iter cost).
    warm = run_trace(server, _paired(2, trace_seed=seed + 1))
    assert all(t.status == rq.COMPLETED for t in warm)
    tracer.spans.clear()

    tickets = run_trace(server, _paired(2 * per_class,
                                        trace_seed=seed))
    assert all(t.status == rq.COMPLETED for t in tickets)
    served = {t.request.request_id: t.served_class for t in tickets}
    approx = [t for t in tickets
              if t.request.traffic_class == "approx"]
    assert approx and all(t.served_class == "approx" for t in approx), \
        "approx requests fell back to exact — certificate not honored"

    doc = xray.merge_process_traces(
        [xray.process_trace(tracer, "serve")])
    cp = xray.critical_path(doc, classes=served)
    return {"config": {"n": n, "width": width, "seed": seed, "k": k,
                       "iterations": iterations,
                       "requests_per_class": per_class,
                       "decompose_s": round(decompose_s, 2),
                       "certificate": cert_source},
            "per_class": cp["per_class"],
            "requests": cp["requests"]}


# ---------------------------------------------------------------------------
# Section 2: wire serialize/transfer cost per MB
# ---------------------------------------------------------------------------

def bench_wire_per_mb(sizes_mb=(1, 4, 16), repeats: int = 5) -> dict:
    """Measured cost of MB-scale ndarray frames over a socketpair,
    using the fleet's own ``send_msg``/``recv_msg_stats`` accounting."""
    import numpy as np

    from arrow_matrix_tpu.fleet import wire

    out = {}
    for mb in sizes_mb:
        x = np.random.default_rng(mb).standard_normal(
            (mb << 20) // 4).astype(np.float32)
        sends, decodes, wires = [], [], []
        for _ in range(repeats):
            a, b = socket.socketpair()
            got = {}

            def _server(sock=b, sink=got):
                msg, stats = wire.recv_msg_stats(sock, role="server")
                sink.update(stats)
                wire.send_msg(sock, {"op": "ack"}, role="server")

            th = threading.Thread(target=_server, daemon=True)
            th.start()
            st = wire.send_msg(a, {"op": "bench", "x": x},
                               role="client")
            wire.recv_msg(a, role="client")
            th.join()
            a.close(); b.close()
            sends.append(st["serialize_ms"])
            wires.append(st["wire_ms"] + got["wire_ms"])
            decodes.append(got["serialize_ms"])
        frame_mb = st["frame_bytes"] / float(1 << 20)
        out[f"{mb}MiB"] = {
            "frame_bytes": st["frame_bytes"],
            "encode_ms_per_mb": round(_median(sends) / frame_mb, 3),
            "decode_ms_per_mb": round(_median(decodes) / frame_mb, 3),
            "wire_ms_per_mb": round(_median(wires) / frame_mb, 3)}
    return out


# ---------------------------------------------------------------------------
# Section 3: tracing overhead on/off
# ---------------------------------------------------------------------------

def bench_tracing_overhead(n: int = 262144, width: int = 512, *,
                           requests: int = 6, iterations: int = 2,
                           k: int = 4, seed: int = 3,
                           repeats: int = 5) -> dict:
    """The same synthetic trace served with tracing+metrics attached
    vs detached, interleaved A/B so drift hits both variants equally."""
    from arrow_matrix_tpu.obs.metrics import MetricsRegistry
    from arrow_matrix_tpu.obs.tracer import Tracer
    from arrow_matrix_tpu.serve import request as rq
    from arrow_matrix_tpu.serve.loadgen import (
        ba_executor_factory,
        run_trace,
        synthetic_trace,
    )
    from arrow_matrix_tpu.serve.scheduler import ArrowServer, ExecConfig

    factory, n_rows = ba_executor_factory(n, width, seed, fmt="fold")
    tracer = Tracer("overhead")
    servers = {
        "on": ArrowServer(factory, ExecConfig(), tracer=tracer,
                          registry=MetricsRegistry(), name="on"),
        "off": ArrowServer(factory, ExecConfig(), name="off"),
    }

    def _run(server) -> float:
        trace = synthetic_trace(n_rows, tenants=2, requests=requests,
                                k=k, iterations=iterations, seed=seed)
        t0 = time.perf_counter()
        tickets = run_trace(server, trace)
        wall = time.perf_counter() - t0
        assert all(t.status == rq.COMPLETED for t in tickets)
        return wall

    for server in servers.values():   # compile both variants first
        _run(server)
    walls = {"on": [], "off": []}
    for _ in range(repeats):
        for name, server in servers.items():
            tracer.spans.clear()
            walls[name].append(_run(server))
    on, off = _median(walls["on"]), _median(walls["off"])

    t0 = time.perf_counter()
    probe = Tracer("span_cost")
    for _ in range(20000):
        with probe.span("noop"):
            pass
    span_us = (time.perf_counter() - t0) / 20000 * 1e6
    return {"config": {"n": n, "width": width, "requests": requests,
                       "iterations": iterations, "repeats": repeats},
            "wall_on_s": round(on, 4), "wall_off_s": round(off, 4),
            "overhead_pct": round((on - off) / off * 100.0, 2),
            "span_cost_us": round(span_us, 2)}


# ---------------------------------------------------------------------------

def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--n", type=int, default=1048576)
    ap.add_argument("--width", type=int, default=2048)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--k", type=int, default=16)
    ap.add_argument("--per-class", type=int, default=2)
    ap.add_argument("--iterations", type=int, default=2)
    ap.add_argument("--out", default=None)
    ap.add_argument("--sections", default="critical,wire,overhead",
                    help="comma list of critical/wire/overhead — the "
                         "overhead A/B is best run in its own fresh "
                         "process, unpolluted by the big section's "
                         "heap")
    args = ap.parse_args(argv)
    force_cpu_devices(1)

    sections = set(args.sections.split(","))
    payload = {}
    if "critical" in sections:
        payload["critical_path"] = bench_critical_path(
            args.n, args.width, args.seed, k=args.k,
            per_class=args.per_class, iterations=args.iterations)
    if "wire" in sections:
        payload["wire_per_mb"] = bench_wire_per_mb()
    if "overhead" in sections:
        payload["tracing_overhead"] = bench_tracing_overhead()
    if args.out:
        from arrow_matrix_tpu.utils.artifacts import atomic_write_json

        atomic_write_json(args.out, payload, indent=2, sort_keys=True)
    print(json.dumps(payload, sort_keys=True))
    return 0


if __name__ == "__main__":
    sys.exit(main())
