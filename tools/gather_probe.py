"""On-chip gather-rate probes: the cost model behind every ELL-family
kernel (PERFORMANCE.md "layout-padding law").

Measures the XLA gather rate (slots/s) as a function of feature count,
dtype, and index sortedness, plus the SELL fold step at protocol scale
for k in {16, 128}.  Run when the TPU tunnel is healthy:

    PYTHONPATH=/root/repo:/root/.axon_site python tools/gather_probe.py
"""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def bench(f, *a, reps: int = 5) -> float:
    import jax

    o = f(*a)
    jax.block_until_ready(o)
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        o = f(*a)
        jax.block_until_ready(o)
        ts.append(time.perf_counter() - t0)
    return min(ts) * 1e3


def gather_rates() -> None:
    import jax
    import jax.numpy as jnp

    n, m = 1 << 20, 16
    rng = np.random.default_rng(0)
    idx = rng.integers(0, n, size=n * m, dtype=np.int32)
    idx_sorted = np.sort(idx)
    slots = idx.size
    for k in (16, 64, 128):
        for dt in ("f32", "bf16"):
            x = rng.standard_normal((k, n)).astype(np.float32)
            xd = jnp.asarray(x if dt == "f32" else x.astype(jnp.bfloat16))
            f = jax.jit(lambda xx, ii: jnp.take(xx, ii, axis=1))
            ms = bench(f, xd, jnp.asarray(idx))
            ms_s = bench(f, xd, jnp.asarray(idx_sorted))
            print(f"k={k:4d} {dt}: {ms:8.2f} ms "
                  f"({slots / ms / 1e3:.0f}M slots/s) sorted {ms_s:8.2f} ms",
                  flush=True)


def fold_step(k: int) -> None:
    import jax

    jax.config.update("jax_default_matmul_precision", "highest")
    from bench import _cached_levels, _measure

    from arrow_matrix_tpu.parallel.multi_level import (
        MultiLevelArrow,
        resolve_feature_dtype,
    )
    from arrow_matrix_tpu.utils.graphs import random_dense

    n = 1 << 20
    levels = _cached_levels(n, 8, 2048, seed=7, max_levels=12)
    x_host = random_dense(n, k, seed=3)
    # One build, both carriage dtypes: feature_dtype is consumed only
    # by set_features (the operator blocks are bit-identical), so
    # retargeting the attribute measures bf16 without a second
    # multi-GB build + upload.
    multi = MultiLevelArrow(levels, 2048, mesh=None, fmt="fold")
    sell = multi.blocks[0]
    print(f"fold k={k}: tiers={len(sell.cols)} slots={sell.n_slots} "
          f"({sell.n_slots / sum(l.matrix.nnz for l in levels):.2f}x nnz) "
          f"bytes={sell.device_nbytes() / 2**30:.2f}GB", flush=True)
    for fd in (None, "bf16"):
        multi.feature_dtype = resolve_feature_dtype(fd)
        x = multi.set_features(x_host)
        ms = _measure(multi, x, 10)
        print(f"fold k={k} feat={fd or 'f32'}: {ms:.2f} ms/iter "
              f"({sell.n_slots / ms / 1e3:.0f}M slots/s)", flush=True)


def main() -> None:
    import jax

    dev = jax.devices()[0]
    print(f"device: {dev.platform} {dev.device_kind}", flush=True)
    gather_rates()
    for k in (16, 128):
        fold_step(k)


if __name__ == "__main__":
    main()
