"""Measure the host-global routing-table build at scale (VERDICT r3
item 9; streamed two-pass build VERDICT r4 item 4).

``routing.build_route`` composes the exchange tables for one level
pair on ONE host.  The in-memory build materializes ~13 full-length
derived vectors plus a global sort (measured linear, ~13 x 8 B x total
peak incremental RSS — ~10 GB at 10^8 rows).  Round 5 added the
chunked two-pass streamed build (auto above 2^24 rows): scratch is
bounded to O(chunk) and the peak becomes the OUTPUT tables plus one
chunk.  This tool measures both modes in ISOLATED subprocesses (peak
RSS is a per-process high-water mark), asserts the tables are
byte-identical via sha256, and appends the numbers to
``bench_results/routing_build.json``.

Usage: PYTHONPATH=/root/repo python tools/measure_routing_build.py
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

CHILD = r"""
import hashlib, json, os, resource, sys, time
sys.path.insert(0, {repo!r})
from arrow_matrix_tpu.utils.platform import force_cpu_devices
force_cpu_devices()
import numpy as np
from arrow_matrix_tpu.parallel.routing import build_route

log2, n_dev, mode = {log2}, {n_dev}, {mode!r}
total = 1 << log2
rng = np.random.default_rng(log2)
table = rng.permutation(total)
rss0 = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 2**20
t0 = time.perf_counter()
route = build_route(table, n_dev,
                    stream_chunk=(1 << 62) if mode == "memory" else None)
dt = time.perf_counter() - t0
h = hashlib.sha256()
bytes_tables = 0
for name in ("local_src", "local_dst", "send_idx", "recv_dst"):
    a = np.asarray(getattr(route, name))
    bytes_tables += a.nbytes
    h.update(name.encode())
    h.update(str(a.shape).encode())
    h.update(a.tobytes())
print(json.dumps({{
    "mode": mode, "build_s": round(dt, 1),
    "peak_rss_gb": round(
        resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 2**20, 2),
    "rss_before_gb": round(rss0, 2),
    "table_bytes_gb": round(bytes_tables / 2**30, 3),
    "sha256": h.hexdigest(),
}}))
"""


def run_child(log2: int, n_dev: int, mode: str) -> dict:
    proc = subprocess.run(
        [sys.executable, "-c",
         CHILD.format(repo=REPO, log2=log2, n_dev=n_dev, mode=mode)],
        capture_output=True, text=True, timeout=3600)
    if proc.returncode != 0:
        raise RuntimeError(f"{mode} child failed:\n{proc.stderr[-2000:]}")
    return json.loads(proc.stdout.strip().splitlines()[-1])


def main() -> None:
    from arrow_matrix_tpu.utils.platform import host_load

    n_dev = int(os.environ.get("AMT_ROUTE_DEVS", 8))
    # Measurement hygiene (VERDICT item 6): committed numbers carry the
    # host contention they were taken under, sampled at both ends (a
    # competitor appearing mid-run shows up in "after").
    out = {"n_dev": n_dev, "host_load": {"before": host_load()},
           "rungs": {}}
    for log2 in (24, 26):
        rung: dict = {"total_rows": 1 << log2}
        for mode in ("memory", "streamed"):
            r = run_child(log2, n_dev, mode)
            rung[mode] = r
            print(f"2^{log2} {mode}: build {r['build_s']}s, peak RSS "
                  f"{r['peak_rss_gb']} GB (before {r['rss_before_gb']}), "
                  f"tables {r['table_bytes_gb']} GB", flush=True)
        rung["identical"] = (rung["memory"]["sha256"]
                             == rung["streamed"]["sha256"])
        assert rung["identical"], f"2^{log2}: streamed tables differ!"
        rung["rss_cut"] = round(
            (rung["memory"]["peak_rss_gb"] - rung["memory"]["rss_before_gb"])
            / max(rung["streamed"]["peak_rss_gb"]
                  - rung["streamed"]["rss_before_gb"], 1e-9), 2)
        print(f"2^{log2}: identical tables, incremental-RSS cut "
              f"{rung['rss_cut']}x", flush=True)
        out["rungs"][f"2^{log2}"] = rung
    out["host_load"]["after"] = host_load()
    path = os.path.join(REPO, "bench_results", "routing_build.json")
    try:
        with open(path) as f:
            prior = json.load(f)
    except (OSError, json.JSONDecodeError):
        prior = {}
    prior[f"devs{n_dev}"] = out
    with open(path, "w") as f:
        json.dump(prior, f, indent=1)
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
