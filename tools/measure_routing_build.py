"""Measure the host-global routing-table build at scale (VERDICT r3
item 9).

``routing.build_route`` composes full-length int arrays per level pair
on ONE host — the acknowledged host-global remainder of the otherwise
streamed multi-level build.  This tool measures its wall time and peak
RSS at total = 2^24..2^26 rows on a realistic table (a random
permutation, the worst case for pair skew: every row moves), appends
the numbers to ``bench_results/routing_build.json``, and prints them.

The measured model (documented in PERFORMANCE.md): the build is
~12 full-length vector passes, so time is linear in ``total`` and peak
incremental memory is ~13 x 8 B x total.  At 10^8 rows that is ~10 GB
and O(1 min) — within one fat host's budget, which is why the build is
documented + guarded (parallel/routing.py warns loudly when the
estimate exceeds available RAM) rather than streamed per shard.

Usage: PYTHONPATH=/root/repo python tools/measure_routing_build.py
"""

from __future__ import annotations

import json
import os
import resource
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from arrow_matrix_tpu.utils.platform import force_cpu_devices  # noqa: E402

force_cpu_devices()

import numpy as np  # noqa: E402

from arrow_matrix_tpu.parallel.routing import build_route  # noqa: E402


def _rss_gb() -> float:
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 2**20


def main() -> None:
    n_dev = int(os.environ.get("AMT_ROUTE_DEVS", 8))
    out = {"n_dev": n_dev, "rungs": {}}
    for log2 in (24, 25, 26):
        total = 1 << log2
        rng = np.random.default_rng(log2)
        table = rng.permutation(total)
        rss0 = _rss_gb()
        t0 = time.perf_counter()
        route = build_route(table, n_dev)
        dt = time.perf_counter() - t0
        bytes_tables = sum(
            int(np.asarray(a).nbytes)
            for a in (route.local_src, route.local_dst,
                      route.send_idx, route.recv_dst))
        out["rungs"][f"2^{log2}"] = {
            "total_rows": total,
            "build_s": round(dt, 1),
            "peak_rss_gb": round(_rss_gb(), 2),
            "rss_before_gb": round(rss0, 2),
            "table_bytes_gb": round(bytes_tables / 2**30, 3),
        }
        print(f"2^{log2}: build {dt:.1f}s, peak RSS {_rss_gb():.1f} GB, "
              f"tables {bytes_tables / 2**30:.2f} GB", flush=True)
        del route, table
    path = os.path.join(REPO, "bench_results", "routing_build.json")
    try:
        with open(path) as f:
            prior = json.load(f)
    except (OSError, json.JSONDecodeError):
        prior = {}
    prior[f"devs{n_dev}"] = out
    with open(path, "w") as f:
        json.dump(prior, f, indent=1)
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
