"""On-chip single-chip iterate at the BA-2^27 scale point (134.2M
rows / 1.07e9 nnz — the reference's "hundreds of millions of rows"
headline class, reference README.md:3) from the packed operator
exported by the ``rehearse_1e8_ba_step`` scale-ladder rung.

The offline half (generate 2^27 -> native decompose -> fold ->
export, ~2.2 h of host work) runs once in degraded mode; this tool is
the online half the tunnel watcher fires on heal: memmap-load the
packed SELL tiers, chunk-upload (~4.5 GB operator), bf16 feature
carriage (2 x 4.3 GB), donated scan — the measured HBM budget is in
the rung's ``rehearsal.json`` (~14 GB vs 16 GB v5e, which is why the
export uses the tight packing).

Prints ONE JSON line; nonzero exit when the chip is unreachable or
the export is missing/toy-sized.

Usage: PYTHONPATH=/root/repo:/root/.axon_site python tools/ba27_bench.py
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

EXPORT = os.environ.get(
    "AMT_BA27_EXPORT", os.path.join(REPO, "bench_cache", "ba27_fold"))


def main() -> None:
    meta_path = os.path.join(EXPORT, "meta.json")
    reh_path = os.path.join(EXPORT, "rehearsal.json")
    if not (os.path.exists(meta_path) and os.path.exists(reh_path)):
        print(json.dumps({"stage": "ba27", "error": "no export"}))
        raise SystemExit(2)
    with open(reh_path) as f:
        reh = json.load(f)
    if reh["n"] < (1 << 27) and not os.environ.get("AMT_BA27_ALLOW_SMALL"):
        print(json.dumps({"stage": "ba27", "error":
                          f"export is a logic-test toy (n={reh['n']})"}))
        raise SystemExit(2)

    if os.environ.get("AMT_BA27_FORCE_CPU"):
        # Logic-validation mode (tests): run the identical path on the
        # host backend instead of probing for an accelerator.
        from arrow_matrix_tpu.utils.platform import force_cpu_devices

        force_cpu_devices()
        platform, kind = "cpu(forced)", "host"
    else:
        from arrow_matrix_tpu.utils.platform import probe_default_backend

        platform, kind, err = probe_default_backend(timeout_s=120,
                                                    retries=1)
        if platform == "cpu":
            print(json.dumps({"stage": "ba27", "error":
                              f"no accelerator: {err}"}))
            raise SystemExit(3)

    import numpy as np

    from arrow_matrix_tpu.parallel.multi_level import MultiLevelArrow
    from arrow_matrix_tpu.utils.graphs import random_dense

    out = {"stage": "ba27", "platform": platform, "device_kind": kind,
           "n": reh["n"], "k": reh["k"], "feature_dtype": "bf16",
           "hbm_budget": reh.get("hbm_budget")}
    t0 = time.perf_counter()
    ml = MultiLevelArrow.load_folded(EXPORT, gather_budget=1 << 29)
    out["load_upload_s"] = round(time.perf_counter() - t0, 1)

    x = random_dense(reh["n"], reh["k"], seed=reh["x_seed"])
    t0 = time.perf_counter()
    xt = ml.set_features(x)
    del x
    out["set_features_s"] = round(time.perf_counter() - t0, 1)

    # One donated step, golden-gated against the rehearsal's scipy
    # sample (the offline run saved want = a[rows] @ x).
    rows = np.load(os.path.join(EXPORT, "sample_rows.npy"))
    want = np.load(os.path.join(EXPORT, "sample_out.npy"))
    t0 = time.perf_counter()
    y = ml.run(xt, 1, donate=True)
    got = np.asarray(y[:, ml.inv_perm0[rows]], dtype=np.float32).T
    out["first_step_s_inc_compile"] = round(time.perf_counter() - t0, 1)
    rel = float(np.linalg.norm(got - want) / np.linalg.norm(want))
    out["golden_sample_rel_err"] = round(rel, 6)
    if rel >= 2e-2:
        out["error"] = "golden gate failed"
        print(json.dumps(out))
        raise SystemExit(4)

    # Timed iterate: one scan dispatch, one small host fetch at the
    # end (tunnel-honest timing: block_until_ready without a fetch can
    # report impossible times over the ~70 ms RTT relay).  The first
    # length-iters donated run compiles that scan program (static n
    # differs from the n=1 golden step) — warm it, then time the
    # second invocation of the SAME compiled program.
    iters = int(os.environ.get("AMT_BA27_ITERS", 8))
    t0 = time.perf_counter()
    y = ml.run(y, iters, donate=True)
    _ = np.asarray(y[:, :128])
    out["warm_run_s_inc_compile"] = round(time.perf_counter() - t0, 1)
    t0 = time.perf_counter()
    y = ml.run(y, iters, donate=True)
    _ = np.asarray(y[:, :128])
    dt = time.perf_counter() - t0
    out["iters"] = iters
    out["ms_per_iter"] = round(dt / iters * 1000, 1)
    out["slots"] = int(ml.blocks[0].n_slots)
    out["slot_rate_g_per_s"] = round(
        ml.blocks[0].n_slots * iters / dt / 1e9, 2)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
