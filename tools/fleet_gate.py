#!/usr/bin/env python
"""Fleet chaos gate: kill-one-worker-of-N survival (graft-fleet).

The acceptance bar for the multi-process fleet, run as real spawned
worker processes through the ``graft_fleet`` CLI:

* **fleet_baseline** — N=2 workers, no faults: every request
  completes, every result is bit-identical to a fault-free
  single-process ArrowServer replay of the same deterministic trace,
  the merged pulse document is problem-free, and the report's fleet
  p99 EQUALS the nearest-rank pooled quantile over all workers' raw
  samples (recomputed here independently — no approximation).
* **fleet_kill** — N=3 workers with >=4 tenants in flight; one victim
  worker is armed (via its spawn environment only) with an
  ``AMT_FAULT_PLAN`` kill plan on ``*.step`` and SIGKILLs itself
  mid-batch.  Required outcome: the router buries exactly that worker
  after health probes, ZERO accepted requests are lost (everything
  not explicitly shed/rejected completes), at least one request was
  requeued onto a survivor, a survivor RESUMED the victim's
  checkpoint (the ``resumed request`` line in its log — replayed work
  is resumed, not recomputed), and every surviving result is
  bit-identical to the fault-free single-process replay.
* **fleet_host_kill** (graft-host) — N=4 workers in TWO host fault
  domains; mid-batch, EVERY worker of host-1 is SIGKILLed at once
  (``--kill_host``) and probed to a verdict through the heartbeat
  ladder.  Required outcome: exactly host-1's workers buried, zero
  accepted-request loss, requeue + checkpoint RESUME on a host-0
  survivor, every completed result bit-identical to the fault-free
  single-process replay, and the same-host shm wire demonstrably
  carried payload (``wire_shm_bytes > 0``).
* **router_quorum** (graft-host) — two shared-nothing routers over
  ONE spawned worker set: provably identical placement + FFD packing
  with no tenant double-admitted (``RouterQuorum.verify_agreement``),
  then one router dies mid-batch (``fail_router``) and its accepted
  requests fail over to the survivor with zero loss, results
  bit-identical to the fault-free replay.

Registered in tools/chaos_gate.py's matrix (subprocess scenarios skip
under ``--fast``, like serve_kill).  Standalone:
``python tools/fleet_gate.py [workdir]``.
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# Small enough for the CPU gate budget, big enough that a mid-batch
# SIGKILL leaves several accepted-but-unfinished requests to requeue.
N, WIDTH, K = 96, 16, 2
TENANTS, REQUESTS, ITERS = 5, 10, 4
SEED, TRACE_SEED = 11, 5
#: *.step hits before the armed worker SIGKILLs itself: late enough
#: that it accepted work, early enough that the work is unfinished.
KILL_AFTER = 6

#: Iterations for the host-kill scenario: long enough that a request
#: is mid-flight for many step+checkpoint cycles, so the router-side
#: domain SIGKILL reliably lands between a checkpoint save and the
#: request's completion (the resume-not-recompute window).
HOST_KILL_ITERS = 24


def _nearest_rank(vals, q):
    s = sorted(vals)
    return s[min(len(s) - 1, int(round(q * (len(s) - 1))))]


def _reference_results(workdir, k=K, iters=ITERS):
    """Fault-free single-process replay of the gate trace: the
    bit-identity reference the scenarios compare against."""
    from arrow_matrix_tpu.serve.loadgen import (
        ba_executor_factory,
        run_trace,
        synthetic_trace,
    )
    from arrow_matrix_tpu.serve.scheduler import ArrowServer, ExecConfig

    factory, n_rows = ba_executor_factory(N, WIDTH, SEED, fmt="fold")
    server = ArrowServer(factory, ExecConfig(), name="fleet-ref")
    trace = synthetic_trace(n_rows, tenants=TENANTS,
                            requests=REQUESTS, k=k, iterations=iters,
                            seed=TRACE_SEED)
    tickets = run_trace(server, trace)
    out = {}
    for t in tickets:
        if t.result is None:
            return None
        out[t.request.request_id] = t.result.tobytes()
    return out


def _run_fleet_cli(workdir, tag, workers, extra):
    """One ``graft_fleet`` subprocess run; returns
    (completed_process, verdict_dict_or_None, run_dir, npz_path)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("AMT_FAULT_PLAN", None)
    run_dir = os.path.join(workdir, f"fleet_{tag}")
    npz = os.path.join(workdir, f"fleet_{tag}.npz")
    cmd = [sys.executable, "-m", "arrow_matrix_tpu.cli.graft_fleet",
           "--run_dir", run_dir, "--workers", str(workers),
           "--vertices", str(N), "--width", str(WIDTH),
           "--seed", str(SEED), "--k", str(K),
           "--tenants", str(TENANTS), "--requests", str(REQUESTS),
           "--iterations", str(ITERS),
           "--trace_seed", str(TRACE_SEED),
           # Coarse pulse windows: on a loaded 1-core CI host the
           # 0.25 s default can idle-gap past the ring's bounded gap
           # fill and drop windows, which (correctly) fails the
           # pooled==streamed merge assertion for a reason that is
           # host speed, not fleet behavior.
           "--window_s", "2.0",
           "--results_npz", npz] + extra
    r = subprocess.run(cmd, env=env, cwd=workdir,
                       capture_output=True, text=True, timeout=900)
    verdict = None
    for line in reversed(r.stdout.strip().splitlines()):
        try:
            verdict = json.loads(line)
            break
        except json.JSONDecodeError:
            continue
    return r, verdict, run_dir, npz


def _check_bit_identity(tag, npz, ref, expect_ids=None):
    import numpy as np

    problems = []
    if not os.path.exists(npz):
        return [f"{tag}: no results npz written"]
    with np.load(npz) as got:
        ids = sorted(got.files)
        want = sorted(expect_ids if expect_ids is not None else ref)
        if ids != want:
            problems.append(f"{tag}: completed set {ids} != "
                            f"expected {want}")
        for rid in ids:
            if rid in ref and got[rid].tobytes() != ref[rid]:
                problems.append(
                    f"{tag}: request {rid} is not bit-identical to "
                    f"the fault-free single-process replay")
    return problems


def _check_exact_pooled_p99(tag, run_dir):
    """Recompute the pooled quantiles from the workers' RAW samples in
    fleet_report.json and require the report's merged latency to
    equal them exactly."""
    path = os.path.join(run_dir, "fleet_report.json")
    try:
        with open(path, encoding="utf-8") as fh:
            report = json.load(fh)
    except (OSError, json.JSONDecodeError) as e:
        return [f"{tag}: fleet_report.json unreadable: {e}"]
    samples = []
    for rec in (report.get("workers") or {}).values():
        if rec.get("alive"):
            samples.extend(rec.get("latency_samples_ms") or [])
    lat = report.get("latency_ms") or {}
    problems = []
    if len(samples) != lat.get("count"):
        problems.append(f"{tag}: merged latency count "
                        f"{lat.get('count')} != pooled sample count "
                        f"{len(samples)}")
        return problems
    if not samples:
        return [f"{tag}: no latency samples in the fleet report"]
    for q, field in ((0.5, "p50"), (0.9, "p90"), (0.99, "p99")):
        want = _nearest_rank(samples, q)
        if lat.get(field) != want:
            problems.append(
                f"{tag}: merged {field} {lat.get(field)!r} != exact "
                f"pooled quantile {want!r} over all workers' raw "
                f"samples")
    return problems


def _check_xray_artifacts(tag, run_dir):
    """graft-xray: every fleet run must leave ONE merged Perfetto
    trace (``fleet_xray.json``) and the per-class critical-path report
    next to its fleet report — the trace is a first-class run
    artifact, not a debug extra.  The kill scenario's truncated-track
    content checks live in tools/chaos_gate.py:scenario_xray_kill."""
    problems = []
    for name in ("fleet_xray.json", "xray_report.json"):
        if not os.path.isfile(os.path.join(run_dir, name)):
            problems.append(f"{tag}: {name} artifact missing")
    return problems


def scenario_fleet_baseline(workdir, ref):
    """No-fault fleet run: complete, bit-identical, exact quantiles,
    clean merged pulse."""
    r, verdict, run_dir, npz = _run_fleet_cli(workdir, "baseline", 2,
                                              [])
    if r.returncode != 0 or verdict is None:
        return [f"fleet_baseline: run failed rc={r.returncode}: "
                f"{r.stderr[-500:]}"]
    problems = []
    if verdict["completed"] != REQUESTS:
        problems.append(f"fleet_baseline: {verdict['completed']}/"
                        f"{REQUESTS} completed")
    if verdict["dead_workers"]:
        problems.append(f"fleet_baseline: unexpected deaths "
                        f"{verdict['dead_workers']}")
    if verdict["pulse_problems"]:
        problems.append(f"fleet_baseline: merged pulse problems: "
                        f"{verdict['pulse_problems']}")
    problems += _check_bit_identity("fleet_baseline", npz, ref)
    problems += _check_exact_pooled_p99("fleet_baseline", run_dir)
    problems += _check_xray_artifacts("fleet_baseline", run_dir)
    return problems


def scenario_fleet_kill(workdir, ref):
    """Kill-one-worker-of-N survival (the acceptance scenario)."""
    plan = json.dumps({"scenario": "kill", "site": "*.step",
                       "after": KILL_AFTER})
    r, verdict, run_dir, npz = _run_fleet_cli(
        workdir, "kill", 3,
        ["--fault_worker", "worker-1", "--fault_plan", plan])
    if r.returncode != 0 or verdict is None:
        return [f"fleet_kill: run failed rc={r.returncode}: "
                f"{r.stderr[-500:]}"]
    problems = []
    if verdict["dead_workers"] != ["worker-1"]:
        problems.append(f"fleet_kill: dead workers "
                        f"{verdict['dead_workers']} != ['worker-1'] "
                        f"(the armed victim, and only it)")
    accounted = (verdict["completed"] + verdict["failed"]
                 + verdict["shed"] + verdict["rejected"])
    if accounted != REQUESTS:
        problems.append(f"fleet_kill: {REQUESTS - accounted} "
                        f"request(s) LOST (no terminal state)")
    if verdict["failed"]:
        problems.append(f"fleet_kill: {verdict['failed']} request(s) "
                        f"failed instead of being requeued")
    shed_explicit = sum((verdict.get("shed_reasons") or {}).values())
    if shed_explicit != verdict["shed"] + verdict["rejected"]:
        problems.append(
            f"fleet_kill: {verdict['shed'] + verdict['rejected']} "
            f"shed/rejected but only {shed_explicit} carry an "
            f"explicit reason in the SLO report")
    if verdict["completed"] + shed_explicit != REQUESTS:
        problems.append(
            f"fleet_kill: zero-loss violated — "
            f"{verdict['completed']} completed + {shed_explicit} "
            f"explicitly shed != {REQUESTS} accepted")
    if verdict["requeues"] < 1:
        problems.append("fleet_kill: the victim died with no request "
                        "requeued — the kill landed outside the "
                        "in-flight window (retune KILL_AFTER)")
    # Survivors must RESUME the victim's checkpointed work, not
    # recompute it: the scheduler's resume line in a survivor log.
    resumed = False
    for wid in ("worker-0", "worker-2"):
        log = os.path.join(run_dir, wid, "worker.log")
        try:
            with open(log, encoding="utf-8") as fh:
                if "resumed request" in fh.read():
                    resumed = True
        except OSError:
            continue
    if not resumed:
        problems.append("fleet_kill: no survivor resumed a "
                        "checkpointed request (requeued work was "
                        "recomputed, not resumed)")
    # Bit-identity of every completed request vs the fault-free
    # single-process replay.
    with open(os.path.join(run_dir, "fleet_report.json"),
              encoding="utf-8") as fh:
        report = json.load(fh)
    completed_ids = sorted(t["request_id"] for t in report["tickets"]
                           if t["status"] == "completed")
    problems += _check_bit_identity("fleet_kill", npz, ref,
                                    expect_ids=completed_ids)
    problems += _check_exact_pooled_p99("fleet_kill", run_dir)
    problems += _check_xray_artifacts("fleet_kill", run_dir)
    return problems


def scenario_fleet_host_kill(workdir, ref):
    """Kill-a-host survival: both host-1 workers SIGKILLed AT ONCE
    mid-batch (graft-host acceptance).  Runs at ``K=4`` — a 96x4 f32
    request (1536 B) clears ``shm.SHM_MIN_BYTES``, so the same-host
    wire demonstrably carries payload via descriptors — and at
    ``HOST_KILL_ITERS`` iterations so the domain SIGKILL lands inside
    a checkpointed request; ``ref`` must be the matching replay."""
    r, verdict, run_dir, npz = _run_fleet_cli(
        workdir, "host_kill", 4,
        ["--hosts", "2", "--kill_host", "host-1", "--measure_wire",
         "--k", "4", "--iterations", str(HOST_KILL_ITERS)])
    if r.returncode != 0 or verdict is None:
        return [f"fleet_host_kill: run failed rc={r.returncode}: "
                f"{r.stderr[-500:]}"]
    problems = []
    domain = sorted((verdict.get("hosts") or {}).get("host-1") or [])
    if domain != ["worker-2", "worker-3"]:
        problems.append(f"fleet_host_kill: host-1 domain {domain} != "
                        f"['worker-2', 'worker-3'] (contiguous "
                        f"2-host split of 4 workers)")
    if sorted(verdict["dead_workers"]) != domain:
        problems.append(
            f"fleet_host_kill: buried {verdict['dead_workers']} != "
            f"the whole killed domain {domain} (and only it)")
    if "host-0" not in (verdict.get("live_hosts") or []) \
            or "host-1" in (verdict.get("live_hosts") or []):
        problems.append(f"fleet_host_kill: live hosts "
                        f"{verdict.get('live_hosts')} != ['host-0']")
    accounted = (verdict["completed"] + verdict["failed"]
                 + verdict["shed"] + verdict["rejected"])
    if accounted != REQUESTS:
        problems.append(f"fleet_host_kill: {REQUESTS - accounted} "
                        f"request(s) LOST (no terminal state)")
    if verdict["failed"]:
        problems.append(f"fleet_host_kill: {verdict['failed']} "
                        f"request(s) failed instead of requeueing")
    shed_explicit = sum((verdict.get("shed_reasons") or {}).values())
    if verdict["completed"] + shed_explicit != REQUESTS:
        problems.append(
            f"fleet_host_kill: zero-loss violated — "
            f"{verdict['completed']} completed + {shed_explicit} "
            f"explicitly shed != {REQUESTS} accepted")
    if verdict["requeues"] < 1:
        problems.append("fleet_host_kill: the domain died with no "
                        "request requeued — the kill landed outside "
                        "the in-flight window")
    resumed = False
    for wid in ("worker-0", "worker-1"):
        log = os.path.join(run_dir, wid, "worker.log")
        try:
            with open(log, encoding="utf-8") as fh:
                if "resumed request" in fh.read():
                    resumed = True
        except OSError:
            continue
    if not resumed:
        problems.append("fleet_host_kill: no host-0 survivor resumed "
                        "a checkpointed request (requeued work was "
                        "recomputed, not resumed)")
    # The same-host data plane must actually have carried payload via
    # shm descriptors, and the measured shm wire must be cheaper per
    # MB than the base64 envelope it replaces.
    if not verdict.get("wire_shm_bytes"):
        problems.append("fleet_host_kill: wire_shm_bytes == 0 — the "
                        "same-host shm data plane carried nothing")
    wm = verdict.get("wire_measured") or {}
    shm_ms = (wm.get("shm") or {}).get("serialize_ms_per_mb")
    b64_ms = (wm.get("base64") or {}).get("serialize_ms_per_mb")
    if shm_ms is None or b64_ms is None or shm_ms >= b64_ms:
        problems.append(f"fleet_host_kill: shm serialize "
                        f"{shm_ms} ms/MB is not cheaper than base64 "
                        f"{b64_ms} ms/MB")
    with open(os.path.join(run_dir, "fleet_report.json"),
              encoding="utf-8") as fh:
        report = json.load(fh)
    completed_ids = sorted(t["request_id"] for t in report["tickets"]
                           if t["status"] == "completed")
    problems += _check_bit_identity("fleet_host_kill", npz, ref,
                                    expect_ids=completed_ids)
    problems += _check_exact_pooled_p99("fleet_host_kill", run_dir)
    return problems


def scenario_router_quorum(workdir, ref):
    """Two shared-nothing routers over one worker set: provable
    placement agreement, no double-admit, router-death failover with
    zero accepted-request loss (graft-host acceptance)."""
    import dataclasses

    from arrow_matrix_tpu.fleet.host import (
        QuorumDisagreement,
        RouterQuorum,
    )
    from arrow_matrix_tpu.fleet.router import FleetRouter
    from arrow_matrix_tpu.serve.loadgen import synthetic_trace

    ckpt = os.path.join(workdir, "quorum_checkpoints")
    problems = []
    routerA = FleetRouter(spawn=3, hosts=1, vertices=N, width=WIDTH,
                          seed=SEED, fmt="fold", checkpoint_dir=ckpt,
                          name="quorumA")
    routerB = None
    try:
        clones = [dataclasses.replace(h, proc=None,
                                      meta=dict(h.meta))
                  for h in routerA.workers.values()]
        routerB = FleetRouter(handles=clones, vertices=N, width=WIDTH,
                              seed=SEED, fmt="fold",
                              checkpoint_dir=ckpt, name="quorumB")
        quorum = RouterQuorum({"A": routerA, "B": routerB})
        trace = synthetic_trace(routerA.n_rows, tenants=TENANTS,
                                requests=REQUESTS, k=K,
                                iterations=ITERS, seed=TRACE_SEED)
        tenants = sorted({r.tenant for r in trace})
        try:
            doc = quorum.verify_agreement(
                tenants, tenant_ks={t: K for t in tenants})
        except QuorumDisagreement as e:
            return [f"router_quorum: placement split between "
                    f"shared-nothing routers: {e}"]
        if not doc["agreed"] or doc["packing"] is None:
            problems.append(f"router_quorum: agreement doc "
                            f"incomplete: {doc}")
        tickets = [quorum.submit(r) for r in trace]
        moved = quorum.fail_router("B")
        if not moved:
            problems.append("router_quorum: router B died holding no "
                            "unfinished request — the failover window "
                            "was empty (retune the trace)")
        quorum.drain(timeout_s=300)
        summ = quorum.summary()
        if summ["lost_requests"]:
            problems.append(f"router_quorum: LOST requests after "
                            f"failover: {summ['lost_requests']}")
        if summ["status_counts"].get("completed", 0) != REQUESTS:
            problems.append(f"router_quorum: {summ['status_counts']} "
                            f"!= {REQUESTS} completed")
        results = quorum.results()
        for rid, ticket in sorted(results.items()):
            if ticket.result is None:
                problems.append(f"router_quorum: {rid} completed "
                                f"with no result array")
            elif rid in ref \
                    and ticket.result.tobytes() != ref[rid]:
                problems.append(
                    f"router_quorum: {rid} is not bit-identical to "
                    f"the fault-free single-process replay")
        del tickets
    finally:
        if routerB is not None:
            routerB.shutdown()
        routerA.shutdown()
    return problems


def run_fleet_scenarios(workdir, fast=False):
    """Run the fleet matrix; returns (problems, scenarios_run).
    Subprocess scenarios (all of them — the fleet IS processes) skip
    under ``--fast``, like serve_kill."""
    if fast:
        return [], []
    ref = _reference_results(workdir)
    ref4 = _reference_results(workdir, k=4, iters=HOST_KILL_ITERS)
    if ref is None or ref4 is None:
        return (["fleet reference: fault-free single-process replay "
                 "did not complete every request"], [])
    problems = []
    scenarios = ["fleet_baseline", "fleet_kill", "fleet_host_kill",
                 "router_quorum"]
    problems += scenario_fleet_baseline(workdir, ref)
    problems += scenario_fleet_kill(workdir, ref)
    problems += scenario_fleet_host_kill(workdir, ref4)
    problems += scenario_router_quorum(workdir, ref)
    return problems, scenarios


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    fast = "--fast" in argv
    argv = [a for a in argv if a != "--fast"]

    from arrow_matrix_tpu.utils.platform import force_cpu_devices

    force_cpu_devices(4)

    import tempfile

    from arrow_matrix_tpu import sync

    # Arm the lock-order witness before any router is constructed; the
    # worker subprocesses inherit AMT_LOCK_WITNESS from the
    # environment, so exporting it witnesses both sides of the fleet.
    registry = sync.enable_witness()

    workdir = argv[0] if argv else tempfile.mkdtemp(prefix="fleet_gate_")
    os.makedirs(workdir, exist_ok=True)
    problems, scenarios = run_fleet_scenarios(workdir, fast=fast)
    snap = registry.snapshot()
    if snap["violations"]:
        problems.extend(f"lock witness: {v}" for v in snap["violations"])
    print(f"fleet gate: lock witness — {snap['acquisitions']} "
          f"acquisitions, {len(snap['threads'])} threads, "
          f"{len(snap['observed_edges'])} observed edges, "
          f"{len(snap['violations'])} violations", file=sys.stderr)
    if problems:
        for p in problems:
            print(f"fleet gate: {p}", file=sys.stderr)
        print("fleet gate: FAILED", file=sys.stderr)
        return 1
    print(f"fleet gate: ok — scenarios {'+'.join(scenarios) or '(fast: skipped)'} "
          f"survived, zero loss, bit-identical ({workdir})",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
