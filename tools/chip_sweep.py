"""One-process format sweep on the live chip at protocol scale.

Races the single-chip execution configs (auto=ELL+platform heads, hyb,
and optionally dense/bf16 when they fit) over one cached decomposition,
printing ms/iter per config — the data that decides bench.py's default
format.  Run when the TPU tunnel is healthy:

    PYTHONPATH=/root/repo:/root/.axon_site python tools/chip_sweep.py [n]
"""

import os
import sys
import time

import numpy as np


def main() -> None:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 1 << 20
    m, width, k, iters = 8, 2048, 16, 10

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import jax

    jax.config.update("jax_default_matmul_precision", "highest")
    dev = jax.devices()[0]
    print(f"device: {dev.platform} {dev.device_kind}", flush=True)

    from bench import _cached_levels, _measure

    from arrow_matrix_tpu.decomposition.decompose import decomposition_spmm
    from arrow_matrix_tpu.parallel.multi_level import MultiLevelArrow
    from arrow_matrix_tpu.utils import numerics
    from arrow_matrix_tpu.utils.graphs import random_dense

    t0 = time.perf_counter()
    levels = _cached_levels(n, m, width, seed=7, max_levels=12)
    print(f"levels: {len(levels)} (setup {time.perf_counter() - t0:.1f}s)",
          flush=True)
    x_host = random_dense(n, k, seed=3)

    golden = decomposition_spmm(levels, x_host)
    nnz = sum(int(l.matrix.nnz) for l in levels)
    tol = numerics.relative_tolerance(nnz / max(n, 1), iters=1)

    configs = {
        "fold": dict(fmt="fold"),
        "hyb": dict(fmt="hyb"),
        "auto": dict(fmt="auto"),
        "ell_headflat": dict(fmt="ell", head_fmt="flat"),
        "ell_headgell": dict(fmt="ell", head_fmt="gell"),
        "hyb_bf16": dict(fmt="hyb", dtype="bf16"),
    }
    for name, kw in configs.items():
        try:
            t0 = time.perf_counter()
            multi = MultiLevelArrow(levels, width, mesh=None, **kw)
            build_s = time.perf_counter() - t0
            x = multi.set_features(x_host)
            ms = _measure(multi, x, iters)
            err = numerics.relative_error(
                multi.gather_result(multi.step(x)), golden)
            blk_gb = sum(b.device_nbytes()
                         for b in multi.blocks) / 2**30
            fmts = getattr(multi, "fmts", [])
            print(f"{name:14s} {ms:9.2f} ms/iter  err={err:.2e} "
                  f"(gate {tol:.0e})  blocks={blk_gb:.2f}GB "
                  f"build={build_s:.0f}s fmts={fmts}", flush=True)
            del multi, x
        except Exception as e:
            print(f"{name:14s} FAILED: {type(e).__name__}: {e}", flush=True)


if __name__ == "__main__":
    main()
