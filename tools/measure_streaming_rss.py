"""Measure peak host RSS of streamed vs eager decomposition ingestion.

Evidence for the streaming-loader claim (VERDICT r1 item 4): building
`MultiLevelArrow` from a memmapped artifact with the per-shard streaming
builder must keep peak host RSS well below the eager (whole-level
host-side packing) path.  Each variant runs in its own subprocess so
`ru_maxrss` isolates it.

Usage:  python tools/measure_streaming_rss.py [n_vertices]
Writes a human-readable comparison to stdout.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile

CHILD = r"""
import json, os, resource, sys
sys.path.insert(0, {repo!r})
from arrow_matrix_tpu.utils.platform import force_cpu_devices
force_cpu_devices(8)

from arrow_matrix_tpu.io.graphio import (as_levels, load_decomposition,
                                         load_level_widths)
from arrow_matrix_tpu.parallel.mesh import make_mesh
from arrow_matrix_tpu.parallel.multi_level import MultiLevelArrow

mode = {mode!r}
base = {base!r}
width = {width}
streamed = mode.endswith("streamed")
widths = load_level_widths(base, width)
loaded = load_decomposition(base, width, mem_map=streamed)
levels = as_levels(loaded, widths, materialize=not streamed)
mesh = make_mesh((8,), ("blocks",))
if mode.startswith("sell"):
    from arrow_matrix_tpu.parallel.sell_slim import SellMultiLevel

    ml = SellMultiLevel(levels, width, mesh, routing="a2a")
    dev_bytes = sum(o.device_nbytes() for o in ml.ops)
else:
    ml = MultiLevelArrow(levels, width, mesh=mesh, fmt="ell")
    dev_bytes = sum(b.device_nbytes() for b in ml.blocks)
peak_kb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
print(json.dumps({{"mode": mode, "peak_rss_mb": peak_kb / 1024,
                  "device_mb": dev_bytes / 2**20}}))
"""


def main() -> None:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 400_000
    width = 4096
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, repo)

    from arrow_matrix_tpu.decomposition.decompose import arrow_decomposition
    from arrow_matrix_tpu.io.graphio import save_decomposition
    from arrow_matrix_tpu.utils.graphs import barabasi_albert

    tmp = tempfile.mkdtemp(prefix="amt_rss_")
    base = os.path.join(tmp, "g")
    print(f"building artifact: n={n} width={width} ...", flush=True)
    a = barabasi_albert(n, 8, seed=1)
    levels = arrow_decomposition(a, arrow_width=width, max_levels=3,
                                 block_diagonal=True, seed=1,
                                 backend="auto")
    save_decomposition(levels, base)
    artifact_mb = sum(
        os.path.getsize(os.path.join(tmp, f))
        for f in os.listdir(tmp)) / 2**20
    print(f"artifact on disk: {artifact_mb:.0f} MB, "
          f"{len(levels)} levels", flush=True)

    results = {}
    for mode in ("streamed", "eager", "sell-streamed", "sell-eager"):
        code = CHILD.format(repo=repo, mode=mode, base=base, width=width)
        out = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, text=True, timeout=3600)
        if out.returncode != 0:
            print(f"{mode} FAILED:\n{out.stderr[-2000:]}")
            continue
        results[mode] = json.loads(out.stdout.strip().splitlines()[-1])
        r = results[mode]
        print(f"{mode:9s}: peak RSS {r['peak_rss_mb']:{8}.0f} MB "
              f"(device-resident {r['device_mb']:.0f} MB)", flush=True)

    for pre, label in (("", "stacked"), ("sell-", "sell")):
        if pre + "eager" in results and pre + "streamed" in results:
            saved = (results[pre + "eager"]["peak_rss_mb"]
                     - results[pre + "streamed"]["peak_rss_mb"])
            print(f"{label}: streaming saves {saved:.0f} MB of peak "
                  f"host RSS (artifact {artifact_mb:.0f} MB on disk)")


if __name__ == "__main__":
    main()
