"""Per-component timing of the multi-level SpMM step on the live chip.

Breaks the bench iteration into its constituent device programs — each
level's full arrow SpMM, that level's head/diag/col stacks separately,
and the inter-level routing gathers — so a slow iteration can be
attributed to a specific kernel (the reference's per-segment timing
philosophy, reference arrow/common/wb_logging.py, applied at kernel
granularity).

Timing goes through the shared ``obs/tracer.py:call_time_ms`` harness
(this script's former private ``timeit`` loop, promoted there), and
every probe is also sunk to a run-dir ledger with the live host load
attached, so an attribution taken on a loaded host is recognisable
after the fact.  Set ``AMT_PROFILE_LEDGER`` to choose the sink
directory (default: a timestamped ``bench_results/profile_runs/``
subdirectory — never the committed drift-gate store).

Usage:  python tools/profile_tpu.py [n] [width] [k]
"""

import functools
import os
import sys
import time

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from arrow_matrix_tpu.obs.tracer import call_time_ms

_LEDGER = None


def _ledger():
    """Lazy run-dir ledger sink (one per process)."""
    global _LEDGER
    if _LEDGER is None:
        from arrow_matrix_tpu.ledger.store import Ledger
        d = os.environ.get("AMT_PROFILE_LEDGER")
        if not d:
            d = os.path.join("bench_results", "profile_runs",
                             time.strftime("%Y%m%d-%H%M%S"))
        os.makedirs(d, exist_ok=True)
        _LEDGER = Ledger(d)
        print(f"ledger: {_LEDGER.path}", flush=True)
    return _LEDGER


def timeit(fn, *args, iters=5, name="call", **labels) -> float:
    """ms per call via the shared harness, sunk to the run ledger.

    ``host_load`` is left to the ledger's live lookup on purpose:
    these are load-SENSITIVE wall-clock probes, unlike the
    load-invariant lens ratios which pin it to None.
    """
    ms = call_time_ms(fn, *args, iters=iters)
    _ledger().record(
        "probe", "call_time_ms", ms, unit="ms",
        knobs={"call": name, "iters": iters,
               **{k: v for k, v in labels.items() if v is not None}})
    return ms


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 1 << 20
    width = int(sys.argv[2]) if len(sys.argv) > 2 else 2048
    k = int(sys.argv[3]) if len(sys.argv) > 3 else 16

    from arrow_matrix_tpu.ops.arrow_blocks import (
        arrow_spmm,
        block_spmm,
        block_spmm_shared,
        head_block_spmm,
    )
    from arrow_matrix_tpu.parallel.multi_level import (
        MultiLevelArrow,
        gather_budget_for,
        resolve_chunk,
    )
    from arrow_matrix_tpu.utils.graphs import random_dense
    from arrow_matrix_tpu.utils.platform import device_memory_budget

    dev = jax.devices()[0]
    print(f"device: {dev.platform} {dev.device_kind}", flush=True)

    # Cached, CONVERGED decomposition — the same problem bench.py runs
    # (a max_levels cap would re-create the degenerate-last-level
    # pathology the bench no longer executes; see PERFORMANCE.md).
    from bench import _cached_levels

    t0 = time.perf_counter()
    levels = _cached_levels(n, 8, width, seed=7, max_levels=12)
    print(f"{n} rows -> {len(levels)} levels "
          f"in {time.perf_counter() - t0:.1f}s", flush=True)

    budget = device_memory_budget(dev)
    fmt = os.environ.get("AMT_PROFILE_FMT", "auto")
    if fmt in ("sell", "sell-space"):
        # Feature-major mesh orchestrations: per-level step attribution
        # (one shard_map'd slim step each) + full chained step.  Mesh
        # from AMT_PROFILE_DEVICES (default: all).
        from arrow_matrix_tpu.parallel import (
            SellMultiLevel,
            SellSpaceShared,
            make_mesh,
        )

        n_dev = int(os.environ.get("AMT_PROFILE_DEVICES",
                                   len(jax.devices())))
        x_host = random_dense(n, k, seed=3)
        if fmt == "sell":
            sm = SellMultiLevel(levels, width,
                                make_mesh((n_dev,), ("blocks",)),
                                routing="a2a")
            print(f"sell/a2a on {n_dev} devices; "
                  f"total_out={sm.ops[0].total_out}", flush=True)
            from arrow_matrix_tpu.parallel.sell_slim import (
                make_sharded_step,
            )

            x = sm.set_features(x_host)
            print(f"full step: "
                  f"{timeit(sm.step, x, name='full_step', fmt='sell'):.1f}"
                  f" ms", flush=True)
            steps = [make_sharded_step(sm.mesh, sm.axis, width,
                                       o.rows_out, hops=o.hops,
                                       rem=o.rem)
                     for o in sm.ops]
            for i, (o, st) in enumerate(zip(sm.ops, steps)):
                f = jax.jit(st)
                ms_i = timeit(f, o.body, o.head, o.head_unsort,
                              o.orig_pos, x[:, :o.total_out],
                              name=f"level{i}", fmt="sell")
                print(f"level {i}: hops={o.hops} rows_out={o.rows_out} "
                      f"{ms_i:.2f} ms", flush=True)
        else:
            K = len(levels)
            sp = SellSpaceShared(levels, width,
                                 make_mesh((K, max(n_dev // K, 1)),
                                           ("lvl", "blocks")))
            x = sp.set_features(x_host)
            print(f"sell/space on ({K},{max(n_dev // K, 1)}) mesh: "
                  f"full step "
                  f"{timeit(sp.step, x, name='full_step', fmt='sell-space'):.1f}"
                  f" ms", flush=True)
        return
    multi = MultiLevelArrow(levels, width, mesh=None, fmt=fmt,
                            dense_budget=budget)
    print(f"fmts: {multi.fmts}  total_rows: {multi.total_rows}", flush=True)

    x_host = random_dense(n, k, seed=3)
    x = multi.set_features(x_host)

    ms = timeit(multi.step, x, name="full_step", fmt=fmt)
    print(f"full step: {ms:.1f} ms", flush=True)

    if fmt == "fold":
        # Per-tier attribution of the folded SELL operator.
        from arrow_matrix_tpu.ops.ell import auto_chunk, ell_spmm_t
        from arrow_matrix_tpu.parallel.multi_level import gather_budget_for

        sell = multi.blocks[0]
        gb = gather_budget_for(multi.dense_budget)
        for t, cols in enumerate(sell.cols):
            m_t, n_t = cols.shape
            if m_t == 0:
                print(f"tier {t}: m=0 n={n_t} (zero-degree rows)",
                      flush=True)
                continue
            chunk = auto_chunk(n_t, k, m_t, gb)
            f = jax.jit(lambda c, dg, xx, ch=chunk: ell_spmm_t(
                c, xx, deg=dg, chunk=ch))
            ms_t = timeit(f, cols, sell.deg[t], x,
                          name=f"tier{t}", fmt="fold")
            print(f"tier {t}: m={m_t} n={n_t} slots={m_t * n_t} "
                  f"{ms_t:.2f} ms ({m_t * n_t / ms_t / 1e3:.0f}M slots/s)",
                  flush=True)
        return

    total = multi.total_rows
    gather_budget = gather_budget_for(multi.dense_budget)
    for i, blk in enumerate(multi.blocks):
        w = multi.widths[i]
        xb = jnp.reshape(x, (total // w, w, k))
        chunk = resolve_chunk("auto", blk, total, k, gather_budget)
        lvl_ms = timeit(jax.jit(functools.partial(arrow_spmm, chunk=chunk)),
                        blk, xb, name=f"level{i}_full", fmt=blk.fmt)
        if blk.head_gell:
            from arrow_matrix_tpu.ops.ell import ell_spmm

            head_ms = timeit(
                jax.jit(lambda b, xx, c=chunk: ell_spmm(
                    b.head_cols, b.head_data,
                    xx.reshape(-1, xx.shape[-1]), chunk=c,
                    deg=b.head_deg)), blk, xb,
                name=f"level{i}_head", fmt=blk.fmt)
        else:
            head_ms = timeit(
                jax.jit(functools.partial(head_block_spmm, chunk=chunk)),
                blk, xb, name=f"level{i}_head", fmt=blk.fmt)
        diag_ms = timeit(
            jax.jit(lambda b, xx, c=chunk: block_spmm(
                b.fmt, b.diag_cols, b.diag_data, xx, chunk=c,
                deg=b.diag_deg)), blk, xb,
            name=f"level{i}_diag", fmt=blk.fmt)
        col_ms = timeit(
            jax.jit(lambda b, xx, c=chunk: block_spmm_shared(
                b.fmt, b.col_cols, b.col_data, xx[0], chunk=c,
                deg=b.col_deg)), blk, xb,
            name=f"level{i}_col", fmt=blk.fmt)
        nnz = int(levels[i].matrix.nnz)
        head_kind = ("gell" if blk.head_gell
                     else "flat" if blk.head_flat else blk.fmt)
        print(f"level {i}: fmt={blk.fmt} w={w} head={head_kind} "
              f"nnz={nnz} full={lvl_ms:.1f}ms head={head_ms:.1f}ms "
              f"diag={diag_ms:.1f}ms col={col_ms:.1f}ms", flush=True)

    if len(multi.blocks) > 1:
        fwd = multi.fwd
        take_ms = timeit(jax.jit(lambda xx, t: jnp.take(xx, t, axis=0)),
                         x, fwd[0], name="routing_gather")
        print(f"routing gather (one exchange): {take_ms:.1f} ms", flush=True)


if __name__ == "__main__":
    main()
