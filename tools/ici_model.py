"""ICI-bandwidth-parameterized mode model (VERDICT r2 item 8).

The 8-virtual-device wall-clock races run on one host core, so their
ms/iter cannot decide time-vs-space or sell-vs-stacked for a real ICI
mesh.  What IS trustworthy off-chip: the per-iteration collective
bytes and counts read from compiled/lowered HLO (utils/commstats) and
the per-chip gather rate measured on the real chip (~95-101M
slots/s, PERFORMANCE.md).  This tool combines them into a predicted
per-iteration time as a function of ICI bandwidth and collective
launch latency:

    T_mode(bw, lat) = compute_ms(mode) + bytes(mode)/bw + n_coll(mode)*lat

  * compute_ms — padded gather slots through the measured per-chip
    gather rate; time-shared runs every level on all n_dev chips
    (sum of levels / n_dev), space-shared runs levels concurrently on
    n_dev/K chips each (max level / (n_dev/K)).
  * bytes/bw — collective payload over the per-chip ICI bandwidth.
  * n_coll*lat — each collective pays a launch/sync latency.  Both
    modes charge their full HLO-accounted op count: the time-shared
    program emits K sequential per-level collectives (K ops), while
    the space-shared program emits ONE K-replica-group op per
    exchange — the K-way overlap is already baked into its (smaller)
    count, so no further overlap factor applies.

Printed: the predicted table at v5e parameters and the crossover
sweep — the (bw, lat) region where each mode wins.  Run with real
chips attached (AMT_RACE_REAL=1) to confirm with measured wall-clock.

Usage: PYTHONPATH=/root/repo python tools/ici_model.py [n_vertices]
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from arrow_matrix_tpu.utils.platform import backend_initialized, force_cpu_devices  # noqa: E402

if not backend_initialized() and os.environ.get("AMT_RACE_REAL") != "1":
    force_cpu_devices(8)

import numpy as np  # noqa: E402
import jax  # noqa: E402

from arrow_matrix_tpu.decomposition.decompose import arrow_decomposition  # noqa: E402
from arrow_matrix_tpu.parallel.mesh import make_mesh  # noqa: E402
from arrow_matrix_tpu.parallel.sell_slim import SellMultiLevel  # noqa: E402
from arrow_matrix_tpu.parallel.sell_space import SellSpaceShared  # noqa: E402
from arrow_matrix_tpu.utils import commstats  # noqa: E402
from arrow_matrix_tpu.utils.graphs import barabasi_albert, random_dense  # noqa: E402

#: Measured on the v5e chip this framework benches on (PERFORMANCE.md):
#: the composed SELL operator streams ~101M padded slots/s; the
#: standalone probe ~95M.  Conservative choice: the probe.
GATHER_ROWS_PER_S = 95e6

#: Public per-chip ICI figures (GB/s, one direction, all links) for
#: the sweep's named points; the model is a function of bw, these just
#: label interesting abscissae.
ICI_POINTS = {"v5e (3 links x ~45GB/s)": 135.0,
              "v4/v5p-class": 270.0,
              "DCN-ish": 25.0,
              "slow DCN": 5.0}

#: Collective launch/sync latency sweep (seconds): ICI collectives on
#: TPU are ~1-10us; DCN-crossing ones 100us+.
LATENCIES_US = (1.0, 10.0, 100.0)


def mode_inputs(n: int, k: int = 16, width: int = 256):
    """(per-level padded slots, per-mode collective bytes+counts) at
    one config, from the real builders and the lowered HLO."""
    n_dev = len(jax.devices())
    a = barabasi_albert(n, 8, seed=7)
    levels = arrow_decomposition(a, width, max_levels=4,
                                 block_diagonal=True, seed=7)
    K = len(levels)
    x = random_dense(n, k, seed=3)

    sm = SellMultiLevel(levels, width, make_mesh((n_dev,), ("blocks",)),
                        routing="a2a")
    # Per-level padded slots from the SELL growth bound: padded slots
    # <= growth (1.2) x nnz (ops/sell.py tiering invariant) — the
    # gather cost model's work term per level.
    slots = [int(1.2 * lvl.matrix.nnz) for lvl in levels]

    def totals(stats) -> tuple:
        count = sum(v["count"] for key, v in stats.items()
                    if isinstance(v, dict))
        return stats["total_bytes"], count

    xt = sm.set_features(x)
    out = {"K": K, "n_dev": n_dev, "slots": slots,
           "time": totals(commstats.collective_stats(
               sm.step_fn, xt, *sm.step_operands()))}
    if n_dev % K == 0:
        sp = SellSpaceShared(levels, width,
                             make_mesh((K, n_dev // K),
                                       ("lvl", "blocks")))
        xp = sp.set_features(x)
        out["space"] = totals(commstats.collective_stats(
            sp.step_fn, xp, *sp.step_operands()))
    return out


def predict_ms(slots, n_dev, K, bytes_, n_coll, bw_gbps, lat_s,
               space: bool) -> float:
    if space:
        compute = max(slots) / (n_dev / K) / GATHER_ROWS_PER_S
        # The HLO count ALREADY embodies the K-way overlap: the
        # space-shared shard_map lowers each cross-level exchange to
        # ONE collective op with K replica groups (sell_space.py), so
        # commstats counts it once — n_coll IS the per-device
        # serialized chain length.  Dividing by K here would charge
        # 1/K of the real launch latency (ADVICE r3 asked for either
        # the division or a docstring fix; the division double-counts,
        # so the docstring carries the model instead).
        serial_coll = n_coll
    else:
        compute = sum(slots) / n_dev / GATHER_ROWS_PER_S
        serial_coll = n_coll           # per-level collectives serialize
    comm = bytes_ / (bw_gbps * 1e9)
    return (compute + comm + serial_coll * lat_s) * 1e3


def main() -> None:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 1 << 14
    mi = mode_inputs(n)
    K, n_dev, slots = mi["K"], mi["n_dev"], mi["slots"]
    print(f"config: n={n} K={K} n_dev={n_dev} "
          f"slots/level={['%.2g' % s for s in slots]}")
    tb, tc = mi["time"]
    print(f"time-shared sell/a2a: {tb:,} B/iter over {tc} collectives")
    if "space" not in mi:
        print(f"(space-shared skipped: {n_dev} devices not divisible "
              f"by K={K})")
        return
    sb, sc = mi["space"]
    print(f"space-shared sell:    {sb:,} B/iter over {sc} collectives")
    print()
    print(f"{'ICI point':28} {'lat us':>7} {'time ms':>9} "
          f"{'space ms':>9}  winner")
    for name, bw in ICI_POINTS.items():
        for lat in LATENCIES_US:
            t = predict_ms(slots, n_dev, K, tb, tc, bw, lat * 1e-6,
                           space=False)
            s = predict_ms(slots, n_dev, K, sb, sc, bw, lat * 1e-6,
                           space=True)
            print(f"{name:28} {lat:7.0f} {t:9.3f} {s:9.3f}  "
                  f"{'time' if t <= s else 'SPACE'}")
    # Crossover condition, symbolically: space wins iff its
    # concurrency saving on per-level compute outweighs its K-fold
    # worse per-chip compute share:
    #   sum(w)/n  vs  K*max(w)/n  -> time-shared's compute never
    # loses when levels are balanced; space-shared can only win on
    # LATENCY (fewer serialized per-level collectives) or when K
    # shrinks per-level work below the collective launch floor.
    lat_floor = (max(slots) / (n_dev / K) - sum(slots) / n_dev) \
        / GATHER_ROWS_PER_S
    print()
    print(f"compute handicap of space-sharing at this shape: "
          f"{lat_floor * 1e3:.3f} ms/iter — space-shared wins only "
          f"where serialized collective latency exceeds this "
          f"(e.g. {tc - sc} extra launches x >"
          f"{lat_floor * 1e6 / max(tc - sc, 1):.0f} us each: "
          f"DCN-class links or sub-ms levels)")
    repl_sweep(n, mi)


def repl_sweep(n: int, mi: dict, k: int = 16) -> None:
    """2.5D replication crossover (graft-repl): T(c) = compute +
    bytes/(c*bw) + n_coll*lat + reduce(c)/bw over the named ICI
    points, with bytes/n_coll from the c=1 lowered HLO.  Replication
    divides only the wire term — the crossover is where the exchange
    stops dominating the latency floor and the amortized final merge,
    which is exactly what ``obs.comm.auto_repl`` minimizes (subject
    to its HBM-budget certificate)."""
    from arrow_matrix_tpu.obs.comm import auto_repl, repl_predict_ms

    K, n_dev, slots = mi["K"], mi["n_dev"], mi["slots"]
    tb, tc = mi["time"]
    compute_ms = sum(slots) / n_dev / GATHER_ROWS_PER_S * 1e3
    # Per-device final-merge payload: the carried output slab.
    reduce_bytes = -(-n // n_dev) * k * 4
    iters = 10  # merge amortized over a representative carried run
    print()
    print("2.5D replication sweep (time-shared sell/a2a step, "
          f"merge amortized over {iters} iters):")
    print(f"{'ICI point':28} {'lat us':>7} "
          + "".join(f"{f'c={c} ms':>10}" for c in (1, 2, 4))
          + "  chosen c")
    for name, bw in ICI_POINTS.items():
        for lat in LATENCIES_US:
            t_c = [repl_predict_ms(c, tb, n_coll=tc,
                                   compute_ms=compute_ms,
                                   reduce_bytes=reduce_bytes,
                                   iterations=iters,
                                   link_bytes_per_s=bw * 1e9,
                                   latency_s=lat * 1e-6)
                   for c in (1, 2, 4)]
            plan = auto_repl(n_dev, k, base_hbm_bytes=0,
                             exchange_bytes=tb, n_coll=tc,
                             compute_ms=compute_ms,
                             reduce_bytes=reduce_bytes,
                             iterations=iters,
                             link_bytes_per_s=bw * 1e9,
                             latency_s=lat * 1e-6, quiet=True)
            print(f"{name:28} {lat:7.0f} "
                  + "".join(f"{t:10.3f}" for t in t_c)
                  + f"  c={plan['c']}")


if __name__ == "__main__":
    main()
