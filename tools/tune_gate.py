#!/usr/bin/env python
"""CI gate over the graft-tune plan cache.

Replays every cached TunePlan (or the ``--hash`` selection) against
its recorded source and exits nonzero if any plan lost bit-identity
vs the golden fold path, regressed more than ``--rel-tol`` (default
5%) vs the default configuration, fails the hash/version integrity
check, or if a search on the unchanged structure is not a pure cache
hit (zero bench children).  ``--refresh`` re-searches each structure
before checking.

Usage:
    python tools/tune_gate.py                       # gate bench_cache/tune_plans
    python tools/tune_gate.py --plan-dir DIR --refresh
    python tools/tune_gate.py --hash 0123abcd...    # one structure
"""

import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--plan-dir", default=None,
                    help="plan cache directory (default "
                         "bench_cache/tune_plans, or $AMT_TUNE_PLAN_DIR)")
    ap.add_argument("--hash", action="append", default=None,
                    help="gate only this structure hash (repeatable)")
    ap.add_argument("--iters", type=int, default=3)
    ap.add_argument("--repeats", type=int, default=3,
                    help="timing replays per side; min is compared")
    ap.add_argument("--rel-tol", type=float, default=0.05)
    ap.add_argument("--abs-tol-ms", type=float, default=0.25)
    ap.add_argument("--refresh", action="store_true",
                    help="re-search each structure before gating")
    ap.add_argument("--no-timing", action="store_true",
                    help="skip the regression replay (identity + "
                         "cache checks only)")
    ap.add_argument("--quiet", action="store_true")
    args = ap.parse_args(argv)

    from arrow_matrix_tpu.tune.gate import run_gate

    return run_gate(directory=args.plan_dir, hashes=args.hash,
                    iters=args.iters, repeats=args.repeats,
                    rel_tol=args.rel_tol, abs_tol_ms=args.abs_tol_ms,
                    refresh=args.refresh, timing=not args.no_timing,
                    quiet=args.quiet)


if __name__ == "__main__":
    sys.exit(main())
