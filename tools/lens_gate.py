#!/usr/bin/env python
"""Tier-1 lens gate: validate the committed graft-lens calibration.

Default mode is pure document validation (no kernels run): load the
committed ba_256_3 profile + fitted cost model from
``bench_results/lens/`` and re-run ``obs/lens.py:check_profile`` —
schema drift, per-level attribution failing to cover the measured
iteration (|1-cov| > 0.10), or any measured/predicted ratio outside
[0.5, 2.0] fails the push.  The profile/model pair must also agree on
the structure hash: a model fitted against a different structure is
exactly the silent miscalibration this gate exists to catch.

Unlike tools/kernel_gate.py's ``--fixture`` (which verifies a planted
fixture TRIPS its rule and exits nonzero when it does NOT), this
gate's ``--fixture`` treats the fixture as real calibration data: a
planted miscalibration therefore EXITS NONZERO.  ``--fixtures`` is
the detection-loss check — it runs every shipped fixture and fails
if any of them passes clean.

Usage:
  python tools/lens_gate.py                 check the committed
                                            profile + model
  python tools/lens_gate.py --refresh       re-profile ba_256_3
                                            (k=64, f32+bf16), rewrite
                                            the committed artifacts,
                                            append kind='lens' ledger
                                            records, rebaseline
  python tools/lens_gate.py --fixture F     check a fixture JSON
                                            ({"profile": .., "model":
                                            ..}) as real data; a
                                            planted miscalibration
                                            exits nonzero
  python tools/lens_gate.py --fixtures      verify every shipped
                                            tests/fixtures/lens/
                                            fixture trips the check
  python tools/lens_gate.py --selftest      synthetic profile/model
                                            round trip: clean passes,
                                            perturbed trips (host
                                            only, no jax execution)
"""

import argparse
import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LENS_DIR = os.path.join(REPO, "bench_results", "lens")
PROFILE_PATH = os.path.join(LENS_DIR, "ba_256_3_profile.json")
MODEL_PATH = os.path.join(LENS_DIR, "ba_256_3_model.json")
#: graft-synth calibration: the SAME structure profiled under its
#: synthesized per-level schedule, fitted on the scheduled width-family
#: keys (``pallas:fam@rbN``) — the tune screen's pricing for generated
#: candidates.  Committed alongside the menu calibration.
SYNTH_PROFILE_PATH = os.path.join(LENS_DIR,
                                  "ba_256_3_synth_profile.json")
SYNTH_MODEL_PATH = os.path.join(LENS_DIR, "ba_256_3_synth_model.json")
FIXTURE_DIR = os.path.join(REPO, "tests", "fixtures", "lens")

#: The committed calibration point: the same deterministic BA 256/3
#: seed-0 width-32 decomposition tests/conftest.py regenerates.
BA_256_3_SOURCE = {"kind": "ba", "n": 256, "m": 3, "width": 32,
                   "seed": 0, "max_levels": 10}
REFRESH_K = 64
REFRESH_ATTEMPTS = 3


def _load(path):
    with open(path, encoding="utf-8") as fh:
        return json.load(fh)


def check_pair(profile: dict, model_doc: dict) -> list:
    """Problems for one profile+model pair: the lens check itself plus
    the cross-document structure-hash agreement."""
    from arrow_matrix_tpu.obs import lens
    from arrow_matrix_tpu.obs.costmodel import CostModel

    try:
        model = CostModel.from_dict(model_doc)
    except (ValueError, KeyError, TypeError) as e:
        return [f"cost model unreadable: {e}"]
    problems = lens.check_profile(profile, model)
    ph = str(profile.get("structure_hash", ""))
    if ph and model.structure_hash and ph != model.structure_hash:
        problems.append(
            f"structure hash mismatch: profile {ph} vs model "
            f"{model.structure_hash}")
    return problems


def run_fixture(path: str) -> int:
    doc = _load(path)
    problems = check_pair(doc["profile"], doc["model"])
    for p in problems:
        print(f"lens gate: {os.path.basename(path)}: {p}",
              file=sys.stderr)
    return 1 if problems else 0


def run_fixtures() -> int:
    paths = sorted(glob.glob(os.path.join(FIXTURE_DIR, "*.json")))
    if not paths:
        print("lens gate: no fixtures found", file=sys.stderr)
        return 1
    rc = 0
    for path in paths:
        if run_fixture(path) == 0:
            print(f"lens gate: FIXTURE {os.path.basename(path)} "
                  f"PASSED CLEAN — the lens check lost a detection",
                  file=sys.stderr)
            rc = 1
    if rc == 0:
        print(f"lens gate: {len(paths)} fixture(s) trip the check",
              file=sys.stderr)
    return rc


def selftest() -> int:
    """Host-only round trip: a self-consistent synthetic profile fits
    and checks clean; scaling one tier's measured time 5x trips the
    ratio band; shrinking the tier sum trips coverage."""
    import copy

    from arrow_matrix_tpu.obs import lens

    tiers = [
        {"tier": 0, "family": "xla:tail", "rows": 200, "nnz": 900,
         "slots": 1600, "slot_width": 8, "padded_slots": 700,
         "streamed_bytes": 409600, "measured_ms": 0.06},
        {"tier": 1, "family": "xla:mid", "rows": 100, "nnz": 1200,
         "slots": 1600, "slot_width": 16, "padded_slots": 400,
         "streamed_bytes": 409600, "measured_ms": 0.04},
    ]
    profile = {
        "schema": lens.LENS_PROFILE_SCHEMA, "kind": "lens_profile",
        "structure_hash": "selftest", "platform": "cpu",
        "device_kind": "cpu", "width": 32, "k": 64, "kernel": "xla",
        "iters": 100, "kernel_opts": {}, "n": 300,
        "dtypes": {"f32": {
            "full_ms": 0.1, "chain_floor_ms": 0.001,
            "resolution_ms": 0.005, "attributed_ms": 0.1,
            "coverage": 1.0, "tiers": tiers, "dma_wait_ms": {}}},
    }
    model = lens.fit_from_profile(profile)
    clean = lens.check_profile(profile, model)
    if clean:
        print(f"lens gate selftest: clean profile reported problems: "
              f"{clean}", file=sys.stderr)
        return 1
    bad_ratio = copy.deepcopy(profile)
    bad_ratio["dtypes"]["f32"]["tiers"][0]["measured_ms"] *= 5.0
    if not any("ratio" in p
               for p in lens.check_profile(bad_ratio, model)):
        print("lens gate selftest: 5x tier did not trip the ratio "
              "band", file=sys.stderr)
        return 1
    bad_cov = copy.deepcopy(profile)
    bad_cov["dtypes"]["f32"]["attributed_ms"] = 0.05
    bad_cov["dtypes"]["f32"]["coverage"] = 0.5
    if not any("cover" in p for p in lens.check_profile(bad_cov)):
        print("lens gate selftest: half coverage did not trip",
              file=sys.stderr)
        return 1
    print("lens gate: selftest ok", file=sys.stderr)
    return 0


def refresh(ledger_dir=None) -> int:
    """Re-profile the committed calibration point and rewrite the
    artifacts + ledger records + baseline.  Retries the measurement a
    few times and only commits a profile that passes its own check —
    a noisy host must not be able to commit a miscalibrated model."""
    from arrow_matrix_tpu.obs import lens
    from arrow_matrix_tpu.tune.search import load_levels_from_source
    from arrow_matrix_tpu.utils.artifacts import atomic_write_json

    import numpy as np

    from arrow_matrix_tpu.tune import synth as synthmod
    from arrow_matrix_tpu.tune.fingerprint import structure_fingerprint

    levels, width = load_levels_from_source(BA_256_3_SOURCE)
    fp = structure_fingerprint(levels, width, np.float32)
    sched = synthmod.synthesize_schedule(fp)
    jobs = [
        ("menu", PROFILE_PATH, MODEL_PATH,
         dict(kernel="auto", feature_dtypes=("f32", "bf16"),
              iters=100)),
        # The graft-synth point: the same structure run under its
        # synthesized per-level schedule — the fit lands on the
        # scheduled width-family keys (pallas:fam@rbN).
        ("synth", SYNTH_PROFILE_PATH, SYNTH_MODEL_PATH,
         dict(kernel="pallas", feature_dtypes=("f32",), iters=100,
              kernel_opts={"schedule": sched})),
    ]
    os.makedirs(LENS_DIR, exist_ok=True)
    ids = []
    for label, ppath, mpath, kwargs in jobs:
        profile = model = problems = None
        for attempt in range(REFRESH_ATTEMPTS):
            profile = lens.profile_fold(levels, width, REFRESH_K,
                                        **kwargs)
            model = lens.fit_from_profile(profile)
            problems = lens.check_profile(profile, model)
            if not problems:
                break
            print(f"lens gate: {label} refresh attempt {attempt + 1} "
                  f"unclean: {problems}", file=sys.stderr)
        if problems:
            print(f"lens gate: {label} refresh could not produce a "
                  f"clean profile", file=sys.stderr)
            return 1
        atomic_write_json(ppath, profile, indent=2, sort_keys=True)
        atomic_write_json(mpath, model.to_dict(), indent=2,
                          sort_keys=True)
        ids += lens.record_profile(profile, model,
                                   directory=ledger_dir)
    from arrow_matrix_tpu.ledger.gate import main as ledger_main
    rc = ledger_main(["--rebaseline"]
                     + (["--ledger-dir", ledger_dir]
                        if ledger_dir else []))
    if rc != 0:
        print("lens gate: ledger rebaseline failed", file=sys.stderr)
        return rc
    print(f"lens gate: refreshed {PROFILE_PATH} + model, "
          f"{len(ids)} ledger record(s)", file=sys.stderr)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--refresh", action="store_true",
                    help="re-profile ba_256_3 and rewrite the "
                         "committed artifacts + ledger + baseline")
    ap.add_argument("--ledger-dir", default=None,
                    help="with --refresh: sink records here instead "
                         "of the committed store")
    ap.add_argument("--fixture", action="append", default=[],
                    help="check this profile+model fixture as real "
                         "data (a planted miscalibration exits "
                         "nonzero; repeatable)")
    ap.add_argument("--fixtures", action="store_true",
                    help="verify every shipped lens fixture trips "
                         "the check")
    ap.add_argument("--selftest", action="store_true",
                    help="synthetic round trip, no jax execution")
    ap.add_argument("--profile", default=PROFILE_PATH,
                    help="profile JSON to check (default: committed)")
    ap.add_argument("--model", default=MODEL_PATH,
                    help="model JSON to check (default: committed)")
    args = ap.parse_args(argv)

    if args.selftest:
        return selftest()
    if args.fixtures:
        return run_fixtures()
    if args.fixture:
        rc = 0
        for path in args.fixture:
            rc |= run_fixture(path)
        return rc
    if args.refresh:
        return refresh(ledger_dir=args.ledger_dir)

    pairs = [(args.profile, args.model, False)]
    if args.profile == PROFILE_PATH and args.model == MODEL_PATH:
        # Checking the committed calibration covers BOTH committed
        # pairs: the menu point and the graft-synth scheduled point.
        pairs.append((SYNTH_PROFILE_PATH, SYNTH_MODEL_PATH, True))
    problems = []
    for ppath, mpath, is_synth in pairs:
        missing = [p for p in (ppath, mpath) if not os.path.isfile(p)]
        if missing:
            for path in missing:
                print(f"lens gate: missing committed artifact {path} "
                      f"— run `python tools/lens_gate.py --refresh`",
                      file=sys.stderr)
            return 1
        model_doc = _load(mpath)
        problems += check_pair(_load(ppath), model_doc)
        if is_synth and not any(
                "@rb" in f for f in (model_doc.get("coeffs") or {})):
            problems.append(
                f"{os.path.basename(mpath)}: no scheduled width-family "
                f"keys (kernel:fam@rbN) — the synth calibration does "
                f"not price generated schedules")
    if problems:
        for p in problems:
            print(f"lens gate: {p}", file=sys.stderr)
        print("lens gate: FAILED", file=sys.stderr)
        return 1
    print("lens gate: ok", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
