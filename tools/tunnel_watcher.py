"""Detached TPU-tunnel watcher: probe until the accelerator heals, then
record the on-chip numbers this round needs.

The axon tunnel wedges for hours at a time (observed: ``jax.devices()``
hanging inside the PJRT plugin, and mid-transfer RPC waits immune to
SIGALRM).  This watcher runs detached (``setsid nohup``), re-probes the
chip with a bounded-subprocess data round-trip, and the moment the link
is healthy runs, in order:

1. the full ``bench.py`` race at protocol scale (the round's headline),
2. the 2^24-row fold bench (the scale rehearsal's on-chip projection),
3. ``tools/gather_probe.py`` (the cost-model probes),

appending everything to ``bench_cache/pipeline.log`` and dropping each
bench JSON line into ``bench_cache/onchip_*.json``.  Exits after one
healthy pass (or when ``--max-hours`` elapses).

Usage:
    setsid nohup python tools/tunnel_watcher.py > /dev/null 2>&1 &
"""

from __future__ import annotations

import argparse
import datetime
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LOG = os.path.join(REPO, "bench_cache", "pipeline.log")


def log(msg: str) -> None:
    stamp = datetime.datetime.now().strftime("%H:%M:%S")
    os.makedirs(os.path.dirname(LOG), exist_ok=True)
    with open(LOG, "a") as f:
        f.write(f"[{stamp}] {msg}\n")


def probe(timeout_s: float = 90.0) -> bool:
    """True iff the default backend is a healthy ACCELERATOR (one
    shared probe contract: utils.platform.probe_default_backend)."""
    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    from arrow_matrix_tpu.utils.platform import probe_default_backend

    platform, _, err = probe_default_backend(timeout_s=timeout_s,
                                             retries=1)
    return err is None and platform != "cpu"


def run_stage(name: str, cmd: list[str], env: dict, timeout_s: float,
              json_name: str | None = None) -> bool:
    log(f"stage {name}: {' '.join(cmd)}")
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=timeout_s, cwd=REPO,
                              env={**os.environ, **env})
    except subprocess.TimeoutExpired:
        log(f"stage {name}: TIMEOUT after {timeout_s:.0f}s")
        return False
    tail = proc.stderr.strip().splitlines()[-8:]
    for ln in tail:
        log(f"  {name}| {ln}")
    out = proc.stdout.strip()
    if out:
        for ln in out.splitlines()[-4:]:
            log(f"  {name}> {ln}")
        if json_name:
            with open(os.path.join(REPO, "bench_cache", json_name),
                      "w") as f:
                f.write(out.splitlines()[-1] + "\n")
    log(f"stage {name}: rc={proc.returncode}")
    return proc.returncode == 0


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--interval", type=float, default=300.0,
                    help="seconds between probes")
    ap.add_argument("--max-hours", type=float, default=11.0)
    ap.add_argument("--skip-scale", action="store_true",
                    help="skip the 2^24 stage (saves ~30 min)")
    args = ap.parse_args()

    deadline = time.time() + args.max_hours * 3600
    log(f"watcher started (interval {args.interval:.0f}s, "
        f"max {args.max_hours:.1f}h)")
    while time.time() < deadline:
        if probe():
            log("tunnel HEALTHY — running on-chip stages")
            ts = datetime.datetime.now().strftime("%m%d_%H%M")
            ok = run_stage(
                "bench_full", [sys.executable, "bench.py"],
                env={"AMT_BENCH_DEADLINE": "3300"},
                timeout_s=3600.0, json_name=f"onchip_bench_{ts}.json")
            if not args.skip_scale:
                run_stage(
                    "bench_2e24", [sys.executable, "bench.py"],
                    env={"AMT_BENCH_N": str(1 << 24),
                         "AMT_BENCH_LEVELS": "14",
                         "AMT_BENCH_FMT": "fold",
                         "AMT_BENCH_K128": "0",
                         "AMT_BENCH_COMPARE": "0",
                         "AMT_BENCH_DEADLINE": "5400"},
                    timeout_s=5700.0,
                    json_name=f"onchip_bench_2e24_{ts}.json")
            run_stage("gather_probe",
                      [sys.executable, "tools/gather_probe.py"],
                      env={}, timeout_s=1800.0)
            if ok:
                log("watcher done (healthy pass complete)")
                return
            log("bench failed on a healthy probe — retrying next cycle")
        time.sleep(args.interval)
    log("watcher expired without a healthy pass")


if __name__ == "__main__":
    main()
