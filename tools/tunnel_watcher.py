"""Detached TPU-tunnel watcher: probe until the accelerator heals, then
record the on-chip numbers this round needs — and keep watching.

The axon tunnel wedges for hours at a time (observed: ``jax.devices()``
hanging inside the PJRT plugin, and mid-transfer RPC waits immune to
SIGALRM).  This watcher runs detached (``setsid nohup``), re-probes the
chip with a bounded-subprocess data round-trip, and the moment the link
is healthy runs, in order:

1. the full ``bench.py`` race at protocol scale (the round's headline),
2. the sell-layout ladder on-chip race (``tools/ladder_race.py``),
3. the 2^24-row fold bench (the scale rehearsal's on-chip projection),
4. the planar grid headline (``tools/planar_bench.py``),
5. ``tools/gather_probe.py`` (the cost-model probes),

appending everything to ``bench_cache/pipeline.log`` and dropping each
bench JSON line into ``bench_cache/onchip_*.json``.

Round-4 hardening (VERDICT r3 item 1 — recovery, not just avoidance):

- every probe failure is LOGGED with its class (init-hang/no-device),
  so the heal time is datable from the log;
- on an init-hang, stale local plugin holders are cleared (a half-dead
  client's claim can block a fresh one server-side);
- while a stage runs, ``bench_cache/tpu_busy.lock`` exists — host-side
  tooling must not start host-heavy work while it does (the round-3
  wedge trigger was host contention pushing a bench child past its
  SIGKILL timeout mid-transfer);
- probe cycles are SKIPPED while any other process holds the plugin
  (e.g. the driver's own end-of-round bench) — the watcher must never
  contend for the one chip;
- after a full healthy pass the watcher keeps probing (cheap heartbeat
  logging only) until --max-hours, so the log records link health
  through driver time.

Usage:
    setsid nohup python tools/tunnel_watcher.py > /dev/null 2>&1 &
"""

from __future__ import annotations

import argparse
import datetime
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LOG = os.path.join(REPO, "bench_cache", "pipeline.log")
BUSY = os.path.join(REPO, "bench_cache", "tpu_busy.lock")
HOST_BUSY = os.path.join(REPO, "bench_cache", "host_busy.lock")


def log(msg: str) -> None:
    stamp = datetime.datetime.now().strftime("%m-%d %H:%M:%S")
    os.makedirs(os.path.dirname(LOG), exist_ok=True)
    with open(LOG, "a") as f:
        f.write(f"[{stamp}] {msg}\n")


def _platform_utils():
    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    from arrow_matrix_tpu.utils import platform as p

    return p


def probe(timeout_s: float = 90.0) -> bool:
    """True iff the default backend is a healthy ACCELERATOR (one
    shared probe contract: utils.platform.probe_default_backend).
    Logs every failure with its class so the heal is datable.
    Recovery of stale holders happens in the MAIN loop (which sees
    holders before probing), not here — a holder that appears during
    the probe window is most likely a live external user."""
    p = _platform_utils()
    platform, _, err = p.probe_default_backend(timeout_s=timeout_s,
                                               retries=1)
    if err is None and platform != "cpu":
        return True
    cls = p.classify_probe_error(err) or "cpu-only"
    log(f"probe: unhealthy ({cls}): {err}")
    return False


def _host_busy_fresh(max_age_s: float = 3600.0) -> bool:
    """True while a RECENT host_busy.lock exists.  Staleness guard: a
    crashed creator must not defer probing forever — locks older than
    an hour are ignored (heavy host jobs here run well under that, and
    their owners re-touch the lock if they genuinely run longer)."""
    try:
        return (os.path.exists(HOST_BUSY)
                and time.time() - os.path.getmtime(HOST_BUSY) < max_age_s)
    except OSError:
        return False


def _foreign_bench_running() -> bool:
    """True when a bench.py we did not spawn is running — e.g. the
    driver's end-of-round run.  The watcher must then neither probe
    (its probe child would race the bench's own probe for the single
    chip's claim) nor start stages."""
    me = os.getpid()
    for entry in os.listdir("/proc"):
        if not entry.isdigit() or int(entry) == me:
            continue
        try:
            with open(f"/proc/{entry}/cmdline", "rb") as f:
                argv = f.read().decode("utf-8", "replace").split("\0")
        except OSError:
            continue
        # Proper argv match: an interpreter arg that IS bench.py — a
        # substring test would also hit processes whose embedded
        # argument text merely MENTIONS bench.py (observed: the
        # driver agent's prompt argument).
        if not argv or "python" not in os.path.basename(argv[0]):
            continue
        if not any(a == "bench.py" or a.endswith("/bench.py")
                   for a in argv[1:3]):
            continue
        # our own stages run bench.py too — skip our descendants
        try:
            with open(f"/proc/{entry}/stat") as f:
                ppid = int(f.read().split(")")[-1].split()[1])
        except (OSError, ValueError, IndexError):
            ppid = -1
        if ppid != me:
            return True
    return False


def chip_in_use_elsewhere() -> bool:
    """True when another process (driver bench, interactive run) holds
    the PJRT plugin — probing would contend for the one chip."""
    p = _platform_utils()
    try:
        return bool(p.find_stale_plugin_holders())
    except Exception:
        return False


def run_stage(name: str, cmd: list[str], env: dict, timeout_s: float,
              json_name: str | None = None) -> bool:
    """One contained stage: ANY failure shape (timeout, OSError on the
    lock file, unwritable artifact) costs the stage, never the
    detached watcher process."""
    log(f"stage {name}: {' '.join(cmd)}")
    try:
        try:
            with open(BUSY, "w") as f:
                f.write(f"{name} started {datetime.datetime.now()}\n")
        except OSError:
            pass   # the lock is advisory; the stage still runs
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=timeout_s, cwd=REPO,
                              env={**os.environ, **env})
    except subprocess.TimeoutExpired:
        log(f"stage {name}: TIMEOUT after {timeout_s:.0f}s")
        return False
    except Exception as e:
        log(f"stage {name}: FAILED to launch: {type(e).__name__}: {e}")
        return False
    finally:
        try:
            os.remove(BUSY)
        except OSError:
            pass
    try:
        tail = proc.stderr.strip().splitlines()[-8:]
        for ln in tail:
            log(f"  {name}| {ln}")
        out = proc.stdout.strip()
        if out:
            for ln in out.splitlines()[-4:]:
                log(f"  {name}> {ln}")
            if json_name:
                with open(os.path.join(REPO, "bench_cache", json_name),
                          "w") as f:
                    f.write(out.splitlines()[-1] + "\n")
    except Exception as e:
        log(f"stage {name}: output handling failed: "
            f"{type(e).__name__}: {e}")
    log(f"stage {name}: rc={proc.returncode}")
    return proc.returncode == 0


def _preemptible_pids() -> list[int]:
    """Verified-live registered host jobs (shared registry contract:
    utils.platform.register_preemptible / read_preemptible).  They and
    their descendants are SIGSTOPped individually for the duration of
    the on-chip stages and SIGCONTed after.  Automates the round-3
    postmortem rule: host contention pushed a bench child past its
    timeout and the SIGKILL mid-transfer wedged the tunnel; pausing
    pure-host compute is free."""
    p = _platform_utils()
    return p.read_preemptible(log=log)


def _descendants(root: int) -> list[int]:
    """``root`` plus its live descendant pids (one /proc pass building
    the ppid tree)."""
    kids: dict[int, list[int]] = {}
    for entry in os.listdir("/proc"):
        if not entry.isdigit():
            continue
        try:
            with open(f"/proc/{entry}/stat") as f:
                ppid = int(f.read().split(")")[-1].split()[1])
        except (OSError, ValueError, IndexError):
            continue
        kids.setdefault(ppid, []).append(int(entry))
    out, stack = [], [root]
    while stack:
        p = stack.pop()
        out.append(p)
        stack.extend(kids.get(p, []))
    return out


def _signal_job(pid: int, sig) -> None:
    """Signal the job and its descendants INDIVIDUALLY — rung workers
    spawned via ``--rung`` must pause with their parent, but a group
    signal could hit unrelated processes sharing the pgid (a
    no-job-control driver script runs its whole pipeline, including a
    live bench, in ONE group).

    SIGSTOP runs to a FIXED POINT: after each sweep the tree is
    re-enumerated, so a child that forked a grandchild while its own
    stop was in flight gets caught on the next pass (a stopped
    process cannot fork, so the set converges)."""
    import signal as _s

    signaled: set[int] = set()
    for _ in range(8):   # bounded; converges in 1-2 passes in practice
        targets = [p for p in _descendants(pid) if p not in signaled]
        if not targets:
            break
        for p in targets:
            try:
                os.kill(p, sig)
                signaled.add(p)
            except OSError:
                pass
        if sig != _s.SIGSTOP:
            break   # only the stop needs the fixed point


class _pause_host_jobs:
    def __enter__(self):
        import signal

        self.pids = _preemptible_pids()
        for p in self.pids:
            try:
                _signal_job(p, signal.SIGSTOP)
                log(f"paused host job {p} (+descendants) for "
                    f"on-chip stages")
            except OSError:
                pass
        return self

    def __exit__(self, *exc):
        import signal

        for p in self.pids:
            try:
                _signal_job(p, signal.SIGCONT)
                log(f"resumed host job {p}")
            except OSError:
                pass
        return False


def healthy_pass(skip_scale: bool) -> bool:
    """Run the full on-chip stage list; True iff the headline landed."""
    ts = datetime.datetime.now().strftime("%m%d_%H%M")
    with _pause_host_jobs():
        return _healthy_pass_stages(skip_scale, ts)


#: Stages that have landed this watcher lifetime.  Replaces the old
#: single pass/quick flags: a tunnel flap mid-pass used to permanently
#: skip every stage after the flap (once the headline latched `passed`,
#: later healthy windows only heartbeat) — now each stage records its
#: own completion and a later heal window retries exactly the stages
#: still missing, never re-running a completed one (duplicate chip
#: minutes).
_stage_done: set[str] = set()


def _bench_stage(name: str, env: dict, timeout_s: float,
                 json_name: str) -> str:
    """Run a bench.py stage; 'onchip' | 'degraded' | 'failed'.
    'degraded' means the artifact EXPLICITLY records a CPU fallback
    (platform=cpu or a degraded flag) — the tunnel is proven down
    again mid-window and the pass bails.  A MISSING or unreadable
    artifact is 'failed', not 'degraded': absence of evidence is not
    evidence of a dead tunnel, so the pass continues and the stage is
    retried in a later window."""
    if not run_stage(name, [sys.executable, "bench.py"], env,
                     timeout_s, json_name=json_name):
        return "failed"
    verdict = _artifact_verdict(json_name)
    if verdict == "onchip":
        return "onchip"
    if verdict == "missing":
        log(f"stage {name}: rc=0 but artifact {json_name} is missing "
            f"or unreadable — counting as failed (retriable), NOT as "
            f"a proven CPU fallback")
        return "failed"
    log(f"stage {name}: completed but DEGRADED (CPU fallback) — "
        f"bailing out of this pass; next probe cycle retries")
    return "degraded"


def _artifact_verdict(json_name: str) -> str:
    """Three-way verdict ('onchip' | 'degraded' | 'missing') on a
    captured bench JSON, via the shared predicate in utils.artifacts —
    ONE definition with bench.py's own evidence scan, so the two sides
    agree on the edge cases (unlabeled pre-platform-label artifacts
    qualify as on-chip; only an explicit label disqualifies)."""
    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    from arrow_matrix_tpu.utils.artifacts import classify_artifact

    return classify_artifact(os.path.join(REPO, "bench_cache",
                                          json_name))


def _healthy_pass_stages(skip_scale: bool, ts: str) -> bool:
    # Order = value-per-healthy-minute under a possibly short heal
    # window: a MINUTES-scale fold-only capture first (round-5
    # observation: the first heal window of the round lasted <8 min —
    # long enough for a platform=tpu headline at the protocol config,
    # not for the full race), then the full race, the
    # defaults-deciding ladder race, the CHEAP measurement probes
    # (VERDICT r4 item 5: the pallas-gather granule question must not
    # die behind hours of scale stages again), then the long scale
    # points.  bench_quick reuses the bench decomposition cache and a
    # single fold candidate with no scipy/k128 comparison.
    # A quick success is recorded in _stage_done (re-running it in a
    # later window would duplicate chip minutes) but does NOT complete
    # the pass — only bench_full does, so a short window's capture
    # never stops the full race from retrying in longer windows.
    #
    # Every bench.py-family stage runs through _bench_stage: bench.py
    # exits 0 on a degraded CPU fallback too (the tunnel closing
    # between our probe and the bench's own is exactly the flap mode
    # this watcher exists for), and a CPU number must neither complete
    # the pass nor justify running hours of further stages on a
    # proven-dead tunnel — "degraded" bails the pass; the next probe
    # cycle retries.  Every OTHER stage records per-stage completion:
    # a degraded bail mid-pass no longer skips the remaining stages
    # for the watcher's whole lifetime — the next healthy window picks
    # up exactly where the flap cut this one off.
    if "bench_quick" not in _stage_done:
        q = _bench_stage(
            "bench_quick",
            env={"AMT_BENCH_FMT": "fold",
                 "AMT_BENCH_COMPARE": "0",
                 "AMT_BENCH_K128": "0",
                 "AMT_BENCH_DEADLINE": "540"},
            timeout_s=720.0, json_name=f"onchip_bench_quick_{ts}.json")
        if q == "degraded":
            return False
        if q == "onchip":
            _stage_done.add("bench_quick")
    if "bench_full" not in _stage_done:
        full = _bench_stage(
            "bench_full", env={"AMT_BENCH_DEADLINE": "3300"},
            timeout_s=3600.0, json_name=f"onchip_bench_{ts}.json")
        if full == "degraded":
            return False
        if full == "onchip":
            _stage_done.add("bench_full")
    ok = "bench_full" in _stage_done
    if "ladder_race" not in _stage_done:
        if os.path.exists(os.path.join(REPO, "tools",
                                       "ladder_race.py")):
            if run_stage(
                    "ladder_race",
                    [sys.executable, "tools/ladder_race.py"],
                    env={}, timeout_s=2400.0,
                    json_name=f"onchip_ladder_{ts}.json"):
                _stage_done.add("ladder_race")
        else:   # tool absent: nothing to retry, don't block completion
            _stage_done.add("ladder_race")
    if "pallas_gather" not in _stage_done:
        if os.path.exists(os.path.join(REPO, "tools",
                                       "pallas_gather_probe.py")):
            if run_stage("pallas_gather",
                         [sys.executable,
                          "tools/pallas_gather_probe.py"],
                         env={}, timeout_s=1200.0,
                         json_name=f"onchip_pallas_gather_{ts}.json"):
                _stage_done.add("pallas_gather")
        else:
            _stage_done.add("pallas_gather")
    if "gather_probe" not in _stage_done:
        if run_stage("gather_probe",
                     [sys.executable, "tools/gather_probe.py"],
                     env={}, timeout_s=1800.0):
            _stage_done.add("gather_probe")
    if not skip_scale and "bench_2e24" not in _stage_done:
        big = _bench_stage(
            "bench_2e24",
            env={"AMT_BENCH_N": str(1 << 24),
                 "AMT_BENCH_LEVELS": "14",
                 "AMT_BENCH_FMT": "fold",
                 "AMT_BENCH_K128": "0",
                 "AMT_BENCH_COMPARE": "0",
                 "AMT_BENCH_DEADLINE": "5400"},
            timeout_s=5700.0,
            json_name=f"onchip_bench_2e24_{ts}.json")
        if big == "onchip":
            _stage_done.add("bench_2e24")
        elif big == "degraded":
            return ok
    if "planar" not in _stage_done:
        if os.path.exists(os.path.join(REPO, "tools",
                                       "planar_bench.py")):
            if run_stage(
                    "planar", [sys.executable, "tools/planar_bench.py"],
                    env={}, timeout_s=2400.0,
                    json_name=f"onchip_planar_{ts}.json"):
                _stage_done.add("planar")
        else:
            _stage_done.add("planar")
            _stage_done.add("planar_1e8")
    if (not skip_scale and "planar_1e8" not in _stage_done
            and "planar" in _stage_done):
        # The flagship scale point: 10240^2 = 104.9M rows on ONE chip
        # via bf16 feature carriage (~8.4 GB resident).  Only after
        # the 4096^2 stage proves the path — a failure there would
        # burn ~40 min of healthy-tunnel time for nothing.  Gated on
        # the planar COMPLETION FLAG, not this pass's local result: a
        # 4096^2 capture from an earlier window proves the path just
        # as well, so a flap between the two stages no longer costs
        # the flagship point the whole round.
        if run_stage(
                "planar_1e8",
                [sys.executable, "tools/planar_bench.py"],
                env={"AMT_PLANAR_SIDE": "10240",
                     "AMT_PLANAR_DTYPE": "bf16"},
                timeout_s=4200.0,
                json_name=f"onchip_planar_1e8_{ts}.json"):
            _stage_done.add("planar_1e8")
    if (not skip_scale and "ba27" not in _stage_done
            and os.path.exists(os.path.join(
                REPO, "bench_cache", "ba27_fold", "rehearsal.json"))
            and os.path.exists(os.path.join(REPO, "tools",
                                            "ba27_bench.py"))):
        # BA-2^27 on-chip iterate from the exported fold operator (the
        # rehearse_1e8_ba_step rung is the offline half; the tool
        # itself refuses a toy-sized export).  Budget ~14 GB of the
        # 16 GB HBM — last in the list: the probes and planar stages
        # above it are cheaper per healthy minute.
        if run_stage("ba27", [sys.executable, "tools/ba27_bench.py"],
                     env={}, timeout_s=4800.0,
                     json_name=f"onchip_ba27_{ts}.json"):
            _stage_done.add("ba27")
    return ok


def _stages_remaining(skip_scale: bool) -> list[str]:
    """Stages a later healthy window should still attempt.  ba27 is
    never listed: its preconditions (an exported rehearsal) may never
    materialize in a round, and an opportunistic extra must not keep
    the watcher re-running full passes forever."""
    stages = ["bench_quick", "bench_full", "ladder_race",
              "pallas_gather", "gather_probe"]
    if not skip_scale:
        stages.append("bench_2e24")
    stages.append("planar")
    if not skip_scale:
        stages.append("planar_1e8")
    return [s for s in stages if s not in _stage_done]


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--interval", type=float, default=300.0,
                    help="seconds between probes")
    ap.add_argument("--max-hours", type=float, default=11.0)
    ap.add_argument("--skip-scale", action="store_true",
                    help="skip the 2^24 stage (saves ~30 min)")
    args = ap.parse_args()

    deadline = time.time() + args.max_hours * 3600
    log(f"watcher started (interval {args.interval:.0f}s, "
        f"max {args.max_hours:.1f}h, pid {os.getpid()})")
    # Startup SIGCONT sweep: a previous watcher SIGKILLed mid-stage
    # leaves registered jobs frozen — unfreeze anything still listed.
    import signal as _signal

    for p in _preemptible_pids():
        try:
            _signal_job(p, _signal.SIGCONT)
            log(f"startup sweep: SIGCONT {p} (possibly left paused)")
        except OSError:
            pass
    passed = False
    p = _platform_utils()
    foreign_since: float | None = None
    while time.time() < deadline:
        if _foreign_bench_running():
            # Staleness escape: a normal driver bench finishes well
            # inside 2 h; one present longer is itself wedged and must
            # not shadow the recovery branches below forever.
            foreign_since = foreign_since or time.time()
            if time.time() - foreign_since < 7200:
                log("probe: skipped (a foreign bench.py is running — "
                    "its probe must win the chip)")
                time.sleep(args.interval)
                continue
            log("foreign bench.py present >2h — treating as wedged, "
                "resuming normal handling")
        else:
            foreign_since = None
        if chip_in_use_elsewhere():
            # Another process holds the plugin: a live user (driver
            # bench, interactive run) — don't contend.  But a
            # half-dead holder is exactly the round-3 wedge mode, so
            # attempt recovery: reset_tunnel_state kills ONLY holders
            # whose CPU stays flat for 7 minutes (a live bench child
            # advances CPU) and no-ops under a fresh tpu_busy.lock.
            log("probe: plugin held by another process — checking "
                "for staleness")
            try:
                cleared = p.reset_tunnel_state(log=log)
                if cleared:
                    log(f"recovery: cleared wedged holders {cleared}")
            except Exception as e:
                log(f"recovery check failed: {type(e).__name__}: {e}")
        elif (_host_busy_fresh()
              and _stages_remaining(args.skip_scale)):
            # Host-heavy work in flight: a bench started now would
            # contend for the single core (round-3 wedge trigger).
            log("probe: deferred (host_busy.lock present)")
        elif probe():
            remaining = _stages_remaining(args.skip_scale)
            if not remaining:
                log("probe: healthy (heartbeat; all stages complete)")
            else:
                log("tunnel HEALTHY — running on-chip stages "
                    f"(pending: {', '.join(remaining)})")
                passed = healthy_pass(args.skip_scale) or passed
                remaining = _stages_remaining(args.skip_scale)
                if not remaining:
                    log("all stages complete — continuing heartbeat "
                        "probes through driver time")
                elif passed:
                    log("headline landed; stages still pending: "
                        f"{', '.join(remaining)} — retrying in the "
                        f"next healthy window")
                else:
                    log("bench failed on a healthy probe — retrying "
                        "next cycle")
        else:
            # Init-hang with NO connected holder in sight: recovery
            # still applies — our own orphaned probe children (killed
            # watchers leave them hanging in the init wedge, cmdline-
            # marked amt_probe) can hold pending claims without a
            # socket; reset_tunnel_state kills only those + flat-CPU
            # connected holders, never innocent idle jax processes.
            try:
                cleared = p.reset_tunnel_state(log=log)
                if cleared:
                    log(f"recovery after failed probe: cleared "
                        f"{cleared}")
            except Exception as e:
                log(f"recovery check failed: {type(e).__name__}: {e}")
        time.sleep(args.interval)
    log("watcher expired")


if __name__ == "__main__":
    main()
