"""Bounded Pallas granule-DMA gather experiment (VERDICT r3 item 2iii).

PERFORMANCE.md's "why no Pallas gather kernel" analysis rejected
*per-row* async DMAs (64 B copies, issue-cost-bound) from first
principles.  This probe settles the remaining open case empirically:
**granule-sized** DMAs — features packed so 8 consecutive rows form one
contiguous 512 B line ``(n/8, 128) f32`` — against XLA's materializing
take on the same chip, same indices.

Three measured variants, each its own jit/pallas program:

1. ``xla_take``      — jnp.take feature-major (k, n), the framework's
                       production gather (reference rate).
2. ``xla_granule``   — jnp.take of packed granule rows (n/8, 128) +
                       in-register sub-row select: tests whether XLA's
                       row gather of full-lane 512 B rows beats its
                       sub-transaction 64 B column gather per slot.
3. ``pallas_granule``— hand-pipelined Pallas kernel: per-slot async
                       copies of 512 B granule lines HBM->VMEM in
                       waves of W in-flight DMAs, then a vectorized
                       sub-row select.  Measures the DMA issue rate
                       against the analysis' ~50-cycle estimate.

Output: one JSON line with M slots/s per variant (plus ms), so the
watcher can archive it as the committed confirm-or-falsify artifact.
Run on CPU (AMT_PROBE_CPU=1, interpret mode, small shapes) only to
validate correctness of the select logic — rates are chip-only.
"""

from __future__ import annotations

import functools
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

C = 8          # rows per granule: 8 x 16 feats x f32 = 512 B lines
K = 16         # features (the k=16 headline regime — the hard case)
LANES = C * K  # 128


def _bench_ms(f, *args, reps: int = 5) -> float:
    import jax

    o = f(*args)
    jax.block_until_ready(o)
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        o = f(*args)
        jax.block_until_ready(o)
        ts.append(time.perf_counter() - t0)
    return min(ts) * 1e3


def xla_take(x_t, idx):
    """Production gather: feature-major materializing take."""
    import jax.numpy as jnp

    return jnp.take(x_t, idx, axis=1)


def xla_granule(x_packed, idx):
    """Packed-granule take + sub-row select, pure XLA."""
    import jax.numpy as jnp

    g = jnp.take(x_packed, idx // C, axis=0)          # (S, 128)
    off = (idx % C).astype(jnp.int32)                  # (S,)
    lane = jnp.arange(LANES, dtype=jnp.int32) // K     # (128,) -> granule row
    mask = (lane[None, :] == off[:, None])             # (S, 128)
    masked = jnp.where(mask, g, 0.0)
    # Fold the C segments of 16 lanes into one (S, 16) result.
    return masked.reshape(-1, C, K).sum(axis=1)


def make_pallas_granule(n_granules: int, block: int, wave: int,
                        interpret: bool = False):
    """Pallas kernel: gather ``block`` granule lines per grid step with
    ``wave`` async copies in flight, select sub-rows, emit (block, K)
    packed as (block // C, LANES)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    assert block % C == 0 and block % wave == 0

    def kernel(idx_smem, idx_vmem, x_hbm, out_ref, scratch, sems):
        n_waves = block // wave
        # idx_smem is the WHOLE (S,) index array (scalar prefetch);
        # this grid step owns slots [pid*block, (pid+1)*block).
        blk0 = pl.program_id(0) * block

        def do_wave(w, _):
            base = w * wave

            def start(j, __):
                s = base + j
                g = idx_smem[blk0 + s] // C
                pltpu.make_async_copy(
                    x_hbm.at[g], scratch.at[s], sems.at[j]).start()
                return __

            jax.lax.fori_loop(0, wave, start, 0)

            def wait(j, __):
                s = base + j
                g = idx_smem[blk0 + s] // C
                pltpu.make_async_copy(
                    x_hbm.at[g], scratch.at[s], sems.at[j]).wait()
                return __

            jax.lax.fori_loop(0, wave, wait, 0)
            return _

        jax.lax.fori_loop(0, n_waves, do_wave, 0)
        # Vectorized sub-row select over the whole block.
        off = (idx_vmem[:] % C).astype(jnp.int32)          # (block,)
        lane = jax.lax.broadcasted_iota(
            jnp.int32, (block, LANES), 1) // K
        masked = jnp.where(lane == off[:, None], scratch[:], 0.0)
        picked = masked.reshape(block // C, C, C, K).sum(axis=2)
        out_ref[:] = picked.reshape(block // C, LANES)

    def run(x_packed, idx):
        s = idx.shape[0]
        gs = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,        # idx -> SMEM, whole array
            grid=(s // block,),
            in_specs=[
                pl.BlockSpec((block,), lambda i, sc: (i,),
                             memory_space=pltpu.VMEM),  # idx, vector math
                pl.BlockSpec(memory_space=pl.ANY),      # x stays in HBM
            ],
            out_specs=pl.BlockSpec((block // C, LANES),
                                   lambda i, sc: (i, 0),
                                   memory_space=pltpu.VMEM),
            scratch_shapes=[
                pltpu.VMEM((block, LANES), jnp.float32),
                pltpu.SemaphoreType.DMA((wave,)),
            ],
        )
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((s // C, LANES), jnp.float32),
            grid_spec=gs,
            interpret=interpret,
        )(idx, idx, x_packed)

    return jax.jit(run)


def main() -> None:
    cpu = os.environ.get("AMT_PROBE_CPU") == "1"
    if cpu:
        from arrow_matrix_tpu.utils.platform import force_cpu_devices

        force_cpu_devices()
    import jax
    import jax.numpy as jnp

    dev = jax.devices()[0]
    out: dict = {"metric": "pallas_gather_probe",
                 "platform": dev.platform, "device_kind": dev.device_kind,
                 "variants": {}}
    n = 1 << 14 if cpu else 1 << 20
    s = 1 << 12 if cpu else 1 << 21
    block, wave = (64, 16) if cpu else (1024, 32)
    rng = np.random.default_rng(5)
    x = rng.standard_normal((n, K)).astype(np.float32)
    idx = rng.integers(0, n, size=s, dtype=np.int32)
    x_t = jnp.asarray(np.ascontiguousarray(x.T))               # (K, n)
    x_packed = jnp.asarray(x.reshape(n // C, LANES))           # (n/8, 128)
    idx_d = jnp.asarray(idx)
    out.update({"n": n, "slots": s, "k": K, "granule": C,
                "block": block, "wave": wave})

    want = x[idx]                                              # (S, K)

    def check(name, got, reshape_packed=False):
        g = np.asarray(got)
        if reshape_packed:
            g = g.reshape(-1, K)
        err = float(np.abs(g - want).max())
        ok = err < 1e-6
        out["variants"].setdefault(name, {})["exact"] = ok
        if not ok:
            out["variants"][name]["max_err"] = err
        return ok

    f1 = jax.jit(xla_take)
    check("xla_take", f1(x_t, idx_d).T)
    ms = _bench_ms(f1, x_t, idx_d)
    out["variants"]["xla_take"].update(
        ms=round(ms, 2), mslots_s=round(s / ms / 1e3, 1))

    f2 = jax.jit(xla_granule)
    check("xla_granule", f2(x_packed, idx_d))
    ms = _bench_ms(f2, x_packed, idx_d)
    out["variants"]["xla_granule"].update(
        ms=round(ms, 2), mslots_s=round(s / ms / 1e3, 1))

    try:
        f3 = make_pallas_granule(n // C, block, wave, interpret=cpu)
        check("pallas_granule", f3(x_packed, idx_d),
              reshape_packed=True)
        ms = _bench_ms(f3, x_packed, idx_d)
        out["variants"]["pallas_granule"].update(
            ms=round(ms, 2), mslots_s=round(s / ms / 1e3, 1))
    except Exception as e:
        out["variants"]["pallas_granule"] = {
            "error": f"{type(e).__name__}: {str(e)[:400]}"}
    v = out["variants"]
    # Verdict gates on the MEASURED platform, not the env flag: a
    # tunnel flap can silently fall back to host CPU with
    # AMT_PROBE_CPU unset, and CPU timings must never write a
    # "productionize" verdict into the onchip_* namespace.
    if dev.platform != "cpu" and all(("mslots_s" in v.get(k, {})
                                      and v[k].get("exact") is True)
                                     for k in ("xla_take",
                                               "pallas_granule")):
        # Verdict requires BOTH variants exact: a fast kernel that
        # returns wrong gathers must never read "productionize".
        # The committed confirm-or-falsify verdict (VERDICT r4 item
        # 5): does the wave-pipelined granule DMA beat XLA's take by
        # enough to productionize as the SELL gather kernel?
        ratio = (v["pallas_granule"]["mslots_s"]
                 / max(v["xla_take"]["mslots_s"], 1e-9))
        out["pallas_vs_xla"] = round(ratio, 2)
        out["verdict"] = ("pallas_wins — productionize"
                          if ratio > 1.1 else "xla_holds")
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
