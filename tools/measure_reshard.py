"""Measure graft-reshard's memory claim at scale (PR 14 acceptance):
the staged a2a exchange's compiled peak HBM must come in STRICTLY
below the one-shot exchange at n = 2^20, and the staged cutover
(``ArrowServer.grow``) downtime must be a number, not a vibe.

Three measurements, all on the virtual 4-device CPU mesh:

* **exchange peak-HBM** — one full random-permutation exchange of a
  (2^20, 4) f32 carriage, one-shot ``routed_take`` vs
  ``staged_routed_take`` under a 2 MiB per-device scratch budget,
  judged by XLA's own ``memory_analysis`` of the compiled program
  (temp bytes: collective payloads + scatter scratch; arguments and
  outputs are identical between the two by construction).
* **ms/iter** — median wall-clock of the same two compiled exchanges
  (the price of the barrier chain).
* **migration downtime** — wall-clock of ``ArrowServer.grow`` while
  it replays mid-flight checkpoints through staged plans (the window
  in which the server answers no requests), at the reshard gate's
  serving scale.

Appends to ``bench_results/reshard_hbm.json`` and records the three
headline numbers in the graft-ledger.

Usage: PYTHONPATH=/root/repo python tools/measure_reshard.py
       [--log2 20] [--budget-mib 2] [--no-ledger]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

K = 4
N_DEV = 4
REPS = 5


def measure_exchange(log2: int, budget: int) -> dict:
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec

    from arrow_matrix_tpu.parallel import routing
    from arrow_matrix_tpu.parallel.mesh import make_mesh, put_global

    n = 1 << log2
    mesh = make_mesh((N_DEV,), ("blocks",),
                     devices=np.asarray(jax.devices()[:N_DEV]))
    rng = np.random.default_rng(log2)
    t0 = time.perf_counter()
    route = routing.build_route(rng.permutation(n).astype(np.int64),
                                N_DEV)
    build_s = time.perf_counter() - t0
    sroute = routing.split_route_stages(route, K, budget)
    x = put_global(rng.standard_normal((n, K)).astype(np.float32),
                   NamedSharding(mesh, PartitionSpec("blocks")))
    variants = {
        "one_shot": jax.jit(lambda xx: routing.routed_take(
            xx, routing.shard_route(route, mesh, "blocks"), mesh,
            "blocks")),
        "staged": jax.jit(lambda xx: routing.staged_routed_take(
            xx, routing.shard_route(sroute, mesh, "blocks"), mesh,
            "blocks")),
    }
    out = {"n": n, "k": K, "n_dev": N_DEV,
           "scratch_budget_bytes": budget,
           "stages": sroute.n_stages,
           "one_shot_payload_bytes_per_dev":
               route.device_bytes_per_exchange(K, 4),
           "staged_payload_bytes_per_dev":
               sroute.device_bytes_per_exchange(K, 4),
           "route_build_s": round(build_s, 3)}
    results = {}
    for name, fn in variants.items():
        compiled = fn.lower(x).compile()
        ma = compiled.memory_analysis()
        y = compiled(x)
        y.block_until_ready()
        results[name] = np.asarray(y)
        times = []
        for _ in range(REPS):
            t0 = time.perf_counter()
            compiled(x).block_until_ready()
            times.append((time.perf_counter() - t0) * 1000)
        out[name] = {
            "temp_bytes": int(ma.temp_size_in_bytes),
            "argument_bytes": int(ma.argument_size_in_bytes),
            "output_bytes": int(ma.output_size_in_bytes),
            "peak_hbm_bytes": int(ma.temp_size_in_bytes
                                  + ma.argument_size_in_bytes
                                  + ma.output_size_in_bytes),
            "ms_per_iter": round(sorted(times)[len(times) // 2], 2),
        }
    out["bit_identical"] = (results["one_shot"].tobytes()
                            == results["staged"].tobytes())
    out["staged_below_one_shot"] = (
        out["staged"]["peak_hbm_bytes"]
        < out["one_shot"]["peak_hbm_bytes"])
    return out


def measure_migration_downtime() -> dict:
    """Time the staged cutover window at the reshard gate's serving
    scale: seed one step-2 checkpoint per request on a 2-device
    layout, then clock ``grow()`` end to end (build the 4-device
    executor, replay every checkpoint through its staged plan, swap
    the resident charge)."""
    import jax
    import numpy as np

    from arrow_matrix_tpu.parallel.mesh import make_mesh
    from arrow_matrix_tpu.serve.loadgen import (
        ba_executor_factory,
        synthetic_trace,
    )
    from arrow_matrix_tpu.serve.scheduler import ArrowServer, ExecConfig
    from arrow_matrix_tpu.utils.checkpoint import save_state

    import tempfile

    n, width, k, requests, iters = 96, 16, 2, 6, 4
    ck = tempfile.mkdtemp(prefix="reshard_measure_ck_")
    devs = jax.devices()
    mesh2 = make_mesh((2,), ("blocks",), devices=np.asarray(devs[:2]))
    mesh4 = make_mesh((4,), ("blocks",), devices=np.asarray(devs[:4]))
    fac2, n_rows = ba_executor_factory(n, width, 3, fmt="auto",
                                       mesh=mesh2)
    fac4, _ = ba_executor_factory(n, width, 3, fmt="auto", mesh=mesh4)
    trace = synthetic_trace(n_rows, tenants=3, requests=requests, k=k,
                            iterations=iters, seed=7)
    ex2 = fac2(ExecConfig())
    for r in trace:
        x = ex2.set_features(r.x)
        for _ in range(2):
            x = ex2.step(x)
        save_state(os.path.join(ck, f"ck_{r.request_id}"),
                   np.asarray(x), 2,
                   layout=f"serve/{r.request_id}/k{r.k}"
                          f"/it{r.iterations}")
    server = ArrowServer(fac2, ExecConfig(), name="measure",
                         checkpoint_dir=ck, checkpoint_every=2,
                         max_batch_k=0, grow_factory=fac4,
                         reshard_budget_bytes=256)
    t0 = time.perf_counter()
    grown = server.grow(reason="measure")
    downtime_s = time.perf_counter() - t0
    assert grown, "grow refused during the downtime measurement"
    # The downtime includes the grown executor's build+compile; the
    # per-checkpoint replay alone is the resharding marginal cost.
    return {"n": n, "width": width, "k": k,
            "checkpoints": requests,
            "reshard_budget_bytes": 256,
            "grow_downtime_ms": round(downtime_s * 1000, 1),
            "checkpoints_resharded": server.checkpoints_resharded}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[0])
    ap.add_argument("--log2", type=int, default=20,
                    help="log2 of the exchanged row count")
    ap.add_argument("--budget-mib", type=float, default=2.0,
                    help="per-device staged scratch budget (MiB)")
    ap.add_argument("--no-ledger", action="store_true",
                    help="skip the graft-ledger records")
    ap.add_argument("--out", default=os.path.join(
        REPO, "bench_results", "reshard_hbm.json"))
    args = ap.parse_args(argv)

    from arrow_matrix_tpu.utils.platform import force_cpu_devices

    force_cpu_devices(4)

    budget = int(args.budget_mib * (1 << 20))
    exch = measure_exchange(args.log2, budget)
    mig = measure_migration_downtime()
    doc = {"exchange": exch, "migration": mig}
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=2, sort_keys=True)
    print(json.dumps(doc, indent=2, sort_keys=True))

    if not exch["bit_identical"]:
        print("FAIL: staged exchange is not bit-identical to one-shot")
        return 1
    if not exch["staged_below_one_shot"]:
        print("FAIL: staged peak HBM is not strictly below one-shot")
        return 1

    if not args.no_ledger:
        from arrow_matrix_tpu.ledger.store import Ledger

        lg = Ledger()
        knobs = {"n": exch["n"], "k": exch["k"],
                 "n_dev": exch["n_dev"],
                 "scratch_budget_bytes": budget,
                 "stages": exch["stages"]}
        for variant in ("one_shot", "staged"):
            lg.record(
                "bench", f"reshard_exchange_peak_hbm_{variant}",
                float(exch[variant]["peak_hbm_bytes"]), unit="bytes",
                knobs=dict(knobs, variant=variant),
                payload={"temp_bytes": exch[variant]["temp_bytes"],
                         "ms_per_iter": exch[variant]["ms_per_iter"],
                         "bit_identical": exch["bit_identical"]})
        lg.record(
            "serve", "reshard_migration_downtime_ms",
            mig["grow_downtime_ms"], unit="ms",
            knobs={"n": mig["n"], "checkpoints": mig["checkpoints"],
                   "reshard_budget_bytes":
                       mig["reshard_budget_bytes"]},
            payload=mig)
        print(f"ledger: 3 record(s) appended to {lg.path}")
    print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
