#!/usr/bin/env python
"""Tier-1 serve gate: chaos-under-load for the graft-serve runtime.

The serving counterpart of tools/chaos_gate.py (which imports these
scenarios into its matrix): a 4-tenant synthetic trace runs against an
:class:`~arrow_matrix_tpu.serve.ArrowServer` over a small BA resident
operator while faults land mid-flight, and every scenario must end
**detected** + **recovered** (or cleanly, explicitly shed) with every
surviving request's result **bit-identical** to a fault-free replay —
and the server process never needing an external restart:

  serve_hang     — an injected stall outlasts the per-request
                   watchdog while 4 tenants are queued; the request is
                   retried and every request still completes.
  serve_corrupt  — a corrupted per-request checkpoint (bad bytes +
                   mismatched sha256 sidecar) is planted before the
                   run; the resume detects it loudly, discards, and
                   recomputes — under a full queue of other tenants.
  serve_overflow — a burst past the bounded queue: the overflow is
                   shed EXPLICITLY (deterministic count, ticket state
                   + reason, flight event), admitted requests are
                   untouched.
  serve_hbm      — an HBM budget with headroom for exactly one
                   request's carriage: admission rejects the rest
                   429-style with zero over-budget admissions
                   (verified against the memview price).
  serve_classes  — graft-classes: approx (bf16) tenants under a real
                   probed certificate serve reduced-precision carriage
                   within the class tolerance of the f32 replay, exact
                   tenants in the same run stay bit-identical, approx
                   admission is priced below exact, and an
                   uncertifiable request (deeper than the curve) falls
                   back to exact with an explicit reason.
  serve_kill     — (subprocess; skipped under ``--fast``) SIGKILL
                   lands mid-request in a checkpointing graft_serve
                   CLI run; the rerun resumes in-flight requests from
                   their sha256-verified checkpoints and the full
                   result set is bit-identical to a never-killed run.
  slo_burn_degrade — sustained fault pressure under a deterministic
                   pulse clock: the graft-pulse SLO-burn watchdog
                   trips after exactly ``min_windows`` burning windows
                   (hysteresis: the first faulty window alone never
                   fires), feeds the degradation ladder
                   (``slo_burn:fault_rate`` rung), emits the
                   ``slo_burn_cleared`` recovery event on the first
                   healthy window — and every request completes
                   bit-identical to a fault-free run on the same base
                   rung.  The whole pass is replayed and must
                   reproduce the identical burn-event sequence.

Exits 0 when every scenario passes, 1 otherwise.

Usage:
  python tools/serve_gate.py [--fast] [workdir]
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

N, WIDTH, K = 128, 16, 2
TENANTS, REQUESTS, ITERS = 4, 8, 4
SEED = 11


def _policy(**kw):
    from arrow_matrix_tpu.faults import RetryPolicy

    base = dict(max_retries=2, backoff_s=0.01, jitter=0.2, seed=SEED)
    base.update(kw)
    return RetryPolicy(**base)


def _server(factory, **kw):
    from arrow_matrix_tpu.serve import ArrowServer, ExecConfig

    base = dict(queue_capacity=16, policy=_policy(), name="gate")
    base.update(kw)
    return ArrowServer(factory, ExecConfig(), **base)


def _trace(n_rows):
    from arrow_matrix_tpu.serve import synthetic_trace

    return synthetic_trace(n_rows, tenants=TENANTS, requests=REQUESTS,
                           k=K, iterations=ITERS, seed=SEED)


def _run(server, n_rows):
    from arrow_matrix_tpu.serve import run_trace

    return run_trace(server, _trace(n_rows))


def _result_bytes(tickets) -> dict:
    return {t.request.request_id: t.result.tobytes()
            for t in tickets if t.result is not None}


def scenario_serve_hang(factory, n_rows, ref):
    """Watchdog-timeout recovery with 4 tenants in flight."""
    from arrow_matrix_tpu import faults

    faults.set_plan({"scenario": "hang", "site": "multi_level.step",
                     "after": 3, "hang_s": 1.0})
    srv = _server(factory,
                  policy=_policy(watchdog_s=0.3,
                                 watchdog_grace_s=60.0))
    try:
        tickets = _run(srv, n_rows)
    finally:
        faults.clear_plan()
    problems = []
    s = srv.summary()
    if s["completed"] != REQUESTS:
        problems.append(f"serve_hang: {s['completed']}/{REQUESTS} "
                        f"requests completed")
    if srv.faults_seen == 0:
        problems.append("serve_hang: the watchdog never fired on the "
                        "injected stall")
    if srv.recoveries == 0:
        problems.append("serve_hang: no recovery was taken")
    if _result_bytes(tickets) != ref:
        problems.append("serve_hang: surviving results are not "
                        "bit-identical to the fault-free replay")
    return problems


def scenario_serve_corrupt(factory, n_rows, ref, workdir):
    """A corrupted per-request checkpoint under a full queue: the
    sha256 sidecar fails the resume loudly; the server discards the
    checkpoint and recomputes — never crashes, never serves poison."""
    ckdir = os.path.join(workdir, "serve_ck_corrupt")
    os.makedirs(ckdir, exist_ok=True)
    # Plant a corrupt npz checkpoint for request r0000 (unbatched key):
    # garbage npz bytes plus a sidecar recording a different digest —
    # exactly what post-write disk corruption looks like.
    victim = os.path.join(ckdir, "ck_r0000.npz")
    with open(victim, "wb") as fh:
        fh.write(b"\x00corrupt\xff" * 64)
    with open(victim + ".sha256", "w", encoding="utf-8") as fh:
        fh.write("0" * 64 + "\n")
    srv = _server(factory, checkpoint_dir=ckdir, checkpoint_every=2)
    tickets = _run(srv, n_rows)
    problems = []
    s = srv.summary()
    if s["checkpoint_corruptions"] < 1:
        problems.append("serve_corrupt: the corrupted checkpoint was "
                        "not detected")
    if s["completed"] != REQUESTS:
        problems.append(f"serve_corrupt: {s['completed']}/{REQUESTS} "
                        f"requests completed")
    if _result_bytes(tickets) != ref:
        problems.append("serve_corrupt: recomputed results are not "
                        "bit-identical to the fault-free replay")
    if os.path.exists(victim):
        problems.append("serve_corrupt: the corrupt checkpoint was "
                        "not discarded")
    return problems


def scenario_serve_overflow(factory, n_rows, ref):
    """Burst past the bounded queue: deterministic, explicit shed."""
    capacity = 3
    srv = _server(factory, queue_capacity=capacity)
    trace = _trace(n_rows)
    tickets = [srv.submit(r) for r in trace]   # burst: no draining
    srv.drain()
    problems = []
    s = srv.summary()
    want_shed = REQUESTS - capacity
    if s["shed"] != want_shed or s["completed"] != capacity:
        problems.append(
            f"serve_overflow: expected exactly {capacity} completed + "
            f"{want_shed} shed, got {s['completed']} + {s['shed']}")
    for t in tickets:
        if not t.done:
            problems.append(f"serve_overflow: request "
                            f"{t.request.request_id} never reached a "
                            f"terminal state (silently dropped)")
        elif t.status == "shed" and t.reason != "queue_full":
            problems.append(f"serve_overflow: shed request "
                            f"{t.request.request_id} lacks the "
                            f"explicit queue_full reason")
    got = _result_bytes(tickets)
    for rid, payload in got.items():
        if ref.get(rid) != payload:
            problems.append(f"serve_overflow: surviving request {rid} "
                            f"is not bit-identical to the fault-free "
                            f"replay")
    # Replay determinism: the same burst sheds the same census.
    srv2 = _server(factory, queue_capacity=capacity)
    tickets2 = [srv2.submit(r) for r in _trace(n_rows)]
    srv2.drain()
    census = [(t.status, t.reason) for t in tickets]
    census2 = [(t.status, t.reason) for t in tickets2]
    if census != census2:
        problems.append("serve_overflow: the shed census is not "
                        "replay-deterministic")
    return problems


def scenario_serve_hbm(factory, n_rows, ref):
    """Admission control: headroom for exactly one request's carriage
    — the burst must see zero over-budget admissions, each rejection
    explicit, and the one admitted request completes bit-identically."""
    from arrow_matrix_tpu.serve import ExecConfig, request_price_bytes

    executor = factory(ExecConfig())
    from arrow_matrix_tpu.obs.memview import predicted_bytes_for

    resident = predicted_bytes_for(executor, 0) or 0
    price = request_price_bytes(executor, K)
    srv = _server(factory, hbm_budget_bytes=resident + price)
    tickets = [srv.submit(r) for r in _trace(n_rows)]   # burst
    srv.drain()
    problems = []
    s = srv.summary()
    if s["admitted"] != 1 or s["rejected"] != REQUESTS - 1:
        problems.append(
            f"serve_hbm: expected exactly 1 admission + "
            f"{REQUESTS - 1} rejections at a one-request budget, got "
            f"{s['admitted']} + {s['rejected']}")
    peak = s["hbm"]["peak_in_use_bytes"]
    if peak > resident + price:
        problems.append(f"serve_hbm: peak HBM {peak} B exceeded the "
                        f"budget {resident + price} B — an "
                        f"over-budget request was admitted")
    for t in tickets:
        if t.status == "rejected" and t.reason != "hbm_budget":
            problems.append(f"serve_hbm: rejected request "
                            f"{t.request.request_id} lacks the "
                            f"explicit hbm_budget reason")
    got = _result_bytes(tickets)
    for rid, payload in got.items():
        if ref.get(rid) != payload:
            problems.append(f"serve_hbm: admitted request {rid} is "
                            f"not bit-identical to the fault-free "
                            f"replay")
    return problems


def scenario_slo_burn_degrade(factory, n_rows):
    """Measured SLO pressure drives the same ladder faults do: two
    consecutive windows with injected (recovered) faults trip the
    ``fault_rate`` burn rule, the watchdog degrades the burning
    window's tenant one rung, the first healthy window clears the
    burn — all on a manual clock (one window per request), so the
    entire episode is replay-deterministic."""
    from arrow_matrix_tpu import faults
    from arrow_matrix_tpu.obs import flight, pulse
    from arrow_matrix_tpu.serve import ArrowServer, ExecConfig

    # overlap_slabs=2 gives the ladder a second rung (-> overlap 1)
    # that accepts the same K, so a forced degradation has somewhere
    # to land without changing kernels.
    base_cfg = ExecConfig(overlap_slabs=2)

    def one_pass(inject):
        now = [0.0]
        mon = pulse.PulseMonitor(
            window_s=1.0, clock=lambda: now[0], name="gate-burn",
            watchdog=pulse.SloWatchdog(
                [pulse.BurnRule.fault_rate(0.0, min_windows=2)]))
        # degrade_after=100: the organic recovered-fault path cannot
        # reach a rung in this run; only the watchdog's forced score
        # (note_slo_pressure) can move the ladder.
        srv = ArrowServer(factory, base_cfg, queue_capacity=16,
                          policy=_policy(), degrade_after=100,
                          name="gate-burn")
        srv.attach_pulse(mon)
        tickets = []
        try:
            for i, r in enumerate(_trace(n_rows)):
                if inject and i < 2:
                    faults.set_plan({"scenario": "error",
                                     "site": "multi_level.step",
                                     "after": 0, "count": 1})
                else:
                    faults.clear_plan()
                tickets.append(srv.submit(r))
                srv.drain()
                now[0] += 1.0
                mon.advance()
        finally:
            faults.clear_plan()
        mon.close("scenario done")
        return srv, mon, tickets

    ref_srv, _, ref_tickets = one_pass(inject=False)
    if ref_srv.summary()["completed"] != REQUESTS:
        return ["slo_burn_degrade: fault-free reference run on the "
                "overlap base rung did not complete every request"]
    ref = _result_bytes(ref_tickets)

    srv, mon, tickets = one_pass(inject=True)
    problems = []
    s = srv.summary()
    if s["completed"] != REQUESTS:
        problems.append(f"slo_burn_degrade: {s['completed']}/"
                        f"{REQUESTS} requests completed")

    # Hysteresis + trip: exactly one burn, at window 1 (the second
    # consecutive faulty window) — window 0 alone must never fire.
    burns = [(e["rule"], e["window"]) for e in mon.burn_events
             if e["event"] == "slo_burn"]
    if burns != [("fault_rate", 1)]:
        problems.append(f"slo_burn_degrade: expected one fault_rate "
                        f"burn at window 1, got {burns}")
    cleared = [(e["rule"], e["window"]) for e in mon.burn_events
               if e["event"] == "slo_burn_cleared"]
    if cleared != [("fault_rate", 2)]:
        problems.append(f"slo_burn_degrade: expected one recovery "
                        f"(slo_burn_cleared) at window 2, got "
                        f"{cleared}")
    faulty = [w["window"] for w in mon.series() if w["faults_seen"]]
    if faulty != [0, 1]:
        problems.append(f"slo_burn_degrade: injected faults landed in "
                        f"windows {faulty}, expected [0, 1]")

    # The burning window's tenant took exactly one forced rung with
    # the watchdog's reason attached.
    hits = [(name, d) for name, t in s["tenants"].items()
            for d in t["degradations"]]
    burn_hits = [(name, d) for name, d in hits
                 if d["reason"] == "slo_burn:fault_rate"]
    if len(burn_hits) != 1:
        problems.append(f"slo_burn_degrade: expected exactly one "
                        f"slo_burn:fault_rate degradation, got "
                        f"{[(n, d['reason']) for n, d in hits]}")
    else:
        name, d = burn_hits[0]
        if s["tenants"][name]["rung"] != 1 \
                or d["to"]["overlap_slabs"] != 1:
            problems.append(
                f"slo_burn_degrade: tenant {name} should sit on rung "
                f"1 (overlap_slabs=1), got rung "
                f"{s['tenants'][name]['rung']} -> {d['to']}")

    if _result_bytes(tickets) != ref:
        problems.append("slo_burn_degrade: surviving results are not "
                        "bit-identical to the fault-free run on the "
                        "same base rung")
    rec = flight.get_recorder()
    if rec is not None:
        kinds = {e.get("kind") for e in rec.events}
        if "slo_burn" not in kinds:
            problems.append("slo_burn_degrade: the watchdog trip left "
                            "no slo_burn flight event")

    # Replay determinism: the identical pass reproduces the identical
    # burn-event sequence, ticket census, and result bytes.
    srv2, mon2, tickets2 = one_pass(inject=True)
    seq = [(e["event"], e["rule"], e["window"])
           for e in mon.burn_events]
    seq2 = [(e["event"], e["rule"], e["window"])
            for e in mon2.burn_events]
    if seq != seq2:
        problems.append(f"slo_burn_degrade: burn-event sequence is "
                        f"not replay-deterministic: {seq} vs {seq2}")
    if [(t.status, t.reason) for t in tickets] != \
            [(t.status, t.reason) for t in tickets2]:
        problems.append("slo_burn_degrade: the ticket census is not "
                        "replay-deterministic")
    if _result_bytes(tickets2) != _result_bytes(tickets):
        problems.append("slo_burn_degrade: replayed results are not "
                        "bit-identical")
    return problems


def scenario_serve_classes(factory, n_rows, ref):
    """graft-classes: approx (bf16) tenants under a REAL probed
    certificate serve reduced-precision carriage — their results land
    within the class tolerance of the f32 replay and are NOT the f32
    bits (the cheaper carriage actually ran) — while exact tenants in
    the same run stay bit-identical, approx admission is priced below
    exact at the same k, an uncertifiable request (iterations beyond
    the curve) falls back to exact LOUDLY, and the whole pass is
    replay-deterministic."""
    import dataclasses

    import numpy as np

    from arrow_matrix_tpu.classes import certificate_from_record
    from arrow_matrix_tpu.ledger.probe import error_curves_for_source
    from arrow_matrix_tpu.serve import run_trace

    # The certificate comes from the probe, never from hand: the same
    # (structure, seed) the gate's factory builds, probed at bf16.
    source = {"kind": "ba", "n": N, "m": 3, "width": WIDTH,
              "seed": SEED}
    recs = error_curves_for_source(source, k=K, iterations=ITERS,
                                   seed=SEED, dtypes=("bf16",))
    cert = certificate_from_record(recs[0])
    if cert is None or not cert.covers(ITERS):
        return [f"serve_classes: the probed bf16 curve does not "
                f"certify {ITERS} iterations "
                f"(curve={None if cert is None else cert.rel_frobenius})"]

    def classed(trace):
        return [dataclasses.replace(r, traffic_class="approx")
                if r.tenant in ("tenant0", "tenant1") else r
                for r in trace]

    def one_pass():
        srv = _server(factory, certificates=[cert])
        tickets = run_trace(srv, classed(_trace(n_rows)))
        return srv, tickets

    srv, tickets = one_pass()
    problems = []
    s = srv.summary()
    if s["completed"] != REQUESTS:
        problems.append(f"serve_classes: {s['completed']}/{REQUESTS} "
                        f"requests completed")
    approx = [t for t in tickets if t.request.traffic_class == "approx"]
    exact = [t for t in tickets if t.request.traffic_class == "exact"]
    if not approx or not exact:
        return [f"serve_classes: trace split degenerate "
                f"({len(approx)} approx / {len(exact)} exact)"]
    for t in approx:
        if t.served_class != "approx" or t.class_fallback is not None:
            problems.append(
                f"serve_classes: certified approx request "
                f"{t.request.request_id} was not served approx "
                f"(served={t.served_class}, "
                f"fallback={t.class_fallback})")
            continue
        if t.certified_bound != cert.bound_at(ITERS):
            problems.append(f"serve_classes: ticket "
                            f"{t.request.request_id} carries bound "
                            f"{t.certified_bound}, certificate says "
                            f"{cert.bound_at(ITERS)}")
        gold = np.frombuffer(ref[t.request.request_id],
                             dtype=np.float32).reshape(t.result.shape)
        d = t.result.astype(np.float64) - gold.astype(np.float64)
        rel = float(np.linalg.norm(d) / np.linalg.norm(
            gold.astype(np.float64)))
        if rel > cert.tolerance:
            problems.append(
                f"serve_classes: approx result "
                f"{t.request.request_id} drifted rel={rel:.3e} past "
                f"the class tolerance {cert.tolerance:.0e}")
        if rel == 0.0:
            problems.append(
                f"serve_classes: approx request "
                f"{t.request.request_id} returned the f32 bits — the "
                f"reduced carriage never ran")
    for t in exact:
        if t.request.request_id in ref and (
                t.result is None
                or t.result.tobytes() != ref[t.request.request_id]):
            problems.append(f"serve_classes: exact request "
                            f"{t.request.request_id} is not "
                            f"bit-identical beside approx traffic")
    # Class economics: approx reserved fewer bytes than exact at the
    # same (structure, k) — the admitted-requests-per-GB lever.
    if approx[0].predicted_bytes >= exact[0].predicted_bytes:
        problems.append(
            f"serve_classes: approx admission price "
            f"{approx[0].predicted_bytes} B is not below exact "
            f"{exact[0].predicted_bytes} B")
    # Uncertifiable: iterations beyond the measured curve must fall
    # back to exact with the explicit reason — never silent approx.
    deep = dataclasses.replace(_trace(n_rows)[0], iterations=ITERS + 2,
                               traffic_class="approx")
    t_deep = srv.submit(deep)
    srv.drain()
    if t_deep.status != "completed" or t_deep.served_class != "exact" \
            or t_deep.class_fallback != "curve_shorter_than_request":
        problems.append(
            f"serve_classes: beyond-curve approx request ended "
            f"{t_deep.status}/{t_deep.served_class} with fallback "
            f"{t_deep.class_fallback!r} (want completed/exact/"
            f"curve_shorter_than_request)")
    if s["classes"]["approx"]["completed"] != len(approx):
        problems.append(
            f"serve_classes: summary counts "
            f"{s['classes']['approx']['completed']} approx "
            f"completions, trace had {len(approx)}")
    # Replay determinism — approx carriage included.
    srv2, tickets2 = one_pass()
    if [(t.status, t.served_class, t.class_fallback)
            for t in tickets] != \
            [(t.status, t.served_class, t.class_fallback)
             for t in tickets2]:
        problems.append("serve_classes: the class census is not "
                        "replay-deterministic")
    if _result_bytes(tickets) != _result_bytes(tickets2):
        problems.append("serve_classes: approx results are not "
                        "replay-deterministic")
    return problems


def scenario_serve_kill(workdir):
    """SIGKILL mid-request in a checkpointing graft_serve CLI run; the
    rerun resumes and the result set is bit-identical to a never-
    killed run."""
    import numpy as np

    problems = []
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("AMT_FAULT_PLAN", None)
    ck = os.path.join(workdir, "serve_ck_kill")
    ref_npz = os.path.join(workdir, "serve_ref.npz")
    kill_npz = os.path.join(workdir, "serve_kill.npz")
    cmd = [sys.executable, "-m", "arrow_matrix_tpu.cli.graft_serve",
           "--vertices", str(N), "--width", str(WIDTH),
           "--features", str(K), "--tenants", str(TENANTS),
           "--requests", str(REQUESTS), "--iterations", str(ITERS),
           "--seed", str(SEED), "--device", "cpu",
           "--checkpoint_every", "2"]

    def run(extra, fault_env=None):
        e = dict(env)
        if fault_env:
            e["AMT_FAULT_PLAN"] = fault_env
        return subprocess.run(cmd + extra, env=e, cwd=workdir,
                              capture_output=True, text=True,
                              timeout=600)

    r = run(["--results_out", ref_npz])
    if r.returncode != 0:
        return [f"serve_kill: fault-free reference run failed rc="
                f"{r.returncode}: {r.stderr[-500:]}"]
    # 8 requests x 4 iterations = 32 step hits; hit 18 lands
    # mid-request-4 with four requests already completed (their final
    # checkpoints on disk) and a step-2 checkpoint for the victim.
    plan = json.dumps({"scenario": "kill", "site": "*.step",
                       "after": 18})
    r = run(["--results_out", kill_npz, "--checkpoint", ck],
            fault_env=plan)
    if r.returncode == 0:
        return ["serve_kill: injected SIGKILL did not terminate the "
                "server"]
    r = run(["--results_out", kill_npz, "--checkpoint", ck])
    if r.returncode != 0:
        return [f"serve_kill: resume run failed rc={r.returncode}: "
                f"{r.stderr[-500:]}"]
    if "resumed request" not in r.stdout:
        problems.append("serve_kill: rerun did not report resuming "
                        "any request from its checkpoint")
    with np.load(ref_npz) as a, np.load(kill_npz) as b:
        if sorted(a.files) != sorted(b.files):
            problems.append(f"serve_kill: result sets differ: "
                            f"{sorted(a.files)} vs {sorted(b.files)}")
        else:
            for rid in a.files:
                if a[rid].tobytes() != b[rid].tobytes():
                    problems.append(
                        f"serve_kill: resumed request {rid} is not "
                        f"bit-identical to the never-killed run")
    return problems


def run_serve_scenarios(workdir, fast=False):
    """Run the serving matrix; returns (problems, scenarios_run).
    Assumes the caller pinned the platform and (optionally) installed
    a flight recorder — tools/chaos_gate.py imports this into its
    matrix."""
    from arrow_matrix_tpu import faults
    from arrow_matrix_tpu.serve import ba_executor_factory

    faults.clear_plan()
    factory, n_rows = ba_executor_factory(N, WIDTH, SEED, fmt="fold")
    ref_srv = _server(factory)
    ref_tickets = _run(ref_srv, n_rows)
    if ref_srv.summary()["completed"] != REQUESTS:
        return (["serve baseline: fault-free serve run did not "
                 "complete every request"], [])
    ref = _result_bytes(ref_tickets)
    problems = []
    scenarios = ["serve_hang", "serve_corrupt", "serve_overflow",
                 "serve_hbm", "slo_burn_degrade", "serve_classes"]
    problems += scenario_serve_hang(factory, n_rows, ref)
    problems += scenario_serve_corrupt(factory, n_rows, ref, workdir)
    problems += scenario_serve_overflow(factory, n_rows, ref)
    problems += scenario_serve_hbm(factory, n_rows, ref)
    problems += scenario_slo_burn_degrade(factory, n_rows)
    problems += scenario_serve_classes(factory, n_rows, ref)
    if not fast:
        scenarios.append("serve_kill")
        problems += scenario_serve_kill(workdir)
    return problems, scenarios


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    fast = "--fast" in argv
    argv = [a for a in argv if a != "--fast"]

    from arrow_matrix_tpu.utils.platform import force_cpu_devices

    force_cpu_devices(4)

    import tempfile

    from arrow_matrix_tpu import sync
    from arrow_matrix_tpu.obs import flight

    # Arm the lock-order witness before any server is constructed so
    # every scenario below doubles as a lock-order execution test
    # (sync.py module docstring).  An inverted acquisition raises
    # LockOrderViolation inside the scenario and fails the gate.
    registry = sync.enable_witness()

    workdir = argv[0] if argv else tempfile.mkdtemp(prefix="serve_gate_")
    os.makedirs(workdir, exist_ok=True)
    rec = flight.FlightRecorder(os.path.join(workdir, "flight.json"))
    flight.set_recorder(rec)
    try:
        problems, scenarios = run_serve_scenarios(workdir, fast=fast)
        kinds = {e.get("kind") for e in rec.events}
        if "serve" not in kinds:
            problems.append(f"flight recorder saw kinds "
                            f"{sorted(kinds)} — serve events are "
                            f"required")
    finally:
        rec.seal("serve gate done")
        flight.set_recorder(None)
    snap = registry.snapshot()
    if snap["violations"]:
        problems.extend(f"lock witness: {v}" for v in snap["violations"])
    if not snap["acquisitions"]:
        problems.append("lock witness: zero witnessed acquisitions — "
                        "the serving stack stopped routing its locks "
                        "through sync.witnessed()")
    print(f"serve gate: lock witness — {snap['acquisitions']} "
          f"acquisitions, {snap['reentries']} reentries, "
          f"{len(snap['threads'])} threads, "
          f"{len(snap['observed_edges'])} observed edges, "
          f"{len(snap['violations'])} violations", file=sys.stderr)
    if problems:
        for p in problems:
            print(f"serve gate: {p}", file=sys.stderr)
        print("serve gate: FAILED", file=sys.stderr)
        return 1
    print(f"serve gate: ok — scenarios {'+'.join(scenarios)} "
          f"detected, recovered (or explicitly shed), bit-identical "
          f"({workdir})", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
